"""Subprocess management (reference: src/process/)."""

from .manager import ProcessExitEvent, ProcessManager

__all__ = ["ProcessExitEvent", "ProcessManager"]
