"""ProcessManager — async subprocess execution
(reference: src/process/ProcessManager{,Impl}.{h,cpp}).

``run_process(cmdline)`` is an async ``system()``: the command runs in a
real OS subprocess, a worker thread waits on it, and the exit status is
posted back to the main crank.  Concurrency is capped at
MAX_CONCURRENT_SUBPROCESSES (main/Config.h:146) with a pending queue —
history archival (curl / gzip / cp) is the main customer.
"""

from __future__ import annotations

import shlex
import subprocess
from collections import deque
from typing import Callable, Deque, Optional, Tuple

from ..util import xlog

log = xlog.logger("Process")


class ProcessExitEvent:
    """Handle for a queued/running subprocess; ``on_exit(returncode)`` fires
    on the main loop when it finishes (0 = success)."""

    __slots__ = ("cmdline", "on_exit", "out_file", "live", "returncode")

    def __init__(
        self,
        cmdline: str,
        on_exit: Optional[Callable[[int], None]],
        out_file: Optional[str] = None,
    ):
        self.cmdline = cmdline
        self.on_exit = on_exit
        self.out_file = out_file
        self.live = False
        self.returncode: Optional[int] = None


class ProcessManager:
    def __init__(self, app):
        self.app = app
        self.max_concurrent = app.config.MAX_CONCURRENT_SUBPROCESSES
        self.running = 0
        self.pending: Deque[ProcessExitEvent] = deque()
        self._live_procs = set()
        self._shutdown = False

    def run_process(
        self,
        cmdline: str,
        on_exit: Optional[Callable[[int], None]] = None,
        out_file: Optional[str] = None,
    ) -> ProcessExitEvent:
        """out_file redirects the child's stdout (the reference's
        runProcess(cmd, outFile) overload, ProcessManagerImpl — history
        archive `get` commands fetch into files this way)."""
        ev = ProcessExitEvent(cmdline, on_exit, out_file)
        self.pending.append(ev)
        self._maybe_start()
        return ev

    def get_num_running(self) -> int:
        return self.running

    def _maybe_start(self) -> None:
        while not self._shutdown and self.pending and self.running < self.max_concurrent:
            ev = self.pending.popleft()
            self._start(ev)

    def _start(self, ev: ProcessExitEvent) -> None:
        self.running += 1
        ev.live = True
        log.debug("running: %s", ev.cmdline)

        def work():
            if self._shutdown:
                return -15  # shutdown raced the spawn: never start the child
            out = subprocess.DEVNULL
            try:
                if ev.out_file is not None:
                    out = open(ev.out_file, "wb")
                proc = subprocess.Popen(
                    ev.cmdline,
                    shell=True,
                    stdout=out,
                    stderr=subprocess.DEVNULL,
                )
            except OSError as e:
                log.warning("spawn failed for %r: %s", ev.cmdline, e)
                return 127
            finally:
                # Popen dup'd the fd (or we never opened one); the parent's
                # handle can close either way
                if out is not subprocess.DEVNULL and not out.closed:
                    out.close()
            self._live_procs.add(proc)
            if self._shutdown:
                # shutdown() ran between the check above and the spawn —
                # it cannot have seen this proc in _live_procs, so kill here
                try:
                    proc.terminate()
                except OSError:
                    pass
            try:
                return proc.wait()
            finally:
                self._live_procs.discard(proc)

        def done(result):
            self.running -= 1
            ev.live = False
            ev.returncode = result if isinstance(result, int) else 1
            if ev.returncode != 0:
                log.debug("process exited %s: %s", ev.returncode, ev.cmdline)
            if ev.on_exit is not None:
                ev.on_exit(ev.returncode)
            self._maybe_start()

        self.app.clock.submit_work(work, done)

    def shutdown(self) -> None:
        """Kill live children so the worker threads joining them unblock
        (the reference ProcessManagerImpl kills on teardown)."""
        self._shutdown = True
        self.pending.clear()
        for proc in list(self._live_procs):
            try:
                proc.terminate()
            except OSError:
                pass
