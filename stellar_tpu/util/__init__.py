"""Util runtime (reference: src/util/, SURVEY.md §2.12)."""

from .clock import REAL_TIME, VIRTUAL_TIME, VirtualClock, VirtualTimer  # noqa: F401
from .metrics import MetricsRegistry  # noqa: F401
from .tmpdir import TmpDir, TmpDirManager  # noqa: F401
from .xdrstream import XDRInputFileStream, XDROutputFileStream  # noqa: F401
from . import fs  # noqa: F401
from . import xlog  # noqa: F401
