"""XDR record-file streams (reference: src/util/XDRStream.h).

RFC 5531 record marking: each record is a 4-byte big-endian length with the
high ('continuation') bit set, followed by the XDR body.  Used for bucket
files and history ledger/tx/result files — byte-compatible with the
reference so bucket hashes agree.
"""

from __future__ import annotations

import os
import struct
from typing import Iterator, Optional, Type

from ..xdr.base import XdrError, codec_of


class XDROutputFileStream:
    """``durable=True`` makes close() fsync the stream before returning
    (crash-safe staging; util/fs.py discipline), with ``point`` naming
    the site's storage kill-points (``<point>:write`` while the payload
    is complete-but-unsynced, ``<point>:staged`` after the fsync)."""

    def __init__(self, path: str, hasher=None, durable: bool = False,
                 point: str = None, ctx=None):
        # streaming writer for a fresh staging path; durability comes
        # from the fsync-on-close below, adoption/rename from the caller
        self._f = open(path, "wb")
        self._path = path
        self._hasher = hasher
        self._durable = durable
        self._point = point
        self._ctx = ctx
        self.bytes_put = 0

    def write_one(self, obj) -> None:
        body = obj.to_xdr()
        if len(body) >= 0x80000000:
            raise XdrError("record too large")
        frame = struct.pack(">I", len(body) | 0x80000000) + body
        self._f.write(frame)
        self.bytes_put += len(frame)
        if self._hasher is not None:
            self._hasher.add(frame)

    def close(self) -> None:
        if self._durable and not self._f.closed:
            from . import fs

            self._f.flush()
            if self._point is not None:
                fs.kill_point(
                    self._point + fs.STAGE_WRITE, path=self._path,
                    ctx=self._ctx,
                )
            os.fsync(self._f.fileno())
            self._f.close()
            if self._point is not None:
                fs.kill_point(
                    self._point + fs.STAGE_STAGED, path=self._path,
                    ctx=self._ctx,
                )
            return
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class XDRInputFileStream:
    def __init__(self, path: str):
        self._f = open(path, "rb")

    def read_one(self, cls) -> Optional[object]:
        hdr = self._f.read(4)
        if not hdr:
            return None
        if len(hdr) < 4:
            raise XdrError("truncated record header")
        sz = struct.unpack(">I", hdr)[0] & 0x7FFFFFFF
        body = self._f.read(sz)
        if len(body) < sz:
            raise XdrError("malformed XDR file: truncated record")
        return codec_of(cls).unpack(body)

    def read_all(self, cls) -> Iterator[object]:
        while True:
            obj = self.read_one(cls)
            if obj is None:
                return
            yield obj

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
