"""Thread-discipline asserts (reference: src/util/GlobalChecks.{h,cpp}).

The reference pins ``mainThread`` at static-init time and calls
``assertThreadIsMain()`` from VirtualClock (Timer.cpp), TCPPeer, and
Database.  Python's equivalent of "the main thread" is ambiguous under
pytest and embedding, so the discipline is per-reactor instead:
``VirtualClock`` records its constructing thread and the reactor entry
points (``post``, ``crank``) assert against it via ``assert_thread_is`` —
same invariant, bound to the object that owns it.  Violations raise in
debug runs and are compiled out under ``python -O`` like the reference's
NDEBUG build.
"""

from __future__ import annotations

import threading


def assert_thread_is(owner_tid: int) -> None:
    """Reactor objects record their constructing thread id and assert
    subsequent same-thread use (workers must use post_from_thread)."""
    assert threading.get_ident() == owner_tid, (
        "thread-affine object used from foreign thread "
        f"{threading.current_thread().name!r} (use post_from_thread)"
    )
