"""Metrics registry (reference: lib/libmedida + per-subsystem NewMeter/NewTimer
call sites, SURVEY.md §5.5).

Same shapes as medida: Counter, Meter (count + EWMA 1/5/15min rates), Histogram
(reservoir percentiles), Timer (histogram-of-durations + meter).  Reported as
JSON with medida's field names so the admin ``/metrics`` endpoint looks like
the reference's (main/CommandHandler.cpp:82).

Metric names are dotted triples like ``scp.envelope.sign``.
"""

from __future__ import annotations

import math
import random
import time
from typing import Dict, Optional


class Counter:
    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1):
        self.count += n

    def dec(self, n: int = 1):
        self.count -= n

    def set_count(self, n: int):
        self.count = n

    def to_json(self):
        return {"type": "counter", "count": self.count}


class EWMA:
    """Exponentially-weighted moving average rate, medida-style (5s ticks)."""

    TICK_SECONDS = 5.0

    def __init__(self, minutes: float, clock=None):
        self._alpha = 1.0 - math.exp(-self.TICK_SECONDS / 60.0 / minutes)
        self._uncounted = 0
        self._rate = 0.0
        self._initialized = False

    def update(self, n: int = 1):
        self._uncounted += n

    def tick(self):
        instant = self._uncounted / self.TICK_SECONDS
        self._uncounted = 0
        if self._initialized:
            self._rate += self._alpha * (instant - self._rate)
        else:
            self._rate = instant
            self._initialized = True

    @property
    def rate(self) -> float:
        return self._rate


class Meter:
    def __init__(self, event_type: str = "event", clock=None):
        self.event_type = event_type
        self.count = 0
        self._clock = clock
        self._start = self._now()
        self._last_tick = self._start
        self._m1 = EWMA(1)
        self._m5 = EWMA(5)
        self._m15 = EWMA(15)

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.monotonic()

    def mark(self, n: int = 1):
        self._tick_if_needed()
        self.count += n
        self._m1.update(n)
        self._m5.update(n)
        self._m15.update(n)

    def _tick_if_needed(self):
        now = self._now()
        while now - self._last_tick >= EWMA.TICK_SECONDS:
            self._m1.tick()
            self._m5.tick()
            self._m15.tick()
            self._last_tick += EWMA.TICK_SECONDS

    @property
    def mean_rate(self) -> float:
        elapsed = self._now() - self._start
        return self.count / elapsed if elapsed > 0 else 0.0

    @property
    def one_minute_rate(self) -> float:
        self._tick_if_needed()
        return self._m1.rate

    def to_json(self):
        self._tick_if_needed()
        return {
            "type": "meter",
            "count": self.count,
            "event_type": self.event_type,
            "mean_rate": self.mean_rate,
            "1_min_rate": self._m1.rate,
            "5_min_rate": self._m5.rate,
            "15_min_rate": self._m15.rate,
        }


class Histogram:
    """Uniform reservoir sample (medida's default), size 1028."""

    RESERVOIR = 1028

    def __init__(self, rng: Optional[random.Random] = None):
        self.count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._sample = []
        self._rng = rng or random.Random(0x5EED)

    def update(self, value: float):
        self.count += 1
        self._sum += value
        self._min = value if self._min is None else min(self._min, value)
        self._max = value if self._max is None else max(self._max, value)
        if len(self._sample) < self.RESERVOIR:
            self._sample.append(value)
        else:
            i = self._rng.randrange(self.count)
            if i < self.RESERVOIR:
                self._sample[i] = value

    def percentile(self, q: float) -> float:
        if not self._sample:
            return 0.0
        s = sorted(self._sample)
        pos = q * (len(s) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1 - frac) + s[hi] * frac

    @property
    def mean(self) -> float:
        return self._sum / self.count if self.count else 0.0

    @property
    def max_value(self) -> float:
        """Largest recorded value (exact, not reservoir-sampled) — the trace
        aggregator's max comes from here."""
        return self._max if self._max is not None else 0.0

    def clear(self) -> None:
        """Reset the reservoir (medida Timer::Clear — the reference's
        auto-load calibration clears between adjustment periods)."""
        self.count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._sample.clear()

    def to_json(self):
        return {
            "type": "histogram",
            "count": self.count,
            "min": self._min or 0.0,
            "max": self._max or 0.0,
            "mean": self.mean,
            "median": self.percentile(0.5),
            "75%": self.percentile(0.75),
            "95%": self.percentile(0.95),
            "98%": self.percentile(0.98),
            "99%": self.percentile(0.99),
            "99.9%": self.percentile(0.999),
        }


class Timer:
    """Duration metric; values recorded in milliseconds like medida."""

    def __init__(self, clock=None):
        self._clock = clock
        self.histogram = Histogram()
        self.meter = Meter("calls", clock)

    def update(self, seconds: float):
        self.histogram.update(seconds * 1000.0)
        self.meter.mark()

    def time_scope(self) -> "TimeScope":
        return TimeScope(self)

    @property
    def count(self):
        return self.histogram.count

    def to_json(self):
        j = self.histogram.to_json()
        j.update(
            {
                "type": "timer",
                "duration_unit": "milliseconds",
                "rate_unit": "calls/s",
                "mean_rate": self.meter.mean_rate,
                "1_min_rate": self.meter.one_minute_rate,
            }
        )
        return j


class TimeScope:
    def __init__(self, timer: Timer):
        self._timer = timer
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Per-Application registry (main/Application.h:168)."""

    def __init__(self, clock=None):
        self._clock = clock
        self._metrics: Dict[str, object] = {}
        # tuple-parts -> metric: the hot apply path looks the same meters/
        # timers up ~8x per tx; this skips the join + isinstance + factory
        # allocation on every hit (0.6 s tottime per 10^6-scale close)
        self._by_parts: Dict[tuple, object] = {}

    def _get(self, parts, factory, want_type):
        # slow path only: the new_* accessors check the (tuple-parts, type)
        # memo inline BEFORE building the factory closure, so reaching
        # here with tuple parts means a guaranteed memo miss — no second
        # probe.  Keying on the type keeps the collision guard intact.
        memo_key = (parts, want_type) if isinstance(parts, tuple) else None
        name = self._name(parts)
        m = self._metrics.get(name)
        if m is None:
            m = factory()
            self._metrics[name] = m
        elif not isinstance(m, want_type):
            # medida asserts on metric-type collisions; so do we
            raise TypeError(
                f"metric {name!r} is {type(m).__name__}, not {want_type.__name__}"
            )
        if memo_key is not None:
            self._by_parts[memo_key] = m
        return m

    @staticmethod
    def _name(parts) -> str:
        return ".".join(parts) if not isinstance(parts, str) else parts

    # the new_* accessors are on the per-op apply path (~3 calls/tx); on a
    # memo hit, return before allocating the factory closure _get takes —
    # the lambda alone costs more than the memo lookup

    def new_counter(self, parts) -> Counter:
        m = self._by_parts.get((parts, Counter)) if type(parts) is tuple else None
        return m if m is not None else self._get(parts, Counter, Counter)

    def new_meter(self, parts, event_type: str = "event") -> Meter:
        m = self._by_parts.get((parts, Meter)) if type(parts) is tuple else None
        if m is not None:
            return m
        return self._get(parts, lambda: Meter(event_type, self._clock), Meter)

    def new_histogram(self, parts) -> Histogram:
        m = self._by_parts.get((parts, Histogram)) if type(parts) is tuple else None
        return m if m is not None else self._get(parts, Histogram, Histogram)

    def new_timer(self, parts) -> Timer:
        m = self._by_parts.get((parts, Timer)) if type(parts) is tuple else None
        if m is not None:
            return m
        return self._get(parts, lambda: Timer(self._clock), Timer)

    def get(self, parts):
        return self._metrics.get(self._name(parts))

    def to_json(self) -> dict:
        return {name: m.to_json() for name, m in sorted(self._metrics.items())}
