"""Metrics registry (reference: lib/libmedida + per-subsystem NewMeter/NewTimer
call sites, SURVEY.md §5.5).

Same shapes as medida: Counter, Meter (count + EWMA 1/5/15min rates), Histogram
(reservoir percentiles), Timer (histogram-of-durations + meter).  Reported as
JSON with medida's field names so the admin ``/metrics`` endpoint looks like
the reference's (main/CommandHandler.cpp:82).

Metric names are dotted triples like ``scp.envelope.sign``.

Hot-path fast lane (round 7): registry-owned metrics record through a shared
append-only lane (``_FastLane``) instead of doing the reservoir/EWMA work per
call — the round-5/6 close profiles bill the per-call wrapper work at
~0.35 s per 5000-tx close (8+ timer/meter updates per applied tx).  A record
is one tuple build + ``deque.append`` (both GIL-atomic, no lock); pending
samples drain into the real reservoir/EWMA state on any read (``to_json``,
``count``, percentiles), when the lane hits its size threshold, or at the
latest one EWMA tick (5 s) after the previous drain — so rates never
report a long-deferred burst as current activity.  Field names and JSON shape are unchanged; the
only observable difference is that EWMA tick timestamps are taken at drain
time instead of per-mark, which is within medida's own 5-second tick
granularity.  Metrics constructed WITHOUT a registry (``Timer()`` in tests,
standalone ``Histogram()``) keep the direct path.
"""

from __future__ import annotations

import math
import random
import threading
import time
from collections import deque
from typing import Dict, Optional


class _FastLane:
    """Shared hot-path sample buffer for one registry.

    ``record`` must stay lock-free: ``deque.append`` is atomic under the
    GIL, so concurrent recorders (main crank, sig-prewarm worker, trace
    spans completing on drain threads) never contend.  ``flush`` applies
    pending samples via each metric's ``_apply`` under a lock so two
    drains cannot interleave one metric's reservoir update; ``popleft``
    is likewise atomic, so a record racing a flush is either drained in
    this pass or stays queued — never lost."""

    __slots__ = ("_q", "_flush_lock", "_last_flush")

    # drain inline once this many samples are pending — bounds memory on a
    # node that is never asked for /metrics (threshold * tuple ≈ a few
    # hundred KB worst case, and the drain amortizes to ~1/8192 of calls)
    FLUSH_THRESHOLD = 8192
    # ...or once this much time has passed since the last drain: pending
    # marks must reach the EWMAs within one medida tick window, or a burst
    # deferred for minutes would be reported as CURRENT activity when a
    # reader finally drains it (rates would spike long after the fact).
    # The time check costs one monotonic() per record — still well under
    # the ≤~1 µs contract.
    FLUSH_SECONDS = 5.0  # = EWMA.TICK_SECONDS

    def __init__(self):
        self._q = deque()
        self._flush_lock = threading.Lock()
        self._last_flush = time.monotonic()

    def record(self, metric, value) -> None:
        q = self._q
        q.append((metric, value))
        if (
            len(q) >= self.FLUSH_THRESHOLD
            or time.monotonic() - self._last_flush >= self.FLUSH_SECONDS
        ):
            self.flush()

    def flush(self) -> None:
        self._last_flush = time.monotonic()
        q = self._q
        if not q:
            return
        with self._flush_lock:
            # group by metric first: a meter marked 5000x in one close then
            # pays ONE tick + EWMA update for the whole batch, and a
            # histogram pays one tight C-speed-ish loop — this is where the
            # per-call reservoir/EWMA work actually disappears, not just
            # moves (the samples are order-preserved within each metric, so
            # the reservoir state is bit-identical to the direct path)
            groups: Dict[int, list] = {}
            order = []
            while q:
                try:
                    m, v = q.popleft()
                except IndexError:  # racing flush drained the tail
                    break
                g = groups.get(id(m))
                if g is None:
                    groups[id(m)] = [v]
                    order.append(m)
                else:
                    g.append(v)
            for m in order:
                m._apply_batch(groups[id(m)])


class Counter:
    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1):
        self.count += n

    def dec(self, n: int = 1):
        self.count -= n

    def set_count(self, n: int):
        self.count = n

    def to_json(self):
        return {"type": "counter", "count": self.count}


class EWMA:
    """Exponentially-weighted moving average rate, medida-style (5s ticks)."""

    TICK_SECONDS = 5.0

    def __init__(self, minutes: float, clock=None):
        self._alpha = 1.0 - math.exp(-self.TICK_SECONDS / 60.0 / minutes)
        self._uncounted = 0
        self._rate = 0.0
        self._initialized = False

    def update(self, n: int = 1):
        self._uncounted += n

    def tick(self):
        instant = self._uncounted / self.TICK_SECONDS
        self._uncounted = 0
        if self._initialized:
            self._rate += self._alpha * (instant - self._rate)
        else:
            self._rate = instant
            self._initialized = True

    @property
    def rate(self) -> float:
        return self._rate


class Meter:
    def __init__(self, event_type: str = "event", clock=None, lane=None):
        self.event_type = event_type
        self._count = 0
        self._clock = clock
        self._lane = lane
        self._start = self._now()
        self._last_tick = self._start
        self._m1 = EWMA(1)
        self._m5 = EWMA(5)
        self._m15 = EWMA(15)

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None else time.monotonic()

    def mark(self, n: int = 1):
        lane = self._lane
        if lane is None:
            self._apply(n)
        else:
            lane.record(self, n)

    def _apply(self, n: int):
        self._tick_if_needed()
        self._count += n
        self._m1.update(n)
        self._m5.update(n)
        self._m15.update(n)

    def _apply_batch(self, ns):
        # EWMA.update only accumulates _uncounted, so one update with the
        # batch total is exactly n separate updates within one tick window
        self._apply(sum(ns))

    def _drain(self):
        if self._lane is not None:
            self._lane.flush()

    @property
    def count(self) -> int:
        self._drain()
        return self._count

    def _tick_if_needed(self):
        now = self._now()
        while now - self._last_tick >= EWMA.TICK_SECONDS:
            self._m1.tick()
            self._m5.tick()
            self._m15.tick()
            self._last_tick += EWMA.TICK_SECONDS

    @property
    def mean_rate(self) -> float:
        self._drain()
        elapsed = self._now() - self._start
        return self._count / elapsed if elapsed > 0 else 0.0

    @property
    def one_minute_rate(self) -> float:
        self._drain()
        self._tick_if_needed()
        return self._m1.rate

    def to_json(self):
        self._drain()
        self._tick_if_needed()
        return {
            "type": "meter",
            "count": self._count,
            "event_type": self.event_type,
            "mean_rate": self.mean_rate,
            "1_min_rate": self._m1.rate,
            "5_min_rate": self._m5.rate,
            "15_min_rate": self._m15.rate,
        }


class Histogram:
    """Uniform reservoir sample (medida's default), size 1028."""

    RESERVOIR = 1028

    def __init__(self, rng: Optional[random.Random] = None, lane=None):
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._sample = []
        self._rng = rng or random.Random(0x5EED)
        self._lane = lane

    def update(self, value: float):
        lane = self._lane
        if lane is None:
            self._apply(value)
        else:
            lane.record(self, value)

    def _apply(self, value: float):
        self._apply_batch((value,))

    def _apply_batch(self, vals):
        """One locals-bound loop over the batch — same per-value algorithm
        (and the same seeded rng call sequence) as the old per-call path,
        so the reservoir state is bit-identical; the dispatch overhead is
        paid once per flush instead of once per sample."""
        count = self._count
        total = self._sum
        mn, mx = self._min, self._max
        sample = self._sample
        append = sample.append
        randrange = self._rng.randrange
        res = self.RESERVOIR
        for v in vals:
            count += 1
            total += v
            if mn is None or v < mn:
                mn = v
            if mx is None or v > mx:
                mx = v
            if len(sample) < res:
                append(v)
            else:
                i = randrange(count)
                if i < res:
                    sample[i] = v
        self._count = count
        self._sum = total
        self._min, self._max = mn, mx

    def _drain(self):
        if self._lane is not None:
            self._lane.flush()

    @property
    def count(self) -> int:
        self._drain()
        return self._count

    def percentile(self, q: float) -> float:
        self._drain()
        if not self._sample:
            return 0.0
        s = sorted(self._sample)
        pos = q * (len(s) - 1)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1 - frac) + s[hi] * frac

    @property
    def mean(self) -> float:
        self._drain()
        return self._sum / self._count if self._count else 0.0

    @property
    def max_value(self) -> float:
        """Largest recorded value (exact, not reservoir-sampled) — the trace
        aggregator's max comes from here."""
        self._drain()
        return self._max if self._max is not None else 0.0

    def clear(self) -> None:
        """Reset the reservoir (medida Timer::Clear — the reference's
        auto-load calibration clears between adjustment periods).  Pending
        lane samples drain FIRST so a pre-clear record can never leak into
        the post-clear window."""
        self._drain()
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._sample.clear()

    def to_json(self):
        self._drain()
        return {
            "type": "histogram",
            "count": self._count,
            "min": self._min or 0.0,
            "max": self._max or 0.0,
            "mean": self.mean,
            "median": self.percentile(0.5),
            "75%": self.percentile(0.75),
            "95%": self.percentile(0.95),
            "98%": self.percentile(0.98),
            "99%": self.percentile(0.99),
            "99.9%": self.percentile(0.999),
        }


class Timer:
    """Duration metric; values recorded in milliseconds like medida."""

    def __init__(self, clock=None, lane=None):
        self._clock = clock
        self._lane = lane
        # the sub-metrics carry the SAME lane so direct reads of
        # timer.histogram.* / timer.meter.* (loadgen reads the mean,
        # clear() between calibration periods) drain pending timer
        # records first; Timer._apply feeds them via _apply/_apply_batch
        # directly, so one hot-path record never re-queues two more
        self.histogram = Histogram(lane=lane)
        self.meter = Meter("calls", clock, lane=lane)

    def update(self, seconds: float):
        lane = self._lane
        if lane is None:
            self._apply(seconds)
        else:
            lane.record(self, seconds)

    def _apply(self, seconds: float):
        self.histogram._apply(seconds * 1000.0)
        self.meter._apply(1)

    def _apply_batch(self, vals):
        self.histogram._apply_batch([s * 1000.0 for s in vals])
        self.meter._apply(len(vals))

    def _drain(self):
        if self._lane is not None:
            self._lane.flush()

    def time_scope(self) -> "TimeScope":
        return TimeScope(self)

    @property
    def count(self):
        self._drain()
        return self.histogram._count

    def to_json(self):
        self._drain()
        j = self.histogram.to_json()
        j.update(
            {
                "type": "timer",
                "duration_unit": "milliseconds",
                "rate_unit": "calls/s",
                "mean_rate": self.meter.mean_rate,
                "1_min_rate": self.meter.one_minute_rate,
            }
        )
        return j


class TimeScope:
    def __init__(self, timer: Timer):
        self._timer = timer
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Per-Application registry (main/Application.h:168)."""

    def __init__(self, clock=None):
        self._clock = clock
        self._metrics: Dict[str, object] = {}
        # tuple-parts -> metric: the hot apply path looks the same meters/
        # timers up ~8x per tx; this skips the join + isinstance + factory
        # allocation on every hit (0.6 s tottime per 10^6-scale close)
        self._by_parts: Dict[tuple, object] = {}
        # shared hot-path sample buffer for every metric this registry owns
        self._lane = _FastLane()

    def flush(self) -> None:
        """Drain pending fast-lane samples into the reservoir/EWMA state.
        Reads (to_json, counts, percentiles) call this themselves; expose
        it for callers that want the lane empty at a known point (tests,
        the bench harness between warmup and timed closes)."""
        self._lane.flush()

    def _get(self, parts, factory, want_type):
        # slow path only: the new_* accessors check the (tuple-parts, type)
        # memo inline BEFORE building the factory closure, so reaching
        # here with tuple parts means a guaranteed memo miss — no second
        # probe.  Keying on the type keeps the collision guard intact.
        memo_key = (parts, want_type) if isinstance(parts, tuple) else None
        name = self._name(parts)
        m = self._metrics.get(name)
        if m is None:
            m = factory()
            self._metrics[name] = m
        elif not isinstance(m, want_type):
            # medida asserts on metric-type collisions; so do we
            raise TypeError(
                f"metric {name!r} is {type(m).__name__}, not {want_type.__name__}"
            )
        if memo_key is not None:
            self._by_parts[memo_key] = m
        return m

    @staticmethod
    def _name(parts) -> str:
        return ".".join(parts) if not isinstance(parts, str) else parts

    # the new_* accessors are on the per-op apply path (~3 calls/tx); on a
    # memo hit, return before allocating the factory closure _get takes —
    # the lambda alone costs more than the memo lookup

    def new_counter(self, parts) -> Counter:
        m = self._by_parts.get((parts, Counter)) if type(parts) is tuple else None
        return m if m is not None else self._get(parts, Counter, Counter)

    def new_meter(self, parts, event_type: str = "event") -> Meter:
        m = self._by_parts.get((parts, Meter)) if type(parts) is tuple else None
        if m is not None:
            return m
        return self._get(
            parts, lambda: Meter(event_type, self._clock, lane=self._lane), Meter
        )

    def new_histogram(self, parts) -> Histogram:
        m = self._by_parts.get((parts, Histogram)) if type(parts) is tuple else None
        if m is not None:
            return m
        return self._get(
            parts, lambda: Histogram(lane=self._lane), Histogram
        )

    def new_timer(self, parts) -> Timer:
        m = self._by_parts.get((parts, Timer)) if type(parts) is tuple else None
        if m is not None:
            return m
        return self._get(
            parts, lambda: Timer(self._clock, lane=self._lane), Timer
        )

    def get(self, parts):
        return self._metrics.get(self._name(parts))

    def to_json(self) -> dict:
        self._lane.flush()
        return {name: m.to_json() for name, m in sorted(self._metrics.items())}
