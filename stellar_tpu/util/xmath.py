"""128-bit-safe integer math (reference: src/util/types.cpp bigDivide, using
the vendored uint128; Python ints are unbounded so only the overflow contract
needs care).
"""

from __future__ import annotations

INT64_MAX = 0x7FFFFFFFFFFFFFFF
INT64_MIN = -0x8000000000000000


def big_divide_checked(a: int, b: int, c: int):
    """floor(a*b/c) with int64 range check -> (ok, result)."""
    assert a >= 0 and b >= 0 and c > 0
    x = (a * b) // c
    if x > INT64_MAX:
        return False, 0
    return True, x


def big_divide(a: int, b: int, c: int) -> int:
    ok, r = big_divide_checked(a, b, c)
    if not ok:
        raise OverflowError("overflow while performing bigDivide")
    return r
