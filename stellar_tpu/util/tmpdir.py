"""TmpDir / TmpDirManager / fs helpers (reference: src/util/TmpDir.*, Fs.*)."""

from __future__ import annotations

import os
import shutil
import uuid


def deltree(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def mkpath(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    return os.path.exists(path)


class TmpDir:
    def __init__(self, path: str):
        self._path = path
        mkpath(path)

    def get_name(self) -> str:
        return self._path

    def __fspath__(self):
        return self._path


class TmpDirManager:
    """Owns a root dir of per-purpose temp subdirs, cleaned on forget/exit.

    A killed process leaves its in-flight ``publish-*``/``catchup-*``
    dirs behind; construction reaps every orphan (counted in
    ``reaped_at_boot`` so the boot self-check can meter it as
    ``selfcheck.tmp-reaped``).  The reap is guarded against IN-FLIGHT
    dirs: anything handed out by *this* manager instance is live and
    never reaped, so a runtime re-sweep can't destroy an active publish
    staging dir."""

    def __init__(self, root: str):
        self._root = root
        self._live: set = set()
        self.reaped_at_boot = self.reap_orphans()
        mkpath(root)

    def tmp_dir(self, prefix: str) -> TmpDir:
        d = TmpDir(os.path.join(self._root, f"{prefix}-{uuid.uuid4().hex[:12]}"))
        self._live.add(d.get_name())
        return d

    def forget(self, d: TmpDir) -> None:
        self._live.discard(d.get_name())
        deltree(d.get_name())

    def reap_orphans(self) -> int:
        """Remove (and count) every entry under the root not owned by a
        live TmpDir of this manager — the crashed-process leftovers."""
        if not os.path.isdir(self._root):
            return 0
        reaped = 0
        for name in os.listdir(self._root):
            path = os.path.join(self._root, name)
            if path in self._live:
                continue
            if os.path.isdir(path):
                deltree(path)
            else:
                try:
                    os.unlink(path)
                except OSError:
                    continue
            reaped += 1
        return reaped

    def get_root(self) -> str:
        return self._root
