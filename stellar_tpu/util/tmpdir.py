"""TmpDir / TmpDirManager / fs helpers (reference: src/util/TmpDir.*, Fs.*)."""

from __future__ import annotations

import os
import shutil
import uuid


def deltree(path: str) -> None:
    shutil.rmtree(path, ignore_errors=True)


def mkpath(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    return os.path.exists(path)


class TmpDir:
    def __init__(self, path: str):
        self._path = path
        mkpath(path)

    def get_name(self) -> str:
        return self._path

    def __fspath__(self):
        return self._path


class TmpDirManager:
    """Owns a root dir of per-purpose temp subdirs, cleaned on forget/exit."""

    def __init__(self, root: str):
        self._root = root
        self.clean()
        mkpath(root)

    def tmp_dir(self, prefix: str) -> TmpDir:
        return TmpDir(os.path.join(self._root, f"{prefix}-{uuid.uuid4().hex[:12]}"))

    def forget(self, d: TmpDir) -> None:
        deltree(d.get_name())

    def clean(self) -> None:
        deltree(self._root)

    def get_root(self) -> str:
        return self._root
