"""Durable file-system writes + the storage kill-point plane.

Every durable artifact this node writes (bucket files, history staging,
the publish-commit JSON) must reach disk through the helpers here:
write-tmp → fsync(file) → rename → fsync(dir), the same discipline the
reference gets from its own Fs.cpp + rename idiom.  A bare
``open(path, "wb")`` on a durable path elsewhere is an analysis
violation (``durable-write`` rule) — the contract that keeps future
writers crash-safe.

The same choke points double as the chaos plane's STORAGE fault surface:
each durable boundary is a named **kill-point** (registered at import
time so ``python -m stellar_tpu.scenarios --kill-sweep`` can enumerate
them), and ``kill_point()`` consults the installed hooks — a trace
recorder during sweep control runs, a ``StorageFaultInjector``
(scenarios/storagefaults.py) during kill runs.  With no hooks installed
the call is one global read + a falsy check, cheap enough for the close
path.

Stage suffix convention for file sites:

- ``:write``   — the payload bytes are fully written (and flushed to the
                 OS) but NOT yet fsynced; torn/truncated-file faults
                 corrupt the on-disk file here before killing.
- ``:staged``  — file fsynced, rename not yet performed (the classic
                 post-write-pre-rename kill).
- ``:renamed`` — renamed into place, directory entry not yet fsynced.

SQL/state boundaries register single names (``db.commit:pre`` etc.).
"""

from __future__ import annotations

import os
import uuid
from typing import Callable, Dict, List, Optional, Tuple

STAGE_WRITE = ":write"
STAGE_STAGED = ":staged"
STAGE_RENAMED = ":renamed"


class SimulatedProcessKill(BaseException):
    """Raised by an in-process storage-fault injector at a kill-point:
    models the process dying at exactly that durable-write boundary.
    Derives from BaseException so no ``except Exception`` recovery path
    can 'survive' its own death — the unwind mirrors a real kill (any
    open SQL transaction rolls back via the context managers, exactly
    what a restart would observe).  Simulation.crank_until catches it
    and reaps the node."""

    def __init__(self, point: str, ctx=None):
        super().__init__(point)
        self.point = point
        self.ctx = ctx


# -- kill-point registry -----------------------------------------------------

# name -> doc; populated at import time by the modules that own each
# durable boundary, so the sweep can enumerate points without running
_REGISTRY: Dict[str, str] = {}

# installed hooks: callables (name, path, ctx) -> None.  Hooks may raise
# SimulatedProcessKill or call os._exit; order is install order.
_hooks: List[Callable[[str, Optional[str], object], None]] = []


def register_kill_point(name: str, doc: str = "") -> str:
    _REGISTRY.setdefault(name, doc)
    return name


def register_durable_site(
    name: str,
    stages: Tuple[str, ...] = (STAGE_WRITE, STAGE_STAGED, STAGE_RENAMED),
    doc: str = "",
) -> str:
    """Register one file-writing site with its stage sub-points; returns
    the bare site name (the helpers derive the stage names from it)."""
    for st in stages:
        register_kill_point(name + st, doc)
    return name


def registered_kill_points() -> Dict[str, str]:
    return dict(_REGISTRY)


def add_kill_hook(hook: Callable) -> None:
    _hooks.append(hook)


def remove_kill_hook(hook: Callable) -> None:
    try:
        _hooks.remove(hook)
    except ValueError:
        pass


def clear_kill_hooks() -> None:
    del _hooks[:]


def kill_point(name: str, path: Optional[str] = None, ctx=None) -> None:
    """One named durable-write boundary.  No-op (one falsy check) unless
    a chaos hook is installed; hooks may corrupt ``path``, exit the
    process, or raise SimulatedProcessKill."""
    if not _hooks:
        return
    # snapshot: a hook that uninstalls itself must not skip its sibling
    for h in tuple(_hooks):
        h(name, path, ctx)


# -- durable-write helpers ---------------------------------------------------


def fsync_file(f) -> None:
    f.flush()
    os.fsync(f.fileno())


def fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it is durable.  Best-effort:
    some filesystems/platforms refuse O_RDONLY on directories — the
    rename itself is still atomic, only the OS-crash window widens."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def stage_write(path: str, data: bytes, point: Optional[str] = None, ctx=None) -> None:
    """Write + fsync a STAGING file in place (no rename) — for artifacts
    a later adoption step renames to their content-addressed home
    (``durable_rename``).  Kill-points: ``<point>:write`` (payload on
    disk, unsynced), ``<point>:staged`` (fsynced)."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        if point is not None:
            kill_point(point + STAGE_WRITE, path=path, ctx=ctx)
        os.fsync(f.fileno())
    if point is not None:
        kill_point(point + STAGE_STAGED, path=path, ctx=ctx)


def durable_rename(
    tmp: str,
    final: str,
    point: Optional[str] = None,
    ctx=None,
    presynced: bool = False,
) -> None:
    """Atomically move a fully-written staging file into place:
    fsync(file) → rename → fsync(dir).  Safe against a kill at any
    point: either the old name or the complete new file survives.
    ``presynced=True`` skips the file fsync for callers whose staging
    step already synced it (``stage_write`` / a durable stream close) —
    fsync dominates the discipline's cost on the close path."""
    if not presynced:
        fsync_path(tmp)
    if point is not None:
        kill_point(point + STAGE_STAGED, path=tmp, ctx=ctx)
    os.replace(tmp, final)
    if point is not None:
        kill_point(point + STAGE_RENAMED, path=final, ctx=ctx)
    fsync_dir(os.path.dirname(os.path.abspath(final)))


def durable_write(
    path: str, data, point: Optional[str] = None, ctx=None
) -> None:
    """The full atomic-durable write for one-shot artifacts:
    write-tmp → fsync → rename over ``path`` → fsync(dir).  ``data``
    may be str (utf-8) or bytes."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    d = os.path.dirname(os.path.abspath(path))
    tmp = os.path.join(
        d, f".durable-{uuid.uuid4().hex[:12]}-{os.path.basename(path)}"
    )
    try:
        stage_write(tmp, data, point=point, ctx=ctx)
        os.replace(tmp, path)
    except SimulatedProcessKill:
        # an in-process kill leaves the orphan tmp for the boot reaper,
        # exactly like a real process death would
        raise
    except BaseException:
        # never leave the orphan tmp behind on a Python-level failure
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    if point is not None:
        kill_point(point + STAGE_RENAMED, path=path, ctx=ctx)
    fsync_dir(d)
