"""VirtualClock / VirtualTimer — the event-loop heart of the node.

Reference: src/util/Timer.{h,cpp} — VirtualClock owns the asio io_service
(docs/architecture.md:24-36); everything consensus/IO runs single-threaded on
it, with a worker pool for self-contained CPU (and here, TPU-dispatch) work.

This is our own loop (not asyncio): a deque of posted callbacks, a heap of
timers, a ``selectors`` poller for sockets, and a thread pool whose results
are posted back through a self-pipe — the same shape as asio.  Two modes:

- REAL_TIME:   ``now()`` is the wall clock; ``crank(block=True)`` sleeps in
               ``select`` until IO or the next timer.
- VIRTUAL_TIME: ``now()`` only moves when the loop is idle, jumping straight
               to the next timer deadline — the reference's deterministic-test
               superpower (SURVEY.md §2.12), kept intact.
"""

from __future__ import annotations

import heapq
import os
import selectors
import socket
import threading
import time as _time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

REAL_TIME = "real"
VIRTUAL_TIME = "virtual"


from . import globalchecks


class VirtualClock:
    def __init__(self, mode: str = VIRTUAL_TIME, num_workers: Optional[int] = None):
        assert mode in (REAL_TIME, VIRTUAL_TIME)
        self.mode = mode
        self._virtual_now = 0.0
        self._queue: deque = deque()  # posted callbacks
        self._timers: List = []  # heap of (deadline, seq, TimerEvent)
        self._seq = 0
        self._stopped = False
        self._selector = selectors.DefaultSelector()
        self._n_watched = 0
        # thread -> main-loop handoff (asio's post from worker threads)
        self._xqueue: deque = deque()
        self._xlock = threading.Lock()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ, self._drain_wake)
        if num_workers is None:
            num_workers = os.cpu_count() or 2
        self._workers = ThreadPoolExecutor(
            max_workers=num_workers, thread_name_prefix="stellar-worker"
        )
        # reactor thread affinity (GlobalChecks assertThreadIsMain)
        self._owner_tid = threading.get_ident()

    # -- time --------------------------------------------------------------
    def now(self) -> float:
        """Seconds.  Virtual mode: logical time; real mode: unix time."""
        if self.mode == VIRTUAL_TIME:
            return self._virtual_now
        return _time.time()

    def set_current_virtual_time(self, t: float) -> None:
        assert self.mode == VIRTUAL_TIME
        assert t >= self._virtual_now
        self._virtual_now = t

    # -- posting -----------------------------------------------------------
    def post(self, fn: Callable[[], None]) -> None:
        """Queue fn to run on the next crank (io_service::post).  Owner
        thread only (GlobalChecks.h assertThreadIsMain); workers use
        post_from_thread."""
        globalchecks.assert_thread_is(self._owner_tid)
        self._queue.append(fn)

    def post_from_thread(self, fn: Callable[[], None]) -> None:
        """Thread-safe post; wakes a blocking crank."""
        with self._xlock:
            self._xqueue.append(fn)
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass

    def submit_work(self, fn: Callable, on_done: Callable = None) -> None:
        """Run fn on the worker pool; post on_done(result_or_exception) back
        to the main loop (the reference's worker-thread pattern,
        ApplicationImpl.cpp:120)."""

        def run():
            try:
                res = fn()
            except Exception as e:  # delivered, not swallowed
                res = e
            if on_done is not None:
                self.post_from_thread(lambda: on_done(res))

        self._workers.submit(run)

    # -- sockets -----------------------------------------------------------
    def watch(self, sock, events: int, cb: Callable[[int], None]) -> None:
        """Register cb(events) for readable/writable; selectors.EVENT_*."""
        try:
            self._selector.modify(sock, events, cb)
        except KeyError:
            self._selector.register(sock, events, cb)
            self._n_watched += 1

    def unwatch(self, sock) -> None:
        try:
            self._selector.unregister(sock)
            self._n_watched -= 1
        except KeyError:
            pass

    # -- timers (used by VirtualTimer) -------------------------------------
    def _schedule(self, deadline: float, ev: "_TimerEvent") -> None:
        self._seq += 1
        heapq.heappush(self._timers, (deadline, self._seq, ev))

    def next_deadline(self) -> Optional[float]:
        while self._timers and self._timers[0][2].dead:
            heapq.heappop(self._timers)
        return self._timers[0][0] if self._timers else None

    def has_ready_work(self) -> bool:
        """True if a crank would process something WITHOUT leaping virtual
        time: posted callbacks, watched IO, or an already-due timer.  Lets
        test harnesses crank to quiescence instead of leaping into
        far-future deadlines (e.g. peers' 30s idle-drop timers)."""
        with self._xlock:
            if self._xqueue:
                return True
        if self._queue:
            return True
        # a watched-but-quiet socket is NOT ready work: probe with a
        # zero-timeout select (nothing is consumed by selecting)
        if self._n_watched > 0 and self._selector.select(0):
            return True
        nd = self.next_deadline()
        return nd is not None and nd <= self.now()

    # -- the crank ---------------------------------------------------------
    def crank(self, block: bool = False, max_block: Optional[float] = None) -> int:
        """Run one burst of ready work; returns number of events processed.

        Mirrors VirtualClock::crank (util/Timer.cpp): drain posted work, poll
        IO, fire due timers; in VIRTUAL mode, if idle, jump time to the next
        deadline and fire it.  Owner thread only (Timer.cpp calls
        assertThreadIsMain at its crank entry).
        """
        globalchecks.assert_thread_is(self._owner_tid)
        if self._stopped:
            return 0
        n = 0
        # cross-thread arrivals
        with self._xlock:
            while self._xqueue:
                self._queue.append(self._xqueue.popleft())
        # posted callbacks — snapshot to keep re-posting loops fair
        burst = len(self._queue)
        for _ in range(burst):
            cb = self._queue.popleft()
            cb()
            n += 1
        # IO poll (non-blocking)
        n += self._poll_io(0)
        # due timers
        n += self._fire_due_timers()
        if n == 0:
            if self.mode == VIRTUAL_TIME:
                # real sockets under virtual time: give in-flight IO a short
                # real-time window before leaping the clock, else timers
                # (ballot timeouts etc.) race ahead of kernel delivery
                if self._n_watched > 0:
                    n += self._poll_io(0.005)
                    if n:
                        return n
                nd = self.next_deadline()
                if nd is not None:
                    self._virtual_now = max(self._virtual_now, nd)
                    n += self._fire_due_timers()
            elif block:
                nd = self.next_deadline()
                timeout = None if nd is None else max(0.0, nd - self.now())
                if max_block is not None:
                    timeout = max_block if timeout is None else min(timeout, max_block)
                n += self._poll_io(timeout)
                n += self._fire_due_timers()
        return n

    def _poll_io(self, timeout) -> int:
        n = 0
        for key, events in self._selector.select(timeout):
            key.data(events)
            n += 1
        return n

    def _fire_due_timers(self) -> int:
        n = 0
        now = self.now()
        while self._timers:
            deadline, _, ev = self._timers[0]
            if ev.dead:
                heapq.heappop(self._timers)
                continue
            if deadline > now:
                break
            heapq.heappop(self._timers)
            ev.fire(cancelled=False)
            n += 1
        return n

    def _drain_wake(self, _events) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    # -- lifecycle ---------------------------------------------------------
    @property
    def stopped(self) -> bool:
        return self._stopped

    def stop(self) -> None:
        self._stopped = True

    def shutdown(self) -> None:
        self.stop()
        self._workers.shutdown(wait=True)
        try:
            self._selector.unregister(self._wake_r)
        except KeyError:
            pass
        self._wake_r.close()
        self._wake_w.close()
        self._selector.close()

    def crank_until(self, pred: Callable[[], bool], timeout: float) -> bool:
        """Crank until pred() or `timeout` seconds pass on THIS clock.
        (Simulation::crankUntil, simulation/Simulation.h:59)."""
        stop_at = self.now() + timeout
        while not pred():
            if self.now() > stop_at or self._stopped:
                return pred()
            blocking = self.mode == REAL_TIME
            cap = max(0.0, stop_at - self.now()) if blocking else None
            if self.crank(block=blocking, max_block=cap) == 0:
                if self.mode == VIRTUAL_TIME and self.next_deadline() is None:
                    return pred()  # fully idle, nothing will ever happen
        return True

    def crank_for(self, seconds: float) -> None:
        stop_at = self.now() + seconds
        self.crank_until(lambda: self.now() >= stop_at, seconds + 1)


class _TimerEvent:
    __slots__ = ("on_trigger", "on_cancel", "dead")

    def __init__(self, on_trigger, on_cancel):
        self.on_trigger = on_trigger
        self.on_cancel = on_cancel
        self.dead = False

    def fire(self, cancelled: bool) -> None:
        if self.dead:
            return
        self.dead = True
        if cancelled:
            if self.on_cancel is not None:
                self.on_cancel()
        elif self.on_trigger is not None:
            self.on_trigger()


class VirtualTimer:
    """asio deadline-timer twin (util/Timer.h:177): arm with expires_*, then
    async_wait(on_trigger, on_cancel); cancel() fires on_cancel handlers."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._deadline: Optional[float] = None
        self._events: List[_TimerEvent] = []

    @property
    def deadline(self) -> Optional[float]:
        return self._deadline

    def seconds_remaining(self) -> float:
        if self._deadline is None:
            return 0.0
        return max(0.0, self._deadline - self._clock.now())

    def expires_at(self, t: float) -> None:
        self.cancel()
        self._deadline = t

    def expires_from_now(self, seconds: float) -> None:
        self.cancel()
        self._deadline = self._clock.now() + seconds

    def async_wait(self, on_trigger: Callable[[], None],
                   on_cancel: Optional[Callable[[], None]] = None) -> None:
        if self._deadline is None:
            raise RuntimeError("timer not armed; call expires_* first")
        ev = _TimerEvent(on_trigger, on_cancel)
        self._events = [e for e in self._events if not e.dead]
        self._events.append(ev)
        self._clock._schedule(self._deadline, ev)

    def cancel(self) -> None:
        for ev in self._events:
            if not ev.dead:
                ev.fire(cancelled=True)
        self._events.clear()
        self._deadline = None
