"""Partitioned logging (reference: src/util/Logging.{h,cpp} over easylogging++).

Partitions (Logging.h:17-27): Fs, SCP, Bucket, Database, History, Process,
Ledger, Overlay, Herder, Tx — each with a runtime-adjustable level, settable
globally or per-partition (the admin ``/ll`` endpoint uses this).
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

PARTITIONS = (
    "Fs",
    "SCP",
    "Bucket",
    "Database",
    "History",
    "Process",
    "Ledger",
    "Overlay",
    "Herder",
    "Tx",
)

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "none": logging.CRITICAL + 10,
}

_initialized = False


def init(level: str = "info", stream=None) -> None:
    global _initialized
    root = logging.getLogger("stellar_tpu")
    if not _initialized:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(name)s [%(levelname)s] %(message)s", "%H:%M:%S"
            )
        )
        root.addHandler(handler)
        root.propagate = False
        _initialized = True
    set_log_level(level)


_file_handler = None
_file_path = ""


def add_file(path: str) -> None:
    """Attach a log file (Config LOG_FILE_PATH).  Reopenable via rotate()."""
    global _file_handler, _file_path
    if not path:
        return
    root = logging.getLogger("stellar_tpu")
    if _file_handler is not None:
        root.removeHandler(_file_handler)
        _file_handler.close()
    _file_path = path
    _file_handler = logging.FileHandler(path)
    _file_handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(name)s [%(levelname)s] %(message)s", "%H:%M:%S"
        )
    )
    root.addHandler(_file_handler)


def rotate() -> bool:
    """Close and reopen the log file so an external rotator can move it
    (the /logrotate admin command; the reference's handler is a stub —
    CommandHandler.cpp:444 — this one actually reopens)."""
    if not _file_path:
        return False
    add_file(_file_path)
    return True


def logger(partition: str) -> logging.Logger:
    return logging.getLogger(f"stellar_tpu.{partition}")


def set_log_level(level: str, partition: Optional[str] = None) -> bool:
    """Set global or per-partition level; returns False on unknown names
    (admin /ll contract, CommandHandler.cpp:75)."""
    lv = _LEVELS.get(level.lower())
    if lv is None:
        return False
    if partition is None:
        logging.getLogger("stellar_tpu").setLevel(lv)
        for p in PARTITIONS:
            logger(p).setLevel(lv)
        return True
    if partition not in PARTITIONS:
        return False
    logger(partition).setLevel(lv)
    return True


def get_log_levels() -> dict:
    return {
        p: logging.getLevelName(logger(p).getEffectiveLevel()) for p in PARTITIONS
    }
