"""PendingEnvelopes — holds SCP envelopes until their dependencies are here
(reference: src/herder/PendingEnvelopes.{h,cpp}).

An SCP envelope can only be fed to consensus once its companion quorum set
and every tx set its values reference are locally known; missing items are
anycast-fetched from peers through the overlay's ItemFetchers.  Caches are
LRU so a malicious flood of hashes can't grow memory unboundedly.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

from ..util import xlog
from ..xdr.ledger import StellarValue
from ..xdr.overlay import MessageType
from ..xdr.scp import SCPEnvelope, SCPQuorumSet
from ..scp.quorum import qset_hash as compute_qset_hash

log = xlog.logger("Herder")

QSET_CACHE_SIZE = 10000
TXSET_CACHE_SIZE = 10000


class _LRU:
    def __init__(self, cap: int):
        self.cap = cap
        self.d: OrderedDict = OrderedDict()

    def get(self, k):
        if k in self.d:
            self.d.move_to_end(k)
            return self.d[k]
        return None

    def put(self, k, v):
        self.d[k] = v
        self.d.move_to_end(k)
        while len(self.d) > self.cap:
            self.d.popitem(last=False)

    def __contains__(self, k):
        return k in self.d


class PendingEnvelopes:
    def __init__(self, app, herder):
        self.app = app
        self.herder = herder
        # slot -> {envelope_bytes: envelope}
        self.processed: Dict[int, Dict[bytes, SCPEnvelope]] = {}
        self.fetching: Dict[int, Dict[bytes, SCPEnvelope]] = {}
        self.pending: Dict[int, List[SCPEnvelope]] = {}
        self.qset_cache = _LRU(QSET_CACHE_SIZE)
        self.txset_cache = _LRU(TXSET_CACHE_SIZE)
        self._recheck_posted = False
        self._shut_down = False
        self._size_counter = app.metrics.new_counter(
            ("scp", "memory", "pending-envelopes")
        )

    # -- item arrival -------------------------------------------------------
    def recv_scp_quorum_set(self, qs_hash: bytes, qset: SCPQuorumSet) -> None:
        self.qset_cache.put(qs_hash, qset)
        om = self.app.overlay_manager
        if om is not None:
            om.qset_fetcher.recv(qs_hash)
        self._post_recheck()

    def recv_tx_set(self, ts_hash: bytes, txset) -> None:
        self.txset_cache.put(ts_hash, txset)
        om = self.app.overlay_manager
        if om is not None:
            om.tx_set_fetcher.recv(ts_hash)
        self._post_recheck()

    def _post_recheck(self) -> None:
        """Coalesce dependency rechecks per crank (the overlay's SCP-batch
        idiom): fetch responses for several items routinely land in one
        delivery burst, and per-message rechecks both rescan ``fetching``
        O(items × envelopes) and — worse — cascade each newly-ready
        EXTERNALIZE into a synchronous ledger close MID-BURST.  One posted
        sweep readies the whole batch first, so a healed/lagging node's
        missed slots externalize back-to-back and drain through the close
        pipeline as a real >1-ledger backlog (dispatch-ahead prewarms the
        next txset while the current one applies) instead of closing
        serially inside the message handlers."""
        if self._recheck_posted:
            return
        # nothing wedged ⇒ nothing a recheck could ready — do NOT post:
        # an unconditional post would keep every crank non-idle, and a
        # VIRTUAL clock never leaps to its next timer while cranks have
        # work (the herder's own trigger path calls recv_tx_set on every
        # proposal, so this would freeze virtual time on quiet nodes)
        if not any(self.fetching.values()):
            return
        self._recheck_posted = True
        self.app.clock.post(self._run_posted_recheck)

    def shutdown(self) -> None:
        """Neutralize any already-posted recheck: clock.post callbacks
        cannot be cancelled, and a crashed/stopped node's posted sweep
        must not externalize ledgers against a closed database (the
        chaos plane's crash fault fires mid-crank)."""
        self._shut_down = True

    def _run_posted_recheck(self) -> None:
        self._recheck_posted = False
        if self._shut_down:
            return
        self._recheck_fetching()

    def get_qset(self, qs_hash: bytes) -> Optional[SCPQuorumSet]:
        return self.qset_cache.get(qs_hash)

    def get_tx_set(self, ts_hash: bytes):
        return self.txset_cache.get(ts_hash)

    def peer_doesnt_have(self, msg_type: MessageType, item_id: bytes, peer) -> None:
        om = self.app.overlay_manager
        if om is None:
            return
        if msg_type == MessageType.TX_SET:
            om.tx_set_fetcher.doesnt_have(item_id, peer)
        elif msg_type == MessageType.SCP_QUORUMSET:
            om.qset_fetcher.doesnt_have(item_id, peer)

    # -- dependencies -------------------------------------------------------
    def _required_items(self, envelope: SCPEnvelope):
        """(qset_hash, [txset hashes]) the envelope depends on."""
        from ..scp.slot import Slot

        st = envelope.statement
        qs = Slot.companion_qset_hash(st)  # None for EXTERNALIZE (self-quorum)
        txsets = []
        for v in Slot.statement_values(st):
            # FULL decode, deliberately not the cheaper xdr_getfield
            # (persist_scp_state uses it on our OWN statements): these
            # values arrive from unverified peers, and a value malformed
            # beyond a plausible-looking 32-byte prefix must be SKIPPED —
            # treating its prefix as a txset dependency would wedge the
            # envelope in `fetching` forever and spray item-fetch requests
            # for a hash nobody has (code-review r7 finding)
            try:
                sv = StellarValue.from_xdr(v)
            except Exception:
                continue
            txsets.append(sv.txSetHash)
        return qs, txsets

    def is_fully_fetched(self, envelope: SCPEnvelope) -> bool:
        qs, txsets = self._required_items(envelope)
        if qs is not None and qs not in self.qset_cache:
            return False
        return all(h in self.txset_cache for h in txsets)

    def _start_fetch(self, envelope: SCPEnvelope) -> None:
        om = self.app.overlay_manager
        if om is None:
            return
        qs, txsets = self._required_items(envelope)
        if qs is not None and qs not in self.qset_cache:
            om.qset_fetcher.fetch(qs, envelope)
        for h in txsets:
            if h not in self.txset_cache:
                om.tx_set_fetcher.fetch(h, envelope)

    # -- envelope flow ------------------------------------------------------
    def recv_scp_envelope(
        self, envelope: SCPEnvelope, raw: Optional[bytes] = None
    ) -> None:
        """``raw`` is the envelope's packed XDR when the caller already
        has it (the herder's post-verify plane packs it once for its
        getfield accounting) — the identity key here, saving a re-pack
        per envelope per queue touch."""
        slot = envelope.statement.slotIndex
        key = raw if raw is not None else envelope.to_xdr()
        if key in self.processed.get(slot, {}):
            return
        if key in self.fetching.get(slot, {}):
            return
        if self.is_fully_fetched(envelope):
            self._envelope_ready(envelope, key=key)
        else:
            self.fetching.setdefault(slot, {})[key] = envelope
            self._size_counter.inc()
            self._start_fetch(envelope)

    def _envelope_ready(
        self,
        envelope: SCPEnvelope,
        process: bool = True,
        key: Optional[bytes] = None,
    ) -> None:
        slot = envelope.statement.slotIndex
        if key is None:
            key = envelope.to_xdr()
        self.processed.setdefault(slot, {})[key] = envelope
        # flood the now-complete envelope onward (PendingEnvelopes.cpp
        # envelopeReady) — the Floodgate dedups, so relaying here is what
        # lets consensus traverse non-fully-meshed topologies
        om = self.app.overlay_manager
        if om is not None:
            from ..xdr.overlay import StellarMessage

            om.broadcast_message(
                StellarMessage(MessageType.SCP_MESSAGE, envelope)
            )
        self.pending.setdefault(slot, []).append(envelope)
        if process:
            self.herder.process_scp_queue()

    def _recheck_fetching(self) -> None:
        ready = []
        for slot, envs in self.fetching.items():
            for key, env in list(envs.items()):
                if self.is_fully_fetched(env):
                    del envs[key]
                    self._size_counter.dec()
                    ready.append((env, key))
        # queue the WHOLE ready batch before processing: when the batch
        # spans several externalizable slots (a lagging node's replay),
        # the herder's sweep sees them all pending and the ledger closes
        # drain as one pipelined backlog rather than one close per item
        for env, key in ready:
            self._envelope_ready(env, process=False, key=key)
        if ready:
            self.herder.process_scp_queue()

    def pop(self, slot_index: int) -> Optional[SCPEnvelope]:
        lst = self.pending.get(slot_index)
        if lst:
            return lst.pop(0)
        return None

    def ready_slots(self) -> List[int]:
        return sorted(s for s, lst in self.pending.items() if lst)

    def erase_below(self, slot_index: int) -> None:
        for d in (self.processed, self.fetching, self.pending):
            for s in [s for s in d if s < slot_index]:
                del d[s]

    def forget_above(self, slot_index: int) -> None:
        """Forget the PROCESSED memory for every slot past ``slot_index``
        (the herder's stall probe, ISSUE r19): envelopes already handed
        to SCP may have been value-rejected under local conditions that
        no longer hold (a healed clock), and the probe's replies carry
        the IDENTICAL packed bytes — without this the processed-dedup
        would swallow the replay.  Re-processing is safe: SCP statement
        handling is idempotent and the floodgate dedups the relay.
        ``fetching`` keeps its entries (still waiting on dependencies);
        ``pending`` keeps its queue (duplicates just re-feed SCP the
        same statement)."""
        for s in [s for s in self.processed if s > slot_index]:
            del self.processed[s]

    def slot_closed(self, slot_index: int) -> None:
        """Drop all state at or below the closed slot (keep newer)."""
        self.erase_below(slot_index + 1)

    def dump_info(self) -> dict:
        return {
            "pending": {s: len(v) for s, v in self.pending.items()},
            "fetching": {s: len(v) for s, v in self.fetching.items()},
            "qsets": len(self.qset_cache.d),
            "txsets": len(self.txset_cache.d),
        }
