"""Herder — consensus glue layer (reference: src/herder/, ~3.6 kLoC)."""

from .herder import (
    CONSENSUS_STUCK_TIMEOUT_SECONDS,
    EXP_LEDGER_TIMESPAN_SECONDS,
    HERDER_SYNCING_STATE,
    HERDER_TRACKING_STATE,
    LEDGER_VALIDITY_BRACKET,
    MAX_TIME_SLIP_SECONDS,
    TX_STATUS_DUPLICATE,
    TX_STATUS_ERROR,
    TX_STATUS_PENDING,
    Herder,
)
from .ledgerclose import LedgerCloseData
from .pendingenvelopes import PendingEnvelopes
from .txset import TxSetFrame

__all__ = [
    "Herder",
    "LedgerCloseData",
    "PendingEnvelopes",
    "TxSetFrame",
    "TX_STATUS_PENDING",
    "TX_STATUS_DUPLICATE",
    "TX_STATUS_ERROR",
    "EXP_LEDGER_TIMESPAN_SECONDS",
    "CONSENSUS_STUCK_TIMEOUT_SECONDS",
    "MAX_TIME_SLIP_SECONDS",
    "LEDGER_VALIDITY_BRACKET",
    "HERDER_SYNCING_STATE",
    "HERDER_TRACKING_STATE",
]
