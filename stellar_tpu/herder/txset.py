"""TxSetFrame (reference: src/herder/TxSetFrame.{h,cpp}).

Canonical form: transactions sorted by full hash; contents hash =
SHA256(previousLedgerHash ‖ envelopes-in-hash-order).  Apply order re-sorts
per account by sequence number with hash-XOR randomized interleave.

**Batch-verify hot spot** (SURVEY.md §2.2): ``check_valid``/``trim_invalid``
first collect every hint-matched (pubkey, contentsHash, sig) candidate across
the whole set and flush them through the app's SigBackend (TPU or CPU) into
the shared verify cache — one device round-trip for the entire set — then run
the reference's exact eager algorithm, which now hits only cache.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..crypto import SHA256
from ..trace import tracer_of
from ..tx.frame import TransactionFrame
from ..xdr.ledger import TransactionSet
from ..xdr.xtypes import PublicKey


def less_than_xored(l: bytes, r: bytes, x: bytes) -> bool:
    """util/types.cpp lessThanXored."""
    v1 = bytes(a ^ b for a, b in zip(x, l))
    v2 = bytes(a ^ b for a, b in zip(x, r))
    return v1 < v2


class TxSetFrame:
    def __init__(self, previous_ledger_hash: bytes, transactions=None):
        self.previous_ledger_hash = previous_ledger_hash
        self.transactions: List[TransactionFrame] = list(transactions or [])
        self._hash: Optional[bytes] = None
        self._triples_memo: Optional[list] = None

    @classmethod
    def from_xdr_set(cls, network_id: bytes, xdr_set: TransactionSet) -> "TxSetFrame":
        txs = [
            TransactionFrame.make_from_wire(network_id, env) for env in xdr_set.txs
        ]
        return cls(xdr_set.previousLedgerHash, txs)

    # -- canonical ordering & hash -----------------------------------------
    def sort_for_hash(self) -> None:
        self.transactions.sort(key=lambda tx: tx.get_full_hash())
        self._hash = None

    def get_contents_hash(self) -> bytes:
        if self._hash is None:
            self.sort_for_hash()
            h = SHA256()
            h.add(self.previous_ledger_hash)
            for tx in self.transactions:
                h.add(tx.env_xdr())
            self._hash = h.finish()
        return self._hash

    def add_transaction(self, tx: TransactionFrame) -> None:
        self.transactions.append(tx)
        self._hash = None
        self._triples_memo = None

    def remove_tx(self, tx: TransactionFrame) -> None:
        try:
            self.transactions.remove(tx)
        except ValueError:
            pass
        self._hash = None
        self._triples_memo = None

    def size(self) -> int:
        return len(self.transactions)

    def to_xdr(self) -> TransactionSet:
        self.sort_for_hash()
        return TransactionSet(
            self.previous_ledger_hash, [tx.envelope for tx in self.transactions]
        )

    # -- apply order (TxSetFrame.cpp:93-131) -------------------------------
    def sort_for_apply(self) -> List[TransactionFrame]:
        txs = sorted(self.transactions, key=lambda tx: tx.get_seq_num())
        batches: List[List[TransactionFrame]] = [[] for _ in range(4)]
        seen_count: Dict[bytes, int] = {}
        for tx in txs:
            v = seen_count.get(tx.source_bytes(), 0)
            if v >= len(batches):
                batches.extend([] for _ in range(4))
            batches[v].append(tx)
            seen_count[tx.source_bytes()] = v + 1

        # lessThanXored(l, r, x) is a lexicographic compare of l^x vs r^x,
        # which equals comparing the big-endian integers (l^x) < (r^x) —
        # so a key sort, not a comparator sort
        xh = int.from_bytes(self.get_contents_hash(), "big")
        out: List[TransactionFrame] = []
        for batch in batches:
            batch.sort(
                key=lambda tx: int.from_bytes(tx.get_full_hash(), "big") ^ xh
            )
            out.extend(batch)
        return out

    def collect_account_ids(self) -> set:
        """Every account this set can touch: tx sources, op sources, and
        op targets (create/payment/path destinations, merge target,
        allow-trust trustor).  Feeds AccountFrame.bulk_warm_cache before
        apply so big random-access ledgers avoid per-miss point SELECTs."""
        from ..xdr.txs import OperationType as OT

        ids = set()
        for tx in self.transactions:
            ids.add(tx.get_source_id())
            for op in tx.envelope.tx.operations:
                if op.sourceAccount is not None:
                    ids.add(op.sourceAccount)
                t = op.body.type
                v = op.body.value
                if t in (OT.CREATE_ACCOUNT, OT.PAYMENT, OT.PATH_PAYMENT):
                    ids.add(v.destination)
                elif t == OT.ACCOUNT_MERGE:
                    ids.add(v)  # merge body is the destination AccountID
                elif t == OT.ALLOW_TRUST:
                    ids.add(v.trustor)
        return ids

    # -- shared validity core ----------------------------------------------
    def _collect_signature_triples(self, app) -> list:
        """Memoized per set: collection does a readonly account load per tx
        (hint-matching needs the signers), and close_ledger prewarms the
        same set check_valid just prewarmed.  The triples are a pure
        prefetch — the eager check_signature path re-verifies anything the
        batch missed — so a memo gone stale against DB signer changes can
        only weaken the prefetch, never change a result.  Invalidated on
        add_transaction/remove_tx."""
        if self._triples_memo is None:
            triples = []
            for tx in self.transactions:
                triples.extend(tx.candidate_signature_pairs(app.database))
            self._triples_memo = triples
        return self._triples_memo

    def _prewarm_signature_cache(self, app) -> None:
        """One SigBackend batch for the entire set (the TPU flush point)."""
        backend = getattr(app, "sig_backend", None)
        if backend is None:
            return
        triples = self._collect_signature_triples(app)
        if triples:
            backend.verify_batch(triples)

    def prewarm_signature_cache_async(self, app):
        """Start the signature-cache prewarm via the backend's async flush
        surface (SigBackend.verify_batch_async); returns a join() the
        caller must invoke before any signature check can depend on the
        warmed cache.

        Triple collection (DB reads via candidate_signature_pairs) happens
        on the CALLER's thread — sqlite connections are not shared across
        threads here.  Only the pure-compute flush (hashing + device/
        libsodium verify + at-completion cache latch, SigFlushFuture) runs
        on the worker, which lets ledger close overlap it with fee
        processing (LedgerManager.close_ledger).

        join() is bounded even through a wedged accelerator transport:
        TpuSigBackend.verify_batch carries its own DEVICE_TIMEOUT + host
        fallback (covering every call site, not just this one); a worker
        error re-raises at join()."""
        from ..crypto.sigbackend import CALLER_CLOSE

        backend = getattr(app, "sig_backend", None)
        if backend is None or not hasattr(backend, "verify_batch_async"):
            return lambda: None
        triples = self._collect_signature_triples(app)
        if not triples:
            return lambda: None
        fut = backend.verify_batch_async(triples, caller=CALLER_CLOSE)
        return fut.result

    def _account_tx_map(self) -> Dict[bytes, List[TransactionFrame]]:
        m: Dict[bytes, List[TransactionFrame]] = {}
        for tx in self.transactions:
            m.setdefault(tx.source_bytes(), []).append(tx)
        return m

    @staticmethod
    def _check_account_chain(app, txs: List[TransactionFrame]):
        """Per-account: seq chain valid + can afford total fees.
        Returns (ok, invalid_txs)."""
        txs.sort(key=lambda t: t.get_seq_num())
        invalid = []
        last_tx = None
        last_seq = 0
        tot_fee = 0
        for tx in txs:
            if not tx.check_valid(app, last_seq):
                invalid.append(tx)
                continue
            tot_fee += tx.get_fee()
            last_tx = tx
            last_seq = tx.get_seq_num()
        if last_tx is not None:
            acct = last_tx.signing_account
            if acct.get_balance() - tot_fee < acct.get_minimum_balance(
                app.ledger_manager
            ):
                return False, txs  # whole account group is bad
        return True, invalid

    def check_valid(self, app) -> bool:
        """TxSetFrame.cpp:247-330."""
        with tracer_of(app).span("txset.validate", txs=len(self.transactions)):
            lcl = app.ledger_manager.get_last_closed_ledger_header()
            if lcl.hash != self.previous_ledger_hash:
                return False
            if len(self.transactions) > lcl.header.maxTxSetSize:
                return False

            last_hash = b"\x00" * 32
            for tx in self.transactions:
                if tx.get_full_hash() < last_hash:
                    return False  # not in canonical order
                last_hash = tx.get_full_hash()

            self._prewarm_signature_cache(app)

            for txs in self._account_tx_map().values():
                ok, invalid = self._check_account_chain(app, list(txs))
                if not ok or invalid:
                    return False
            return True

    def trim_invalid(self, app) -> List[TransactionFrame]:
        """Remove invalid txs; returns the trimmed ones (TxSetFrame.cpp:190)."""
        self.sort_for_hash()
        self._prewarm_signature_cache(app)
        trimmed: List[TransactionFrame] = []
        for txs in self._account_tx_map().values():
            ok, invalid = self._check_account_chain(app, list(txs))
            if not ok:
                for tx in txs:
                    trimmed.append(tx)
                    self.remove_tx(tx)
            else:
                for tx in invalid:
                    trimmed.append(tx)
                    self.remove_tx(tx)
        return trimmed

    # -- surge pricing (TxSetFrame.cpp:156-186) ----------------------------
    def surge_pricing_filter(self, lm) -> None:
        max_size = lm.get_max_tx_set_size()
        if len(self.transactions) <= max_size:
            return
        account_fee: Dict[bytes, float] = {}
        for tx in self.transactions:
            r = tx.get_fee() / tx.get_min_fee(lm)
            cur = account_fee.get(tx.source_bytes(), 0.0)
            if cur == 0 or r < cur:
                account_fee[tx.source_bytes()] = r

        def surge_key(tx):
            # higher fee ratio first; ties by account id; within an account by seq
            return (
                -account_fee[tx.source_bytes()],
                tx.source_bytes(),
                tx.get_seq_num(),
            )

        ordered = sorted(self.transactions, key=surge_key)
        for tx in ordered[max_size:]:
            self.remove_tx(tx)
