"""LedgerCloseData (reference: src/herder/LedgerCloseData.h):
the (ledgerSeq, TxSet, StellarValue) bundle consensus hands to the ledger."""

from __future__ import annotations

from dataclasses import dataclass

from ..xdr.ledger import StellarValue
from .txset import TxSetFrame


@dataclass
class LedgerCloseData:
    ledger_seq: int
    tx_set: TxSetFrame
    value: StellarValue
