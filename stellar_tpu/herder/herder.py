"""Herder — glue between SCP and the rest of the node
(reference: src/herder/HerderImpl.{h,cpp}).

Implements SCPDriver over the application: slot = ledger sequence, value =
XDR-encoded ``StellarValue{txSetHash, closeTime, upgrades}``.  Owns the
4-generation pending-transaction queues, the ledger trigger timer, and the
tracking/not-tracking consensus state machine (herder/readme.md).

Batch-verify note (the TPU angle): inbound SCP envelope signatures all
funnel through ``verify_envelope`` → the shared verify cache; envelopes
arriving through the overlay are coalesced per crank and verified in one
SigBackend batch by ``OverlayManager._flush_scp_batch`` before being fed
here one by one, so the eager check is a cache hit (same pattern as
TxSetFrame.check_valid).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto import PubKeyUtils, sha256
from ..scp import SCP, SCPDriver
from ..scp.quorum import iter_all_nodes
from ..scp.quorum import qset_hash as compute_qset_hash
from ..scp.slot import Slot
from ..util import VirtualTimer, fs, xlog
from ..xdr.base import xdr_getfield, xdr_to_opaque
from ..xdr.entries import EnvelopeType
from ..xdr.ledger import (
    LedgerUpgrade,
    LedgerUpgradeType,
    StellarValue,
)
from ..xdr.overlay import MessageType, StellarMessage
from ..xdr.scp import SCPEnvelope, SCPQuorumSet
from ..xdr.txs import TransactionResultCode
from ..xdr.xtypes import NodeID, PublicKey
from .ledgerclose import LedgerCloseData
from .pendingenvelopes import PendingEnvelopes
from .txset import TxSetFrame

log = xlog.logger("Herder")

# protocol cadence constants (reference: src/herder/Herder.cpp:7-12)
EXP_LEDGER_TIMESPAN_SECONDS = 5
MAX_SCP_TIMEOUT_SECONDS = 240
CONSENSUS_STUCK_TIMEOUT_SECONDS = 35
MAX_TIME_SLIP_SECONDS = 60
NODE_EXPIRATION_SECONDS = 240
LEDGER_VALIDITY_BRACKET = 1000
MAX_SLOTS_TO_REMEMBER = 4

# storage kill-points (util/fs.py): the SCP-state persist is the boot
# reconciliation's third leg next to the header chain + publish queue
KP_SCP_PERSIST_PRE = fs.register_kill_point(
    "scp.persist:pre", "lastscpdata row about to be written"
)
KP_SCP_PERSIST_POST = fs.register_kill_point(
    "scp.persist:post", "lastscpdata row written (autocommit durable)"
)

# TransactionSubmitStatus (herder/Herder.h)
TX_STATUS_PENDING = "PENDING"
TX_STATUS_DUPLICATE = "DUPLICATE"
TX_STATUS_ERROR = "ERROR"

# Herder::State
HERDER_SYNCING_STATE = "HERDER_SYNCING_STATE"
HERDER_TRACKING_STATE = "HERDER_TRACKING_STATE"


@dataclass
class ConsensusData:
    """Last tracked consensus slot + value (HerderImpl.h ConsensusData)."""

    index: int
    value: StellarValue


@dataclass
class TxMap:
    """Per-account pending transactions (HerderImpl.h TxMap)."""

    transactions: Dict[bytes, object] = field(default_factory=dict)  # fullhash -> tx
    max_seq: int = 0
    total_fees: int = 0

    def add_tx(self, tx) -> None:
        h = tx.get_full_hash()
        if h in self.transactions:
            return
        self.transactions[h] = tx
        self.max_seq = max(tx.get_seq_num(), self.max_seq)
        self.total_fees += tx.get_fee()

    def recalculate(self) -> None:
        self.max_seq = max((t.get_seq_num() for t in self.transactions.values()), default=0)
        self.total_fees = sum(t.get_fee() for t in self.transactions.values())


class Herder(SCPDriver):
    def __init__(self, app):
        self.app = app
        self.ledger_manager = app.ledger_manager
        cfg = app.config

        if cfg.NODE_SEED is None:
            raise ValueError("NODE_SEED required to run a herder")
        self.secret_key = cfg.NODE_SEED
        self.scp = SCP(
            self,
            self.secret_key.get_public_key(),
            cfg.NODE_IS_VALIDATOR,
            cfg.QUORUM_SET,
        )
        self.pending_envelopes = PendingEnvelopes(app, self)
        # publish our own quorum set so statements referencing it resolve
        self.pending_envelopes.recv_scp_quorum_set(
            self.scp.local_qset_hash, cfg.QUORUM_SET
        )

        # 4 generations of received txs, shifted at each close
        # (HerderImpl.h:157, HerderImpl.cpp:611-628)
        self.received_transactions: List[Dict[bytes, TxMap]] = [{} for _ in range(4)]
        # ingest-rate fast lane over the generations (ISSUE r20
        # satellite): every pending tx hash (duplicate checks go through
        # ONE set instead of a per-generation probe) and a per-account
        # cache of (total fees, highest seq) summed ACROSS generations.
        # Aging only moves txs between generations — the cross-generation
        # aggregate is invariant under it — so the cache is dropped only
        # where txs actually leave the queue (_remove_received_txs).
        self._pending_tx_ids: set = set()
        self._acct_agg: Dict[bytes, List[int]] = {}

        self.tracking: Optional[ConsensusData] = None
        self.current_value: bytes = b""
        self.last_trigger: Optional[float] = None

        clock = app.clock
        self.trigger_timer = VirtualTimer(clock)
        self.rebroadcast_timer = VirtualTimer(clock)
        self.tracking_timer = VirtualTimer(clock)
        # slot -> timer_id -> VirtualTimer (SCP nomination/ballot timers)
        self.scp_timers: Dict[int, Dict[int, VirtualTimer]] = {}

        # trace/ spans keyed by slot index: whole-slot consensus
        # (nominate → externalize), the currently-open nomination round,
        # and the ballot phase.  Dangling spans for slots that never
        # externalize are dropped (never ring-recorded) when a newer slot
        # completes.
        self._trace_slot_spans: Dict[int, object] = {}
        self._trace_nom_spans: Dict[int, object] = {}
        self._trace_ballot_spans: Dict[int, object] = {}

        # consensus-liveness counters (chaos-plane scoreboard,
        # stellar_tpu/scenarios/scoreboard.py): how many nomination rounds
        # opened and how many ballot rounds (max counter reached per slot)
        # consensus burned — under faults these climb while
        # ledgers-closed/wall-time falls, which is exactly the liveness
        # story the scoreboard tells
        self.n_nomination_rounds = 0
        self.n_ballot_rounds = 0
        self._ballot_round_high: Dict[int, int] = {}

        # per-slot aggregation buckets (TRUSTED post-verify accounting):
        # slot -> {statement-type int -> count} for envelopes that passed
        # the eager signature gate.  This is the herder-side ledger of
        # what the aggregate scheme's slot buckets saw — surfaced via
        # dump_info / the chaos scoreboard, trimmed with slot_closed.
        # Reads come from cxdrpack.getfield over the envelope's raw XDR
        # (HerderImpl.cpp:347-364's type switch), never a re-decode.
        # Hard-capped: while NOT tracking there is no slot bracket, so a
        # flood of validly-self-signed envelopes with arbitrary far-future
        # slot indexes would otherwise grow this dict unboundedly (the
        # close-time trim never reaches slots above the chain tip); when
        # full, the farthest-future slot loses its telemetry — honest
        # traffic clusters at the bracket's low end.
        self.scp_slot_buckets: Dict[int, Dict[int, int]] = {}
        self.MAX_SLOT_BUCKETS = 1024
        # lazy-deletion max-heap (negated slots) over scp_slot_buckets:
        # the at-cap evict decision is O(log n) per envelope instead of a
        # max() scan over 1024 keys — the scan would sit on exactly the
        # flood path the cap defends (valid-sig envelopes with arbitrary
        # fresh far-future slots).  Entries for slots trimmed elsewhere
        # (slot_closed) go stale in place and are popped when they
        # surface; a periodic rebuild bounds the stale mass.
        self._slot_bucket_heap: List[int] = []

        m = app.metrics
        self.m_envelope_sign = m.new_meter(("scp", "envelope", "sign"), "envelope")
        self.m_envelope_validsig = m.new_meter(("scp", "envelope", "validsig"), "envelope")
        self.m_envelope_invalidsig = m.new_meter(("scp", "envelope", "invalidsig"), "envelope")
        self.m_envelope_receive = m.new_meter(("scp", "envelope", "receive"), "envelope")
        self.m_envelope_emit = m.new_meter(("scp", "envelope", "emit"), "envelope")
        self.m_value_valid = m.new_meter(("scp", "value", "valid"), "value")
        self.m_value_invalid = m.new_meter(("scp", "value", "invalid"), "value")
        # time-slip rejections (ISSUE r19 satellite): the closeTime gates
        # in _validate_value_helper used to drop too-old/too-future values
        # SILENTLY — under inter-node clock skew these meters are the only
        # observable telling an operator "my clock disagrees with the
        # quorum" apart from unexplained liveness loss.  Surfaced in
        # dump_info and digested by the chaos scoreboard's skew classes.
        self.m_value_close_past = m.new_meter(
            ("herder", "value", "reject-closetime-past"), "value"
        )
        self.m_value_close_future = m.new_meter(
            ("herder", "value", "reject-closetime-future"), "value"
        )
        # stalled-while-tracking SCP-state probes (ISSUE r19): how often
        # this node, seeing signed evidence the quorum moved on without
        # it, asked its peers to replay their recent SCP state
        self.m_scp_state_probe = m.new_meter(
            ("herder", "scp-state", "probe"), "probe"
        )
        # duplicate tx submissions (ISSUE r20 satellite): a silent return
        # pre-r20 — under flood this is the cheapest reject in the node
        # and the meter is the only observable of re-flooded traffic
        self.m_tx_duplicate = m.new_meter(("herder", "tx", "duplicate"), "tx")
        # stall-probe bookkeeping (see _note_quorum_ahead): last local
        # consensus progress and last probe, on the app clock; the
        # quorum-member set is cached keyed by local qset hash
        self._last_progress_at = app.clock.now()
        self._last_probe_at = float("-inf")
        self._quorum_members: Optional[tuple] = None
        self.m_value_externalize = m.new_meter(("scp", "value", "externalize"), "value")
        self.m_quorum_heard = m.new_meter(("scp", "quorum", "heard"), "quorum")
        self.m_lost_sync = m.new_meter(("scp", "sync", "lost"), "sync")
        # post-verify per-statement-type meters (the reference's type
        # switch right after the eager verify, HerderImpl.cpp:347-364)
        from ..xdr.scp import SCPStatementType

        self.m_envelope_type = {
            int(t): m.new_meter(
                ("scp", "envelope", t.name.replace("SCP_ST_", "").lower()),
                "envelope",
            )
            for t in SCPStatementType
        }

    # ------------------------------------------------------------------
    # state machine
    # ------------------------------------------------------------------
    def get_state(self) -> str:
        return HERDER_TRACKING_STATE if self.tracking else HERDER_SYNCING_STATE

    def last_consensus_ledger_index(self) -> int:
        return self.tracking.index if self.tracking else 0

    def next_consensus_ledger_index(self) -> int:
        return self.last_consensus_ledger_index() + 1

    def get_current_ledger_seq(self) -> int:
        if self.tracking:
            return self.tracking.index
        return self.ledger_manager.get_last_closed_ledger_num()

    def shutdown(self) -> None:
        """Cancel every timer this herder armed on the (possibly shared)
        clock.  A crashed/stopped validator in a multi-node simulation must
        never fire a trigger or rebroadcast against its closed database —
        the chaos plane's crash/restart fault depends on this."""
        self.pending_envelopes.shutdown()
        self.trigger_timer.cancel()
        self.rebroadcast_timer.cancel()
        self.tracking_timer.cancel()
        for slot_timers in self.scp_timers.values():
            for t in slot_timers.values():
                t.cancel()
        self.scp_timers.clear()

    def bootstrap(self) -> None:
        """Force-join SCP from local state (FORCE_SCP; HerderImpl.cpp:160)."""
        assert self.scp.is_validator
        lcl = self.ledger_manager.get_last_closed_ledger_header()
        self.tracking = ConsensusData(lcl.header.ledgerSeq, lcl.header.scpValue)
        self._last_progress_at = self.app.clock.now()
        self._tracking_heartbeat()
        self.last_trigger = self.app.clock.now() - EXP_LEDGER_TIMESPAN_SECONDS
        self.ledger_closed()

    def _is_slot_compatible_with_current_state(self, slot_index: int) -> bool:
        return (
            self.ledger_manager.is_synced()
            and slot_index == self.ledger_manager.get_last_closed_ledger_num() + 1
        )

    def _tracking_heartbeat(self) -> None:
        if self.app.config.MANUAL_CLOSE:
            return
        assert self.tracking
        self.tracking_timer.expires_from_now(CONSENSUS_STUCK_TIMEOUT_SECONDS)
        self.tracking_timer.async_wait(self._out_of_sync)

    def _out_of_sync(self) -> None:
        log.info("Lost track of consensus")
        self.m_lost_sync.mark()
        self.tracking = None
        self.process_scp_queue()

    def lost_sync(self) -> None:
        """External notification (catchup started)."""
        pass

    # ------------------------------------------------------------------
    # SCPDriver: crypto
    # ------------------------------------------------------------------
    def _envelope_payload(self, envelope: SCPEnvelope) -> bytes:
        return xdr_to_opaque(
            self.app.network_id, EnvelopeType.ENVELOPE_TYPE_SCP, envelope.statement
        )

    def sign_envelope(self, envelope: SCPEnvelope) -> None:
        self.m_envelope_sign.mark()
        envelope.signature = self.secret_key.sign(self._envelope_payload(envelope))

    def _scheme(self):
        """The node's SCP signature scheme (Config.SCP_SIG_SCHEME); a
        bare test harness without an Application-built scheme rides the
        reference per-envelope path."""
        scheme = getattr(self.app, "scp_scheme", None)
        if scheme is None:
            from ..crypto.aggregate import make_scheme
            from ..crypto.keys import verify_cache

            scheme = make_scheme(
                "ed25519", self.app.sig_backend, verify_cache()
            )
            self.app.scp_scheme = scheme
        return scheme

    def verify_envelope(self, envelope: SCPEnvelope) -> bool:
        """The second runtime ed25519 hot spot (SURVEY §2.8 site 2);
        routed through the scheme seam — under either scheme this is a
        warm-cache hit for envelopes the overlay batch flush (or an
        aggregate-accepted slot bucket) already verified."""
        ok = self._scheme().verify_envelope_cached(
            envelope.statement.nodeID,
            envelope.signature,
            self._envelope_payload(envelope),
        )
        (self.m_envelope_validsig if ok else self.m_envelope_invalidsig).mark()
        return ok

    def envelope_verify_triple(self, envelope: SCPEnvelope):
        """(pubkey, msg, sig) for SigBackend batch pre-warming."""
        return (
            envelope.statement.nodeID.value,
            self._envelope_payload(envelope),
            envelope.signature,
        )

    # ------------------------------------------------------------------
    # SCPDriver: values
    # ------------------------------------------------------------------
    def _validate_value_helper(self, slot_index: int, sv: StellarValue) -> bool:
        compat = self._is_slot_compatible_with_current_state(slot_index)
        if compat:
            last_close_time = (
                self.ledger_manager.get_last_closed_ledger_header().header.scpValue.closeTime
            )
        else:
            if not self.tracking:
                return True  # not much more we can check
            if self.next_consensus_ledger_index() > slot_index:
                return True  # old slot: let it flow for final messages
            if self.next_consensus_ledger_index() < slot_index:
                log.error("validate_value: future message while tracking")
                return False
            last_close_time = self.tracking.value.closeTime

        if sv.closeTime <= last_close_time:
            self.m_value_close_past.mark()
            return False
        if sv.closeTime > self.app.time_now() + MAX_TIME_SLIP_SECONDS:
            self.m_value_close_future.mark()
            return False
        if not compat:
            return True

        tx_set = self.pending_envelopes.get_tx_set(sv.txSetHash)
        if tx_set is None:
            log.error("validate_value: txset %s not found", sv.txSetHash.hex()[:8])
            return False
        return tx_set.check_valid(self.app)

    def _validate_upgrade_step(self, raw: bytes) -> Optional[LedgerUpgradeType]:
        try:
            up = LedgerUpgrade.from_xdr(raw)
        except Exception:
            return None
        cfg = self.app.config
        if up.type == LedgerUpgradeType.LEDGER_UPGRADE_VERSION:
            ok = up.value == cfg.LEDGER_PROTOCOL_VERSION
        elif up.type == LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE:
            ok = cfg.DESIRED_BASE_FEE * 0.5 <= up.value <= cfg.DESIRED_BASE_FEE * 2
        elif up.type == LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            ok = (
                cfg.DESIRED_MAX_TX_PER_LEDGER * 7 // 10
                <= up.value
                <= cfg.DESIRED_MAX_TX_PER_LEDGER * 13 // 10
            )
        else:
            ok = False
        return up.type if ok else None

    def validate_value(self, slot_index: int, value: bytes) -> bool:
        try:
            sv = StellarValue.from_xdr(value)
        except Exception:
            self.m_value_invalid.mark()
            return False
        res = self._validate_value_helper(slot_index, sv)
        if res:
            last_type = -1
            for raw in sv.upgrades:
                t = self._validate_upgrade_step(raw)
                if t is None or int(t) <= last_type:
                    res = False
                    break
                last_type = int(t)
        (self.m_value_valid if res else self.m_value_invalid).mark()
        return res

    def extract_valid_value(self, slot_index: int, value: bytes) -> bytes:
        try:
            sv = StellarValue.from_xdr(value)
        except Exception:
            return b""
        if not self._validate_value_helper(slot_index, sv):
            return b""
        # drop just the upgrade steps we disagree with
        sv.upgrades = [u for u in sv.upgrades if self._validate_upgrade_step(u) is not None]
        return sv.to_xdr()

    def combine_candidates(self, slot_index: int, candidates) -> bytes:
        """Composite: max closeTime, per-type max upgrades, biggest txset
        (ties by hash xored with the candidates hash) — HerderImpl.cpp:646."""
        from .txset import less_than_xored

        lcl = self.ledger_manager.get_last_closed_ledger_header()
        comp = StellarValue(b"\x00" * 32, 0, [], 0)
        upgrades: Dict[LedgerUpgradeType, LedgerUpgrade] = {}
        candidates_hash = bytearray(32)
        values = []
        for c in sorted(candidates):
            sv = StellarValue.from_xdr(c)
            values.append(sv)
            h = sha256(c)
            candidates_hash = bytearray(a ^ b for a, b in zip(candidates_hash, h))
            comp.closeTime = max(comp.closeTime, sv.closeTime)
            for raw in sv.upgrades:
                up = LedgerUpgrade.from_xdr(raw)
                cur = upgrades.get(up.type)
                if cur is None or cur.value < up.value:
                    upgrades[up.type] = up

        best_tx_set = None
        highest = b"\x00" * 32
        for sv in values:
            cand = self.pending_envelopes.get_tx_set(sv.txSetHash)
            if cand is None or cand.previous_ledger_hash != lcl.hash:
                continue
            if (
                best_tx_set is None
                or cand.size() > best_tx_set.size()
                or (
                    cand.size() == best_tx_set.size()
                    and less_than_xored(highest, sv.txSetHash, bytes(candidates_hash))
                )
            ):
                best_tx_set = cand
                highest = sv.txSetHash

        for t in sorted(upgrades):
            comp.upgrades.append(upgrades[t].to_xdr())

        if best_tx_set is None:
            # every candidate's txset is missing locally (LRU eviction or
            # candidates validated while out of sync): propose an empty set
            # rather than crash — peers will converge on someone else's value
            log.warning("combine_candidates: no usable candidate txset")
            best_tx_set = TxSetFrame(lcl.hash)
            self.pending_envelopes.recv_tx_set(
                best_tx_set.get_contents_hash(), best_tx_set
            )

        # defensively re-trim: candidates went through validate_value but the
        # intersection of upgrades/sets must still be valid
        removed = best_tx_set.trim_invalid(self.app)
        comp.txSetHash = best_tx_set.get_contents_hash()
        if removed:
            log.warning("candidate set had %d invalid transactions", len(removed))
            self.app.clock.post(
                lambda: self.pending_envelopes.recv_tx_set(
                    best_tx_set.get_contents_hash(), best_tx_set
                )
            )
        return comp.to_xdr()

    def get_value_string(self, value: bytes) -> str:
        if not value:
            return "[:empty:]"
        try:
            sv = StellarValue.from_xdr(value)
            return f"[txH: {sv.txSetHash.hex()[:8]}, ct: {sv.closeTime}, upgrades: {len(sv.upgrades)}]"
        except Exception:
            return "[:invalid:]"

    # ------------------------------------------------------------------
    # SCPDriver: infrastructure
    # ------------------------------------------------------------------
    def get_qset(self, qs_hash: bytes) -> Optional[SCPQuorumSet]:
        return self.pending_envelopes.get_qset(qs_hash)

    def setup_timer(self, slot_index: int, timer_id: int, timeout: float, cb) -> None:
        # don't arm timers for old slots
        if self.tracking and slot_index < self.tracking.index:
            self.scp_timers.pop(slot_index, None)
            return
        slot_timers = self.scp_timers.setdefault(slot_index, {})
        timer = slot_timers.get(timer_id)
        if timer is None:
            timer = slot_timers.setdefault(timer_id, VirtualTimer(self.app.clock))
        timer.cancel()
        if cb is not None:
            timer.expires_from_now(timeout)
            timer.async_wait(cb)

    def emit_envelope(self, envelope: SCPEnvelope) -> None:
        if not self.scp.is_validator:
            return
        slot_index = envelope.statement.slotIndex
        # don't broadcast state changes made while out of sync
        if not self._is_slot_compatible_with_current_state(slot_index) and (
            not self.tracking or not self.ledger_manager.is_synced()
        ):
            return
        # persist for the emitted slot, not get_ledger_num(): when an emit
        # cascades synchronously into externalize + close (single-node
        # networks), the close advances the ledger pointer before this line
        # runs and persisting "current" would store an empty blob
        self.persist_scp_state(slot_index)
        self._broadcast(envelope)
        self._start_rebroadcast_timer()

    def _broadcast(self, envelope: SCPEnvelope) -> None:
        if self.app.config.MANUAL_CLOSE:
            return
        om = self.app.overlay_manager
        if om is None:
            return
        self.m_envelope_emit.mark()
        om.broadcast_message(
            StellarMessage(MessageType.SCP_MESSAGE, envelope), force=True
        )

    def _rebroadcast(self) -> None:
        for e in self.scp.get_latest_messages_send(self.ledger_manager.get_ledger_num()):
            self._broadcast(e)
        self._start_rebroadcast_timer()

    def _start_rebroadcast_timer(self) -> None:
        self.rebroadcast_timer.expires_from_now(2)
        self.rebroadcast_timer.async_wait(self._rebroadcast)

    # ------------------------------------------------------------------
    # SCPDriver: monitoring
    # ------------------------------------------------------------------
    def ballot_did_hear_from_quorum(self, slot_index: int, ballot) -> None:
        self.m_quorum_heard.mark()

    def nominating_value(self, slot_index: int, value: bytes) -> None:
        log.debug("nominating value i=%d v=%s", slot_index, self.get_value_string(value))

    def nomination_round_started(
        self, slot_index: int, round_number: int, timed_out: bool
    ) -> None:
        """Per-round nomination latency: round N's span closes when round
        N+1 starts (its timer fired), a ballot begins, or the slot
        externalizes."""
        self.n_nomination_rounds += 1
        tr = self.app.tracer
        tr.end(self._trace_nom_spans.pop(slot_index, None))
        self._trace_nom_spans[slot_index] = tr.begin(
            "scp.nominate_round",
            slot=slot_index,
            round=round_number,
            timed_out=timed_out,
        )

    def started_ballot_protocol(self, slot_index: int, ballot) -> None:
        # liveness: the highest ballot counter this slot reached is its
        # ballot-round count; accumulated into n_ballot_rounds when the
        # slot externalizes (or discarded with the stale-slot sweep there)
        high = self._ballot_round_high.get(slot_index, 0)
        self._ballot_round_high[slot_index] = max(high, ballot.counter)
        tr = self.app.tracer
        tr.end(self._trace_nom_spans.pop(slot_index, None))
        # only the FIRST ballot opens the span — later bump_state calls are
        # counter bumps inside the same ballot phase
        if slot_index not in self._trace_ballot_spans:
            self._trace_ballot_spans[slot_index] = tr.begin(
                "scp.ballot", slot=slot_index
            )

    # ------------------------------------------------------------------
    # externalization
    # ------------------------------------------------------------------
    def value_externalized(self, slot_index: int, value: bytes) -> None:
        self.m_value_externalize.mark()
        self.n_ballot_rounds += self._ballot_round_high.pop(slot_index, 0)
        tr = self.app.tracer
        tr.end(self._trace_nom_spans.pop(slot_index, None))
        tr.end(self._trace_ballot_spans.pop(slot_index, None))
        tr.end(self._trace_slot_spans.pop(slot_index, None))
        for d in (
            self._trace_nom_spans,
            self._trace_ballot_spans,
            self._trace_slot_spans,
            self._ballot_round_high,
        ):
            for stale in [s for s in d if s < slot_index]:
                d.pop(stale)
        self.scp_timers.pop(slot_index, None)
        sv = StellarValue.from_xdr(value)  # validated upstream; crash if not

        self.current_value = b""
        self.tracking = ConsensusData(slot_index, sv)
        self._last_progress_at = self.app.clock.now()
        self._tracking_heartbeat()

        externalized_set = self.pending_envelopes.get_tx_set(sv.txSetHash)
        self.trigger_timer.cancel()

        ledger_data = LedgerCloseData(slot_index, externalized_set, sv)
        self.ledger_manager.externalize_value(ledger_data)

        self._remove_received_txs(externalized_set.transactions)

        # rebroadcast generation-1 leftovers in apply order
        om = self.app.overlay_manager
        if om is not None:
            leftovers = TxSetFrame(b"\x00" * 32)
            for txmap in self.received_transactions[1].values():
                for tx in txmap.transactions.values():
                    leftovers.add_transaction(tx)
            for tx in leftovers.sort_for_apply():
                om.broadcast_message(tx.to_stellar_message())

        if slot_index > MAX_SLOTS_TO_REMEMBER:
            self.scp.purge_slots(slot_index - MAX_SLOTS_TO_REMEMBER)

        self._age_pending_transactions()
        self.ledger_closed()

    def _age_pending_transactions(self) -> None:
        """Shift each generation up one; the oldest generation keeps
        accumulating (HerderImpl.cpp:611-628)."""
        for n in range(len(self.received_transactions) - 1, 0, -1):
            curr, prev = self.received_transactions[n], self.received_transactions[n - 1]
            for acc, txmap in prev.items():
                dst = curr.setdefault(acc, TxMap())
                for tx in txmap.transactions.values():
                    dst.add_tx(tx)
            prev.clear()

    def ledger_closed(self) -> None:
        """Arm the next trigger (HerderImpl.cpp:1090-1160)."""
        self.trigger_timer.cancel()
        last_index = self.last_consensus_ledger_index()
        self.pending_envelopes.slot_closed(last_index)
        for s in [s for s in self.scp_slot_buckets if s <= last_index]:
            del self.scp_slot_buckets[s]
        om = self.app.overlay_manager
        if om is not None:
            om.ledger_closed(last_index)

        next_index = self.next_consensus_ledger_index()
        # process statements for the new slot (may externalize immediately)
        self._process_scp_queue_at_index(next_index)
        if next_index != self.next_consensus_ledger_index():
            return  # externalized a newer slot; obsolete trigger

        if not self.scp.is_validator or not self.ledger_manager.is_synced():
            return

        seconds = EXP_LEDGER_TIMESPAN_SECONDS
        if self.app.config.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING:
            seconds = 1

        now = self.app.clock.now()
        if self.last_trigger is not None and (now - self.last_trigger) < seconds:
            self.trigger_timer.expires_from_now(seconds - (now - self.last_trigger))
        else:
            self.trigger_timer.expires_from_now(0)
        if not self.app.config.MANUAL_CLOSE:
            self.trigger_timer.async_wait(lambda: self.trigger_next_ledger(next_index))

    # ------------------------------------------------------------------
    # transaction queue
    # ------------------------------------------------------------------
    def recv_transaction(self, tx) -> str:
        acc = tx.source_bytes()
        tx_id = tx.get_full_hash()

        # O(1) duplicate check against ALL generations (a tx hash lives in
        # at most one generation; aging moves it, removal discards it)
        if tx_id in self._pending_tx_ids:
            self.m_tx_duplicate.mark()
            return TX_STATUS_DUPLICATE

        agg = self._acct_agg.get(acc)
        if agg is None:
            fees = 0
            high_seq = 0
            for gen in self.received_transactions:
                txmap = gen.get(acc)
                if txmap is not None:
                    fees += txmap.total_fees
                    high_seq = max(high_seq, txmap.max_seq)
            agg = [fees, high_seq]
            self._acct_agg[acc] = agg
        tot_fee = tx.get_fee() + agg[0]

        if not tx.check_valid(self.app, agg[1]):
            return TX_STATUS_ERROR

        if tx.signing_account.get_balance_above_reserve(self.ledger_manager) < tot_fee:
            tx.set_result_code(TransactionResultCode.txINSUFFICIENT_BALANCE)
            return TX_STATUS_ERROR

        self.received_transactions[0].setdefault(acc, TxMap()).add_tx(tx)
        self._pending_tx_ids.add(tx_id)
        agg[0] += tx.get_fee()
        if tx.get_seq_num() > agg[1]:
            agg[1] = tx.get_seq_num()
        return TX_STATUS_PENDING

    def recv_tx_set_txs(self, txset) -> bool:
        """Feed every tx of a downloaded set into the queue — through the
        ingest plane's replay edge when it exists (ONE batched signature
        dispatch per accumulator fill instead of per-tx eager verifies;
        no rate/surge admission on replay), else per-tx."""
        txs = txset.sort_for_apply()
        ingest = getattr(self.app, "ingest", None)
        if ingest is not None:
            statuses = ingest.submit_replay(txs)
            return all(s == TX_STATUS_PENDING for s in statuses)
        ok = True
        for tx in txs:
            if self.recv_transaction(tx) != TX_STATUS_PENDING:
                ok = False
        return ok

    def num_pending_txs(self) -> int:
        """Queue depth across all generations (the ingest plane's surge
        high-water measure)."""
        return len(self._pending_tx_ids)

    def get_max_seq_in_pending_txs(self, acc: PublicKey) -> int:
        high = 0
        for gen in self.received_transactions:
            txmap = gen.get(acc.value)
            if txmap is not None:
                high = max(high, txmap.max_seq)
        return high

    def _remove_received_txs(self, drop_txs) -> None:
        for gen in self.received_transactions:
            if not gen:
                continue
            dirty = set()
            for tx in drop_txs:
                acc = tx.source_bytes()
                txmap = gen.get(acc)
                if txmap is None:
                    continue
                if txmap.transactions.pop(tx.get_full_hash(), None) is not None:
                    self._pending_tx_ids.discard(tx.get_full_hash())
                    if not txmap.transactions:
                        del gen[acc]
                    else:
                        dirty.add(acc)
            for acc in dirty:
                if acc in gen:
                    gen[acc].recalculate()
        # fee/seq aggregates for the touched accounts are stale now;
        # recomputed lazily at the next submission from each account
        for tx in drop_txs:
            self._acct_agg.pop(tx.source_bytes(), None)

    # ------------------------------------------------------------------
    # SCP envelope queue
    # ------------------------------------------------------------------
    def recv_scp_envelope(self, envelope: SCPEnvelope) -> None:
        if self.app.config.MANUAL_CLOSE:
            return
        self.m_envelope_receive.mark()
        if self.tracking:
            min_seq = self.next_consensus_ledger_index()
            max_seq = min_seq + LEDGER_VALIDITY_BRACKET
            if not (min_seq <= envelope.statement.slotIndex <= max_seq):
                return
        # flood fast-reject (the reference's eager verify,
        # HerderImpl.cpp:347-364): an envelope whose signature fails must
        # never reach the fetch plane — a byzantine flood of invalid-sig
        # envelopes referencing made-up qset/txset hashes would otherwise
        # wedge in `fetching` forever AND spray item-fetch requests for
        # hashes nobody has.  Routed through the scheme seam: the
        # overlay's per-crank batch flush (per-envelope or aggregate)
        # already verified-and-dropped its batch, so this check is a
        # warm-cache hit for every honest envelope; only the reject marks
        # here — the accept mark stays at SCP's own pre-process verify so
        # validsig/invalidsig stay one-mark-per-envelope.
        ok = self._scheme().verify_envelope_cached(
            envelope.statement.nodeID,
            envelope.signature,
            self._envelope_payload(envelope),
        )
        if not ok:
            self.m_envelope_invalidsig.mark()
            return
        # TRUSTED post-verify plane from here on: the envelope's raw XDR
        # (packed from our own decode, signature just checked) serves the
        # hot slot-index / statement-type reads via the C field accessors
        # — no re-decode — and doubles as the pending-envelope identity
        # key, so the queue never re-packs it (reference anchor
        # HerderImpl.cpp:347-364's post-verify type switch; the UNTRUSTED
        # pre-verify ingest above keeps full decode, per the PR 3
        # rationale in pendingenvelopes._required_items).
        raw = envelope.to_xdr()
        slot = xdr_getfield(SCPEnvelope, raw, "statement.slotIndex")
        stype = xdr_getfield(SCPEnvelope, raw, ("statement", "pledges"))
        meter = self.m_envelope_type.get(stype)
        if meter is not None:
            meter.mark()
        # stalled-while-tracking recovery (ISSUE r19): a signed envelope
        # for a FUTURE slot from a node IN OUR TRANSITIVE QUORUM is
        # evidence the quorum externalized slots we never closed.  A
        # node that stalls WITHOUT losing its connections — one-way
        # partition (it hears nothing but is heard), beyond-slip clock
        # skew (it hears everything and rejects it) — never gets the
        # on-connect SCP-state replay that heals a reconnecting node,
        # and pre-r19 its only way back was a full history-archive
        # catchup once the gap outgrew MAX_SLOTS_TO_REMEMBER.  Probe
        # instead: ask peers to replay their recent state while the gap
        # is still inside the window.  The membership gate keeps an
        # unprivileged valid-sig key from repeatedly wiping the flood
        # dedup + triggering GET_SCP_STATE amplification on a merely
        # slow (not left-behind) node.
        if (
            self.tracking
            and slot > self.next_consensus_ledger_index()
            and self._in_transitive_quorum(envelope.statement.nodeID)
        ):
            self._note_quorum_ahead()
        bucket = self.scp_slot_buckets.get(slot)
        if bucket is None:
            make = True
            if len(self.scp_slot_buckets) >= self.MAX_SLOT_BUCKETS:
                evict = self._slot_bucket_max()
                if evict is not None and slot < evict:
                    del self.scp_slot_buckets[evict]
                    heapq.heappop(self._slot_bucket_heap)
                else:
                    make = False  # farther than everything tracked
            if make:
                bucket = self.scp_slot_buckets.setdefault(slot, {})
                heapq.heappush(self._slot_bucket_heap, -slot)
                # stale entries from slot_closed trims accrue even far
                # below cap (one per closed slot, forever)
                self._maybe_rebuild_slot_bucket_heap()
        if bucket is not None:
            bucket[stype] = bucket.get(stype, 0) + 1
        self.pending_envelopes.recv_scp_envelope(envelope, raw=raw)

    def _maybe_rebuild_slot_bucket_heap(self) -> None:
        """Rebuild the lazy heap when stale entries outnumber live ones
        ~3:1 — the bound is relative to LIVE size (not the cap) so a
        healthy below-cap node's per-closed-slot stale entries can never
        accumulate; amortized O(1) over the pushes that grew it."""
        heap = self._slot_bucket_heap
        if len(heap) > 4 * max(len(self.scp_slot_buckets), 16):
            heap[:] = [-s for s in self.scp_slot_buckets]
            heapq.heapify(heap)

    def _slot_bucket_max(self) -> Optional[int]:
        """Largest slot currently tracked in scp_slot_buckets, via the
        lazy-deletion heap: stale tops (slots trimmed by slot_closed)
        pop here; amortized cost O(log n) per envelope."""
        self._maybe_rebuild_slot_bucket_heap()
        heap = self._slot_bucket_heap
        while heap:
            s = -heap[0]
            if s in self.scp_slot_buckets:
                return s
            heapq.heappop(heap)
        return None

    def _in_transitive_quorum(self, node_id) -> bool:
        """Is ``node_id`` mentioned anywhere in our (nested) local quorum
        set?  Cached keyed by the local qset hash so the walk happens
        once per qset, not per envelope."""
        qh = self.scp.local_qset_hash
        cached = self._quorum_members
        if cached is None or cached[0] != qh:
            members = frozenset(
                n.value for n in iter_all_nodes(self.scp.local_qset)
            )
            self._quorum_members = cached = (qh, members)
        return node_id.value in cached[1]

    def _trigger_cadence(self) -> float:
        """The expected seconds between closes on this node's config."""
        if self.app.config.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING:
            return 1.0
        return float(EXP_LEDGER_TIMESPAN_SECONDS)

    def _note_quorum_ahead(self) -> None:
        """Signed evidence arrived that the quorum is past our next slot.
        If we have made no local progress for two close cadences, the
        quorum externalized without us — rate-limited to one probe per
        cadence, ask every authenticated peer for its recent SCP state
        (GET_SCP_STATE 0 → send_scp_state_to_peer replays max-3..max),
        the same ≤MAX_SLOTS_TO_REMEMBER replay a reconnecting peer gets
        at AUTH.  Before probing, the pending-envelope plane forgets the
        gap slots: envelopes we already handed to SCP may have been
        rejected under conditions that no longer hold (a healed clock
        re-validates the same closeTime), and the replies re-deliver the
        identical packed bytes the processed-dedup would otherwise
        swallow."""
        now = self.app.clock.now()
        cadence = self._trigger_cadence()
        if now - self._last_progress_at < 2 * cadence:
            return
        if now - self._last_probe_at < cadence:
            return
        om = self.app.overlay_manager
        if om is None:
            return
        peers = om.authenticated_peers()
        if not peers:
            return
        self._last_probe_at = now
        self.pending_envelopes.forget_above(
            self.last_consensus_ledger_index()
        )
        # ...and the overlay's at-most-once flood memory for the same
        # window: the replies re-deliver packed-identical messages the
        # floodgate would otherwise drop before the herder sees them
        om.floodgate.forget_from(self.next_consensus_ledger_index())
        self.m_scp_state_probe.mark()
        log.info(
            "quorum ahead of slot %d with no local progress: probing %d"
            " peer(s) for recent SCP state",
            self.next_consensus_ledger_index(),
            len(peers),
        )
        for peer in peers:
            peer.send_message(
                StellarMessage(MessageType.GET_SCP_STATE, 0)
            )

    def note_envelope_rejected(self, envelope: SCPEnvelope) -> None:
        """The overlay's batch flush verified this envelope's signature
        invalid and dropped it before the herder — account it exactly like
        the eager-reject path above would have."""
        self.m_envelope_receive.mark()
        self.m_envelope_invalidsig.mark()

    def recv_scp_quorum_set(self, qs_hash: bytes, qset: SCPQuorumSet) -> None:
        self.pending_envelopes.recv_scp_quorum_set(qs_hash, qset)

    def recv_tx_set(self, ts_hash: bytes, txset) -> None:
        self.pending_envelopes.recv_tx_set(ts_hash, txset)

    def peer_doesnt_have(self, msg_type, item_id: bytes, peer) -> None:
        self.pending_envelopes.peer_doesnt_have(msg_type, item_id, peer)

    def get_tx_set(self, ts_hash: bytes):
        return self.pending_envelopes.get_tx_set(ts_hash)

    def process_scp_queue(self) -> None:
        # drain holdoff around the whole sweep: when several slots are
        # externalizable (a healed partition's replay run readied them in
        # one batch), each value_externalized ENQUEUES through the close
        # pipeline and the closes happen at release as one pipelined
        # backlog — slot N+1's signature prewarm dispatches while slot N
        # applies (ledger/closepipeline.py; ROADMAP #3's remaining leg).
        # Everything is still synchronous within this call: by return,
        # every enqueued ledger has closed.
        self.ledger_manager.hold_pipeline_drains()
        try:
            if self.tracking:
                self.pending_envelopes.erase_below(
                    self.next_consensus_ledger_index()
                )
                self._process_scp_queue_at_index(
                    self.next_consensus_ledger_index()
                )
            else:
                for slot in self.pending_envelopes.ready_slots():
                    self._process_scp_queue_at_index(slot)
                    if self.tracking:
                        break  # a slot externalized; back to the regular flow
        finally:
            self.ledger_manager.release_pipeline_drains()

    def _process_scp_queue_at_index(self, slot_index: int) -> None:
        while True:
            env = self.pending_envelopes.pop(slot_index)
            if env is None:
                return
            self.scp.receive_envelope(env)

    def send_scp_state_to_peer(self, ledger_seq: int, peer) -> None:
        if ledger_seq == 0:
            max_seq = self.get_current_ledger_seq()
            min_seq = max(2, max_seq - 3) if max_seq >= 5 else 2
        else:
            min_seq = max_seq = ledger_seq
        for seq in range(min_seq, max_seq + 1):
            for e in self.scp.get_current_state(seq):
                self.m_envelope_emit.mark()
                peer.send_message(StellarMessage(MessageType.SCP_MESSAGE, e))

    # ------------------------------------------------------------------
    # triggering the next ledger
    # ------------------------------------------------------------------
    def trigger_next_ledger(self, ledger_seq_to_trigger: int) -> None:
        if not self.tracking or not self.ledger_manager.is_synced():
            log.debug("trigger_next_ledger: skipping (out of sync)")
            return

        lcl = self.ledger_manager.get_last_closed_ledger_header()
        proposed = TxSetFrame(lcl.hash)
        for gen in self.received_transactions:
            for txmap in gen.values():
                for tx in txmap.transactions.values():
                    proposed.add_transaction(tx)

        removed = proposed.trim_invalid(self.app)
        self._remove_received_txs(removed)
        proposed.surge_pricing_filter(self.ledger_manager)

        if not proposed.check_valid(self.app):
            raise RuntimeError("wanting to emit an invalid txSet")

        tx_set_hash = proposed.get_contents_hash()
        self.pending_envelopes.recv_tx_set(tx_set_hash, proposed)

        slot_index = lcl.header.ledgerSeq + 1
        if ledger_seq_to_trigger != slot_index:
            return  # externalize happened on a more recent ledger

        self.last_trigger = self.app.clock.now()
        next_close_time = max(int(self.app.time_now()), lcl.header.scpValue.closeTime + 1)

        new_value = StellarValue(tx_set_hash, next_close_time, [], 0)

        cfg = self.app.config
        upgrades = []
        if lcl.header.ledgerVersion != cfg.LEDGER_PROTOCOL_VERSION:
            upgrades.append(
                LedgerUpgrade(
                    LedgerUpgradeType.LEDGER_UPGRADE_VERSION, cfg.LEDGER_PROTOCOL_VERSION
                )
            )
        if lcl.header.baseFee != cfg.DESIRED_BASE_FEE:
            upgrades.append(
                LedgerUpgrade(LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE, cfg.DESIRED_BASE_FEE)
            )
        if lcl.header.maxTxSetSize != cfg.DESIRED_MAX_TX_PER_LEDGER:
            upgrades.append(
                LedgerUpgrade(
                    LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE,
                    cfg.DESIRED_MAX_TX_PER_LEDGER,
                )
            )
        for up in upgrades:
            raw = up.to_xdr()
            if len(raw) < 128:
                new_value.upgrades.append(raw)

        self.current_value = new_value.to_xdr()
        prev_value = lcl.header.scpValue.to_xdr()
        # whole-slot consensus span: nominate → value_externalized (must be
        # registered BEFORE nominate — a single-node network externalizes
        # synchronously inside this call)
        self._trace_slot_spans[slot_index] = self.app.tracer.begin(
            "scp.consensus", slot=slot_index, txs=proposed.size()
        )
        self.scp.nominate(slot_index, self.current_value, prev_value)

    # ------------------------------------------------------------------
    # SCP state persistence (HerderImpl.cpp:1442-1531)
    # ------------------------------------------------------------------
    def persist_scp_state(self, slot_index: Optional[int] = None) -> None:
        import base64

        from ..main.persistentstate import K_LAST_SCP_DATA
        from ..xdr.base import pack_var_array_of
        from ..xdr.ledger import TransactionSet

        if slot_index is None:
            slot_index = self.ledger_manager.get_ledger_num()
        envs = self.scp.get_latest_messages_send(slot_index)
        txsets: Dict[bytes, object] = {}
        qsets: Dict[bytes, SCPQuorumSet] = {}
        for e in envs:
            for v in Slot.statement_values(e.statement):
                # only the txSetHash is needed: C field accessor over the
                # value bytes, no full StellarValue decode
                try:
                    h = xdr_getfield(StellarValue, v, "txSetHash")
                except Exception:
                    continue
                ts = self.pending_envelopes.get_tx_set(h)
                if ts is not None:
                    txsets[h] = ts
            qh = Slot.companion_qset_hash(e.statement)
            if qh is not None:
                qs = self.pending_envelopes.get_qset(qh)
                if qs is not None:
                    qsets[qh] = qs

        blob = (
            pack_var_array_of(SCPEnvelope, envs)
            + pack_var_array_of(TransactionSet, [t.to_xdr() for t in txsets.values()])
            + pack_var_array_of(SCPQuorumSet, list(qsets.values()))
        )
        fs.kill_point(KP_SCP_PERSIST_PRE, ctx=self.app.database)
        self.app.persistent_state.set_state(
            K_LAST_SCP_DATA, base64.b64encode(blob).decode()
        )
        fs.kill_point(KP_SCP_PERSIST_POST, ctx=self.app.database)

    def restore_scp_state(self) -> None:
        import base64

        from ..main.persistentstate import K_LAST_SCP_DATA
        from ..xdr.base import unpack_var_arrays
        from ..xdr.ledger import TransactionSet

        latest64 = self.app.persistent_state.get_state(K_LAST_SCP_DATA)
        if not latest64:
            return
        blob = base64.b64decode(latest64)
        # crash on unrecognized data: participating with bad SCP state is
        # unsafe; the way out is --newdb + catchup
        envs, txset_xdrs, qsets = unpack_var_arrays(
            blob, (SCPEnvelope, TransactionSet, SCPQuorumSet)
        )
        for xs in txset_xdrs:
            ts = TxSetFrame.from_xdr_set(self.app.network_id, xs)
            self.pending_envelopes.recv_tx_set(ts.get_contents_hash(), ts)
        for qs in qsets:
            self.pending_envelopes.recv_scp_quorum_set(compute_qset_hash(qs), qs)
        for e in envs:
            self.scp.set_state_from_envelope(e.statement.slotIndex, e)
        if envs:
            self._start_rebroadcast_timer()
        self._replay_interrupted_close(envs)

    def _replay_interrupted_close(self, envs) -> None:
        """Finish a close the previous life died inside (the crash-
        survival plane, ISSUE r18).  A node killed between SCP
        externalize and the close's SQL COMMIT restarts with LCL = n-1
        while its restored slot-n state is already in EXTERNALIZE phase
        — set_state_from_envelope never re-fires value_externalized, so
        without this the node can neither close n itself nor (its vote
        being gated on sync) help a 3-of-3 quorum move past n+1.  The
        decision for slot n is final (quorum externalized it; our own
        restored statement proves we saw that quorum), so re-driving
        the close from the persisted value + txset is deterministic
        replay, not re-deciding — the kill-sweep pins the resulting
        hashes bit-exact against an unkilled control."""
        from ..xdr.scp import SCPStatementType

        lcl = self.ledger_manager.get_last_closed_ledger_num()
        for e in envs:
            st = e.statement
            if (
                st.pledges.type != SCPStatementType.SCP_ST_EXTERNALIZE
                or st.slotIndex != lcl + 1
            ):
                continue
            try:
                sv = StellarValue.from_xdr(st.pledges.value.commit.value)
            except Exception:
                continue  # value undecodable: leave it to catchup
            ts = self.pending_envelopes.get_tx_set(sv.txSetHash)
            if ts is None:
                continue  # txset not persisted: leave it to catchup
            log.info(
                "replaying interrupted close of ledger %d from restored"
                " SCP state",
                st.slotIndex,
            )
            self.ledger_manager.externalize_value(
                LedgerCloseData(st.slotIndex, ts, sv)
            )
            return

    # ------------------------------------------------------------------
    # misc
    # ------------------------------------------------------------------
    def is_quorum_set_sane(self, node_id: NodeID, qset: SCPQuorumSet) -> bool:
        # delegates to SCP so the self-absence rule lives in one place
        # (reference: HerderImpl.cpp:1396 -> LocalNode::isQuorumSetSane)
        return self.scp.is_qset_sane_for(node_id, qset)

    def dump_info(self) -> dict:
        return {
            "state": self.get_state(),
            "tracking": self.tracking.index if self.tracking else None,
            "queue": self.pending_envelopes.dump_info(),
            "scp": self.scp.dump_info(),
            "sig_scheme": self._scheme().stats(),
            "slot_buckets": {
                s: dict(v) for s, v in self.scp_slot_buckets.items()
            },
            "closetime_rejects": {
                "past": self.m_value_close_past.count,
                "future": self.m_value_close_future.count,
            },
            "scp_state_probes": self.m_scp_state_probe.count,
        }
