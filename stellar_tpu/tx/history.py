"""txhistory / txfeehistory tables (reference: TransactionFrame::storeTransaction
/ storeTransactionFee, src/transactions/TransactionFrame.cpp:497-560).

Rows keep base64 XDR blobs of the envelope, result pair, and meta — the
publish state machine reads them back out to build history checkpoint files.
"""

from __future__ import annotations

import base64
from typing import List, Optional, Tuple

from ..xdr.ledger import (
    LEDGER_ENTRY_CHANGES,
    TransactionHistoryEntry,
    TransactionHistoryResultEntry,
    TransactionMeta,
    TransactionResultPair,
)
from ..xdr.txs import TransactionEnvelope


def drop_tx_history(db) -> None:
    db.execute("DROP TABLE IF EXISTS txhistory")
    db.execute("DROP TABLE IF EXISTS txfeehistory")
    db.execute(
        """CREATE TABLE txhistory (
            txid      CHARACTER(64) NOT NULL,
            ledgerseq INT NOT NULL CHECK (ledgerseq >= 0),
            txindex   INT NOT NULL,
            txbody    TEXT NOT NULL,
            txresult  TEXT NOT NULL,
            txmeta    TEXT NOT NULL,
            PRIMARY KEY (txid, ledgerseq)
        )"""
    )
    db.execute("CREATE INDEX histbyseq ON txhistory (ledgerseq)")
    db.execute(
        """CREATE TABLE txfeehistory (
            txid      CHARACTER(64) NOT NULL,
            ledgerseq INT NOT NULL CHECK (ledgerseq >= 0),
            txindex   INT NOT NULL,
            txchanges TEXT NOT NULL,
            PRIMARY KEY (txid, ledgerseq)
        )"""
    )
    db.execute("CREATE INDEX histfeebyseq ON txfeehistory (ledgerseq)")


def transaction_row(
    tx_id: bytes,
    ledger_seq: int,
    tx_index: int,
    envelope_xdr: bytes,
    result_pair: TransactionResultPair,
    meta: TransactionMeta,
) -> Tuple:
    return (
        tx_id.hex(),
        ledger_seq,
        tx_index,
        base64.b64encode(envelope_xdr).decode(),
        base64.b64encode(result_pair.to_xdr()).decode(),
        base64.b64encode(meta.to_xdr()).decode(),
    )


def fee_row(tx_id: bytes, ledger_seq: int, tx_index: int, changes) -> Tuple:
    return (
        tx_id.hex(),
        ledger_seq,
        tx_index,
        base64.b64encode(LEDGER_ENTRY_CHANGES.pack(changes)).decode(),
    )


_TX_INSERT = (
    "INSERT INTO txhistory (txid, ledgerseq, txindex, txbody, txresult, txmeta)"
    " VALUES (?,?,?,?,?,?)"
)
_FEE_INSERT = (
    "INSERT INTO txfeehistory (txid, ledgerseq, txindex, txchanges)"
    " VALUES (?,?,?,?)"
)


def insert_transaction_rows(db, rows: List[Tuple]) -> None:
    """Bulk path for ledger close: one executemany for the whole txset."""
    if rows:
        db.executemany(_TX_INSERT, rows)


def insert_fee_rows(db, rows: List[Tuple]) -> None:
    if rows:
        db.executemany(_FEE_INSERT, rows)


def load_transaction_history(db, ledger_seq: int) -> List[Tuple]:
    """[(envelope, result_pair)] in apply (txindex) order."""
    rows = db.query_all(
        "SELECT txbody, txresult FROM txhistory WHERE ledgerseq=? ORDER BY txindex",
        (ledger_seq,),
    )
    return [
        (
            TransactionEnvelope.from_xdr(base64.b64decode(b)),
            TransactionResultPair.from_xdr(base64.b64decode(r)),
        )
        for b, r in rows
    ]


def delete_old_entries(db, ledger_seq: int) -> None:
    db.execute("DELETE FROM txhistory WHERE ledgerseq <= ?", (ledger_seq,))
    db.execute("DELETE FROM txfeehistory WHERE ledgerseq <= ?", (ledger_seq,))
