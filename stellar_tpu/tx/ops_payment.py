"""Payment and PathPayment operations (reference:
src/transactions/PaymentOpFrame.cpp, PathPaymentOpFrame.cpp).

Payment is sugar over PathPayment with a single-asset path (the reference
literally builds a PathPaymentOp and maps its result codes back).
"""

from __future__ import annotations

from ..ledger.accountframe import AccountFrame
from ..ledger.trustframe import TrustFrame
from ..util.xmath import INT64_MAX
from ..xdr.txs import (
    Operation,
    OperationBody,
    OperationResult,
    OperationResultCode,
    OperationResultTr,
    OperationType,
    PathPaymentOp,
    PathPaymentResult,
    PathPaymentResultCode,
    PathPaymentSuccess,
    PaymentResult,
    PaymentResultCode,
    SimplePaymentResult,
)
from .offerexchange import ConvertResult, OfferExchange, OfferFilterResult
from .opframe import OperationFrame, is_asset_valid

_PP_TO_PAYMENT = {
    PathPaymentResultCode.PATH_PAYMENT_UNDERFUNDED: PaymentResultCode.PAYMENT_UNDERFUNDED,
    PathPaymentResultCode.PATH_PAYMENT_SRC_NOT_AUTHORIZED: PaymentResultCode.PAYMENT_SRC_NOT_AUTHORIZED,
    PathPaymentResultCode.PATH_PAYMENT_SRC_NO_TRUST: PaymentResultCode.PAYMENT_SRC_NO_TRUST,
    PathPaymentResultCode.PATH_PAYMENT_NO_DESTINATION: PaymentResultCode.PAYMENT_NO_DESTINATION,
    PathPaymentResultCode.PATH_PAYMENT_NO_TRUST: PaymentResultCode.PAYMENT_NO_TRUST,
    PathPaymentResultCode.PATH_PAYMENT_NOT_AUTHORIZED: PaymentResultCode.PAYMENT_NOT_AUTHORIZED,
    PathPaymentResultCode.PATH_PAYMENT_LINE_FULL: PaymentResultCode.PAYMENT_LINE_FULL,
    PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER: PaymentResultCode.PAYMENT_NO_ISSUER,
}


class PaymentOpFrame(OperationFrame):
    @property
    def payment(self):
        return self.operation.body.value

    def do_check_valid(self, metrics) -> bool:
        if self.payment.amount <= 0:
            metrics.new_meter(
                ("op-payment", "invalid", "malformed-negative-amount"), "operation"
            ).mark()
            self.set_inner_result(PaymentResult(PaymentResultCode.PAYMENT_MALFORMED))
            return False
        if not is_asset_valid(self.payment.asset):
            metrics.new_meter(
                ("op-payment", "invalid", "malformed-invalid-asset"), "operation"
            ).mark()
            self.set_inner_result(PaymentResult(PaymentResultCode.PAYMENT_MALFORMED))
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        if self.payment.destination == self.get_source_id():
            metrics.new_meter(("op-payment", "success", "apply"), "operation").mark()
            self.set_inner_result(PaymentResult(PaymentResultCode.PAYMENT_SUCCESS))
            return True

        pp_op = Operation(
            self.operation.sourceAccount,
            OperationBody(
                OperationType.PATH_PAYMENT,
                PathPaymentOp(
                    sendAsset=self.payment.asset,
                    sendMax=self.payment.amount,
                    destination=self.payment.destination,
                    destAsset=self.payment.asset,
                    destAmount=self.payment.amount,
                    path=[],
                ),
            ),
        )
        pp_res = OperationResult(
            OperationResultCode.opINNER,
            OperationResultTr(OperationType.PATH_PAYMENT, None),
        )
        pp = PathPaymentOpFrame(pp_op, pp_res, self.parent_tx)
        pp.source_account = self.source_account

        if not pp.do_check_valid(metrics) or not pp.do_apply(metrics, delta, lm):
            if pp.get_result_code() != OperationResultCode.opINNER:
                raise RuntimeError("Unexpected error code from pathPayment")
            inner_code = pp.inner_result().type
            mapped = _PP_TO_PAYMENT.get(inner_code)
            if mapped is None:
                raise RuntimeError("Unexpected error code from pathPayment")
            self.set_inner_result(PaymentResult(mapped))
            return False

        assert pp.inner_result().type == PathPaymentResultCode.PATH_PAYMENT_SUCCESS
        metrics.new_meter(("op-payment", "success", "apply"), "operation").mark()
        self.set_inner_result(PaymentResult(PaymentResultCode.PAYMENT_SUCCESS))
        return True


class PathPaymentOpFrame(OperationFrame):
    @property
    def pp(self):
        return self.operation.body.value

    def _fail(self, metrics, tag, code, no_issuer_asset=None):
        metrics.new_meter(("op-path-payment", "failure", tag), "operation").mark()
        if code == PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER:
            self.set_inner_result(PathPaymentResult(code, no_issuer_asset))
        else:
            self.set_inner_result(PathPaymentResult(code))
        return False

    def do_check_valid(self, metrics) -> bool:
        pp = self.pp
        if pp.destAmount <= 0 or pp.sendMax <= 0:
            metrics.new_meter(
                ("op-path-payment", "invalid", "malformed-amounts"), "operation"
            ).mark()
            self.set_inner_result(
                PathPaymentResult(PathPaymentResultCode.PATH_PAYMENT_MALFORMED)
            )
            return False
        if not is_asset_valid(pp.sendAsset) or not is_asset_valid(pp.destAsset) or not all(
            is_asset_valid(a) for a in pp.path
        ):
            metrics.new_meter(
                ("op-path-payment", "invalid", "malformed-currencies"), "operation"
            ).mark()
            self.set_inner_result(
                PathPaymentResult(PathPaymentResultCode.PATH_PAYMENT_MALFORMED)
            )
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        db = lm.database
        pp = self.pp

        success = PathPaymentSuccess([], None)
        self.set_inner_result(
            PathPaymentResult(PathPaymentResultCode.PATH_PAYMENT_SUCCESS, success)
        )

        cur_b_received = pp.destAmount
        cur_b = pp.destAsset
        full_path = [pp.sendAsset] + list(pp.path)

        # send-credits-back-to-issuer shortcut: destination account need not
        # exist when it IS the issuer of a direct single-asset payment
        bypass_issuer_check = (
            not cur_b.is_native()
            and len(full_path) == 1
            and pp.sendAsset == pp.destAsset
            and cur_b.code_and_issuer()[1] == pp.destination
        )

        destination = None
        if not bypass_issuer_check:
            destination = AccountFrame.load_account(pp.destination, db)
            if destination is None:
                return self._fail(
                    metrics,
                    "no-destination",
                    PathPaymentResultCode.PATH_PAYMENT_NO_DESTINATION,
                )

        # credit the last hop
        if cur_b.is_native():
            destination.mut().balance += cur_b_received
            destination.store_change(delta, db)
        else:
            if bypass_issuer_check:
                dest_line = TrustFrame.load_trust_line(pp.destination, cur_b, db)
            else:
                dest_line, issuer = TrustFrame.load_trust_line_issuer(
                    pp.destination, cur_b, db
                )
                if issuer is None:
                    return self._fail(
                        metrics,
                        "no-issuer",
                        PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER,
                        cur_b,
                    )
            if dest_line is None:
                return self._fail(
                    metrics, "no-trust", PathPaymentResultCode.PATH_PAYMENT_NO_TRUST
                )
            if not dest_line.is_authorized():
                return self._fail(
                    metrics,
                    "not-authorized",
                    PathPaymentResultCode.PATH_PAYMENT_NOT_AUTHORIZED,
                )
            if not dest_line.add_balance(cur_b_received):
                return self._fail(
                    metrics, "line-full", PathPaymentResultCode.PATH_PAYMENT_LINE_FULL
                )
            dest_line.store_change(delta, db)

        success.last = SimplePaymentResult(pp.destination, cur_b, cur_b_received)

        # walk the path backwards converting through the book
        for cur_a in reversed(full_path):
            if cur_a == cur_b:
                continue
            if not cur_a.is_native():
                if (
                    AccountFrame.load_account(
                        cur_a.code_and_issuer()[1], db, readonly=True
                    )
                    is None
                ):
                    return self._fail(
                        metrics,
                        "no-issuer",
                        PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER,
                        cur_a,
                    )

            oe = OfferExchange(delta, lm)
            stop_code = []

            def offer_filter(o):
                if o.get_seller_id() == self.get_source_id():
                    metrics.new_meter(
                        ("op-path-payment", "failure", "offer-cross-self"), "operation"
                    ).mark()
                    stop_code.append(
                        PathPaymentResultCode.PATH_PAYMENT_OFFER_CROSS_SELF
                    )
                    return OfferFilterResult.STOP
                return OfferFilterResult.KEEP

            r, cur_a_sent, actual_b_received = oe.convert_with_offers(
                cur_a, INT64_MAX, cur_b, cur_b_received, offer_filter
            )
            if r == ConvertResult.FILTER_STOP:
                self.set_inner_result(PathPaymentResult(stop_code[0]))
                return False
            if r == ConvertResult.OK and cur_b_received == actual_b_received:
                pass
            else:
                return self._fail(
                    metrics,
                    "too-few-offers",
                    PathPaymentResultCode.PATH_PAYMENT_TOO_FEW_OFFERS,
                )

            cur_b_received = cur_a_sent
            cur_b = cur_a
            success.offers = oe.offer_trail + success.offers

        # finally: debit the source
        cur_b_sent = cur_b_received
        if cur_b_sent > pp.sendMax:
            return self._fail(
                metrics, "over-send-max", PathPaymentResultCode.PATH_PAYMENT_OVER_SENDMAX
            )

        if cur_b.is_native():
            min_balance = self.source_account.get_minimum_balance(lm)
            if self.source_account.get_balance() - cur_b_sent < min_balance:
                return self._fail(
                    metrics,
                    "underfunded",
                    PathPaymentResultCode.PATH_PAYMENT_UNDERFUNDED,
                )
            self.source_account.mut().balance -= cur_b_sent
            self.source_account.store_change(delta, db)
        else:
            if bypass_issuer_check:
                source_line = TrustFrame.load_trust_line(
                    self.get_source_id(), cur_b, db
                )
            else:
                source_line, issuer = TrustFrame.load_trust_line_issuer(
                    self.get_source_id(), cur_b, db
                )
                if issuer is None:
                    return self._fail(
                        metrics,
                        "no-issuer",
                        PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER,
                        cur_b,
                    )
            if source_line is None:
                return self._fail(
                    metrics, "src-no-trust", PathPaymentResultCode.PATH_PAYMENT_SRC_NO_TRUST
                )
            if not source_line.is_authorized():
                return self._fail(
                    metrics,
                    "src-not-authorized",
                    PathPaymentResultCode.PATH_PAYMENT_SRC_NOT_AUTHORIZED,
                )
            if not source_line.add_balance(-cur_b_sent):
                return self._fail(
                    metrics, "underfunded", PathPaymentResultCode.PATH_PAYMENT_UNDERFUNDED
                )
            source_line.store_change(delta, db)

        metrics.new_meter(("op-path-payment", "success", "apply"), "operation").mark()
        return True
