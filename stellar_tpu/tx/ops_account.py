"""Account-administration operations (reference:
CreateAccountOpFrame.cpp, SetOptionsOpFrame.cpp, ChangeTrustOpFrame.cpp,
AllowTrustOpFrame.cpp, MergeOpFrame.cpp, InflationOpFrame.cpp)."""

from __future__ import annotations

from ..ledger.accountframe import AccountFrame
from ..ledger.delta import LedgerDelta
from ..ledger.trustframe import TrustFrame
from ..util.xmath import big_divide
from ..xdr.entries import (
    Asset,
    AssetType,
    LedgerEntry,
    LedgerEntryData,
    LedgerEntryType,
    MASK_ACCOUNT_FLAGS,
    ThresholdIndexes,
    TrustLineEntry,
)
from ..xdr.txs import (
    AccountMergeResult,
    AccountMergeResultCode,
    AllowTrustResult,
    AllowTrustResultCode,
    ChangeTrustResult,
    ChangeTrustResultCode,
    CreateAccountResult,
    CreateAccountResultCode,
    InflationPayout,
    InflationResult,
    InflationResultCode,
    SetOptionsResult,
    SetOptionsResultCode,
)
from .opframe import OperationFrame, is_asset_valid, is_string32_valid

# AUTH_REQUIRED | AUTH_REVOCABLE | AUTH_IMMUTABLE — once immutable is set,
# NO auth flag (immutable included) may change (SetOptionsOpFrame.cpp:15-18)
ALL_ACCOUNT_AUTH_FLAGS = 0x7
MAX_SIGNERS = 20

# inflation constants (InflationOpFrame.cpp:12-19)
INFLATION_FREQUENCY = 60 * 60 * 24 * 7  # every 7 days
INFLATION_RATE_TRILLIONTHS = 190721000
TRILLION = 1000000000000
INFLATION_WIN_MIN_PERCENT = 500000000  # .05%
INFLATION_NUM_WINNERS = 2000
INFLATION_START_TIME = 1404172800  # 1-jul-2014


class CreateAccountOpFrame(OperationFrame):
    @property
    def ca(self):
        return self.operation.body.value

    def do_check_valid(self, metrics) -> bool:
        if self.ca.startingBalance <= 0:
            metrics.new_meter(
                ("op-create-account", "invalid", "malformed-negative-balance"),
                "operation",
            ).mark()
            self.set_inner_result(
                CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_MALFORMED)
            )
            return False
        if self.ca.destination == self.get_source_id():
            metrics.new_meter(
                ("op-create-account", "invalid", "malformed-destination-equals-source"),
                "operation",
            ).mark()
            self.set_inner_result(
                CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_MALFORMED)
            )
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        db = lm.database
        dest = AccountFrame.load_account(self.ca.destination, db)
        if dest is not None:
            metrics.new_meter(
                ("op-create-account", "failure", "already-exist"), "operation"
            ).mark()
            self.set_inner_result(
                CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST)
            )
            return False
        if self.ca.startingBalance < lm.get_min_balance(0):
            metrics.new_meter(
                ("op-create-account", "failure", "low-reserve"), "operation"
            ).mark()
            self.set_inner_result(
                CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_LOW_RESERVE)
            )
            return False
        min_balance = self.source_account.get_minimum_balance(lm)
        if self.source_account.get_balance() - min_balance < self.ca.startingBalance:
            metrics.new_meter(
                ("op-create-account", "failure", "underfunded"), "operation"
            ).mark()
            self.set_inner_result(
                CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED)
            )
            return False
        self.source_account.mut().balance -= self.ca.startingBalance
        self.source_account.store_change(delta, db)
        dest = AccountFrame(account_id=self.ca.destination)
        # new accounts start at (currentLedgerSeq << 32)
        body = dest.mut()
        body.seqNum = delta.header_ro().ledgerSeq << 32
        body.balance = self.ca.startingBalance
        dest.store_add(delta, db)
        metrics.new_meter(("op-create-account", "success", "apply"), "operation").mark()
        self.set_inner_result(
            CreateAccountResult(CreateAccountResultCode.CREATE_ACCOUNT_SUCCESS)
        )
        return True


class SetOptionsOpFrame(OperationFrame):
    @property
    def so(self):
        return self.operation.body.value

    def get_needed_threshold(self) -> int:
        so = self.so
        if (
            so.masterWeight is not None
            or so.lowThreshold is not None
            or so.medThreshold is not None
            or so.highThreshold is not None
            or so.signer is not None
        ):
            return self.source_account.get_high_threshold()
        return self.source_account.get_medium_threshold()

    def _fail(self, metrics, tag, code):
        if tag:
            metrics.new_meter(("op-set-options", "invalid", tag), "operation").mark()
        self.set_inner_result(SetOptionsResult(code))
        return False

    def do_check_valid(self, metrics) -> bool:
        so = self.so
        if so.setFlags is not None and so.setFlags & ~MASK_ACCOUNT_FLAGS:
            return self._fail(metrics, None, SetOptionsResultCode.SET_OPTIONS_UNKNOWN_FLAG)
        if so.clearFlags is not None and so.clearFlags & ~MASK_ACCOUNT_FLAGS:
            return self._fail(metrics, None, SetOptionsResultCode.SET_OPTIONS_UNKNOWN_FLAG)
        if (
            so.setFlags is not None
            and so.clearFlags is not None
            and so.setFlags & so.clearFlags
        ):
            return self._fail(
                metrics, "bad-flags", SetOptionsResultCode.SET_OPTIONS_BAD_FLAGS
            )
        for field in (so.masterWeight, so.lowThreshold, so.medThreshold, so.highThreshold):
            if field is not None and field > 255:
                return self._fail(
                    metrics,
                    "threshold-out-of-range",
                    SetOptionsResultCode.SET_OPTIONS_THRESHOLD_OUT_OF_RANGE,
                )
        if so.signer is not None and so.signer.pubKey == self.get_source_id():
            return self._fail(
                metrics, "bad-signer", SetOptionsResultCode.SET_OPTIONS_BAD_SIGNER
            )
        if so.homeDomain is not None and not is_string32_valid(so.homeDomain):
            return self._fail(
                metrics,
                "invalid-home-domain",
                SetOptionsResultCode.SET_OPTIONS_INVALID_HOME_DOMAIN,
            )
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        so = self.so
        db = lm.database
        # mut(): the shared signing frame may be sealed (fee charging or
        # an earlier op stored it); every branch below mutates `account`
        # in place, so bind the CoW-unsealed entry once up front
        account = self.source_account.mut()

        def fail(tag, code):
            metrics.new_meter(("op-set-options", "failure", tag), "operation").mark()
            self.set_inner_result(SetOptionsResult(code))
            return False

        if so.inflationDest is not None:
            if AccountFrame.load_account(so.inflationDest, db, readonly=True) is None:
                return fail(
                    "invalid-inflation",
                    SetOptionsResultCode.SET_OPTIONS_INVALID_INFLATION,
                )
            account.inflationDest = so.inflationDest

        for flags_change, is_set in ((so.clearFlags, False), (so.setFlags, True)):
            if flags_change is None:
                continue
            if (
                flags_change & ALL_ACCOUNT_AUTH_FLAGS
            ) and self.source_account.is_immutable_auth():
                return fail("cant-change", SetOptionsResultCode.SET_OPTIONS_CANT_CHANGE)
            if is_set:
                account.flags |= flags_change
            else:
                account.flags &= ~flags_change

        if so.homeDomain is not None:
            account.homeDomain = so.homeDomain

        th = bytearray(account.thresholds)
        for idx, v in (
            (ThresholdIndexes.THRESHOLD_MASTER_WEIGHT, so.masterWeight),
            (ThresholdIndexes.THRESHOLD_LOW, so.lowThreshold),
            (ThresholdIndexes.THRESHOLD_MED, so.medThreshold),
            (ThresholdIndexes.THRESHOLD_HIGH, so.highThreshold),
        ):
            if v is not None:
                th[idx] = v & 0xFF
        account.thresholds = bytes(th)

        if so.signer is not None:
            signers = account.signers
            if so.signer.weight:
                for old in signers:
                    if old.pubKey == so.signer.pubKey:
                        old.weight = so.signer.weight
                        break
                else:
                    if len(signers) >= MAX_SIGNERS:
                        return fail(
                            "too-many-signers",
                            SetOptionsResultCode.SET_OPTIONS_TOO_MANY_SIGNERS,
                        )
                    if not self.source_account.add_num_entries(1, lm):
                        return fail(
                            "low-reserve", SetOptionsResultCode.SET_OPTIONS_LOW_RESERVE
                        )
                    signers.append(so.signer)
            else:
                kept = []
                for old in signers:
                    if old.pubKey == so.signer.pubKey:
                        self.source_account.add_num_entries(-1, lm)
                    else:
                        kept.append(old)
                account.signers = kept
            # canonical raw-pubKey ordering is enforced by
            # AccountFrame._normalize at the store below

        metrics.new_meter(("op-set-options", "success", "apply"), "operation").mark()
        self.set_inner_result(SetOptionsResult(SetOptionsResultCode.SET_OPTIONS_SUCCESS))
        self.source_account.store_change(delta, db)
        return True


class ChangeTrustOpFrame(OperationFrame):
    @property
    def ct(self):
        return self.operation.body.value

    def do_check_valid(self, metrics) -> bool:
        if self.ct.limit < 0:
            metrics.new_meter(
                ("op-change-trust", "invalid", "malformed-negative-limit"), "operation"
            ).mark()
            self.set_inner_result(
                ChangeTrustResult(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            )
            return False
        if not is_asset_valid(self.ct.line):
            metrics.new_meter(
                ("op-change-trust", "invalid", "malformed-invalid-asset"), "operation"
            ).mark()
            self.set_inner_result(
                ChangeTrustResult(ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)
            )
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        db = lm.database
        ct = self.ct

        def fail(tag, code):
            metrics.new_meter(("op-change-trust", "failure", tag), "operation").mark()
            self.set_inner_result(ChangeTrustResult(code))
            return False

        def succeed():
            metrics.new_meter(("op-change-trust", "success", "apply"), "operation").mark()
            self.set_inner_result(
                ChangeTrustResult(ChangeTrustResultCode.CHANGE_TRUST_SUCCESS)
            )
            return True

        if ct.line.is_native():
            return fail("malformed", ChangeTrustResultCode.CHANGE_TRUST_MALFORMED)

        line, issuer = TrustFrame.load_trust_line_issuer(self.get_source_id(), ct.line, db)
        if line is not None:
            if ct.limit < line.get_balance():
                return fail("invalid-limit", ChangeTrustResultCode.CHANGE_TRUST_INVALID_LIMIT)
            if ct.limit == 0:
                line.store_delete(delta, db)
                self.source_account.add_num_entries(-1, lm)
                self.source_account.store_change(delta, db)
            else:
                if issuer is None:
                    return fail("no-issuer", ChangeTrustResultCode.CHANGE_TRUST_NO_ISSUER)
                line.mut().limit = ct.limit
                line.store_change(delta, db)
            return succeed()
        else:
            if ct.limit == 0:
                return fail("invalid-limit", ChangeTrustResultCode.CHANGE_TRUST_INVALID_LIMIT)
            if issuer is None:
                return fail("no-issuer", ChangeTrustResultCode.CHANGE_TRUST_NO_ISSUER)
            tl = TrustLineEntry(
                accountID=self.get_source_id(),
                asset=ct.line,
                balance=0,
                limit=ct.limit,
                flags=0,
                ext=0,
            )
            new_line = TrustFrame(
                LedgerEntry(0, LedgerEntryData(LedgerEntryType.TRUSTLINE, tl), 0)
            )
            new_line.set_authorized(not issuer.is_auth_required())
            if not self.source_account.add_num_entries(1, lm):
                return fail("low-reserve", ChangeTrustResultCode.CHANGE_TRUST_LOW_RESERVE)
            self.source_account.store_change(delta, db)
            new_line.store_add(delta, db)
            return succeed()


class AllowTrustOpFrame(OperationFrame):
    @property
    def at(self):
        return self.operation.body.value

    def get_needed_threshold(self) -> int:
        return self.source_account.get_low_threshold()

    def _asset(self) -> Asset:
        at = self.at
        if at.asset.type == AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
            return Asset.alphanum4(at.asset.value, self.get_source_id())
        return Asset.alphanum12(at.asset.value, self.get_source_id())

    def do_check_valid(self, metrics) -> bool:
        if self.at.asset.type == AssetType.ASSET_TYPE_NATIVE:
            metrics.new_meter(
                ("op-allow-trust", "invalid", "malformed-non-alphanum"), "operation"
            ).mark()
            self.set_inner_result(
                AllowTrustResult(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            )
            return False
        if not is_asset_valid(self._asset()):
            metrics.new_meter(
                ("op-allow-trust", "invalid", "malformed-invalid-asset"), "operation"
            ).mark()
            self.set_inner_result(
                AllowTrustResult(AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
            )
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        def fail(tag, code):
            metrics.new_meter(("op-allow-trust", "failure", tag), "operation").mark()
            self.set_inner_result(AllowTrustResult(code))
            return False

        if not self.source_account.is_auth_required():
            return fail("not-required", AllowTrustResultCode.ALLOW_TRUST_TRUST_NOT_REQUIRED)
        if not self.source_account.is_auth_revocable() and not self.at.authorize:
            return fail("cant-revoke", AllowTrustResultCode.ALLOW_TRUST_CANT_REVOKE)

        db = lm.database
        line = TrustFrame.load_trust_line(self.at.trustor, self._asset(), db)
        if line is None or line.is_issuer:
            return fail("no-trust-line", AllowTrustResultCode.ALLOW_TRUST_NO_TRUST_LINE)
        metrics.new_meter(("op-allow-trust", "success", "apply"), "operation").mark()
        self.set_inner_result(AllowTrustResult(AllowTrustResultCode.ALLOW_TRUST_SUCCESS))
        line.set_authorized(self.at.authorize)
        line.store_change(delta, db)
        return True


class MergeOpFrame(OperationFrame):
    def get_needed_threshold(self) -> int:
        return self.source_account.get_high_threshold()

    def do_check_valid(self, metrics) -> bool:
        if self.get_source_id() == self.operation.body.value:
            metrics.new_meter(
                ("op-merge", "invalid", "malformed-self-merge"), "operation"
            ).mark()
            self.set_inner_result(
                AccountMergeResult(AccountMergeResultCode.ACCOUNT_MERGE_MALFORMED)
            )
            return False
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        db = lm.database

        def fail(tag, code):
            metrics.new_meter(("op-merge", "failure", tag), "operation").mark()
            self.set_inner_result(AccountMergeResult(code))
            return False

        other = AccountFrame.load_account(self.operation.body.value, db)
        if other is None:
            return fail("no-account", AccountMergeResultCode.ACCOUNT_MERGE_NO_ACCOUNT)
        if self.source_account.is_immutable_auth():
            return fail("static-auth", AccountMergeResultCode.ACCOUNT_MERGE_IMMUTABLE_SET)
        acc = self.source_account.account
        # numSubEntries counts signers + trustlines + offers; equality with
        # len(signers) means no trustlines/offers remain
        if acc.numSubEntries != len(acc.signers):
            return fail(
                "has-sub-entries", AccountMergeResultCode.ACCOUNT_MERGE_HAS_SUB_ENTRIES
            )
        balance = acc.balance
        other.mut().balance += balance
        other.store_change(delta, db)
        self.source_account.store_delete(delta, db)
        metrics.new_meter(("op-merge", "success", "apply"), "operation").mark()
        self.set_inner_result(
            AccountMergeResult(AccountMergeResultCode.ACCOUNT_MERGE_SUCCESS, balance)
        )
        return True


class InflationOpFrame(OperationFrame):
    def get_needed_threshold(self) -> int:
        return self.source_account.get_low_threshold()

    def do_check_valid(self, metrics) -> bool:
        return True

    def do_apply(self, metrics, delta, lm) -> bool:
        inflation_delta = LedgerDelta(outer=delta)
        header = inflation_delta.get_header()
        close_time = header.scpValue.closeTime
        seq = header.inflationSeq
        inflation_time = INFLATION_START_TIME + seq * INFLATION_FREQUENCY
        if close_time < inflation_time:
            metrics.new_meter(("op-inflation", "failure", "not-time"), "operation").mark()
            self.set_inner_result(
                InflationResult(InflationResultCode.INFLATION_NOT_TIME)
            )
            return False

        total_votes = header.totalCoins
        min_votes = big_divide(total_votes, INFLATION_WIN_MIN_PERCENT, TRILLION)
        db = lm.database
        winners = [
            (votes, dest)
            for votes, dest in AccountFrame.process_for_inflation(
                db, INFLATION_NUM_WINNERS
            )
            if votes >= min_votes
        ]
        amount_to_dole = big_divide(
            header.totalCoins, INFLATION_RATE_TRILLIONTHS, TRILLION
        )
        amount_to_dole += header.feePool
        header.feePool = 0
        header.inflationSeq += 1

        payouts = []
        left = amount_to_dole
        for votes, dest in winners:
            to_dole = big_divide(amount_to_dole, votes, total_votes)
            if to_dole == 0:
                continue
            winner = AccountFrame.load_account(dest, db)
            if winner is not None:
                left -= to_dole
                header.totalCoins += to_dole
                winner.mut().balance += to_dole
                winner.store_change(inflation_delta, db)
                payouts.append(InflationPayout(dest, to_dole))
        header.feePool += left

        self.set_inner_result(
            InflationResult(InflationResultCode.INFLATION_SUCCESS, payouts)
        )
        inflation_delta.commit()
        metrics.new_meter(("op-inflation", "success", "apply"), "operation").mark()
        return True
