"""TransactionFrame (reference: src/transactions/TransactionFrame.{h,cpp}).

Envelope wrapper: hashing, signature checking with signer weights/thresholds
and used-signature tracking, validity (commonValid/checkValid), fee+seqnum
processing, and apply with per-tx SQL savepoint + nested LedgerDelta.

Hash preimages (consensus-critical):
- contents hash = SHA256(xdr(networkID) ‖ xdr(ENVELOPE_TYPE_TX) ‖ xdr(tx))
  (TransactionFrame.cpp:55-61); signatures sign this 32-byte hash.
- full hash = SHA256(xdr(envelope)) (TransactionFrame.cpp:45-52).

Batched-verify integration: signature checks call PubKeyUtils.verify_sig,
which hits the global verify cache.  The TxSet layer *pre-warms* that cache
through the SigBackend batch path (cpu or tpu) before running this eager
algorithm — results are bit-identical to the reference's inline verify, the
batch is just a prefetch (SURVEY.md §7 design note on batched-verify
semantics).
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto import PubKeyUtils, sha256
from ..crypto.keys import SecretKey
from ..ledger.accountframe import _ACCT_KEY_PREFIX, AccountFrame
from ..ledger.delta import LedgerDelta
from .opframe import OperationFrame
from ..util.xmath import INT64_MAX
from ..xdr.base import xdr_to_opaque
from ..xdr.entries import EnvelopeType, PublicKey, Signer
from ..xdr.ledger import OperationMeta, TransactionResultPair, TransactionMeta
from ..xdr.overlay import MessageType, StellarMessage
from ..xdr.txs import (
    DecoratedSignature,
    OperationResult,
    TransactionEnvelope,
    TransactionResult,
    TransactionResultCode,
    TransactionResultResult,
)
from . import history as tx_history


def _acct_kb(pk: PublicKey) -> bytes:
    """ACCOUNT cache key (prefix + raw pubkey) — the footprint pre-pass
    builds thousands of these, so skip the LedgerKey/XDR round-trip the
    same way AccountFrame.load_account does."""
    return _ACCT_KEY_PREFIX + pk.value


class TransactionFrame:
    def __init__(self, network_id: bytes, envelope: TransactionEnvelope):
        self.network_id = network_id
        self.envelope = envelope
        self._src_bytes: Optional[bytes] = None
        self._contents_hash: Optional[bytes] = None
        self._full_hash: Optional[bytes] = None
        self._env_xdr: Optional[bytes] = None
        self.result: TransactionResult = TransactionResult()
        self.operations: List = []
        self.signing_account: Optional[AccountFrame] = None
        self.used_signatures: List[bool] = []
        self.reset_results()

    # -- construction ------------------------------------------------------
    @classmethod
    def make_from_wire(cls, network_id: bytes, envelope: TransactionEnvelope):
        return cls(network_id, envelope)

    # -- hashing -----------------------------------------------------------
    def clear_cached(self):
        self._contents_hash = None
        self._full_hash = None
        self._env_xdr = None

    def env_xdr(self) -> bytes:
        """Memoized envelope encoding — the envelope is packed for the full
        hash, the txset contents hash, and the txhistory row; it only
        changes when a signature is added (clear_cached)."""
        if self._env_xdr is None:
            self._env_xdr = self.envelope.to_xdr()
        return self._env_xdr

    def get_contents_hash(self) -> bytes:
        if self._contents_hash is None:
            self._contents_hash = sha256(
                xdr_to_opaque(
                    self.network_id, EnvelopeType.ENVELOPE_TYPE_TX, self.envelope.tx
                )
            )
        return self._contents_hash

    def get_full_hash(self) -> bytes:
        if self._full_hash is None:
            self._full_hash = sha256(self.env_xdr())
        return self._full_hash

    # -- basic accessors ---------------------------------------------------
    @property
    def tx(self):
        return self.envelope.tx

    def get_source_id(self) -> PublicKey:
        return self.envelope.tx.sourceAccount

    def source_bytes(self) -> bytes:
        """Memoized raw source-account key — the per-account grouping maps
        (txset chain check, apply-order batches, surge pricing) key on it
        once per tx instead of chasing the attribute chain per lookup."""
        sb = self._src_bytes
        if sb is None:
            sb = self._src_bytes = self.envelope.tx.sourceAccount.value
        return sb

    def get_seq_num(self) -> int:
        return self.envelope.tx.seqNum

    def get_fee(self) -> int:
        return self.envelope.tx.fee

    def get_min_fee(self, lm) -> int:
        count = len(self.envelope.tx.operations) or 1
        return lm.get_tx_fee() * count

    def add_signature(self, secret_key: SecretKey) -> None:
        self.clear_cached()
        self.envelope.signatures.append(
            DecoratedSignature(
                PubKeyUtils.get_hint(secret_key.get_public_key()),
                secret_key.sign(self.get_contents_hash()),
            )
        )

    # -- results -----------------------------------------------------------
    def reset_results(self):
        op_results = []
        for op in self.envelope.tx.operations:
            op_results.append(OperationResult(None, None))  # filled by op frames
        self.result = TransactionResult(
            feeCharged=self.get_fee(),
            result=TransactionResultResult(
                TransactionResultCode.txSUCCESS, op_results
            ),
            ext=0,
        )
        self.operations = [
            OperationFrame.make_helper(op, res, self)
            for op, res in zip(self.envelope.tx.operations, op_results)
        ]

    def set_result_code(self, code: TransactionResultCode):
        self.result.result = TransactionResultResult(code, None)

    def mark_result_failed(self):
        """txSUCCESS -> txFAILED keeping op results (markResultFailed)."""
        results = self.result.result.value
        self.result.result = TransactionResultResult(
            TransactionResultCode.txFAILED, results
        )

    def get_result_code(self) -> TransactionResultCode:
        return self.result.result.type

    def get_result_pair(self) -> TransactionResultPair:
        return TransactionResultPair(self.get_contents_hash(), self.result)

    # -- signature checking (TransactionFrame.cpp:129-167) -----------------
    def reset_signature_tracker(self):
        self.signing_account = None
        self.used_signatures = [False] * len(self.envelope.signatures)

    def check_signature(self, account: AccountFrame, needed_weight: int) -> bool:
        # Fast path for the dominant shape — one signature, master key
        # only, master weight sufficient: same decision and same
        # used-signature marking as the general loop below, without
        # building the Signer list (~4 calls/tx on the close path)
        acc = account.account
        if (
            len(self.envelope.signatures) == 1
            and not acc.signers
            and acc.thresholds[0] >= needed_weight
            and acc.thresholds[0] > 0
        ):
            sig = self.envelope.signatures[0]
            master = account.get_id()
            if PubKeyUtils.has_hint(master, sig.hint) and PubKeyUtils.verify_sig(
                master, sig.signature, self.get_contents_hash()
            ):
                self.used_signatures[0] = True
                return True
            return False
        key_weights: List[Signer] = []
        if account.account.thresholds[0]:
            key_weights.append(Signer(account.get_id(), account.account.thresholds[0]))
        key_weights.extend(account.account.signers)

        contents_hash = self.get_contents_hash()
        total_weight = 0
        for i, sig in enumerate(self.envelope.signatures):
            for j, kw in enumerate(key_weights):
                if PubKeyUtils.has_hint(kw.pubKey, sig.hint) and PubKeyUtils.verify_sig(
                    kw.pubKey, sig.signature, contents_hash
                ):
                    self.used_signatures[i] = True
                    total_weight += kw.weight
                    if total_weight >= needed_weight:
                        return True
                    del key_weights[j]  # can't sign twice
                    break
        return False

    def check_all_signatures_used(self) -> bool:
        for used in self.used_signatures:
            if not used:
                self.set_result_code(TransactionResultCode.txBAD_AUTH_EXTRA)
                return False
        return True

    def candidate_signature_pairs(self, db):
        """All hint-matched (pubkey, contents_hash, sig) triples this tx could
        verify — the batch-prefetch set for the SigBackend (covers the tx
        source and every op source account's signers)."""
        triples = []
        seen_accounts = set()
        accounts = [self.get_source_id()]
        for op in self.envelope.tx.operations:
            if op.sourceAccount is not None:
                accounts.append(op.sourceAccount)
        contents_hash = self.get_contents_hash()
        for aid in accounts:
            if aid.value in seen_accounts:
                continue
            seen_accounts.add(aid.value)
            af = AccountFrame.load_account(aid, db, readonly=True)
            if af is None:
                continue
            keys = []
            if af.account.thresholds[0]:
                keys.append(af.get_id())
            keys.extend(s.pubKey for s in af.account.signers)
            for sig in self.envelope.signatures:
                for pk in keys:
                    if PubKeyUtils.has_hint(pk, sig.hint):
                        triples.append((pk.value, contents_hash, sig.signature))
        return triples

    # -- static footprint (ledger/applysched.py pre-pass) ------------------
    def static_footprint(self):
        """The set of ACCOUNT cache keys (prefix+pubkey bytes, the same
        shape ``AccountFrame.load_account`` keys on) this tx can touch
        during apply, or None when the footprint cannot be statically
        bounded.

        Bounded ops declare exactly the accounts their apply path loads:
        native-asset payments (source + destination, no order-book walk),
        create-account, account-merge, and set-options without an
        inflation destination (the dest branch loads a THIRD account the
        bulk warm never sees).  Everything that walks the order book
        (offers, path payments, non-native payments) or aggregates over
        the whole ledger (inflation) is unbounded — the scheduler
        classifies those CONFLICTING and the whole set applies serially.
        Signer keys are auth-only (verify-cache lookups, no entry loads),
        so they do not widen the footprint."""
        from ..xdr.entries import AssetType
        from ..xdr.txs import OperationType as OT

        keys = {_acct_kb(self.get_source_id())}
        for op in self.envelope.tx.operations:
            if op.sourceAccount is not None:
                keys.add(_acct_kb(op.sourceAccount))
            t = op.body.type
            v = op.body.value
            if t == OT.PAYMENT:
                if v.asset.type != AssetType.ASSET_TYPE_NATIVE:
                    return None  # trustlines + possible issuer loads
                keys.add(_acct_kb(v.destination))
            elif t == OT.CREATE_ACCOUNT:
                keys.add(_acct_kb(v.destination))
            elif t == OT.ACCOUNT_MERGE:
                keys.add(_acct_kb(v))  # merge body is the destination
            elif t == OT.SET_OPTIONS:
                if v.inflationDest is not None:
                    return None  # loads the dest account (cold cache)
            else:
                # PATH_PAYMENT / MANAGE_OFFER / CREATE_PASSIVE_OFFER /
                # CHANGE_TRUST / ALLOW_TRUST / INFLATION: order-book or
                # trustline or whole-ledger state — not boundable here
                return None
        return keys

    # -- account loading ---------------------------------------------------
    def load_account(self, db, readonly: bool = False):
        """(Re)load the tx source into signing_account.  readonly skips
        the defensive cache copy — validation-path loads (check_valid /
        txset chain checks) only read; the apply path reloads mutable via
        common_valid(applying=True) and process_fee_seq_num.

        signing=True routes through the close's FrameContext identity map
        (ledger/framecontext.py): fee charging and validity-at-apply get
        the SAME frame instead of a copy per load — the one aliasing the
        reference itself has (mSigningAccount)."""
        self.signing_account = AccountFrame.load_account(
            self.get_source_id(), db, readonly=readonly, signing=True
        )
        return self.signing_account

    def load_account_shared(self, db, account_id: PublicKey):
        """Reuse the already-loaded signing account when an op's source is
        the tx source — the reference shares mSigningAccount the same way
        (TransactionFrame::loadAccount, src/transactions/TransactionFrame.cpp),
        so op mutations are visible through the tx frame and vice versa."""
        sa = self.signing_account
        if sa is not None and sa.account.accountID == account_id:
            if sa._sealed:
                # an earlier op (or fee charging) stored — and thereby
                # sealed — the shared signing frame; this op may mutate it
                # through raw entry fields, so CoW-unseal on hand-out
                # exactly like FrameContext.lend does (the recorded
                # snapshots in the delta/cache/buffer stay immutable)
                sa.touch()
            return sa
        return AccountFrame.load_account(account_id, db)

    # -- validity (TransactionFrame.cpp:215-312) ---------------------------
    def common_valid(self, app, applying: bool, current: int) -> bool:
        metrics = app.metrics
        lm = app.ledger_manager
        tx = self.envelope.tx

        def invalid(tag, code):
            metrics.new_meter(("transaction", "invalid", tag), "transaction").mark()
            self.set_result_code(code)
            return False

        if len(tx.operations) == 0:
            return invalid("missing-operation", TransactionResultCode.txMISSING_OPERATION)

        if tx.timeBounds is not None:
            close_time = lm.get_current_ledger_header().scpValue.closeTime
            if tx.timeBounds.minTime > close_time:
                return invalid("too-early", TransactionResultCode.txTOO_EARLY)
            if tx.timeBounds.maxTime and tx.timeBounds.maxTime < close_time:
                return invalid("too-late", TransactionResultCode.txTOO_LATE)

        if tx.fee < self.get_min_fee(lm):
            return invalid("insufficient-fee", TransactionResultCode.txINSUFFICIENT_FEE)

        if not self.load_account(app.database, readonly=not applying):
            return invalid("no-account", TransactionResultCode.txNO_ACCOUNT)

        # when applying, the seq num was already bumped by processFeeSeqNum
        if not applying:
            if current == 0:
                current = self.signing_account.get_seq_num()
            if current + 1 != tx.seqNum:
                return invalid("bad-seq", TransactionResultCode.txBAD_SEQ)

        if not self.check_signature(
            self.signing_account, self.signing_account.get_low_threshold()
        ):
            return invalid("bad-auth", TransactionResultCode.txBAD_AUTH)

        if (
            self.signing_account.get_balance() - tx.fee
            < self.signing_account.get_minimum_balance(lm)
        ):
            return invalid(
                "insufficient-balance", TransactionResultCode.txINSUFFICIENT_BALANCE
            )

        return True

    def check_valid(self, app, current: int = 0) -> bool:
        """Full validity: commonValid + per-op checkValid + no stray sigs
        (TransactionFrame.cpp:384-417)."""
        self.reset_signature_tracker()
        self.reset_results()
        res = self.common_valid(app, False, current)
        if res:
            for op in self.operations:
                if not op.check_valid(app, for_apply=False):
                    app.metrics.new_meter(
                        ("transaction", "invalid", "invalid-op"), "transaction"
                    ).mark()
                    self.mark_result_failed()
                    return False
            res = self.check_all_signatures_used()
            if not res:
                app.metrics.new_meter(
                    ("transaction", "invalid", "bad-auth-extra"), "transaction"
                ).mark()
        return res

    # -- fee + sequence (TransactionFrame.cpp:314-348) ---------------------
    def process_fee_seq_num(self, delta: LedgerDelta, lm) -> None:
        self.reset_signature_tracker()
        self.reset_results()
        if not self.load_account(lm.database):
            raise RuntimeError("Unexpected database state: missing source account")
        fee = self.result.feeCharged
        if fee > 0:
            avail = self.signing_account.get_balance()
            if avail < fee:
                fee = avail  # take all they have
                self.result.feeCharged = fee
            self.signing_account.mut().balance -= fee
            delta.get_header().feePool += fee
        if self.signing_account.get_seq_num() + 1 != self.envelope.tx.seqNum:
            raise RuntimeError("Unexpected account state: bad sequence")
        self.signing_account.set_seq_num(self.envelope.tx.seqNum)
        self.signing_account.store_change(delta, lm.database)

    # -- apply (TransactionFrame.cpp:439-495) ------------------------------
    def apply(self, delta: LedgerDelta, app, meta: Optional[TransactionMeta] = None) -> bool:
        if meta is None:
            meta = TransactionMeta(0, [])
        self.reset_signature_tracker()
        if not self.common_valid(app, True, 0):
            return False

        error_encountered = False
        stray_signatures = False
        db = app.database
        op_timer = app.metrics.new_timer(("transaction", "op", "apply"))
        this_tx_delta = LedgerDelta(outer=delta)
        try:
            with db.transaction():
                for op in self.operations:
                    with op_timer.time_scope():
                        op_delta = LedgerDelta(outer=this_tx_delta)
                        try:
                            ok = op.apply(op_delta, app)
                        except BaseException:
                            # EntryFrame stores hit the shared decoded-entry
                            # cache immediately, before op_delta.commit()
                            # lifts the keys into this_tx_delta — if apply
                            # dies mid-op only op_delta knows those keys, so
                            # its rollback must flush them or the caller's
                            # txINTERNAL_ERROR path leaves stale cache lines
                            op_delta.rollback()
                            raise
                    if not ok:
                        error_encountered = True
                    meta.value.append(OperationMeta(op_delta.get_changes()))
                    op_delta.commit()
                if not error_encountered:
                    if not self.check_all_signatures_used():
                        # malformed tx slipped through validation: roll back
                        # all effects and fail with txBAD_AUTH_EXTRA (set by
                        # check_all_signatures_used), matching
                        # TransactionFrame.cpp:474-480
                        stray_signatures = True
                        raise _TxRollback()
                    this_tx_delta.commit()
                else:
                    raise _TxRollback()
        except _TxRollback:
            pass
        finally:
            # The SQL savepoint rollback above undoes the rows, but entry
            # writes also populated the shared decoded-entry cache — flush
            # every touched key or later loads read rolled-back state (the
            # reference gets this from ~LedgerDelta calling rollback(),
            # LedgerDelta.cpp:39-44,204-220).  No-op when committed.
            this_tx_delta.rollback()

        if stray_signatures:
            return False
        if error_encountered:
            meta.value.clear()
            self.mark_result_failed()
        return not error_encountered

    # -- persistence (txhistory / txfeehistory) ----------------------------
    def history_row(self, ledger_seq: int, tx_index: int, meta):
        """Row tuple for the bulk txhistory insert at ledger close."""
        return tx_history.transaction_row(
            self.get_contents_hash(),
            ledger_seq,
            tx_index,
            self.env_xdr(),
            self.get_result_pair(),
            meta,
        )

    def fee_history_row(self, ledger_seq: int, tx_index: int, changes):
        return tx_history.fee_row(
            self.get_contents_hash(), ledger_seq, tx_index, changes
        )

    def to_stellar_message(self) -> StellarMessage:
        return StellarMessage(MessageType.TRANSACTION, self.envelope)


class _TxRollback(Exception):
    """Internal: unwind the SQL savepoint for a failed tx apply."""
