"""Transaction-test DSL (reference: src/transactions/TxTests.{h,cpp}).

Builders for envelopes of every op type + direct-apply helpers, used by the
tx suite, herder tests, simulation and the load generator — same role the
reference's TxTests helpers play across its suites.
"""

from __future__ import annotations

from typing import List, Optional

import stellar_tpu.xdr as X
from ..crypto.keys import SecretKey
from ..ledger.delta import LedgerDelta
from ..main.config import Config
from .frame import TransactionFrame

TEST_PASSPHRASE = "(V) (;,,;) (V) test network"


def get_test_config(instance: int = 0, backend: str = "cpu") -> Config:
    """Per-instance test config (reference: main/test.cpp:36 getTestConfig):
    in-memory sqlite, standalone, manual close, deterministic node seed,
    self-quorum, FORCE_SCP."""
    from ..xdr.scp import SCPQuorumSet

    cfg = Config()
    cfg.NETWORK_PASSPHRASE = TEST_PASSPHRASE
    cfg.DATABASE = "sqlite3://:memory:"
    cfg.RUN_STANDALONE = True
    cfg.MANUAL_CLOSE = True
    cfg.HTTP_PORT = 39100 + instance * 2
    cfg.PEER_PORT = 39200 + instance * 2
    cfg.TMP_DIR_PATH = f"/tmp/stellar-tpu-test-{instance}"
    cfg.BUCKET_DIR_PATH = f"/tmp/stellar-tpu-test-buckets-{instance}"
    cfg.SIGNATURE_BACKEND = backend
    cfg.NODE_SEED = SecretKey.from_seed(
        bytes([instance % 256]) + b"test-node-seed".ljust(31, b"\x00")
    )
    cfg.NODE_IS_VALIDATOR = True
    cfg.FORCE_SCP = True
    cfg.QUORUM_SET = SCPQuorumSet(1, [cfg.NODE_SEED.get_public_key()], [])
    # tests run the invariant plane ALL-ON (production default is
    # sampled): every test close pays the full conservation sums and
    # per-entry re-reads, so an aliasing/copy-elision regression fails
    # loudly here first (ROADMAP "Correctness" policy).  Perf harnesses
    # that need round-comparable p50s re-pin sampled themselves
    # (bench.py, profile_close.py).
    cfg.INVARIANT_SAMPLED = False
    return cfg


def root_key_for(app) -> SecretKey:
    return SecretKey.from_seed(app.network_id)


def get_account(n) -> SecretKey:
    if isinstance(n, str):
        # reference TxTests::getAccount (TxTests.cpp:200-208): the name
        # itself, stretched to 32 bytes with '.', IS the seed — same
        # account IDs as stellar-core's testacc/testtx for the same name
        seed = n.encode()
        seed = (seed + b"." * 32)[:32]
        return SecretKey.from_seed(seed)
    return SecretKey.pseudo_random_for_testing(n)


# -- envelope builders ------------------------------------------------------


def tx_from_ops(
    app, source: SecretKey, seq: int, ops: List[X.Operation], fee: Optional[int] = None
) -> TransactionFrame:
    if fee is None:
        fee = app.ledger_manager.get_tx_fee() * max(1, len(ops))
    tx = X.Transaction(
        sourceAccount=source.get_public_key(),
        fee=fee,
        seqNum=seq,
        timeBounds=None,
        memo=X.Memo.none(),
        operations=ops,
        ext=0,
    )
    frame = TransactionFrame(app.network_id, X.TransactionEnvelope(tx, []))
    frame.add_signature(source)
    return frame


def op(body_type: X.OperationType, value, source: Optional[SecretKey] = None) -> X.Operation:
    return X.Operation(
        source.get_public_key() if source else None,
        X.OperationBody(body_type, value),
    )


def create_account_op(dest: SecretKey, balance: int, source=None) -> X.Operation:
    return op(
        X.OperationType.CREATE_ACCOUNT,
        X.CreateAccountOp(dest.get_public_key(), balance),
        source,
    )


def payment_op(dest: SecretKey, amount: int, asset=None, source=None) -> X.Operation:
    return op(
        X.OperationType.PAYMENT,
        X.PaymentOp(dest.get_public_key(), asset or X.Asset.native(), amount),
        source,
    )


def path_payment_op(
    dest: SecretKey, send_asset, send_max, dest_asset, dest_amount, path=(), source=None
) -> X.Operation:
    return op(
        X.OperationType.PATH_PAYMENT,
        X.PathPaymentOp(
            send_asset, send_max, dest.get_public_key(), dest_asset, dest_amount,
            list(path),
        ),
        source,
    )


def change_trust_op(asset, limit: int, source=None) -> X.Operation:
    return op(X.OperationType.CHANGE_TRUST, X.ChangeTrustOp(asset, limit), source)


def allow_trust_op(trustor: SecretKey, code: bytes, authorize: bool, source=None) -> X.Operation:
    at_asset = X.AllowTrustAsset(
        X.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4
        if len(code) <= 4
        else X.AssetType.ASSET_TYPE_CREDIT_ALPHANUM12,
        code.ljust(4 if len(code) <= 4 else 12, b"\x00"),
    )
    return op(
        X.OperationType.ALLOW_TRUST,
        X.AllowTrustOp(trustor.get_public_key(), at_asset, authorize),
        source,
    )


def manage_offer_op(selling, buying, amount: int, price: X.Price, offer_id=0, source=None):
    return op(
        X.OperationType.MANAGE_OFFER,
        X.ManageOfferOp(selling, buying, amount, price, offer_id),
        source,
    )


def create_passive_offer_op(selling, buying, amount: int, price: X.Price, source=None):
    return op(
        X.OperationType.CREATE_PASSIVE_OFFER,
        X.CreatePassiveOfferOp(selling, buying, amount, price),
        source,
    )


def set_options_op(
    inflation_dest=None,
    clear_flags=None,
    set_flags=None,
    master_weight=None,
    low=None,
    med=None,
    high=None,
    home_domain=None,
    signer=None,
    source=None,
):
    return op(
        X.OperationType.SET_OPTIONS,
        X.SetOptionsOp(
            inflation_dest, clear_flags, set_flags, master_weight, low, med, high,
            home_domain, signer,
        ),
        source,
    )


def merge_op(dest: SecretKey, source=None) -> X.Operation:
    return op(X.OperationType.ACCOUNT_MERGE, dest.get_public_key(), source)


def inflation_op(source=None) -> X.Operation:
    return op(X.OperationType.INFLATION, None, source)


# -- apply helpers (TxTests applyCheck pattern) -----------------------------


def close_ledger_on(app, close_time: int, txs=(), externalize: bool = False) -> None:
    """The reference's closeLedgerOn (TxTests.cpp): close one real ledger
    at a chosen closeTime, optionally carrying transactions.

    ``externalize=True`` drives ``LedgerManager.externalize_value`` instead
    of closing inline — the path consensus takes, which routes through the
    close-pipeline scheduler's enqueue/drain/join machinery when
    ``Config.CLOSE_PIPELINE`` is on (ledger/closepipeline.py)."""
    from ..herder.ledgerclose import LedgerCloseData
    from ..herder.txset import TxSetFrame
    from ..xdr.ledger import StellarValue

    lm = app.ledger_manager
    txset = TxSetFrame(lm.last_closed.hash, list(txs))
    txset.sort_for_hash()
    sv = StellarValue(txset.get_contents_hash(), close_time, [], 0)
    ld = LedgerCloseData(lm.current.header.ledgerSeq, txset, sv)
    if externalize:
        lm.externalize_value(ld)
    else:
        lm.close_ledger(ld)


def dump_state(db) -> dict:
    """Entry tables + the history planes (txmeta/txchanges columns carry
    the XDR'd LedgerEntryChanges) — THE bit-exactness oracle shared by
    every differential suite and A/B harness (frame-context / CoW /
    close-pipeline).  Add new state tables HERE so every differential
    keeps covering them."""
    out = {}
    for table, order in (
        ("accounts", "accountid"),
        ("signers", "accountid, publickey"),
        ("trustlines", "accountid, issuer, assetcode"),
        ("offers", "offerid"),
        ("txhistory", "ledgerseq, txindex"),
        ("txfeehistory", "ledgerseq, txindex"),
    ):
        out[table] = db.query_all(f"SELECT * FROM {table} ORDER BY {order}")
    return out


def test_date(day: int, month: int, year: int) -> int:
    """UTC epoch seconds (the reference's getTestDate)."""
    import calendar

    return calendar.timegm((year, month, day, 0, 0, 0))


def apply_tx(app, tx: TransactionFrame, expect_code=None) -> TransactionFrame:
    """Charge fee+seq then apply against the current ledger delta, like one
    iteration of closeLedger's hot loop; commits to the DB."""
    lm = app.ledger_manager
    with app.database.transaction():
        delta = LedgerDelta(lm.current.header, app.database)
        tx.process_fee_seq_num(delta, lm)
        tx.apply(delta, app)
        delta.commit()
    if expect_code is not None:
        assert tx.get_result_code() == expect_code, (
            f"expected {expect_code!r}, got {tx.get_result_code()!r} "
            f"(ops: {[getattr(o.result, 'type', None) for o in tx.operations]})"
        )
    return tx


def op_result_of(tx: TransactionFrame, i: int = 0):
    return tx.result.result.value[i]


def inner_op_code(tx: TransactionFrame, i: int = 0):
    return op_result_of(tx, i).value.value.type
