"""Order-book crossing engine (reference: src/transactions/OfferExchange.cpp).

Terminology follows the reference: the taker sends "sheep" to receive
"wheat" from resting offers that sell wheat for sheep.  All division is
floor((a*b)/c) on 128-bit-wide intermediates (util/xmath.big_divide) — the
rounding direction is consensus-critical ("bias towards seller").
"""

from __future__ import annotations

import enum
from typing import Callable, List, Optional

from ..ledger.accountframe import AccountFrame
from ..ledger.offerframe import OfferFrame
from ..ledger.trustframe import TrustFrame
from ..util.xmath import INT64_MAX, big_divide_checked
from ..xdr.txs import ClaimOfferAtom


class CrossOfferResult(enum.Enum):
    TAKEN = 0
    PARTIAL = 1
    CANT_CONVERT = 2


class ConvertResult(enum.Enum):
    OK = 0
    PARTIAL = 1  # not enough offers to convert everything
    FILTER_STOP = 2


class OfferFilterResult(enum.Enum):
    KEEP = 0
    STOP = 1
    SKIP = 2


class OfferExchange:
    def __init__(self, delta, lm):
        self.delta = delta
        self.lm = lm
        self.offer_trail: List[ClaimOfferAtom] = []

    def cross_offer(
        self,
        selling_wheat_offer: OfferFrame,
        max_wheat_received: int,
        max_sheep_send: int,
    ):
        """-> (CrossOfferResult, num_wheat_received, num_sheep_send)."""
        # mut(), not the read alias: this binding is mutated in place
        # (amount shrink below) until the store seals it — mut() keeps
        # that legal even if a future path hands us a sealed frame
        # (load_best_offers frames are freshly decoded today)
        offer = selling_wheat_offer.mut()
        sheep = offer.buying
        wheat = offer.selling
        account_b_id = offer.sellerID
        db = self.lm.database

        account_b = AccountFrame.load_account(account_b_id, db)
        if account_b is None:
            raise RuntimeError("invalid database state: offer without account")

        wheat_line_b: Optional[TrustFrame] = None
        if not wheat.is_native():
            wheat_line_b = TrustFrame.load_trust_line(account_b_id, wheat, db)

        sheep_line_b: Optional[TrustFrame] = None
        if sheep.is_native():
            num_wheat_received = INT64_MAX
        else:
            sheep_line_b = TrustFrame.load_trust_line(account_b_id, sheep, db)
            seller_max_sheep = (
                sheep_line_b.get_max_amount_receive() if sheep_line_b else 0
            )
            ok, num_wheat_received = big_divide_checked(
                seller_max_sheep, offer.price.d, offer.price.n
            )
            if not ok:
                num_wheat_received = INT64_MAX

        # clamp by what the seller can actually sell
        if wheat.is_native():
            wheat_can_sell = account_b.get_balance_above_reserve(self.lm)
        else:
            if wheat_line_b is not None and wheat_line_b.is_authorized():
                wheat_can_sell = wheat_line_b.get_balance()
            else:
                wheat_can_sell = 0
        num_wheat_received = min(num_wheat_received, wheat_can_sell)

        if num_wheat_received >= offer.amount:
            num_wheat_received = offer.amount
        else:
            # shrink the offer to the seller's real capacity (written below)
            offer.amount = num_wheat_received

        reduced_offer = False
        if num_wheat_received > max_wheat_received:
            num_wheat_received = max_wheat_received
            reduced_offer = True

        ok, num_sheep_send = big_divide_checked(
            num_wheat_received, offer.price.n, offer.price.d
        )
        if not ok:
            num_sheep_send = INT64_MAX

        if num_sheep_send > max_sheep_send:
            num_sheep_send = max_sheep_send
            reduced_offer = True

        # bias towards seller (recompute wheat from the sheep actually sent)
        _, num_wheat_received = big_divide_checked(
            num_sheep_send, offer.price.d, offer.price.n
        )

        offer_taken = False
        if num_wheat_received == 0 or num_sheep_send == 0:
            if reduced_offer:
                return CrossOfferResult.CANT_CONVERT, 0, 0
            # bogus offer: force delete
            num_wheat_received = 0
            num_sheep_send = 0
            offer_taken = True

        offer_taken = offer_taken or offer.amount <= num_wheat_received
        if offer_taken:
            selling_wheat_offer.store_delete(self.delta, db)
            account_b.add_num_entries(-1, self.lm)
            account_b.store_change(self.delta, db)
        else:
            offer.amount -= num_wheat_received
            selling_wheat_offer.store_change(self.delta, db)

        if num_sheep_send != 0:
            if sheep.is_native():
                # mut(): the offer-taken branch above may already have
                # stored (and thereby sealed) account_b — the credit must
                # CoW, not reach the recorded numSubEntries snapshot
                account_b.mut().balance += num_sheep_send
                account_b.store_change(self.delta, db)
            else:
                if not sheep_line_b.add_balance(num_sheep_send):
                    return CrossOfferResult.CANT_CONVERT, 0, 0
                sheep_line_b.store_change(self.delta, db)

        if num_wheat_received != 0:
            if wheat.is_native():
                account_b.mut().balance -= num_wheat_received
                account_b.store_change(self.delta, db)
            else:
                if not wheat_line_b.add_balance(-num_wheat_received):
                    return CrossOfferResult.CANT_CONVERT, 0, 0
                wheat_line_b.store_change(self.delta, db)

        self.offer_trail.append(
            ClaimOfferAtom(
                account_b.get_id(),
                offer.offerID,
                wheat,
                num_wheat_received,
                sheep,
                num_sheep_send,
            )
        )
        return (
            CrossOfferResult.TAKEN if offer_taken else CrossOfferResult.PARTIAL,
            num_wheat_received,
            num_sheep_send,
        )

    def convert_with_offers(
        self,
        sheep,
        max_sheep_send: int,
        wheat,
        max_wheat_receive: int,
        offer_filter: Optional[Callable[[OfferFrame], OfferFilterResult]] = None,
    ):
        """-> (ConvertResult, sheep_sent, wheat_received); walks the book
        cheapest-first in pages of 5 (convertWithOffers)."""
        sheep_sent = 0
        wheat_received = 0
        db = self.lm.database
        offer_offset = 0
        need_more = max_wheat_receive > 0 and max_sheep_send > 0

        while need_more:
            batch = OfferFrame.load_best_offers(5, offer_offset, wheat, sheep, db)
            offer_offset += len(batch)
            for wheat_offer in batch:
                if offer_filter is not None:
                    fr = offer_filter(wheat_offer)
                    if fr == OfferFilterResult.STOP:
                        return ConvertResult.FILTER_STOP, sheep_sent, wheat_received
                    if fr == OfferFilterResult.SKIP:
                        continue

                cor, num_wheat, num_sheep = self.cross_offer(
                    wheat_offer, max_wheat_receive, max_sheep_send
                )
                if cor == CrossOfferResult.TAKEN:
                    assert offer_offset > 0
                    offer_offset -= 1  # a row disappeared under the cursor
                elif cor == CrossOfferResult.CANT_CONVERT:
                    return ConvertResult.PARTIAL, sheep_sent, wheat_received

                sheep_sent += num_sheep
                max_sheep_send -= num_sheep
                wheat_received += num_wheat
                max_wheat_receive -= num_wheat

                need_more = max_wheat_receive > 0 and max_sheep_send > 0
                if not need_more:
                    return ConvertResult.OK, sheep_sent, wheat_received
                if cor == CrossOfferResult.PARTIAL:
                    return ConvertResult.PARTIAL, sheep_sent, wheat_received

            if need_more and len(batch) < 5:
                return ConvertResult.OK, sheep_sent, wheat_received
        return ConvertResult.OK, sheep_sent, wheat_received
