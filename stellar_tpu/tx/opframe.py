"""OperationFrame base + factory (reference: src/transactions/OperationFrame.cpp).

Threshold categories (transactions/readme.md "Thresholds"):
- low: AllowTrust, Inflation
- medium: everything else (default)
- high: AccountMerge; SetOptions when touching thresholds/signers
"""

from __future__ import annotations

from typing import Optional

from ..ledger.accountframe import AccountFrame
from ..xdr.entries import AssetType, PublicKey
from ..xdr.txs import (
    Operation,
    OperationResult,
    OperationResultCode,
    OperationResultTr,
    OperationType,
)

# locale-independent alphanumeric check (the reference pins the C locale)
_ALNUM = set(
    b"0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz"
)


def is_asset_valid(asset) -> bool:
    """util/types.cpp isAssetValid: [a-zA-Z0-9]+ then zero padding only."""
    if asset.type == AssetType.ASSET_TYPE_NATIVE:
        return True
    code = asset.value.assetCode
    zeros = False
    onechar = False
    for b in code:
        if b == 0:
            zeros = True
        elif zeros:
            return False  # zeros must be trailing
        elif b not in _ALNUM:
            return False
        else:
            onechar = True
    return onechar


def is_string32_valid(s: str) -> bool:
    """util/types.cpp:60-71 isString32Valid: every byte must be ASCII and
    not a control character (rejects NUL, \\r, DEL, and anything >= 0x80 —
    the reference's `c < 0` on signed char).  Length is the XDR codec's
    job, but check it here too for defense in depth."""
    b = s.encode("utf-8")
    return len(b) <= 32 and all(0x20 <= c < 0x7F for c in b)


class OperationFrame:
    def __init__(self, op: Operation, result: OperationResult, parent_tx):
        self.operation = op
        self.result = result
        self.parent_tx = parent_tx
        self.source_account: Optional[AccountFrame] = None

    # -- factory (OperationFrame::makeHelper) ------------------------------
    # built lazily ONCE: the op modules import this one, so the mapping
    # can't exist at module load — but rebuilding it (and re-executing ten
    # imports) per op was measurable at 5000-tx closes
    _HELPER_MAP = None

    @staticmethod
    def make_helper(op: Operation, result: OperationResult, parent_tx):
        mapping = OperationFrame._HELPER_MAP
        if mapping is None:
            from .ops_account import (
                AllowTrustOpFrame,
                ChangeTrustOpFrame,
                CreateAccountOpFrame,
                InflationOpFrame,
                MergeOpFrame,
                SetOptionsOpFrame,
            )
            from .ops_offers import CreatePassiveOfferOpFrame, ManageOfferOpFrame
            from .ops_payment import PathPaymentOpFrame, PaymentOpFrame

            mapping = OperationFrame._HELPER_MAP = {
                OperationType.CREATE_ACCOUNT: CreateAccountOpFrame,
                OperationType.PAYMENT: PaymentOpFrame,
                OperationType.PATH_PAYMENT: PathPaymentOpFrame,
                OperationType.MANAGE_OFFER: ManageOfferOpFrame,
                OperationType.CREATE_PASSIVE_OFFER: CreatePassiveOfferOpFrame,
                OperationType.SET_OPTIONS: SetOptionsOpFrame,
                OperationType.CHANGE_TRUST: ChangeTrustOpFrame,
                OperationType.ALLOW_TRUST: AllowTrustOpFrame,
                OperationType.ACCOUNT_MERGE: MergeOpFrame,
                OperationType.INFLATION: InflationOpFrame,
            }
        cls = mapping.get(op.body.type)
        if cls is None:
            raise ValueError(f"Unknown op type {op.body.type!r}")
        return cls(op, result, parent_tx)

    # -- result plumbing ---------------------------------------------------
    def set_inner_result(self, inner) -> None:
        self.result.type = OperationResultCode.opINNER
        self.result.value = OperationResultTr(self.operation.body.type, inner)

    def set_result_code(self, code: OperationResultCode) -> None:
        self.result.type = code
        self.result.value = None

    def get_result_code(self) -> OperationResultCode:
        return self.result.type

    def inner_result(self):
        return self.result.value.value

    # -- identity ----------------------------------------------------------
    def get_source_id(self) -> PublicKey:
        if self.operation.sourceAccount is not None:
            return self.operation.sourceAccount
        return self.parent_tx.envelope.tx.sourceAccount

    def load_account(self, db) -> bool:
        self.source_account = self.parent_tx.load_account_shared(
            db, self.get_source_id()
        )
        return self.source_account is not None

    # -- auth --------------------------------------------------------------
    def get_needed_threshold(self) -> int:
        return self.source_account.get_medium_threshold()

    def check_signature(self) -> bool:
        return self.parent_tx.check_signature(
            self.source_account, self.get_needed_threshold()
        )

    # -- validity / apply (OperationFrame.cpp:95-160) ----------------------
    def check_valid(self, app, for_apply: bool) -> bool:
        metrics = app.metrics
        if not self.load_account(app.database):
            if for_apply or self.operation.sourceAccount is None:
                metrics.new_meter(
                    ("operation", "invalid", "no-account"), "operation"
                ).mark()
                self.set_result_code(OperationResultCode.opNO_ACCOUNT)
                return False
            # validation of an op whose (explicit) source doesn't exist yet:
            # check sigs against a synthetic auth-only shell
            self.source_account = AccountFrame.make_auth_only(
                self.operation.sourceAccount
            )

        if not self.check_signature():
            metrics.new_meter(("operation", "invalid", "bad-auth"), "operation").mark()
            self.set_result_code(OperationResultCode.opBAD_AUTH)
            return False

        if not for_apply:
            # ops must not rely on ledger state during validation: earlier ops
            # in the tx may change it
            self.source_account = None

        self.result.type = OperationResultCode.opINNER
        self.result.value = OperationResultTr(self.operation.body.type, None)
        return self.do_check_valid(app.metrics)

    def apply(self, delta, app) -> bool:
        if not self.check_valid(app, for_apply=True):
            return False
        return self.do_apply(app.metrics, delta, app.ledger_manager)

    # -- abstract ----------------------------------------------------------
    def do_check_valid(self, metrics) -> bool:
        raise NotImplementedError

    def do_apply(self, metrics, delta, lm) -> bool:
        raise NotImplementedError
