"""ManageOffer / CreatePassiveOffer (reference:
src/transactions/ManageOfferOpFrame.cpp, CreatePassiveOfferOpFrame.cpp)."""

from __future__ import annotations

from ..ledger.offerframe import OfferFrame
from ..ledger.trustframe import TrustFrame
from ..util.xmath import INT64_MAX, big_divide_checked
from ..xdr.entries import LedgerEntry, LedgerEntryData, LedgerEntryType, OfferEntry, OfferEntryFlags
from ..xdr.txs import (
    ManageOfferEffect,
    ManageOfferOp,
    ManageOfferResult,
    ManageOfferResultCode,
    ManageOfferSuccessResult,
    ManageOfferSuccessResultOffer,
)
from .offerexchange import ConvertResult, OfferExchange, OfferFilterResult
from .opframe import OperationFrame, is_asset_valid


def _price_cmp(a, b):
    """compare fractions a.n/a.d vs b.n/b.d exactly."""
    lhs = a.n * b.d
    rhs = b.n * a.d
    return (lhs > rhs) - (lhs < rhs)


class ManageOfferOpFrame(OperationFrame):
    passive = False

    @property
    def mo(self) -> ManageOfferOp:
        return self.operation.body.value

    def _fail(self, metrics, tag, code):
        metrics.new_meter(("op-manage-offer", "invalid", tag), "operation").mark()
        self.set_inner_result(ManageOfferResult(code))
        return False

    def do_check_valid(self, metrics) -> bool:
        mo = self.mo
        if not is_asset_valid(mo.selling) or not is_asset_valid(mo.buying):
            return self._fail(
                metrics, "invalid-asset", ManageOfferResultCode.MANAGE_OFFER_MALFORMED
            )
        if mo.selling == mo.buying:
            return self._fail(
                metrics, "equal-currencies", ManageOfferResultCode.MANAGE_OFFER_MALFORMED
            )
        if mo.amount < 0 or mo.price.d <= 0 or mo.price.n <= 0:
            return self._fail(
                metrics,
                "negative-or-zero-values",
                ManageOfferResultCode.MANAGE_OFFER_MALFORMED,
            )
        return True

    def _check_offer_valid(self, metrics, db) -> bool:
        """Issuers exist + lines exist/authorized (checkOfferValid)."""
        mo = self.mo
        sheep, wheat = mo.selling, mo.buying
        self.sheep_line = None
        self.wheat_line = None
        if mo.amount == 0:
            return True  # deleting: no line checks

        if not sheep.is_native():
            line, issuer = TrustFrame.load_trust_line_issuer(
                self.get_source_id(), sheep, db
            )
            self.sheep_line = line
            if issuer is None:
                return self._fail(
                    metrics, "sell-no-issuer",
                    ManageOfferResultCode.MANAGE_OFFER_SELL_NO_ISSUER,
                )
            if line is None:
                return self._fail(
                    metrics, "sell-no-trust",
                    ManageOfferResultCode.MANAGE_OFFER_SELL_NO_TRUST,
                )
            if line.get_balance() == 0:
                return self._fail(
                    metrics, "underfunded",
                    ManageOfferResultCode.MANAGE_OFFER_UNDERFUNDED,
                )
            if not line.is_authorized():
                return self._fail(
                    metrics, "sell-not-authorized",
                    ManageOfferResultCode.MANAGE_OFFER_SELL_NOT_AUTHORIZED,
                )

        if not wheat.is_native():
            line, issuer = TrustFrame.load_trust_line_issuer(
                self.get_source_id(), wheat, db
            )
            self.wheat_line = line
            if issuer is None:
                return self._fail(
                    metrics, "buy-no-issuer",
                    ManageOfferResultCode.MANAGE_OFFER_BUY_NO_ISSUER,
                )
            if line is None:
                return self._fail(
                    metrics, "buy-no-trust",
                    ManageOfferResultCode.MANAGE_OFFER_BUY_NO_TRUST,
                )
            if not line.is_authorized():
                return self._fail(
                    metrics, "buy-not-authorized",
                    ManageOfferResultCode.MANAGE_OFFER_BUY_NOT_AUTHORIZED,
                )
        return True

    @staticmethod
    def _build_offer(account, mo: ManageOfferOp, flags: int) -> OfferEntry:
        return OfferEntry(
            sellerID=account,
            offerID=mo.offerID,
            selling=mo.selling,
            buying=mo.buying,
            amount=mo.amount,
            price=mo.price,
            flags=flags,
            ext=0,
        )

    def do_apply(self, metrics, delta, lm) -> bool:
        from ..ledger.delta import LedgerDelta

        db = lm.database
        if not self._check_offer_valid(metrics, db):
            return False

        mo = self.mo
        sheep, wheat = mo.selling, mo.buying
        creating_new = mo.offerID == 0

        if not creating_new:
            sell_offer = OfferFrame.load_offer(self.get_source_id(), mo.offerID, db)
            if sell_offer is None:
                return self._fail(
                    metrics, "not-found", ManageOfferResultCode.MANAGE_OFFER_NOT_FOUND
                )
            old_flags = sell_offer.offer.flags
            sell_offer.replace_body(
                self._build_offer(self.get_source_id(), mo, old_flags)
            )
            self.passive = bool(old_flags & OfferEntryFlags.PASSIVE_FLAG)
        else:
            flags = int(OfferEntryFlags.PASSIVE_FLAG) if self.passive else 0
            le = LedgerEntry(
                0,
                LedgerEntryData(
                    LedgerEntryType.OFFER,
                    self._build_offer(self.get_source_id(), mo, flags),
                ),
                0,
            )
            sell_offer = OfferFrame(le)

        max_sheep_send = sell_offer.offer.amount
        success = ManageOfferSuccessResult(
            [], ManageOfferSuccessResultOffer(ManageOfferEffect.MANAGE_OFFER_DELETED)
        )
        self.set_inner_result(
            ManageOfferResult(ManageOfferResultCode.MANAGE_OFFER_SUCCESS, success)
        )

        stop_code = []
        try:
            with db.transaction():
                temp_delta = LedgerDelta(outer=delta)
                if mo.amount == 0:
                    sell_offer.mut().amount = 0
                else:
                    if sheep.is_native():
                        max_sheep_can_sell = (
                            self.source_account.get_balance_above_reserve(lm)
                        )
                    else:
                        max_sheep_can_sell = self.sheep_line.get_balance()
                    if wheat.is_native():
                        max_wheat_can_sell = INT64_MAX
                    else:
                        max_wheat_can_sell = self.wheat_line.get_max_amount_receive()
                        if max_wheat_can_sell == 0:
                            self._fail(
                                metrics, "line-full",
                                ManageOfferResultCode.MANAGE_OFFER_LINE_FULL,
                            )
                            raise _OfferAbort()

                    price = sell_offer.offer.price
                    ok, max_sheep_by_wheat = big_divide_checked(
                        max_wheat_can_sell, price.d, price.n
                    )
                    if not ok:
                        max_sheep_by_wheat = INT64_MAX
                    max_sheep_can_sell = min(max_sheep_can_sell, max_sheep_by_wheat)
                    max_sheep_send = min(max_sheep_can_sell, max_sheep_send)

                    oe = OfferExchange(temp_delta, lm)
                    from ..xdr.entries import Price

                    max_wheat_price = Price(price.d, price.n)

                    def offer_filter(o):
                        if o.get_offer_id() == sell_offer.offer.offerID:
                            return OfferFilterResult.SKIP  # never cross self-update
                        c = _price_cmp(o.get_price(), max_wheat_price)
                        if (self.passive and c >= 0) or c > 0:
                            return OfferFilterResult.STOP
                        if o.get_seller_id() == self.get_source_id():
                            stop_code.append(
                                ManageOfferResultCode.MANAGE_OFFER_CROSS_SELF
                            )
                            return OfferFilterResult.STOP
                        return OfferFilterResult.KEEP

                    r, sheep_sent, wheat_received = oe.convert_with_offers(
                        sheep, max_sheep_send, wheat, max_wheat_can_sell, offer_filter
                    )
                    if r == ConvertResult.FILTER_STOP and stop_code:
                        self.set_inner_result(ManageOfferResult(stop_code[0]))
                        raise _OfferAbort()

                    success.offersClaimed = list(oe.offer_trail)

                    if wheat_received > 0:
                        if wheat.is_native():
                            self.source_account.mut().balance += wheat_received
                            self.source_account.store_change(delta, db)
                        else:
                            if not self.wheat_line.add_balance(wheat_received):
                                raise RuntimeError("offer claimed over limit")
                            self.wheat_line.store_change(delta, db)
                        if sheep.is_native():
                            # the store above SEALED the frame: mut() pays
                            # the CoW copy so the debit cannot reach the
                            # wheat-credit snapshot already recorded
                            self.source_account.mut().balance -= sheep_sent
                            self.source_account.store_change(delta, db)
                        else:
                            if not self.sheep_line.add_balance(-sheep_sent):
                                raise RuntimeError("offer sold more than balance")
                            self.sheep_line.store_change(delta, db)

                    sell_offer.mut().amount = max_sheep_send - sheep_sent

                if sell_offer.offer.amount > 0:
                    if creating_new:
                        if not self.source_account.add_num_entries(1, lm):
                            self._fail(
                                metrics, "low reserve",
                                ManageOfferResultCode.MANAGE_OFFER_LOW_RESERVE,
                            )
                            raise _OfferAbort()
                        sell_offer.mut().offerID = temp_delta.generate_id()
                        success.offer = ManageOfferSuccessResultOffer(
                            ManageOfferEffect.MANAGE_OFFER_CREATED, None
                        )
                        sell_offer.store_add(temp_delta, db)
                        self.source_account.store_change(temp_delta, db)
                    else:
                        success.offer = ManageOfferSuccessResultOffer(
                            ManageOfferEffect.MANAGE_OFFER_UPDATED, None
                        )
                        sell_offer.store_change(temp_delta, db)
                    # analysis: off cow-mutation -- `success` is the ManageOfferSuccessResult XDR union (a tx result, not an EntryFrame); `.offer` here is its effect arm, not an entry alias
                    success.offer.value = sell_offer.offer
                else:
                    success.offer = ManageOfferSuccessResultOffer(
                        ManageOfferEffect.MANAGE_OFFER_DELETED, None
                    )
                    if not creating_new:
                        sell_offer.store_delete(temp_delta, db)
                        self.source_account.add_num_entries(-1, lm)
                        self.source_account.store_change(temp_delta, db)
                temp_delta.commit()
        except _OfferAbort:
            return False

        metrics.new_meter(("op-create-offer", "success", "apply"), "operation").mark()
        return True


class _OfferAbort(Exception):
    """Unwind the offer-op SQL savepoint after a failure result is set."""


class CreatePassiveOfferOpFrame(ManageOfferOpFrame):
    """Same machinery with mPassive=true and offerID=0.  The original op is
    kept as self.operation (so the result union's discriminant stays
    CREATE_PASSIVE_OFFER); only the ManageOfferOp view is synthetic."""

    passive = True

    def __init__(self, op, result, parent_tx):
        OperationFrame.__init__(self, op, result, parent_tx)
        cp = op.body.value
        self._synth = ManageOfferOp(
            selling=cp.selling,
            buying=cp.buying,
            amount=cp.amount,
            price=cp.price,
            offerID=0,
        )
        self.passive = True

    @property
    def mo(self) -> ManageOfferOp:
        return self._synth
