"""Wire types from the reference's src/xdr/Stellar-SCP.x (87 lines)."""

from __future__ import annotations

import enum
from typing import List, Optional

from .base import (
    DepthLimited,
    option,
    uint32,
    uint64,
    var_array,
    var_opaque,
    xenum,
    xf,
    xstruct,
    xunion,
)
from .xtypes import HASH, PUBLIC_KEY, SIGNATURE, PublicKey

VALUE = var_opaque()  # typedef opaque Value<>


@xstruct
class SCPBallot:
    counter: int = xf(uint32, 0)  # n
    value: bytes = xf(VALUE, b"")  # x


class SCPStatementType(enum.IntEnum):
    SCP_ST_PREPARE = 0
    SCP_ST_CONFIRM = 1
    SCP_ST_EXTERNALIZE = 2
    SCP_ST_NOMINATE = 3


@xstruct
class SCPNomination:
    quorumSetHash: bytes = xf(HASH, b"\x00" * 32)  # D
    votes: List[bytes] = xf(var_array(VALUE), factory=list)  # X
    accepted: List[bytes] = xf(var_array(VALUE), factory=list)  # Y


@xstruct
class SCPStatementPrepare:
    quorumSetHash: bytes = xf(HASH, b"\x00" * 32)  # D
    ballot: SCPBallot = xf(SCPBallot._codec, factory=SCPBallot)  # b
    prepared: Optional[SCPBallot] = xf(option(SCPBallot._codec), None)  # p
    preparedPrime: Optional[SCPBallot] = xf(option(SCPBallot._codec), None)  # p'
    nC: int = xf(uint32, 0)
    nP: int = xf(uint32, 0)


@xstruct
class SCPStatementConfirm:
    quorumSetHash: bytes = xf(HASH, b"\x00" * 32)  # D
    nPrepared: int = xf(uint32, 0)  # n_p
    commit: SCPBallot = xf(SCPBallot._codec, factory=SCPBallot)  # c
    nP: int = xf(uint32, 0)


@xstruct
class SCPStatementExternalize:
    commit: SCPBallot = xf(SCPBallot._codec, factory=SCPBallot)  # c
    nP: int = xf(uint32, 0)
    commitQuorumSetHash: bytes = xf(HASH, b"\x00" * 32)  # D before EXTERNALIZE


@xunion(
    xenum(SCPStatementType),
    {
        SCPStatementType.SCP_ST_PREPARE: ("prepare", SCPStatementPrepare._codec),
        SCPStatementType.SCP_ST_CONFIRM: ("confirm", SCPStatementConfirm._codec),
        SCPStatementType.SCP_ST_EXTERNALIZE: (
            "externalize",
            SCPStatementExternalize._codec,
        ),
        SCPStatementType.SCP_ST_NOMINATE: ("nominate", SCPNomination._codec),
    },
)
class SCPStatementPledges:
    type: SCPStatementType
    value: object = None


@xstruct
class SCPStatement:
    nodeID: PublicKey = xf(PUBLIC_KEY)  # v
    slotIndex: int = xf(uint64, 0)  # i
    pledges: SCPStatementPledges = xf(SCPStatementPledges._codec)


@xstruct
class SCPEnvelope:
    statement: SCPStatement = xf(SCPStatement._codec)
    signature: bytes = xf(SIGNATURE, b"")


_QSET_RECURSION = DepthLimited(max_depth=8)

@xstruct
class SCPQuorumSet:
    threshold: int = xf(uint32, 0)
    validators: List[PublicKey] = xf(var_array(PUBLIC_KEY), factory=list)
    innerSets: List["SCPQuorumSet"] = xf(var_array(_QSET_RECURSION), factory=list)


# Tie the recursive knot in place, so the codec in the struct codec AND the
# codec in the dataclass field metadata are the same object.  The reference
# allows only 2 levels of nesting (Stellar-SCP.x:80 comment), enforced
# semantically in the herder; the depth-8 bound here is pure decode safety.
_QSET_RECURSION.inner = SCPQuorumSet._codec
