"""Wire types from the reference's src/xdr/Stellar-ledger-entries.x (226 lines)."""

from __future__ import annotations

import enum
from typing import List, Optional

from .base import (
    array,
    int32,
    int64,
    opaque,
    option,
    string,
    uint32,
    uint64,
    var_array,
    xenum,
    xf,
    xstruct,
    xunion,
)
from .xtypes import PUBLIC_KEY, PublicKey

ACCOUNT_ID = PUBLIC_KEY  # typedef PublicKey AccountID
AccountID = PublicKey
THRESHOLDS = opaque(4)
STRING32 = string(32)
SEQUENCE_NUMBER = uint64


class AssetType(enum.IntEnum):
    ASSET_TYPE_NATIVE = 0
    ASSET_TYPE_CREDIT_ALPHANUM4 = 1
    ASSET_TYPE_CREDIT_ALPHANUM12 = 2


@xstruct
class AssetAlphaNum4:
    XDR_VALUE_SEMANTICS = True

    assetCode: bytes = xf(opaque(4))  # 1 to 4 characters
    issuer: PublicKey = xf(ACCOUNT_ID)


@xstruct
class AssetAlphaNum12:
    XDR_VALUE_SEMANTICS = True

    assetCode: bytes = xf(opaque(12))  # 5 to 12 characters
    issuer: PublicKey = xf(ACCOUNT_ID)


@xunion(
    xenum(AssetType),
    {
        AssetType.ASSET_TYPE_NATIVE: None,
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AssetAlphaNum4._codec),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AssetAlphaNum12._codec),
    },
)
class Asset:
    type: AssetType
    value: object = None

    @classmethod
    def native(cls) -> "Asset":
        return cls(AssetType.ASSET_TYPE_NATIVE, None)

    @classmethod
    def alphanum4(cls, code: bytes, issuer: PublicKey) -> "Asset":
        return cls(
            AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
            AssetAlphaNum4(code.ljust(4, b"\x00"), issuer),
        )

    @classmethod
    def alphanum12(cls, code: bytes, issuer: PublicKey) -> "Asset":
        return cls(
            AssetType.ASSET_TYPE_CREDIT_ALPHANUM12,
            AssetAlphaNum12(code.ljust(12, b"\x00"), issuer),
        )

    def is_native(self) -> bool:
        return self.type == AssetType.ASSET_TYPE_NATIVE

    def code_and_issuer(self):
        if self.type == AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
            return self.value.assetCode, self.value.issuer
        if self.type == AssetType.ASSET_TYPE_CREDIT_ALPHANUM12:
            return self.value.assetCode, self.value.issuer
        return None, None

    def __hash__(self):
        code, issuer = self.code_and_issuer()
        return hash((int(self.type), code, issuer.value if issuer else None))


ASSET = Asset._codec


@xstruct
class Price:
    XDR_VALUE_SEMANTICS = True

    n: int = xf(int32, 0)  # numerator
    d: int = xf(int32, 1)  # denominator


class ThresholdIndexes(enum.IntEnum):
    THRESHOLD_MASTER_WEIGHT = 0
    THRESHOLD_LOW = 1
    THRESHOLD_MED = 2
    THRESHOLD_HIGH = 3


class LedgerEntryType(enum.IntEnum):
    ACCOUNT = 0
    TRUSTLINE = 1
    OFFER = 2


@xstruct
class Signer:
    pubKey: PublicKey = xf(ACCOUNT_ID)
    weight: int = xf(uint32, 0)


class AccountFlags(enum.IntFlag):
    AUTH_REQUIRED_FLAG = 0x1
    AUTH_REVOCABLE_FLAG = 0x2
    AUTH_IMMUTABLE_FLAG = 0x4


MASK_ACCOUNT_FLAGS = 0x7


class _Ext0Codec(int32.__class__):
    """The ubiquitous reserved `union switch (int v) { case 0: void; } ext`."""

    def pack_into(self, val, out):
        # reserved arm: always writes 0 regardless of the field value, so a
        # stray in-memory value can never produce undecodable bytes
        super().pack_into(0, out)

    def unpack_from(self, buf, off):
        v, off = super().unpack_from(buf, off)
        if v != 0:
            from .base import XdrError

            raise XdrError(f"reserved ext union has v={v}")
        return 0, off


EXT0 = _Ext0Codec()


@xstruct
class AccountEntry:
    accountID: PublicKey = xf(ACCOUNT_ID)
    balance: int = xf(int64, 0)  # in stroops
    seqNum: int = xf(SEQUENCE_NUMBER, 0)
    numSubEntries: int = xf(uint32, 0)
    inflationDest: Optional[PublicKey] = xf(option(ACCOUNT_ID), None)
    flags: int = xf(uint32, 0)
    homeDomain: str = xf(STRING32, "")
    thresholds: bytes = xf(THRESHOLDS, b"\x01\x00\x00\x00")
    signers: List[Signer] = xf(var_array(Signer._codec, 20), factory=list)
    ext: int = xf(EXT0, 0)


class TrustLineFlags(enum.IntFlag):
    AUTHORIZED_FLAG = 1


MASK_TRUSTLINE_FLAGS = 1


@xstruct
class TrustLineEntry:
    accountID: PublicKey = xf(ACCOUNT_ID)
    asset: Asset = xf(ASSET)
    balance: int = xf(int64, 0)
    limit: int = xf(int64, 0)
    flags: int = xf(uint32, 0)
    ext: int = xf(EXT0, 0)


class OfferEntryFlags(enum.IntFlag):
    PASSIVE_FLAG = 1


@xstruct
class OfferEntry:
    sellerID: PublicKey = xf(ACCOUNT_ID)
    offerID: int = xf(uint64, 0)
    selling: Asset = xf(ASSET)  # A
    buying: Asset = xf(ASSET)  # B
    amount: int = xf(int64, 0)  # amount of A
    price: Price = xf(Price._codec, factory=Price)  # price of A in terms of B
    flags: int = xf(uint32, 0)
    ext: int = xf(EXT0, 0)


@xunion(
    xenum(LedgerEntryType),
    {
        LedgerEntryType.ACCOUNT: ("account", AccountEntry._codec),
        LedgerEntryType.TRUSTLINE: ("trustLine", TrustLineEntry._codec),
        LedgerEntryType.OFFER: ("offer", OfferEntry._codec),
    },
)
class LedgerEntryData:
    type: LedgerEntryType
    value: object = None


@xstruct
class LedgerEntry:
    lastModifiedLedgerSeq: int = xf(uint32, 0)
    data: LedgerEntryData = xf(LedgerEntryData._codec)
    ext: int = xf(EXT0, 0)


class EnvelopeType(enum.IntEnum):
    ENVELOPE_TYPE_SCP = 1
    ENVELOPE_TYPE_TX = 2
    ENVELOPE_TYPE_AUTH = 3
