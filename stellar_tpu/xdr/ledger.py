"""Wire types from the reference's src/xdr/Stellar-ledger.x (234 lines)."""

from __future__ import annotations

import enum
from typing import List

from .base import (
    array,
    int32,
    int64,
    uint32,
    uint64,
    var_array,
    var_opaque,
    xenum,
    xf,
    xstruct,
    xunion,
)
from .entries import (
    ACCOUNT_ID,
    ASSET,
    EXT0,
    Asset,
    LedgerEntry,
    LedgerEntryType,
    PublicKey,
)
from .txs import TransactionEnvelope, TransactionResult
from .xtypes import HASH

UPGRADE_TYPE = var_opaque(128)
MAX_TX_PER_LEDGER = 5000


@xstruct
class StellarValue:
    txSetHash: bytes = xf(HASH, b"\x00" * 32)
    closeTime: int = xf(uint64, 0)
    upgrades: List[bytes] = xf(var_array(UPGRADE_TYPE, 6), factory=list)
    ext: int = xf(EXT0, 0)


@xstruct
class LedgerHeader:
    ledgerVersion: int = xf(uint32, 0)
    previousLedgerHash: bytes = xf(HASH, b"\x00" * 32)
    scpValue: StellarValue = xf(StellarValue._codec, factory=StellarValue)
    txSetResultHash: bytes = xf(HASH, b"\x00" * 32)
    bucketListHash: bytes = xf(HASH, b"\x00" * 32)
    ledgerSeq: int = xf(uint32, 0)
    totalCoins: int = xf(int64, 0)
    feePool: int = xf(int64, 0)
    inflationSeq: int = xf(uint32, 0)
    idPool: int = xf(uint64, 0)
    baseFee: int = xf(uint32, 100)
    baseReserve: int = xf(uint32, 100000000)
    maxTxSetSize: int = xf(uint32, 100)
    skipList: List[bytes] = xf(array(HASH, 4), factory=lambda: [b"\x00" * 32] * 4)
    ext: int = xf(EXT0, 0)


class LedgerUpgradeType(enum.IntEnum):
    LEDGER_UPGRADE_VERSION = 1
    LEDGER_UPGRADE_BASE_FEE = 2
    LEDGER_UPGRADE_MAX_TX_SET_SIZE = 3


@xunion(
    xenum(LedgerUpgradeType),
    {
        LedgerUpgradeType.LEDGER_UPGRADE_VERSION: ("newLedgerVersion", uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: ("newBaseFee", uint32),
        LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE: (
            "newMaxTxSetSize",
            uint32,
        ),
    },
)
class LedgerUpgrade:
    type: LedgerUpgradeType
    value: object = None


@xstruct
class LedgerKeyAccount:
    accountID: PublicKey = xf(ACCOUNT_ID)


@xstruct
class LedgerKeyTrustLine:
    accountID: PublicKey = xf(ACCOUNT_ID)
    asset: Asset = xf(ASSET)


@xstruct
class LedgerKeyOffer:
    sellerID: PublicKey = xf(ACCOUNT_ID)
    offerID: int = xf(uint64, 0)


@xunion(
    xenum(LedgerEntryType),
    {
        LedgerEntryType.ACCOUNT: ("account", LedgerKeyAccount._codec),
        LedgerEntryType.TRUSTLINE: ("trustLine", LedgerKeyTrustLine._codec),
        LedgerEntryType.OFFER: ("offer", LedgerKeyOffer._codec),
    },
)
class LedgerKey:
    type: LedgerEntryType
    value: object = None

    def __hash__(self):
        return hash(self.to_xdr())


class BucketEntryType(enum.IntEnum):
    LIVEENTRY = 0
    DEADENTRY = 1


@xunion(
    xenum(BucketEntryType),
    {
        BucketEntryType.LIVEENTRY: ("liveEntry", LedgerEntry._codec),
        BucketEntryType.DEADENTRY: ("deadEntry", LedgerKey._codec),
    },
)
class BucketEntry:
    type: BucketEntryType
    value: object = None


@xstruct
class TransactionSet:
    previousLedgerHash: bytes = xf(HASH, b"\x00" * 32)
    txs: List[TransactionEnvelope] = xf(
        var_array(TransactionEnvelope._codec, MAX_TX_PER_LEDGER), factory=list
    )


@xstruct
class TransactionResultPair:
    transactionHash: bytes = xf(HASH, b"\x00" * 32)
    result: TransactionResult = xf(TransactionResult._codec, factory=TransactionResult)


@xstruct
class TransactionResultSet:
    results: List[TransactionResultPair] = xf(
        var_array(TransactionResultPair._codec, MAX_TX_PER_LEDGER), factory=list
    )


@xstruct
class TransactionHistoryEntry:
    ledgerSeq: int = xf(uint32, 0)
    txSet: TransactionSet = xf(TransactionSet._codec, factory=TransactionSet)
    ext: int = xf(EXT0, 0)


@xstruct
class TransactionHistoryResultEntry:
    ledgerSeq: int = xf(uint32, 0)
    txResultSet: TransactionResultSet = xf(
        TransactionResultSet._codec, factory=TransactionResultSet
    )
    ext: int = xf(EXT0, 0)


@xstruct
class LedgerHeaderHistoryEntry:
    hash: bytes = xf(HASH, b"\x00" * 32)
    header: LedgerHeader = xf(LedgerHeader._codec, factory=LedgerHeader)
    ext: int = xf(EXT0, 0)


class LedgerEntryChangeType(enum.IntEnum):
    LEDGER_ENTRY_CREATED = 0
    LEDGER_ENTRY_UPDATED = 1
    LEDGER_ENTRY_REMOVED = 2


@xunion(
    xenum(LedgerEntryChangeType),
    {
        LedgerEntryChangeType.LEDGER_ENTRY_CREATED: ("created", LedgerEntry._codec),
        LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: ("updated", LedgerEntry._codec),
        LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: ("removed", LedgerKey._codec),
    },
)
class LedgerEntryChange:
    type: LedgerEntryChangeType
    value: object = None


LEDGER_ENTRY_CHANGES = var_array(LedgerEntryChange._codec)


@xstruct
class OperationMeta:
    changes: List[LedgerEntryChange] = xf(LEDGER_ENTRY_CHANGES, factory=list)


@xunion(
    # `union TransactionMeta switch (int v) { case 0: OperationMeta operations<>; }`
    # — discriminant is a plain int, not an enum.
    int32,
    {0: ("operations", var_array(OperationMeta._codec))},
)
class TransactionMeta:
    type: int
    value: object = None
