"""Declarative XDR (RFC 4506) runtime.

This is the TPU-native framework's replacement for the reference's xdrpp +
``xdrc`` code generator (reference: lib/xdrpp, src/Makefile.am:15-19): instead
of generating C++ from ``.x`` files, protocol types are declared once in Python
(see siblings ``xtypes.py``, ``scp.py``, ``entries.py``, ``txs.py``,
``ledger.py``, ``overlay.py``) and this module derives byte-exact
pack/unpack — ``xdr_to_opaque`` here must produce the identical octet stream
xdrpp's ``xdr_to_opaque`` produces, because every hash in the system
(tx contents hash, txset hash, bucket hashes, ledger header hash) is a SHA-256
over these bytes.
"""

from __future__ import annotations

import dataclasses
import enum
import struct
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

__all__ = [
    "XdrError",
    "XdrCodec",
    "uint32",
    "int32",
    "uint64",
    "int64",
    "xbool",
    "opaque",
    "var_opaque",
    "string",
    "array",
    "var_array",
    "option",
    "xenum",
    "xstruct",
    "xunion",
    "xf",
    "codec_of",
    "pack",
    "pack_many",
    "unpack",
    "xdr_copy",
    "xdr_copy_calls",
    "xdr_to_opaque",
    "xdr_getfield",
    "xdr_setfield",
]


class XdrError(Exception):
    """Malformed or out-of-bounds XDR data."""


_U32 = struct.Struct(">I")
_I32 = struct.Struct(">i")
_U64 = struct.Struct(">Q")
_I64 = struct.Struct(">q")


class XdrCodec:
    """Base codec: packs values into a bytearray, unpacks from a buffer."""

    # True when this codec's Python values are immutable (or declared
    # value-semantics), so xdr_copy may share them instead of rebuilding.
    immutable = False

    # C fast path: None = not compiled yet, False = unsupported/unavailable,
    # else a cxdrpack program capsule (see _compile_cprog)
    _cprog = None

    def pack_into(self, val: Any, out: bytearray) -> None:
        raise NotImplementedError

    def unpack_from(self, buf: bytes, off: int) -> Tuple[Any, int]:
        raise NotImplementedError

    def copy(self, val: Any) -> Any:
        """Structural deep copy without serializing.  Scalar/bytes codecs
        return the (immutable) value; containers rebuild.  The ledger
        apply path copies entries/headers per nested delta — an XDR
        round-trip per copy was ~25% of ledger-close time."""
        return val  # immutable leaf by default

    def _compile_cprog(self):
        mod = _cxdr()
        if mod is None:
            self._cprog = False
            return False
        try:
            defs: List[Any] = []
            root = _cspec_of(self, defs, {})
            prog = mod.compile(defs, root, XdrError)
        except _CUnsupported:
            prog = False
        except ValueError as e:
            # mod.compile's own limits (e.g. >MAX_DEPTH_SLOTS depth guards)
            # — degrade to the Python path and latch _cprog=False so we
            # don't re-raise on every call.  ValueError also covers
            # malformed specs (a _cspec_of bug), so the fallback must be
            # loud: the C fast path silently turning off would surface
            # only as an unexplained perf regression.
            import logging

            logging.getLogger("stellar_tpu.xdr").warning(
                "C codec compile failed for %s (%s); using Python path",
                type(self).__name__, e,
            )
            prog = False
        self._cprog = prog
        return prog

    def pack(self, val: Any) -> bytes:
        prog = self._cprog
        if prog is None:
            prog = self._compile_cprog()
        if prog is not False:
            return _cxdr().pack(prog, val)
        out = bytearray()
        self.pack_into(val, out)
        return bytes(out)

    def unpack(self, data: bytes) -> Any:
        prog = self._cprog
        if prog is None:
            prog = self._compile_cprog()
        if prog is not False:
            return _cxdr().unpack(prog, data)
        val, off = self.unpack_from(data, 0)
        if off != len(data):
            raise XdrError(f"trailing bytes: consumed {off} of {len(data)}")
        return val


class _UInt32(XdrCodec):
    immutable = True
    def pack_into(self, val, out):
        if not 0 <= val <= 0xFFFFFFFF:
            raise XdrError(f"uint32 out of range: {val}")
        out += _U32.pack(val)

    def unpack_from(self, buf, off):
        if off + 4 > len(buf):
            raise XdrError("short buffer for uint32")
        return _U32.unpack_from(buf, off)[0], off + 4


class _Int32(XdrCodec):
    immutable = True
    def pack_into(self, val, out):
        if not -0x80000000 <= val <= 0x7FFFFFFF:
            raise XdrError(f"int32 out of range: {val}")
        out += _I32.pack(val)

    def unpack_from(self, buf, off):
        if off + 4 > len(buf):
            raise XdrError("short buffer for int32")
        return _I32.unpack_from(buf, off)[0], off + 4


class _UInt64(XdrCodec):
    immutable = True
    def pack_into(self, val, out):
        if not 0 <= val <= 0xFFFFFFFFFFFFFFFF:
            raise XdrError(f"uint64 out of range: {val}")
        out += _U64.pack(val)

    def unpack_from(self, buf, off):
        if off + 8 > len(buf):
            raise XdrError("short buffer for uint64")
        return _U64.unpack_from(buf, off)[0], off + 8


class _Int64(XdrCodec):
    immutable = True
    def pack_into(self, val, out):
        if not -0x8000000000000000 <= val <= 0x7FFFFFFFFFFFFFFF:
            raise XdrError(f"int64 out of range: {val}")
        out += _I64.pack(val)

    def unpack_from(self, buf, off):
        if off + 8 > len(buf):
            raise XdrError("short buffer for int64")
        return _I64.unpack_from(buf, off)[0], off + 8


class _Bool(XdrCodec):
    immutable = True
    def pack_into(self, val, out):
        out += _U32.pack(1 if val else 0)

    def unpack_from(self, buf, off):
        v, off = uint32.unpack_from(buf, off)
        if v not in (0, 1):
            raise XdrError(f"bad bool discriminant {v}")
        return bool(v), off


uint32 = _UInt32()
int32 = _Int32()
uint64 = _UInt64()
int64 = _Int64()
xbool = _Bool()


def _pad(n: int) -> int:
    return (4 - n % 4) % 4


class _Opaque(XdrCodec):
    """Fixed-length opaque[n]."""

    immutable = True

    def __init__(self, n: int):
        self.n = n

    def pack_into(self, val, out):
        if len(val) != self.n:
            raise XdrError(f"opaque[{self.n}] got {len(val)} bytes")
        out += val
        out += b"\x00" * _pad(self.n)

    def unpack_from(self, buf, off):
        end = off + self.n
        pend = end + _pad(self.n)
        if pend > len(buf):
            raise XdrError(f"short buffer for opaque[{self.n}]")
        if any(buf[end:pend]):
            raise XdrError("nonzero padding")
        return bytes(buf[off:end]), pend


class _VarOpaque(XdrCodec):
    """Variable-length opaque<max>."""

    immutable = True

    def __init__(self, maxlen: Optional[int] = None):
        self.maxlen = maxlen if maxlen is not None else 0xFFFFFFFF

    def pack_into(self, val, out):
        if len(val) > self.maxlen:
            raise XdrError(f"opaque<{self.maxlen}> got {len(val)} bytes")
        out += _U32.pack(len(val))
        out += val
        out += b"\x00" * _pad(len(val))

    def unpack_from(self, buf, off):
        n, off = uint32.unpack_from(buf, off)
        if n > self.maxlen:
            raise XdrError(f"opaque<{self.maxlen}> length {n}")
        end = off + n
        pend = end + _pad(n)
        if pend > len(buf):
            raise XdrError("short buffer for var opaque")
        if any(buf[end:pend]):
            raise XdrError("nonzero padding")
        return bytes(buf[off:end]), pend


class _String(_VarOpaque):
    """string<max>; values are ``str``, encoded as the raw bytes on the wire.

    XDR strings are byte strings; we keep them as ``str`` (utf-8/ascii) at the
    Python level and enforce the byte-length bound like xdrpp does.
    """

    def pack_into(self, val, out):
        _VarOpaque.pack_into(self, val.encode("utf-8"), out)

    def unpack_from(self, buf, off):
        raw, off = _VarOpaque.unpack_from(self, buf, off)
        try:
            return raw.decode("utf-8"), off
        except UnicodeDecodeError as e:
            raise XdrError(f"invalid string bytes: {e}") from e


class _Array(XdrCodec):
    """Fixed-length array T[n]."""

    def __init__(self, elem: XdrCodec, n: int):
        self.elem = elem
        self.n = n

    def pack_into(self, val, out):
        if len(val) != self.n:
            raise XdrError(f"array[{self.n}] got {len(val)} elements")
        for v in val:
            self.elem.pack_into(v, out)

    def unpack_from(self, buf, off):
        vals = []
        for _ in range(self.n):
            v, off = self.elem.unpack_from(buf, off)
            vals.append(v)
        return vals, off

    def copy(self, val):
        if self.elem.immutable:
            return list(val)
        return [self.elem.copy(v) for v in val]


class _VarArray(XdrCodec):
    """Variable-length array T<max>."""

    def __init__(self, elem: XdrCodec, maxlen: Optional[int] = None):
        self.elem = elem
        self.maxlen = maxlen if maxlen is not None else 0xFFFFFFFF

    def pack_into(self, val, out):
        if len(val) > self.maxlen:
            raise XdrError(f"array<{self.maxlen}> got {len(val)} elements")
        out += _U32.pack(len(val))
        for v in val:
            self.elem.pack_into(v, out)

    def unpack_from(self, buf, off):
        n, off = uint32.unpack_from(buf, off)
        if n > self.maxlen:
            raise XdrError(f"array<{self.maxlen}> length {n}")
        vals = []
        for _ in range(n):
            v, off = self.elem.unpack_from(buf, off)
            vals.append(v)
        return vals, off

    def copy(self, val):
        if self.elem.immutable:
            return list(val)
        return [self.elem.copy(v) for v in val]


class _Option(XdrCodec):
    """Optional data (T*): bool-prefixed."""

    def __init__(self, elem: XdrCodec):
        self.elem = elem
        self.immutable = elem.immutable

    def pack_into(self, val, out):
        if val is None:
            out += _U32.pack(0)
        else:
            out += _U32.pack(1)
            self.elem.pack_into(val, out)

    def unpack_from(self, buf, off):
        present, off = xbool.unpack_from(buf, off)
        if not present:
            return None, off
        return self.elem.unpack_from(buf, off)

    def copy(self, val):
        return None if val is None else self.elem.copy(val)


class _Enum(XdrCodec):
    immutable = True
    def __init__(self, enum_cls):
        self.enum_cls = enum_cls

    def pack_into(self, val, out):
        try:
            val = self.enum_cls(val)
        except ValueError as e:
            raise XdrError(
                f"bad {self.enum_cls.__name__} value {val!r}"
            ) from e
        out += _I32.pack(int(val))

    def unpack_from(self, buf, off):
        v, off = int32.unpack_from(buf, off)
        try:
            return self.enum_cls(v), off
        except ValueError as e:
            raise XdrError(f"bad {self.enum_cls.__name__} value {v}") from e


def opaque(n: int) -> XdrCodec:
    return _Opaque(n)


def var_opaque(maxlen: Optional[int] = None) -> XdrCodec:
    return _VarOpaque(maxlen)


def string(maxlen: Optional[int] = None) -> XdrCodec:
    return _String(maxlen)


def array(elem: XdrCodec, n: int) -> XdrCodec:
    return _Array(elem, n)


def var_array(elem: XdrCodec, maxlen: Optional[int] = None) -> XdrCodec:
    return _VarArray(elem, maxlen)


def option(elem: XdrCodec) -> XdrCodec:
    return _Option(elem)


_ENUM_CODECS: Dict[type, _Enum] = {}


def xenum(enum_cls):
    """Register an IntEnum as an XDR enum; returns its codec."""
    codec = _ENUM_CODECS.get(enum_cls)
    if codec is None:
        codec = _Enum(enum_cls)
        _ENUM_CODECS[enum_cls] = codec
    return codec


def xf(codec: XdrCodec, default: Any = dataclasses.MISSING, factory: Any = None):
    """Declare a dataclass field carrying its XDR codec in metadata.

    Fields with no explicit default get ``None`` so positional/keyword
    construction stays flexible; packing a ``None`` required field raises.
    """
    kw: Dict[str, Any] = {"metadata": {"xdr": codec}}
    if factory is not None:
        kw["default_factory"] = factory
    elif default is not dataclasses.MISSING:
        kw["default"] = default
    else:
        kw["default"] = None
    return dataclasses.field(**kw)


def _fixed_leaf(codec):
    """(struct-format, byte-check-n, enum-cls) for codecs packable inside a
    single struct.Struct run, else None.  Opaque[n%4==0] needs an explicit
    length check ('Ns' silently pads short values); enums pack their int
    value and keep decode-side validation."""
    if isinstance(codec, _UInt32):
        return ("I", None, None)
    if isinstance(codec, _Int32):
        return ("i", None, None)
    if isinstance(codec, _UInt64):
        return ("Q", None, None)
    if isinstance(codec, _Int64):
        return ("q", None, None)
    if isinstance(codec, _Opaque) and codec.n % 4 == 0:
        return (f"{codec.n}s", codec.n, None)
    if isinstance(codec, _Enum):
        return ("i", None, codec.enum_cls)
    return None


class _StructCodec(XdrCodec):
    """Derived struct codec with a fast path: maximal runs of fixed-size
    leaf fields (ints, fixed opaque, enums) pack/unpack through one
    precompiled struct.Struct instead of per-field codec dispatch — the
    generic loop was the top ledger-close cost after the copy fixes."""

    def __init__(self, cls, fields: List[Tuple[str, XdrCodec]]):
        self.cls = cls
        self.fields = fields
        # plan items: ("run", Struct, names, checks, enums) | ("one", name, codec)
        plan = []
        fmt, names, checks, enums = "", [], [], []

        def flush():
            nonlocal fmt, names, checks, enums
            if names:
                plan.append(
                    ("run", struct.Struct(">" + fmt), tuple(names),
                     tuple(checks), tuple(enums))
                )
                fmt, names, checks, enums = "", [], [], []

        for name, codec in fields:
            leaf = _fixed_leaf(codec)
            if leaf is None:
                flush()
                plan.append(("one", name, codec))
            else:
                f, n, ecls = leaf
                fmt += f
                names.append(name)
                checks.append((name, n) if n is not None else None)
                enums.append(ecls)
        flush()
        self._plan = plan
        # copy plan: skip codec dispatch for immutable-valued fields; a
        # whole struct declaring XDR_VALUE_SEMANTICS (all-immutable fields,
        # instances never mutated in place — e.g. PublicKey) is shared
        self._copy_plan = tuple((n, c, c.immutable) for n, c in fields)
        self.immutable = bool(
            getattr(cls, "XDR_VALUE_SEMANTICS", False)
        ) and all(imm for _, _, imm in self._copy_plan)

    def pack_into(self, val, out):
        for item in self._plan:
            if item[0] == "run":
                _, st, names, checks, enums = item
                for chk in checks:
                    if chk is not None:
                        v = getattr(val, chk[0])
                        if not isinstance(v, (bytes, bytearray)) or len(
                            v
                        ) != chk[1]:
                            raise XdrError(
                                f"{self.cls.__name__}.{chk[0]}: opaque"
                                f"[{chk[1]}] needs {chk[1]} bytes, got "
                                f"{v!r:.32}"
                            )
                vals = []
                for n, ecls in zip(names, enums):
                    v = getattr(val, n)
                    if ecls is not None and (
                        v not in ecls._value2member_map_
                    ):
                        # keep _Enum.pack_into's fail-fast contract: a bad
                        # enum int must never silently reach the wire/hash
                        raise XdrError(
                            f"bad {ecls.__name__} value {v!r}"
                        )
                    vals.append(v)
                try:
                    out += st.pack(*vals)
                except (struct.error, TypeError, ValueError) as e:
                    raise XdrError(
                        f"packing {self.cls.__name__}: {e}"
                    ) from e
            else:
                _, name, codec = item
                try:
                    codec.pack_into(getattr(val, name), out)
                except XdrError:
                    raise
                except Exception as e:
                    raise XdrError(
                        f"packing {self.cls.__name__}.{name}: {e}"
                    ) from e

    def unpack_from(self, buf, off):
        kw = {}
        for item in self._plan:
            if item[0] == "run":
                _, st, names, _, enums = item
                if off + st.size > len(buf):
                    raise XdrError(
                        f"short buffer for {self.cls.__name__}"
                    )
                vals = st.unpack_from(buf, off)
                off += st.size
                for name, v, ecls in zip(names, vals, enums):
                    if ecls is not None:
                        m = ecls._value2member_map_.get(v)
                        if m is None:
                            raise XdrError(
                                f"bad {ecls.__name__} value {v}"
                            )
                        v = m
                    kw[name] = v
            else:
                _, name, codec = item
                kw[name], off = codec.unpack_from(buf, off)
        return self.cls(**kw), off

    def copy(self, val):
        if self.immutable:
            return val
        return self.cls(
            *[
                getattr(val, n) if imm else c.copy(getattr(val, n))
                for n, c, imm in self._copy_plan
            ]
        )


def xstruct(cls):
    """Decorator: dataclass + XDR codec derived from ``xf`` field metadata.

    Classes declaring ``XDR_VALUE_SEMANTICS = True`` become frozen
    dataclasses: xdr_copy shares their instances, so an accidental in-place
    mutation must fail loudly instead of corrupting shared snapshots."""
    cls = dataclass(cls, frozen=bool(getattr(cls, "XDR_VALUE_SEMANTICS", False)))
    fields = []
    for f in dataclasses.fields(cls):
        codec = f.metadata.get("xdr")
        if codec is None:
            raise TypeError(f"{cls.__name__}.{f.name} lacks xdr metadata")
        fields.append((f.name, codec))
    cls._codec = _StructCodec(cls, fields)
    cls.to_xdr = lambda self: self._codec.pack(self)
    cls.from_xdr = classmethod(lambda c, data: c._codec.unpack(data))
    return cls


class _UnionCodec(XdrCodec):
    def __init__(self, cls, switch_codec, arms, default_void):
        self.cls = cls
        self.switch_codec = switch_codec
        self.arms = arms  # discriminant -> codec | None (void)
        self.default_void = default_void
        # see _StructCodec: XDR_VALUE_SEMANTICS unions (e.g. PublicKey)
        # with immutable arms are shared by xdr_copy
        self.immutable = bool(
            getattr(cls, "XDR_VALUE_SEMANTICS", False)
        ) and all(c is None or c.immutable for c in arms.values())

    def _arm_codec(self, disc):
        try:
            return self.arms[disc]
        except KeyError:
            if self.default_void:
                return None
            raise XdrError(
                f"{self.cls.__name__}: bad discriminant {disc!r}"
            ) from None

    def pack_into(self, val, out):
        try:
            self.switch_codec.pack_into(val.type, out)
        except XdrError:
            raise
        except Exception as e:
            raise XdrError(
                f"{self.cls.__name__}: bad discriminant {val.type!r}: {e}"
            ) from e
        codec = self._arm_codec(val.type)
        if codec is not None:
            codec.pack_into(val.value, out)
        elif val.value is not None:
            raise XdrError(
                f"{self.cls.__name__}: void arm {val.type!r} carries a value"
            )

    def unpack_from(self, buf, off):
        disc, off = self.switch_codec.unpack_from(buf, off)
        codec = self._arm_codec(disc)
        if codec is None:
            return self.cls(disc, None), off
        v, off = codec.unpack_from(buf, off)
        return self.cls(disc, v), off

    def copy(self, val):
        if self.immutable:
            return val
        codec = self._arm_codec(val.type)
        if codec is None:
            return self.cls(val.type, None)
        if codec.immutable:
            return self.cls(val.type, val.value)
        return self.cls(val.type, codec.copy(val.value))


def xunion(switch_codec, arms: Dict[Any, Optional[XdrCodec]], default_void=False):
    """Class decorator for XDR unions.

    The decorated class becomes a dataclass with fields ``type`` and ``value``
    plus one read-only property per named arm.  ``arms`` maps discriminant ->
    (name, codec) for data arms or (name, None)/None for void arms.
    """

    def deco(cls):
        if not dataclasses.is_dataclass(cls):
            cls = dataclass(
                cls, frozen=bool(getattr(cls, "XDR_VALUE_SEMANTICS", False))
            )
        names = {f.name for f in dataclasses.fields(cls)}
        if not {"type", "value"} <= names:
            raise TypeError(f"{cls.__name__} must declare 'type' and 'value' fields")
        norm_arms: Dict[Any, Optional[XdrCodec]] = {}
        for disc, spec in arms.items():
            if spec is None:
                norm_arms[disc] = None
                continue
            name, codec = spec
            norm_arms[disc] = codec
            if name:
                def _mk(d):
                    def get(self):
                        if self.type != d:
                            raise ValueError(
                                f"{cls.__name__} is {self.type!r}, not {d!r}"
                            )
                        return self.value
                    return get
                setattr(cls, name, property(_mk(disc)))
        cls._codec = _UnionCodec(cls, switch_codec, norm_arms, default_void)
        cls.to_xdr = lambda self: self._codec.pack(self)
        cls.from_xdr = classmethod(lambda c, data: c._codec.unpack(data))
        return cls

    return deco


import threading as _threading


class DepthLimited(XdrCodec):
    """Bounds recursion for self-referential types (e.g. SCPQuorumSet), so a
    crafted wire message deepens into XdrError instead of RecursionError.
    Depth is tracked per-thread: decodes on worker threads don't interfere."""

    def __init__(self, inner: Optional[XdrCodec] = None, max_depth: int = 8):
        self.inner = inner
        self.max_depth = max_depth
        self._tls = _threading.local()

    def _enter(self):
        depth = getattr(self._tls, "depth", 0) + 1
        if depth > self.max_depth:
            raise XdrError(f"recursion deeper than {self.max_depth}")
        self._tls.depth = depth

    def _exit(self):
        self._tls.depth -= 1

    def pack_into(self, val, out):
        self._enter()
        try:
            self.inner.pack_into(val, out)
        finally:
            self._exit()

    def copy(self, val):
        self._enter()
        try:
            return self.inner.copy(val)
        finally:
            self._exit()

    def unpack_from(self, buf, off):
        self._enter()
        try:
            return self.inner.unpack_from(buf, off)
        finally:
            self._exit()


def codec_of(obj_or_cls) -> XdrCodec:
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    codec = getattr(cls, "_codec", None)
    if codec is None:
        raise TypeError(f"{cls.__name__} is not an XDR type")
    return codec


def pack(val: Any, codec: Optional[XdrCodec] = None) -> bytes:
    return (codec or codec_of(val)).pack(val)


def pack_many(values, cls_or_codec, frames: bool = False) -> bytes:
    """Concatenated XDR encoding of ``values`` (all one codec) in ONE C
    call when the extension compiled — the batch plane for hot sites that
    serialize whole lists per ledger close (bucket add_batch packs the
    close's live/dead entries through this).  ``frames=True`` prefixes
    every record with the RFC 5531 record mark (length | 0x80000000), the
    XDROutputFileStream framing, so a bucket batch becomes one buffer to
    hash and one write.

    Same octet stream and XdrError failure contract as per-value
    ``pack``: a malformed element raises and nothing is returned (the
    partially-built buffer is discarded — pinned by the hostile cases in
    tests/test_cxdrpack.py).  Hosts without the extension (or with a
    codec the C side does not model) run the equivalent Python loop."""
    codec = (
        cls_or_codec
        if isinstance(cls_or_codec, XdrCodec)
        else codec_of(cls_or_codec)
    )
    vals = values if isinstance(values, (list, tuple)) else list(values)
    prog = codec._cprog
    if prog is None:
        prog = codec._compile_cprog()
    if prog is not False:
        fn = getattr(_cxdr(), "pack_many", None)  # tolerate a stale .so
        if fn is not None:
            return fn(prog, vals, 1 if frames else 0)
    out = bytearray()
    for v in vals:
        body = codec.pack(v)
        if frames:
            if len(body) >= 0x80000000:
                raise XdrError("record too large")
            out += _U32.pack(len(body) | 0x80000000)
        out += body
    return bytes(out)


def unpack(cls, data: bytes) -> Any:
    return codec_of(cls).unpack(data)


def xdr_to_opaque(*items: Any) -> bytes:
    """Concatenated XDR encoding of several values, matching xdrpp's
    variadic ``xdr_to_opaque`` (the form used for hash preimages, e.g.
    TransactionFrame.cpp:60 and HerderImpl.cpp:343).

    Each item is either an instance of an ``xstruct``/``xunion`` class, a
    ``(codec, value)`` tuple, an IntEnum registered with ``xenum``, or raw
    32-byte ``bytes`` (packed as opaque[32] — the Hash/uint256 case).
    """
    out = bytearray()
    for it in items:
        if isinstance(it, tuple) and len(it) == 2 and isinstance(it[0], XdrCodec):
            out += it[0].pack(it[1])  # .pack takes the C path when compiled
        elif isinstance(it, enum.IntEnum):
            xenum(type(it)).pack_into(it, out)
        elif isinstance(it, (bytes, bytearray)):
            if len(it) != 32:
                raise XdrError(
                    "raw bytes in xdr_to_opaque must be 32-byte hashes; "
                    "use (codec, value) otherwise"
                )
            _Opaque(32).pack_into(bytes(it), out)
        else:
            out += codec_of(it).pack(it)
    return bytes(out)


def pack_var_array_of(cls, items) -> bytes:
    """XDR xvector<T> encoding of `items` (count + each element)."""
    out = bytearray()
    var_array(codec_of(cls)).pack_into(list(items), out)
    return bytes(out)


def unpack_var_arrays(data: bytes, classes) -> Tuple[list, ...]:
    """Decode consecutive xvector<T> blocks — the layout xdrpp produces for
    `xdr_to_opaque(vecA, vecB, ...)` (e.g. the persisted SCP state blob,
    HerderImpl.cpp:1482)."""
    offset = 0
    out = []
    for cls in classes:
        lst, offset = var_array(codec_of(cls)).unpack_from(data, offset)
        out.append(lst)
    if offset != len(data):
        raise XdrError("trailing bytes after var arrays")
    return tuple(out)


# process-wide xdr_copy call counter: the copy plane is the ledger close's
# dominant remaining host cost (PROFILE.md r7/r8), so bench.py surfaces
# copies-per-tx on every close line and profile_close.py --copy-report
# attributes them per call site.  A bare int += keeps the hot path cost
# to nanoseconds; readers only ever difference two samples.
_N_COPIES = 0


def xdr_copy_calls() -> int:
    """Total xdr_copy invocations in this process (monotonic; sample
    before/after a workload and difference)."""
    return _N_COPIES


def xdr_copy(obj):
    """Codec-driven structural deep copy of any xstruct/xunion value —
    equivalent to ``from_xdr(to_xdr(obj))`` without the serialization.
    Takes the C fast path (native/cxdrpack.c copy_node — same sharing
    semantics: immutable subtrees shared, containers rebuilt) when the
    codec compiled; the ledger apply path copies entries/headers per
    nested delta, so this is hot at close."""
    global _N_COPIES
    _N_COPIES += 1
    codec = obj._codec
    prog = codec._cprog
    if prog is None:
        prog = codec._compile_cprog()
    if prog is not False:
        return _cxdr().copy(prog, obj)
    return codec.copy(obj)


# -- C pack fast path -------------------------------------------------------
#
# The declarative codec tree compiles to a flat program interpreted by the
# cxdrpack CPython extension (stellar_tpu/native/cxdrpack.c) — same octet
# stream, same XdrError failure contract, ~an order of magnitude less pack
# time (the pack layer was ~1.2 s of a 5000-tx ledger close).  Compilation
# is lazy per codec; anything the C side does not model falls back to the
# pure-Python pack_into path forever (codec._cprog = False).

_cxdr_mod: Any = None
_cxdr_checked = False


def _cxdr():
    global _cxdr_mod, _cxdr_checked
    if not _cxdr_checked:
        _cxdr_checked = True
        try:
            from ..native import load_cxdrpack

            _cxdr_mod = load_cxdrpack()
        except Exception:
            _cxdr_mod = None
    return _cxdr_mod


class _CUnsupported(Exception):
    """Codec shape the C interpreter does not model."""


def _min_wire_size(codec: XdrCodec, _seen: Optional[Set[int]] = None) -> int:
    """Conservative lower bound on the serialized size (bytes) of one value
    of `codec`.  Validates the C unpacker's hostile-count guard at compile
    time (see the _VarArray branch of _cspec_of).  Recursion cycles
    contribute 0, which can only under-estimate — i.e. reject a codec the
    C path could have handled, never accept one it can't."""
    if _seen is None:
        _seen = set()
    if id(codec) in _seen:
        return 0
    _seen.add(id(codec))
    try:
        if isinstance(codec, (_UInt32, _Int32, _Bool, _Enum)):
            return 4
        if isinstance(codec, (_UInt64, _Int64)):
            return 8
        if isinstance(codec, _Opaque):
            return (codec.n + 3) // 4 * 4
        if isinstance(codec, (_String, _VarOpaque, _VarArray, _Option)):
            return 4  # count / discriminant alone
        if isinstance(codec, _Array):
            return codec.n * _min_wire_size(codec.elem, _seen)
        if isinstance(codec, _StructCodec):
            return sum(_min_wire_size(c, _seen) for _, c in codec.fields)
        if isinstance(codec, _UnionCodec):
            arms = [
                0 if c is None else _min_wire_size(c, _seen)
                for c in codec.arms.values()
            ]
            if codec.default_void or not arms:
                arms.append(0)
            return 4 + min(arms)
        if isinstance(codec, DepthLimited):
            return 0 if codec.inner is None else _min_wire_size(codec.inner, _seen)
    finally:
        _seen.discard(id(codec))
    return 0  # unknown codec: conservative


def _cspec_of(codec: XdrCodec, defs: List[Any], memo: Dict[int, int]) -> int:
    """Append the compiled spec of `codec` (and its children) to `defs`,
    returning its slot index.  `memo` closes recursive codec cycles
    (SCPQuorumSet) by reserving the slot before descending."""
    key = id(codec)
    if key in memo:
        return memo[key]
    idx = len(defs)
    memo[key] = idx
    defs.append(None)  # reserved; filled below (recursion-safe)

    if isinstance(codec, _UInt32):
        spec: Any = ("u32",)
    elif isinstance(codec, _Int32):
        spec = ("i32",)
    elif isinstance(codec, _UInt64):
        spec = ("u64",)
    elif isinstance(codec, _Int64):
        spec = ("i64",)
    elif isinstance(codec, _Bool):
        spec = ("bool",)
    elif isinstance(codec, _Enum):
        # one source of truth: the C side derives its validation set from
        # the member map's keys
        spec = ("enum", dict(codec.enum_cls._value2member_map_))
    elif isinstance(codec, _Opaque):
        spec = ("opaque", codec.n)
    elif isinstance(codec, _String):  # before _VarOpaque: subclass
        spec = ("string", codec.maxlen)
    elif isinstance(codec, _VarOpaque):
        spec = ("varopaque", codec.maxlen)
    elif isinstance(codec, _Array):
        spec = ("array", codec.n, _cspec_of(codec.elem, defs, memo))
    elif isinstance(codec, _VarArray):
        if _min_wire_size(codec.elem) < 4:
            # the C unpacker's hostile-count guard (cxdrpack.c
            # rd_check_count: n > remaining/4) assumes every element
            # occupies >= 4 wire bytes; a zero/short-sized element
            # (fieldless struct, opaque[0], array[T,0]) would make it
            # reject streams the Python decoder accepts — keep such
            # codecs on the Python path
            raise _CUnsupported("vararray element min wire size < 4")
        spec = ("vararray", codec.maxlen, _cspec_of(codec.elem, defs, memo))
    elif isinstance(codec, _Option):
        spec = ("option", _cspec_of(codec.elem, defs, memo))
    elif isinstance(codec, _StructCodec):
        names = tuple(n for n, _ in codec.fields)
        kids = tuple(_cspec_of(c, defs, memo) for _, c in codec.fields)
        spec = ("struct", names, kids, codec.cls, int(codec.immutable))
    elif isinstance(codec, _UnionCodec):
        sw = codec.switch_codec
        if isinstance(sw, _Enum):
            sw_spec: Any = ("enum", dict(sw.enum_cls._value2member_map_))
        elif isinstance(sw, _Int32):
            sw_spec = ("i32",)
        elif isinstance(sw, _UInt32):
            sw_spec = ("u32",)
        else:
            raise _CUnsupported(f"union switch {type(sw).__name__}")
        arms = {
            int(disc): (-1 if c is None else _cspec_of(c, defs, memo))
            for disc, c in codec.arms.items()
        }
        spec = (
            "union", sw_spec, arms, int(codec.default_void), codec.cls,
            int(codec.immutable),
        )
    elif isinstance(codec, DepthLimited):
        if codec.inner is None:
            raise _CUnsupported("DepthLimited with unbound inner")
        spec = (
            "depth",
            codec.max_depth,
            _cspec_of(codec.inner, defs, memo),
        )
    else:
        raise _CUnsupported(type(codec).__name__)
    defs[idx] = spec
    return idx


# -- hot-field accessors (C getfield/setfield over raw XDR bytes) -----------
#
# Read or patch ONE scalar field of a packed value without a full unpack:
# the C interpreter (native/cxdrpack.c getfield/setfield) walks the same
# compiled spec the pack/copy/unpack fast paths use, skipping everything
# off the field path.  Shaped like the other interpreters: same program
# capsule, same XdrError failure contract, pinned by the fuzzed
# differential suite (tests/test_cxdrpack.py).  Paths are resolved ONCE
# per (codec, path) against the declarative codec tree — struct fields by
# name, union arms by discriminant (mismatch on the wire raises), array
# elements by index; option/DepthLimited wrappers are transparent, and an
# absent option on the path reads as None.  Hosts without the C toolchain
# fall back to unpack + attribute walk (+ repack for setfield) — slower,
# same results.

_FIELD_PATH_MEMO: Dict[Tuple[int, tuple], tuple] = {}


def _normalize_field_path(path) -> tuple:
    if isinstance(path, str):
        parts: tuple = tuple(path.split("."))
    elif isinstance(path, (tuple, list)):
        parts = tuple(path)
    else:
        parts = (path,)
    out = []
    for p in parts:
        if isinstance(p, str) and p.lstrip("-").isdigit():
            p = int(p)
        out.append(p)
    return tuple(out)


def _resolve_field_path(codec: XdrCodec, path: tuple):
    """(C step ints, terminal codec) for `path` rooted at `codec`."""
    steps = []
    cur = codec
    for elt in path:
        while isinstance(cur, (DepthLimited, _Option)):
            cur = cur.inner if isinstance(cur, DepthLimited) else cur.elem
        if isinstance(cur, _StructCodec):
            if not isinstance(elt, str):
                raise TypeError(
                    f"struct step must be a field name, got {elt!r}"
                )
            for i, (n, c) in enumerate(cur.fields):
                if n == elt:
                    steps.append(i)
                    cur = c
                    break
            else:
                raise KeyError(
                    f"{cur.cls.__name__} has no field {elt!r}"
                )
        elif isinstance(cur, _UnionCodec):
            if isinstance(elt, str):
                raise TypeError(
                    f"union step must be a discriminant, got {elt!r}"
                )
            disc = int(elt)
            arm = _MISSING_ARM
            for d, c in cur.arms.items():
                if int(d) == disc:
                    arm = c
                    break
            if arm is _MISSING_ARM or arm is None:
                raise KeyError(
                    f"{cur.cls.__name__}: no data arm for discriminant"
                    f" {disc}"
                )
            steps.append(disc)
            cur = arm
        elif isinstance(cur, (_Array, _VarArray)):
            steps.append(int(elt))
            cur = cur.elem
        else:
            raise TypeError(
                f"field path descends into a scalar at {elt!r}"
            )
    return tuple(steps), cur


_MISSING_ARM = object()


def _field_path_of(codec: XdrCodec, path) -> tuple:
    """(C steps, normalized path, terminal-is-union) for `path`.  A path
    may TERMINATE at a union: it then addresses the DISCRIMINANT (read as
    a plain int, never settable) — the hot statement-type accessor shape
    (``xdr_getfield(SCPEnvelope, raw, ("statement", "pledges"))``)."""
    norm = _normalize_field_path(path)
    key = (id(codec), norm)
    hit = _FIELD_PATH_MEMO.get(key)
    if hit is None:
        steps, terminal = _resolve_field_path(codec, norm)
        while isinstance(terminal, (DepthLimited, _Option)):
            terminal = (
                terminal.inner
                if isinstance(terminal, DepthLimited)
                else terminal.elem
            )
        hit = (steps, norm, isinstance(terminal, _UnionCodec))
        _FIELD_PATH_MEMO[key] = hit
    return hit


def _py_walk(obj, norm: tuple):
    """Python-fallback (and oracle) walk over a DECODED value."""
    for elt in norm:
        if obj is None:
            return None  # absent option on the path
        if isinstance(elt, str):
            obj = getattr(obj, elt)
        elif hasattr(obj, "type") and hasattr(obj, "value") and not isinstance(
            obj, (list, bytes)
        ):
            if int(obj.type) != int(elt):
                raise XdrError(
                    f"union arm mismatch: value carries {int(obj.type)},"
                    f" path expects {int(elt)}"
                )
            obj = obj.value
        else:
            try:
                obj = obj[int(elt)]
            except IndexError:
                raise XdrError(
                    f"array index {int(elt)} out of range"
                ) from None
    return obj


def _cprog_for(codec: XdrCodec):
    prog = codec._cprog
    if prog is None:
        prog = codec._compile_cprog()
    return prog


def xdr_getfield(cls_or_codec, data: bytes, path):
    """The scalar at `path` inside the packed value `data` — without a
    full unpack when the C interpreter is available.  `path` is a dotted
    string or tuple: struct fields by name, union arms by discriminant
    (int/IntEnum), array elements by index.  Absent options read as None.

    NOT a validator: only the bytes on the path are bounds-checked; a
    value that is malformed OFF the path can still answer.  Anything that
    must reject malformed input keeps calling ``unpack``."""
    codec = cls_or_codec if isinstance(cls_or_codec, XdrCodec) else codec_of(
        cls_or_codec
    )
    steps, norm, union_terminal = _field_path_of(codec, path)
    prog = _cprog_for(codec)
    if prog is not False:
        return _cxdr().getfield(prog, data, steps)
    obj = _py_walk(codec.unpack(data), norm)
    if union_terminal:
        # parity with the C walker: a terminal union reads as its
        # discriminant (plain int), None behind an absent option
        return None if obj is None else int(obj.type)
    return obj


def xdr_setfield(cls_or_codec, data: bytes, path, value) -> bytes:
    """New bytes with the FIXED-WIDTH scalar at `path` patched in place
    (ints, bools, enums, opaque[n]) — no unpack/repack round trip on the
    C path.  Raises XdrError for variable-width terminals, out-of-range
    values, union-arm mismatches, or truncated buffers."""
    codec = cls_or_codec if isinstance(cls_or_codec, XdrCodec) else codec_of(
        cls_or_codec
    )
    steps, norm, union_terminal = _field_path_of(codec, path)
    if union_terminal:
        # patching a discriminant would change which arm follows (and
        # usually the value's length) — not a fixed-width scalar patch
        raise XdrError("cannot set a union discriminant")
    prog = _cprog_for(codec)
    if prog is not False:
        return _cxdr().setfield(prog, data, steps, value)
    # fallback: decode, set, re-encode (same octets, slower)
    obj = codec.unpack(data)
    if len(norm) == 0:
        raise XdrError("empty field path")
    parent = _py_walk(obj, norm[:-1])
    if parent is None:
        raise XdrError("cannot set a field behind an absent option")
    last = norm[-1]
    if isinstance(last, str):
        object.__setattr__(parent, last, value)
    elif isinstance(parent, list):
        parent[int(last)] = value
    else:
        if int(parent.type) != int(last):
            raise XdrError(
                f"union arm mismatch: value carries {int(parent.type)},"
                f" path expects {int(last)}"
            )
        object.__setattr__(parent, "value", value)
    return codec.pack(obj)


def iter_scalar_field_paths(codec: XdrCodec, val):
    """Yield (path, leaf_codec, value) for every scalar leaf reachable in
    the DECODED value `val` — paths in xdr_getfield/xdr_setfield shape
    (struct names, union discriminants, array indices; options and depth
    guards transparent).  Shared by the fuzzer's structured single-field
    mutants and the accessor differential tests, so the one walker stays
    in lockstep with the path grammar it feeds."""
    while isinstance(codec, DepthLimited):
        codec = codec.inner
    if isinstance(codec, _Option):
        if val is None:
            return
        codec = codec.elem
    if isinstance(codec, _StructCodec):
        for name, c in codec.fields:
            for p, leaf, v in iter_scalar_field_paths(c, getattr(val, name)):
                yield (name,) + p, leaf, v
    elif isinstance(codec, _UnionCodec):
        arm = codec.arms.get(val.type)
        if arm is not None:
            for p, leaf, v in iter_scalar_field_paths(arm, val.value):
                yield (int(val.type),) + p, leaf, v
    elif isinstance(codec, (_Array, _VarArray)):
        for i, item in enumerate(val):
            for p, leaf, v in iter_scalar_field_paths(codec.elem, item):
                yield (i,) + p, leaf, v
    else:
        yield (), codec, val
