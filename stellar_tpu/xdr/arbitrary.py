"""Random XDR value generation — the xdrpp/autocheck equivalent
(reference: lib/xdrpp autocheck.h, used by --genfuzz and ItemFetcherTests).

Walks the declarative codec tree (xdr/base.py) and produces a random value
of any registered XDR type.  Sizes are bounded by a ``size`` fuel parameter
so nested var-arrays stay small, like autocheck's generator(10).
"""

from __future__ import annotations

import random
from typing import Any

from .base import (
    DepthLimited,
    XdrCodec,
    _Array,
    _Bool,
    _Enum,
    _Int32,
    _Int64,
    _Opaque,
    _Option,
    _String,
    _StructCodec,
    _UInt32,
    _UInt64,
    _UnionCodec,
    _VarArray,
    _VarOpaque,
)


def arbitrary(codec: XdrCodec, size: int = 10, rng: random.Random = None) -> Any:
    """A random value packable by ``codec``."""
    rng = rng or random.Random()
    return _gen(codec, size, rng)


def arbitrary_of(cls, size: int = 10, rng: random.Random = None) -> Any:
    return arbitrary(cls._codec, size, rng)


def _gen(codec: XdrCodec, size: int, rng: random.Random) -> Any:
    if isinstance(codec, DepthLimited):
        # shrink fast inside self-referential types so generation terminates
        return _gen(codec.inner, max(0, size - 4), rng)
    if isinstance(codec, _Bool):
        return rng.random() < 0.5
    if isinstance(codec, _UInt32):
        return rng.randrange(0, 1 << 32)
    if isinstance(codec, _Int32):
        return rng.randrange(-(1 << 31), 1 << 31)
    if isinstance(codec, _UInt64):
        return rng.randrange(0, 1 << 64)
    if isinstance(codec, _Int64):
        return rng.randrange(-(1 << 63), 1 << 63)
    if isinstance(codec, _String):
        n = rng.randrange(0, min(size, codec.maxlen) + 1)
        return "".join(chr(rng.randrange(32, 127)) for _ in range(n))
    if isinstance(codec, _VarOpaque):
        n = rng.randrange(0, min(size, codec.maxlen) + 1)
        return rng.randbytes(n)
    if isinstance(codec, _Opaque):
        return rng.randbytes(codec.n)
    if isinstance(codec, _Array):
        return [_gen(codec.elem, size // 2, rng) for _ in range(codec.n)]
    if isinstance(codec, _VarArray):
        n = rng.randrange(0, min(size, codec.maxlen) + 1)
        return [_gen(codec.elem, size // 2, rng) for _ in range(n)]
    if isinstance(codec, _Option):
        if rng.random() < 0.5:
            return None
        return _gen(codec.elem, size, rng)
    if isinstance(codec, _Enum):
        return rng.choice(list(codec.enum_cls))
    if isinstance(codec, _StructCodec):
        return codec.cls(
            **{name: _gen(c, size // 2, rng) for name, c in codec.fields}
        )
    if isinstance(codec, _UnionCodec):
        # normalized arms map disc -> codec-or-None(void); stick to known
        # arms unless the union tolerates unknown discriminants
        if not codec.arms or (codec.default_void and rng.random() < 0.1):
            # zero declared arms (e.g. AllowTrustResult: every code is
            # void) or an unknown-tolerant union probing a random value
            disc = _gen(codec.switch_codec, size, rng)
        else:
            disc = rng.choice(list(codec.arms))
        arm = codec.arms.get(disc)
        val = None if arm is None else _gen(arm, size // 2, rng)
        return codec.cls(disc, val)
    raise TypeError(f"no generator for codec {type(codec).__name__}")
