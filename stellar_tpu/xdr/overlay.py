"""Wire types from the reference's src/xdr/Stellar-overlay.x (161 lines)."""

from __future__ import annotations

import enum
from typing import List

from .base import (
    int32,
    opaque,
    string,
    uint32,
    uint64,
    var_array,
    xenum,
    xf,
    xstruct,
    xunion,
)
from .ledger import TransactionSet
from .scp import SCPEnvelope, SCPQuorumSet
from .txs import TransactionEnvelope
from .xtypes import (
    HASH,
    SIGNATURE,
    UINT256,
    Curve25519Public,
    HmacSha256Mac,
    PublicKey,
)


class ErrorCode(enum.IntEnum):
    ERR_MISC = 0
    ERR_DATA = 1
    ERR_CONF = 2
    ERR_AUTH = 3
    ERR_LOAD = 4


@xstruct
class Error:
    code: ErrorCode = xf(xenum(ErrorCode), ErrorCode.ERR_MISC)
    msg: str = xf(string(100), "")


@xstruct
class AuthCert:
    pubkey: Curve25519Public = xf(Curve25519Public._codec)
    expiration: int = xf(uint64, 0)
    sig: bytes = xf(SIGNATURE, b"")


@xstruct
class Hello:
    ledgerVersion: int = xf(uint32, 0)
    overlayVersion: int = xf(uint32, 0)
    networkID: bytes = xf(HASH, b"\x00" * 32)
    versionStr: str = xf(string(100), "")
    listeningPort: int = xf(int32, 0)
    peerID: PublicKey = xf(PublicKey._codec)
    cert: AuthCert = xf(AuthCert._codec)
    nonce: bytes = xf(UINT256, b"\x00" * 32)


@xstruct
class Hello2:
    ledgerVersion: int = xf(uint32, 0)
    overlayVersion: int = xf(uint32, 0)
    overlayMinVersion: int = xf(uint32, 0)
    networkID: bytes = xf(HASH, b"\x00" * 32)
    versionStr: str = xf(string(100), "")
    listeningPort: int = xf(int32, 0)
    peerID: PublicKey = xf(PublicKey._codec)
    cert: AuthCert = xf(AuthCert._codec)
    nonce: bytes = xf(UINT256, b"\x00" * 32)


@xstruct
class Auth:
    unused: int = xf(int32, 0)


class IPAddrType(enum.IntEnum):
    IPv4 = 0
    IPv6 = 1


@xunion(
    xenum(IPAddrType),
    {IPAddrType.IPv4: ("ipv4", opaque(4)), IPAddrType.IPv6: ("ipv6", opaque(16))},
)
class PeerAddressIp:
    type: IPAddrType
    value: object = None


@xstruct
class PeerAddress:
    ip: PeerAddressIp = xf(PeerAddressIp._codec)
    port: int = xf(uint32, 0)
    numFailures: int = xf(uint32, 0)


class MessageType(enum.IntEnum):
    ERROR_MSG = 0
    HELLO = 1
    AUTH = 2
    DONT_HAVE = 3
    GET_PEERS = 4
    PEERS = 5
    GET_TX_SET = 6
    TX_SET = 7
    TRANSACTION = 8
    GET_SCP_QUORUMSET = 9
    SCP_QUORUMSET = 10
    SCP_MESSAGE = 11
    GET_SCP_STATE = 12
    HELLO2 = 13


@xstruct
class DontHave:
    type: MessageType = xf(xenum(MessageType), MessageType.TX_SET)
    reqHash: bytes = xf(UINT256, b"\x00" * 32)


@xunion(
    xenum(MessageType),
    {
        MessageType.ERROR_MSG: ("error", Error._codec),
        MessageType.HELLO: ("hello", Hello._codec),
        MessageType.HELLO2: ("hello2", Hello2._codec),
        MessageType.AUTH: ("auth", Auth._codec),
        MessageType.DONT_HAVE: ("dontHave", DontHave._codec),
        MessageType.GET_PEERS: None,
        MessageType.PEERS: ("peers", var_array(PeerAddress._codec)),
        MessageType.GET_TX_SET: ("txSetHash", UINT256),
        MessageType.TX_SET: ("txSet", TransactionSet._codec),
        MessageType.TRANSACTION: ("transaction", TransactionEnvelope._codec),
        MessageType.GET_SCP_QUORUMSET: ("qSetHash", UINT256),
        MessageType.SCP_QUORUMSET: ("qSet", SCPQuorumSet._codec),
        MessageType.SCP_MESSAGE: ("envelope", SCPEnvelope._codec),
        MessageType.GET_SCP_STATE: ("getSCPLedgerSeq", uint32),
    },
)
class StellarMessage:
    type: MessageType
    value: object = None


@xstruct
class AuthenticatedMessageV0:
    sequence: int = xf(uint64, 0)
    message: StellarMessage = xf(StellarMessage._codec)
    mac: HmacSha256Mac = xf(
        HmacSha256Mac._codec, factory=lambda: HmacSha256Mac(b"\x00" * 32)
    )


@xunion(uint32, {0: ("v0", AuthenticatedMessageV0._codec)})
class AuthenticatedMessage:
    type: int
    value: object = None

    @classmethod
    def v0_of(cls, sequence: int, message: StellarMessage, mac: bytes) -> "AuthenticatedMessage":
        return cls(0, AuthenticatedMessageV0(sequence, message, HmacSha256Mac(mac)))
