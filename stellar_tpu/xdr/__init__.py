"""XDR wire protocol: runtime + the six Stellar-*.x type modules.

Replaces the reference's lib/xdrpp + xdrc codegen (src/Makefile.am:15-19)
with declarative Python; byte-exact with xdrpp's encoding.
"""

from .base import XdrError, pack, unpack, xdr_to_opaque  # noqa: F401
from .xtypes import *  # noqa: F401,F403
from .scp import *  # noqa: F401,F403
from .entries import *  # noqa: F401,F403
from .txs import *  # noqa: F401,F403
from .ledger import *  # noqa: F401,F403
from .overlay import *  # noqa: F401,F403
