"""Wire types from the reference's src/xdr/Stellar-transaction.x (677 lines)."""

from __future__ import annotations

import enum
from typing import List, Optional

from .base import (
    int32,
    int64,
    opaque,
    option,
    string,
    uint32,
    uint64,
    var_array,
    xbool,
    xenum,
    xf,
    xstruct,
    xunion,
)
from .entries import (
    ACCOUNT_ID,
    ASSET,
    EXT0,
    SEQUENCE_NUMBER,
    STRING32,
    Asset,
    AssetType,
    OfferEntry,
    Price,
    PublicKey,
    Signer,
)
from .xtypes import HASH, SIGNATURE, SIGNATURE_HINT


@xstruct
class DecoratedSignature:
    hint: bytes = xf(SIGNATURE_HINT, b"\x00" * 4)  # last 4 bytes of pubkey
    signature: bytes = xf(SIGNATURE, b"")


class OperationType(enum.IntEnum):
    CREATE_ACCOUNT = 0
    PAYMENT = 1
    PATH_PAYMENT = 2
    MANAGE_OFFER = 3
    CREATE_PASSIVE_OFFER = 4
    SET_OPTIONS = 5
    CHANGE_TRUST = 6
    ALLOW_TRUST = 7
    ACCOUNT_MERGE = 8
    INFLATION = 9


@xstruct
class CreateAccountOp:
    destination: PublicKey = xf(ACCOUNT_ID)
    startingBalance: int = xf(int64, 0)


@xstruct
class PaymentOp:
    destination: PublicKey = xf(ACCOUNT_ID)
    asset: Asset = xf(ASSET)
    amount: int = xf(int64, 0)


@xstruct
class PathPaymentOp:
    sendAsset: Asset = xf(ASSET)
    sendMax: int = xf(int64, 0)
    destination: PublicKey = xf(ACCOUNT_ID)
    destAsset: Asset = xf(ASSET)
    destAmount: int = xf(int64, 0)
    path: List[Asset] = xf(var_array(ASSET, 5), factory=list)


@xstruct
class ManageOfferOp:
    selling: Asset = xf(ASSET)
    buying: Asset = xf(ASSET)
    amount: int = xf(int64, 0)  # 0 deletes the offer
    price: Price = xf(Price._codec, factory=Price)
    offerID: int = xf(uint64, 0)  # 0 creates a new offer


@xstruct
class CreatePassiveOfferOp:
    selling: Asset = xf(ASSET)  # A
    buying: Asset = xf(ASSET)  # B
    amount: int = xf(int64, 0)
    price: Price = xf(Price._codec, factory=Price)


@xstruct
class SetOptionsOp:
    inflationDest: Optional[PublicKey] = xf(option(ACCOUNT_ID), None)
    clearFlags: Optional[int] = xf(option(uint32), None)
    setFlags: Optional[int] = xf(option(uint32), None)
    masterWeight: Optional[int] = xf(option(uint32), None)
    lowThreshold: Optional[int] = xf(option(uint32), None)
    medThreshold: Optional[int] = xf(option(uint32), None)
    highThreshold: Optional[int] = xf(option(uint32), None)
    homeDomain: Optional[str] = xf(option(STRING32), None)
    signer: Optional[Signer] = xf(option(Signer._codec), None)


@xstruct
class ChangeTrustOp:
    line: Asset = xf(ASSET)
    limit: int = xf(int64, 0)  # 0 deletes the trust line


@xunion(
    xenum(AssetType),
    {
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("assetCode4", opaque(4)),
        AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("assetCode12", opaque(12)),
    },
)
class AllowTrustAsset:
    type: AssetType
    value: object = None


@xstruct
class AllowTrustOp:
    trustor: PublicKey = xf(ACCOUNT_ID)
    asset: AllowTrustAsset = xf(AllowTrustAsset._codec)
    authorize: bool = xf(xbool, False)


@xunion(
    xenum(OperationType),
    {
        OperationType.CREATE_ACCOUNT: ("createAccountOp", CreateAccountOp._codec),
        OperationType.PAYMENT: ("paymentOp", PaymentOp._codec),
        OperationType.PATH_PAYMENT: ("pathPaymentOp", PathPaymentOp._codec),
        OperationType.MANAGE_OFFER: ("manageOfferOp", ManageOfferOp._codec),
        OperationType.CREATE_PASSIVE_OFFER: (
            "createPassiveOfferOp",
            CreatePassiveOfferOp._codec,
        ),
        OperationType.SET_OPTIONS: ("setOptionsOp", SetOptionsOp._codec),
        OperationType.CHANGE_TRUST: ("changeTrustOp", ChangeTrustOp._codec),
        OperationType.ALLOW_TRUST: ("allowTrustOp", AllowTrustOp._codec),
        OperationType.ACCOUNT_MERGE: ("destination", ACCOUNT_ID),
        OperationType.INFLATION: None,
    },
)
class OperationBody:
    type: OperationType
    value: object = None


@xstruct
class Operation:
    sourceAccount: Optional[PublicKey] = xf(option(ACCOUNT_ID), None)
    body: OperationBody = xf(OperationBody._codec)


class MemoType(enum.IntEnum):
    MEMO_NONE = 0
    MEMO_TEXT = 1
    MEMO_ID = 2
    MEMO_HASH = 3
    MEMO_RETURN = 4


@xunion(
    xenum(MemoType),
    {
        MemoType.MEMO_NONE: None,
        MemoType.MEMO_TEXT: ("text", string(28)),
        MemoType.MEMO_ID: ("id", uint64),
        MemoType.MEMO_HASH: ("hash", HASH),
        MemoType.MEMO_RETURN: ("retHash", HASH),
    },
)
class Memo:
    type: MemoType
    value: object = None

    @classmethod
    def none(cls) -> "Memo":
        return cls(MemoType.MEMO_NONE, None)


@xstruct
class TimeBounds:
    minTime: int = xf(uint64, 0)
    maxTime: int = xf(uint64, 0)


@xstruct
class Transaction:
    sourceAccount: PublicKey = xf(ACCOUNT_ID)
    fee: int = xf(uint32, 0)
    seqNum: int = xf(SEQUENCE_NUMBER, 0)
    timeBounds: Optional[TimeBounds] = xf(option(TimeBounds._codec), None)
    memo: Memo = xf(Memo._codec, factory=Memo.none)
    operations: List[Operation] = xf(var_array(Operation._codec, 100), factory=list)
    ext: int = xf(EXT0, 0)


@xstruct
class TransactionEnvelope:
    tx: Transaction = xf(Transaction._codec)
    signatures: List[DecoratedSignature] = xf(
        var_array(DecoratedSignature._codec, 20), factory=list
    )


# ---------------------------------------------------------------------------
# Operation results
# ---------------------------------------------------------------------------


@xstruct
class ClaimOfferAtom:
    sellerID: PublicKey = xf(ACCOUNT_ID)
    offerID: int = xf(uint64, 0)
    assetSold: Asset = xf(ASSET)
    amountSold: int = xf(int64, 0)
    assetBought: Asset = xf(ASSET)
    amountBought: int = xf(int64, 0)


class CreateAccountResultCode(enum.IntEnum):
    CREATE_ACCOUNT_SUCCESS = 0
    CREATE_ACCOUNT_MALFORMED = -1
    CREATE_ACCOUNT_UNDERFUNDED = -2
    CREATE_ACCOUNT_LOW_RESERVE = -3
    CREATE_ACCOUNT_ALREADY_EXIST = -4


@xunion(xenum(CreateAccountResultCode), {}, default_void=True)
class CreateAccountResult:
    type: CreateAccountResultCode
    value: object = None


class PaymentResultCode(enum.IntEnum):
    PAYMENT_SUCCESS = 0
    PAYMENT_MALFORMED = -1
    PAYMENT_UNDERFUNDED = -2
    PAYMENT_SRC_NO_TRUST = -3
    PAYMENT_SRC_NOT_AUTHORIZED = -4
    PAYMENT_NO_DESTINATION = -5
    PAYMENT_NO_TRUST = -6
    PAYMENT_NOT_AUTHORIZED = -7
    PAYMENT_LINE_FULL = -8
    PAYMENT_NO_ISSUER = -9


@xunion(xenum(PaymentResultCode), {}, default_void=True)
class PaymentResult:
    type: PaymentResultCode
    value: object = None


class PathPaymentResultCode(enum.IntEnum):
    PATH_PAYMENT_SUCCESS = 0
    PATH_PAYMENT_MALFORMED = -1
    PATH_PAYMENT_UNDERFUNDED = -2
    PATH_PAYMENT_SRC_NO_TRUST = -3
    PATH_PAYMENT_SRC_NOT_AUTHORIZED = -4
    PATH_PAYMENT_NO_DESTINATION = -5
    PATH_PAYMENT_NO_TRUST = -6
    PATH_PAYMENT_NOT_AUTHORIZED = -7
    PATH_PAYMENT_LINE_FULL = -8
    PATH_PAYMENT_NO_ISSUER = -9
    PATH_PAYMENT_TOO_FEW_OFFERS = -10
    PATH_PAYMENT_OFFER_CROSS_SELF = -11
    PATH_PAYMENT_OVER_SENDMAX = -12


@xstruct
class SimplePaymentResult:
    destination: PublicKey = xf(ACCOUNT_ID)
    asset: Asset = xf(ASSET)
    amount: int = xf(int64, 0)


@xstruct
class PathPaymentSuccess:
    offers: List[ClaimOfferAtom] = xf(var_array(ClaimOfferAtom._codec), factory=list)
    last: SimplePaymentResult = xf(SimplePaymentResult._codec)


@xunion(
    xenum(PathPaymentResultCode),
    {
        PathPaymentResultCode.PATH_PAYMENT_SUCCESS: (
            "success",
            PathPaymentSuccess._codec,
        ),
        PathPaymentResultCode.PATH_PAYMENT_NO_ISSUER: ("noIssuer", ASSET),
    },
    default_void=True,
)
class PathPaymentResult:
    type: PathPaymentResultCode
    value: object = None


class ManageOfferResultCode(enum.IntEnum):
    MANAGE_OFFER_SUCCESS = 0
    MANAGE_OFFER_MALFORMED = -1
    MANAGE_OFFER_SELL_NO_TRUST = -2
    MANAGE_OFFER_BUY_NO_TRUST = -3
    MANAGE_OFFER_SELL_NOT_AUTHORIZED = -4
    MANAGE_OFFER_BUY_NOT_AUTHORIZED = -5
    MANAGE_OFFER_LINE_FULL = -6
    MANAGE_OFFER_UNDERFUNDED = -7
    MANAGE_OFFER_CROSS_SELF = -8
    MANAGE_OFFER_SELL_NO_ISSUER = -9
    MANAGE_OFFER_BUY_NO_ISSUER = -10
    MANAGE_OFFER_NOT_FOUND = -11
    MANAGE_OFFER_LOW_RESERVE = -12


class ManageOfferEffect(enum.IntEnum):
    MANAGE_OFFER_CREATED = 0
    MANAGE_OFFER_UPDATED = 1
    MANAGE_OFFER_DELETED = 2


@xunion(
    xenum(ManageOfferEffect),
    {
        ManageOfferEffect.MANAGE_OFFER_CREATED: ("created", OfferEntry._codec),
        ManageOfferEffect.MANAGE_OFFER_UPDATED: ("updated", OfferEntry._codec),
    },
    default_void=True,
)
class ManageOfferSuccessResultOffer:
    type: ManageOfferEffect
    value: object = None


@xstruct
class ManageOfferSuccessResult:
    offersClaimed: List[ClaimOfferAtom] = xf(
        var_array(ClaimOfferAtom._codec), factory=list
    )
    offer: ManageOfferSuccessResultOffer = xf(
        ManageOfferSuccessResultOffer._codec,
        factory=lambda: ManageOfferSuccessResultOffer(
            ManageOfferEffect.MANAGE_OFFER_DELETED, None
        ),
    )


@xunion(
    xenum(ManageOfferResultCode),
    {
        ManageOfferResultCode.MANAGE_OFFER_SUCCESS: (
            "success",
            ManageOfferSuccessResult._codec,
        )
    },
    default_void=True,
)
class ManageOfferResult:
    type: ManageOfferResultCode
    value: object = None


class SetOptionsResultCode(enum.IntEnum):
    SET_OPTIONS_SUCCESS = 0
    SET_OPTIONS_LOW_RESERVE = -1
    SET_OPTIONS_TOO_MANY_SIGNERS = -2
    SET_OPTIONS_BAD_FLAGS = -3
    SET_OPTIONS_INVALID_INFLATION = -4
    SET_OPTIONS_CANT_CHANGE = -5
    SET_OPTIONS_UNKNOWN_FLAG = -6
    SET_OPTIONS_THRESHOLD_OUT_OF_RANGE = -7
    SET_OPTIONS_BAD_SIGNER = -8
    SET_OPTIONS_INVALID_HOME_DOMAIN = -9


@xunion(xenum(SetOptionsResultCode), {}, default_void=True)
class SetOptionsResult:
    type: SetOptionsResultCode
    value: object = None


class ChangeTrustResultCode(enum.IntEnum):
    CHANGE_TRUST_SUCCESS = 0
    CHANGE_TRUST_MALFORMED = -1
    CHANGE_TRUST_NO_ISSUER = -2
    CHANGE_TRUST_INVALID_LIMIT = -3
    CHANGE_TRUST_LOW_RESERVE = -4


@xunion(xenum(ChangeTrustResultCode), {}, default_void=True)
class ChangeTrustResult:
    type: ChangeTrustResultCode
    value: object = None


class AllowTrustResultCode(enum.IntEnum):
    ALLOW_TRUST_SUCCESS = 0
    ALLOW_TRUST_MALFORMED = -1
    ALLOW_TRUST_NO_TRUST_LINE = -2
    ALLOW_TRUST_TRUST_NOT_REQUIRED = -3
    ALLOW_TRUST_CANT_REVOKE = -4


@xunion(xenum(AllowTrustResultCode), {}, default_void=True)
class AllowTrustResult:
    type: AllowTrustResultCode
    value: object = None


class AccountMergeResultCode(enum.IntEnum):
    ACCOUNT_MERGE_SUCCESS = 0
    ACCOUNT_MERGE_MALFORMED = -1
    ACCOUNT_MERGE_NO_ACCOUNT = -2
    ACCOUNT_MERGE_IMMUTABLE_SET = -3
    ACCOUNT_MERGE_HAS_SUB_ENTRIES = -4


@xunion(
    xenum(AccountMergeResultCode),
    {AccountMergeResultCode.ACCOUNT_MERGE_SUCCESS: ("sourceAccountBalance", int64)},
    default_void=True,
)
class AccountMergeResult:
    type: AccountMergeResultCode
    value: object = None


class InflationResultCode(enum.IntEnum):
    INFLATION_SUCCESS = 0
    INFLATION_NOT_TIME = -1


@xstruct
class InflationPayout:
    destination: PublicKey = xf(ACCOUNT_ID)
    amount: int = xf(int64, 0)


@xunion(
    xenum(InflationResultCode),
    {
        InflationResultCode.INFLATION_SUCCESS: (
            "payouts",
            var_array(InflationPayout._codec),
        )
    },
    default_void=True,
)
class InflationResult:
    type: InflationResultCode
    value: object = None


class OperationResultCode(enum.IntEnum):
    opINNER = 0
    opBAD_AUTH = -1
    opNO_ACCOUNT = -2


@xunion(
    xenum(OperationType),
    {
        OperationType.CREATE_ACCOUNT: (
            "createAccountResult",
            CreateAccountResult._codec,
        ),
        OperationType.PAYMENT: ("paymentResult", PaymentResult._codec),
        OperationType.PATH_PAYMENT: ("pathPaymentResult", PathPaymentResult._codec),
        OperationType.MANAGE_OFFER: ("manageOfferResult", ManageOfferResult._codec),
        OperationType.CREATE_PASSIVE_OFFER: (
            "createPassiveOfferResult",
            ManageOfferResult._codec,
        ),
        OperationType.SET_OPTIONS: ("setOptionsResult", SetOptionsResult._codec),
        OperationType.CHANGE_TRUST: ("changeTrustResult", ChangeTrustResult._codec),
        OperationType.ALLOW_TRUST: ("allowTrustResult", AllowTrustResult._codec),
        OperationType.ACCOUNT_MERGE: ("accountMergeResult", AccountMergeResult._codec),
        OperationType.INFLATION: ("inflationResult", InflationResult._codec),
    },
)
class OperationResultTr:
    type: OperationType
    value: object = None


@xunion(
    xenum(OperationResultCode),
    {OperationResultCode.opINNER: ("tr", OperationResultTr._codec)},
    default_void=True,
)
class OperationResult:
    type: OperationResultCode
    value: object = None


class TransactionResultCode(enum.IntEnum):
    txSUCCESS = 0
    txFAILED = -1
    txTOO_EARLY = -2
    txTOO_LATE = -3
    txMISSING_OPERATION = -4
    txBAD_SEQ = -5
    txBAD_AUTH = -6
    txINSUFFICIENT_BALANCE = -7
    txNO_ACCOUNT = -8
    txINSUFFICIENT_FEE = -9
    txBAD_AUTH_EXTRA = -10
    txINTERNAL_ERROR = -11


@xunion(
    xenum(TransactionResultCode),
    {
        TransactionResultCode.txSUCCESS: (
            "results",
            var_array(OperationResult._codec),
        ),
        TransactionResultCode.txFAILED: (
            "failedResults",
            var_array(OperationResult._codec),
        ),
    },
    default_void=True,
)
class TransactionResultResult:
    type: TransactionResultCode
    value: object = None


@xstruct
class TransactionResult:
    feeCharged: int = xf(int64, 0)
    result: TransactionResultResult = xf(
        TransactionResultResult._codec,
        factory=lambda: TransactionResultResult(
            TransactionResultCode.txINTERNAL_ERROR, None
        ),
    )
    ext: int = xf(EXT0, 0)
