"""Wire types from the reference's src/xdr/Stellar-types.x (55 lines)."""

from __future__ import annotations

import enum

from .base import (
    opaque,
    uint32,
    var_opaque,
    xenum,
    xf,
    xstruct,
    xunion,
)

HASH = opaque(32)
UINT256 = opaque(32)
SIGNATURE = var_opaque(64)
SIGNATURE_HINT = opaque(4)


class CryptoKeyType(enum.IntEnum):
    KEY_TYPE_ED25519 = 0


@xunion(xenum(CryptoKeyType), {CryptoKeyType.KEY_TYPE_ED25519: ("ed25519", UINT256)})
class PublicKey:
    # never mutated in place anywhere in the tree — xdr_copy shares instances
    XDR_VALUE_SEMANTICS = True

    type: CryptoKeyType
    value: bytes = None

    @classmethod
    def from_ed25519(cls, raw: bytes) -> "PublicKey":
        return cls(CryptoKeyType.KEY_TYPE_ED25519, bytes(raw))

    def __hash__(self):
        return hash((int(self.type), self.value))


PUBLIC_KEY = PublicKey._codec
NODE_ID = PUBLIC_KEY  # typedef PublicKey NodeID
NodeID = PublicKey


@xstruct
class Curve25519Secret:
    key: bytes = xf(opaque(32))


@xstruct
class Curve25519Public:
    key: bytes = xf(opaque(32))


@xstruct
class HmacSha256Key:
    key: bytes = xf(opaque(32))


@xstruct
class HmacSha256Mac:
    mac: bytes = xf(opaque(32))
