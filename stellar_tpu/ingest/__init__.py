"""Verify-at-ingest admission plane: the batched tx front door
(micro-batched signature verify under CALLER_INGEST, per-account rate
limits, fee-based surge admission).  See plane.py."""

from .plane import INGEST_STATUS_TRY_AGAIN, IngestPlane  # noqa: F401
