"""Verify-at-ingest admission plane (round 20, ROADMAP #5).

The submission edge — ``/tx`` via the CommandHandler, overlay tx flood
via ``Peer.recv_transaction``, LoadGenerator submits, and catchup txset
replay — used to pay ad-hoc per-tx signature costs inside
``herder.recv_transaction`` with no admission control.  This plane puts
a batched front door in front of the herder's tx queue:

* **Micro-batched verify.**  Submitted and flooded txs accumulate into a
  size/deadline-bounded accumulator on the VirtualClock and ride the
  SAME SigBackend dispatch the close path uses, under their own
  ``CALLER_INGEST`` class (so a wedged ingest dispatch latches only the
  ingest plane onto host — close/prewarm/overlay flushes keep the
  device).  The flush owns the peek/verify/latch split at ingest
  granularity: cached verdicts are peeked first, only misses reach the
  inner backend, and VALID verdicts latch into the shared verify cache —
  the same valid-only quarantine contract as ``CachingSigBackend`` (a
  byzantine flood of distinct invalid-sig txs must not evict honest
  entries from the bounded LRU).  By the time an admitted tx reaches the
  herder's eager ``check_signature`` — and later the close/prewarm
  flush — every one of its signatures is an all-hit by construction.

* **Edge shedding.**  A tx whose hint-matched candidate triples ALL
  verify invalid can never satisfy ``check_signature`` (the candidate
  set covers every (key, sig) pair the eager loop would try), so it is
  shed at the edge — metered ``ingest.reject-badsig`` — before
  ``check_valid``, account loads, or flood fan-out spend anything on it.
  Txs with no candidate triples (unknown source account, no hint match)
  pass through untouched: the herder's validity path stays the oracle,
  which is what keeps INGEST_BATCH on/off ledger-bit-exact.

* **Admission control.**  Per-account token-bucket rate limits
  (``INGEST_RATE_LIMIT``/``INGEST_RATE_BURST``, clocked on the
  VirtualClock) and fee-based surge admission: when the pending backlog
  (herder queue + accumulator) exceeds ``INGEST_SURGE_HIGH_WATER``, the
  lowest fee-per-min-fee tx loses its seat — the same fee ordering
  ``TxSetFrame.surge_pricing_filter`` applies at close, generalized to
  the front door.  Both reject with ``TRY_AGAIN_LATER`` surfaced to
  ``/tx`` (the reference's TX_STATUS for an overloaded queue).

Catchup replay (``Herder.recv_tx_set_txs``) rides ``submit_replay``:
batched verify, but NO rate/surge admission — replayed sets were
already externalized somewhere and must reach the queue.

Determinism: the plane runs entirely on the caller's crank — enqueue,
size-triggered flush, and the VirtualTimer deadline flush are all pure
functions of crank order and clock time, so chaos-scenario replay
digests stay byte-identical (the ``determinism`` analysis rule scopes
``ingest/``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..crypto.keys import verify_cache
from ..crypto.sigbackend import CALLER_INGEST, CachingSigBackend
from ..util import VirtualTimer
from ..xdr.txs import TransactionResultCode

# TX_STATUS vocabulary: the herder owns PENDING/DUPLICATE/ERROR; the
# admission plane adds the reference's overload answer.
INGEST_STATUS_TRY_AGAIN = "TRY_AGAIN_LATER"


class _Entry:
    """One queued submission: the tx plus its decision callback (the
    overlay floods / the HTTP handler answers only once the batch
    verdict lands)."""

    __slots__ = ("tx", "on_status", "status", "fee_ratio", "seq")

    def __init__(self, tx, on_status, fee_ratio, seq):
        self.tx = tx
        self.on_status = on_status
        self.status: Optional[str] = None
        self.fee_ratio = fee_ratio
        self.seq = seq  # arrival index: deterministic surge tie-break


class _TokenBucket:
    __slots__ = ("tokens", "stamp")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.stamp = now


class IngestPlane:
    """Batched admission front door in front of ``Herder.recv_transaction``.

    All four submission edges route through here; with
    ``Config.INGEST_BATCH`` off every call falls through to the herder
    per-tx (bit-exact pre-plane behavior — the differential suite pins
    it)."""

    def __init__(self, app):
        self.app = app
        cfg = app.config
        self.enabled = bool(cfg.INGEST_BATCH)
        self.batch_max = int(cfg.INGEST_BATCH_MAX)
        self.deadline_s = cfg.INGEST_BATCH_DEADLINE_MS / 1000.0
        self.rate_limit = int(cfg.INGEST_RATE_LIMIT)
        self.rate_burst = int(cfg.INGEST_RATE_BURST)
        self.surge_high_water = int(cfg.INGEST_SURGE_HIGH_WATER)

        # the flush owns the peek/verify/latch split (CachingSigBackend
        # would re-hash + re-peek every key on the miss path) — unwrap to
        # the inner backend and the shared cache it latches
        be = app.sig_backend
        if isinstance(be, CachingSigBackend):
            self._inner, self._cache = be.inner, be.cache
        else:
            self._inner, self._cache = be, verify_cache()

        self._queue: List[_Entry] = []
        self._arrivals = 0
        self._buckets: Dict[bytes, _TokenBucket] = {}
        self._timer = VirtualTimer(app.clock)
        self._timer_armed = False
        self._shutting_down = False

        m = app.metrics
        self.m_admit = m.new_meter(("ingest", "tx", "admit"), "tx")
        self.m_passthrough = m.new_meter(("ingest", "tx", "passthrough"), "tx")
        self.m_reject_badsig = m.new_meter(("ingest", "reject", "badsig"), "tx")
        self.m_reject_rate = m.new_meter(("ingest", "reject", "ratelimit"), "tx")
        self.m_reject_surge = m.new_meter(("ingest", "reject", "surge"), "tx")
        self.m_flush = m.new_meter(("ingest", "batch", "flush"), "batch")
        self.h_batch_size = m.new_histogram(("ingest", "batch", "size"))
        self.h_occupancy = m.new_histogram(("ingest", "batch", "occupancy"))
        self.c_cache_hits = m.new_counter(("ingest", "verify", "cache-hits"))
        self.c_verified = m.new_counter(("ingest", "verify", "triples"))

    # ------------------------------------------------------------------
    # submission edges
    # ------------------------------------------------------------------
    def submit(self, tx, on_status: Optional[Callable[[str], None]] = None) -> Optional[str]:
        """Queue one tx (overlay flood edge).  Returns the status when it
        is decided immediately (bypass / rate-limited / surge-rejected /
        size-triggered flush), else None — ``on_status`` fires when the
        batch verdict lands."""
        if not self.enabled or self._shutting_down:
            status = self.app.herder.recv_transaction(tx)
            if on_status is not None:
                on_status(status)
            return status
        entry = self._admit(tx, on_status)
        if entry is None:
            return INGEST_STATUS_TRY_AGAIN
        if len(self._queue) >= self.batch_max:
            self.flush_now()
            return entry.status
        self._arm_deadline()
        return None

    def submit_sync(self, tx) -> str:
        """Queue + flush immediately (the ``/tx`` and LoadGenerator
        edges need a synchronous answer); everything already queued
        rides the same dispatch."""
        if not self.enabled or self._shutting_down:
            return self.app.herder.recv_transaction(tx)
        entry = self._admit(tx, None)
        if entry is None:
            return INGEST_STATUS_TRY_AGAIN
        if entry.status is None:
            self.flush_now()
        return entry.status if entry.status is not None else INGEST_STATUS_TRY_AGAIN

    def submit_replay(self, txs) -> List[str]:
        """Catchup/downloaded-txset edge: batched verify, NO rate/surge
        admission (the set was externalized somewhere; admission control
        on replay would wedge catchup)."""
        if not self.enabled or self._shutting_down:
            return [self.app.herder.recv_transaction(tx) for tx in txs]
        entries = []
        for tx in txs:
            e = _Entry(tx, None, 0.0, self._arrivals)
            self._arrivals += 1
            self._queue.append(e)
            entries.append(e)
            if len(self._queue) >= self.batch_max:
                self.flush_now()
        self.flush_now()
        return [e.status for e in entries]

    # ------------------------------------------------------------------
    # admission control
    # ------------------------------------------------------------------
    def _fee_ratio(self, tx) -> float:
        # surge_pricing_filter's ordering key, generalized to the front
        # door: fee per min-fee unit (≈ fee per operation)
        try:
            min_fee = tx.get_min_fee(self.app.ledger_manager)
        except Exception:
            min_fee = 0
        return tx.get_fee() / float(max(1, min_fee))

    def _admit(self, tx, on_status) -> Optional[_Entry]:
        """Rate-limit + surge gate; returns the queued entry or None
        (rejected — the caller answers TRY_AGAIN_LATER)."""
        if self.rate_limit > 0 and not self._take_token(tx.source_bytes()):
            self.m_reject_rate.mark()
            if on_status is not None:
                on_status(INGEST_STATUS_TRY_AGAIN)
            return None
        entry = _Entry(tx, on_status, self._fee_ratio(tx), self._arrivals)
        self._arrivals += 1
        if self.surge_high_water > 0:
            backlog = self.app.herder.num_pending_txs() + len(self._queue)
            if backlog >= self.surge_high_water and self._queue:
                # lowest fee-ratio loses its seat; ties keep the EARLIER
                # arrival (deterministic: arrival index, never id()/hash)
                victim = min(self._queue, key=lambda e: (e.fee_ratio, -e.seq))
                if victim.fee_ratio < entry.fee_ratio:
                    self._queue.remove(victim)
                    victim.status = INGEST_STATUS_TRY_AGAIN
                    self.m_reject_surge.mark()
                    if victim.on_status is not None:
                        victim.on_status(INGEST_STATUS_TRY_AGAIN)
                else:
                    self.m_reject_surge.mark()
                    if on_status is not None:
                        on_status(INGEST_STATUS_TRY_AGAIN)
                    return None
            elif backlog >= self.surge_high_water:
                self.m_reject_surge.mark()
                if on_status is not None:
                    on_status(INGEST_STATUS_TRY_AGAIN)
                return None
        self._queue.append(entry)
        return entry

    def _take_token(self, acc: bytes) -> bool:
        now = self.app.clock.now()
        b = self._buckets.get(acc)
        if b is None:
            b = _TokenBucket(float(self.rate_burst), now)
            self._buckets[acc] = b
        else:
            b.tokens = min(
                float(self.rate_burst),
                b.tokens + (now - b.stamp) * self.rate_limit,
            )
            b.stamp = now
        if b.tokens >= 1.0:
            b.tokens -= 1.0
            return True
        return False

    # ------------------------------------------------------------------
    # flush
    # ------------------------------------------------------------------
    def _arm_deadline(self) -> None:
        if self._timer_armed or not self._queue:
            return
        self._timer_armed = True
        self._timer.expires_from_now(self.deadline_s)
        self._timer.async_wait(self._on_deadline)

    def _on_deadline(self) -> None:
        self._timer_armed = False
        self.flush_now()

    def flush_now(self) -> None:
        """Drain the accumulator through ONE backend dispatch; decide and
        deliver every queued entry's status."""
        if self._timer_armed:
            self._timer.cancel()
            self._timer_armed = False
        batch, self._queue = self._queue, []
        if not batch:
            return
        self.m_flush.mark()
        self.h_batch_size.update(len(batch))
        self.h_occupancy.update(len(batch) / float(max(1, self.batch_max)))
        sp = self.app.tracer.begin("ingest.flush")

        db = self.app.database
        cache = self._cache
        # per-entry candidate triples; triple-less txs pass through (the
        # herder's eager path stays the validity oracle for them)
        slices = []  # (entry, start, end) into the concatenated triples
        keys: List[bytes] = []
        triples = []
        for e in batch:
            try:
                cand = e.tx.candidate_signature_pairs(db)
            except Exception:
                cand = []
            start = len(triples)
            triples.extend(cand)
            keys.extend(cache.key_for(pk, sig, msg) for pk, msg, sig in cand)
            slices.append((e, start, len(triples)))

        cached = cache.peek_many(keys)
        miss_idx = [i for i, c in enumerate(cached) if c is None]
        self.c_cache_hits.inc(len(keys) - len(miss_idx))
        self.c_verified.inc(len(miss_idx))
        if miss_idx:
            fresh = self._inner.verify_batch(
                [triples[i] for i in miss_idx], caller=CALLER_INGEST
            )
            # valid-only latch — the CachingSigBackend quarantine
            # contract at ingest granularity: a flood of distinct
            # invalid-sig txs must never evict honest cache entries, and
            # re-verifying an invalid triple later is cheap and pure
            cache.put_many(
                (keys[i], ok) for i, ok in zip(miss_idx, fresh) if ok
            )
            for i, ok in zip(miss_idx, fresh):
                cached[i] = ok

        n_shed = 0
        herder = self.app.herder
        for e, start, end in slices:
            if end > start and not any(cached[start:end]):
                # every (key, sig) pair the eager check_signature loop
                # could try verifies invalid — shed at the edge
                e.tx.set_result_code(TransactionResultCode.txBAD_AUTH)
                e.status = "ERROR"
                n_shed += 1
                self.m_reject_badsig.mark()
            else:
                if end == start:
                    self.m_passthrough.mark()
                e.status = herder.recv_transaction(e.tx)
                if e.status == "PENDING":
                    self.m_admit.mark()
            if e.on_status is not None:
                e.on_status(e.status)
        self.app.tracer.end(
            sp, batch=len(batch), triples=len(keys), shed=n_shed
        )

    # ------------------------------------------------------------------
    # lifecycle / introspection
    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        """Drain the accumulator (every queued submitter gets an answer),
        then fall back to per-tx pass-through for any late arrivals."""
        if self._shutting_down:
            return
        self.flush_now()
        self._shutting_down = True
        self._timer.cancel()
        self._timer_armed = False

    def stats(self) -> dict:
        """The ``/ingest`` admin route's payload (and bench's occupancy
        source)."""
        flushes = self.m_flush.count
        return {
            "enabled": self.enabled,
            "queued": len(self._queue),
            "batch_max": self.batch_max,
            "deadline_ms": self.deadline_s * 1000.0,
            "flushes": flushes,
            "batch_size_mean": self.h_batch_size.mean,
            "batch_size_p95": self.h_batch_size.percentile(0.95),
            "occupancy_mean": self.h_occupancy.mean,
            "admitted": self.m_admit.count,
            "passthrough": self.m_passthrough.count,
            "rejects": {
                "badsig": self.m_reject_badsig.count,
                "ratelimit": self.m_reject_rate.count,
                "surge": self.m_reject_surge.count,
            },
            "verify": {
                "cache_hits": self.c_cache_hits.count,
                "triples_verified": self.c_verified.count,
            },
            "rate_limit": {
                "per_account_tx_per_s": self.rate_limit,
                "burst": self.rate_burst,
                "tracked_accounts": len(self._buckets),
            },
            "surge_high_water": self.surge_high_water,
        }
