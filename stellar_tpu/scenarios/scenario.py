"""Scenario — one declarative chaos run: topology × load × fault program
× liveness scoreboard.

The runner composes a multi-node Simulation (core mesh or core-and-tier
ring), streams LoadGenerator traffic through it, arms the fault program on
the shared clock, and cranks until the liveness target (or the timeout)
while tracking recovery from heals/restarts.  Every run:

- runs the invariant plane all-on (get_test_config default) and FAILS on
  any accepted-ledger violation;
- asserts the surviving nodes agree on the chain;
- emits one LivenessScoreboard, with a deterministic digest for
  VIRTUAL_TIME scenarios (same topology + seed + program ⇒ same digest —
  tests/test_scenarios.py pins the replay);
- enforces the spec's liveness floors (ledgers/sec, recovery ms).

Clock modes: chaos scenarios default to VIRTUAL_TIME (deterministic,
seeded).  Catchup-under-load runs REAL_TIME like the history suite — the
archive get/put commands are real subprocesses whose completion the
virtual clock would leap past.
"""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..simulation import LoadGenerator, Simulation, topologies
from ..simulation.simulation import OVER_LOOPBACK, OVER_TCP
from ..tx.testutils import get_test_config
from ..util import REAL_TIME, VIRTUAL_TIME, VirtualClock, VirtualTimer, xlog
from ..xdr.scp import SCPQuorumSet
from .faults import Fault
from .scoreboard import LivenessScoreboard, snapshot

log = xlog.logger("Scenario")

# scenario node instance numbers start high so tmp/bucket dirs never
# collide with the unit suites' get_test_config(0..n) apps
_INSTANCE_BASE = 9100

# slack on the straggler-disconnect window verdict: the stall timer
# fires at the CRITICAL head's deadline on the virtual clock, so the
# recorded stall age sits AT the budget; this absorbs crank granularity
_STALL_POLL_SLACK_MS = 250.0


@dataclass
class ScenarioSpec:
    name: str
    fault_class: str
    faults: List[Fault]
    n_nodes: int = 3
    threshold: Optional[int] = None  # None = BFT majority
    topology: str = "core"  # "core" | "core_and_tier"
    tier_n: int = 0
    # False = tier nodes are WATCHERS (track + relay, never nominate):
    # the committee-plus-relays shape the 100+ node scale scenario runs
    tier_validators: bool = True
    clock_mode: str = "virtual"  # "virtual" | "real"
    # transport: "loopback" (in-process pairs, full fault surface) or
    # "tcp" (real localhost sockets — the 100+ node scale shape, ISSUE
    # r19; link-level fault knobs are loopback-only, node-API faults
    # like floods still apply)
    overlay_mode: str = "loopback"
    seed: int = 1
    # SCP envelope signature scheme for every node (Config.SCP_SIG_SCHEME):
    # "ed25519" or "ed25519-halfagg" — the flood matrix runs the same
    # storm under both and compares scheme verify wall
    scp_sig_scheme: str = "ed25519"
    # signature backend for every node (Config.SIGNATURE_BACKEND): None
    # keeps the test default ("cpu"); "tpu" engages the device batch
    # plane (the tpu-backend flood leg, ISSUE r19 — tier-1 runs it on
    # the XLA-CPU oracle).  tpu_cpu_cutover=0 forces every flush onto
    # the device path so a flood-scale batch can't ride the host ladder.
    signature_backend: Optional[str] = None
    tpu_cpu_cutover: Optional[int] = None
    # load (streams through node `load_target` for the whole run)
    load_accounts: int = 6
    load_txs: int = 400
    load_rate: int = 40
    load_backlog_ledgers: int = 0
    load_target: int = 0
    # per-node DESIRED_MAX_TX_PER_LEDGER override — the backlog shapes
    # need a cap SMALLER than the queued load so consecutive closes each
    # propose a full set (one giant set swallowing the whole load makes
    # the >1-close pipelined-backlog assertion hinge on which single
    # slot the burst lands in).  None keeps the Config default
    max_tx_per_ledger: Optional[int] = None
    # overlay survival plane (overlay/sendqueue.py) — None keeps the
    # Config default on every node; 0 for sendq_bytes turns the plane
    # off (the knob-off transparency leg)
    sendq_bytes: Optional[int] = None
    sendq_flood_msgs: Optional[int] = None
    straggler_stall_ms: Optional[float] = None
    # conflict-partitioned parallel apply (ledger/applysched.py) — None
    # keeps the Config default on every node; True also pins
    # APPLY_WORKERS=4 so the 1-core CI host genuinely shards instead of
    # auto-sizing to a single (serial-short-circuit) worker
    parallel_apply: Optional[bool] = None
    # floors/verdicts for the survival plane: a run must disconnect at
    # least one straggler (slow_reader), must shed at least this many
    # FLOOD frames (overload shapes), and the per-peer queue-byte
    # high-water must stay under the configured cap when set
    expect_straggler_disconnect: bool = False
    min_flood_sheds: int = 0
    assert_high_water_bounded: bool = False
    # time-slip verdicts (ISSUE r19): the run must meter at least /
    # at most this many closeTime-gate rejections (past + future,
    # summed across nodes) — the skew classes' observable
    min_slip_rejects: int = 0
    max_slip_rejects: Optional[int] = None
    # verify-at-ingest admission plane (ISSUE r20): per-node Config
    # overrides for the front door's admission knobs (None keeps the
    # Config defaults), and the flood shape's floor — the run must shed
    # at least this many invalid-sig txs at the ingest edge (metered
    # ingest.reject.badsig, summed across nodes)
    ingest_rate_limit: Optional[int] = None
    ingest_surge_high_water: Optional[int] = None
    min_ingest_sheds: int = 0
    # per-tier scoreboard aggregates: {tier_name: [node indices]} —
    # report-only grouping (targeted faults read "tier-1 undisturbed,
    # tier-2 shed" off it)
    tiers: Optional[Dict[str, List[int]]] = None
    # liveness target + floors
    target_ledgers: int = 12  # absolute min LCL across nodes at the end
    stabilize_ledgers: int = 2
    timeout: float = 300.0
    min_ledgers_per_sec: float = 0.0
    max_recovery_ms: Optional[float] = None
    # node indices EXCLUDED from the liveness target/floor (a deliberate
    # straggler cannot gate the consensus floor it is designed to miss);
    # chain agreement still covers them at the lowest common sequence
    liveness_exclude: List[int] = field(default_factory=list)
    # infrastructure
    disk_db: bool = False  # crash/restart needs on-disk sqlite
    archives: bool = False  # catchup needs a history archive
    checkpoint_frequency: int = 8
    doctor_tick: float = 1.0


@dataclass
class ScenarioResult:
    name: str
    ok: bool
    failures: List[str]
    scoreboard: LivenessScoreboard

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "failures": self.failures,
            "scoreboard": self.scoreboard.to_dict(),
        }


class Scenario:
    def __init__(self, spec: ScenarioSpec, workdir: Optional[str] = None):
        self.spec = spec
        self.workdir = workdir
        self._own_workdir = False
        self.sim: Optional[Simulation] = None
        self.node_keys: List = []
        self.loadgen: Optional[LoadGenerator] = None
        self.done = False
        self._fault_timers: List[VirtualTimer] = []
        self._doctor_timer: Optional[VirtualTimer] = None
        self._armed_at = 0.0
        self._notes: List[str] = []
        # recovery bookkeeping (heals/restarts stamp the start; the crank
        # predicate stamps the end at the first agreed post-event close)
        self._expected_recoveries = 0
        self._recovery_t0: Optional[float] = None
        self._recovery_from_lcl = 0
        self._recoveries: List[float] = []

    # -- fault-program surface ----------------------------------------------
    def note(self, msg: str) -> None:
        log.info("[%s] %s", self.spec.name, msg)
        self._notes.append(msg)

    def elapsed(self) -> float:
        return self.sim.clock.now() - self._armed_at

    def elapsed_since_arm(self) -> float:
        return self.elapsed()

    def mark_recovery_start(self) -> None:
        self._recovery_t0 = self.sim.clock.now()
        self._recovery_from_lcl = max(
            (
                app.ledger_manager.get_last_closed_ledger_num()
                for app in self.sim.nodes.values()
            ),
            default=0,
        )

    # -- build ---------------------------------------------------------------
    def _cfg(self, i: int):
        cfg = get_test_config(_INSTANCE_BASE + i)
        cfg.MANUAL_CLOSE = False
        cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = True
        cfg.SCP_SIG_SCHEME = self.spec.scp_sig_scheme
        if self.spec.signature_backend is not None:
            cfg.SIGNATURE_BACKEND = self.spec.signature_backend
        if self.spec.tpu_cpu_cutover is not None:
            cfg.TPU_CPU_CUTOVER = self.spec.tpu_cpu_cutover
        if self.spec.sendq_bytes is not None:
            cfg.OVERLAY_SENDQ_BYTES = self.spec.sendq_bytes
        if self.spec.sendq_flood_msgs is not None:
            cfg.OVERLAY_SENDQ_FLOOD_MSGS = self.spec.sendq_flood_msgs
        if self.spec.straggler_stall_ms is not None:
            cfg.STRAGGLER_STALL_MS = self.spec.straggler_stall_ms
        if self.spec.max_tx_per_ledger is not None:
            cfg.DESIRED_MAX_TX_PER_LEDGER = self.spec.max_tx_per_ledger
        if self.spec.ingest_rate_limit is not None:
            cfg.INGEST_RATE_LIMIT = self.spec.ingest_rate_limit
        if self.spec.ingest_surge_high_water is not None:
            cfg.INGEST_SURGE_HIGH_WATER = self.spec.ingest_surge_high_water
        if self.spec.parallel_apply is not None:
            cfg.PARALLEL_APPLY = self.spec.parallel_apply
            if self.spec.parallel_apply:
                cfg.APPLY_WORKERS = 4
        if self.spec.disk_db or self.spec.archives:
            cfg.DATABASE = f"sqlite3://{self.workdir}/node{i}.db"
        if self.spec.archives:
            cfg.CHECKPOINT_FREQUENCY = self.spec.checkpoint_frequency
            archive = f"{self.workdir}/archive"
            spec = {"get": f"cp {archive}/{{0}} {{1}}"}
            if i == 0:  # one writer avoids concurrent cp races
                spec["put"] = f"cp {{0}} {archive}/{{1}}"
                spec["mkdir"] = f"mkdir -p {archive}/{{0}}"
            cfg.HISTORY = {"scenario": spec}
        return cfg

    def _build(self) -> None:
        spec = self.spec
        if (spec.disk_db or spec.archives) and self.workdir is None:
            self.workdir = tempfile.mkdtemp(prefix="stellar-tpu-scn-")
            self._own_workdir = True
        if self.spec.archives:
            import os

            os.makedirs(f"{self.workdir}/archive", exist_ok=True)
        mode = VIRTUAL_TIME if spec.clock_mode == "virtual" else REAL_TIME
        clock = VirtualClock(mode)
        overlay_mode = (
            OVER_TCP if spec.overlay_mode == "tcp" else OVER_LOOPBACK
        )
        if spec.topology == "core_and_tier":
            sim = topologies.core_and_tier(
                core_n=spec.n_nodes,
                tier_n=spec.tier_n,
                clock=clock,
                cfg_factory=self._cfg,
                mode=overlay_mode,
                tier_validators=spec.tier_validators,
            )
            self.node_keys = sim.topology_keys
        else:
            sim = Simulation(overlay_mode, clock)
            from ..crypto.keys import SecretKey

            keys = [
                SecretKey.pseudo_random_for_testing(i + 1)
                for i in range(spec.n_nodes)
            ]
            threshold = (
                spec.threshold
                if spec.threshold is not None
                else spec.n_nodes - (spec.n_nodes - 1) // 3
            )
            qset = SCPQuorumSet(
                threshold, [k.get_public_key() for k in keys], []
            )
            for i, k in enumerate(keys):
                sim.add_node(k, qset, cfg=self._cfg(i))
            for i in range(len(keys)):
                for j in range(i + 1, len(keys)):
                    sim.add_pending_connection(keys[i], keys[j])
            self.node_keys = keys
        sim.set_fault_seed(spec.seed)
        self.sim = sim

    # -- run ------------------------------------------------------------------
    def run(self) -> ScenarioResult:
        spec = self.spec
        self._build()
        sim = self.sim
        failures: List[str] = []
        try:
            sim.start_all_nodes()
            ok = sim.crank_until(
                lambda: sim.have_all_externalized(spec.stabilize_ledgers),
                spec.timeout / 3,
            )
            if not ok:
                failures.append(
                    "stabilization stuck at %s" % sim.ledger_nums()
                )
                sb = LivenessScoreboard(
                    scenario=spec.name, fault_class=spec.fault_class,
                    seed=spec.seed, clock_mode=spec.clock_mode,
                )
                return ScenarioResult(spec.name, False, failures, sb)

            # chaos window opens: snapshot, arm load + faults + doctor
            before = snapshot(sim)
            self._armed_at = sim.clock.now()
            self.loadgen = LoadGenerator(seed=spec.seed)
            self.loadgen.generate_load(
                sim.nodes[self._raw(spec.load_target)],
                spec.load_accounts,
                spec.load_txs,
                spec.load_rate,
                backlog_ledgers=spec.load_backlog_ledgers,
            )
            for f in spec.faults:
                marks_recovery = (
                    getattr(f, "heal_at", None) is not None
                    or type(f).__name__
                    in (
                        "CrashRestart",
                        "HardKillMidClose",
                        "PartitionUntilCheckpoint",
                    )
                )
                if marks_recovery:
                    self._expected_recoveries += 1
                f.arm(self)
            self._doctor(first=True)

            ok = sim.crank_until(self._target_reached, spec.timeout)
            self.done = True
            if not ok:
                failures.append(
                    "liveness target %d not reached in %.0fs: lcls=%s,"
                    " recoveries=%d/%d"
                    % (
                        spec.target_ledgers,
                        spec.timeout,
                        sim.ledger_nums(),
                        len(self._recoveries),
                        self._expected_recoveries,
                    )
                )

            after = snapshot(sim)
            tier_map = None
            if spec.tiers:
                tier_map = {
                    tier: {self._raw(i).hex()[:8] for i in idxs}
                    for tier, idxs in spec.tiers.items()
                }
            sb = LivenessScoreboard.from_snapshots(
                sim,
                before,
                after,
                exclude_nodes=self._excluded_prefixes(),
                tiers=tier_map,
                scenario=spec.name,
                fault_class=spec.fault_class,
                seed=spec.seed,
                clock_mode=spec.clock_mode,
            )
            if self._recoveries:
                sb.recovery_ms = round(max(self._recoveries), 1)
            sb.notes = list(self._notes)

            # -- verdicts ---------------------------------------------------
            if sb.invariant_violations:
                failures.append(
                    "%d ledger-invariant violation(s) under chaos"
                    % sb.invariant_violations
                )
            if not sb.ledgers_agree:
                failures.append("surviving nodes disagree on the chain")
            if spec.min_ledgers_per_sec and (
                sb.ledgers_per_sec < spec.min_ledgers_per_sec
            ):
                failures.append(
                    "liveness floor miss: %.3f < %.3f ledgers/sec"
                    % (sb.ledgers_per_sec, spec.min_ledgers_per_sec)
                )
            if spec.max_recovery_ms is not None and (
                sb.recovery_ms is None
                or sb.recovery_ms > spec.max_recovery_ms
            ):
                failures.append(
                    "recovery floor miss: %s ms (max %.0f)"
                    % (sb.recovery_ms, spec.max_recovery_ms)
                )
            # time-slip verdicts (ISSUE r19): the skew classes assert the
            # closeTime gates actually fired (beyond-slip) or stayed
            # silent (within-slip) — the metered observable, not just
            # liveness side effects
            total_slip = sb.slip_rejects_past + sb.slip_rejects_future
            if spec.min_slip_rejects and total_slip < spec.min_slip_rejects:
                failures.append(
                    "expected >= %d metered time-slip rejections, got %d"
                    % (spec.min_slip_rejects, total_slip)
                )
            if (
                spec.max_slip_rejects is not None
                and total_slip > spec.max_slip_rejects
            ):
                failures.append(
                    "%d time-slip rejections metered against a ceiling"
                    " of %d — a within-slip skew must not trip the gate"
                    % (total_slip, spec.max_slip_rejects)
                )
            # ingest-edge verdict (ISSUE r20): the flood shapes must have
            # shed their invalid-sig txs at the admission plane — before
            # check_valid, account loads, or flood fan-out spent anything
            if spec.min_ingest_sheds and (
                sb.ingest_rejects.get("badsig", 0) < spec.min_ingest_sheds
            ):
                failures.append(
                    "expected >= %d invalid-sig txs shed at the ingest"
                    " edge, got %d"
                    % (
                        spec.min_ingest_sheds,
                        sb.ingest_rejects.get("badsig", 0),
                    )
                )
            # overlay survival plane verdicts — CRITICAL is never shed,
            # in ANY scenario (the tentpole contract)
            if sb.sendq_sheds.get("critical", 0):
                failures.append(
                    "%d CRITICAL-class frames shed from a send queue —"
                    " consensus traffic must never shed"
                    % sb.sendq_sheds["critical"]
                )
            if (
                spec.min_flood_sheds
                and sb.sendq_sheds.get("flood", 0) < spec.min_flood_sheds
            ):
                failures.append(
                    "expected >= %d FLOOD-class sheds under overload, got %d"
                    % (spec.min_flood_sheds, sb.sendq_sheds.get("flood", 0))
                )
            if spec.assert_high_water_bounded:
                cap = (
                    spec.sendq_bytes
                    if spec.sendq_bytes is not None
                    else self._cfg(0).OVERLAY_SENDQ_BYTES
                )
                if cap and sb.sendq_bytes_high_water > cap:
                    if sb.sendq_oversized_admits == 0:
                        failures.append(
                            "per-peer queue-byte high-water %d exceeds"
                            " the configured cap %d"
                            % (sb.sendq_bytes_high_water, cap)
                        )
                    else:
                        # an oversized unsheddable frame admitted alone
                        # relaxes the documented per-peer bound to
                        # max(cap, that frame) — report, don't fail
                        sb.notes.append(
                            "high-water %d over cap %d under %d"
                            " oversized admit(s) — the documented"
                            " max(cap, one frame) bound applies"
                            % (
                                sb.sendq_bytes_high_water,
                                cap,
                                sb.sendq_oversized_admits,
                            )
                        )
            if spec.expect_straggler_disconnect:
                stall_budget = (
                    spec.straggler_stall_ms
                    if spec.straggler_stall_ms is not None
                    else self._cfg(0).STRAGGLER_STALL_MS
                )
                if sb.sendq_straggler_disconnects < 1:
                    failures.append(
                        "expected a straggler disconnect (ERR_LOAD) and"
                        " none happened"
                    )
                elif (
                    sb.sendq_max_stall_ms
                    > stall_budget + 1.5 * _STALL_POLL_SLACK_MS
                ):
                    # the stall timer fires AT the head's deadline on the
                    # virtual clock; any observed stall materially past
                    # the budget means detection drifted
                    failures.append(
                        "straggler stalled %.0f ms against a %.0f ms"
                        " budget — disconnect landed outside the window"
                        % (sb.sendq_max_stall_ms, stall_budget)
                    )
            for f in spec.faults:
                # fault-specific verdicts (the hard-kill class asserts
                # its kill fired and the restarted node's self-check
                # repaired; future classes plug in the same way)
                outcome = getattr(f, "verify_outcome", None)
                if outcome is not None:
                    outcome(failures)
            for f in spec.faults:
                checker = getattr(f, "assert_cache_unpolluted", None)
                if checker is not None:
                    try:
                        checked = checker()
                        self._notes.append(
                            "verify cache clean across %d flooded"
                            " invalid-sig envelopes" % checked
                        )
                    except AssertionError as e:
                        failures.append(str(e))
                fetchers = getattr(f, "n_envelopes", None)
                if fetchers:
                    # the fetch plane must not have wedged on made-up
                    # hashes (the eager-reject defense the flood attacks)
                    for raw, app in sim.nodes.items():
                        info = app.herder.pending_envelopes.dump_info()
                        wedged = sum(info["fetching"].values())
                        if wedged:
                            failures.append(
                                "node %s wedged %d envelopes in the fetch"
                                " plane under flood" % (raw.hex()[:8], wedged)
                            )
            sb.notes = list(self._notes)
            return ScenarioResult(spec.name, not failures, failures, sb)
        finally:
            self.done = True
            for f in spec.faults:
                # remove any process-global fs kill hooks a fault armed
                disarm = getattr(f, "disarm", None)
                if disarm is not None:
                    disarm()
            for t in self._fault_timers:
                t.cancel()
            if self._doctor_timer is not None:
                self._doctor_timer.cancel()
            if self.loadgen is not None:
                self.loadgen.stop()
            sim.stop_all_nodes()
            sim.clock.shutdown()
            if self._own_workdir:
                shutil.rmtree(self.workdir, ignore_errors=True)

    # -- internals ------------------------------------------------------------
    def _raw(self, idx: int) -> bytes:
        return Simulation._raw_key(self.node_keys[idx])

    def _excluded_raw(self) -> set:
        return {self._raw(i) for i in self.spec.liveness_exclude}

    def _excluded_prefixes(self) -> set:
        return {r.hex()[:8] for r in self._excluded_raw()}

    def _liveness_lcls(self) -> List[int]:
        """LCLs of the liveness-gated nodes (the spec's deliberate
        straggler, if any, is excluded from the floor it cannot meet)."""
        excluded = self._excluded_raw()
        return [
            app.ledger_manager.get_last_closed_ledger_num()
            for raw, app in self.sim.nodes.items()
            if raw not in excluded
        ]

    def _doctor(self, first: bool = False) -> None:
        """Link doctor tick: re-establish flapped/expected links (lossy
        links kill connections via MAC-sequence breaks; restarts rejoin
        here too), then re-arm."""
        if self.done:
            return
        if not first:
            self.sim.ensure_links()
        if self._doctor_timer is None:
            self._doctor_timer = VirtualTimer(self.sim.clock)
        self._doctor_timer.expires_from_now(self.spec.doctor_tick)
        self._doctor_timer.async_wait(self._doctor)

    def _target_reached(self) -> bool:
        sim = self.sim
        lcls = self._liveness_lcls()
        if not lcls:
            return False
        # recovery stamp: first moment every surviving node moved past the
        # pre-heal high-water mark in lockstep
        if self._recovery_t0 is not None:
            if min(lcls) > self._recovery_from_lcl and min(lcls) == max(lcls):
                self._recoveries.append(
                    (sim.clock.now() - self._recovery_t0) * 1000.0
                )
                self._recovery_t0 = None
        return (
            min(lcls) >= self.spec.target_ledgers
            and len(self._recoveries) >= self._expected_recoveries
        )
