"""Scenario — one declarative chaos run: topology × load × fault program
× liveness scoreboard.

The runner composes a multi-node Simulation (core mesh or core-and-tier
ring), streams LoadGenerator traffic through it, arms the fault program on
the shared clock, and cranks until the liveness target (or the timeout)
while tracking recovery from heals/restarts.  Every run:

- runs the invariant plane all-on (get_test_config default) and FAILS on
  any accepted-ledger violation;
- asserts the surviving nodes agree on the chain;
- emits one LivenessScoreboard, with a deterministic digest for
  VIRTUAL_TIME scenarios (same topology + seed + program ⇒ same digest —
  tests/test_scenarios.py pins the replay);
- enforces the spec's liveness floors (ledgers/sec, recovery ms).

Clock modes: chaos scenarios default to VIRTUAL_TIME (deterministic,
seeded).  Catchup-under-load runs REAL_TIME like the history suite — the
archive get/put commands are real subprocesses whose completion the
virtual clock would leap past.
"""

from __future__ import annotations

import shutil
import tempfile
from dataclasses import dataclass
from typing import List, Optional

from ..simulation import LoadGenerator, Simulation, topologies
from ..simulation.simulation import OVER_LOOPBACK
from ..tx.testutils import get_test_config
from ..util import REAL_TIME, VIRTUAL_TIME, VirtualClock, VirtualTimer, xlog
from ..xdr.scp import SCPQuorumSet
from .faults import Fault
from .scoreboard import LivenessScoreboard, snapshot

log = xlog.logger("Scenario")

# scenario node instance numbers start high so tmp/bucket dirs never
# collide with the unit suites' get_test_config(0..n) apps
_INSTANCE_BASE = 9100


@dataclass
class ScenarioSpec:
    name: str
    fault_class: str
    faults: List[Fault]
    n_nodes: int = 3
    threshold: Optional[int] = None  # None = BFT majority
    topology: str = "core"  # "core" | "core_and_tier"
    tier_n: int = 0
    clock_mode: str = "virtual"  # "virtual" | "real"
    seed: int = 1
    # SCP envelope signature scheme for every node (Config.SCP_SIG_SCHEME):
    # "ed25519" or "ed25519-halfagg" — the flood matrix runs the same
    # storm under both and compares scheme verify wall
    scp_sig_scheme: str = "ed25519"
    # load (streams through node `load_target` for the whole run)
    load_accounts: int = 6
    load_txs: int = 400
    load_rate: int = 40
    load_backlog_ledgers: int = 0
    load_target: int = 0
    # liveness target + floors
    target_ledgers: int = 12  # absolute min LCL across nodes at the end
    stabilize_ledgers: int = 2
    timeout: float = 300.0
    min_ledgers_per_sec: float = 0.0
    max_recovery_ms: Optional[float] = None
    # infrastructure
    disk_db: bool = False  # crash/restart needs on-disk sqlite
    archives: bool = False  # catchup needs a history archive
    checkpoint_frequency: int = 8
    doctor_tick: float = 1.0


@dataclass
class ScenarioResult:
    name: str
    ok: bool
    failures: List[str]
    scoreboard: LivenessScoreboard

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "ok": self.ok,
            "failures": self.failures,
            "scoreboard": self.scoreboard.to_dict(),
        }


class Scenario:
    def __init__(self, spec: ScenarioSpec, workdir: Optional[str] = None):
        self.spec = spec
        self.workdir = workdir
        self._own_workdir = False
        self.sim: Optional[Simulation] = None
        self.node_keys: List = []
        self.loadgen: Optional[LoadGenerator] = None
        self.done = False
        self._fault_timers: List[VirtualTimer] = []
        self._doctor_timer: Optional[VirtualTimer] = None
        self._armed_at = 0.0
        self._notes: List[str] = []
        # recovery bookkeeping (heals/restarts stamp the start; the crank
        # predicate stamps the end at the first agreed post-event close)
        self._expected_recoveries = 0
        self._recovery_t0: Optional[float] = None
        self._recovery_from_lcl = 0
        self._recoveries: List[float] = []

    # -- fault-program surface ----------------------------------------------
    def note(self, msg: str) -> None:
        log.info("[%s] %s", self.spec.name, msg)
        self._notes.append(msg)

    def elapsed(self) -> float:
        return self.sim.clock.now() - self._armed_at

    def elapsed_since_arm(self) -> float:
        return self.elapsed()

    def mark_recovery_start(self) -> None:
        self._recovery_t0 = self.sim.clock.now()
        self._recovery_from_lcl = max(
            (
                app.ledger_manager.get_last_closed_ledger_num()
                for app in self.sim.nodes.values()
            ),
            default=0,
        )

    # -- build ---------------------------------------------------------------
    def _cfg(self, i: int):
        cfg = get_test_config(_INSTANCE_BASE + i)
        cfg.MANUAL_CLOSE = False
        cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING = True
        cfg.SCP_SIG_SCHEME = self.spec.scp_sig_scheme
        if self.spec.disk_db or self.spec.archives:
            cfg.DATABASE = f"sqlite3://{self.workdir}/node{i}.db"
        if self.spec.archives:
            cfg.CHECKPOINT_FREQUENCY = self.spec.checkpoint_frequency
            archive = f"{self.workdir}/archive"
            spec = {"get": f"cp {archive}/{{0}} {{1}}"}
            if i == 0:  # one writer avoids concurrent cp races
                spec["put"] = f"cp {{0}} {archive}/{{1}}"
                spec["mkdir"] = f"mkdir -p {archive}/{{0}}"
            cfg.HISTORY = {"scenario": spec}
        return cfg

    def _build(self) -> None:
        spec = self.spec
        if (spec.disk_db or spec.archives) and self.workdir is None:
            self.workdir = tempfile.mkdtemp(prefix="stellar-tpu-scn-")
            self._own_workdir = True
        if self.spec.archives:
            import os

            os.makedirs(f"{self.workdir}/archive", exist_ok=True)
        mode = VIRTUAL_TIME if spec.clock_mode == "virtual" else REAL_TIME
        clock = VirtualClock(mode)
        if spec.topology == "core_and_tier":
            sim = topologies.core_and_tier(
                core_n=spec.n_nodes,
                tier_n=spec.tier_n,
                clock=clock,
                cfg_factory=self._cfg,
            )
            self.node_keys = sim.topology_keys
        else:
            sim = Simulation(OVER_LOOPBACK, clock)
            from ..crypto.keys import SecretKey

            keys = [
                SecretKey.pseudo_random_for_testing(i + 1)
                for i in range(spec.n_nodes)
            ]
            threshold = (
                spec.threshold
                if spec.threshold is not None
                else spec.n_nodes - (spec.n_nodes - 1) // 3
            )
            qset = SCPQuorumSet(
                threshold, [k.get_public_key() for k in keys], []
            )
            for i, k in enumerate(keys):
                sim.add_node(k, qset, cfg=self._cfg(i))
            for i in range(len(keys)):
                for j in range(i + 1, len(keys)):
                    sim.add_pending_connection(keys[i], keys[j])
            self.node_keys = keys
        sim.set_fault_seed(spec.seed)
        self.sim = sim

    # -- run ------------------------------------------------------------------
    def run(self) -> ScenarioResult:
        spec = self.spec
        self._build()
        sim = self.sim
        failures: List[str] = []
        try:
            sim.start_all_nodes()
            ok = sim.crank_until(
                lambda: sim.have_all_externalized(spec.stabilize_ledgers),
                spec.timeout / 3,
            )
            if not ok:
                failures.append(
                    "stabilization stuck at %s" % sim.ledger_nums()
                )
                sb = LivenessScoreboard(
                    scenario=spec.name, fault_class=spec.fault_class,
                    seed=spec.seed, clock_mode=spec.clock_mode,
                )
                return ScenarioResult(spec.name, False, failures, sb)

            # chaos window opens: snapshot, arm load + faults + doctor
            before = snapshot(sim)
            self._armed_at = sim.clock.now()
            self.loadgen = LoadGenerator(seed=spec.seed)
            self.loadgen.generate_load(
                sim.nodes[self._raw(spec.load_target)],
                spec.load_accounts,
                spec.load_txs,
                spec.load_rate,
                backlog_ledgers=spec.load_backlog_ledgers,
            )
            for f in spec.faults:
                marks_recovery = (
                    getattr(f, "heal_at", None) is not None
                    or type(f).__name__
                    in ("CrashRestart", "PartitionUntilCheckpoint")
                )
                if marks_recovery:
                    self._expected_recoveries += 1
                f.arm(self)
            self._doctor(first=True)

            ok = sim.crank_until(self._target_reached, spec.timeout)
            self.done = True
            if not ok:
                failures.append(
                    "liveness target %d not reached in %.0fs: lcls=%s,"
                    " recoveries=%d/%d"
                    % (
                        spec.target_ledgers,
                        spec.timeout,
                        sim.ledger_nums(),
                        len(self._recoveries),
                        self._expected_recoveries,
                    )
                )

            after = snapshot(sim)
            sb = LivenessScoreboard.from_snapshots(
                sim,
                before,
                after,
                scenario=spec.name,
                fault_class=spec.fault_class,
                seed=spec.seed,
                clock_mode=spec.clock_mode,
            )
            if self._recoveries:
                sb.recovery_ms = round(max(self._recoveries), 1)
            sb.notes = list(self._notes)

            # -- verdicts ---------------------------------------------------
            if sb.invariant_violations:
                failures.append(
                    "%d ledger-invariant violation(s) under chaos"
                    % sb.invariant_violations
                )
            if not sb.ledgers_agree:
                failures.append("surviving nodes disagree on the chain")
            if spec.min_ledgers_per_sec and (
                sb.ledgers_per_sec < spec.min_ledgers_per_sec
            ):
                failures.append(
                    "liveness floor miss: %.3f < %.3f ledgers/sec"
                    % (sb.ledgers_per_sec, spec.min_ledgers_per_sec)
                )
            if spec.max_recovery_ms is not None and (
                sb.recovery_ms is None
                or sb.recovery_ms > spec.max_recovery_ms
            ):
                failures.append(
                    "recovery floor miss: %s ms (max %.0f)"
                    % (sb.recovery_ms, spec.max_recovery_ms)
                )
            for f in spec.faults:
                checker = getattr(f, "assert_cache_unpolluted", None)
                if checker is not None:
                    try:
                        checked = checker()
                        self._notes.append(
                            "verify cache clean across %d flooded"
                            " invalid-sig envelopes" % checked
                        )
                    except AssertionError as e:
                        failures.append(str(e))
                fetchers = getattr(f, "n_envelopes", None)
                if fetchers:
                    # the fetch plane must not have wedged on made-up
                    # hashes (the eager-reject defense the flood attacks)
                    for raw, app in sim.nodes.items():
                        info = app.herder.pending_envelopes.dump_info()
                        wedged = sum(info["fetching"].values())
                        if wedged:
                            failures.append(
                                "node %s wedged %d envelopes in the fetch"
                                " plane under flood" % (raw.hex()[:8], wedged)
                            )
            sb.notes = list(self._notes)
            return ScenarioResult(spec.name, not failures, failures, sb)
        finally:
            self.done = True
            for t in self._fault_timers:
                t.cancel()
            if self._doctor_timer is not None:
                self._doctor_timer.cancel()
            if self.loadgen is not None:
                self.loadgen.stop()
            sim.stop_all_nodes()
            sim.clock.shutdown()
            if self._own_workdir:
                shutil.rmtree(self.workdir, ignore_errors=True)

    # -- internals ------------------------------------------------------------
    def _raw(self, idx: int) -> bytes:
        return Simulation._raw_key(self.node_keys[idx])

    def _doctor(self, first: bool = False) -> None:
        """Link doctor tick: re-establish flapped/expected links (lossy
        links kill connections via MAC-sequence breaks; restarts rejoin
        here too), then re-arm."""
        if self.done:
            return
        if not first:
            self.sim.ensure_links()
        if self._doctor_timer is None:
            self._doctor_timer = VirtualTimer(self.sim.clock)
        self._doctor_timer.expires_from_now(self.spec.doctor_tick)
        self._doctor_timer.async_wait(self._doctor)

    def _target_reached(self) -> bool:
        sim = self.sim
        lcls = sim.ledger_nums()
        if not lcls:
            return False
        # recovery stamp: first moment every surviving node moved past the
        # pre-heal high-water mark in lockstep
        if self._recovery_t0 is not None:
            if min(lcls) > self._recovery_from_lcl and min(lcls) == max(lcls):
                self._recoveries.append(
                    (sim.clock.now() - self._recovery_t0) * 1000.0
                )
                self._recovery_t0 = None
        return (
            min(lcls) >= self.spec.target_ledgers
            and len(self._recoveries) >= self._expected_recoveries
        )
