"""Adversarial-network chaos plane (ROADMAP #5).

Declarative chaos harness over the in-process Simulation: topology × load
× scheduled fault program × consensus-liveness scoreboard.  See
``scenario.py`` for the runner, ``faults.py`` for the fault vocabulary,
``matrix.py`` for the named small/big shapes per fault class, and
``python -m stellar_tpu.scenarios`` for the CI entry point
(relay_watch ``scenario_liveness_r12``).
"""

from .faults import (  # noqa: F401
    ByzantineFlood,
    CrashRestart,
    Fault,
    IngestFlood,
    OverloadStorm,
    Partition,
    PartitionUntilCheckpoint,
    SlowLossyLinks,
    SlowReader,
)
from .matrix import (  # noqa: F401
    FAULT_CLASSES,
    big_specs,
    run_matrix,
    small_specs,
)
from .scenario import Scenario, ScenarioResult, ScenarioSpec  # noqa: F401
from .scoreboard import LivenessScoreboard, snapshot  # noqa: F401

__all__ = [
    "ByzantineFlood",
    "CrashRestart",
    "Fault",
    "IngestFlood",
    "OverloadStorm",
    "SlowReader",
    "Partition",
    "PartitionUntilCheckpoint",
    "SlowLossyLinks",
    "FAULT_CLASSES",
    "big_specs",
    "run_matrix",
    "small_specs",
    "Scenario",
    "ScenarioResult",
    "ScenarioSpec",
    "LivenessScoreboard",
    "snapshot",
]
