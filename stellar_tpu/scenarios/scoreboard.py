"""LivenessScoreboard — what the chaos plane measures.

A scenario is only as good as its verdict: every run emits one scoreboard
covering consensus liveness (ledgers closed / wall time, nomination and
ballot rounds burned), the flood plane (fan-out, strict-gate fast
rejects), the close pipeline's overlap stats, recovery time after a
heal/restart, and the invariant plane's violation count.  The scoreboard
is built from COUNTER DELTAS between two snapshots, so the stabilization
phase before the fault program arms never pollutes the chaos window.

``digest()`` is the deterministic-replay oracle (ISSUE r12 satellite):
same topology + seed + fault program ⇒ identical digest across runs.  It
deliberately covers only clock-deterministic fields — worker-thread
timing artifacts (pipeline joined_warm, overlap ms) are reported but
excluded.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..crypto import sha256


_PIPE_KEYS = (
    "dispatched", "joined", "joined_warm", "quarantined",
    "overlap_hidden_ms", "backlog_drains",
)

# SCP signature-scheme plane (crypto/aggregate/): flushed envelope count
# and verify wall are reported for BOTH schemes (the flood A/B compares
# verify_wall_ms across schemes at the same storm); the agg_* counters
# stay zero under the per-envelope scheme.  Wall is thread/host timing —
# reported, never digested.
_AGG_KEYS = (
    "flush_envelopes", "verify_wall_ms", "agg_checks", "agg_envelopes",
    "fallback_envelopes", "gate_rejects",
)

# overlay survival plane (overlay/sendqueue.py): per-class send-side
# sheds + straggler disconnects are crank-deterministic counters (they
# join the virtual-mode digest); bytes_high_water/max_stall_ms are
# node-level maxima (taken from the AFTER snapshot, monotone per node)
# and recv_load_sheds is the LoadManager's receive-side decision count.
_SENDQ_DELTA_KEYS = (
    "shed_critical", "shed_fetch", "shed_flood", "shed_gossip",
    "stragglers", "oversized_admits",
)
_SENDQ_MAX_KEYS = ("bytes_high_water", "max_stall_ms")


def _node_counters(app) -> Dict[str, int]:
    h = app.herder
    om = app.overlay_manager
    inv = getattr(app, "invariants", None)
    pipe = getattr(app, "close_pipeline", None)
    pipe_stats = pipe.stats() if pipe is not None else {}
    scheme = getattr(app, "scp_scheme", None)
    scheme_stats = scheme.stats() if scheme is not None else {}
    out = {
        "pipe." + k: pipe_stats.get(k, 0) for k in _PIPE_KEYS
    }
    out.update(
        {"agg." + k: scheme_stats.get(k, 0) for k in _AGG_KEYS}
    )
    sq = getattr(om, "sendq_stats", None) if om else None
    if sq is not None:
        from ..overlay.sendqueue import (
            CLASS_CRITICAL, CLASS_FETCH, CLASS_FLOOD, CLASS_GOSSIP,
        )

        out.update({
            "sendq.shed_critical": sq.shed_msgs[CLASS_CRITICAL],
            "sendq.shed_fetch": sq.shed_msgs[CLASS_FETCH],
            "sendq.shed_flood": sq.shed_msgs[CLASS_FLOOD],
            "sendq.shed_gossip": sq.shed_msgs[CLASS_GOSSIP],
            "sendq.stragglers": sq.straggler_disconnects,
            "sendq.oversized_admits": sq.oversized_admits,
            "sendq.bytes_high_water": sq.bytes_high_water,
            "sendq.max_stall_ms": sq.max_stall_ms,
        })
    else:
        out.update({"sendq." + k: 0 for k in _SENDQ_DELTA_KEYS})
        out.update({"sendq." + k: 0 for k in _SENDQ_MAX_KEYS})
    ing = getattr(app, "ingest", None)
    out.update({
        # verify-at-ingest admission plane (ingest/plane.py, ISSUE r20):
        # edge sheds per reject class + admitted txs + flush count, all
        # crank-deterministic (the badsig sheds join the digest)
        "ingest.reject_badsig": ing.m_reject_badsig.count if ing else 0,
        "ingest.reject_ratelimit": ing.m_reject_rate.count if ing else 0,
        "ingest.reject_surge": ing.m_reject_surge.count if ing else 0,
        "ingest.admitted": ing.m_admit.count if ing else 0,
        "ingest.flushes": ing.m_flush.count if ing else 0,
    })
    out.update({
        "recv_load_sheds": (
            om.load_manager.n_sheds
            if om and getattr(om, "load_manager", None) is not None
            else 0
        ),
        "externalized": h.m_value_externalize.count if h else 0,
        # time-slip rejections (ISSUE r19): the herder's closeTime gates
        # — under clock skew these are the defense that fires; crank-
        # deterministic, so they join the virtual-mode digest
        "slip_rejects_past": h.m_value_close_past.count if h else 0,
        "slip_rejects_future": h.m_value_close_future.count if h else 0,
        "nomination_rounds": h.n_nomination_rounds if h else 0,
        "ballot_rounds": h.n_ballot_rounds if h else 0,
        "envelopes_emitted": h.m_envelope_emit.count if h else 0,
        "envelopes_received": h.m_envelope_receive.count if h else 0,
        "envelopes_invalid_sig": h.m_envelope_invalidsig.count if h else 0,
        "flood_fanout": om.floodgate.n_sent if om else 0,
        "scp_batch_rejected": om.m_scp_batch_rejected.count if om else 0,
        "invariant_violations": inv.total_violations if inv else 0,
    })
    return out


@dataclass
class Snapshot:
    at: float
    lcls: Dict[str, int]
    counters: Dict[str, Dict[str, int]]  # node hex prefix -> counters


def snapshot(sim) -> Snapshot:
    return Snapshot(
        at=sim.clock.now(),
        lcls={
            raw.hex()[:8]: app.ledger_manager.get_last_closed_ledger_num()
            for raw, app in sim.nodes.items()
        },
        counters={
            raw.hex()[:8]: _node_counters(app)
            for raw, app in sim.nodes.items()
        },
    )


@dataclass
class LivenessScoreboard:
    scenario: str = ""
    fault_class: str = ""
    seed: int = 0
    clock_mode: str = "virtual"
    # liveness
    ledgers_closed: int = 0  # min across surviving nodes, chaos window
    wall_seconds: float = 0.0
    ledgers_per_sec: float = 0.0
    nomination_rounds: int = 0
    ballot_rounds: int = 0
    # flood plane
    envelopes_emitted: int = 0
    envelopes_received: int = 0
    flood_fanout: int = 0
    fast_rejects: int = 0  # invalid-sig envelopes rejected (eager + batch)
    fast_reject_rate_per_sec: float = 0.0
    # time-and-asymmetry plane (ISSUE r19): closeTime-gate rejections —
    # a skewed node rejecting the quorum's values reads as `future` on
    # the skewed node; a forward-skewed proposer's values read as
    # `future` on everyone else.  Crank-deterministic, digested.
    slip_rejects_past: int = 0
    slip_rejects_future: int = 0
    # recovery
    recovery_ms: Optional[float] = None  # heal/restart -> next agreed close
    # correctness
    invariant_violations: int = 0
    ledgers_agree: bool = True
    final_lcls: Dict[str, int] = field(default_factory=dict)
    final_hash: str = ""  # ledger hash at the lowest common sequence
    # overlay survival plane (overlay/sendqueue.py): send-side sheds per
    # class (window deltas; CRITICAL must stay 0 — Scenario.run fails any
    # run that sheds it), straggler disconnects, and node-level maxima
    # for queue-byte high-water / observed CRITICAL stall
    sendq_sheds: Dict[str, int] = field(default_factory=dict)
    sendq_straggler_disconnects: int = 0
    # unsheddable frames bigger than the whole cap admitted alone on an
    # empty queue: while one is queued the documented per-peer bound is
    # max(cap, that frame), so the high-water verdict must not read a
    # breach off the raw cap when these occurred
    sendq_oversized_admits: int = 0
    sendq_bytes_high_water: int = 0
    sendq_max_stall_ms: float = 0.0
    recv_load_sheds: int = 0  # LoadManager (receive-cost) shed decisions
    # verify-at-ingest admission plane (ingest/plane.py, ISSUE r20):
    # edge sheds per reject class (window deltas; badsig is the flood
    # defense and joins the virtual-mode digest), admitted txs, and the
    # standing per-pod line-rate claim — rejects/sec over the window
    ingest_rejects: Dict[str, int] = field(default_factory=dict)
    ingest_admitted: int = 0
    ingest_flushes: int = 0
    ingest_reject_rate_per_sec: float = 0.0
    # close pipeline (reported, excluded from digest: thread timing)
    pipeline: Dict[str, float] = field(default_factory=dict)
    # SCP signature-scheme plane (reported, excluded from digest: wall
    # timing; the flood A/B reads verify_wall_ms across schemes)
    aggregate: Dict[str, float] = field(default_factory=dict)
    # per-tier aggregates (ISSUE r19; reported, not digested — the lean-
    # digest policy): for specs that name tiers (core_and_tier shapes),
    # ledger progress and survival-plane counters grouped per tier, so a
    # targeted fault's verdict can read "tier-1 undisturbed, tier-2 shed"
    per_tier: Dict[str, dict] = field(default_factory=dict)
    notes: List[str] = field(default_factory=list)

    @classmethod
    def from_snapshots(
        cls, sim, before: Snapshot, after: Snapshot, exclude_nodes=(),
        tiers=None, **kw
    ):
        """``exclude_nodes``: node hex prefixes excluded from the min-LCL
        liveness computation (a scenario's deliberate straggler must not
        gate the consensus floor it is designed to miss); every other
        counter — and chain agreement — still covers them.  ``tiers``:
        optional {tier_name: set of node hex prefixes} — fills the
        report-only per_tier aggregates (ISSUE r19)."""
        sb = cls(**kw)
        sb.wall_seconds = max(1e-9, after.at - before.at)
        node_deltas = {}
        for node, c1 in after.counters.items():
            c0 = before.counters.get(node, {})
            # a restarted validator is a fresh Application: its counters
            # reset to zero mid-window, so a value below the snapshot
            # means "count since restart" — use it whole, not the
            # (negative) difference
            node_deltas[node] = {
                k: (c1[k] - c0.get(k, 0)) if c1[k] >= c0.get(k, 0)
                else c1[k]
                for k in c1
            }
        deltas = list(node_deltas.values())
        closed = [
            after.lcls[n] - before.lcls.get(n, 0)
            for n in after.lcls
            if n not in exclude_nodes
        ]
        sb.ledgers_closed = min(closed) if closed else 0
        sb.ledgers_per_sec = round(sb.ledgers_closed / sb.wall_seconds, 3)
        for d in deltas:
            sb.nomination_rounds += d["nomination_rounds"]
            sb.ballot_rounds += d["ballot_rounds"]
            sb.envelopes_emitted += d["envelopes_emitted"]
            sb.envelopes_received += d["envelopes_received"]
            sb.flood_fanout += d["flood_fanout"]
            sb.fast_rejects += d["envelopes_invalid_sig"]
            sb.invariant_violations += d["invariant_violations"]
            sb.slip_rejects_past += d.get("slip_rejects_past", 0)
            sb.slip_rejects_future += d.get("slip_rejects_future", 0)
        sb.fast_reject_rate_per_sec = round(
            sb.fast_rejects / sb.wall_seconds, 2
        )
        sb.final_lcls = dict(after.lcls)
        sb.ledgers_agree = sim.all_ledgers_agree()
        if sb.ledgers_agree and sim.nodes:
            from ..ledger.headerframe import LedgerHeaderFrame

            min_seq = min(
                app.ledger_manager.get_last_closed_ledger_num()
                for app in sim.nodes.values()
            )
            any_app = next(iter(sim.nodes.values()))
            f = LedgerHeaderFrame.load_by_sequence(any_app.database, min_seq)
            if f is not None:
                sb.final_hash = f.get_hash().hex()
        # pipeline stats ride the same snapshot-delta discipline as the
        # other counters: stabilization-phase dispatches never count
        # toward the chaos window
        sb.pipeline = {
            k: round(sum(d.get("pipe." + k, 0) for d in deltas), 1)
            for k in _PIPE_KEYS
        }
        sb.aggregate = {
            k: round(sum(d.get("agg." + k, 0) for d in deltas), 1)
            for k in _AGG_KEYS
        }
        for short, key in (
            ("critical", "sendq.shed_critical"),
            ("fetch", "sendq.shed_fetch"),
            ("flood", "sendq.shed_flood"),
            ("gossip", "sendq.shed_gossip"),
        ):
            sb.sendq_sheds[short] = sum(d.get(key, 0) for d in deltas)
        sb.sendq_straggler_disconnects = sum(
            d.get("sendq.stragglers", 0) for d in deltas
        )
        sb.sendq_oversized_admits = sum(
            d.get("sendq.oversized_admits", 0) for d in deltas
        )
        # maxima, not deltas: monotone per node, the AFTER snapshot IS
        # the run's high-water (stabilization traffic never congests)
        sb.sendq_bytes_high_water = max(
            (c.get("sendq.bytes_high_water", 0)
             for c in after.counters.values()),
            default=0,
        )
        sb.sendq_max_stall_ms = round(
            max(
                (c.get("sendq.max_stall_ms", 0.0)
                 for c in after.counters.values()),
                default=0.0,
            ),
            1,
        )
        sb.recv_load_sheds = sum(
            d.get("recv_load_sheds", 0) for d in deltas
        )
        for short, key in (
            ("badsig", "ingest.reject_badsig"),
            ("ratelimit", "ingest.reject_ratelimit"),
            ("surge", "ingest.reject_surge"),
        ):
            sb.ingest_rejects[short] = sum(d.get(key, 0) for d in deltas)
        sb.ingest_admitted = sum(
            d.get("ingest.admitted", 0) for d in deltas
        )
        sb.ingest_flushes = sum(d.get("ingest.flushes", 0) for d in deltas)
        sb.ingest_reject_rate_per_sec = round(
            sum(sb.ingest_rejects.values()) / sb.wall_seconds, 2
        )
        if tiers:
            for tier, members in tiers.items():
                tier_closed = [
                    after.lcls[n] - before.lcls.get(n, 0)
                    for n in after.lcls
                    if n in members
                ]
                td = [d for n, d in node_deltas.items() if n in members]
                tier_min = min(tier_closed) if tier_closed else 0
                sb.per_tier[tier] = {
                    "nodes": len(td),
                    "ledgers_closed": tier_min,
                    "ledgers_per_sec": round(tier_min / sb.wall_seconds, 3),
                    "flood_sheds": sum(
                        d.get("sendq.shed_flood", 0) for d in td
                    ),
                    "critical_sheds": sum(
                        d.get("sendq.shed_critical", 0) for d in td
                    ),
                    "stragglers": sum(
                        d.get("sendq.stragglers", 0) for d in td
                    ),
                    "fast_rejects": sum(
                        d.get("envelopes_invalid_sig", 0) for d in td
                    ),
                    "slip_rejects": sum(
                        d.get("slip_rejects_past", 0)
                        + d.get("slip_rejects_future", 0)
                        for d in td
                    ),
                }
        return sb

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["digest"] = self.digest()
        return d

    def digest(self) -> str:
        """Deterministic-replay oracle: clock-deterministic fields only.
        Virtual-clock scenarios must reproduce this exactly for the same
        (topology, seed, fault program); real-clock scenarios report it
        for the record but rates/wall-time fields stay out regardless."""
        stable = {
            "scenario": self.scenario,
            "seed": self.seed,
            "ledgers_closed": self.ledgers_closed,
            "final_lcls": self.final_lcls,
            "final_hash": self.final_hash,
            "ledgers_agree": self.ledgers_agree,
            "invariant_violations": self.invariant_violations,
        }
        if self.clock_mode == "virtual":
            # deterministic under VIRTUAL_TIME only: counters below move
            # with message/crank interleaving, which the virtual clock
            # replays exactly but a real clock does not
            stable.update(
                wall_seconds=round(self.wall_seconds, 6),
                nomination_rounds=self.nomination_rounds,
                ballot_rounds=self.ballot_rounds,
                fast_rejects=self.fast_rejects,
                recovery_ms=self.recovery_ms,
                # send-side sheds + stragglers are byte- and crank-
                # deterministic; the byte high-water is reported but NOT
                # digested (it depends on per-host frame sizes only
                # through deterministic packing, but keeping the digest
                # lean keeps cross-version replays comparable)
                sendq_sheds=dict(sorted(self.sendq_sheds.items())),
                sendq_stragglers=self.sendq_straggler_disconnects,
                # closeTime-gate rejections are message/crank-order
                # deterministic like fast_rejects (the skew schedules
                # are pure functions of the shared virtual clock)
                slip_rejects_past=self.slip_rejects_past,
                slip_rejects_future=self.slip_rejects_future,
                # ingest-edge sheds ride the same crank-determinism as
                # fast_rejects: injection timers, deadline flushes, and
                # size triggers are pure functions of the virtual clock
                ingest_rejects=dict(sorted(self.ingest_rejects.items())),
            )
        return sha256(
            json.dumps(stable, sort_keys=True).encode()
        ).hex()[:32]
