"""Fault programs — the scheduled adversities a scenario runs under.

Each fault is a declarative event (or event pair) with clock offsets; the
Scenario runner arms them on the simulation's clock at start.  Faults talk
only to the Simulation's chaos surface (partition/heal/crash_node/
restart_node/set_link_faults/ensure_links) and to the public node APIs the
reference's byzantine tests use (enqueue_scp_envelope, recv_transaction),
so a fault program composes with any topology.

Determinism: every fault that rolls randomness derives its RNG from the
scenario seed (never the module-level ``random``), and link-fault knobs
reseed the LoopbackPeer fault RNGs through the simulation's
``set_fault_seed`` plumbing — same topology + seed + program ⇒ identical
faults, identical scoreboard (the replay contract).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List, Optional

from ..overlay.loopback import FaultProfile
from ..util import VirtualTimer, xlog

log = xlog.logger("Scenario")


class Fault:
    """Base: subclasses implement ``arm(scn)`` — schedule whatever timers
    the fault needs on ``scn.sim.clock`` (offsets are seconds from the
    moment the fault program arms, i.e. after stabilization)."""

    def arm(self, scn) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__

    # shared helper: one-shot timer on the scenario clock.  ``slot`` names
    # a reusable timer on this fault — recurring ticks (flood cadence,
    # lag polls) re-arm ONE timer instead of allocating a fresh
    # VirtualTimer per tick (all of which the scenario would retain for
    # teardown cancellation).
    def _at(self, scn, delay: float, fn, slot: Optional[str] = None) -> None:
        if slot is None:
            t = VirtualTimer(scn.sim.clock)
            scn._fault_timers.append(t)
        else:
            slots = self.__dict__.setdefault("_timer_slots", {})
            t = slots.get(slot)
            if t is None:
                t = slots[slot] = VirtualTimer(scn.sim.clock)
                scn._fault_timers.append(t)
        t.expires_from_now(max(0.0, delay))
        t.async_wait(fn)

    # shared engage→heal scaffold for faults with a lag-polled heal
    # (Partition, ClockSkew, AsymmetricPartition): arm ``engage_fn`` at
    # ``at``; heal at the ``heal_at`` deadline or as soon as the fastest
    # node is ``heal_lag`` ledgers past the slowest — whichever first,
    # at most once.  ``heal_fn(reason)`` performs the class-specific
    # undo + note; the scaffold owns the once-only sentinel, the
    # recovery-clock stamp, and the poll rescheduling.
    def _arm_engage_heal(
        self, scn, engage_fn, heal_fn, *,
        at: float,
        heal_at: Optional[float] = None,
        heal_lag: Optional[int] = None,
        poll: float = 0.25,
    ) -> None:
        healed = []

        def heal(reason):
            if healed or scn.done:
                return
            healed.append(True)
            heal_fn(reason)
            scn.mark_recovery_start()

        def poll_lag():
            if healed or scn.done:
                return
            lcls = scn.sim.ledger_nums()
            if lcls and max(lcls) - min(lcls) >= heal_lag:
                heal("lag=%d" % (max(lcls) - min(lcls)))
            else:
                self._at(scn, poll, poll_lag, slot="poll")

        def engage():
            engage_fn()
            if heal_lag is not None:
                self._at(scn, poll, poll_lag, slot="poll")

        self._at(scn, at, engage)
        if heal_at is not None:
            self._at(scn, heal_at, lambda: heal("deadline"))


@dataclass
class Partition(Fault):
    """Split the topology into ``groups`` (lists of node indices) at ``at``;
    heal at ``heal_at`` (None = never — the scenario end heals).  The heal
    stamps the scoreboard's recovery clock.

    ``heal_lag`` (with ``heal_at`` as the backstop deadline) heals as soon
    as the fastest group has closed ``heal_lag`` ledgers past the slowest
    — the shape that pins a REPLAYABLE lag: ≤ the SCP state window
    (MAX_SLOTS_TO_REMEMBER), so the laggards replay the missed slots from
    peers' state as one pipelined close backlog instead of needing a
    history archive.  Leader-election stalls right after the split make a
    pure-time heal roll the dice on how much lag actually built; the
    lag-polled heal is deterministic about it."""

    at: float
    heal_at: Optional[float]
    groups: List[List[int]]
    heal_lag: Optional[int] = None
    poll: float = 0.25

    def arm(self, scn) -> None:
        def split():
            keys = [[scn.node_keys[i] for i in g] for g in self.groups]
            scn.sim.partition(*keys)
            scn.note("partition at t=%.1f: %s" % (scn.elapsed(), self.groups))

        def heal(reason):
            scn.sim.heal()
            scn.note("heal at t=%.1f (%s)" % (scn.elapsed(), reason))

        self._arm_engage_heal(
            scn, split, heal, at=self.at, heal_at=self.heal_at,
            heal_lag=self.heal_lag, poll=self.poll,
        )


@dataclass
class ClockSkew(Fault):
    """Per-node wall-clock skew (ISSUE r19): at ``at``, node ``node``'s
    ``Application.time_now`` view diverges from the shared clock through
    the Simulation's clock-offset seam — closeTime nomination and the
    MAX_TIME_SLIP_SECONDS acceptance gate see the skewed time, while
    every timer still rides the one shared clock.  Three schedules,
    composable and all pure functions of the shared virtual clock (the
    determinism contract — no wall reads, no RNG):

    - static:  ``offset`` seconds from the moment the fault engages;
    - drift:   ``drift_per_sec`` grows the offset linearly from engage
               (the cheap-oscillator shape);
    - step:    with ``step_at`` set, the static ``offset`` lands as a
               JUMP that many seconds after engage (the NTP-step shape;
               drift, if any, still accrues from engage).

    ``heal_at`` (deadline) / ``heal_lag`` (heal as soon as the fastest
    node is ``heal_lag`` ledgers past the slowest — the replayable-lag
    shape, like Partition) clear the offset and stamp the recovery
    clock.  A skew beyond MAX_TIME_SLIP_SECONDS makes the skewed node
    reject the quorum's values (metered as
    herder.value.reject-closetime-future) or the quorum reject the
    skewed node's — either way consensus must ride it out and the
    skewed node must rejoin once the skew heals."""

    at: float
    node: int
    offset: float = 0.0
    drift_per_sec: float = 0.0
    step_at: Optional[float] = None
    heal_at: Optional[float] = None
    heal_lag: Optional[int] = None
    poll: float = 0.25

    def arm(self, scn) -> None:
        key = scn.node_keys[self.node]

        def engage():
            t0 = scn.sim.clock.now()
            step_t = None if self.step_at is None else t0 + self.step_at
            static, drift = self.offset, self.drift_per_sec

            def offset_fn(now: float) -> float:
                off = drift * (now - t0)
                if step_t is None or now >= step_t:
                    off += static
                return off

            scn.sim.set_clock_offset(key, offset_fn)
            scn.note(
                "clock skew on node %d at t=%.1f: offset=%+.1fs"
                " drift=%+.3f/s step_at=%s"
                % (self.node, scn.elapsed(), static, drift, self.step_at)
            )

        def heal(reason):
            scn.sim.clear_clock_offset(key)
            scn.note(
                "clock skew on node %d healed at t=%.1f (%s)"
                % (self.node, scn.elapsed(), reason)
            )

        self._arm_engage_heal(
            scn, engage, heal, at=self.at, heal_at=self.heal_at,
            heal_lag=self.heal_lag, poll=self.poll,
        )


@dataclass
class AsymmetricPartition(Fault):
    """One-way isolation (ISSUE r19): at ``at``, frames TOWARD the
    ``deaf`` nodes are silently dropped while their own frames keep
    flowing — ``Simulation.partition(deaf, rest, oneway=True)``, the
    half-open-connection case.  Links stay up and authenticated the
    whole time (the drop happens before the MAC/sequence plane), so the
    deaf node keeps voting into a network it can no longer hear; heal
    resumes delivery on the SAME connections (no flap) and the deaf
    node replays the missed slots from peers' SCP state rebroadcast.
    ``heal_lag`` (with ``heal_at`` as deadline backstop) keeps the lag
    inside the replayable SCP window, like Partition."""

    at: float
    deaf: List[int]
    heal_at: Optional[float] = None
    heal_lag: Optional[int] = None
    poll: float = 0.25

    def arm(self, scn) -> None:
        deaf_keys = [scn.node_keys[i] for i in self.deaf]
        rest = [
            k for i, k in enumerate(scn.node_keys) if i not in self.deaf
        ]

        def split():
            # group0→group1 delivered, group1→group0 dropped: the deaf
            # nodes are heard (group0 = deaf) but hear nothing back
            scn.sim.partition(deaf_keys, rest, oneway=True)
            scn.note(
                "one-way partition at t=%.1f: nodes %s deaf"
                % (scn.elapsed(), self.deaf)
            )

        def heal(reason):
            scn.sim.heal()
            scn.note(
                "one-way partition healed at t=%.1f (%s)"
                % (scn.elapsed(), reason)
            )

        self._arm_engage_heal(
            scn, split, heal, at=self.at, heal_at=self.heal_at,
            heal_lag=self.heal_lag, poll=self.poll,
        )


@dataclass
class SlowLossyLinks(Fault):
    """Arm a lossy/latency FaultProfile on every link at ``at`` (and back
    to clean at ``heal_at``).  Post-handshake loss/damage flaps the
    connection (MAC-sequence break, overlay/loopback.py) — the scenario's
    link doctor re-establishes flapped pairs each tick, so what this
    models is a degraded, flapping network that consensus must ride out."""

    at: float
    heal_at: Optional[float] = None
    profile: FaultProfile = field(
        default_factory=lambda: FaultProfile(
            drop=0.02, duplicate=0.02, reorder=0.03, damage=0.01,
            latency=0.05,
        )
    )

    def arm(self, scn) -> None:
        def degrade():
            scn.sim.set_link_faults(self.profile)
            scn.note("links degraded at t=%.1f" % scn.elapsed())

        self._at(scn, self.at, degrade)
        if self.heal_at is not None:
            def restore():
                scn.sim.set_link_faults(FaultProfile())
                scn.sim.ensure_links()
                scn.mark_recovery_start()
                scn.note("links clean at t=%.1f" % scn.elapsed())

            self._at(scn, self.heal_at, restore)


@dataclass
class CrashRestart(Fault):
    """Take node ``node`` down hard at ``at``; bring it back on its
    on-disk state at ``restart_at`` (requires a disk-backed DATABASE,
    which the Scenario provisions for fault programs containing this).
    The restart stamps the recovery clock."""

    at: float
    restart_at: float
    node: int

    def arm(self, scn) -> None:
        key = scn.node_keys[self.node]

        def crash():
            scn.sim.crash_node(key)
            scn.note("crashed node %d at t=%.1f" % (self.node, scn.elapsed()))

        def restart():
            scn.sim.restart_node(key)
            scn.mark_recovery_start()
            scn.note("restarted node %d at t=%.1f" % (self.node, scn.elapsed()))

        self._at(scn, self.at, crash)
        self._at(scn, self.restart_at, restart)


@dataclass
class HardKillMidClose(Fault):
    """The storage plane's chaos class (ISSUE r18): a REAL kill, not
    ``graceful_stop``.  At ``at`` an in-process storage-fault injector
    (scenarios/storagefaults.py) arms on the target node's Database; the
    next time that node crosses the named durable-write kill-point —
    ``close.pre-commit`` by default: the whole close applied, bucket
    files written/renamed, header + LCL + publish-queue rows staged,
    COMMIT not yet run — a ``SimulatedProcessKill`` unwinds the node's
    entire in-flight close (the SQL transaction rolls back through the
    context managers, exactly what a restart observes) and
    ``Simulation.kill_node`` reaps it with NO graceful shutdown.  At
    ``restart_at`` the node comes back on its on-disk state; the boot
    self-check (main/selfcheck.py) must report ok/repaired before it
    rejoins.  Deterministic: (point, nth, owner) under the virtual
    clock's crank order — the class passes two-run replay."""

    at: float
    restart_at: float
    node: int
    point: str = "close.pre-commit"
    nth: int = 1

    def __post_init__(self):
        self.n_kills = 0
        self.selfcheck = None
        self._inj = None

    def arm(self, scn) -> None:
        from ..util import fs
        from .storagefaults import StorageFaultInjector

        key = scn.node_keys[self.node]

        def arm_injector():
            app = scn.sim.nodes.get(scn.sim._raw_key(key))
            if app is None:
                return
            inj = StorageFaultInjector(
                self.point, nth=self.nth, mode="raise",
                owner=app.database,
            )
            self._inj = inj
            fs.add_kill_hook(inj)
            scn.note(
                "armed hard-kill at %s (nth=%d) on node %d, t=%.1f"
                % (self.point, self.nth, self.node, scn.elapsed())
            )

        def restart():
            self.disarm()
            raw = scn.sim._raw_key(key)
            if raw not in scn.sim._crashed:
                scn.note(
                    "hard-kill never fired — node %d still alive at"
                    " restart deadline" % self.node
                )
                return
            self.n_kills += 1
            app = scn.sim.restart_node(key)
            self.selfcheck = app.last_selfcheck
            scn.mark_recovery_start()
            scn.note(
                "restarted hard-killed node %d at t=%.1f (selfcheck=%s)"
                % (
                    self.node,
                    scn.elapsed(),
                    (self.selfcheck or {}).get("status"),
                )
            )

        self._at(scn, self.at, arm_injector)
        self._at(scn, self.restart_at, restart)

    def disarm(self) -> None:
        from ..util import fs

        if self._inj is not None:
            fs.remove_kill_hook(self._inj)

    # Scenario.run verdict hook
    def verify_outcome(self, failures: List[str]) -> None:
        if self.n_kills < 1:
            failures.append(
                "hard_kill_mid_close: the kill-point injector never"
                " fired (no close crossed %s)" % self.point
            )
            return
        status = (self.selfcheck or {}).get("status")
        if status not in ("ok", "repaired"):
            failures.append(
                "hard_kill_mid_close: restarted node's boot self-check"
                " reported %r" % status
            )


@dataclass
class ByzantineFlood(Fault):
    """Invalid-signature envelope + transaction flood at volume, against
    ``target`` (node index), between ``at`` and ``until`` on a ``tick``
    cadence.  Envelopes ride the overlay's per-crank batch flush — the
    strict-gate fast-reject path under CALLER_OVERLAY — and reference
    made-up qset/txset hashes, so any regression of the eager reject
    would wedge the fetch plane (the scenario asserts it stays empty).
    Transactions carry garbage signatures through recv_transaction.

    The fault records every injected envelope's verify-cache key:
    ``assert_cache_unpolluted`` pins the no-latch-invalid contract
    (ISSUE r12 satellite 2) after the run — extended (ISSUE r15) to
    aggregate verdicts: storm keys may latch only True, never False.

    ``storm_per_tick`` adds the VALID-signature ballot storm (the
    expensive flood class: every envelope passes the strict gate and
    pays full curve math).  Storm envelopes are CONFIRM ballots from
    distinct ephemeral keys, pre-built and pre-signed at arm time (so
    injection never competes with the node for signing CPU), pinned to
    ``storm_slot`` — below the herder's slot bracket, so they exercise
    exactly the signature plane (the overlay flush verifies them; the
    herder drops them before the fetch/SCP planes) and land in ONE
    aggregation bucket per crank under SCP_SIG_SCHEME="ed25519-halfagg".
    They reference the target's real quorum-set hash: even a bracket
    straggler can never wedge the fetch plane."""

    at: float
    until: float
    target: int = 0
    # targeted flood (ISSUE r19): inject into EVERY node listed instead
    # of the single `target` — the tier-scoped flood shape (aim only at
    # tier-2 validators and assert tier-1's floor is undisturbed).  The
    # per-tick volumes apply PER TARGET.  None = [target].
    targets: Optional[List[int]] = None
    envelopes_per_tick: int = 25
    txs_per_tick: int = 5
    tick: float = 0.5
    storm_per_tick: int = 0
    storm_slot: int = 2
    # the storm signs from a FIXED byzantine committee (keys reused
    # across ticks, messages always distinct): realistic — an adversary
    # controls a validator set, not infinite fresh identities — and it
    # exercises the aggregate plane's validator-point cache the way a
    # real quorum does (A_i decode amortizes; only fresh R_i pay)
    storm_validators: int = 200

    def __post_init__(self):
        self.n_envelopes = 0
        self.n_txs = 0
        self.n_storm = 0
        self._cache_keys: List[bytes] = []
        self._storm_keys: List[bytes] = []
        self._storm_pool: List = []

    def arm(self, scn) -> None:
        self._rng = random.Random(scn.spec.seed ^ 0xF100D)
        if self.storm_per_tick:
            self._build_storm_pool(scn)
        self._at(scn, self.at, lambda: self._tick_fn(scn), slot='tick')

    def _build_storm_pool(self, scn) -> None:
        """Pre-sign the whole storm: one envelope per (tick, index) the
        window can consume, deterministic per scenario seed."""
        from ..crypto.keys import SecretKey, verify_cache
        from ..xdr.base import xdr_to_opaque
        from ..xdr.entries import EnvelopeType
        from ..xdr.scp import (
            SCPBallot,
            SCPEnvelope,
            SCPStatement,
            SCPStatementConfirm,
            SCPStatementPledges,
            SCPStatementType,
        )

        app = scn.sim.nodes[
            scn.sim._raw_key(scn.node_keys[self._target_indices()[0]])
        ]
        qset_hash = app.herder.scp.local_qset_hash
        n_ticks = int((self.until - self.at) / self.tick) + 2
        n = self.storm_per_tick * n_ticks * len(self._target_indices())
        base = 50_000_000 + (scn.spec.seed % 1000) * 100_000
        committee = [
            SecretKey.pseudo_random_for_testing(base + i)
            for i in range(self.storm_validators)
        ]
        for i in range(n):
            sk = committee[i % self.storm_validators]
            st = SCPStatement(
                nodeID=sk.get_public_key(),
                slotIndex=self.storm_slot,
                pledges=SCPStatementPledges(
                    SCPStatementType.SCP_ST_CONFIRM,
                    SCPStatementConfirm(
                        qset_hash,
                        1,
                        # NOT StellarValue-decodable: can never read as a
                        # txset dependency even off the bracket path
                        SCPBallot(1, b"storm %08d" % i),
                        1,
                    ),
                ),
            )
            payload = xdr_to_opaque(
                app.network_id, EnvelopeType.ENVELOPE_TYPE_SCP, st
            )
            env = SCPEnvelope(statement=st, signature=sk.sign(payload))
            self._storm_pool.append(env)
            self._storm_keys.append(
                verify_cache().key_for(
                    sk.public_raw, env.signature, payload
                )
            )

    def _target_indices(self) -> List[int]:
        return self.targets if self.targets is not None else [self.target]

    # -- injection ----------------------------------------------------------
    def _tick_fn(self, scn) -> None:
        if scn.elapsed_since_arm() >= self.until or scn.done:
            return
        for idx in self._target_indices():
            app = scn.sim.nodes.get(scn.sim._raw_key(scn.node_keys[idx]))
            if app is None:
                continue
            for _ in range(self.envelopes_per_tick):
                self._inject_envelope(app)
            for _ in range(self.txs_per_tick):
                self._inject_tx(app)
            for _ in range(self.storm_per_tick):
                if not self._storm_pool:
                    break
                app.overlay_manager.enqueue_scp_envelope(
                    self._storm_pool.pop()
                )
                self.n_storm += 1
        self._at(scn, self.tick, lambda: self._tick_fn(scn), slot='tick')

    def _forged_envelope(self, app):
        from ..crypto.keys import SecretKey
        from ..xdr.ledger import StellarValue
        from ..xdr.scp import (
            SCPEnvelope,
            SCPNomination,
            SCPStatement,
            SCPStatementPledges,
            SCPStatementType,
        )

        signer = SecretKey.pseudo_random_for_testing(
            30_000_000 + self._rng.randrange(1 << 30)
        )
        sv = StellarValue(
            txSetHash=self._rng.randbytes(32),
            closeTime=app.time_now() + 1,
            upgrades=[],
            ext=0,
        )
        nom = SCPNomination(
            quorumSetHash=self._rng.randbytes(32),
            votes=[sv.to_xdr()],
            accepted=[],
        )
        st = SCPStatement(
            nodeID=signer.get_public_key(),
            slotIndex=app.herder.next_consensus_ledger_index(),
            pledges=SCPStatementPledges(
                SCPStatementType.SCP_ST_NOMINATE, nom
            ),
        )
        return SCPEnvelope(statement=st, signature=self._rng.randbytes(64))

    def _inject_envelope(self, app) -> None:
        env = self._forged_envelope(app)
        pk, msg, sig = app.herder.envelope_verify_triple(env)
        from ..crypto.keys import verify_cache

        self._cache_keys.append(verify_cache().key_for(pk, sig, msg))
        app.overlay_manager.enqueue_scp_envelope(env)
        self.n_envelopes += 1

    def _inject_tx(self, app) -> None:
        from ..crypto.keys import SecretKey
        from ..tx import testutils as T
        from ..tx.frame import TransactionFrame
        import stellar_tpu.xdr as X

        src = SecretKey.pseudo_random_for_testing(
            40_000_000 + self._rng.randrange(1 << 30)
        )
        dst = SecretKey.pseudo_random_for_testing(
            40_000_000 + self._rng.randrange(1 << 30)
        )
        tx = X.Transaction(
            sourceAccount=src.get_public_key(),
            fee=100,
            seqNum=self._rng.randrange(1, 1 << 40),
            timeBounds=None,
            memo=X.Memo.none(),
            operations=[T.payment_op(dst, 1)],
            ext=0,
        )
        frame = TransactionFrame(
            app.network_id, X.TransactionEnvelope(tx, [])
        )
        frame.add_signature(src)
        # corrupt the signature AFTER signing: a structurally-plausible
        # envelope whose sig fails strict verification
        sig = bytearray(frame.envelope.signatures[0].signature)
        sig[0] ^= 0xFF
        frame.envelope.signatures[0].signature = bytes(sig)
        app.herder.recv_transaction(frame)
        self.n_txs += 1

    # -- oracles -------------------------------------------------------------
    def assert_cache_unpolluted(self) -> int:
        """The shared verify cache must hold NO verdict for any flooded
        invalid-sig envelope (the no-latch-invalid / quarantine-under-
        flood contract) — and, for the valid-sig storm, no FALSE verdict
        either (an aggregate-accepted bucket latches True only; a False
        anywhere means some path broke the valid-only latch contract).
        Returns how many keys were checked."""
        from ..crypto.keys import verify_cache

        latched = [
            v for v in verify_cache().peek_many(self._cache_keys)
            if v is not None
        ]
        if latched:
            raise AssertionError(
                "%d/%d flooded invalid-sig envelopes latched a verdict in"
                " the shared verify cache" % (len(latched), len(self._cache_keys))
            )
        storm_false = [
            v for v in verify_cache().peek_many(self._storm_keys)
            if v is False
        ]
        if storm_false:
            raise AssertionError(
                "%d/%d storm envelopes latched a FALSE verdict — the"
                " valid-only latch contract broke on the aggregate path"
                % (len(storm_false), len(self._storm_keys))
            )
        return len(self._cache_keys) + len(self._storm_keys)


@dataclass
class IngestFlood(Fault):
    """Byzantine invalid-signature TRANSACTION flood through the verify-
    at-ingest front door (ISSUE r20), against ``target``'s admission
    plane, between ``at`` and ``until`` on a ``tick`` cadence — mixed
    with the spec's legitimate LoadGenerator stream at a multiple of its
    arrival rate.

    Every flooded tx is a structurally-plausible payment FROM THE
    EXISTING ROOT ACCOUNT whose signature is corrupted after signing:
    the root account hint-matches, so ``candidate_signature_pairs`` is
    non-empty and the admission plane's edge shed is the defense that
    must fire (metered ``ingest.reject.badsig``) — before check_valid,
    account loads, or flood fan-out spend anything.  (ByzantineFlood's
    tx flood uses NONEXISTENT accounts, which die in check_valid before
    any signature work; this class attacks the signature plane itself.)

    The fault records every corrupted triple's verify-cache key:
    ``assert_cache_unpolluted`` pins the valid-only latch contract at
    the admission plane — a flood of distinct invalid-sig txs latches
    NOTHING into the shared cache, so it can never evict honest entries
    from the bounded LRU."""

    at: float
    until: float
    target: int = 0
    txs_per_tick: int = 100
    tick: float = 0.25

    def __post_init__(self):
        self.n_txs = 0
        self._cache_keys: List[bytes] = []
        self._scn = None

    def arm(self, scn) -> None:
        self._scn = scn
        self._rng = random.Random(scn.spec.seed ^ 0x1609E57)
        self._at(scn, self.at, lambda: self._tick_fn(scn), slot='tick')

    def _tick_fn(self, scn) -> None:
        if scn.elapsed_since_arm() >= self.until or scn.done:
            return
        app = scn.sim.nodes.get(
            scn.sim._raw_key(scn.node_keys[self.target])
        )
        if app is not None and getattr(app, "ingest", None) is not None:
            for _ in range(self.txs_per_tick):
                self._inject_tx(app)
        self._at(scn, self.tick, lambda: self._tick_fn(scn), slot='tick')

    def _inject_tx(self, app) -> None:
        from ..crypto.keys import SecretKey, verify_cache
        from ..tx import testutils as T
        from ..tx.frame import TransactionFrame
        import stellar_tpu.xdr as X

        root = T.root_key_for(app)
        dst = SecretKey.pseudo_random_for_testing(
            60_000_000 + self._rng.randrange(1 << 30)
        )
        tx = X.Transaction(
            sourceAccount=root.get_public_key(),
            fee=100,
            seqNum=self._rng.randrange(1, 1 << 40),
            timeBounds=None,
            memo=X.Memo.none(),
            operations=[T.payment_op(dst, 1)],
            ext=0,
        )
        frame = TransactionFrame(
            app.network_id, X.TransactionEnvelope(tx, [])
        )
        frame.add_signature(root)
        sig = bytearray(frame.envelope.signatures[0].signature)
        sig[0] ^= 0xFF
        frame.envelope.signatures[0].signature = bytes(sig)
        self._cache_keys.append(
            verify_cache().key_for(
                root.public_raw, bytes(sig), frame.get_contents_hash()
            )
        )
        app.ingest.submit(frame)
        self.n_txs += 1

    # -- oracles -------------------------------------------------------------
    def verify_outcome(self, failures: List[str]) -> None:
        """Every injected tx must have been shed at the ingest edge: the
        root source hint-matches, so the candidate triples are non-empty
        and all-invalid — a leak means signature work (or worse, a queue
        seat) was spent on provably-unauthorized traffic."""
        if self.n_txs == 0:
            failures.append("ingest_flood: no flood txs were injected")
            return
        planes = [
            app.ingest
            for app in self._scn.sim.nodes.values()
            if getattr(app, "ingest", None) is not None
        ]
        if not planes:
            failures.append("ingest_flood: no node built an IngestPlane")
            return
        for p in planes:
            # drain a final partial batch so every injected tx is decided
            # (the scoreboard snapshot already closed; this only feeds
            # the exact-count oracle below)
            p.flush_now()
        shed = sum(p.m_reject_badsig.count for p in planes)
        if shed != self.n_txs:
            failures.append(
                "ingest_flood: %d invalid-sig txs injected but %d shed at"
                " the edge — the admission plane leaked or double-counted"
                % (self.n_txs, shed)
            )

    def assert_cache_unpolluted(self) -> int:
        """The shared verify cache must hold NO verdict for any flooded
        invalid-sig tx triple — the valid-only latch contract at the
        admission plane.  Returns how many keys were checked."""
        from ..crypto.keys import verify_cache

        latched = [
            v for v in verify_cache().peek_many(self._cache_keys)
            if v is not None
        ]
        if latched:
            raise AssertionError(
                "%d/%d flooded invalid-sig ingest txs latched a verdict"
                " in the shared verify cache — the valid-only latch"
                " contract broke at the admission plane"
                % (len(latched), len(self._cache_keys))
            )
        return len(self._cache_keys)


@dataclass
class SlowReader(Fault):
    """The overlay survival plane's defining adversary (ISSUE r17): one
    peer drains its links at a fraction of the offered rate — the
    crashed-but-connected / underpowered / hostile slow reader.  Every
    link touching node ``node`` gets a ``FaultProfile(drain=...)`` byte
    -rate cap at ``at`` (whole frames, in order, no flaps), so its
    NEIGHBORS' transports back up: their send queues shed FLOOD toward
    it, keep CRITICAL first, and — once the CRITICAL head-of-line age
    crosses STRAGGLER_STALL_MS — disconnect it with ERR_LOAD inside the
    stall budget.  The link doctor re-establishes the pair (profile
    carried over), so the cycle repeats for the whole window; the
    consensus floor is asserted over the OTHER nodes."""

    at: float
    node: int
    drain_bytes_per_sec: float = 4096.0
    heal_at: Optional[float] = None

    def arm(self, scn) -> None:
        key = scn.node_keys[self.node]
        raw = scn.sim._raw_key(key)
        links = [
            (ia, ib) for (ia, ib) in scn.sim.links if raw in (ia, ib)
        ]

        def degrade():
            for ia, ib in links:
                scn.sim.set_link_faults(
                    FaultProfile(drain=self.drain_bytes_per_sec), ia, ib
                )
            scn.note(
                "slow reader: node %d drains at %d B/s from t=%.1f"
                % (self.node, self.drain_bytes_per_sec, scn.elapsed())
            )

        self._at(scn, self.at, degrade)
        if self.heal_at is not None:
            def restore():
                for ia, ib in links:
                    scn.sim.set_link_faults(FaultProfile(), ia, ib)
                scn.sim.ensure_links()
                scn.mark_recovery_start()
                scn.note("slow reader healed at t=%.1f" % scn.elapsed())

            self._at(scn, self.heal_at, restore)


@dataclass
class OverloadStorm(Fault):
    """Saturating tx-broadcast overload (ISSUE r17): every link is
    drain-capped at ``drain_bytes_per_sec`` and node ``source`` floods
    distinct invalid-signature TRANSACTION messages at several times that
    capacity between ``at`` and ``until``.  Without per-peer send-side
    bounding this queues consensus traffic behind the flood and grows the
    write buffers without bound; with the survival plane on, FLOOD sheds
    (metered), CRITICAL jumps every queue, the per-peer byte high-water
    stays under OVERLAY_SENDQ_BYTES, and the liveness floor holds.  The
    storm pool is pre-built at arm time from the scenario seed
    (deterministic replay; injection never competes for signing CPU)."""

    at: float
    until: float
    source: int = 0
    msgs_per_tick: int = 30
    tick: float = 0.25
    drain_bytes_per_sec: float = 16384.0
    # targeted overload (ISSUE r19): cap only the links TOUCHING these
    # node indices instead of every link — the tier-scoped storm (tier-2
    # links saturate and shed; tier-1's core links stay clean, so its
    # consensus floor is the undisturbed one).  None = every link.
    drain_nodes: Optional[List[int]] = None

    def __post_init__(self):
        self.n_storm = 0
        self._pool: List = []

    def _capped_links(self, scn) -> Optional[List[tuple]]:
        if self.drain_nodes is None:
            return None
        raws = {scn.sim._raw_key(scn.node_keys[i]) for i in self.drain_nodes}
        return [
            (ia, ib) for (ia, ib) in scn.sim.links if raws & {ia, ib}
        ]

    def arm(self, scn) -> None:
        self._rng = random.Random(scn.spec.seed ^ 0x570A4)
        self._build_pool(scn)

        def degrade():
            links = self._capped_links(scn)
            profile = FaultProfile(drain=self.drain_bytes_per_sec)
            if links is None:
                scn.sim.set_link_faults(profile)
            else:
                for ia, ib in links:
                    scn.sim.set_link_faults(profile, ia, ib)
            scn.note(
                "overload storm: %s drain at %d B/s, %d tx/tick"
                % (
                    "all links"
                    if links is None
                    else "%d links @ nodes %s" % (len(links), self.drain_nodes),
                    self.drain_bytes_per_sec,
                    self.msgs_per_tick,
                )
            )
            self._tick_fn(scn)

        def restore():
            links = self._capped_links(scn)
            if links is None:
                scn.sim.set_link_faults(FaultProfile())
            else:
                for ia, ib in links:
                    scn.sim.set_link_faults(FaultProfile(), ia, ib)
            scn.sim.ensure_links()
            scn.note("overload storm over at t=%.1f" % scn.elapsed())

        self._at(scn, self.at, degrade)
        self._at(scn, self.until, restore)

    def _build_pool(self, scn) -> None:
        """Distinct structurally-valid transactions with corrupted
        signatures (receivers fast-reject at the strict gate), packed
        once each — the flood rides broadcast_message's pack-once
        fan-out, so the storm's cost lands on the SEND queues."""
        from ..crypto.keys import SecretKey
        from ..tx import testutils as T
        from ..tx.frame import TransactionFrame
        import stellar_tpu.xdr as X

        app = scn.sim.nodes[scn.sim._raw_key(scn.node_keys[self.source])]
        n_ticks = int((self.until - self.at) / self.tick) + 2
        for i in range(self.msgs_per_tick * n_ticks):
            src = SecretKey.pseudo_random_for_testing(
                60_000_000 + self._rng.randrange(1 << 30)
            )
            dst = SecretKey.pseudo_random_for_testing(
                60_000_000 + self._rng.randrange(1 << 30)
            )
            tx = X.Transaction(
                sourceAccount=src.get_public_key(),
                fee=100,
                seqNum=self._rng.randrange(1, 1 << 40),
                timeBounds=None,
                memo=X.Memo.none(),
                operations=[T.payment_op(dst, 1)],
                ext=0,
            )
            frame = TransactionFrame(
                app.network_id, X.TransactionEnvelope(tx, [])
            )
            frame.add_signature(src)
            sig = bytearray(frame.envelope.signatures[0].signature)
            sig[0] ^= 0xFF
            frame.envelope.signatures[0].signature = bytes(sig)
            self._pool.append(frame.to_stellar_message())

    def _tick_fn(self, scn) -> None:
        if scn.elapsed_since_arm() >= self.until or scn.done:
            return
        app = scn.sim.nodes.get(
            scn.sim._raw_key(scn.node_keys[self.source])
        )
        if app is not None:
            for _ in range(self.msgs_per_tick):
                if not self._pool:
                    break
                app.overlay_manager.broadcast_message(self._pool.pop())
                self.n_storm += 1
        self._at(scn, self.tick, lambda: self._tick_fn(scn), slot='tick')


@dataclass
class PartitionUntilCheckpoint(Fault):
    """The catchup-under-load shape: partition ``lagger`` off at ``at``
    and heal only once the majority's LCL has crossed
    ``heal_after_ledger`` — far enough that the lagger's SCP gap exceeds
    MAX_SLOTS_TO_REMEMBER and it must catch up from the history archive
    while the network keeps closing under load."""

    at: float
    heal_after_ledger: int
    lagger: int
    poll: float = 0.5

    def arm(self, scn) -> None:
        lag_key = scn.node_keys[self.lagger]
        rest = [k for i, k in enumerate(scn.node_keys) if i != self.lagger]

        def split():
            scn.sim.partition(rest, [lag_key])
            scn.note("catchup-lag partition at t=%.1f" % scn.elapsed())
            self._at(scn, self.poll, poll, slot='poll')

        def poll():
            if scn.done:
                return
            majority = max(
                scn.sim.get_node(k).ledger_manager.get_last_closed_ledger_num()
                for k in rest
            )
            if majority >= self.heal_after_ledger:
                scn.sim.heal()
                scn.mark_recovery_start()
                scn.note(
                    "heal at t=%.1f (majority lcl=%d)"
                    % (scn.elapsed(), majority)
                )
            else:
                self._at(scn, self.poll, poll, slot='poll')

        self._at(scn, self.at, split)
