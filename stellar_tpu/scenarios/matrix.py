"""The scenario matrix — named shapes per fault class.

SMALL shapes run in tier-1 (each ≥10 ledgers closed in the chaos window,
invariants all-on, deterministic seeded replay for the virtual-clock
classes); BIG shapes are the same programs at core-and-tier ring scale
and longer fault windows, behind ``-m slow`` / the relay_watch
``scenario_liveness_r12`` step's ``--matrix big`` mode.

Fault classes (ROADMAP #5 / ISSUE r12 acceptance):
- ``partition_heal``    — majority/minority split, heal, lagging node
                          replays the missed slots through ClosePipeline
- ``byzantine_flood``   — invalid-signature envelope + tx flood at volume
                          (strict-gate fast-reject, CALLER_OVERLAY plane)
- ``byzantine_flood_halfagg`` — the same invalid flood plus a VALID-
                          signature ballot storm under
                          SCP_SIG_SCHEME="ed25519-halfagg" (ISSUE r15):
                          storm buckets verify as aggregate MSM checks;
                          the paired per-signature A/B compares scheme
                          verify wall at the same rate
- ``slow_lossy``        — latency + loss/duplicate/reorder/damage on every
                          link; flapped connections re-established by the
                          link doctor
- ``crash_restart``     — validator hard-crash with a 3-of-3 quorum (the
                          network halts) and restart from its on-disk
                          state; recovery time measured
- ``hard_kill_mid_close`` — a REAL kill (ISSUE r18, not graceful_stop):
                          a storage-fault injector unwinds the node's
                          in-flight close at a named durable-write
                          kill-point (close.pre-commit) and reaps it
                          with no shutdown hooks; the restart must pass
                          the boot self-check (main/selfcheck.py)
                          before consensus recovers
- ``catchup_load``      — node partitioned past MAX_SLOTS_TO_REMEMBER
                          while the network closes through checkpoint
                          boundaries under load; rejoin via history-archive
                          catchup (REAL_TIME clock, like the history suite)
- ``slow_reader``       — one tier peer drains its links at a fraction of
                          the offered rate (ISSUE r17): neighbors shed
                          FLOOD toward it, never CRITICAL, and disconnect
                          it (ERR_LOAD) inside the straggler stall budget;
                          consensus floor asserted over everyone else
- ``overload_storm``    — tx flood at several times total drain capacity
                          across all links: FLOOD sheds at every queue,
                          CRITICAL jumps them, queue-byte high-water stays
                          under OVERLAY_SENDQ_BYTES, liveness floor holds
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..overlay.loopback import FaultProfile
from .faults import (
    ByzantineFlood,
    CrashRestart,
    HardKillMidClose,
    OverloadStorm,
    Partition,
    PartitionUntilCheckpoint,
    SlowLossyLinks,
    SlowReader,
)
from .scenario import Scenario, ScenarioResult, ScenarioSpec

FAULT_CLASSES = (
    "partition_heal",
    "byzantine_flood",
    "byzantine_flood_halfagg",
    "slow_lossy",
    "crash_restart",
    "hard_kill_mid_close",
    "catchup_load",
    "slow_reader",
    "overload_storm",
)


def small_specs(seed: int = 1) -> Dict[str, ScenarioSpec]:
    """Tier-1 shapes: 3 nodes, ≥10 chaos-window ledgers each."""
    return {
        "partition_heal": ScenarioSpec(
            name="partition_heal_small",
            fault_class="partition_heal",
            n_nodes=3,
            threshold=2,  # 2-of-3: the majority side must keep closing
            seed=seed,
            # heal at exactly 3 ledgers of lag: within the SCP state
            # window (send_scp_state_to_peer replays max-3..max), so the
            # minority node replays the missed slots from peers' state —
            # the reentrant-externalize ClosePipeline backlog; heal_at is
            # the backstop if leader-election stalls starve the majority
            faults=[
                Partition(
                    at=0.5, heal_at=12.0, groups=[[0, 1], [2]], heal_lag=3
                )
            ],
            load_backlog_ledgers=2,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            max_recovery_ms=15_000,
            timeout=180.0,
        ),
        "byzantine_flood": ScenarioSpec(
            name="byzantine_flood_small",
            fault_class="byzantine_flood",
            n_nodes=3,
            seed=seed,
            faults=[
                ByzantineFlood(
                    at=0.5, until=7.0, target=0,
                    envelopes_per_tick=25, txs_per_tick=5, tick=0.4,
                )
            ],
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        # the aggregate-scheme flood leg (ISSUE r15): the SAME invalid
        # flood plus a VALID-signature ballot storm — the expensive flood
        # class, where every envelope passes the strict gate and pays
        # full curve math.  Under "ed25519-halfagg" each crank's storm
        # bucket verifies as ONE aggregate MSM check; the paired A/B in
        # tests/test_scenarios.py runs this identical spec under
        # "ed25519" and asserts the per-signature path pays >= ~2x the
        # scheme verify wall at the same rate (the wall that wedges a
        # flooded crank), while this leg holds the same liveness floor
        # with the cache provably clean of aggregate-path pollution.
        "byzantine_flood_halfagg": ScenarioSpec(
            name="byzantine_flood_halfagg_small",
            fault_class="byzantine_flood_halfagg",
            n_nodes=3,
            seed=seed,
            scp_sig_scheme="ed25519-halfagg",
            faults=[
                ByzantineFlood(
                    at=0.5, until=7.0, target=0,
                    envelopes_per_tick=10, txs_per_tick=2, tick=0.4,
                    storm_per_tick=240,
                )
            ],
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        "slow_lossy": ScenarioSpec(
            name="slow_lossy_small",
            fault_class="slow_lossy",
            n_nodes=3,
            seed=seed,
            faults=[
                SlowLossyLinks(
                    at=0.5,
                    profile=FaultProfile(
                        drop=0.005, duplicate=0.005, reorder=0.01,
                        damage=0.002, latency=0.05,
                    ),
                )
            ],
            # every fault roll that fires flaps the CONNECTION (MAC
            # sequence break) and costs a latency-taxed re-handshake, so
            # liveness degrades by design here; the floor asserts the
            # network still grinds forward, not that it stays fast
            doctor_tick=0.5,
            target_ledgers=14,
            min_ledgers_per_sec=0.04,
            timeout=400.0,
        ),
        "crash_restart": ScenarioSpec(
            name="crash_restart_small",
            fault_class="crash_restart",
            n_nodes=3,
            threshold=3,  # 3-of-3: the crash halts consensus outright
            seed=seed,
            disk_db=True,
            faults=[CrashRestart(at=2.0, restart_at=8.0, node=2)],
            target_ledgers=14,
            min_ledgers_per_sec=0.1,
            max_recovery_ms=20_000,
            timeout=240.0,
        ),
        # the storage survival plane's chaos class (ISSUE r18): a REAL
        # kill — the injector unwinds node 2's close at close.pre-commit
        # (every durable close artifact staged, COMMIT not run) and the
        # node is reaped with NO graceful shutdown; 3-of-3 quorum so the
        # kill halts consensus outright, and the restart must pass the
        # boot self-check before recovery is measured.  Deterministic
        # two-run replay like crash_restart.
        "hard_kill_mid_close": ScenarioSpec(
            name="hard_kill_mid_close_small",
            fault_class="hard_kill_mid_close",
            n_nodes=3,
            threshold=3,
            seed=seed,
            disk_db=True,
            faults=[HardKillMidClose(at=2.0, restart_at=8.0, node=2)],
            target_ledgers=14,
            min_ledgers_per_sec=0.1,
            max_recovery_ms=20_000,
            timeout=240.0,
        ),
        # the overlay survival plane's two shapes (ISSUE r17).  Caps are
        # deliberately SMALL (32 KiB vs the 2 MiB production default) so
        # the defenses engage at test-scale traffic; every knob is a
        # per-node Config override through the spec.
        "slow_reader": ScenarioSpec(
            name="slow_reader_small",
            fault_class="slow_reader",
            # 3-core mesh + 2-node tier ring; the slow reader is tier
            # node 4 (links to tier node 3 + core node 1): its quorum
            # slice rides the core, so disconnecting it costs nobody
            # else a vote
            topology="core_and_tier",
            n_nodes=3,
            tier_n=2,
            seed=seed,
            sendq_bytes=32 * 1024,
            sendq_flood_msgs=64,
            straggler_stall_ms=1500,
            faults=[
                SlowReader(at=0.5, node=4, drain_bytes_per_sec=2048)
            ],
            load_txs=600,
            load_rate=50,
            # the straggler cannot meet the floor it is built to miss
            liveness_exclude=[4],
            expect_straggler_disconnect=True,
            min_flood_sheds=1,
            assert_high_water_bounded=True,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=240.0,
        ),
        "overload_storm": ScenarioSpec(
            name="overload_storm_small",
            fault_class="overload_storm",
            n_nodes=3,
            seed=seed,
            sendq_bytes=32 * 1024,
            sendq_flood_msgs=48,
            straggler_stall_ms=2500,
            faults=[
                OverloadStorm(
                    at=0.5, until=8.0, source=0,
                    msgs_per_tick=30, tick=0.25,
                    drain_bytes_per_sec=16384,
                )
            ],
            # light legit load: the storm supplies the flood pressure;
            # txsets stay small enough that FETCH replies clear the
            # drain-capped links
            load_accounts=4,
            load_txs=120,
            load_rate=15,
            min_flood_sheds=10,
            assert_high_water_bounded=True,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=240.0,
        ),
        "catchup_load": ScenarioSpec(
            name="catchup_load_small",
            fault_class="catchup_load",
            n_nodes=3,
            threshold=2,  # majority keeps closing while the lagger is cut
            seed=seed,
            clock_mode="real",  # archive get/put are real subprocesses
            disk_db=True,
            archives=True,
            checkpoint_frequency=8,
            faults=[
                PartitionUntilCheckpoint(
                    at=1.0, heal_after_ledger=12, lagger=2
                )
            ],
            load_backlog_ledgers=1,
            target_ledgers=18,
            # real-clock scenario: wall time includes archive subprocess
            # latency; the floor stays conservative
            min_ledgers_per_sec=0.05,
            timeout=150.0,
        ),
    }


def big_specs(seed: int = 1) -> Dict[str, ScenarioSpec]:
    """Core-and-tier ring scale (-m slow / scenario_liveness_r12 --matrix
    big): 4-core + 4-tier ring, longer fault windows, bigger floods."""
    small = small_specs(seed)
    out: Dict[str, ScenarioSpec] = {}
    for cls, spec in small.items():
        big = ScenarioSpec(**{**spec.__dict__})
        big.name = spec.name.replace("_small", "_big")
        big.topology = "core_and_tier"
        big.n_nodes = 4
        big.tier_n = 4
        big.threshold = None
        big.target_ledgers = spec.target_ledgers + 16
        big.timeout = spec.timeout * 3
        big.load_txs = 1200
        if cls == "byzantine_flood":
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, target=0,
                    envelopes_per_tick=100, txs_per_tick=20, tick=0.4,
                )
            ]
        elif cls == "byzantine_flood_halfagg":
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, target=0,
                    envelopes_per_tick=40, txs_per_tick=8, tick=0.4,
                    storm_per_tick=400,
                )
            ]
        elif cls == "partition_heal":
            # cut the ring AND a core node off the rest
            big.faults = [
                Partition(
                    at=0.5, heal_at=4.0,
                    groups=[[0, 1, 2], [3, 4, 5, 6, 7]],
                )
            ]
            big.max_recovery_ms = 30_000
        elif cls == "crash_restart":
            # 8-node shape keeps BFT majority; crash a TIER node so ring
            # consensus must route around it, then recover on restart
            big.faults = [CrashRestart(at=2.0, restart_at=10.0, node=5)]
            big.threshold = None
            big.max_recovery_ms = 40_000
        elif cls == "hard_kill_mid_close":
            # hard-kill a TIER node mid-close while the ring keeps
            # closing; the restart must self-check + replay the gap
            big.faults = [
                HardKillMidClose(at=2.0, restart_at=10.0, node=5)
            ]
            big.threshold = None
            big.max_recovery_ms = 40_000
        elif cls == "catchup_load":
            big.faults = [
                PartitionUntilCheckpoint(
                    at=1.0, heal_after_ledger=20, lagger=7
                )
            ]
            big.target_ledgers = 26
        elif cls == "slow_reader":
            # 4-core + 4-tier ring; the slow reader is the last tier node
            big.faults = [
                SlowReader(at=0.5, node=7, drain_bytes_per_sec=2048)
            ]
            big.liveness_exclude = [7]
        elif cls == "overload_storm":
            big.faults = [
                OverloadStorm(
                    at=0.5, until=20.0, source=0,
                    msgs_per_tick=80, tick=0.25,
                    drain_bytes_per_sec=16384,
                )
            ]
            big.load_txs = 300
        out[cls] = big
    return out


def run_matrix(
    matrix: str = "small",
    only: Optional[List[str]] = None,
    seed: int = 1,
    workdir: Optional[str] = None,
) -> List[ScenarioResult]:
    specs = small_specs(seed) if matrix == "small" else big_specs(seed)
    results = []
    for cls in FAULT_CLASSES:
        if only and cls not in only:
            continue
        results.append(Scenario(specs[cls], workdir=workdir).run())
    return results
