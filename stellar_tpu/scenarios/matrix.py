"""The scenario matrix — named shapes per fault class.

SMALL shapes run in tier-1 (each ≥10 ledgers closed in the chaos window,
invariants all-on, deterministic seeded replay for the virtual-clock
classes); BIG shapes are the same programs at core-and-tier ring scale
and longer fault windows, behind ``-m slow`` / the relay_watch
``scenario_liveness_r12`` step's ``--matrix big`` mode.

Fault classes (ROADMAP #5 / ISSUE r12 acceptance):
- ``partition_heal``    — majority/minority split, heal, lagging node
                          replays the missed slots through ClosePipeline
- ``byzantine_flood``   — invalid-signature envelope + tx flood at volume
                          (strict-gate fast-reject, CALLER_OVERLAY plane)
- ``byzantine_flood_halfagg`` — the same invalid flood plus a VALID-
                          signature ballot storm under
                          SCP_SIG_SCHEME="ed25519-halfagg" (ISSUE r15):
                          storm buckets verify as aggregate MSM checks;
                          the paired per-signature A/B compares scheme
                          verify wall at the same rate
- ``slow_lossy``        — latency + loss/duplicate/reorder/damage on every
                          link; flapped connections re-established by the
                          link doctor
- ``crash_restart``     — validator hard-crash with a 3-of-3 quorum (the
                          network halts) and restart from its on-disk
                          state; recovery time measured
- ``hard_kill_mid_close`` — a REAL kill (ISSUE r18, not graceful_stop):
                          a storage-fault injector unwinds the node's
                          in-flight close at a named durable-write
                          kill-point (close.pre-commit) and reaps it
                          with no shutdown hooks; the restart must pass
                          the boot self-check (main/selfcheck.py)
                          before consensus recovers
- ``catchup_load``      — node partitioned past MAX_SLOTS_TO_REMEMBER
                          while the network closes through checkpoint
                          boundaries under load; rejoin via history-archive
                          catchup (REAL_TIME clock, like the history suite)
- ``slow_reader``       — one tier peer drains its links at a fraction of
                          the offered rate (ISSUE r17): neighbors shed
                          FLOOD toward it, never CRITICAL, and disconnect
                          it (ERR_LOAD) inside the straggler stall budget;
                          consensus floor asserted over everyone else
- ``overload_storm``    — tx flood at several times total drain capacity
                          across all links: FLOOD sheds at every queue,
                          CRITICAL jumps them, queue-byte high-water stays
                          under OVERLAY_SENDQ_BYTES, liveness floor holds
- ``clock_skew_within_slip`` — per-node clock offsets INSIDE the
                          MAX_TIME_SLIP_SECONDS acceptance window (static
                          +30s on one node, slow drift on another): the
                          closeTime gates must stay silent (0 metered
                          rejections) and the consensus floor must hold —
                          the tolerance the protocol promises
- ``clock_skew_beyond_slip`` — an NTP-step skew BEYOND the slip window:
                          the skewed node rejects the quorum's values
                          (herder.value.reject-closetime-future metered,
                          ≥1 asserted) and stalls while the unskewed
                          majority keeps its floor; when the skew heals
                          (lag-polled, inside the SCP replay window) the
                          node replays the missed slots and recovery is
                          measured against a floor
- ``asymmetric_partition`` — ONE-WAY isolation of a tier-1 node (frames
                          toward it dropped pre-MAC, its own frames keep
                          flowing — the half-open connection the
                          symmetric groups API cannot express): links
                          never flap, the deaf node stalls, heal resumes
                          the same connections and recovery is measured
- ``targeted_flood_tier2`` — byzantine flood + drain-capped overload
                          storm aimed ONLY at tier-2 nodes of a
                          core-and-tier ring: tier-1 holds its
                          undisturbed floor, tier-2 sheds FLOOD through
                          the r17 send queues, 0 CRITICAL sheds anywhere
                          (per-tier scoreboard aggregates carry the
                          verdict)
- ``byzantine_flood_tpu`` — the byzantine flood with the DEVICE batch
                          plane engaged (SIGNATURE_BACKEND="tpu",
                          cutover 0): every overlay flush rides the
                          verify kernel; tier-1 runs the XLA-CPU oracle
                          and the CALLER_OVERLAY wedge-latch contract is
                          pinned under flood
- ``ingest_flood``      — sustained LoadGenerator stream + byzantine
                          invalid-sig TX flood through the verify-at-
                          ingest front door at 10x the legit arrival
                          rate (ISSUE r20): every flooded tx sheds at
                          the edge (ingest.reject.badsig) before
                          check_valid or fan-out, the verify cache
                          stays clean (valid-only latch), the liveness
                          floor holds, two-run deterministic replay
- ``tcp_scale``         — the 100+ node core-and-tier shape OVER REAL
                          TCP SOCKETS (big matrix / -m slow only): the
                          sendqueue + pack-once fan-out planes at
                          production-transport scale, ≥5 ledgers
                          externalized with per-tier aggregates
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..overlay.loopback import FaultProfile
from .faults import (
    AsymmetricPartition,
    ByzantineFlood,
    ClockSkew,
    CrashRestart,
    HardKillMidClose,
    IngestFlood,
    OverloadStorm,
    Partition,
    PartitionUntilCheckpoint,
    SlowLossyLinks,
    SlowReader,
)
from .scenario import Scenario, ScenarioResult, ScenarioSpec

FAULT_CLASSES = (
    "partition_heal",
    "byzantine_flood",
    "byzantine_flood_halfagg",
    "byzantine_flood_tpu",
    "ingest_flood",
    "slow_lossy",
    "crash_restart",
    "hard_kill_mid_close",
    "catchup_load",
    "slow_reader",
    "overload_storm",
    "clock_skew_within_slip",
    "clock_skew_beyond_slip",
    "asymmetric_partition",
    "targeted_flood_tier2",
    "tcp_scale",
)


def small_specs(seed: int = 1) -> Dict[str, ScenarioSpec]:
    """Tier-1 shapes: 3 nodes, ≥10 chaos-window ledgers each."""
    return {
        "partition_heal": ScenarioSpec(
            name="partition_heal_small",
            fault_class="partition_heal",
            n_nodes=3,
            threshold=2,  # 2-of-3: the majority side must keep closing
            seed=seed,
            # heal at exactly 3 ledgers of lag: within the SCP state
            # window (send_scp_state_to_peer replays max-3..max), so the
            # minority node replays the missed slots from peers' state —
            # the reentrant-externalize ClosePipeline backlog; heal_at is
            # the backstop if leader-election stalls starve the majority
            faults=[
                Partition(
                    at=0.5, heal_at=12.0, groups=[[0, 1], [2]], heal_lag=3
                )
            ],
            load_backlog_ledgers=2,
            # cap well under load_txs: the 400-tx load spreads over ≥4
            # consecutive FULL closes instead of one uncapped burst slot,
            # so the healed node's replay window carries txful sets
            # wherever the ready-sweep boundaries land (the dispatched≥1
            # assertion must not hinge on which slot one burst hits)
            max_tx_per_ledger=100,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            max_recovery_ms=15_000,
            timeout=180.0,
        ),
        "byzantine_flood": ScenarioSpec(
            name="byzantine_flood_small",
            fault_class="byzantine_flood",
            n_nodes=3,
            seed=seed,
            faults=[
                ByzantineFlood(
                    at=0.5, until=7.0, target=0,
                    envelopes_per_tick=25, txs_per_tick=5, tick=0.4,
                )
            ],
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        # the aggregate-scheme flood leg (ISSUE r15): the SAME invalid
        # flood plus a VALID-signature ballot storm — the expensive flood
        # class, where every envelope passes the strict gate and pays
        # full curve math.  Under "ed25519-halfagg" each crank's storm
        # bucket verifies as ONE aggregate MSM check; the paired A/B in
        # tests/test_scenarios.py runs this identical spec under
        # "ed25519" and asserts the per-signature path pays >= ~2x the
        # scheme verify wall at the same rate (the wall that wedges a
        # flooded crank), while this leg holds the same liveness floor
        # with the cache provably clean of aggregate-path pollution.
        "byzantine_flood_halfagg": ScenarioSpec(
            name="byzantine_flood_halfagg_small",
            fault_class="byzantine_flood_halfagg",
            n_nodes=3,
            seed=seed,
            scp_sig_scheme="ed25519-halfagg",
            faults=[
                ByzantineFlood(
                    at=0.5, until=7.0, target=0,
                    envelopes_per_tick=10, txs_per_tick=2, tick=0.4,
                    storm_per_tick=240,
                )
            ],
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        "slow_lossy": ScenarioSpec(
            name="slow_lossy_small",
            fault_class="slow_lossy",
            n_nodes=3,
            seed=seed,
            faults=[
                SlowLossyLinks(
                    at=0.5,
                    profile=FaultProfile(
                        drop=0.005, duplicate=0.005, reorder=0.01,
                        damage=0.002, latency=0.05,
                    ),
                )
            ],
            # every fault roll that fires flaps the CONNECTION (MAC
            # sequence break) and costs a latency-taxed re-handshake, so
            # liveness degrades by design here; the floor asserts the
            # network still grinds forward, not that it stays fast
            doctor_tick=0.5,
            target_ledgers=14,
            min_ledgers_per_sec=0.04,
            timeout=400.0,
        ),
        "crash_restart": ScenarioSpec(
            name="crash_restart_small",
            fault_class="crash_restart",
            n_nodes=3,
            threshold=3,  # 3-of-3: the crash halts consensus outright
            seed=seed,
            disk_db=True,
            faults=[CrashRestart(at=2.0, restart_at=8.0, node=2)],
            target_ledgers=14,
            min_ledgers_per_sec=0.1,
            max_recovery_ms=20_000,
            timeout=240.0,
        ),
        # the storage survival plane's chaos class (ISSUE r18): a REAL
        # kill — the injector unwinds node 2's close at close.pre-commit
        # (every durable close artifact staged, COMMIT not run) and the
        # node is reaped with NO graceful shutdown; 3-of-3 quorum so the
        # kill halts consensus outright, and the restart must pass the
        # boot self-check before recovery is measured.  Deterministic
        # two-run replay like crash_restart.
        "hard_kill_mid_close": ScenarioSpec(
            name="hard_kill_mid_close_small",
            fault_class="hard_kill_mid_close",
            n_nodes=3,
            threshold=3,
            seed=seed,
            disk_db=True,
            faults=[HardKillMidClose(at=2.0, restart_at=8.0, node=2)],
            target_ledgers=14,
            min_ledgers_per_sec=0.1,
            max_recovery_ms=20_000,
            timeout=240.0,
        ),
        # the overlay survival plane's two shapes (ISSUE r17).  Caps are
        # deliberately SMALL (32 KiB vs the 2 MiB production default) so
        # the defenses engage at test-scale traffic; every knob is a
        # per-node Config override through the spec.
        "slow_reader": ScenarioSpec(
            name="slow_reader_small",
            fault_class="slow_reader",
            # 3-core mesh + 2-node tier ring; the slow reader is tier
            # node 4 (links to tier node 3 + core node 1): its quorum
            # slice rides the core, so disconnecting it costs nobody
            # else a vote
            topology="core_and_tier",
            n_nodes=3,
            tier_n=2,
            seed=seed,
            sendq_bytes=32 * 1024,
            sendq_flood_msgs=64,
            straggler_stall_ms=1500,
            faults=[
                SlowReader(at=0.5, node=4, drain_bytes_per_sec=2048)
            ],
            load_txs=600,
            load_rate=50,
            # the straggler cannot meet the floor it is built to miss
            liveness_exclude=[4],
            expect_straggler_disconnect=True,
            min_flood_sheds=1,
            assert_high_water_bounded=True,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=240.0,
        ),
        "overload_storm": ScenarioSpec(
            name="overload_storm_small",
            fault_class="overload_storm",
            n_nodes=3,
            seed=seed,
            sendq_bytes=32 * 1024,
            sendq_flood_msgs=48,
            straggler_stall_ms=2500,
            faults=[
                OverloadStorm(
                    at=0.5, until=8.0, source=0,
                    msgs_per_tick=30, tick=0.25,
                    drain_bytes_per_sec=16384,
                )
            ],
            # light legit load: the storm supplies the flood pressure;
            # txsets stay small enough that FETCH replies clear the
            # drain-capped links
            load_accounts=4,
            load_txs=120,
            load_rate=15,
            min_flood_sheds=10,
            assert_high_water_bounded=True,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=240.0,
        ),
        # the time-and-asymmetry plane (ISSUE r19).  Within-slip: one
        # node statically +30s ahead (half the 60s MAX_TIME_SLIP window)
        # and another drifting at +20ms/s — tolerable skew the protocol
        # promises to absorb: the closeTime gates must meter NOTHING and
        # the floor is the undisturbed one.
        "clock_skew_within_slip": ScenarioSpec(
            name="clock_skew_within_slip_small",
            fault_class="clock_skew_within_slip",
            n_nodes=3,
            threshold=2,
            seed=seed,
            faults=[
                ClockSkew(at=0.5, node=2, offset=30.0),
                ClockSkew(at=0.5, node=1, drift_per_sec=0.02),
            ],
            max_slip_rejects=0,
            target_ledgers=14,
            min_ledgers_per_sec=0.5,
            timeout=180.0,
        ),
        # Beyond-slip: node 2's clock NTP-steps 90s BEHIND shortly after
        # the window opens, so every honest value reads >60s in the
        # future through its skewed gate — it stalls, metering
        # reject-closetime-future, while the 2-of-3 majority keeps its
        # floor.  The lag-polled heal (inside the SCP replay window)
        # models the operator fixing NTP; the node must replay the
        # missed slots and the recovery clock has a floor.
        "clock_skew_beyond_slip": ScenarioSpec(
            name="clock_skew_beyond_slip_small",
            fault_class="clock_skew_beyond_slip",
            n_nodes=3,
            threshold=2,
            seed=seed,
            faults=[
                ClockSkew(
                    at=0.5, node=2, offset=-90.0, step_at=0.5,
                    heal_lag=3, heal_at=12.0,
                )
            ],
            load_backlog_ledgers=2,
            min_slip_rejects=1,
            target_ledgers=14,
            min_ledgers_per_sec=0.5,
            max_recovery_ms=15_000,
            timeout=180.0,
        ),
        # One-way isolation of a tier-1 node: node 2 is heard but hears
        # nothing (rest→2 dropped pre-MAC; 2→rest delivered) — the
        # half-open-connection case.  Links stay up the whole time; the
        # deaf node keeps voting into the void, stalls, and after the
        # lag-polled heal replays the missed slots from the still-open
        # connections' SCP rebroadcast.
        "asymmetric_partition": ScenarioSpec(
            name="asymmetric_partition_small",
            fault_class="asymmetric_partition",
            n_nodes=3,
            threshold=2,
            seed=seed,
            faults=[
                AsymmetricPartition(
                    at=0.5, deaf=[2], heal_lag=3, heal_at=12.0
                )
            ],
            load_backlog_ledgers=2,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            max_recovery_ms=15_000,
            timeout=180.0,
        ),
        # Targeted tier flood: invalid-sig envelope/tx flood injected
        # ONLY into the tier-2 ring nodes, plus a drain-capped overload
        # storm broadcast from a tier node across tier-touching links
        # only.  Tier-1's core mesh is untouched — its floor is the
        # UNDISTURBED one (vs the 0.2 global floors above) — while
        # tier-2 sheds FLOOD through the r17 send queues; per-tier
        # aggregates carry the verdict, and 0 CRITICAL sheds anywhere.
        "targeted_flood_tier2": ScenarioSpec(
            name="targeted_flood_tier2_small",
            fault_class="targeted_flood_tier2",
            topology="core_and_tier",
            n_nodes=3,
            tier_n=2,
            seed=seed,
            sendq_bytes=32 * 1024,
            sendq_flood_msgs=48,
            straggler_stall_ms=2500,
            faults=[
                ByzantineFlood(
                    at=0.5, until=8.0, targets=[3, 4],
                    envelopes_per_tick=15, txs_per_tick=3, tick=0.4,
                ),
                OverloadStorm(
                    at=0.5, until=8.0, source=3,
                    msgs_per_tick=25, tick=0.25,
                    drain_bytes_per_sec=16384,
                    drain_nodes=[3, 4],
                ),
            ],
            load_accounts=4,
            load_txs=120,
            load_rate=15,
            tiers={"tier1": [0, 1, 2], "tier2": [3, 4]},
            liveness_exclude=[3, 4],
            min_flood_sheds=1,
            assert_high_water_bounded=True,
            target_ledgers=14,
            min_ledgers_per_sec=0.5,
            timeout=240.0,
        ),
        # The tpu-backend flood leg (ROADMAP 6(a)): the byzantine flood
        # with the DEVICE batch plane engaged — SIGNATURE_BACKEND="tpu"
        # with cutover 0 routes every overlay flush (honest + flood)
        # through BatchVerifier's device dispatch; in tier-1 the
        # "device" is the XLA-CPU oracle.  The test pins the
        # CALLER_OVERLAY wedge-latch contract: zero wedge fallbacks and
        # zero latch flips under flood, verdicts identical to the cpu
        # path (same floors, same cache-cleanliness oracle).
        "byzantine_flood_tpu": ScenarioSpec(
            name="byzantine_flood_tpu_small",
            fault_class="byzantine_flood_tpu",
            n_nodes=3,
            seed=seed,
            signature_backend="tpu",
            tpu_cpu_cutover=0,
            faults=[
                ByzantineFlood(
                    at=0.5, until=7.0, target=0,
                    envelopes_per_tick=25, txs_per_tick=5, tick=0.4,
                )
            ],
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        # the admission-plane flood leg (ISSUE r20): the LoadGenerator's
        # legit stream (40 tx/s) keeps flowing while a byzantine flood
        # of invalid-sig txs FROM THE EXISTING ROOT ACCOUNT (so the
        # candidate triples hint-match and the edge shed — not
        # check_valid — is the defense that fires) hits node 0's ingest
        # front door at 400 tx/s, 10x the legit rate.  Every flooded tx
        # must shed at the edge (spec floor + the fault's exact-count
        # oracle), the verify cache stays clean, and the close cadence
        # holds the same floor as the un-flooded shapes.
        "ingest_flood": ScenarioSpec(
            name="ingest_flood_small",
            fault_class="ingest_flood",
            n_nodes=3,
            seed=seed,
            faults=[
                IngestFlood(
                    at=0.5, until=7.0, target=0,
                    txs_per_tick=100, tick=0.25,
                )
            ],
            min_ingest_sheds=2000,
            target_ledgers=14,
            min_ledgers_per_sec=0.2,
            timeout=180.0,
        ),
        "catchup_load": ScenarioSpec(
            name="catchup_load_small",
            fault_class="catchup_load",
            n_nodes=3,
            threshold=2,  # majority keeps closing while the lagger is cut
            seed=seed,
            clock_mode="real",  # archive get/put are real subprocesses
            disk_db=True,
            archives=True,
            checkpoint_frequency=8,
            faults=[
                PartitionUntilCheckpoint(
                    at=1.0, heal_after_ledger=12, lagger=2
                )
            ],
            load_backlog_ledgers=1,
            target_ledgers=18,
            # real-clock scenario: wall time includes archive subprocess
            # latency; the floor stays conservative
            min_ledgers_per_sec=0.05,
            timeout=150.0,
        ),
    }


def big_specs(seed: int = 1) -> Dict[str, ScenarioSpec]:
    """Core-and-tier ring scale (-m slow / scenario_liveness_r12 --matrix
    big): 4-core + 4-tier ring, longer fault windows, bigger floods —
    plus the big-only ``tcp_scale`` 100+ node OVER_TCP shape."""
    small = small_specs(seed)
    out: Dict[str, ScenarioSpec] = {}
    for cls, spec in small.items():
        big = ScenarioSpec(**{**spec.__dict__})
        big.name = spec.name.replace("_small", "_big")
        big.topology = "core_and_tier"
        big.n_nodes = 4
        big.tier_n = 4
        big.threshold = None
        big.target_ledgers = spec.target_ledgers + 16
        big.timeout = spec.timeout * 3
        big.load_txs = 1200
        if cls == "byzantine_flood":
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, target=0,
                    envelopes_per_tick=100, txs_per_tick=20, tick=0.4,
                )
            ]
        elif cls == "byzantine_flood_halfagg":
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, target=0,
                    envelopes_per_tick=40, txs_per_tick=8, tick=0.4,
                    storm_per_tick=400,
                )
            ]
        elif cls == "partition_heal":
            # cut the ring AND a core node off the rest
            big.faults = [
                Partition(
                    at=0.5, heal_at=4.0,
                    groups=[[0, 1, 2], [3, 4, 5, 6, 7]],
                )
            ]
            big.max_recovery_ms = 30_000
        elif cls == "crash_restart":
            # 8-node shape keeps BFT majority; crash a TIER node so ring
            # consensus must route around it, then recover on restart
            big.faults = [CrashRestart(at=2.0, restart_at=10.0, node=5)]
            big.threshold = None
            big.max_recovery_ms = 40_000
        elif cls == "hard_kill_mid_close":
            # hard-kill a TIER node mid-close while the ring keeps
            # closing; the restart must self-check + replay the gap
            big.faults = [
                HardKillMidClose(at=2.0, restart_at=10.0, node=5)
            ]
            big.threshold = None
            big.max_recovery_ms = 40_000
        elif cls == "catchup_load":
            big.faults = [
                PartitionUntilCheckpoint(
                    at=1.0, heal_after_ledger=20, lagger=7
                )
            ]
            big.target_ledgers = 26
        elif cls == "slow_reader":
            # 4-core + 4-tier ring; the slow reader is the last tier node
            big.faults = [
                SlowReader(at=0.5, node=7, drain_bytes_per_sec=2048)
            ]
            big.liveness_exclude = [7]
        elif cls == "overload_storm":
            big.faults = [
                OverloadStorm(
                    at=0.5, until=20.0, source=0,
                    msgs_per_tick=80, tick=0.25,
                    drain_bytes_per_sec=16384,
                )
            ]
            big.load_txs = 300
        elif cls == "byzantine_flood_tpu":
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, target=0,
                    envelopes_per_tick=50, txs_per_tick=10, tick=0.4,
                )
            ]
        elif cls == "ingest_flood":
            big.faults = [
                IngestFlood(
                    at=0.5, until=20.0, target=0,
                    txs_per_tick=200, tick=0.25,
                )
            ]
            big.min_ingest_sheds = 10_000
        elif cls in ("clock_skew_within_slip", "clock_skew_beyond_slip"):
            # node 2 is a core node in the 4+4 shape; the core's 3-of-4
            # majority absorbs a beyond-slip stall exactly like the
            # small shape's 2-of-3
            pass
        elif cls == "asymmetric_partition":
            pass  # deaf=[2] — core node, 3-of-4 majority holds
        elif cls == "targeted_flood_tier2":
            # re-aim at the 4-node tier ring of the 4+4 shape
            big.faults = [
                ByzantineFlood(
                    at=0.5, until=20.0, targets=[4, 5, 6, 7],
                    envelopes_per_tick=15, txs_per_tick=3, tick=0.4,
                ),
                OverloadStorm(
                    at=0.5, until=20.0, source=4,
                    msgs_per_tick=40, tick=0.25,
                    drain_bytes_per_sec=16384,
                    drain_nodes=[4, 5, 6, 7],
                ),
            ]
            big.tiers = {"tier1": [0, 1, 2, 3], "tier2": [4, 5, 6, 7]}
            big.liveness_exclude = [4, 5, 6, 7]
            big.load_txs = 300
        out[cls] = big
    # the big-only scale shape (ISSUE r19 / ROADMAP 6(b')): 4-core +
    # 96-tier ring over REAL localhost TCP sockets — the per-peer
    # bounded send queues and pack-once fan-out at production-transport
    # scale.  Real clock (socket delivery is kernel-timed; the digest
    # policy already excludes counters for real-clock runs), no
    # link-level faults (loopback-only knobs), floors: ≥5 ledgers
    # externalized by every one of the 100 nodes inside the timeout.
    out["tcp_scale"] = ScenarioSpec(
        name="tcp_scale_100",
        fault_class="tcp_scale",
        topology="core_and_tier",
        overlay_mode="tcp",
        clock_mode="real",
        n_nodes=4,
        tier_n=96,
        # watchers: a 4-core committee decides, 96 tier nodes track and
        # relay — 100 independent nominators churn nomination for
        # minutes/slot, which is a different (known) pathology than the
        # transport-scale claim this shape certifies
        tier_validators=False,
        seed=seed,
        faults=[],
        load_accounts=4,
        load_txs=80,
        load_rate=10,
        tiers={"tier1": [0, 1, 2, 3], "tier2": list(range(4, 100))},
        target_ledgers=7,
        stabilize_ledgers=2,
        min_ledgers_per_sec=0.0,
        timeout=900.0,
    )
    return out


def run_matrix(
    matrix: str = "small",
    only: Optional[List[str]] = None,
    seed: int = 1,
    workdir: Optional[str] = None,
) -> List[ScenarioResult]:
    specs = small_specs(seed) if matrix == "small" else big_specs(seed)
    if only:
        # an EXPLICIT request for a class this matrix doesn't carry must
        # not read as a green (empty) run — raise for every caller
        # (bench, tests), not just the CLI's own pre-check
        missing = [c for c in only if c not in specs]
        if missing:
            raise ValueError(
                "fault class(es) not in the %s matrix: %s"
                % (matrix, ",".join(missing))
            )
    results = []
    for cls in FAULT_CLASSES:
        if only and cls not in only:
            continue
        if cls not in specs:
            continue  # big-only shape (tcp_scale) absent from small
        results.append(Scenario(specs[cls], workdir=workdir).run())
    return results
