"""Storage fault injection — the deterministic kill-switch / torn-write
shim under the durable-write kill-point plane (util/fs.py).

Two hooks plug into ``fs.kill_point``:

- ``KillPointTrace`` — append-only record of every hit (flushed per
  line, because the process may die at any moment).  The kill-sweep's
  control run uses it to ENUMERATE the points a close+publish window
  actually crosses.
- ``StorageFaultInjector`` — fires ONCE at the ``nth`` hit of one named
  point, optionally corrupting the on-disk file first, then kills:

  * ``exit``      — ``os._exit(code)`` right at the point (the literal
                    hard-kill; no atexit, no finally, no flush)
  * ``truncate``  — truncate the file at the point to half, then exit
  * ``torn``      — truncate to half + append garbage (a torn partial
                    write: what an OS crash can leave of an unsynced
                    write), then exit
  * ``raise``     — raise ``fs.SimulatedProcessKill`` instead of
                    exiting: the in-process chaos matrix's hard kill
                    (Simulation.crank_until catches it and reaps the
                    node mid-close)

Determinism: the injector is a pure (point, nth, owner) counter — same
topology + seed + crank order ⇒ same firing moment, which is what lets
``hard_kill_mid_close`` pass the two-run replay gate.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..util import fs
from ..util.fs import SimulatedProcessKill  # noqa: F401  (re-export)

KILL_EXIT_CODE = 137  # what SIGKILL would report; the sweep asserts it

MODES = ("exit", "truncate", "torn", "raise")

# deterministic torn-tail garbage: recognizable in a hexdump, never a
# valid RFC 5531 record mark (high bit pattern is nonsense mid-stream)
TORN_GARBAGE = b"\xde\xad\xbe\xef" * 16


def corrupt_file(path: str, mode: str) -> None:
    """Apply the named corruption to an on-disk file (used by the
    injector at a ``:write`` stage, and directly by tests building
    corrupt artifacts)."""
    if not path or not os.path.exists(path):
        return
    size = os.path.getsize(path)
    keep = size // 2
    with open(path, "r+b") as f:
        f.truncate(keep)
        if mode == "torn":
            f.seek(keep)
            f.write(TORN_GARBAGE)
        f.flush()
        os.fsync(f.fileno())


class KillPointTrace:
    """fs hook: append one ``name\\tpath`` line per hit, flushed
    immediately (the process this traces is built to die mid-write)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = open(path, "a", buffering=1)

    def __call__(self, name: str, path: Optional[str], ctx) -> None:
        with self._lock:
            self._f.write("%s\t%s\n" % (name, path or ""))
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            self._f.close()

    @staticmethod
    def read_points(path: str):
        """Ordered unique point names from a trace file."""
        seen = []
        have = set()
        with open(path) as f:
            for line in f:
                name = line.split("\t", 1)[0].strip()
                if name and name not in have:
                    have.add(name)
                    seen.append(name)
        return seen


class StorageFaultInjector:
    """fs hook: one deterministic fault at the nth hit of one point.

    ``owner`` scopes the counter to one node in a multi-node process
    (matched by identity against the kill-point's ``ctx`` — the node's
    Database object on every registered point that has one)."""

    def __init__(
        self,
        point: str,
        nth: int = 1,
        mode: str = "exit",
        owner=None,
        exit_code: int = KILL_EXIT_CODE,
    ):
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} (one of {MODES})")
        if nth < 1:
            raise ValueError("nth must be >= 1")
        self.point = point
        self.nth = nth
        self.mode = mode
        self.owner = owner
        self.exit_code = exit_code
        self.hits = 0
        self.fired = False

    def __call__(self, name: str, path: Optional[str], ctx) -> None:
        if name != self.point or self.fired:
            return
        if self.owner is not None and ctx is not self.owner:
            return
        self.hits += 1
        if self.hits != self.nth:
            return
        self.fired = True
        if self.mode in ("truncate", "torn"):
            corrupt_file(path, self.mode)
        if self.mode == "raise":
            raise SimulatedProcessKill(name, ctx)
        # the hard kill: no atexit, no finally, no buffered-IO flush —
        # the closest a Python process gets to SIGKILLing itself
        os._exit(self.exit_code)


def parse_arm_spec(spec: str) -> StorageFaultInjector:
    """``point[:nth[:mode]]`` — note the point name itself may contain a
    stage suffix like ``bucket.fresh:write``, so nth/mode are parsed
    from the RIGHT and must be an integer / a known mode."""
    parts = spec.split(":")
    nth, mode = 1, "exit"
    if parts and parts[-1] in MODES:
        mode = parts.pop()
    if parts and parts[-1].isdigit():
        nth = int(parts.pop())
    point = ":".join(parts)
    if not point.strip(":"):
        raise ValueError(f"bad kill spec {spec!r}")
    return StorageFaultInjector(point, nth=nth, mode=mode)


def install_from_env() -> list:
    """Arm hooks from the environment (the kill-sweep child's seam):

    - ``STELLAR_TPU_KILLPOINT_TRACE=<file>``  — record every hit
    - ``STELLAR_TPU_KILL_POINT=point[:nth[:mode]]`` — one injector

    Returns the installed hooks (caller removes them via
    ``fs.remove_kill_hook`` when its fault window closes)."""
    hooks = []
    trace = os.environ.get("STELLAR_TPU_KILLPOINT_TRACE")
    if trace:
        hooks.append(KillPointTrace(trace))
    spec = os.environ.get("STELLAR_TPU_KILL_POINT")
    if spec:
        hooks.append(parse_arm_spec(spec))
    for h in hooks:
        fs.add_kill_hook(h)
    return hooks
