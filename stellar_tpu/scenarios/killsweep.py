"""Kill-sweep harness — the storage half of the chaos story's proof.

``python -m stellar_tpu.scenarios --kill-sweep`` drives one standalone
validator through a deterministic close+publish window, then, for every
registered durable-write kill-point the window crosses (util/fs.py),
spawns a fresh subprocess that HARD-KILLS itself (``os._exit``) at
exactly that point — optionally leaving a truncated or torn file behind
— restarts the node on the survivor's on-disk state, and asserts the
boot self-check (main/selfcheck.py) repairs it back onto the control
run's exact trajectory: bit-identical LCL hash, bucket-list hash, and
full SQL state digest at the target ledger, with ``checkdb`` green and
the publish queue drained.

Determinism: the window's transactions and close times are pure
functions of the ledger sequence, so a node resumed from ANY kill point
re-closes the remaining ledgers to the same hashes iff its repaired
state is exactly the pre-kill durable state.  Two control legs run the
window through both bucket-merge engines (C and Python — bit-identical
output, pinned by tests/test_native_merge.py) so both engines' kill
points are enumerable and every kill child runs the leg that actually
crosses its point.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

from ..util import fs
from .storagefaults import KILL_EXIT_CODE, KillPointTrace, install_from_env

# fixed epoch for close times: monotone in seq, identical across lives
CLOSE_T0 = 1_700_000_000
DEFAULT_TARGET = 10
CHECKPOINT_FREQ = 4

# kill-points where the torn/truncated-file modes are ALSO swept (the
# payload is complete-but-unsynced on disk there).  publish.stage-bucket
# is deliberately exit-only: the staging entry is a HARD LINK, so
# corrupting it would corrupt the canonical bucket through the shared
# inode — that shape (canonical-file corruption + archive re-fetch) is
# exercised deterministically by tests/test_selfcheck.py instead.
CORRUPTIBLE_STAGES = (":write",)


# -- the child node (one subprocess per sweep leg) ---------------------------


def _child_config(workdir: str):
    from ..tx.testutils import get_test_config

    cfg = get_test_config(9500)
    cfg.DATABASE = f"sqlite3://{workdir}/node.db"
    cfg.BUCKET_DIR_PATH = f"{workdir}/buckets"
    cfg.TMP_DIR_PATH = f"{workdir}/tmp"
    cfg.HTTP_PORT = 0
    cfg.CHECKPOINT_FREQUENCY = CHECKPOINT_FREQ
    # no FORCE_SCP: the window drives closes directly (deterministic
    # close times), the herder only persists/restores SCP state
    cfg.FORCE_SCP = False
    archive = f"{workdir}/archive"
    cfg.HISTORY = {
        "sweep": {
            "get": f"cp {archive}/{{0}} {{1}}",
            "put": f"cp {{0}} {archive}/{{1}}",
            "mkdir": f"mkdir -p {archive}/{{0}}",
        }
    }
    return cfg


def _window_txs(app, seq: int):
    """The deterministic load: a pure function of the ledger sequence
    (and therefore of the durable state a repaired node resumes from)."""
    from ..ledger.accountframe import AccountFrame
    from ..tx import testutils as T

    root = T.root_key_for(app)
    accounts = [T.get_account(f"sweep-{i}") for i in range(3)]
    root_seq = AccountFrame.load_account(
        root.get_public_key(), app.database
    ).get_seq_num()
    if seq == 2:
        ops = [T.create_account_op(a, 10**15) for a in accounts]
        return [T.tx_from_ops(app, root, root_seq + 1, ops)]
    dest = accounts[seq % len(accounts)]
    return [
        T.tx_from_ops(
            app, root, root_seq + 1,
            [T.payment_op(dest, 1000 + seq)],
        )
    ]


def _drain_publish(app, timeout: float = 120.0) -> bool:
    from ..history import publish as publish_queue

    hm = app.history_manager

    def drained():
        return (
            publish_queue.min_queued(app.database) == 0
            and not hm.publishing
        )

    app.clock.post(hm.publish_queued_history)
    return app.clock.crank_until(drained, timeout)


def _dump_result(app) -> dict:
    import hashlib

    from ..history import publish as publish_queue
    from ..tx.testutils import dump_state

    lm = app.ledger_manager
    state = dump_state(app.database)
    checkdb = "skipped"
    try:
        checkdb = app.bucket_manager.check_db()["status"]
    except Exception as e:
        checkdb = f"FAILED: {e}"
    return {
        "lcl_seq": lm.get_last_closed_ledger_num(),
        "lcl_hash": lm.last_closed.hash.hex(),
        "bucket_hash": app.bucket_manager.get_hash().hex(),
        "state_digest": hashlib.sha256(
            repr(state).encode()
        ).hexdigest(),
        "queued_checkpoints": len(
            publish_queue.queued_checkpoints(app.database)
        ),
        "checkdb": checkdb,
        "selfcheck": app.last_selfcheck,
    }


def child_main(workdir: str, target: int, out_path: str) -> int:
    """One sweep leg: boot (fresh or resumed), arm any env-specified
    fault, close to ``target`` with deterministic load, drain publish,
    dump the verdict JSON.  A kill child never reaches the dump — it
    ``os._exit``s at its point."""
    from ..main.application import Application
    from ..tx.testutils import close_ledger_on
    from ..util.clock import REAL_TIME, VirtualClock

    os.makedirs(workdir, exist_ok=True)
    os.makedirs(f"{workdir}/archive", exist_ok=True)
    fresh = not os.path.exists(f"{workdir}/node.db")
    cfg = _child_config(workdir)
    clock = VirtualClock(REAL_TIME)
    app = Application.create(clock, cfg, new_db=fresh)
    hooks = []
    try:
        app.start()
        # the fault window opens AFTER boot: control and kill children
        # count hits from the same instant, so (point, nth=1) means the
        # same moment in both
        hooks = install_from_env()
        lm = app.ledger_manager
        while lm.get_last_closed_ledger_num() < target:
            seq = lm.current.header.ledgerSeq
            close_ledger_on(
                app, CLOSE_T0 + seq * 5, txs=_window_txs(app, seq)
            )
            # the herder's own persist rides externalize; the sweep
            # window drives closes directly, so persist explicitly —
            # same kill-points, same row
            app.herder.persist_scp_state(seq)
        ok = _drain_publish(app)
        for h in hooks:
            fs.remove_kill_hook(h)
        hooks = []
        result = _dump_result(app)
        result["publish_drained"] = bool(ok)
        with open(out_path, "w") as f:
            json.dump(result, f, sort_keys=True)
        return 0
    finally:
        for h in hooks:
            fs.remove_kill_hook(h)
        app.graceful_stop()
        clock.shutdown()


# -- the parent sweep --------------------------------------------------------


def ensure_points_registered() -> None:
    """Import every module that owns a kill-point so the parent's
    registry is the complete inventory (registration happens at import
    time; the parent never exercises most of these paths itself)."""
    import stellar_tpu.bucket.bucket  # noqa: F401
    import stellar_tpu.bucket.manager  # noqa: F401
    import stellar_tpu.database.database  # noqa: F401
    import stellar_tpu.herder.herder  # noqa: F401
    import stellar_tpu.history.publish  # noqa: F401
    import stellar_tpu.history.publishsm  # noqa: F401
    import stellar_tpu.ledger.manager  # noqa: F401


# sentinel returncode for a timed-out sweep leg: never a real exit code,
# so every caller's rc check classifies the leg as failed/missed instead
# of the TimeoutExpired aborting the whole sweep with no report
TIMEOUT_RC = -9999


def _run_child(
    workdir: str,
    target: int,
    out_path: str,
    env_extra: Dict[str, str],
    timeout: float = 180.0,
):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.update(env_extra)
    cmd = [
        sys.executable, "-m", "stellar_tpu.scenarios",
        "--kill-child", "--workdir", workdir,
        "--target", str(target), "--out", out_path,
    ]
    try:
        return subprocess.run(
            cmd,
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        def _text(b):
            if isinstance(b, bytes):
                return b.decode("utf-8", "replace")
            return b or ""

        return subprocess.CompletedProcess(
            cmd,
            TIMEOUT_RC,
            stdout=_text(e.stdout),
            stderr=_text(e.stderr)
            + "\n[sweep] child timed out after %.0f s" % timeout,
        )


def _slug(point: str, mode: str) -> str:
    return "%s-%s" % (point.replace(":", "_").replace(".", "_"), mode)


class SweepVerdict:
    def __init__(self, point, mode, leg):
        self.point = point
        self.mode = mode
        self.leg = leg
        self.ok = False
        self.detail = ""
        self.selfcheck_status = None
        self.resumed_lcl = None

    def to_dict(self):
        return {
            "point": self.point,
            "mode": self.mode,
            "leg": self.leg,
            "ok": self.ok,
            "detail": self.detail,
            "selfcheck": self.selfcheck_status,
            "resumed_lcl": self.resumed_lcl,
        }


def run_kill_sweep(
    points: Optional[List[str]] = None,
    all_modes: bool = True,
    target: int = DEFAULT_TARGET,
    base_dir: Optional[str] = None,
    keep: bool = False,
    log=print,
) -> dict:
    """The full sweep.  Returns a report dict; ``report["ok"]`` is the
    green/red verdict (any unrecovered point, hash mismatch, missed
    kill, or failed resume is red)."""
    own_base = base_dir is None
    base = base_dir or tempfile.mkdtemp(prefix="stellar-tpu-killsweep-")
    os.makedirs(base, exist_ok=True)
    ensure_points_registered()
    legs = {
        "native": {},
        "pymerge": {"STELLAR_TPU_NO_NATIVE_MERGE": "1"},
    }
    try:
        # -- control legs: enumerate the window's kill points + pin the
        # target state both merge engines must agree on
        controls, hit_points = {}, {}
        for leg, env in legs.items():
            wd = os.path.join(base, f"control-{leg}")
            out = os.path.join(wd, "result.json")
            trace = os.path.join(base, f"trace-{leg}.tsv")
            os.makedirs(wd, exist_ok=True)
            proc = _run_child(
                wd, target, out,
                {**env, "STELLAR_TPU_KILLPOINT_TRACE": trace},
            )
            if proc.returncode != 0:
                return {
                    "ok": False,
                    "error": "control leg %r failed rc=%d: %s" % (
                        leg, proc.returncode, proc.stderr[-2000:]
                    ),
                    "verdicts": [],
                }
            with open(out) as f:
                controls[leg] = json.load(f)
            hit_points[leg] = KillPointTrace.read_points(trace)
        if (
            controls["native"]["lcl_hash"] != controls["pymerge"]["lcl_hash"]
            or controls["native"]["bucket_hash"]
            != controls["pymerge"]["bucket_hash"]
            or controls["native"]["state_digest"]
            != controls["pymerge"]["state_digest"]
        ):
            return {
                "ok": False,
                "error": "merge engines disagree on the control state",
                "verdicts": [],
            }
        control = controls["native"]

        # -- the plan: every hit point, on the leg that crosses it.
        # ``crossed`` is the window's coverage (every point the control
        # legs traversed — the acceptance's >= 25 inventory); ``swept``
        # is what this run actually kills, which a --points filter may
        # narrow.  Reporting them separately keeps a filtered run from
        # overstating its coverage.
        plan: List[tuple] = []
        swept, crossed = set(), set()
        for leg in ("native", "pymerge"):
            for p in hit_points[leg]:
                if p in crossed:
                    continue
                crossed.add(p)
                if points is not None and p not in points:
                    continue
                swept.add(p)
                plan.append((p, "exit", leg))
                if all_modes and p.endswith(CORRUPTIBLE_STAGES):
                    plan.append((p, "truncate", leg))
                    plan.append((p, "torn", leg))
        registered = sorted(fs.registered_kill_points())
        unexercised = [p for p in registered if p not in crossed]

        # -- kill + resume, one workdir per (point, mode)
        verdicts: List[SweepVerdict] = []
        for point, mode, leg in plan:
            v = SweepVerdict(point, mode, leg)
            verdicts.append(v)
            wd = os.path.join(base, _slug(point, mode))
            out = os.path.join(wd, "result.json")
            os.makedirs(wd, exist_ok=True)
            kill_env = {
                **legs[leg],
                "STELLAR_TPU_KILL_POINT": f"{point}:1:{mode}",
            }
            proc = _run_child(wd, target, out, kill_env)
            if proc.returncode != KILL_EXIT_CODE:
                if proc.returncode == TIMEOUT_RC:
                    v.detail = "kill child timed out before the point fired"
                else:
                    v.detail = (
                        "kill child survived (rc=%d) — point never fired"
                        % proc.returncode
                    )
                log("  %-42s %-8s MISSED  %s" % (point, mode, v.detail))
                continue
            proc = _run_child(wd, target, out, dict(legs[leg]))
            if proc.returncode != 0:
                v.detail = "resume failed rc=%d: %s" % (
                    proc.returncode, (proc.stderr or "")[-800:]
                )
                log("  %-42s %-8s FAIL    %s" % (point, mode, v.detail))
                continue
            with open(out) as f:
                resumed = json.load(f)
            sc = resumed.get("selfcheck") or {}
            v.selfcheck_status = sc.get("status")
            v.resumed_lcl = resumed.get("lcl_seq")
            mismatches = [
                k
                for k in ("lcl_hash", "bucket_hash", "state_digest")
                if resumed.get(k) != control[k]
            ]
            if mismatches:
                v.detail = "state mismatch vs control: %s" % mismatches
            elif resumed.get("checkdb") != "ok":
                v.detail = "checkdb after repair: %s" % resumed.get("checkdb")
            elif resumed.get("queued_checkpoints"):
                v.detail = (
                    "%d checkpoint(s) still queued after resume"
                    % resumed["queued_checkpoints"]
                )
            elif v.selfcheck_status not in ("ok", "repaired"):
                v.detail = "selfcheck status %r" % v.selfcheck_status
            else:
                v.ok = True
            log(
                "  %-42s %-8s %s selfcheck=%s"
                % (
                    point, mode,
                    "ok  " if v.ok else "FAIL",
                    v.selfcheck_status,
                )
            )
            if not keep and v.ok:
                shutil.rmtree(wd, ignore_errors=True)

        n_ok = sum(1 for v in verdicts if v.ok)
        report = {
            "ok": bool(verdicts) and n_ok == len(verdicts),
            "target_ledger": target,
            "control": {
                k: control[k]
                for k in ("lcl_seq", "lcl_hash", "bucket_hash")
            },
            "points_hit": sorted(crossed),
            "points_swept": sorted(swept),
            "points_registered": len(registered),
            "points_unexercised": unexercised,
            "swept": len(verdicts),
            "recovered": n_ok,
            "verdicts": [v.to_dict() for v in verdicts],
        }
        return report
    finally:
        if own_base and not keep:
            shutil.rmtree(base, ignore_errors=True)
