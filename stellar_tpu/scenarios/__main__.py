"""CLI: run the chaos-scenario matrix and report liveness.

    python -m stellar_tpu.scenarios [--matrix small|big] [--only CLS[,CLS]]
                                    [--seed N] [--json]

One line per scenario; exits nonzero when ANY scenario fails — invariant
violation, chain disagreement, liveness-floor miss, unrecovered heal, or
a polluted verify cache under flood.  This is the relay_watch
``scenario_liveness_r12`` step's entry point.

The storage plane's sweep (relay_watch ``crash_sweep_r18``):

    python -m stellar_tpu.scenarios --kill-sweep [--points P[,P]]
                                    [--modes exit|all] [--target N] [--json]

hard-kills a standalone node at every registered durable-write
kill-point it crosses in a close+publish window (one subprocess per
point × fault mode; scenarios/killsweep.py) and exits 1 on ANY
unrecovered point or post-repair hash mismatch.  ``--kill-child`` is
the internal per-leg entry point those subprocesses run.
"""

from __future__ import annotations

import argparse
import json
import sys

from .matrix import FAULT_CLASSES, run_matrix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="stellar_tpu.scenarios")
    ap.add_argument("--matrix", choices=("small", "big"), default="small")
    ap.add_argument(
        "--only",
        help="comma-separated fault classes (%s)" % ",".join(FAULT_CLASSES),
    )
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--json", action="store_true", dest="as_json")
    # kill-sweep mode (scenarios/killsweep.py)
    ap.add_argument("--kill-sweep", action="store_true", dest="kill_sweep")
    ap.add_argument("--points", help="comma-separated kill-point names")
    ap.add_argument("--modes", choices=("exit", "all"), default="all")
    ap.add_argument("--target", type=int, default=None)
    ap.add_argument("--keep", action="store_true")
    # internal: one sweep leg (the subprocess the sweep spawns)
    ap.add_argument("--kill-child", action="store_true", dest="kill_child")
    ap.add_argument("--workdir")
    ap.add_argument("--out")
    args = ap.parse_args(argv)

    if args.kill_child:
        from .killsweep import DEFAULT_TARGET, child_main

        return child_main(
            args.workdir, args.target or DEFAULT_TARGET, args.out
        )
    if args.kill_sweep:
        from .killsweep import DEFAULT_TARGET, run_kill_sweep

        points = args.points.split(",") if args.points else None
        if points:
            from ..util import fs
            from .killsweep import ensure_points_registered

            ensure_points_registered()
            unknown = [
                p for p in points if p not in fs.registered_kill_points()
            ]
            if unknown:
                print(
                    "unknown kill point(s): %s" % ",".join(unknown),
                    file=sys.stderr,
                )
                return 2
        report = run_kill_sweep(
            points=points,
            all_modes=args.modes == "all",
            target=args.target or DEFAULT_TARGET,
            keep=args.keep,
            log=lambda s: None if args.as_json else print(s),
        )
        if args.as_json:
            print(json.dumps(report, sort_keys=True))
        else:
            if report.get("error"):
                print("kill-sweep ERROR: %s" % report["error"])
            print(
                "kill-sweep: %d/%d point×mode legs recovered bit-exact"
                " (%d distinct points killed; window crosses %d of %d"
                " registered)"
                % (
                    report.get("recovered", 0),
                    report.get("swept", 0),
                    len(report.get("points_swept", [])),
                    len(report.get("points_hit", [])),
                    report.get("points_registered", 0),
                )
            )
        return 0 if report.get("ok") else 1

    only = args.only.split(",") if args.only else None
    if only:
        unknown = [c for c in only if c not in FAULT_CLASSES]
        if unknown:
            print("unknown fault class(es): %s" % ",".join(unknown),
                  file=sys.stderr)
            return 2

    try:
        results = run_matrix(matrix=args.matrix, only=only, seed=args.seed)
    except ValueError as e:
        # run_matrix raises for classes absent from the chosen matrix
        # (big-only shapes like tcp_scale) — a silently-empty run must
        # not read as a green matrix
        print(str(e), file=sys.stderr)
        return 2
    any_fail = False
    for r in results:
        if args.as_json:
            print(json.dumps(r.to_dict(), sort_keys=True))
        else:
            sb = r.scoreboard
            print(
                "%-24s %-4s ledgers=%d (%.2f/s) nom=%d ballot=%d "
                "rejects=%d slip=%d recovery=%s inv=%d digest=%s"
                % (
                    r.name,
                    "ok" if r.ok else "FAIL",
                    sb.ledgers_closed,
                    sb.ledgers_per_sec,
                    sb.nomination_rounds,
                    sb.ballot_rounds,
                    sb.fast_rejects,
                    sb.slip_rejects_past + sb.slip_rejects_future,
                    ("%.0fms" % sb.recovery_ms)
                    if sb.recovery_ms is not None
                    else "-",
                    sb.invariant_violations,
                    sb.digest(),
                )
            )
            for f in r.failures:
                print("    FAIL: %s" % f)
        any_fail = any_fail or not r.ok
    return 1 if any_fail else 0


if __name__ == "__main__":
    sys.exit(main())
