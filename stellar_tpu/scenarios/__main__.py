"""CLI: run the chaos-scenario matrix and report liveness.

    python -m stellar_tpu.scenarios [--matrix small|big] [--only CLS[,CLS]]
                                    [--seed N] [--json]

One line per scenario; exits nonzero when ANY scenario fails — invariant
violation, chain disagreement, liveness-floor miss, unrecovered heal, or
a polluted verify cache under flood.  This is the relay_watch
``scenario_liveness_r12`` step's entry point.
"""

from __future__ import annotations

import argparse
import json
import sys

from .matrix import FAULT_CLASSES, run_matrix


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="stellar_tpu.scenarios")
    ap.add_argument("--matrix", choices=("small", "big"), default="small")
    ap.add_argument(
        "--only",
        help="comma-separated fault classes (%s)" % ",".join(FAULT_CLASSES),
    )
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    only = args.only.split(",") if args.only else None
    if only:
        unknown = [c for c in only if c not in FAULT_CLASSES]
        if unknown:
            print("unknown fault class(es): %s" % ",".join(unknown),
                  file=sys.stderr)
            return 2

    results = run_matrix(matrix=args.matrix, only=only, seed=args.seed)
    any_fail = False
    for r in results:
        if args.as_json:
            print(json.dumps(r.to_dict(), sort_keys=True))
        else:
            sb = r.scoreboard
            print(
                "%-24s %-4s ledgers=%d (%.2f/s) nom=%d ballot=%d "
                "rejects=%d recovery=%s inv=%d digest=%s"
                % (
                    r.name,
                    "ok" if r.ok else "FAIL",
                    sb.ledgers_closed,
                    sb.ledgers_per_sec,
                    sb.nomination_rounds,
                    sb.ballot_rounds,
                    sb.fast_rejects,
                    ("%.0fms" % sb.recovery_ms)
                    if sb.recovery_ms is not None
                    else "-",
                    sb.invariant_violations,
                    sb.digest(),
                )
            )
            for f in r.failures:
                print("    FAIL: %s" % f)
        any_fail = any_fail or not r.ok
    return 1 if any_fail else 0


if __name__ == "__main__":
    sys.exit(main())
