"""Floodgate — at-most-once flood dedup (reference: src/overlay/Floodgate.{h,cpp}).

Keyed by message hash; each record remembers which peers already have the
message so a broadcast only sends to the rest.  Records are GC'd as ledgers
close (``clear_below`` keeps the last two ledgers, Floodgate.cpp:46-58).
"""

from __future__ import annotations

from typing import Dict, Set

from ..crypto import sha256
from ..trace import tracer_of
from ..util import xlog
from ..xdr.base import pack_many, xdr_to_opaque
from ..xdr.overlay import StellarMessage

log = xlog.logger("Overlay")


class FloodRecord:
    __slots__ = ("ledger_seq", "message", "peers_told")

    def __init__(self, ledger_seq: int, message: StellarMessage):
        self.ledger_seq = ledger_seq
        self.message = message
        self.peers_told: Set[object] = set()


class Floodgate:
    def __init__(self, app):
        self.app = app
        self.flood_map: Dict[bytes, FloodRecord] = {}
        self._shutting_down = False
        self.m_added = app.metrics.new_counter(("overlay", "memory", "flood-known"))
        # cumulative per-peer sends (flood fan-out) — the chaos plane's
        # scoreboard reads this as "how much the network amplified"
        self.n_sent = 0

    @staticmethod
    def message_key(msg: StellarMessage, body: bytes = None) -> bytes:
        """Flood identity = hash of the packed message; ``body`` lets a
        caller that already packed the message (broadcast's pack-once
        fan-out) skip the re-serialization."""
        return sha256(body if body is not None else msg.to_xdr())

    def clear_below(self, current_ledger: int) -> None:
        """Drop records older than the previous ledger (Floodgate.cpp:46)."""
        keep = current_ledger - 1
        for k in [k for k, r in self.flood_map.items() if r.ledger_seq < keep]:
            del self.flood_map[k]
        self.m_added.set_count(len(self.flood_map))

    def forget_from(self, ledger_seq: int) -> None:
        """Forget records stamped at or after ``ledger_seq`` — the
        herder's stall probe (ISSUE r19): a node stalled while tracking
        accumulated at-most-once records for exactly the slots it failed
        to close, and the probe's SCP-state replay re-delivers those
        same messages — without this the dedup swallows them before the
        herder ever sees the retry.  Cost is bounded re-flood chatter
        for the forgotten window (receivers still dedup), paid only at
        the probe's own rate limit."""
        for k in [
            k for k, r in self.flood_map.items() if r.ledger_seq >= ledger_seq
        ]:
            del self.flood_map[k]
        self.m_added.set_count(len(self.flood_map))

    def add_record(self, msg: StellarMessage, from_peer) -> bool:
        """Returns True if the message is NEW (should be processed/forwarded)."""
        if self._shutting_down:
            return False
        key = self.message_key(msg)
        rec = self.flood_map.get(key)
        if rec is None:
            lm = self.app.ledger_manager
            seq = lm.get_ledger_num() if lm.last_closed is not None else 0
            rec = FloodRecord(seq, msg)
            self.flood_map[key] = rec
            self.m_added.set_count(len(self.flood_map))
            if from_peer is not None:
                rec.peers_told.add(from_peer)
            return True
        if from_peer is not None:
            rec.peers_told.add(from_peer)
        return False

    def broadcast(self, msg: StellarMessage, force: bool) -> None:
        """Send to every authenticated peer not already told
        (Floodgate.cpp:84-110).  The record is created when missing (locally
        originated message); ``force`` resets it so our own SCP messages
        re-flood each rebroadcast tick even to peers already told."""
        if self._shutting_down:
            return
        tracer = tracer_of(self.app)
        sp = tracer.begin("overlay.flood")
        # pack-once fan-out: ONE serialization (the C pack_many path)
        # serves the flood key and every peer's send queue — each queue
        # entry holds a reference to this same immutable buffer, so a
        # 100-peer flood never re-serializes and shedding is O(1)
        body = pack_many([msg], StellarMessage)
        key = self.message_key(msg, body)
        rec = self.flood_map.get(key)
        if rec is None or force:
            lm = self.app.ledger_manager
            seq = lm.get_ledger_num() if lm.last_closed is not None else 0
            rec = FloodRecord(seq, msg)
            self.flood_map[key] = rec
            self.m_added.set_count(len(self.flood_map))
        om = self.app.overlay_manager
        sent = 0
        for peer in list(om.authenticated_peers()):
            if peer not in rec.peers_told:
                rec.peers_told.add(peer)
                peer.send_message(msg, body=body)
                sent += 1
        self.n_sent += sent
        tracer.end(
            sp, msg_type=getattr(msg.type, "name", str(msg.type)), sent=sent
        )

    def shutdown(self) -> None:
        self._shutting_down = True
        self.flood_map.clear()
