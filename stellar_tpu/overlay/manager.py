"""OverlayManager — peer lifecycle + flood routing
(reference: src/overlay/OverlayManagerImpl.{h,cpp}).

Every 2 seconds ``tick`` tops the connection count up toward
TARGET_PEER_CONNECTIONS: preferred peers first, then the SQL peer address
book ordered by next-attempt backoff (OverlayManagerImpl.cpp:215-260).
Flooded messages (transactions, SCP envelopes) pass through the Floodgate
for at-most-once semantics; tx-set / quorum-set fetch rides the two
ItemFetchers' anycast ask-one-peer loops.
"""

from __future__ import annotations

from typing import List, Optional

from ..util import VirtualTimer, xlog
from ..xdr.overlay import MessageType, StellarMessage
from .floodgate import Floodgate
from .itemfetcher import ItemFetcher
from .peer import Peer, PeerRole, PeerState
from .peerauth import PeerAuth
from .peerrecord import PeerRecord
from .sendqueue import SendQueueStats

log = xlog.logger("Overlay")

TICK_SECONDS = 2.0


class OverlayManager:
    def __init__(self, app):
        self.app = app
        self.peer_auth = PeerAuth(app)
        self.floodgate = Floodgate(app)
        self.peers: List[Peer] = []  # pending + authenticated
        self.door = None
        self.tick_timer = VirtualTimer(app.clock)
        self._shutting_down = False
        self.tx_set_fetcher = ItemFetcher(app, lambda p, h: p.send_get_tx_set(h))
        self.qset_fetcher = ItemFetcher(app, lambda p, h: p.send_get_quorum_set(h))
        self.m_connections = app.metrics.new_counter(("overlay", "connection", "count"))
        from .loadmanager import LoadManager

        self.load_manager = LoadManager(app)
        # node-level aggregate over every peer's SendQueue (peers die
        # with their connections; the chaos scoreboard and /peers need
        # the surviving view): per-class sheds, straggler disconnects,
        # queue-byte high-water, max observed CRITICAL stall
        self.sendq_stats = SendQueueStats()
        # per-crank SCP envelope coalescing (enqueue_scp_envelope)
        self._scp_batch: List = []
        self._scp_flush_posted = False
        self.m_scp_batch_flush = app.metrics.new_meter(
            ("overlay", "scp-batch", "flush"), "batch"
        )
        self.m_scp_batch_size = app.metrics.new_counter(
            ("overlay", "scp-batch", "envelopes")
        )
        # byzantine-flood fast rejects: envelopes the per-crank batch
        # verify found invalid and dropped at this boundary (the herder
        # never sees them; chaos-plane scoreboards read this)
        self.m_scp_batch_rejected = app.metrics.new_counter(
            ("overlay", "scp-batch", "rejected")
        )

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        from .tcppeer import PeerDoor

        self.store_config_peers()
        if self.door is None:
            self.door = PeerDoor(self.app)
            try:
                self.door.start()
            except OSError as e:
                log.warning("could not listen on peer port: %s", e)
                self.door = None
        self.tick()

    def shutdown(self) -> None:
        if self._shutting_down:
            return
        self._shutting_down = True
        self.tick_timer.cancel()
        if self.door is not None:
            self.door.close()
        self.floodgate.shutdown()
        for p in list(self.peers):
            p.drop()
        self.peers.clear()

    def is_shutting_down(self) -> bool:
        return self._shutting_down

    # -- connection management ----------------------------------------------
    def store_config_peers(self) -> None:
        """Seed the address book from config (OverlayManagerImpl::storeConfigPeers)."""
        cfg = self.app.config
        for s in cfg.PREFERRED_PEERS + cfg.KNOWN_PEERS:
            try:
                pr = PeerRecord.parse_ip_port(s, cfg.PEER_PORT)
            except ValueError:
                log.warning("bad peer address in config: %r", s)
                continue
            pr.store(self.app.database)

    def tick(self) -> None:
        """Top up outbound connections (OverlayManagerImpl.cpp:215)."""
        if self._shutting_down:
            return
        cfg = self.app.config
        need = cfg.TARGET_PEER_CONNECTIONS - len(self.peers)
        if need > 0:
            connected = {(p.ip(), p.remote_listening_port) for p in self.peers}
            for pr in PeerRecord.load_peers(
                self.app.database, need, self.app.clock.now()
            ):
                if (pr.ip, pr.port) in connected:
                    continue
                self.connect_to(pr)
        self.load_manager.maybe_shed_excess_load()
        self.tick_timer.expires_from_now(TICK_SECONDS)
        self.tick_timer.async_wait(self.tick)

    def connect_to(self, pr: PeerRecord) -> None:
        from .tcppeer import TCPPeer

        if len(self.peers) >= self.app.config.MAX_PEER_CONNECTIONS:
            return
        pr.back_off(self.app.database, self.app.clock.now())
        peer = TCPPeer.initiate(self.app, pr.ip, pr.port)
        if peer.state != PeerState.CLOSING:
            self.peers.append(peer)
            self.m_connections.set_count(len(self.peers))

    def add_pending_peer(self, peer: Peer) -> None:
        if self._shutting_down or len(self.peers) >= self.app.config.MAX_PEER_CONNECTIONS:
            peer.drop()
            return
        self.peers.append(peer)
        self.m_connections.set_count(len(self.peers))

    def accept_authenticated_peer(self, peer: Peer) -> bool:
        """Post-handshake admission (OverlayManagerImpl::isPeerAccepted):
        room check + preferred-peers-only policy; successful auth resets the
        address-book backoff."""
        cfg = self.app.config
        if cfg.PREFERRED_PEERS_ONLY and not self.is_preferred(peer):
            return False
        n_auth = len(self.authenticated_peers())
        if n_auth > cfg.MAX_PEER_CONNECTIONS:
            return self.is_preferred(peer)
        if peer.remote_listening_port:
            pr = PeerRecord(peer.ip(), peer.remote_listening_port)
            pr.store(self.app.database)
            pr.reset_back_off(self.app.database, self.app.clock.now())
        return True

    def is_preferred(self, peer: Peer) -> bool:
        cfg = self.app.config
        addr = f"{peer.ip()}:{peer.remote_listening_port}"
        if addr in cfg.PREFERRED_PEERS:
            return True
        if peer.peer_id is not None:
            from ..crypto.keys import PubKeyUtils

            if PubKeyUtils.to_strkey(peer.peer_id) in cfg.PREFERRED_PEER_KEYS:
                return True
        return False

    def drop_peer(self, peer: Peer) -> None:
        if peer in self.peers:
            self.peers.remove(peer)
            self.m_connections.set_count(len(self.peers))

    # -- views --------------------------------------------------------------
    def get_peers(self) -> List[Peer]:
        return list(self.peers)

    def authenticated_peers(self) -> List[Peer]:
        return [p for p in self.peers if p.is_authenticated()]

    def get_authenticated_peer_count(self) -> int:
        return len(self.authenticated_peers())

    # -- flooding -----------------------------------------------------------
    def enqueue_scp_envelope(self, envelope) -> None:
        """Coalesce every SCP envelope received during the current crank
        into ONE SigBackend batch, then hand them to the herder.

        The reference verifies eagerly inside Herder::recvSCPEnvelope
        (/root/reference/src/herder/HerderImpl.cpp:347-364); on the TPU
        backend an eager per-envelope check would be one device dispatch
        per message.  Instead the flush — posted once per crank — verifies
        all queued envelopes in a single batch, warming the shared verify
        cache so the herder's eager checks are cache hits with identical
        accept/reject results."""
        self._scp_batch.append(envelope)
        if not self._scp_flush_posted:
            self._scp_flush_posted = True
            self.app.clock.post(self._flush_scp_batch)

    def pending_scp_triples(self) -> list:
        """Verify triples for the envelopes queued for this crank's batch
        flush — the close pipeline (ledger/closepipeline.py) dispatches
        these asynchronously while a ledger applies, so the flush on the
        next crank is all cache hits.  A stale prefetch is harmless: the
        flush re-verifies anything the cache missed."""
        herder = self.app.herder
        if herder is None or not self._scp_batch:
            return []
        return [herder.envelope_verify_triple(env) for env in self._scp_batch]

    def _flush_scp_batch(self) -> None:
        batch, self._scp_batch = self._scp_batch, []
        self._scp_flush_posted = False
        if self._shutting_down or not batch:
            return
        herder = self.app.herder
        triples = [herder.envelope_verify_triple(env) for env in batch]
        # hand the batch SLOT-GROUPED to the node's SCP signature scheme
        # (Config.SCP_SIG_SCHEME): the per-envelope scheme is exactly the
        # old sig_backend.verify_batch(caller=CALLER_OVERLAY) call; the
        # half-aggregation scheme buckets these triples per slot and
        # verifies each bucket as one MSM check, with the same backend
        # (same caller class, so the wedge latch stays per-plane) as the
        # fallback for thin buckets and poisoned aggregates
        slots = [env.statement.slotIndex for env in batch]
        scheme = getattr(self.app, "scp_scheme", None)
        if scheme is not None:
            verdicts = scheme.verify_flush(triples, slots)
        else:  # bare harness apps without an Application-built scheme
            from ..crypto.sigbackend import CALLER_OVERLAY

            verdicts = self.app.sig_backend.verify_batch(
                triples, caller=CALLER_OVERLAY
            )
        self.m_scp_batch_flush.mark()
        self.m_scp_batch_size.inc(len(batch))
        # strict-gate fast-reject at the flood boundary: the batch verify
        # just computed every verdict, so invalid-sig envelopes drop HERE
        # — they never reach the herder's fetch plane, and (since the
        # verify cache latches only valid verdicts) they cannot park a
        # verdict in the shared cache either.  Valid envelopes flow on;
        # the herder's eager re-check is a warm-cache hit.
        for env, ok in zip(batch, verdicts):
            if ok:
                herder.recv_scp_envelope(env)
            else:
                self.m_scp_batch_rejected.inc()
                herder.note_envelope_rejected(env)

    def recv_flooded_msg(self, msg: StellarMessage, peer: Peer) -> bool:
        """Record a flooded message arrival; False if already seen."""
        return self.floodgate.add_record(msg, peer)

    def broadcast_message(self, msg: StellarMessage, force: bool = False) -> None:
        self.floodgate.broadcast(msg, force)

    def ledger_closed(self, ledger_seq: int) -> None:
        self.floodgate.clear_below(ledger_seq)
        self.tx_set_fetcher.stop_fetching_below(ledger_seq + 1)
        self.qset_fetcher.stop_fetching_below(ledger_seq + 1)

    def dump_info(self) -> dict:
        return {
            "peers": [
                {
                    "ip": p.ip(),
                    "port": p.remote_listening_port,
                    "ver": p.remote_version,
                    "auth": p.is_authenticated(),
                    "id": None if p.peer_id is None else p.peer_id.value.hex()[:8],
                }
                for p in self.peers
            ],
            "authenticated_count": self.get_authenticated_peer_count(),
            "sendq": self.sendq_stats.to_dict(),
        }
