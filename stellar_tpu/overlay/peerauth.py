"""PeerAuth — per-connection session authentication material
(reference: src/overlay/PeerAuth.{h,cpp}).

Each node keeps one ephemeral Curve25519 keypair plus an *auth cert*: the
ephemeral public key and an expiration time, ed25519-signed by the node's
identity key over ``sha256(networkID ‖ ENVELOPE_TYPE_AUTH ‖ expiration ‖
pubkey)`` (PeerAuth.cpp:32-44).  On handshake the peers exchange certs,
verify them (PeerAuth.cpp:72 — one ed25519 verify per connection), run ECDH
over the ephemeral keys, and HKDF-expand the shared key into one HMAC-SHA256
key per direction (PeerAuth.cpp:94-118).
"""

from __future__ import annotations

from ..crypto.ecdh import (
    ecdh_derive_public,
    ecdh_derive_shared_key,
    ecdh_random_secret,
)
from ..crypto.keys import PubKeyUtils
from ..crypto.sha import SHA256, hkdf_expand
from ..xdr.base import xdr_to_opaque
from ..xdr.entries import EnvelopeType
from ..xdr.overlay import AuthCert
from ..xdr.xtypes import Curve25519Public
from ..xdr.base import uint64, xenum

# cert lifetime (PeerAuth.cpp:27: expiration = now + 3600)
AUTH_CERT_LIFETIME_SECONDS = 3600


def _cert_signed_payload(network_id: bytes, expiration: int, pubkey: bytes) -> bytes:
    h = SHA256()
    h.add(network_id)
    h.add(xenum(EnvelopeType).pack(EnvelopeType.ENVELOPE_TYPE_AUTH))
    h.add(uint64.pack(expiration))
    h.add(pubkey)
    return h.finish()


class PeerAuth:
    def __init__(self, app):
        self.app = app
        self._secret = ecdh_random_secret()
        self.public = ecdh_derive_public(self._secret)
        self._cert: AuthCert | None = None

    # -- certs --------------------------------------------------------------
    def get_auth_cert(self) -> AuthCert:
        now = int(self.app.clock.now())
        if self._cert is None or self._cert.expiration < now + AUTH_CERT_LIFETIME_SECONDS // 2:
            expiration = now + AUTH_CERT_LIFETIME_SECONDS
            payload = _cert_signed_payload(self.app.network_id, expiration, self.public)
            sig = self.app.config.NODE_SEED.sign(payload)
            self._cert = AuthCert(Curve25519Public(self.public), expiration, sig)
        return self._cert

    def verify_remote_auth_cert(self, remote_node_id, cert: AuthCert) -> bool:
        """The third ed25519-verify site (PeerAuth.cpp:72)."""
        if cert.expiration < int(self.app.clock.now()):
            return False
        payload = _cert_signed_payload(
            self.app.network_id, cert.expiration, cert.pubkey.key
        )
        return PubKeyUtils.verify_sig(remote_node_id, cert.sig, payload)

    # -- session keys -------------------------------------------------------
    def get_shared_key(self, remote_public: bytes, we_called_remote: bool) -> bytes:
        return ecdh_derive_shared_key(
            self._secret, self.public, remote_public, local_first=we_called_remote
        )

    def get_sending_mac_key(
        self, local_nonce: bytes, remote_nonce: bytes,
        remote_public: bytes, we_called_remote: bool,
    ) -> bytes:
        """HKDF(shared, 0 ‖ localNonce ‖ remoteNonce) for the caller's
        send direction; role byte flips for the acceptor (PeerAuth.cpp:94)."""
        buf = (b"\x00" if we_called_remote else b"\x01") + local_nonce + remote_nonce
        return hkdf_expand(self.get_shared_key(remote_public, we_called_remote), buf)

    def get_receiving_mac_key(
        self, local_nonce: bytes, remote_nonce: bytes,
        remote_public: bytes, we_called_remote: bool,
    ) -> bytes:
        buf = (b"\x01" if we_called_remote else b"\x00") + remote_nonce + local_nonce
        return hkdf_expand(self.get_shared_key(remote_public, we_called_remote), buf)
