"""SendQueue — the overlay survival plane: one bounded, priority-classed
outbound queue per Peer (ROADMAP #6(b); reference gap: the reference sheds
on RECEIVE cost only — src/overlay/LoadManager.cpp, ported as
``loadmanager.py`` — and its write buffers grow without bound, so one
slow, crashed-but-connected, or hostile peer absorbs memory forever and a
saturating tx flood queues consensus-critical SCP traffic behind gossip).

Four classes, drained strictly in priority order:

- ``CRITICAL`` — SCP envelopes, handshake (HELLO/HELLO2/AUTH), errors.
  NEVER shed: consensus-message delivery latency is what breaks liveness
  under load (arXiv:2302.00418), so these jump every queue.
- ``FETCH``    — tx-set / quorum-set replies and the GET_* requests +
  DONT_HAVE.  Never shed either (they answer explicit asks), but they
  count against the byte budget.
- ``FLOOD``    — transaction broadcast.  Shed oldest-within-class.
- ``GOSSIP``   — peer-address exchange.  Shed oldest-within-class, and
  first when an unsheddable push needs room.

The queue is the single choke point: ``Peer.send_message`` classifies and
enqueues the packed ``StellarMessage`` BODY; MAC sequence numbers are
assigned at DRAIN time (``_emit``), so priority reordering and shedding
never open a gap in the receiver's MAC sequence.  That also makes
flooding pack-once/fan-out: ``Floodgate.broadcast`` packs the message
once and every peer's queue holds a reference to the same immutable
buffer — shedding is an O(1) deque pop, and a 100-peer flood serializes
the message exactly once.

Bounding (all knobs validated at boot, ``Config``):

- ``OVERLAY_SENDQ_BYTES``  — total queued bytes per peer.  0 disables the
  plane entirely: enqueue degenerates to the immediate assemble-and-send
  the reference performs, bit-exactly (pinned by tests/test_sendqueue.py).
- ``OVERLAY_SENDQ_FLOOD_MSGS`` — per-class message cap for FLOOD/GOSSIP.
- ``STRAGGLER_STALL_MS`` — a peer whose CRITICAL head-of-line age exceeds
  this budget (VirtualTimer-polled, so the disconnect lands INSIDE the
  budget deterministically), or whose unsheddable backlog would exceed
  the byte budget, is dropped with ``ERR_LOAD`` and its address backs off
  in the peerrecord book.

Transports are drains: the queue releases frames into the transport only
while the transport's in-flight window (``_inflight``) has room, and
``Peer.wrote_bytes(n)`` credits bytes the wire actually accepted back to
the queue.  Sheds are metered per class on the metrics fast lane
(``overlay.sendq.shed-<class>``); straggler disconnects emit an
``overlay.sendq.stall`` trace span.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Tuple

from ..crypto.sha import hmac_sha256
from ..trace import tracer_of
from ..util import VirtualTimer, xlog
from ..xdr.base import uint64
from ..xdr.overlay import ErrorCode, MessageType

log = xlog.logger("Overlay")

# priority classes, drained low index first
CLASS_CRITICAL = 0
CLASS_FETCH = 1
CLASS_FLOOD = 2
CLASS_GOSSIP = 3
N_CLASSES = 4
CLASS_NAMES = ("critical", "fetch", "flood", "gossip")
SHEDDABLE = (CLASS_FLOOD, CLASS_GOSSIP)

_CLASS_OF = {
    MessageType.ERROR_MSG: CLASS_CRITICAL,
    MessageType.HELLO: CLASS_CRITICAL,
    MessageType.HELLO2: CLASS_CRITICAL,
    MessageType.AUTH: CLASS_CRITICAL,
    MessageType.SCP_MESSAGE: CLASS_CRITICAL,
    MessageType.DONT_HAVE: CLASS_FETCH,
    MessageType.GET_TX_SET: CLASS_FETCH,
    MessageType.TX_SET: CLASS_FETCH,
    MessageType.GET_SCP_QUORUMSET: CLASS_FETCH,
    MessageType.SCP_QUORUMSET: CLASS_FETCH,
    MessageType.GET_SCP_STATE: CLASS_FETCH,
    MessageType.TRANSACTION: CLASS_FLOOD,
    MessageType.GET_PEERS: CLASS_GOSSIP,
    MessageType.PEERS: CLASS_GOSSIP,
}

# message types sent before MAC keys exist (handshake/error) — seq 0,
# zero MAC, exactly the reference's unauthenticated envelope
UNMACED = (MessageType.HELLO2, MessageType.ERROR_MSG)

# AuthenticatedMessage wire layout: union disc uint32(0) + V0{sequence
# uint64, message, mac opaque[32]} — the frame is spliced from these
# parts around the shared message body (bit-exact vs
# AuthenticatedMessage.v0_of(...).to_xdr(); pinned in test_sendqueue.py)
_AM_DISC = b"\x00\x00\x00\x00"
_ZERO_MAC = b"\x00" * 32
# fixed per-frame envelope bytes around the body (disc + seq + mac)
FRAME_ENVELOPE_BYTES = 4 + 8 + 32

# transport in-flight window: how many wire bytes may sit in the
# transport's own buffer (TCP _wbuf / loopback out_queue) before the
# queue holds frames back — the "kernel socket buffer" model.  Bounded
# by the byte cap so tiny test caps stay observable.
INFLIGHT_HIGH_WATER = 64 * 1024


def classify(msg_type) -> int:
    """Priority class for a StellarMessage type; unknown (future) types
    ride FETCH — bounded-but-never-shed, the conservative middle."""
    return _CLASS_OF.get(msg_type, CLASS_FETCH)


class SendQueueStats:
    """Per-OverlayManager aggregate across all peers (peers die with
    their connections; the chaos scoreboard needs the node-level view)."""

    __slots__ = (
        "shed_msgs",
        "shed_bytes",
        "straggler_disconnects",
        "bytes_high_water",
        "max_stall_ms",
        "emitted_frames",
        "oversized_admits",
    )

    def __init__(self):
        self.shed_msgs = [0] * N_CLASSES
        self.shed_bytes = [0] * N_CLASSES
        self.straggler_disconnects = 0
        self.bytes_high_water = 0
        self.max_stall_ms = 0.0
        self.emitted_frames = 0
        # while an admitted oversized frame is queued the high-water may
        # exceed the cap by that frame (the documented relaxed bound)
        self.oversized_admits = 0

    def to_dict(self) -> dict:
        return {
            "shed": dict(zip(CLASS_NAMES, self.shed_msgs)),
            "shed_bytes": dict(zip(CLASS_NAMES, self.shed_bytes)),
            "straggler_disconnects": self.straggler_disconnects,
            "bytes_high_water": self.bytes_high_water,
            "max_stall_ms": round(self.max_stall_ms, 1),
            "emitted_frames": self.emitted_frames,
            "oversized_admits": self.oversized_admits,
        }


def _emit(peer, msg_type, body: bytes) -> int:
    """Assemble the AuthenticatedMessage frame around ``body`` and hand
    it to the transport.  THE only legal ``send_frame`` call site
    (analysis rule ``send-path``): MAC sequence numbers are assigned
    here, at drain time, so the wire order IS the MAC order no matter
    how the queue reordered or shed."""
    if msg_type in UNMACED:
        seq_bytes = b"\x00" * 8
        mac = _ZERO_MAC
    else:
        # ONE encoding serves both the MAC input and the wire splice —
        # the MAC-input/wire-bytes equivalence is structural, not a
        # coincidence of two encoders agreeing
        seq_bytes = uint64.pack(peer.send_mac_seq)
        mac = hmac_sha256(peer.send_mac_key, seq_bytes + body)
        peer.send_mac_seq += 1
    frame = _AM_DISC + seq_bytes + body + mac
    # per-peer send accounting happens HERE, not at enqueue: shed frames
    # never hit the wire and must not count as sent messages/bytes
    peer._m_sent.mark()
    lm = getattr(peer.app.overlay_manager, "load_manager", None)
    if lm is not None and peer.peer_id is not None:
        lm.get_peer_costs(bytes(peer.peer_id.value)).bytes_send += len(frame)
    peer.send_frame(frame)
    return len(frame)


class SendQueue:
    """One per Peer; owns the four class deques, the byte/message caps,
    the transport in-flight window, and the straggler stall timer."""

    def __init__(self, peer):
        cfg = peer.app.config
        self.peer = peer
        self.max_bytes = int(getattr(cfg, "OVERLAY_SENDQ_BYTES", 0) or 0)
        self.active = self.max_bytes > 0
        self.max_class_msgs = int(getattr(cfg, "OVERLAY_SENDQ_FLOOD_MSGS", 1024))
        self.stall_budget = (
            float(getattr(cfg, "STRAGGLER_STALL_MS", 5000)) / 1000.0
        )
        # (body, msg_type, enqueued_at, wire_bytes) per entry; bodies are
        # shared immutable buffers (pack-once fan-out), so an entry is a
        # few pointers and shedding is an O(1) pop
        self._q: List[Deque[Tuple[bytes, object, float, int]]] = [
            deque() for _ in range(N_CLASSES)
        ]
        self.queued_bytes = 0
        # per-class queued bytes: the shed-feasibility pre-check needs
        # "how much room could evicting this order actually open"
        self.class_bytes = [0] * N_CLASSES
        self.bytes_high_water = 0
        self._inflight = 0
        self._inflight_limit = (
            min(self.max_bytes, INFLIGHT_HIGH_WATER) if self.active else 0
        )
        self.shed_msgs = [0] * N_CLASSES
        self.shed_bytes = [0] * N_CLASSES
        self.n_enqueued = 0
        self.n_emitted = 0
        # unsheddable frames bigger than the whole cap admitted alone on
        # an empty queue: while one is queued, bytes_high_water may
        # legitimately exceed max_bytes (bound = max(cap, that frame))
        self.n_oversized_admits = 0
        self.stalled_out = False  # set once on the straggler disconnect
        self.closed = False
        self._pass_through = not self.active
        self._draining = False
        self._stall_timer: Optional[VirtualTimer] = None
        self._stall_armed = False
        om = getattr(peer.app, "overlay_manager", None)
        self._stats: Optional[SendQueueStats] = (
            getattr(om, "sendq_stats", None) if om is not None else None
        )
        if self.active:
            m = peer.app.metrics
            self._m_shed = [
                m.new_meter(("overlay", "sendq", "shed-" + n), "message")
                for n in CLASS_NAMES
            ]
            self._m_straggler = m.new_meter(
                ("overlay", "sendq", "straggler"), "drop"
            )

    def bypass(self) -> None:
        """Teardown mode: further enqueues emit straight into the
        transport, skipping every cap — the goodbye ERROR frame of a
        disconnect must not queue behind the congestion that caused it
        (the transport is being torn down; delivery is best-effort,
        exactly the reference's direct write)."""
        self._pass_through = True

    # -- enqueue -------------------------------------------------------------
    def enqueue(self, msg, body: Optional[bytes] = None) -> bool:
        """Classify + queue one message; returns False when the message
        itself was shed.  ``body`` is the pre-packed StellarMessage XDR
        (the flood fan-out shares ONE buffer across every peer's queue);
        when absent the message packs here, once."""
        peer = self.peer
        if body is None:
            body = msg.to_xdr()
        if self._pass_through:
            # knob off (or the goodbye frame of a disconnect): the
            # reference's immediate assemble-and-send, bit-exact
            _emit(peer, msg.type, body)
            return True
        if self.closed:
            return False  # post-drop stragglers: the transport is gone
        cls = classify(msg.type)
        nbytes = FRAME_ENVELOPE_BYTES + len(body) + peer.FRAME_WIRE_OVERHEAD
        if cls in SHEDDABLE:
            if not self._fits_even_after_evicting(nbytes, cls):
                # the frame can NEVER fit — bigger than the whole cap,
                # or the unsheddable backlog leaves no room any shed
                # could open: the incoming frame itself is the only
                # shed.  Checked FIRST, before the count-cap loop or any
                # eviction, so an unfittable frame cannot cost the live
                # queued backlog a single frame chasing room that
                # arithmetically cannot exist.
                self._note_shed(cls, nbytes)
                return False
            q = self._q[cls]
            while len(q) >= self.max_class_msgs:
                self._shed_oldest(cls)
            self._make_room(nbytes, for_class=cls)
        else:
            if not self._make_room(nbytes, for_class=cls):
                if nbytes > self.max_bytes and self.queued_bytes == 0:
                    # an unsheddable frame larger than the WHOLE cap (a
                    # near-capacity TX_SET reply under a small cap) with
                    # NOTHING else queued: admit it alone rather than
                    # disconnecting a healthy, responsive peer — the
                    # memory bound becomes max(cap, one frame).  The
                    # same frame behind ANY unsheddable backlog takes
                    # the straggler branch below: a peer that cannot
                    # clear small frames will not clear a giant one,
                    # and admitting would stack oversized frames
                    self.n_oversized_admits += 1
                    if self._stats is not None:
                        self._stats.oversized_admits += 1
                else:
                    # the peer's unsheddable BACKLOG exceeds the budget
                    # even with every FLOOD/GOSSIP frame shed — it is a
                    # straggler, not a queue.  Deliberately instant (the
                    # ISSUE's hard memory bound), not stall-clocked: on
                    # TCP every emit attempts a synchronous kernel write
                    # first, so a backlog this deep means the socket
                    # already refused ~cap bytes — genuine backpressure,
                    # not a same-crank burst racing the event loop
                    self._disconnect_straggler(
                        "queued bytes over budget", stall_ms=None
                    )
                    return False
        now = peer.app.clock.now()
        self._q[cls].append((body, msg.type, now, nbytes))
        self.queued_bytes += nbytes
        self.class_bytes[cls] += nbytes
        self.n_enqueued += 1
        self._drain()
        if cls == CLASS_CRITICAL:
            # only a CRITICAL frame the drain could NOT release starts
            # the stall clock (the arm no-ops on an empty class queue),
            # so the uncongested fast path never touches the timer
            self._arm_stall_timer()
        # high-water is the POST-drain backlog: an uncongested queue that
        # passes frames straight through holds nothing
        if self.queued_bytes > self.bytes_high_water:
            self.bytes_high_water = self.queued_bytes
            if (
                self._stats is not None
                and self.queued_bytes > self._stats.bytes_high_water
            ):
                self._stats.bytes_high_water = self.queued_bytes
        return True

    @staticmethod
    def _evict_order(for_class: int) -> Tuple[int, ...]:
        """Classes an incoming push may evict, in eviction order: its own
        class first for sheddable pushes (keep the freshest of each
        stream), so a GOSSIP frame can never displace queued FLOOD
        traffic that drains ahead of it; an unsheddable push sheds
        GOSSIP before FLOOD (peer addresses are the cheapest loss)."""
        if for_class == CLASS_FLOOD:
            return (CLASS_FLOOD, CLASS_GOSSIP)
        if for_class == CLASS_GOSSIP:
            return (CLASS_GOSSIP,)
        return (CLASS_GOSSIP, CLASS_FLOOD)

    def _fits_even_after_evicting(self, nbytes: int, for_class: int) -> bool:
        """Could ``nbytes`` fit under the cap if every frame in the
        push's eviction order were shed?  (The backlog that survives is
        the unevictable remainder.)"""
        evictable = sum(
            self.class_bytes[c] for c in self._evict_order(for_class)
        )
        return self.queued_bytes - evictable + nbytes <= self.max_bytes

    def _make_room(self, nbytes: int, for_class: int) -> bool:
        """Shed the push's eviction order oldest-first until ``nbytes``
        fits under the byte cap (see ``_evict_order``)."""
        order = self._evict_order(for_class)
        while self.queued_bytes + nbytes > self.max_bytes:
            for cls in order:
                if self._q[cls]:
                    self._shed_oldest(cls)
                    break
            else:
                return False
        return True

    def _shed_oldest(self, cls: int) -> None:
        _body, _mt, _at, nbytes = self._q[cls].popleft()
        self.queued_bytes -= nbytes
        self.class_bytes[cls] -= nbytes
        self._note_shed(cls, nbytes)

    def _note_shed(self, cls: int, nbytes: int) -> None:
        self.shed_msgs[cls] += 1
        self.shed_bytes[cls] += nbytes
        self._m_shed[cls].mark()
        if self._stats is not None:
            self._stats.shed_msgs[cls] += 1
            self._stats.shed_bytes[cls] += nbytes

    # -- drain ---------------------------------------------------------------
    def credit(self, n: int) -> None:
        """Transport hook: ``n`` wire bytes left the building (kernel
        accepted them / the loopback delivered a frame) — open the
        in-flight window and keep draining."""
        if not self.active or self.closed:
            return
        self._inflight = max(0, self._inflight - n)
        self._drain()

    def _drain(self) -> None:
        if self.closed or self._draining:
            return
        self._draining = True
        try:
            while self._inflight < self._inflight_limit:
                entry = None
                for cls in range(N_CLASSES):
                    if self._q[cls]:
                        entry = self._q[cls].popleft()
                        break
                if entry is None:
                    break
                body, msg_type, _at, nbytes = entry
                self.queued_bytes -= nbytes
                self.class_bytes[cls] -= nbytes
                self._inflight += nbytes
                self.n_emitted += 1
                if self._stats is not None:
                    self._stats.emitted_frames += 1
                _emit(self.peer, msg_type, body)
        finally:
            self._draining = False

    # -- straggler detection -------------------------------------------------
    def _arm_stall_timer(self) -> None:
        if self._stall_armed or self.closed:
            return
        q = self._q[CLASS_CRITICAL]
        if not q:
            return
        if self._stall_timer is None:
            self._stall_timer = VirtualTimer(self.peer.app.clock)
        self._stall_armed = True
        head_at = q[0][2]
        self._stall_timer.expires_at(head_at + self.stall_budget)
        self._stall_timer.async_wait(self._stall_check)

    def _stall_check(self) -> None:
        self._stall_armed = False
        if self.closed:
            return
        q = self._q[CLASS_CRITICAL]
        if not q:
            return  # drained since arming; re-armed on the next enqueue
        age = self.peer.app.clock.now() - q[0][2]
        if age + 1e-9 >= self.stall_budget:
            self._disconnect_straggler(
                "CRITICAL head-of-line stall", stall_ms=age * 1000.0
            )
        else:
            self._arm_stall_timer()  # a fresher head took over

    def _disconnect_straggler(self, reason: str, stall_ms) -> None:
        if self.closed or self.stalled_out:
            return
        peer = self.peer
        self.stalled_out = True
        self._m_straggler.mark()
        if self._stats is not None:
            self._stats.straggler_disconnects += 1
            if stall_ms is not None and stall_ms > self._stats.max_stall_ms:
                self._stats.max_stall_ms = stall_ms
        tracer = tracer_of(peer.app)
        sp = tracer.begin("overlay.sendq.stall")
        log.warning(
            "straggler disconnect %r: %s (queued=%dB inflight=%dB)",
            peer, reason, self.queued_bytes, self._inflight,
        )
        # the goodbye ERROR frame must not re-enter the caps it just
        # tripped; everything after this is best-effort into a transport
        # that is being torn down anyway
        self.bypass()
        peer.note_straggler_backoff()
        peer.drop(ErrorCode.ERR_LOAD, "send queue " + reason)
        tracer.end(
            sp,
            reason=reason,
            stall_ms=round(stall_ms, 1) if stall_ms is not None else -1,
        )

    # -- teardown / views ----------------------------------------------------
    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self._stall_timer is not None:
            self._stall_timer.cancel()
        for q in self._q:
            q.clear()
        self.queued_bytes = 0
        self.class_bytes = [0] * N_CLASSES

    def stats(self) -> dict:
        return {
            "active": self.active,
            "queued_bytes": self.queued_bytes,
            "bytes_high_water": self.bytes_high_water,
            "inflight": self._inflight,
            "queued_msgs": {
                CLASS_NAMES[i]: len(self._q[i]) for i in range(N_CLASSES)
            },
            "shed": dict(zip(CLASS_NAMES, self.shed_msgs)),
            "shed_bytes": dict(zip(CLASS_NAMES, self.shed_bytes)),
            "enqueued": self.n_enqueued,
            "emitted": self.n_emitted,
            "oversized_admits": self.n_oversized_admits,
            "stalled_out": self.stalled_out,
        }
