"""LoopbackPeer — in-process peer pair for tests and simulation
(reference: src/overlay/LoopbackPeer.{h,cpp}).

A pair of Peers whose transports are each other's in-memory queues, with
fault injection: per-message drop / duplicate / reorder / byte-damage
probabilities, cork control, queue bounding, and a lossy/latency delivery
mode — the byzantine test rig (LoopbackPeer.h:24-100).  Delivery is
explicit (``deliver_one`` / ``deliver_all``) or scheduled on the clock, so
tests and the Simulation can crank message-by-message deterministically;
with ``latency`` set, scheduled delivery rides a VirtualTimer instead of
the next crank, modeling a slow link under the same (virtual or real)
clock.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass
from typing import Deque, Optional

from ..util import VirtualTimer, xlog
from ..xdr.overlay import MessageType
from .peer import Peer, PeerRole

log = xlog.logger("Overlay")

MAX_QUEUE_DEPTH = 1000


@dataclass
class FaultProfile:
    """One link side's fault knobs, as the chaos plane schedules them
    (stellar_tpu/scenarios/faults.py).  ``latency`` is seconds of delivery
    delay on the link; ``drain`` is a byte-rate cap (bytes/sec, 0 =
    unlimited) modeling a SLOW READER — scheduled pumps deliver at most
    their interval's byte budget and leave the rest queued, so the
    sender's transport backs up exactly like a peer that stops reading
    its socket; the probabilistic knobs map 1:1 onto the LoopbackPeer
    attributes of the same name.  NOTE: post-handshake, any
    drop/duplicate/reorder/damage that actually fires breaks the peers'
    MAC sequence and costs the CONNECTION (exactly like losing bytes
    inside a TCP stream) — a lossy profile therefore models link FLAPS,
    and liveness comes from the scenario's link doctor re-establishing
    the pair plus SCP rebroadcast.  A pure drain cap delivers whole
    frames in order and never flaps."""

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    damage: float = 0.0
    latency: float = 0.0
    drain: float = 0.0

    def apply(self, peer: "LoopbackPeer", seed: Optional[int] = None) -> None:
        peer.drop_prob = self.drop
        peer.duplicate_prob = self.duplicate
        peer.reorder_prob = self.reorder
        peer.damage_prob = self.damage
        peer.latency = self.latency
        peer.drain_rate = self.drain
        if seed is not None:
            # scenario-scoped determinism: the per-process ctor nonce makes
            # pairs uncorrelated but NOT replayable across two runs in one
            # process — a chaos run reseeds every armed peer from its own
            # seed space so the same fault program rolls the same faults
            peer._rng = random.Random(seed)


class LoopbackPeer(Peer):
    # per-process construction counter feeding the fault-roll seed (see
    # __init__): same construction order => same rolls, pairs uncorrelated
    _ctor_nonce = 0

    def __init__(self, app, role: str):
        super().__init__(app, role)
        self.remote: Optional["LoopbackPeer"] = None
        self.out_queue: Deque[bytes] = deque()
        self.corked = False
        self.max_queue_depth = MAX_QUEUE_DEPTH
        # fault injection (LoopbackPeer.h:36-41)
        self.damage_prob = 0.0
        self.drop_prob = 0.0
        self.duplicate_prob = 0.0
        self.reorder_prob = 0.0
        self.damage_cert = False
        self.damage_auth = False
        # lossy/latency delivery mode: >0 delays each scheduled pump by
        # this many (clock) seconds — frames sent while the pump is armed
        # ride the same delayed batch, the "slow link" shape
        self.latency = 0.0
        # slow-reader mode: >0 caps delivery at this many bytes/sec —
        # each scheduled pump spends one interval's byte budget and the
        # remainder waits, so the transport genuinely backs up (the shape
        # the send queue's shed/straggler plane defends against)
        self.drain_rate = 0.0
        self._drain_tokens = 0.0  # deficit-carrying byte budget (see _pump)
        self._latency_timer: Optional[VirtualTimer] = None
        self._latency_armed = False
        # seeded: fault-injection rolls (drop/damage/reorder) must replay
        # identically so a chaos run that found a bug can be re-run
        # (determinism rule; probabilities default 0.0, so the seed is
        # inert outside fault-injection tests).  Role bit + per-process
        # construction nonce: the two sides of a pair AND distinct pairs
        # in one topology all roll independent sequences, while the same
        # construction order replays the same faults run-to-run.
        LoopbackPeer._ctor_nonce += 1
        self._rng = random.Random(
            0x100BBAC0
            ^ (1 if role == PeerRole.WE_CALLED_REMOTE else 2)
            ^ (LoopbackPeer._ctor_nonce << 8)
        )
        self._closed = False

    # -- transport ----------------------------------------------------------
    def send_frame(self, data: bytes) -> None:
        if self._closed or self.remote is None:
            return
        self.out_queue.append(data)
        if not self.send_queue.active:
            # legacy bounded transport (knob-off only): indiscriminate
            # shed-oldest at depth.  With the survival plane on, the
            # class-aware SendQueue is the bounding layer and its
            # in-flight window keeps this deque small — shedding frames
            # that already consumed a MAC sequence number here would
            # break the receiver's sequence check.
            while len(self.out_queue) > self.max_queue_depth:
                self.out_queue.popleft()
        if not self.corked:
            self._schedule_delivery()

    def close_transport(self) -> None:
        self._closed = True
        remote = self.remote
        if remote is not None and not remote._closed:
            # async close notification, as a socket EOF would be
            self.app.clock.post(lambda: remote.drop())

    def ip(self) -> str:
        return "127.0.0.1"

    # -- explicit delivery (tests) ------------------------------------------
    def deliver_one(self) -> bool:
        """Move one queued frame into the remote peer, applying faults."""
        if self.remote is None or not self.out_queue:
            return False
        entry = self.out_queue.popleft()
        # entries re-queued by a fault are marked stale so the duplicate /
        # reorder faults can't recurse and delivery always terminates
        data, fresh = entry if isinstance(entry, tuple) else (entry, True)
        # like TCPPeer (which stamps on kernel-accepted bytes), write
        # progress is stamped when a frame actually moves on the "wire" —
        # a peer whose output only ever piles into a shedding queue makes
        # no progress and must trip the idle write timeout (advisor r03);
        # the byte count credits the send queue's in-flight window.
        # Fault-requeued (stale) entries were charged to the window only
        # ONCE, so only the fresh pass credits it — a double credit would
        # over-open the window and drift the transport bound.
        self.wrote_bytes(len(data) if fresh else 0)

        if self.drop_prob > 0 and self._rng.random() < self.drop_prob:
            log.debug("loopback dropping frame")
            return True
        if fresh and self.duplicate_prob > 0 and (
            self._rng.random() < self.duplicate_prob
        ):
            log.debug("loopback duplicating frame")
            self.out_queue.append((data, False))
        if fresh and self.reorder_prob > 0 and len(self.out_queue) > 0 and (
            self._rng.random() < self.reorder_prob
        ):
            log.debug("loopback reordering frame")
            self.out_queue.append((data, False))
            return True
        if self.damage_prob > 0 and self._rng.random() < self.damage_prob:
            log.debug("loopback damaging frame")
            data = self._flip_random_byte(data)
        # targeted handshake damage (LoopbackPeer.h:83-100), applied at
        # delivery so tests can arm the knobs after the connection starts
        mt = self._frame_msg_type(data)
        if self.damage_cert and mt == MessageType.HELLO2:
            data = self._damage_hello2_cert(data)
        if self.damage_auth and mt == MessageType.AUTH:
            data = self._flip_random_byte(data)

        remote = self.remote
        if remote is not None and not remote._closed:
            remote.recv_frame(data)
        return True

    def deliver_all(self) -> None:
        while self.deliver_one():
            pass

    def drop_all(self) -> None:
        self.out_queue.clear()

    # pump cadence for a drain-limited link with no latency set: the
    # byte budget per pump window is drain_rate * interval
    DRAIN_TICK = 0.05

    def _schedule_delivery(self) -> None:
        if self.latency > 0 or self.drain_rate > 0:
            if self._latency_armed:
                return  # queued frames ride the already-armed pump
            if self._latency_timer is None:
                self._latency_timer = VirtualTimer(self.app.clock)
            self._latency_armed = True
            self._latency_timer.expires_from_now(
                self.latency if self.latency > 0 else self.DRAIN_TICK
            )
            self._latency_timer.async_wait(self._latency_pump)
        else:
            self.app.clock.post(self._pump)

    def _latency_pump(self) -> None:
        self._latency_armed = False
        self._pump()
        # frames that arrived while this pump ran (or that a fault
        # re-queued, or that the drain cap left behind) wait a fresh
        # window, like bytes behind a slow link's send buffer
        if self.out_queue and not self.corked and not self._closed:
            self._schedule_delivery()

    def _pump(self) -> None:
        if self.corked:
            return
        if self.drain_rate > 0:
            # slow reader: token bucket with deficit carry — each window
            # adds rate*interval tokens; a frame bigger than one window's
            # quantum drives the balance negative and later windows pay
            # the debt off, so the AVERAGE rate equals the configured
            # bytes/sec regardless of frame size (no per-tick
            # at-least-one-frame under-throttle).  Whole frames, in
            # order, never faulted by the cap itself.
            interval = self.latency if self.latency > 0 else self.DRAIN_TICK
            quantum = self.drain_rate * interval
            self._drain_tokens += quantum
            if not self.out_queue:
                # idle links must not bank unbounded burst credit
                self._drain_tokens = min(self._drain_tokens, quantum)
            while self.out_queue and self._drain_tokens > 0:
                head = self.out_queue[0]
                data, fresh = (
                    head if isinstance(head, tuple) else (head, True)
                )
                if fresh:
                    # fault-requeued (stale) entries were billed on
                    # their first pass — mirroring the wrote_bytes
                    # fresh-only credit below, or a reorder/duplicate
                    # fault under a drain cap would double-charge the
                    # budget and sink the link below its configured rate
                    self._drain_tokens -= len(data)
                if not self.deliver_one():
                    break
        else:
            self.deliver_all()

    def set_corked(self, corked: bool) -> None:
        self.corked = corked
        if not corked:
            self._schedule_delivery()

    @staticmethod
    def _damage_hello2_cert(data: bytes) -> bytes:
        """Corrupt the auth-cert signature inside a HELLO2 frame."""
        from ..xdr.overlay import AuthenticatedMessage

        try:
            amsg = AuthenticatedMessage.from_xdr(data)
            cert = amsg.value.message.value.cert
            sig = bytearray(cert.sig)
            sig[0] ^= 0x01
            cert.sig = bytes(sig)
            return amsg.to_xdr()
        except Exception:
            return data

    @staticmethod
    def _frame_msg_type(data: bytes):
        """StellarMessage type inside an XDR AuthenticatedMessage frame:
        union disc (4) + sequence (8) + message type (4)."""
        if len(data) < 16:
            return None
        try:
            return MessageType(int.from_bytes(data[12:16], "big"))
        except ValueError:
            return None

    def _flip_random_byte(self, data: bytes) -> bytes:
        if not data:
            return data
        i = self._rng.randrange(len(data))
        b = bytearray(data)
        b[i] ^= 1 << self._rng.randrange(8)
        return bytes(b)


class LoopbackPeerConnection:
    """Wires an initiator/acceptor LoopbackPeer pair between two apps and
    kicks off the handshake (LoopbackPeer.cpp LoopbackPeerConnection)."""

    def __init__(self, initiator_app, acceptor_app):
        self.initiator = LoopbackPeer(initiator_app, PeerRole.WE_CALLED_REMOTE)
        self.acceptor = LoopbackPeer(acceptor_app, PeerRole.REMOTE_CALLED_US)
        self.initiator.remote = self.acceptor
        self.acceptor.remote = self.initiator
        initiator_app.overlay_manager.add_pending_peer(self.initiator)
        acceptor_app.overlay_manager.add_pending_peer(self.acceptor)
        self.initiator.connect_handler()
