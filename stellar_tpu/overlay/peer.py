"""Peer — the per-connection protocol state machine
(reference: src/overlay/Peer.{h,cpp}).

Handshake (HELLO2 path, Peer.cpp:949-1005): initiator sends HELLO2 with its
auth cert + nonce; acceptor verifies the cert, derives per-direction
HMAC-SHA256 keys from ECDH(cert ephemerals) + both nonces, replies HELLO2;
initiator does the same and sends AUTH; acceptor replies AUTH.  Every frame
after HELLO2 carries a strictly-increasing sequence number and an HMAC over
``xdr(seq ‖ msg)`` (Peer.cpp:461-464, verified at :524-543); any mismatch
drops the connection — transport-level tamper evidence on top of the
per-message ed25519 signatures.

TPU note: inbound SCP envelopes are pre-warmed through the app's SigBackend
(one batched verify populating the shared cache) before being handed to the
Herder, so the Herder's eager per-envelope check is a cache hit.
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto import sha256
from ..crypto.sha import hmac_sha256_verify
from ..crypto.sodium import randombytes
from ..util import xlog
from ..util.clock import VirtualTimer
from ..xdr.base import uint64, xdr_to_opaque
from .sendqueue import SendQueue
from ..xdr.overlay import (
    Auth,
    AuthCert,
    AuthenticatedMessage,
    DontHave,
    Error,
    ErrorCode,
    Hello2,
    MessageType,
    PeerAddress,
    PeerAddressIp,
    IPAddrType,
    StellarMessage,
)
from ..xdr.scp import SCPEnvelope
from ..xdr.xtypes import HmacSha256Mac, PublicKey

log = xlog.logger("Overlay")


class PeerRole:
    WE_CALLED_REMOTE = "WE_CALLED_REMOTE"
    REMOTE_CALLED_US = "REMOTE_CALLED_US"


class PeerState:
    CONNECTING = 0
    CONNECTED = 1
    GOT_HELLO = 2
    GOT_AUTH = 3
    CLOSING = 4


# hot-path dispatch table (resolved per-instance via getattr)
_DISPATCH = {
    MessageType.ERROR_MSG: "recv_error",
    MessageType.HELLO2: "recv_hello2",
    MessageType.AUTH: "recv_auth",
    MessageType.DONT_HAVE: "recv_dont_have",
    MessageType.GET_PEERS: "recv_get_peers",
    MessageType.PEERS: "recv_peers",
    MessageType.GET_TX_SET: "recv_get_tx_set",
    MessageType.TX_SET: "recv_tx_set",
    MessageType.TRANSACTION: "recv_transaction",
    MessageType.GET_SCP_QUORUMSET: "recv_get_scp_quorum_set",
    MessageType.SCP_QUORUMSET: "recv_scp_quorum_set",
    MessageType.SCP_MESSAGE: "recv_scp_message",
    MessageType.GET_SCP_STATE: "recv_get_scp_state",
}


class Peer:
    # wire bytes the transport adds around each frame (TCP: 4-byte
    # length header) — the send queue charges them against its in-flight
    # window so queue credits balance against raw socket byte counts
    FRAME_WIRE_OVERHEAD = 0

    def __init__(self, app, role: str):
        self.app = app
        self.role = role
        self.state = (
            PeerState.CONNECTING
            if role == PeerRole.WE_CALLED_REMOTE
            else PeerState.CONNECTED
        )
        self.peer_id: Optional[PublicKey] = None
        self.remote_version = ""
        self.remote_overlay_version = 0
        self.remote_listening_port = 0
        self.send_nonce = randombytes(32)
        self.recv_nonce = b""
        self.send_mac_key = b""
        self.recv_mac_key = b""
        self.send_mac_seq = 0
        self.recv_mac_seq = 0
        self._m_drop = app.metrics.new_meter(("overlay", "drop", "count"), "drop")
        self._m_recv = app.metrics.new_meter(("overlay", "message", "read"), "message")
        self._m_sent = app.metrics.new_meter(("overlay", "message", "write"), "message")
        self._m_timeout_idle = app.metrics.new_meter(
            ("overlay", "timeout", "idle"), "timeout"
        )
        # idle-drop timer (Peer::startIdleTimer, Peer.cpp:231-264): a peer
        # silent in both directions for io_timeout_seconds is dropped —
        # 5s during handshake, 30s once authenticated.  The transports
        # stamp last_read/last_write at the BYTE level (received_bytes/
        # wrote_bytes), so a slow large frame counts as activity and a
        # dead connection with queued-but-unsent output does not.
        self.last_read = app.clock.now()
        self.last_write = app.clock.now()
        self._idle_timer = VirtualTimer(app.clock)
        # the overlay survival plane: bounded priority-classed outbound
        # queue (overlay/sendqueue.py) — send_message enqueues, the queue
        # drains into the transport in class order, OVERLAY_SENDQ_BYTES=0
        # degenerates to the reference's immediate unbounded sends
        self.send_queue = SendQueue(self)
        # one-way fault seam (chaos plane, ISSUE r19): True silently drops
        # every outbound message at the send choke point, BEFORE it enters
        # the queue or consumes a MAC sequence number — the half-open-
        # connection model.  The reverse direction keeps delivering with
        # valid MACs, and clearing the flag resumes THIS direction on the
        # same connection with the sequence intact (no flap): dropping any
        # later (post-queue or post-sequencing) would open a MAC-sequence
        # gap and cost the connection on heal.
        self.outbound_blackhole = False
        self._start_idle_timer()

    def io_timeout_seconds(self) -> int:
        return 30 if self.is_authenticated() else 5

    def received_bytes(self) -> None:
        """Transport hook: any inbound bytes count as read activity
        (Peer::receivedBytes — per byte, not per decoded frame)."""
        self.last_read = self.app.clock.now()

    def wrote_bytes(self, n: int = 0) -> None:
        """Transport hook: bytes actually flushed to the wire count as
        write activity (queued-but-unsent output does not) AND credit the
        send queue's in-flight window so it can release more frames."""
        self.last_write = self.app.clock.now()
        if n:
            self.send_queue.credit(n)

    def _start_idle_timer(self) -> None:
        if self.should_abort():
            return
        self._idle_timer.expires_from_now(self.io_timeout_seconds())
        self._idle_timer.async_wait(self._idle_timer_expired)

    def _idle_timer_expired(self) -> None:
        now = self.app.clock.now()
        timeout = self.io_timeout_seconds()
        if now - self.last_read >= timeout and now - self.last_write >= timeout:
            log.warning("idle timeout on %r", self)
            self._m_timeout_idle.mark()
            self.drop()
        else:
            self._start_idle_timer()

    # -- abstract transport -------------------------------------------------
    def send_frame(self, data: bytes) -> None:
        raise NotImplementedError

    def close_transport(self) -> None:
        raise NotImplementedError

    def ip(self) -> str:
        return ""

    # -- identity -----------------------------------------------------------
    def is_connected(self) -> bool:
        return self.state not in (PeerState.CONNECTING, PeerState.CLOSING)

    def is_authenticated(self) -> bool:
        return self.state == PeerState.GOT_AUTH

    def should_abort(self) -> bool:
        om = self.app.overlay_manager
        return self.state == PeerState.CLOSING or (
            om is not None and om.is_shutting_down()
        )

    def __repr__(self):
        pid = "?" if self.peer_id is None else self.peer_id.value[:4].hex()
        return f"<Peer {self.role[:2]} {pid} s={self.state}>"

    # -- outbound -----------------------------------------------------------
    def connect_handler(self) -> None:
        """Transport established (TCPPeer::connectHandler): say hello."""
        self.state = PeerState.CONNECTED
        self.send_hello2()

    def send_hello2(self) -> None:
        cfg = self.app.config
        om = self.app.overlay_manager
        msg = StellarMessage(
            MessageType.HELLO2,
            Hello2(
                ledgerVersion=cfg.LEDGER_PROTOCOL_VERSION,
                overlayVersion=cfg.OVERLAY_PROTOCOL_VERSION,
                overlayMinVersion=cfg.OVERLAY_PROTOCOL_MIN_VERSION,
                networkID=self.app.network_id,
                versionStr=cfg.VERSION_STR,
                listeningPort=cfg.PEER_PORT,
                peerID=cfg.NODE_SEED.get_public_key(),
                cert=om.peer_auth.get_auth_cert(),
                nonce=self.send_nonce,
            ),
        )
        self.send_message(msg)

    def send_auth(self) -> None:
        self.send_message(StellarMessage(MessageType.AUTH, Auth(0)))

    def send_error(self, code: ErrorCode, text: str) -> None:
        self.send_message(StellarMessage(MessageType.ERROR_MSG, Error(code, text)))

    def send_dont_have(self, msg_type: MessageType, item_hash: bytes) -> None:
        self.send_message(
            StellarMessage(MessageType.DONT_HAVE, DontHave(msg_type, item_hash))
        )

    def send_get_tx_set(self, h: bytes) -> None:
        self.send_message(StellarMessage(MessageType.GET_TX_SET, h))

    def send_get_quorum_set(self, h: bytes) -> None:
        self.send_message(StellarMessage(MessageType.GET_SCP_QUORUMSET, h))

    def send_get_peers(self) -> None:
        self.send_message(StellarMessage(MessageType.GET_PEERS, None))

    def send_peers(self) -> None:
        from .peerrecord import PeerRecord

        addrs: List[PeerAddress] = []
        for pr in PeerRecord.load_peers(self.app.database, 50, self.app.clock.now() + 3600):
            if pr.is_private_address():
                continue  # never advertise RFC1918 space (Peer.cpp:392)
            try:
                parts = bytes(int(x) for x in pr.ip.split("."))
            except ValueError:
                continue
            if len(parts) != 4:
                continue
            addrs.append(
                PeerAddress(
                    PeerAddressIp(IPAddrType.IPv4, parts), pr.port, pr.num_failures
                )
            )
        self.send_message(StellarMessage(MessageType.PEERS, addrs))

    def send_message(self, msg: StellarMessage, body: bytes = None) -> None:
        """THE outbound choke point (Peer::sendMessage, Peer.cpp:457-467):
        classify + enqueue on the survival-plane send queue, which wraps
        the body in an AuthenticatedMessage (MAC + seq assigned at DRAIN
        time, unless handshake/error) as it releases frames into the
        transport.  ``body`` is the pre-packed StellarMessage XDR — the
        flood fan-out passes ONE shared buffer to every peer."""
        if self.should_abort() and msg.type != MessageType.ERROR_MSG:
            return
        if self.outbound_blackhole:
            return  # one-way fault: the frame vanishes pre-queue, pre-seq
        # the sent-message meter and bytes_send both mark at the queue's
        # DRAIN (sendqueue._emit) — a shed frame never counted as sent
        self.send_queue.enqueue(msg, body)

    def note_straggler_backoff(self) -> None:
        """A straggler disconnect (ERR_LOAD) lands the peer's address in
        peerrecord backoff, so the next overlay tick does not instantly
        redial a connection we just shed for being underwater."""
        from .peerrecord import PeerRecord

        ip = self.ip()
        port = self.remote_listening_port
        if not ip or not port:
            return
        try:
            pr = PeerRecord.load(self.app.database, ip, port) or PeerRecord(
                ip, port
            )
            pr.back_off(self.app.database, self.app.clock.now())
        except Exception as e:  # DB closing mid-teardown must not mask the drop
            log.warning("could not back off straggler %s:%d: %s", ip, port, e)

    # -- inbound ------------------------------------------------------------
    def recv_frame(self, data: bytes) -> None:
        self.received_bytes()
        try:
            amsg = AuthenticatedMessage.from_xdr(data)
        except Exception as e:
            log.warning("bad frame from %r: %s", self, e)
            self.drop()
            return
        # attribute processing cost + bytes to this peer (LoadManager)
        lm = getattr(self.app.overlay_manager, "load_manager", None)
        node = bytes(self.peer_id.value) if self.peer_id is not None else None
        if lm is None:
            self.recv_authenticated_message(amsg)
            return
        with lm.peer_context(node):
            if node is not None:
                lm.get_peer_costs(node).bytes_recv += len(data)
            self.recv_authenticated_message(amsg)

    def recv_authenticated_message(self, amsg: AuthenticatedMessage) -> None:
        """Sequence + MAC check once keys exist (Peer.cpp:522-543)."""
        v0 = amsg.value
        msg = v0.message
        if self.state >= PeerState.GOT_HELLO and msg.type != MessageType.ERROR_MSG:
            if v0.sequence != self.recv_mac_seq:
                log.warning("unexpected auth sequence from %r", self)
                self.drop(ErrorCode.ERR_AUTH, "unexpected auth sequence")
                return
            if not hmac_sha256_verify(
                v0.mac.mac, self.recv_mac_key, xdr_to_opaque((uint64, v0.sequence), msg)
            ):
                log.warning("MAC failed on recv from %r", self)
                self.drop(ErrorCode.ERR_AUTH, "unexpected MAC")
                return
            self.recv_mac_seq += 1
        self.recv_message(msg)

    def recv_message(self, msg: StellarMessage) -> None:
        if self.should_abort():
            return
        self._m_recv.mark()
        t = msg.type
        if not self.is_authenticated() and t not in (
            MessageType.HELLO2,
            MessageType.AUTH,
            MessageType.ERROR_MSG,
        ):
            log.warning("recv %s before handshake from %r", t.name, self)
            self.drop()
            return
        name = _DISPATCH.get(t)
        if name is None:
            log.warning("unhandled message type %s from %r", t, self)
            return
        getattr(self, name)(msg)

    # -- handshake handlers -------------------------------------------------
    def recv_hello2(self, msg: StellarMessage) -> None:
        elo: Hello2 = msg.value
        om = self.app.overlay_manager
        if self.state >= PeerState.GOT_HELLO:
            log.warning("unexpected HELLO2 from %r", self)
            self.drop()
            return
        if not om.peer_auth.verify_remote_auth_cert(elo.peerID, elo.cert):
            log.warning("bad auth cert from %r", self)
            self.drop()
            return
        if elo.peerID == self.app.config.NODE_SEED.get_public_key():
            self.drop(ErrorCode.ERR_CONF, "connecting to self")
            return
        if elo.networkID != self.app.network_id:
            self.drop(ErrorCode.ERR_CONF, "wrong network passphrase")
            return
        if not (0 < elo.listeningPort <= 65535):
            self.drop(ErrorCode.ERR_CONF, "bad port number")
            return
        for p in om.get_peers():
            if p is not self and p.peer_id == elo.peerID:
                self.drop(ErrorCode.ERR_CONF, "already connected")
                return
        if (
            elo.overlayMinVersion > self.app.config.OVERLAY_PROTOCOL_VERSION
            or elo.overlayVersion < self.app.config.OVERLAY_PROTOCOL_MIN_VERSION
        ):
            self.drop(ErrorCode.ERR_CONF, "wrong protocol version")
            return
        self.peer_id = elo.peerID
        self.remote_version = elo.versionStr
        self.remote_overlay_version = elo.overlayVersion
        self.remote_listening_port = elo.listeningPort
        self.recv_nonce = elo.nonce
        we_called = self.role == PeerRole.WE_CALLED_REMOTE
        self.send_mac_seq = 0
        self.recv_mac_seq = 0
        self.send_mac_key = om.peer_auth.get_sending_mac_key(
            self.send_nonce, self.recv_nonce, elo.cert.pubkey.key, we_called
        )
        self.recv_mac_key = om.peer_auth.get_receiving_mac_key(
            self.send_nonce, self.recv_nonce, elo.cert.pubkey.key, we_called
        )
        self.state = PeerState.GOT_HELLO
        if we_called:
            self.send_auth()
        else:
            self.send_hello2()

    def recv_auth(self, msg: StellarMessage) -> None:
        if self.state != PeerState.GOT_HELLO:
            self.drop(ErrorCode.ERR_MISC, "out-of-order AUTH")
            return
        self.state = PeerState.GOT_AUTH
        if self.role == PeerRole.REMOTE_CALLED_US:
            self.send_auth()
        om = self.app.overlay_manager
        if not om.accept_authenticated_peer(self):
            self.drop(ErrorCode.ERR_LOAD, "peer rejected")
            return
        # learn more of the network, and push our recent SCP state so a
        # late joiner can follow consensus (Peer.cpp:1095: seq 0 = recent)
        self.send_get_peers()
        if self.app.herder is not None:
            self.app.herder.send_scp_state_to_peer(0, self)

    def recv_error(self, msg: StellarMessage) -> None:
        err: Error = msg.value
        log.warning("peer %r sent error %s: %s", self, err.code, err.msg)
        self.drop()

    # -- item handlers ------------------------------------------------------
    def recv_dont_have(self, msg: StellarMessage) -> None:
        dh: DontHave = msg.value
        self.app.herder.peer_doesnt_have(dh.type, dh.reqHash, self)

    def recv_get_peers(self, msg: StellarMessage) -> None:
        self.send_peers()

    def recv_peers(self, msg: StellarMessage) -> None:
        import random

        from .peerrecord import SECONDS_PER_BACKOFF, PeerRecord

        cfg = self.app.config
        for addr in msg.value:
            if addr.ip.type != IPAddrType.IPv4:
                continue
            if not (0 < addr.port <= 65535):
                continue  # remote-supplied; don't let bad data near the DB
            ip = ".".join(str(b) for b in addr.ip.value)
            try:
                # numFailures deliberately NOT copied from the remote — we
                # may have better luck, and remote data must not poison
                # our backoff (Peer.cpp:1128-1151); the first attempt is
                # randomized over the new-peer window instead of now() so a
                # PEERS burst doesn't stampede the next tick into dialing
                # every learned address at once
                pr = PeerRecord(
                    ip,
                    addr.port,
                    self.app.clock.now()
                    # analysis: off determinism -- anti-stampede jitter over LEARNED peer addresses: spreading dials across the backoff window is the point, and the jitter never feeds consensus (PR 1 review added it deliberately)
                    + random.uniform(0.0, SECONDS_PER_BACKOFF),
                    0,
                )
                if pr.is_private_address():
                    log.warning("ignoring received private address %s", pr.to_string())
                    continue
                if pr.is_self_address_and_port(self.ip(), cfg.PEER_PORT):
                    log.debug("ignoring received self-address %s", pr.to_string())
                    continue
                if pr.is_localhost() and not cfg.ALLOW_LOCALHOST_FOR_TESTING:
                    log.warning("ignoring received localhost %s", pr.to_string())
                    continue
                pr.insert_if_new(self.app.database)
            except Exception as e:
                log.warning("could not store peer %s:%d: %s", ip, addr.port, e)

    def recv_get_tx_set(self, msg: StellarMessage) -> None:
        ts = self.app.herder.get_tx_set(msg.value)
        if ts is not None:
            self.send_message(StellarMessage(MessageType.TX_SET, ts.to_xdr()))
        else:
            self.send_dont_have(MessageType.TX_SET, msg.value)

    def recv_tx_set(self, msg: StellarMessage) -> None:
        from ..herder.txset import TxSetFrame

        frame = TxSetFrame.from_xdr_set(self.app.network_id, msg.value)
        self.app.herder.recv_tx_set(frame.get_contents_hash(), frame)

    def recv_transaction(self, msg: StellarMessage) -> None:
        from ..tx.frame import TransactionFrame
        from ..herder.herder import TX_STATUS_PENDING

        om = self.app.overlay_manager
        if not om.recv_flooded_msg(msg, self):
            return  # duplicate
        tx = TransactionFrame.make_from_wire(self.app.network_id, msg.value)
        ingest = getattr(self.app, "ingest", None)
        if ingest is not None:
            # admission front door: the tx joins the current micro-batch
            # and floods onward ONLY once the batch verdict admits it —
            # an invalid-sig flood dies here without fan-out
            def _flood_on_accept(status, _msg=msg, _om=om):
                if status == TX_STATUS_PENDING:
                    _om.broadcast_message(_msg)

            ingest.submit(tx, on_status=_flood_on_accept)
        elif self.app.herder.recv_transaction(tx) == TX_STATUS_PENDING:
            om.broadcast_message(msg)

    def recv_get_scp_quorum_set(self, msg: StellarMessage) -> None:
        qset = self.app.herder.get_qset(msg.value)
        if qset is not None:
            self.send_message(StellarMessage(MessageType.SCP_QUORUMSET, qset))
        else:
            self.send_dont_have(MessageType.SCP_QUORUMSET, msg.value)

    def recv_scp_quorum_set(self, msg: StellarMessage) -> None:
        from ..scp.quorum import qset_hash

        self.app.herder.recv_scp_quorum_set(qset_hash(msg.value), msg.value)

    def recv_scp_message(self, msg: StellarMessage) -> None:
        om = self.app.overlay_manager
        if not om.recv_flooded_msg(msg, self):
            return  # already seen
        envelope: SCPEnvelope = msg.value
        # all envelopes that arrive this crank verify as ONE SigBackend
        # batch before reaching the herder (OverlayManager flush)
        om.enqueue_scp_envelope(envelope)

    def recv_get_scp_state(self, msg: StellarMessage) -> None:
        self.app.herder.send_scp_state_to_peer(msg.value, self)

    # -- teardown -----------------------------------------------------------
    def drop(self, code: Optional[ErrorCode] = None, text: str = "") -> None:
        if self.state == PeerState.CLOSING:
            return
        if code is not None:
            try:
                # the goodbye frame must not queue behind the congestion
                # that may have caused this drop — emit it straight into
                # the transport like the reference's direct write (the
                # straggler path already runs in bypass by the time it
                # gets here)
                self.send_queue.bypass()
                self.send_error(code, text)
            except Exception:
                pass
        self.state = PeerState.CLOSING
        self._m_drop.mark()
        self._idle_timer.cancel()
        self.send_queue.close()
        om = self.app.overlay_manager
        if om is not None:
            om.drop_peer(self)
        self.close_transport()
