"""LoadManager — per-peer load attribution and shedding
(reference: src/overlay/LoadManager.{h,cpp}).

Heuristic blame assignment: while a peer's message is being processed, a
``PeerContext`` is on the stack; when it exits, the elapsed work time,
bytes moved, and SQL query count since entry are debited to that peer.
When the node's recent idle fraction drops below MINIMUM_IDLE_PERCENT,
``maybe_shed_excess_load`` drops the single worst-costed connected peer.
Costs live in an LRU so churn in low-cost peers can't grow the table.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional

from ..util import xlog

log = xlog.logger("Overlay")

LRU_SIZE = 128


class PeerCosts:
    __slots__ = ("time_spent", "bytes_send", "bytes_recv", "sql_queries")

    def __init__(self):
        self.time_spent = 0.0
        self.bytes_send = 0
        self.bytes_recv = 0
        self.sql_queries = 0

    def is_less_than(self, other: "PeerCosts") -> bool:
        """Lexicographic by (time, send, recv, sql) — LoadManager.cpp
        PeerCosts::isLessThan."""
        mine = (self.time_spent, self.bytes_send, self.bytes_recv, self.sql_queries)
        theirs = (
            other.time_spent,
            other.bytes_send,
            other.bytes_recv,
            other.sql_queries,
        )
        return mine < theirs

    def to_json(self) -> dict:
        return {
            "time_spent_s": round(self.time_spent, 6),
            "bytes_send": self.bytes_send,
            "bytes_recv": self.bytes_recv,
            "sql_queries": self.sql_queries,
        }


class LoadManager:
    def __init__(self, app):
        self.app = app
        self._costs: OrderedDict[bytes, PeerCosts] = OrderedDict()
        self._shed_meter = app.metrics.new_meter(("overlay", "drop", "load-shed"), "drop")
        # receive-side shed decisions, read by the chaos scoreboard next
        # to the send-side (SendQueue) shed counters
        self.n_sheds = 0
        # recent-load window for the idle estimate
        self._window_start = time.monotonic()
        self._busy_seconds = 0.0

    def get_peer_costs(self, node_id: bytes) -> PeerCosts:
        pc = self._costs.get(node_id)
        if pc is None:
            pc = PeerCosts()
            self._costs[node_id] = pc
        self._costs.move_to_end(node_id)
        while len(self._costs) > LRU_SIZE:
            self._costs.popitem(last=False)
        return pc

    def report_loads(self) -> dict:
        """Diagnostic view for /peers &c (LoadManager::reportLoads)."""
        out = {}
        for node_id, pc in self._costs.items():
            out[node_id.hex()[:16]] = pc.to_json()
        return out

    # -- idle tracking ------------------------------------------------------
    def _note_busy(self, seconds: float) -> None:
        self._busy_seconds += seconds

    def _idle_percent(self) -> int:
        elapsed = time.monotonic() - self._window_start
        if elapsed <= 0:
            return 100
        busy = min(self._busy_seconds, elapsed)
        return int(100 * (1.0 - busy / elapsed))

    def _reset_window(self) -> None:
        self._window_start = time.monotonic()
        self._busy_seconds = 0.0

    def maybe_shed_excess_load(self) -> None:
        """Drop the worst-costed authenticated peer when idle time is
        below MINIMUM_IDLE_PERCENT (LoadManager::maybeShedExcessLoad)."""
        min_idle = self.app.config.MINIMUM_IDLE_PERCENT
        if min_idle <= 0:
            # keep the accounting window fresh while shedding is disabled,
            # or a later enable (via /ll or config reload) would judge idle
            # time over the entire process uptime and shed spuriously
            self._reset_window()
            return
        if self._idle_percent() >= min_idle:
            self._reset_window()
            return
        om = self.app.overlay_manager
        peers = [p for p in om.get_peers() if p.is_authenticated()]
        worst = None
        worst_costs = None
        for p in peers:
            pid = getattr(p, "peer_id", None)
            if pid is None:
                continue
            # peek only: inserting/promoting here would LRU-evict the very
            # cost records the scan is ranking
            pc = self._costs.get(bytes(pid.value))
            if pc is None:
                continue
            if worst_costs is None or worst_costs.is_less_than(pc):
                worst, worst_costs = p, pc
        if worst is not None:
            log.warning(
                "load shedding peer %s (idle %d%% < %d%%)",
                worst,
                self._idle_percent(),
                min_idle,
            )
            self._shed_meter.mark()
            self.n_sheds += 1
            worst.drop()
        self._reset_window()

    def peer_context(self, node_id: Optional[bytes]) -> "PeerContext":
        return PeerContext(self, node_id)


class PeerContext:
    """Stack context attributing work to a peer (LoadManager::PeerContext)."""

    def __init__(self, lm: LoadManager, node_id: Optional[bytes]):
        self.lm = lm
        self.node_id = node_id
        self._t0 = 0.0
        self._q0 = 0

    def __enter__(self):
        self._t0 = time.monotonic()
        self._q0 = getattr(self.lm.app.database, "query_count", 0)
        return self

    def __exit__(self, *exc):
        dt = time.monotonic() - self._t0
        self.lm._note_busy(dt)
        if self.node_id is not None:
            pc = self.lm.get_peer_costs(self.node_id)
            pc.time_spent += dt
            pc.sql_queries += (
                getattr(self.lm.app.database, "query_count", 0) - self._q0
            )
        return False
