"""TCPPeer / PeerDoor — real-socket transport on the VirtualClock selector
(reference: src/overlay/TCPPeer.{h,cpp}, src/overlay/PeerDoor.{h,cpp}).

Frames are 4-byte big-endian length-prefixed XDR ``AuthenticatedMessage``s.
All socket callbacks run on the clock's crank (the node's single IO thread),
mirroring the reference's asio single-reactor model.
"""

from __future__ import annotations

import errno
import selectors
import socket
from collections import deque
from typing import Deque, Optional

from ..util import xlog
from .peer import Peer, PeerRole

log = xlog.logger("Overlay")

MAX_MESSAGE_SIZE = 16 * 1024 * 1024
HDR_SIZE = 4


class TCPPeer(Peer):
    # the 4-byte length header send_frame prepends: charged by the send
    # queue per frame, credited back through wrote_bytes(n) as the kernel
    # accepts raw wire bytes — charge and credit balance exactly
    FRAME_WIRE_OVERHEAD = HDR_SIZE

    def __init__(self, app, role: str, sock: socket.socket):
        super().__init__(app, role)
        self.sock = sock
        self.sock.setblocking(False)
        self._rbuf = bytearray()
        self._wbuf: Deque[bytes] = deque()
        self._wpos = 0
        self._writing = False
        self._connecting = role == PeerRole.WE_CALLED_REMOTE
        self._closed = False
        self._peer_ip = ""
        try:
            self._peer_ip = sock.getpeername()[0]
        except OSError:
            pass

    # -- connection setup ---------------------------------------------------
    @classmethod
    def initiate(cls, app, ip: str, port: int) -> "TCPPeer":
        """Begin an async connect (TCPPeer::initiate)."""
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setblocking(False)
        peer = cls(app, PeerRole.WE_CALLED_REMOTE, s)
        peer._peer_ip = ip
        try:
            s.connect((ip, port))
        except BlockingIOError:
            pass
        except OSError as e:
            log.warning("connect to %s:%d failed: %s", ip, port, e)
            peer.drop()
            return peer
        app.clock.watch(s, selectors.EVENT_WRITE, peer._on_connect_ready)
        return peer

    @classmethod
    def accept(cls, app, sock: socket.socket) -> "TCPPeer":
        peer = cls(app, PeerRole.REMOTE_CALLED_US, sock)
        peer._start_read()
        return peer

    def _on_connect_ready(self, _events) -> None:
        err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
        if err != 0:
            log.info("connect failed: %s", errno.errorcode.get(err, err))
            self.drop()
            return
        self._connecting = False
        self._start_read()
        self.connect_handler()

    # -- IO -----------------------------------------------------------------
    def _wanted_events(self) -> int:
        ev = selectors.EVENT_READ
        if self._wbuf:
            ev |= selectors.EVENT_WRITE
        return ev

    def _start_read(self) -> None:
        if not self._closed:
            self.app.clock.watch(self.sock, self._wanted_events(), self._on_io)

    def _on_io(self, events) -> None:
        if self._closed:
            return
        if events & selectors.EVENT_READ:
            self._do_read()
        if self._closed:
            return
        if events & selectors.EVENT_WRITE:
            self._do_write()
        if not self._closed:
            self.app.clock.watch(self.sock, self._wanted_events(), self._on_io)

    def _do_read(self) -> None:
        try:
            chunk = self.sock.recv(256 * 1024)
        except (BlockingIOError, InterruptedError):
            return
        except OSError as e:
            log.info("read error from %r: %s", self, e)
            self.drop()
            return
        if not chunk:
            self.drop()  # EOF
            return
        self.received_bytes()  # partial frames still count as activity
        self._rbuf += chunk
        # decode as many complete frames as arrived; batch SCP pre-warm
        # happens naturally since each recv_frame call runs back-to-back
        while True:
            if len(self._rbuf) < HDR_SIZE:
                break
            ln = int.from_bytes(self._rbuf[:HDR_SIZE], "big")
            if ln > MAX_MESSAGE_SIZE:
                log.warning("oversized frame (%d) from %r", ln, self)
                self.drop()
                return
            if len(self._rbuf) < HDR_SIZE + ln:
                break
            frame = bytes(self._rbuf[HDR_SIZE : HDR_SIZE + ln])
            del self._rbuf[: HDR_SIZE + ln]
            self.recv_frame(frame)
            if self._closed:
                return

    def _do_write(self) -> None:
        # reentrancy guard: wrote_bytes(n) credits the send queue, whose
        # drain may emit a fresh frame -> send_frame -> back here while
        # the outer loop is mid-entry.  The nested call is a no-op; the
        # outer loop picks the appended frames up naturally.
        if self._writing:
            return
        self._writing = True
        try:
            while self._wbuf:
                buf = self._wbuf[0]
                try:
                    n = self.sock.send(buf[self._wpos :])
                except (BlockingIOError, InterruptedError):
                    return
                except OSError as e:
                    log.info("write error to %r: %s", self, e)
                    self.drop()
                    return
                if n > 0:
                    # only bytes accepted by the kernel count as progress
                    # — and they credit the send queue's in-flight window
                    self.wrote_bytes(n)
                self._wpos += n
                if self._wpos >= len(buf):
                    self._wbuf.popleft()
                    self._wpos = 0
        finally:
            self._writing = False

    # -- Peer transport interface -------------------------------------------
    def send_frame(self, data: bytes) -> None:
        if self._closed:
            return
        self._wbuf.append(len(data).to_bytes(HDR_SIZE, "big") + data)
        self._do_write()
        if self._wbuf and not self._closed:
            self.app.clock.watch(self.sock, self._wanted_events(), self._on_io)

    def close_transport(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.app.clock.unwatch(self.sock)
        try:
            self.sock.close()
        except OSError:
            pass

    def ip(self) -> str:
        return self._peer_ip


class PeerDoor:
    """Listening acceptor (PeerDoor.{h,cpp}): hands new sockets to
    TCPPeer.accept and registers them as pending peers."""

    def __init__(self, app):
        self.app = app
        self.sock: Optional[socket.socket] = None

    def start(self) -> None:
        cfg = self.app.config
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.setblocking(False)
        s.bind(("0.0.0.0", cfg.PEER_PORT))
        s.listen(64)
        self.sock = s
        self.app.clock.watch(s, selectors.EVENT_READ, self._on_accept)
        log.info("listening for peers on :%d", cfg.PEER_PORT)

    def _on_accept(self, _events) -> None:
        while True:
            try:
                conn, addr = self.sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            om = self.app.overlay_manager
            if om is None or om.is_shutting_down():
                conn.close()
                return
            peer = TCPPeer.accept(self.app, conn)
            om.add_pending_peer(peer)

    def close(self) -> None:
        if self.sock is not None:
            self.app.clock.unwatch(self.sock)
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
