"""ItemFetcher — anycast fetch of txsets / quorum sets by hash
(reference: src/overlay/ItemFetcher.{h,cpp}).

One Tracker per outstanding hash: ask one peer (preferring whoever sent the
envelope that needs the item), and on DONT_HAVE or timeout move to the next
authenticated peer.  Retry hardening (ISSUE r17): the reference's fixed
1.5 s retry became capped exponential backoff — the interval doubles per
FULL no-progress round through the peer list (every peer asked, nobody
answered), with seeded jitter from the tracker's item-hash RNG so replays
stay deterministic — and a tracker that burns ``GIVE_UP_ROUNDS`` full
rounds without progress surfaces a metered give-up
(``overlay.fetch.give-up``) instead of spinning forever against a network
that does not have the item.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional

from ..trace import tracer_of
from ..util import VirtualTimer, xlog
from ..xdr.overlay import MessageType, StellarMessage
from ..xdr.scp import SCPEnvelope

log = xlog.logger("Overlay")

MS_TO_WAIT_FOR_FETCH_REPLY = 1.5  # seconds (ItemFetcher.cpp:17 — 1500ms)
# backoff doubles per full no-progress round, capped here (seconds)
FETCH_BACKOFF_CAP = 24.0
# full no-answer rounds through the whole peer list before the metered
# give-up — with the capped backoff this is minutes of trying, far past
# any fetch the consensus path still needs (slots GC via
# stop_fetching_below long before)
FETCH_GIVE_UP_ROUNDS = 12


class Tracker:
    def __init__(
        self,
        app,
        item_hash: bytes,
        ask_peer: Callable,
        on_give_up: Optional[Callable] = None,
    ):
        self.app = app
        self.item_hash = item_hash
        self.ask_peer = ask_peer  # fn(peer, hash) -> sends the GET_* message
        self.on_give_up = on_give_up  # fn() -> fetcher forgets this tracker
        self.gave_up = False
        self.last_asked_peer = None
        self.peers_asked: List[object] = []
        # peer pick order is load-balancing, not security: seed it from the
        # item hash so a fetch sequence replays identically run-to-run
        # (VirtualClock determinism discipline — analyzer rule
        # `determinism`; the reference's gRandomEngine is likewise
        # deterministically seeded under test)
        self._rng = random.Random(int.from_bytes(item_hash[:8], "big"))
        self.timer = VirtualTimer(app.clock)
        self.envelopes: List[SCPEnvelope] = []
        self.num_list_rebuild = 0
        # consecutive retries with NO authenticated peers at all: these
        # escalate the retry delay (mildly — see _retry_delay) but never
        # count toward the give-up, and reset the moment peers return —
        # a partitioned node must neither spin its timer at full rate
        # nor abandon a fetch the heal will satisfy
        self.num_empty_rounds = 0
        # fetch latency span: opens with the tracker, ends at finish()
        self._span = tracer_of(app).begin(
            "overlay.fetch", item=item_hash.hex()[:8]
        )

    def finish(self, outcome: str) -> None:
        """Close the fetch span (double-finish safe: end(None) is a no-op)."""
        tracer_of(self.app).end(
            self._span,
            outcome=outcome,
            asked=len(self.peers_asked),
            rebuilds=self.num_list_rebuild,
        )
        self._span = None

    def listen(self, envelope: SCPEnvelope) -> None:
        self.envelopes.append(envelope)

    def pop(self) -> Optional[SCPEnvelope]:
        if self.envelopes:
            return self.envelopes.pop(0)
        return None

    def cancel(self) -> None:
        self.timer.cancel()
        self.last_asked_peer = None

    def _retry_delay(self) -> float:
        """Capped exponential backoff keyed to FULL no-progress rounds
        (num_list_rebuild), with seeded jitter from the item-hash RNG —
        determinism-rule compliant, replays identically.  Peer-less
        retries escalate too, but their exponent caps at 2 (≤6 s base):
        once the partition heals, the next ask must land quickly enough
        not to threaten the recovery floors."""
        exponent = min(self.num_list_rebuild, 6) + min(self.num_empty_rounds, 2)
        base = min(
            MS_TO_WAIT_FOR_FETCH_REPLY * (2 ** exponent),
            FETCH_BACKOFF_CAP,
        )
        if self.num_empty_rounds:
            # peer-less retry: cap the TOTAL base at the ≤6 s promise
            # regardless of how many no-progress rounds came before the
            # partition — the first ask after a heal must land fast
            base = min(base, MS_TO_WAIT_FOR_FETCH_REPLY * 4)
        return base + self._rng.uniform(0.0, base * 0.25)

    def _give_up(self) -> None:
        """Every peer exhausted FETCH_GIVE_UP_ROUNDS full rounds with no
        progress: stop asking, meter it, and let the fetcher forget the
        tracker (the waiting envelopes stay parked in pendingenvelopes
        until their slots GC — a fresh envelope re-opens the fetch)."""
        self.gave_up = True
        self.timer.cancel()
        self.last_asked_peer = None
        self.app.metrics.new_meter(("overlay", "fetch", "give-up"), "fetch").mark()
        log.warning(
            "giving up fetch of %s after %d full no-progress rounds",
            self.item_hash.hex()[:8], self.num_list_rebuild,
        )
        self.finish("gave-up")
        if self.on_give_up is not None:
            self.on_give_up()

    def try_next_peer(self) -> None:
        """Ask the next candidate peer (ItemFetcher.cpp tryNextPeer): first
        whoever sent an envelope needing this item, then random others."""
        om = self.app.overlay_manager
        if om is None or self.gave_up:
            return
        peers = [p for p in om.authenticated_peers()]
        if not peers:
            # retry once peers exist; the empty-round counter escalates
            # the delay (capped low) so a partitioned node does not spin
            # at full rate, without ever counting toward the give-up
            self.num_empty_rounds += 1
            self.timer.expires_from_now(self._retry_delay())
            self.timer.async_wait(self.try_next_peer)
            return
        self.num_empty_rounds = 0
        candidate = None
        # prefer senders of waiting envelopes we haven't asked yet
        sender_ids = {
            e.statement.nodeID.value
            for e in self.envelopes
            if e.statement.nodeID is not None
        }
        fresh = [p for p in peers if p not in self.peers_asked]
        for p in fresh:
            if p.peer_id is not None and p.peer_id.value in sender_ids:
                candidate = p
                break
        if candidate is None and fresh:
            candidate = self._rng.choice(fresh)
        if candidate is None:
            # exhausted everyone: one full round without progress
            if self.num_list_rebuild + 1 >= FETCH_GIVE_UP_ROUNDS:
                self._give_up()
                return
            self.peers_asked.clear()
            self.num_list_rebuild += 1
            candidate = self._rng.choice(peers)
        self.peers_asked.append(candidate)
        self.last_asked_peer = candidate
        self.ask_peer(candidate, self.item_hash)
        self.timer.expires_from_now(self._retry_delay())
        self.timer.async_wait(self.try_next_peer)

    def doesnt_have(self, peer) -> None:
        if self.last_asked_peer is peer:
            self.try_next_peer()


class ItemFetcher:
    def __init__(self, app, ask_peer: Callable):
        self.app = app
        self.ask_peer = ask_peer
        self.trackers: Dict[bytes, Tracker] = {}

    def fetch(self, item_hash: bytes, envelope: SCPEnvelope) -> None:
        tr = self.trackers.get(item_hash)
        if tr is None:
            tr = Tracker(
                self.app,
                item_hash,
                self.ask_peer,
                on_give_up=lambda: self.trackers.pop(item_hash, None),
            )
            self.trackers[item_hash] = tr
            tr.listen(envelope)
            tr.try_next_peer()
        else:
            tr.listen(envelope)

    def recv(self, item_hash: bytes) -> None:
        tr = self.trackers.pop(item_hash, None)
        if tr is not None:
            tr.cancel()
            tr.finish("received")

    def stop_fetch(self, item_hash: bytes) -> None:
        self.recv(item_hash)

    def stop_fetching_below(self, slot_index: int) -> None:
        """Drop trackers only needed by slots below `slot_index`."""
        for h, tr in list(self.trackers.items()):
            tr.envelopes = [
                e for e in tr.envelopes if e.statement.slotIndex >= slot_index
            ]
            if not tr.envelopes:
                tr.cancel()
                tr.finish("abandoned")
                del self.trackers[h]

    def doesnt_have(self, item_hash: bytes, peer) -> None:
        tr = self.trackers.get(item_hash)
        if tr is not None:
            tr.doesnt_have(peer)

    def __len__(self) -> int:
        return len(self.trackers)
