"""ItemFetcher — anycast fetch of txsets / quorum sets by hash
(reference: src/overlay/ItemFetcher.{h,cpp}).

One Tracker per outstanding hash: ask one peer (preferring whoever sent the
envelope that needs the item), and on DONT_HAVE or timeout move to the next
authenticated peer, looping forever until ``recv`` or ``stop_fetch``.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional

from ..trace import tracer_of
from ..util import VirtualTimer, xlog
from ..xdr.overlay import MessageType, StellarMessage
from ..xdr.scp import SCPEnvelope

log = xlog.logger("Overlay")

MS_TO_WAIT_FOR_FETCH_REPLY = 1.5  # seconds (ItemFetcher.cpp:17 — 1500ms)


class Tracker:
    def __init__(self, app, item_hash: bytes, ask_peer: Callable):
        self.app = app
        self.item_hash = item_hash
        self.ask_peer = ask_peer  # fn(peer, hash) -> sends the GET_* message
        self.last_asked_peer = None
        self.peers_asked: List[object] = []
        # peer pick order is load-balancing, not security: seed it from the
        # item hash so a fetch sequence replays identically run-to-run
        # (VirtualClock determinism discipline — analyzer rule
        # `determinism`; the reference's gRandomEngine is likewise
        # deterministically seeded under test)
        self._rng = random.Random(int.from_bytes(item_hash[:8], "big"))
        self.timer = VirtualTimer(app.clock)
        self.envelopes: List[SCPEnvelope] = []
        self.num_list_rebuild = 0
        # fetch latency span: opens with the tracker, ends at finish()
        self._span = tracer_of(app).begin(
            "overlay.fetch", item=item_hash.hex()[:8]
        )

    def finish(self, outcome: str) -> None:
        """Close the fetch span (double-finish safe: end(None) is a no-op)."""
        tracer_of(self.app).end(
            self._span,
            outcome=outcome,
            asked=len(self.peers_asked),
            rebuilds=self.num_list_rebuild,
        )
        self._span = None

    def listen(self, envelope: SCPEnvelope) -> None:
        self.envelopes.append(envelope)

    def pop(self) -> Optional[SCPEnvelope]:
        if self.envelopes:
            return self.envelopes.pop(0)
        return None

    def cancel(self) -> None:
        self.timer.cancel()
        self.last_asked_peer = None

    def try_next_peer(self) -> None:
        """Ask the next candidate peer (ItemFetcher.cpp tryNextPeer): first
        whoever sent an envelope needing this item, then random others."""
        om = self.app.overlay_manager
        if om is None:
            return
        peers = [p for p in om.authenticated_peers()]
        if not peers:
            # retry once peers exist
            self.timer.expires_from_now(MS_TO_WAIT_FOR_FETCH_REPLY)
            self.timer.async_wait(self.try_next_peer)
            return
        candidate = None
        # prefer senders of waiting envelopes we haven't asked yet
        sender_ids = {
            e.statement.nodeID.value
            for e in self.envelopes
            if e.statement.nodeID is not None
        }
        fresh = [p for p in peers if p not in self.peers_asked]
        for p in fresh:
            if p.peer_id is not None and p.peer_id.value in sender_ids:
                candidate = p
                break
        if candidate is None and fresh:
            candidate = self._rng.choice(fresh)
        if candidate is None:
            # exhausted everyone: rebuild the ask list and start over
            self.peers_asked.clear()
            self.num_list_rebuild += 1
            candidate = self._rng.choice(peers)
        self.peers_asked.append(candidate)
        self.last_asked_peer = candidate
        self.ask_peer(candidate, self.item_hash)
        self.timer.expires_from_now(MS_TO_WAIT_FOR_FETCH_REPLY)
        self.timer.async_wait(self.try_next_peer)

    def doesnt_have(self, peer) -> None:
        if self.last_asked_peer is peer:
            self.try_next_peer()


class ItemFetcher:
    def __init__(self, app, ask_peer: Callable):
        self.app = app
        self.ask_peer = ask_peer
        self.trackers: Dict[bytes, Tracker] = {}

    def fetch(self, item_hash: bytes, envelope: SCPEnvelope) -> None:
        tr = self.trackers.get(item_hash)
        if tr is None:
            tr = Tracker(self.app, item_hash, self.ask_peer)
            self.trackers[item_hash] = tr
            tr.listen(envelope)
            tr.try_next_peer()
        else:
            tr.listen(envelope)

    def recv(self, item_hash: bytes) -> None:
        tr = self.trackers.pop(item_hash, None)
        if tr is not None:
            tr.cancel()
            tr.finish("received")

    def stop_fetch(self, item_hash: bytes) -> None:
        self.recv(item_hash)

    def stop_fetching_below(self, slot_index: int) -> None:
        """Drop trackers only needed by slots below `slot_index`."""
        for h, tr in list(self.trackers.items()):
            tr.envelopes = [
                e for e in tr.envelopes if e.statement.slotIndex >= slot_index
            ]
            if not tr.envelopes:
                tr.cancel()
                tr.finish("abandoned")
                del self.trackers[h]

    def doesnt_have(self, item_hash: bytes, peer) -> None:
        tr = self.trackers.get(item_hash)
        if tr is not None:
            tr.doesnt_have(peer)

    def __len__(self) -> int:
        return len(self.trackers)
