"""PeerRecord: SQL-backed peer address book (reference: src/overlay/PeerRecord.*).

peers table with backoff (numfailures -> exponential nextattempt) and ranking;
the overlay tick picks non-preferred peers from here ordered by nextattempt.
"""

from __future__ import annotations

import ipaddress
from typing import List, Optional

MAX_NUM_FAILURES = 10
SECONDS_PER_BACKOFF = 10


class PeerRecord:
    def __init__(self, ip: str, port: int, next_attempt: float = 0.0, num_failures: int = 0):
        self.ip = ip
        self.port = int(port)
        self.next_attempt = next_attempt
        self.num_failures = num_failures

    # -- parsing (PeerRecord::parseIPPort) ---------------------------------
    @classmethod
    def parse_ip_port(cls, s: str, default_port: int = 39133) -> "PeerRecord":
        host, _, port_s = s.partition(":")
        port = int(port_s) if port_s else default_port
        if not (0 < port <= 65535):
            raise ValueError(f"bad port in {s!r}")
        ipaddress.ip_address(host)  # raises on non-IP (no DNS here, like tests)
        return cls(host, port)

    def to_string(self) -> str:
        return f"{self.ip}:{self.port}"

    def is_localhost(self) -> bool:
        """127/8 loopback (PeerRecord::isLocalhost)."""
        try:
            return ipaddress.ip_address(self.ip).is_loopback
        except ValueError:
            return False

    def is_self_address_and_port(self, ip: str, port: int) -> bool:
        """PeerRecord::isSelfAddressAndPort — remote-supplied lists can echo
        an endpoint back at its owner."""
        return self.ip == ip and self.port == port

    def is_private_address(self) -> bool:
        """RFC1918 check, exactly the reference's ranges
        (PeerRecord.cpp:213-229): 10/8, 172.16/12, 192.168/16.  NOT
        ipaddress.is_private — that also counts 127/8 and link-local,
        and loopback/TCP tests legitimately exchange 127.0.0.1."""
        try:
            val = int(ipaddress.IPv4Address(self.ip))
        except (ipaddress.AddressValueError, ValueError):
            return False
        return (
            (val >> 24) == 10
            or (val >> 20) == 2753
            or (val >> 16) == 49320
        )

    # -- SQL ---------------------------------------------------------------
    @staticmethod
    def drop_all(db) -> None:
        db.execute("DROP TABLE IF EXISTS peers")
        db.execute(
            """CREATE TABLE peers (
                ip          VARCHAR(15) NOT NULL,
                port        INT DEFAULT 0 CHECK (port > 0 AND port <= 65535) NOT NULL,
                nextattempt TIMESTAMP NOT NULL,
                numfailures INT DEFAULT 0 CHECK (numfailures >= 0) NOT NULL,
                PRIMARY KEY (ip, port)
            )"""
        )

    @classmethod
    def load(cls, db, ip: str, port: int) -> Optional["PeerRecord"]:
        row = db.query_one(
            "SELECT nextattempt, numfailures FROM peers WHERE ip=? AND port=?",
            (ip, port),
        )
        return cls(ip, port, row[0], row[1]) if row else None

    @classmethod
    def load_peers(cls, db, max_num: int, next_attempt_cutoff: float) -> List["PeerRecord"]:
        rows = db.query_all(
            "SELECT ip, port, nextattempt, numfailures FROM peers"
            " WHERE nextattempt <= ? ORDER BY nextattempt ASC, numfailures ASC LIMIT ?",
            (next_attempt_cutoff, max_num),
        )
        return [cls(*r) for r in rows]

    def insert_if_new(self, db) -> bool:
        """Store ONLY when the (ip, port) is unknown (PeerRecord::insertIfNew):
        remote-supplied data must never clobber the backoff/next-attempt
        state we already track for a known peer."""
        if (
            db.query_one(
                "SELECT 1 FROM peers WHERE ip=? AND port=?", (self.ip, self.port)
            )
            is not None
        ):
            return False
        return self.store(db)

    def store(self, db) -> bool:
        """Insert-or-update; returns True if newly inserted."""
        existed = (
            db.query_one(
                "SELECT 1 FROM peers WHERE ip=? AND port=?", (self.ip, self.port)
            )
            is not None
        )
        db.execute(
            "INSERT INTO peers (ip, port, nextattempt, numfailures) VALUES (?,?,?,?)"
            " ON CONFLICT(ip, port) DO UPDATE SET"
            " nextattempt=excluded.nextattempt, numfailures=excluded.numfailures",
            (self.ip, self.port, self.next_attempt, self.num_failures),
        )
        return not existed

    def back_off(self, db, now: float) -> None:
        """Exponential backoff on failure (PeerRecord::backOff)."""
        self.num_failures += 1
        self.next_attempt = now + SECONDS_PER_BACKOFF * min(
            2 ** min(self.num_failures, MAX_NUM_FAILURES), 256
        )
        self.store(db)

    def reset_back_off(self, db, now: float) -> None:
        self.num_failures = 0
        self.next_attempt = now
        self.store(db)

    @staticmethod
    def delete(db, ip: str, port: int) -> None:
        db.execute("DELETE FROM peers WHERE ip=? AND port=?", (ip, port))
