"""P2P overlay: authenticated flood/anycast mesh (reference: src/overlay/).

This is the byzantine-tolerant control plane (SURVEY §2.3, §5.8): selector-
driven sockets on the VirtualClock, HMAC-framed XDR messages, flood dedup,
anycast item fetch.  The TPU data plane (batched signature tensors) lives in
``stellar_tpu.crypto.sigbackend`` / ``stellar_tpu.parallel`` — the overlay's
job is only to keep those batches fed.
"""

from .floodgate import Floodgate
from .itemfetcher import ItemFetcher, Tracker
from .loopback import LoopbackPeer, LoopbackPeerConnection
from .manager import OverlayManager
from .peer import Peer, PeerRole, PeerState
from .peerauth import PeerAuth
from .peerrecord import PeerRecord
from .sendqueue import SendQueue, SendQueueStats
from .tcppeer import PeerDoor, TCPPeer

__all__ = [
    "Floodgate", "ItemFetcher", "Tracker", "LoopbackPeer",
    "LoopbackPeerConnection", "OverlayManager", "Peer", "PeerRole",
    "PeerState", "PeerAuth", "PeerRecord", "PeerDoor", "TCPPeer",
    "SendQueue", "SendQueueStats",
]
