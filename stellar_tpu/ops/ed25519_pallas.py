"""Pallas TPU kernel for batched ed25519 verification.

Same math and bit-exact semantics as ops/ed25519.verify_kernel (decompress +
Straus double-scalar-mult + encode + compare; see that module for the
host/device split and provenance), but tiled over the batch so the per-item
dynamic niels table and the accumulator stay **VMEM-resident** for the whole
64-window ladder.  PROFILE.md: the XLA version re-reads the (4·16·20·N)
table from HBM on every window (~10.7 GB per 32k batch) — that traffic and
the fusion-boundary spills are what this kernel removes.

Layout per grid step: a batch tile of ``NT`` lanes; field elements are
(20, NT) int32 (radix-2^13 limbs on sublanes, items on lanes — ops/fe.py).
VMEM budget at NT=512: inputs ~3 MB (incl. the pre-broadcast tables),
table scratch 2.6 MB, live temps ~2 MB — under the 16 MB core limit.

Mosaic lowering constraints shaped this module (all hit in practice):
no lax.scatter (`.at[].add/.set`), no lax.dynamic_slice on values, no
broadcast across sublanes AND lanes in one op (constants arrive
pre-broadcast to (…, NT)), no zero-sized vectors.  fe.py selects
Mosaic-safe forms via the ``PALLAS`` const-override flag.

Falls back to interpreter mode off-TPU so the differential tests exercise
the same code path on the CPU mesh.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import fe
from . import ed25519 as ed

NT = 512  # batch tile (lanes); must divide the padded batch

# Compress-stage lane-tree Montgomery inversion (round-4 optimization,
# ~11% modeled).  Env-switchable so profile_kernel.py can A/B it against
# the per-lane pow-chain inversion within ONE relay window — cross-window
# absolute comparisons are confounded by window quality (PROFILE.md).
_BATCH_INV = os.environ.get("STELLAR_TPU_BATCH_INV", "1") != "0"

# Signed-digit windows (round-5 experiment): recode the radix-16 scalar
# digits to [-8, 7] with carry, so both niels tables need only k = 1..8
# (half the dynamic-table build, ~half the select where-chains; the sign
# is applied at select time — a niels negation is one component swap plus
# one field negation).  Env-switchable for the same-window device A/B.
_SIGNED_WIN = os.environ.get("STELLAR_TPU_SIGNED_WINDOWS", "0") != "0"

_CONST_NAMES = ("SUB_PAD", "P_COL", "D", "D2", "SQRT_M1")


def _niels_identity(n):
    zero = jnp.zeros((fe.LIMBS, n), jnp.int32)
    one = fe.one_fe(n)
    return (one, one, zero, one + one)


def _select_niels(tab_ref, nib):
    """Where-chain select of niels entry ``nib`` from a (4, 16, 20, NT)
    VMEM table ref -> 4 × (20, NT).  Entry 0 is the niels identity."""
    comps = list(_niels_identity(nib.shape[0]))
    for k in range(1, 16):
        mask = (nib == k)[None, :]
        for c in range(4):
            comps[c] = jnp.where(mask, tab_ref[c, k], comps[c])
    return tuple(comps)


def _select_niels_signed(tab_ref, d):
    """Signed-digit select: the table holds k·P (niels) for k = 1..8 and
    ``d`` ∈ [-8, 7]; |d| picks the entry, d < 0 negates it (x → −x in
    niels form: swap Y+X ↔ Y−X, negate T·2d, Z unchanged)."""
    k = jnp.abs(d)
    comps = list(_niels_identity(d.shape[0]))
    for kk in range(1, 9):
        mask = (k == kk)[None, :]
        for c in range(4):
            comps[c] = jnp.where(mask, tab_ref[c, kk], comps[c])
    yp, ym, t2d, z2 = comps
    negm = (d < 0)[None, :]
    return (
        jnp.where(negm, ym, yp),
        jnp.where(negm, yp, ym),
        jnp.where(negm, fe.neg(t2d), t2d),
        z2,
    )


def _kernel(
    const_ref, base_ref, a_ref, r_ref, s_ref, h_ref, out_ref, tab_ref,
    nib_ref, *, signed,
):
    override = {
        name: const_ref[i] for i, name in enumerate(_CONST_NAMES)
    }  # each (20, NT), pre-broadcast on host
    override["PALLAS"] = True  # select Mosaic-compatible lowerings in fe ops
    with fe.const_override(override):
        a_bytes = a_ref[:].astype(jnp.int32)
        r_bytes = r_ref[:].astype(jnp.int32)

        a_sign = a_bytes[31] >> 7
        a_masked = fe.set_row(a_bytes, 31, a_bytes[31] & 0x7F)
        a_y_limbs = fe.limbs_from_bytes(a_masked)
        a_pt, fail = ed.decompress(a_y_limbs, a_sign)
        neg_a = ed.point_negate(a_pt)

        # dynamic table: k * (-A), niels form, into VMEM scratch —
        # k = 1..15 unsigned, only 1..8 signed (the select negates)
        top = 9 if signed else 16
        pt = neg_a
        for k in range(1, top):
            niels = ed.to_niels(pt)
            for c in range(4):
                tab_ref[c, k] = niels[c]
            if k < top - 1:
                pt = ed.point_add(pt, neg_a)

        n = a_bytes.shape[1]

        # scalars arrive as 32 packed bytes (8x less transfer than int32
        # nibbles); split into (64, NT) int32 nibble scratch with STATIC
        # row indices — Mosaic allows dynamic row reads on int32 refs but
        # not int8, and the loop below indexes rows dynamically.
        for j in range(32):
            sb = s_ref[j].astype(jnp.int32)
            hb = h_ref[j].astype(jnp.int32)
            nib_ref[0, 2 * j] = sb & 0xF
            nib_ref[0, 2 * j + 1] = sb >> 4
            nib_ref[1, 2 * j] = hb & 0xF
            nib_ref[1, 2 * j + 1] = hb >> 4

        if signed:
            # recode digits to [-8, 7] with carry; both scalars are < L
            # < 2^253 (strict gate / host mod-L — the verify_kernel_pallas
            # docstring's stated precondition), so the top nibble is at
            # most 1 and the final carry can never overflow window 63
            for plane in range(2):
                carry = jnp.zeros((n,), jnp.int32)
                for t in range(64):
                    d = nib_ref[plane, t] + carry
                    carry = (d >= 8).astype(jnp.int32)
                    nib_ref[plane, t] = d - (carry << 4)

        sel = _select_niels_signed if signed else _select_niels

        def body(i, acc):
            t = ed.WINDOWS - 1 - i
            for k in range(4):
                acc = ed.point_double(acc, need_t=(k == 3))
            s_nib = nib_ref[0, t]
            h_nib = nib_ref[1, t]
            acc = ed.point_add_niels(acc, sel(base_ref, s_nib))
            acc = ed.point_add_niels(
                acc, sel(tab_ref, h_nib), need_t=False
            )
            return acc

        acc = jax.lax.fori_loop(0, ed.WINDOWS, body, ed.point_identity(n))
        enc = ed.compress(acc, batch_inv=_BATCH_INV)
        match = jnp.all(enc == r_bytes, axis=0)
        out_ref[:] = (match & ~fail)[None]


@functools.partial(jax.jit, static_argnames=("interpret", "signed"))
def verify_kernel_pallas(
    a_bytes, r_bytes, s_bytes, h_bytes, interpret=False, signed=None
):
    """Same math/result as ops/ed25519.verify_kernel, but the four inputs
    are raw (32, N) uint8 byte columns (A, R, s, h=SHA-512(R‖A‖M) mod L,
    all little-endian) — 8x less host->device transfer than the XLA
    kernel's int32+nibble interface.  N must be a multiple of NT.
    ``signed`` picks the signed-digit window variant (default: the
    STELLAR_TPU_SIGNED_WINDOWS env flag).  PRECONDITION for equivalence:
    s and h < 2^253 — i.e. gate-canonical s (strict_input_ok_batch
    rejects s >= L, exactly libsodium's rule) and host-reduced h.  Every
    BatchVerifier path guarantees this; a RAW caller feeding an ungated
    s in [8L, 2^256) would see the unsigned kernel accept via the modular
    identity while the signed recode drops its window-63 carry and
    rejects — neither answer is consensus-reachable because the composed
    verifier (gate + kernel) rejects such s before dispatch either way."""
    if signed is None:
        signed = _SIGNED_WIN
    tabn = 9 if signed else 16
    n = a_bytes.shape[1]
    assert n % NT == 0, f"batch {n} not a multiple of tile {NT}"
    grid = n // NT
    consts = jnp.stack(
        [
            jnp.broadcast_to(c, (fe.LIMBS, NT))
            for c in (
                fe.SUB_PAD,
                fe.P_LIMBS_COL,
                fe.const_fe(ed.D),
                fe.const_fe(ed.D2),
                fe.const_fe(ed.SQRT_M1),
            )
        ]
    )  # (5, 20, NT)
    base_tab = jnp.broadcast_to(
        ed._BASE_TABLE[:, :tabn, :, None], (4, tabn, fe.LIMBS, NT)
    )  # static niels table of k*B, lane-replicated for Mosaic
    return pl.pallas_call(
        functools.partial(_kernel, signed=signed),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (5, fe.LIMBS, NT), lambda i: (0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (4, tabn, fe.LIMBS, NT), lambda i: (0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((32, NT), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((32, NT), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((32, NT), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((32, NT), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, NT), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.bool_),
        scratch_shapes=[
            pltpu.VMEM((4, tabn, fe.LIMBS, NT), jnp.int32),
            pltpu.VMEM((2, 64, NT), jnp.int32),
        ],
        interpret=interpret,
    )(consts, base_tab, a_bytes, r_bytes, s_bytes, h_bytes)[0]
