"""JAX/TPU kernels: GF(2^255-19) field arithmetic and batched ed25519 verify.

Importing this package enables JAX's persistent compilation cache (under the
repo, so recompiles of the verify kernel are paid once per machine, not per
process — the CPU fallback compile of the full kernel is ~70s).
"""

import os

try:
    import jax

    _cache_dir = os.environ.get(
        "STELLAR_TPU_JAX_CACHE",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            ".jax_cache",
        ),
    )
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # pragma: no cover - cache is best-effort
    pass
