"""Batched ed25519 verification on TPU (JAX).

The split (SURVEY.md §7 hard-part #1, BASELINE.json north star):

- **host**: libsodium's strict input gate (canonical s, canonical A, small-
  order A/R rejection — byte compares, see ops/ref25519.strict_input_ok),
  SHA-512(R‖A‖M) mod L (hashlib), scalar→nibble splitting (numpy);
- **device**: point decompress of A (field exponentiation), Straus
  double-scalar multiplication R' = s·B + h·(−A) with 4-bit windows
  (shared doublings, niels tables, complete a=−1 twisted Edwards formulas),
  point encoding, byte compare against R.

Verification semantics are bit-exact with libsodium
``crypto_sign_verify_detached`` (differential suite: tests/test_ed25519_tpu.py).

Curve math dataflow is pure int32; batch axis N rides the TPU vector lanes
(layout notes in ops/fe.py).  One compile per padded batch size.
"""

from __future__ import annotations

import hashlib
import os
import time
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import fe
from . import ref25519 as ref

D = ref.D
D2 = (2 * ref.D) % ref.P
SQRT_M1 = ref.SQRT_M1
L = ref.L

_D_FE = fe.const_fe(D)
_D2_FE = fe.const_fe(D2)
_SQRT_M1_FE = fe.const_fe(SQRT_M1)

WINDOWS = 64  # 4-bit windows over 256-bit scalars
PIPELINE_DEPTH = 2  # max in-flight device chunks in BatchVerifier.verify


# ---------------------------------------------------------------------------
# point ops — extended coordinates (X:Y:Z:T), a=-1 complete formulas
# ---------------------------------------------------------------------------


def point_identity(n, dtype=jnp.int32):
    zero = jnp.zeros((fe.LIMBS, n), dtype)
    one = fe.one_fe(n, dtype)
    return (zero, one, one, zero)


def point_add(p, q):
    """General extended + extended (add-2008-hwcd-3 shape, 9M)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = fe.mul(fe.sub(Y1, X1), fe.sub(Y2, X2))
    b = fe.mul(fe.add(Y1, X1), fe.add(Y2, X2))
    c = fe.mul(fe.mul(T1, T2), fe._c("D2", _D2_FE))
    d = fe.mul_small(fe.mul(Z1, Z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_add_niels(p, n, need_t: bool = True):
    """Extended + precomputed niels (YpX, YmX, T2d, Z2): 8M (7M w/o T).

    ``need_t=False`` when the result feeds a doubling (which ignores T)."""
    X1, Y1, Z1, T1 = p
    YpX2, YmX2, T2d2, Z22 = n
    a = fe.mul(fe.sub(Y1, X1), YmX2)
    b = fe.mul(fe.add(Y1, X1), YpX2)
    c = fe.mul(T1, T2d2)
    d = fe.mul(Z1, Z22)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    t = fe.mul(e, h) if need_t else jnp.zeros_like(X1)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), t)


def point_double(p, need_t: bool = True):
    """dbl-2008-hwcd with a=-1: 4S + 4M (3M with ``need_t=False``).

    Doubling never reads the input T, so inside a doubling chain only the
    last double before an addition needs to produce T — the others skip
    the E·H multiply and return a zero T placeholder.
    """
    X1, Y1, Z1, _ = p
    a = fe.sqr(X1)
    b = fe.sqr(Y1)
    c = fe.mul_small(fe.sqr(Z1), 2)
    d = fe.neg(a)  # a_coef = -1
    e = fe.sub(fe.sub(fe.sqr(fe.add(X1, Y1)), a), b)
    g = fe.add(d, b)
    f = fe.sub(g, c)
    h = fe.sub(d, b)
    t = fe.mul(e, h) if need_t else jnp.zeros_like(X1)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), t)


def to_niels(p):
    X, Y, Z, T = p
    return (
        fe.add(Y, X),
        fe.sub(Y, X),
        fe.mul(T, fe._c("D2", _D2_FE)),
        fe.mul_small(Z, 2),
    )


def point_negate(p):
    X, Y, Z, T = p
    return (fe.neg(X), Y, Z, fe.neg(T))


def compress(p, batch_inv: bool = False):
    """-> ((32, N) bytes, x-parity already folded into byte 31).

    ``batch_inv`` switches the Z inversion to fe.inv_batch (tree-product
    Montgomery inversion across lanes) — correct only when the batch axis
    is local to the caller (Pallas tile / unsharded XLA batch; NOT under a
    mesh-sharded jit, where cross-lane slicing would force collectives) and
    when zero-Z lanes are masked downstream (inv_batch returns garbage for
    them, not 0)."""
    X, Y, Z, _ = p
    zinv = fe.inv_batch(Z) if batch_inv else fe.inv(Z)
    x = fe.mul(X, zinv)
    y = fe.mul(Y, zinv)
    by = fe.bytes_from_limbs(fe.canonical(y))
    sign = fe.parity(x)
    by = fe.set_row(by, 31, by[31] + (sign << 7))
    return by


def decompress(y_limbs, sign):
    """-> (point, fail) matching ref25519.decompress for canonical y."""
    one = fe.one_fe(y_limbs.shape[1:], y_limbs.dtype)
    yy = fe.sqr(y_limbs)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, fe._c("D", _D_FE)), one)
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sqr(x))
    ok1 = fe.eq(vxx, u)
    ok2 = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok2, fe.mul(x, fe._c("SQRT_M1", _SQRT_M1_FE)), x)
    fail = ~(ok1 | ok2)
    fail = fail | (fe.is_zero(x) & (sign == 1))
    flip = fe.parity(x) != sign
    x = fe.select(flip, fe.neg(x), x)
    return (x, y_limbs, one, fe.mul(x, y_limbs)), fail


# ---------------------------------------------------------------------------
# fixed-base table (host-precomputed from the reference implementation)
# ---------------------------------------------------------------------------


def _base_niels_table_np() -> np.ndarray:
    """(4, 16, 20) int32: niels components of k*B for k=0..15."""
    tab = np.zeros((4, 16, fe.LIMBS), dtype=np.int32)
    pt = ref.IDENT
    B = ref.base_point()
    for k in range(16):
        x, y, z, t = pt
        zinv = ref.fe_inv(z)
        xa, ya = x * zinv % ref.P, y * zinv % ref.P
        ta = xa * ya % ref.P
        tab[0, k] = fe.int_to_limbs((ya + xa) % ref.P)
        tab[1, k] = fe.int_to_limbs((ya - xa) % ref.P)
        tab[2, k] = fe.int_to_limbs(ta * D2 % ref.P)
        tab[3, k] = fe.int_to_limbs(2)
        pt = ref.point_add(pt, B)
    return tab


_BASE_TABLE = jnp.asarray(_base_niels_table_np())  # (4, 16, 20)


def _select_base(nib):
    """nib (N,) -> niels tuple of (20, N) from the static base table."""
    onehot = (nib[None, :] == jnp.arange(16, dtype=nib.dtype)[:, None]).astype(
        jnp.int32
    )  # (16, N)
    comps = jnp.einsum("kn,ckl->cln", onehot, _BASE_TABLE)  # (4, 20, N)
    return (comps[0], comps[1], comps[2], comps[3])


def _select_dyn(table, nib):
    """table: tuple of 4 arrays (20, 16, N); nib (N,)."""
    onehot = (nib[None, :] == jnp.arange(16, dtype=nib.dtype)[:, None]).astype(
        jnp.int32
    )  # (16, N)
    return tuple(jnp.einsum("kn,lkn->ln", onehot, t) for t in table)


def _build_a_table(neg_a):
    """niels table of k*(-A) for k=0..15: tuple of 4 arrays (20, 16, N).

    Sequential adds run under lax.scan (15 iterations, one traced body);
    the niels conversion is then vectorized across all 16 entries at once —
    fe ops are shape-polymorphic in the trailing dims.
    """
    n = neg_a[0].shape[1]

    def step(p, _):
        p2 = point_add(p, neg_a)
        return p2, p2

    _, mults = jax.lax.scan(step, point_identity(n), None, length=15)
    # mults: 4 arrays (15, 20, N); prepend identity and move limbs first
    ident = point_identity(n)
    full = tuple(
        jnp.concatenate([ident[c][None], mults[c]], axis=0).transpose(1, 0, 2)
        for c in range(4)
    )  # (20, 16, N)
    return to_niels(full)


# ---------------------------------------------------------------------------
# the verify kernel
# ---------------------------------------------------------------------------


def verify_kernel(a_bytes, r_bytes, s_nibs, h_nibs, batch_inv: bool = False):
    """All-device batched check R' == R.

    a_bytes   (32,N) — public key A bytes (little-endian, sign in bit 255)
    r_bytes   (32,N) — signature R bytes (to compare against)
    s_nibs    (64,N) — s scalar nibbles, little-endian
    h_nibs    (64,N) — h = SHA512(R‖A‖M) mod L nibbles, little-endian
    batch_inv — use lane-tree Montgomery inversion in compress; only valid
                when the batch axis is unsharded (see compress)
    returns   (N,) bool
    """
    a_sign = a_bytes[31] >> 7
    a_masked = fe.set_row(a_bytes, 31, a_bytes[31] & 0x7F)
    a_y_limbs = fe.limbs_from_bytes(a_masked)
    a_pt, fail = decompress(a_y_limbs, a_sign)
    neg_a = point_negate(a_pt)
    a_table = _build_a_table(neg_a)

    n = a_bytes.shape[1]

    def body(i, acc):
        t = WINDOWS - 1 - i
        for k in range(4):
            # only the last double feeds an addition, which is the sole
            # consumer of T — the first three skip the E·H multiply
            acc = point_double(acc, need_t=(k == 3))
        acc = point_add_niels(acc, _select_base(s_nibs[t]))
        # the next consumer is the following window's doubling: no T needed
        acc = point_add_niels(acc, _select_dyn(a_table, h_nibs[t]), need_t=False)
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, point_identity(n))
    enc = compress(acc, batch_inv=batch_inv)
    match = jnp.all(enc == r_bytes, axis=0)
    return match & ~fail


# ---------------------------------------------------------------------------
# host orchestration
# ---------------------------------------------------------------------------


def _nibbles_np(scalars_le_bytes: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (64, N) int32 nibbles little-endian."""
    lo = scalars_le_bytes & 0x0F
    hi = scalars_le_bytes >> 4
    inter = np.empty((scalars_le_bytes.shape[0], 64), dtype=np.int32)
    inter[:, 0::2] = lo
    inter[:, 1::2] = hi
    return np.ascontiguousarray(inter.T)


class BatchVerifier:
    """Pads batches to pow-2 buckets (one XLA compile per bucket), runs the
    kernel, scatters results; host gate failures never reach the device.

    ``backend="auto"`` picks the Pallas kernel (ops/ed25519_pallas.py —
    measured 4× the XLA lowering on v5e, PROFILE.md) on a real
    accelerator and the plain XLA kernel on CPU.  With a mesh, the Pallas
    kernel runs PER SHARD under shard_map (each chip grids its local
    slice of the batch; no cross-shard communication — XLA inserts only
    the output all-gather), so multi-chip keeps the fast kernel."""

    def __init__(
        self,
        max_batch: int = 4096,
        mesh=None,
        min_device_batch: int = 16,
        backend: str = "auto",
        streams: Optional[int] = None,
        host_assist: Optional[float] = None,
        tracer=None,
    ):
        from ..trace import NULL_TRACER

        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.max_batch = max_batch
        self.min_device_batch = min_device_batch
        self.mesh = mesh
        if streams is None:
            streams = int(os.environ.get("STELLAR_TPU_VERIFY_STREAMS", "1"))
        if host_assist is None:
            try:
                host_assist = float(
                    os.environ.get("STELLAR_TPU_HOST_ASSIST", "0") or 0.0
                )
            except ValueError:
                host_assist = 0.0
        # Fraction of each large batch peeled off to a concurrent libsodium
        # loop: while device chunks upload/execute, the otherwise-idle host
        # core verifies the tail.  Worth cpu_rate/(cpu_rate+device_rate)
        # (~10-20%) of extra end-to-end throughput; results are identical
        # by construction (libsodium IS the ground truth the kernel is
        # differential-tested against).  0 disables.
        self.host_assist = min(0.9, max(0.0, host_assist))
        # dispatch streams: stager threads that stage+upload+launch chunks
        # concurrently.  1 = the classic pipeline (host prep of chunk k+1
        # overlaps device drain of chunk k).  2 = additionally overlap one
        # chunk's relay UPLOAD with another's EXECUTION — a win only if
        # the transport allows it (probe_overlap.py measures this; bench
        # A/Bs both and reports the better)
        self.streams = max(1, streams)
        if backend == "auto":
            # pallas is a TPU (Mosaic) lowering: not CPU, and not GPU
            # either (interpret mode exists but is far slower than XLA)
            backend = "pallas" if jax.default_backend() == "tpu" else "xla"
        self.backend = backend
        if self.backend == "pallas":
            from .ed25519_pallas import NT

            # every device batch must be a whole number of pallas tiles —
            # PER SHARD when a mesh splits the batch axis
            n_shards = len(mesh.devices.flat) if mesh is not None else 1
            self._granule = NT * n_shards
            self.max_batch = max(
                self._granule,
                -(-self.max_batch // self._granule) * self._granule,
            )
        else:
            self._granule = 1
        self._kernel = self._make_kernel()
        self.n_device_calls = 0
        self.n_items = 0
        self.n_gate_rejects = 0
        self.n_host_assist_items = 0
        self.verify_seconds = 0.0
        # n_device_calls is bumped from every stager thread; += alone
        # drops increments under streams>1 and the counter feeds
        # profiling conclusions
        import threading

        self._calls_lock = threading.Lock()

    def _make_kernel(self):
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PSpec

            batch_axis = self.mesh.axis_names[0]
            shard = NamedSharding(self.mesh, PSpec(None, batch_axis))
            vec = NamedSharding(self.mesh, PSpec(batch_axis))
            if self.backend == "pallas":
                # jax >= 0.6 exports shard_map at top level with a
                # check_vma kwarg; 0.4/0.5 have the experimental module
                # with the same check under its old name check_rep
                try:
                    from jax import shard_map

                    check_kw = "check_vma"
                except ImportError:
                    from jax.experimental.shard_map import shard_map

                    check_kw = "check_rep"

                from .ed25519_pallas import verify_kernel_pallas

                body = partial(
                    verify_kernel_pallas,
                    # per-shard pallas grids compile with Mosaic only on
                    # real TPU; the CPU mesh (tests, driver dryrun) runs
                    # the same kernel in interpreter mode
                    interpret=jax.default_backend() != "tpu",
                )
                fn = shard_map(
                    body,
                    mesh=self.mesh,
                    in_specs=(PSpec(None, batch_axis),) * 4,
                    out_specs=PSpec(batch_axis),
                    # pallas_call's out_shape carries no varying-mesh-axes
                    # annotation; the per-shard kernel is trivially
                    # batch-varying, so skip the VMA/replication check
                    **{check_kw: False},
                )
                return jax.jit(
                    fn,
                    in_shardings=(shard, shard, shard, shard),
                    out_shardings=vec,
                )
            return jax.jit(
                verify_kernel,
                in_shardings=(shard, shard, shard, shard),
                out_shardings=vec,
            )
        if self.backend == "pallas":
            from .ed25519_pallas import verify_kernel_pallas

            return verify_kernel_pallas
        # unsharded batch axis: the lane-tree batched inversion is safe
        return jax.jit(partial(verify_kernel, batch_inv=True))

    def _bucket(self, n: int) -> int:
        b = max(self.min_device_batch, self._granule)
        b = -(-b // self._granule) * self._granule  # whole tiles per shard
        while b < n:
            b *= 2
        if self.mesh is not None:
            b = max(b, len(self.mesh.devices.flat))
        return min(b, self.max_batch) if n <= self.max_batch else self.max_batch

    def verify(self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        """items: (pubkey32, msg, sig64) triples -> list of bool."""
        out = [False] * len(items)
        todo = []  # (orig_idx, pk, msg, sig)
        wellformed = []
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) == 32 and len(sig) == 64:
                wellformed.append((i, pk, msg, sig))
            else:
                self.n_gate_rejects += 1
        if wellformed:
            pk_arr = np.frombuffer(
                b"".join(w[1] for w in wellformed), dtype=np.uint8
            ).reshape(-1, 32)
            sig_arr = np.frombuffer(
                b"".join(w[3] for w in wellformed), dtype=np.uint8
            ).reshape(-1, 64)
            gate = ref.strict_input_ok_batch(pk_arr, sig_arr)
            for ok, w in zip(gate, wellformed):
                if ok:
                    todo.append(w)
                else:
                    self.n_gate_rejects += 1
        self.n_items += len(items)
        # Host-assist: peel the tail of a large batch onto a concurrent
        # libsodium loop (ctypes releases the GIL) so the host core works
        # while device chunks upload/execute.  Peel only what exceeds a
        # whole device granule so small batches keep their single chunk.
        assist_join = None
        assist_err: List[BaseException] = []
        if self.host_assist > 0.0 and len(todo) >= 4 * self._granule:
            host_n = int(len(todo) * self.host_assist)
            if host_n > 0:
                host_part, todo = todo[-host_n:], todo[:-host_n]
                self.n_host_assist_items += host_n
                # _sodium_verify_loop pools over spare cores by itself —
                # the assist must not cap at one thread on the multi-core
                # hosts it exists for (r05 review)
                from ..crypto.sigbackend import _sodium_verify_loop
                import threading

                def assist():
                    # a raise here must NOT die silently with the thread:
                    # out[] rows would stay False and valid signatures
                    # would be reported failed — capture and re-raise on
                    # the caller after the join
                    try:
                        with self._tracer.span(
                            "ed25519.host_assist", items=len(host_part)
                        ):
                            oks = _sodium_verify_loop(
                                [(pk, msg, sig) for _, pk, msg, sig in host_part]
                            )
                            for (i, *_), ok in zip(host_part, oks):
                                out[i] = ok
                    except BaseException as e:
                        assist_err.append(e)

                _t = threading.Thread(
                    target=assist, name="verify-host-assist", daemon=True
                )
                _t.start()
                assist_join = _t.join
        # Pipelined with bounded depth: a stager thread stages AND
        # dispatches chunk k+1 (numpy/hashlib prep is GIL-releasing C work)
        # while the main thread blocks draining chunk k-1 from the device;
        # at most PIPELINE_DEPTH chunks of device buffers are ever in
        # flight (unbounded dispatch could OOM the chip on huge replays).
        pending = []
        t0 = time.perf_counter()

        def drain_one():
            chunk, fut = pending.pop(0)
            dsp = self._tracer.begin("ed25519.drain")
            results = np.asarray(fut)[: len(chunk)]
            self._tracer.end(dsp, items=len(chunk))
            for (i, *_), ok in zip(chunk, results):
                out[i] = bool(ok)

        chunks = [
            todo[s : s + self.max_batch]
            for s in range(0, len(todo), self.max_batch)
        ]
        try:
            self._run_pipeline(chunks, pending, drain_one)
        finally:
            # join even when the device pipeline raises: an orphan assist
            # thread would compete with the caller's retry for host cores
            # (r05 review)
            if assist_join is not None:
                assist_join()
        if assist_err:
            # assist failure surfaces on the caller exactly like a device
            # failure would — after the join, so no orphan thread races a
            # retry for host cores
            raise assist_err[0]
        # wall time of the whole batched call: staging + hashing + device
        # compute + sync (NOT device-only — see stats())
        self.verify_seconds += time.perf_counter() - t0
        return out

    def _run_pipeline(self, chunks, pending, drain_one):
        if len(chunks) <= 1:
            for chunk in chunks:
                pending.append((chunk, self._dispatch_chunk(chunk)))
            while pending:
                drain_one()
        else:
            from concurrent.futures import ThreadPoolExecutor

            # Bound SUBMITTED-but-undrained chunks at `depth`: a queued
            # future can start the moment a worker frees, so the
            # submission count is the device in-flight bound.  The bound
            # lives in a plain main-thread counter, NOT a semaphore
            # acquired on the workers — with streams>1 a later chunk's
            # worker could steal the last permit out of chunk order while
            # the main thread blocks on an earlier chunk's future that
            # can then never dispatch (deadlock, r05 review).  With >1
            # streams each needs an in-flight slot plus one being
            # drained, or the second stream can never overlap.
            depth = max(PIPELINE_DEPTH, self.streams + 1)

            def stage_and_dispatch(c):
                staged = self._stage_chunk(c)
                return self._dispatch_staged(staged)

            with ThreadPoolExecutor(max_workers=self.streams) as stager:
                futs = []
                drained = 0

                def drain_oldest():
                    nonlocal drained
                    chunk, f = futs[drained]
                    drained += 1
                    pending.append((chunk, f.result()))
                    drain_one()

                try:
                    for c in chunks:
                        if len(futs) - drained >= depth:
                            drain_oldest()
                        futs.append((c, stager.submit(stage_and_dispatch, c)))
                    while drained < len(futs):
                        drain_oldest()
                except BaseException:
                    # drop queued work; running workers just finish their
                    # chunk (nothing blocks on a lock), so executor
                    # __exit__ joins cleanly and the error propagates
                    for _, f in futs:
                        f.cancel()
                    raise

    def _stage_chunk(self, chunk):
        """Host-side prep: bucket-padded byte columns + SHA-512 mod L.
        Pure numpy/hashlib (GIL-releasing C) — safe on the stager thread."""
        n = len(chunk)
        if n == 0:
            return None
        bucket = self._bucket(n)
        a_bytes = np.zeros((bucket, 32), dtype=np.uint8)
        r_bytes = np.zeros((bucket, 32), dtype=np.uint8)
        s_bytes = np.zeros((bucket, 32), dtype=np.uint8)
        h_bytes = np.zeros((bucket, 32), dtype=np.uint8)
        # bulk staging: one frombuffer per column set, not one per item
        a_bytes[:n] = np.frombuffer(
            b"".join(pk for _, pk, _, _ in chunk), dtype=np.uint8
        ).reshape(n, 32)
        sigs = np.frombuffer(
            b"".join(sig for _, _, _, sig in chunk), dtype=np.uint8
        ).reshape(n, 64)
        r_bytes[:n] = sigs[:, :32]
        s_bytes[:n] = sigs[:, 32:]
        sha = hashlib.sha512
        for j, (_, pk, msg, sig) in enumerate(chunk):
            h = int.from_bytes(sha(sig[:32] + pk + msg).digest(), "little") % L
            h_bytes[j] = np.frombuffer(h.to_bytes(32, "little"), dtype=np.uint8)
        return (a_bytes, r_bytes, s_bytes, h_bytes)

    def _dispatch_staged(self, staged):
        """Upload staged byte columns and launch the kernel.  Runs on the
        stager thread in the multi-chunk pipeline, on the caller's thread
        for single-chunk batches."""
        if staged is None:
            return np.zeros(0, dtype=bool)
        a_bytes, r_bytes, s_bytes, h_bytes = staged
        dsp = self._tracer.begin("ed25519.device_dispatch")
        if self.backend == "pallas":
            # raw uint8 byte columns; nibble split happens on device
            ok = self._kernel(
                jnp.asarray(np.ascontiguousarray(a_bytes.T)),
                jnp.asarray(np.ascontiguousarray(r_bytes.T)),
                jnp.asarray(np.ascontiguousarray(s_bytes.T)),
                jnp.asarray(np.ascontiguousarray(h_bytes.T)),
            )
        else:
            ok = self._kernel(
                jnp.asarray(np.ascontiguousarray(a_bytes.T).astype(np.int32)),
                jnp.asarray(np.ascontiguousarray(r_bytes.T).astype(np.int32)),
                jnp.asarray(_nibbles_np(s_bytes)),
                jnp.asarray(_nibbles_np(h_bytes)),
            )
        self._tracer.end(dsp, bucket=a_bytes.shape[0], backend=self.backend)
        with self._calls_lock:
            self.n_device_calls += 1
        return ok

    def _dispatch_chunk(self, chunk):
        return self._dispatch_staged(self._stage_chunk(chunk))

    def stats(self) -> dict:
        return {
            "backend": "tpu",
            "device_calls": self.n_device_calls,
            "items": self.n_items,
            "gate_rejects": self.n_gate_rejects,
            "host_assist_items": self.n_host_assist_items,
            "verify_seconds": self.verify_seconds,
        }
