"""Batched ed25519 verification on TPU (JAX).

The split (SURVEY.md §7 hard-part #1, BASELINE.json north star):

- **host**: libsodium's strict input gate (canonical s, canonical A, small-
  order A/R rejection) + SHA-512(R‖A‖M) mod L + packed staging, all in one
  GIL-releasing C pass per chunk (native/sighash.c; hashlib/numpy fallback
  mirrors ops/ref25519.strict_input_ok);
- **device**: point decompress of A (field exponentiation), Straus
  double-scalar multiplication R' = s·B + h·(−A) with 4-bit windows
  (shared doublings, niels tables, complete a=−1 twisted Edwards formulas),
  point encoding, byte compare against R.

Verification semantics are bit-exact with libsodium
``crypto_sign_verify_detached`` (differential suite: tests/test_ed25519_tpu.py).

Curve math dataflow is pure int32; batch axis N rides the TPU vector lanes
(layout notes in ops/fe.py).  One compile per padded batch size.
"""

from __future__ import annotations

import hashlib
import os
import time
from functools import partial
from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import fe
from . import ref25519 as ref

D = ref.D
D2 = (2 * ref.D) % ref.P
SQRT_M1 = ref.SQRT_M1
L = ref.L

_D_FE = fe.const_fe(D)
_D2_FE = fe.const_fe(D2)
_SQRT_M1_FE = fe.const_fe(SQRT_M1)

WINDOWS = 64  # 4-bit windows over 256-bit scalars
PIPELINE_DEPTH = 2  # max in-flight device chunks in BatchVerifier.verify


# ---------------------------------------------------------------------------
# point ops — extended coordinates (X:Y:Z:T), a=-1 complete formulas
# ---------------------------------------------------------------------------


def point_identity(n, dtype=jnp.int32):
    zero = jnp.zeros((fe.LIMBS, n), dtype)
    one = fe.one_fe(n, dtype)
    return (zero, one, one, zero)


def point_add(p, q):
    """General extended + extended (add-2008-hwcd-3 shape, 9M)."""
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    a = fe.mul(fe.sub(Y1, X1), fe.sub(Y2, X2))
    b = fe.mul(fe.add(Y1, X1), fe.add(Y2, X2))
    c = fe.mul(fe.mul(T1, T2), fe._c("D2", _D2_FE))
    d = fe.mul_small(fe.mul(Z1, Z2), 2)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def point_add_niels(p, n, need_t: bool = True):
    """Extended + precomputed niels (YpX, YmX, T2d, Z2): 8M (7M w/o T).

    ``need_t=False`` when the result feeds a doubling (which ignores T)."""
    X1, Y1, Z1, T1 = p
    YpX2, YmX2, T2d2, Z22 = n
    a = fe.mul(fe.sub(Y1, X1), YmX2)
    b = fe.mul(fe.add(Y1, X1), YpX2)
    c = fe.mul(T1, T2d2)
    d = fe.mul(Z1, Z22)
    e = fe.sub(b, a)
    f = fe.sub(d, c)
    g = fe.add(d, c)
    h = fe.add(b, a)
    t = fe.mul(e, h) if need_t else jnp.zeros_like(X1)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), t)


def point_double(p, need_t: bool = True):
    """dbl-2008-hwcd with a=-1: 4S + 4M (3M with ``need_t=False``).

    Doubling never reads the input T, so inside a doubling chain only the
    last double before an addition needs to produce T — the others skip
    the E·H multiply and return a zero T placeholder.
    """
    X1, Y1, Z1, _ = p
    a = fe.sqr(X1)
    b = fe.sqr(Y1)
    c = fe.mul_small(fe.sqr(Z1), 2)
    d = fe.neg(a)  # a_coef = -1
    e = fe.sub(fe.sub(fe.sqr(fe.add(X1, Y1)), a), b)
    g = fe.add(d, b)
    f = fe.sub(g, c)
    h = fe.sub(d, b)
    t = fe.mul(e, h) if need_t else jnp.zeros_like(X1)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), t)


def to_niels(p):
    X, Y, Z, T = p
    return (
        fe.add(Y, X),
        fe.sub(Y, X),
        fe.mul(T, fe._c("D2", _D2_FE)),
        fe.mul_small(Z, 2),
    )


def point_negate(p):
    X, Y, Z, T = p
    return (fe.neg(X), Y, Z, fe.neg(T))


def compress(p, batch_inv: bool = False):
    """-> ((32, N) bytes, x-parity already folded into byte 31).

    ``batch_inv`` switches the Z inversion to fe.inv_batch (tree-product
    Montgomery inversion across lanes) — correct only when the batch axis
    is local to the caller (Pallas tile / unsharded XLA batch; NOT under a
    mesh-sharded jit, where cross-lane slicing would force collectives) and
    when zero-Z lanes are masked downstream (inv_batch returns garbage for
    them, not 0)."""
    X, Y, Z, _ = p
    zinv = fe.inv_batch(Z) if batch_inv else fe.inv(Z)
    x = fe.mul(X, zinv)
    y = fe.mul(Y, zinv)
    by = fe.bytes_from_limbs(fe.canonical(y))
    sign = fe.parity(x)
    by = fe.set_row(by, 31, by[31] + (sign << 7))
    return by


def decompress(y_limbs, sign):
    """-> (point, fail) matching ref25519.decompress for canonical y."""
    one = fe.one_fe(y_limbs.shape[1:], y_limbs.dtype)
    yy = fe.sqr(y_limbs)
    u = fe.sub(yy, one)
    v = fe.add(fe.mul(yy, fe._c("D", _D_FE)), one)
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow_p58(fe.mul(u, v7)))
    vxx = fe.mul(v, fe.sqr(x))
    ok1 = fe.eq(vxx, u)
    ok2 = fe.eq(vxx, fe.neg(u))
    x = fe.select(ok2, fe.mul(x, fe._c("SQRT_M1", _SQRT_M1_FE)), x)
    fail = ~(ok1 | ok2)
    fail = fail | (fe.is_zero(x) & (sign == 1))
    flip = fe.parity(x) != sign
    x = fe.select(flip, fe.neg(x), x)
    return (x, y_limbs, one, fe.mul(x, y_limbs)), fail


# ---------------------------------------------------------------------------
# fixed-base table (host-precomputed from the reference implementation)
# ---------------------------------------------------------------------------


def _base_niels_table_np() -> np.ndarray:
    """(4, 16, 20) int32: niels components of k*B for k=0..15."""
    tab = np.zeros((4, 16, fe.LIMBS), dtype=np.int32)
    pt = ref.IDENT
    B = ref.base_point()
    for k in range(16):
        x, y, z, t = pt
        zinv = ref.fe_inv(z)
        xa, ya = x * zinv % ref.P, y * zinv % ref.P
        ta = xa * ya % ref.P
        tab[0, k] = fe.int_to_limbs((ya + xa) % ref.P)
        tab[1, k] = fe.int_to_limbs((ya - xa) % ref.P)
        tab[2, k] = fe.int_to_limbs(ta * D2 % ref.P)
        tab[3, k] = fe.int_to_limbs(2)
        pt = ref.point_add(pt, B)
    return tab


_BASE_TABLE = jnp.asarray(_base_niels_table_np())  # (4, 16, 20)


def _select_base(nib):
    """nib (N,) -> niels tuple of (20, N) from the static base table."""
    onehot = (nib[None, :] == jnp.arange(16, dtype=nib.dtype)[:, None]).astype(
        jnp.int32
    )  # (16, N)
    comps = jnp.einsum("kn,ckl->cln", onehot, _BASE_TABLE)  # (4, 20, N)
    return (comps[0], comps[1], comps[2], comps[3])


def _select_dyn(table, nib):
    """table: tuple of 4 arrays (20, 16, N); nib (N,)."""
    onehot = (nib[None, :] == jnp.arange(16, dtype=nib.dtype)[:, None]).astype(
        jnp.int32
    )  # (16, N)
    return tuple(jnp.einsum("kn,lkn->ln", onehot, t) for t in table)


def _build_a_table(neg_a):
    """niels table of k*(-A) for k=0..15: tuple of 4 arrays (20, 16, N).

    Sequential adds run under lax.scan (15 iterations, one traced body);
    the niels conversion is then vectorized across all 16 entries at once —
    fe ops are shape-polymorphic in the trailing dims.
    """
    n = neg_a[0].shape[1]

    def step(p, _):
        p2 = point_add(p, neg_a)
        return p2, p2

    _, mults = jax.lax.scan(step, point_identity(n), None, length=15)
    # mults: 4 arrays (15, 20, N); prepend identity and move limbs first
    ident = point_identity(n)
    full = tuple(
        jnp.concatenate([ident[c][None], mults[c]], axis=0).transpose(1, 0, 2)
        for c in range(4)
    )  # (20, 16, N)
    return to_niels(full)


# ---------------------------------------------------------------------------
# the verify kernel
# ---------------------------------------------------------------------------


def verify_kernel(a_bytes, r_bytes, s_nibs, h_nibs, batch_inv: bool = False):
    """All-device batched check R' == R.

    a_bytes   (32,N) — public key A bytes (little-endian, sign in bit 255)
    r_bytes   (32,N) — signature R bytes (to compare against)
    s_nibs    (64,N) — s scalar nibbles, little-endian
    h_nibs    (64,N) — h = SHA512(R‖A‖M) mod L nibbles, little-endian
    batch_inv — use lane-tree Montgomery inversion in compress; only valid
                when the batch axis is unsharded (see compress)
    returns   (N,) bool
    """
    a_sign = a_bytes[31] >> 7
    a_masked = fe.set_row(a_bytes, 31, a_bytes[31] & 0x7F)
    a_y_limbs = fe.limbs_from_bytes(a_masked)
    a_pt, fail = decompress(a_y_limbs, a_sign)
    neg_a = point_negate(a_pt)
    a_table = _build_a_table(neg_a)

    n = a_bytes.shape[1]

    def body(i, acc):
        t = WINDOWS - 1 - i
        for k in range(4):
            # only the last double feeds an addition, which is the sole
            # consumer of T — the first three skip the E·H multiply
            acc = point_double(acc, need_t=(k == 3))
        acc = point_add_niels(acc, _select_base(s_nibs[t]))
        # the next consumer is the following window's doubling: no T needed
        acc = point_add_niels(acc, _select_dyn(a_table, h_nibs[t]), need_t=False)
        return acc

    acc = jax.lax.fori_loop(0, WINDOWS, body, point_identity(n))
    enc = compress(acc, batch_inv=batch_inv)
    match = jnp.all(enc == r_bytes, axis=0)
    return match & ~fail


# ---------------------------------------------------------------------------
# host orchestration
# ---------------------------------------------------------------------------


def _nibbles_np(scalars_le_bytes: np.ndarray) -> np.ndarray:
    """(N, 32) uint8 -> (64, N) int32 nibbles little-endian."""
    lo = scalars_le_bytes & 0x0F
    hi = scalars_le_bytes >> 4
    inter = np.empty((scalars_le_bytes.shape[0], 64), dtype=np.int32)
    inter[:, 0::2] = lo
    inter[:, 1::2] = hi
    return np.ascontiguousarray(inter.T)


def _nibbles_dev(b):
    """(32, N) byte rows -> (64, N) int32 little-endian nibbles, on device
    (the packed-upload path widens and splits inside the jit program)."""
    b = b.astype(jnp.int32)
    return jnp.stack([b & 0x0F, b >> 4], axis=1).reshape(64, -1)


def _verify_packed(p, batch_inv: bool = False):
    """verify_kernel over the packed (128, N) uint8 staging layout
    (rows 0:32 A, 32:64 R, 64:96 s, 96:128 h)."""
    a = p[0:32].astype(jnp.int32)
    r = p[32:64].astype(jnp.int32)
    return verify_kernel(
        a, r, _nibbles_dev(p[64:96]), _nibbles_dev(p[96:128]),
        batch_inv=batch_inv,
    )


def _verify_packed_device_hash(p, batch_inv: bool = False):
    """The DEVICE-HASH fusion: SHA-512(R‖A‖M) mod L computed on device
    (ops/sha512.py) from the packed (160, N) raw-byte staging layout,
    then the same verify kernel — one jit, no host hash.  flag=0 lanes
    (multi-block residuals, torsion-proof columns) carry a host h in
    rows 96:128 and bypass the device hash by selection."""
    from . import sha512 as dsha

    a = p[0:32].astype(jnp.int32)
    r = p[32:64].astype(jnp.int32)
    h = dsha.h_rows_from_packed(p)
    return verify_kernel(
        a, r, _nibbles_dev(p[64:96]), _nibbles_dev(h),
        batch_inv=batch_inv,
    )


# sign-masked small-order encodings for the native gate (identical table
# to the Python gate's — both derive from ref25519.small_order_blacklist)
_BLACKLIST = b"".join(ref.small_order_blacklist())


class _Staged(NamedTuple):
    """One staged chunk: the packed upload buffer(s) plus the host
    gate verdicts that mask the device results at drain time.

    Unsharded: ``packed`` is the single (128, bucket) buffer.  Under a
    mesh it is a LIST of per-shard (128, bucket // n_shards) buffers —
    each uploads straight to its chip (``_upload_sharded``)."""

    packed: object      # (128, bucket) uint8 C-contiguous, or per-shard list
    ok: np.ndarray      # (n,) bool — strict-input gate results
    n: int              # live lanes (bucket - n are zero padding)
    bufs: tuple         # staging-pool token(s); released after drain


class _StagingPool:
    """Reusable preallocated staging buffers, keyed by (rows, bucket)
    shape — 128 rows for the host-hash layout, sha512.DH_ROWS for the
    device-hash raw layout.

    ``jnp.asarray`` may alias host memory on the CPU backend, so a buffer
    returns to the pool only AFTER its chunk's results have been drained
    (the device computation that reads it has completed) — never while a
    dispatch may still be in flight.  Pool size is naturally bounded by
    the pipeline depth (at most depth+1 chunks hold buffers at once)."""

    def __init__(self):
        import threading

        self._free = {}
        self._lock = threading.Lock()

    def acquire(self, bucket: int, rows: int = 128):
        key = (rows, bucket)
        with self._lock:
            lst = self._free.get(key)
            if lst:
                return lst.pop()
        return (
            np.empty((rows, bucket), dtype=np.uint8),
            np.empty(bucket, dtype=np.uint8),
        )

    def release(self, bufs) -> None:
        if bufs is None:
            return
        if not isinstance(bufs[0], np.ndarray):
            # a mesh chunk's per-shard buffer list: release every pair
            for pair in bufs:
                self.release(pair)
            return
        with self._lock:
            self._free.setdefault(bufs[0].shape, []).append(bufs)


class BatchVerifier:
    """Pads batches to pow-2 buckets (one XLA compile per bucket), runs the
    kernel, scatters results; host gate verdicts mask the device results,
    so a gate-rejected lane can never report True (and a chunk whose lanes
    ALL fail the gate skips its device round-trip entirely).

    ``backend="auto"`` picks the Pallas kernel (ops/ed25519_pallas.py —
    measured 4× the XLA lowering on v5e, PROFILE.md) on a real
    accelerator and the plain XLA kernel on CPU.  With a mesh, the Pallas
    kernel runs PER SHARD under shard_map (each chip grids its local
    slice of the batch; no cross-shard communication — XLA inserts only
    the output all-gather), so multi-chip keeps the fast kernel."""

    def __init__(
        self,
        max_batch: int = 4096,
        mesh=None,
        min_device_batch: int = 16,
        backend: str = "auto",
        streams: Optional[int] = None,
        host_assist: Optional[float] = None,
        native_hash: Optional[bool] = None,
        device_hash: Optional[bool] = None,
        tracer=None,
    ):
        from ..trace import NULL_TRACER

        self._tracer = tracer if tracer is not None else NULL_TRACER
        self.max_batch = max_batch
        self.min_device_batch = min_device_batch
        self.mesh = mesh
        # Device-resident hash stage (ops/sha512.py; Config.DEVICE_HASH /
        # STELLAR_TPU_DEVICE_HASH): the single-block SHA-512(R‖A‖M) mod L
        # runs fused ahead of the verify kernel in the same jit, staging
        # uploads RAW bytes (160 rows/item) and the host keeps only the
        # strict gate; multi-block (>111-byte preimage) residuals ride
        # the C hash path and merge via the flag row.  Off (default, like
        # SIG_MESH) = the host-hash 128-row path, bit-exact either way.
        if device_hash is None:
            device_hash = (
                os.environ.get("STELLAR_TPU_DEVICE_HASH", "0") == "1"
            )
        self.device_hash = bool(device_hash)
        if self.device_hash:
            from . import sha512 as _dsha

            self._rows = _dsha.DH_ROWS
        else:
            self._rows = 128
        # Host stage: the native C extension (gate + batch SHA-512 mod L +
        # packed staging with the GIL released — native/sighash.c) when it
        # builds, else the hashlib/numpy fallback.  native_hash=False (or
        # STELLAR_TPU_NATIVE_SIGHASH=0) pins the fallback for A/Bs.
        if native_hash is None:
            native_hash = (
                os.environ.get("STELLAR_TPU_NATIVE_SIGHASH", "1") != "0"
            )
        self._sighash = None
        if native_hash:
            from .. import native as _native

            self._sighash = _native.load_sighash()
        # a stale pre-r16 .so exposes stage() but not stage_raw(): the
        # device-hash path then stages via the Python fallback (bit-exact,
        # slower) instead of failing — tests pin this
        self._has_stage_raw = hasattr(self._sighash, "stage_raw")
        # 0 = auto (the C stage fans out over its pool for large chunks)
        try:
            self._hash_threads = int(
                os.environ.get("STELLAR_TPU_SIGHASH_THREADS", "0") or 0
            )
        except ValueError:
            self._hash_threads = 0
        self._pool = _StagingPool()
        if streams is None:
            streams = int(os.environ.get("STELLAR_TPU_VERIFY_STREAMS", "1"))
        if host_assist is None:
            try:
                host_assist = float(
                    os.environ.get("STELLAR_TPU_HOST_ASSIST", "0") or 0.0
                )
            except ValueError:
                host_assist = 0.0
        # Fraction of each large batch peeled off to a concurrent libsodium
        # loop: while device chunks upload/execute, the otherwise-idle host
        # core verifies the tail.  Worth cpu_rate/(cpu_rate+device_rate)
        # (~10-20%) of extra end-to-end throughput; results are identical
        # by construction (libsodium IS the ground truth the kernel is
        # differential-tested against).  0 disables.
        self.host_assist = min(0.9, max(0.0, host_assist))
        # dispatch streams: stager threads that stage+upload+launch chunks
        # concurrently.  1 = the classic pipeline (host prep of chunk k+1
        # overlaps device drain of chunk k).  2 = additionally overlap one
        # chunk's relay UPLOAD with another's EXECUTION — a win only if
        # the transport allows it (probe_overlap.py measures this; bench
        # A/Bs both and reports the better)
        self.streams = max(1, streams)
        if backend == "auto":
            # pallas is a TPU (Mosaic) lowering: not CPU, and not GPU
            # either (interpret mode exists but is far slower than XLA)
            backend = "pallas" if jax.default_backend() == "tpu" else "xla"
        self.backend = backend
        n_shards = len(mesh.devices.flat) if mesh is not None else 1
        if self.backend == "pallas":
            from .ed25519_pallas import NT

            # every device batch must be a whole number of pallas tiles —
            # PER SHARD when a mesh splits the batch axis
            self._granule = NT * n_shards
        else:
            # every bucket must split evenly over the mesh's batch axis:
            # staging is one fixed-width buffer per shard, and a chunk
            # whose length is not divisible by n_shards pads the tail
            # shard (masked at drain — see _stage_chunk_sharded)
            self._granule = n_shards
        if self._granule > 1:
            self.max_batch = max(
                self._granule,
                -(-self.max_batch // self._granule) * self._granule,
            )
        self._kernel = self._make_kernel()
        self.n_device_calls = 0
        self.n_items = 0
        self.n_gate_rejects = 0
        self.n_host_assist_items = 0
        self.n_torsion_items = 0
        self.verify_seconds = 0.0
        # n_device_calls is bumped from every stager thread; += alone
        # drops increments under streams>1 and the counter feeds
        # profiling conclusions
        import threading

        self._calls_lock = threading.Lock()

    def _make_kernel(self):
        """-> callable over the packed (128, N) — or, with device_hash,
        (160, N) — uint8 staging array.

        ONE host->device upload carries the whole chunk (A/R/s/h byte
        rows, or A/R/s/raw-M under device_hash); the row slicing, int32
        widening, nibble splitting — and with device_hash the whole
        SHA-512 mod L stage (ops/sha512.py) — all happen inside the jit
        program, so the host never touches the hash path for the
        dominant single-block class."""
        packed_fn = (
            _verify_packed_device_hash if self.device_hash else _verify_packed
        )
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PSpec

            batch_axis = self.mesh.axis_names[0]
            shard = NamedSharding(self.mesh, PSpec(None, batch_axis))
            vec = NamedSharding(self.mesh, PSpec(batch_axis))
            # _upload_sharded assembles each chunk's per-shard staging
            # buffers under exactly this sharding, so the jit below never
            # inserts a reshard in front of the kernel
            self._shard_sharding = shard
            if self.backend == "pallas":
                # jax >= 0.6 exports shard_map at top level with a
                # check_vma kwarg; 0.4/0.5 have the experimental module
                # with the same check under its old name check_rep
                try:
                    from jax import shard_map

                    check_kw = "check_vma"
                except ImportError:
                    from jax.experimental.shard_map import shard_map

                    check_kw = "check_rep"

                from .ed25519_pallas import verify_kernel_pallas

                # per-shard pallas grids compile with Mosaic only on
                # real TPU; the CPU mesh (tests, driver dryrun) runs
                # the same kernel in interpreter mode
                interpret = jax.default_backend() != "tpu"

                if self.device_hash:
                    from .sha512 import sha512_pallas

                    def body(p):
                        # the sha stage grids the same per-shard batch
                        # tiles, so both pallas_calls fuse into one jit
                        # with no cross-shard communication
                        h = sha512_pallas(p, interpret=interpret)
                        return verify_kernel_pallas(
                            p[0:32], p[32:64], p[64:96],
                            h.astype(jnp.uint8),
                            interpret=interpret,
                        )

                else:

                    def body(p):
                        return verify_kernel_pallas(
                            p[0:32], p[32:64], p[64:96], p[96:128],
                            interpret=interpret,
                        )

                fn = shard_map(
                    body,
                    mesh=self.mesh,
                    in_specs=(PSpec(None, batch_axis),),
                    out_specs=PSpec(batch_axis),
                    # pallas_call's out_shape carries no varying-mesh-axes
                    # annotation; the per-shard kernel is trivially
                    # batch-varying, so skip the VMA/replication check
                    **{check_kw: False},
                )
                return jax.jit(fn, in_shardings=(shard,), out_shardings=vec)
            return jax.jit(
                partial(packed_fn, batch_inv=False),
                in_shardings=(shard,),
                out_shardings=vec,
            )
        if self.backend == "pallas":
            from .ed25519_pallas import verify_kernel_pallas

            interpret = jax.default_backend() != "tpu"

            if self.device_hash:
                from .sha512 import sha512_pallas

                def packed_pallas(p):
                    h = sha512_pallas(p, interpret=interpret)
                    return verify_kernel_pallas(
                        p[0:32], p[32:64], p[64:96], h.astype(jnp.uint8),
                        interpret=interpret,
                    )

            else:

                def packed_pallas(p):
                    return verify_kernel_pallas(
                        p[0:32], p[32:64], p[64:96], p[96:128],
                        interpret=interpret,
                    )

            return jax.jit(packed_pallas)
        # unsharded batch axis: the lane-tree batched inversion is safe
        return jax.jit(partial(packed_fn, batch_inv=True))

    def _bucket(self, n: int) -> int:
        # _granule already folds the mesh width in (n_shards, or NT tiles
        # per shard for pallas), so every bucket splits evenly over chips
        b = max(self.min_device_batch, self._granule)
        b = -(-b // self._granule) * self._granule  # whole tiles per shard
        while b < n:
            b *= 2
        return min(b, self.max_batch) if n <= self.max_batch else self.max_batch

    def verify(self, items: Sequence[Tuple[bytes, bytes, bytes]]) -> List[bool]:
        """items: (pubkey32, msg, sig64) triples -> list of bool.

        Chunks are (start, n) RANGES over ``items`` — no per-item tuple
        rebuild, no join/frombuffer of the whole batch: each chunk's gate
        + hash + staging happens in one C call over the original bytes
        objects (native/sighash.c), and gate verdicts mask the device
        results at drain time (a gate-rejected lane still occupies a
        device slot but can never report True)."""
        items = items if isinstance(items, (list, tuple)) else list(items)
        out = [False] * len(items)
        self.n_items += len(items)
        n_dev = len(items)
        # Host-assist: peel the tail of a large batch onto a concurrent
        # libsodium loop (ctypes releases the GIL) so the host core works
        # while device chunks upload/execute.  Peel only what exceeds a
        # whole device granule so small batches keep their single chunk.
        assist_join = None
        assist_err: List[BaseException] = []
        if self.host_assist > 0.0 and len(items) >= 4 * self._granule:
            host_n = int(len(items) * self.host_assist)
            if host_n > 0:
                n_dev = len(items) - host_n
                self.n_host_assist_items += host_n
                # _sodium_verify_loop pools over spare cores by itself —
                # the assist must not cap at one thread on the multi-core
                # hosts it exists for (r05 review)
                from ..crypto.sigbackend import _sodium_verify_loop
                import threading

                def assist(start=n_dev, count=host_n):
                    # a raise here must NOT die silently with the thread:
                    # out[] rows would stay False and valid signatures
                    # would be reported failed — capture and re-raise on
                    # the caller after the join
                    try:
                        with self._tracer.span(
                            "ed25519.host_assist", items=count
                        ):
                            oks = _sodium_verify_loop(
                                items[start : start + count]
                            )
                            for j, ok in enumerate(oks):
                                out[start + j] = ok
                    except BaseException as e:
                        assist_err.append(e)

                _t = threading.Thread(
                    target=assist, name="verify-host-assist", daemon=True
                )
                _t.start()
                assist_join = _t.join
        # Pipelined with bounded depth: a stager thread stages AND
        # dispatches chunk k+1 (the C host stage releases the GIL for the
        # whole gate+hash+staging pass) while the main thread blocks
        # draining chunk k-1 from the device; at most PIPELINE_DEPTH
        # chunks of device buffers are ever in flight (unbounded dispatch
        # could OOM the chip on huge replays).
        pending = []
        t0 = time.perf_counter()

        def drain_one():
            (start, n), staged, fut = pending.pop(0)
            dsp = self._tracer.begin("ed25519.drain")
            if fut is not None:
                res = np.logical_and(
                    np.asarray(fut)[:n], staged.ok[:n]
                ).tolist()
                out[start : start + n] = res
            # fut None: every lane was gate-rejected — out[] rows stay
            # False without a device round-trip
            self._tracer.end(dsp, items=n)
            if staged is not None:
                self._pool.release(staged.bufs)

        chunks = [
            (s, min(self.max_batch, n_dev - s))
            for s in range(0, n_dev, self.max_batch)
        ]
        try:
            self._run_pipeline(items, chunks, pending, drain_one)
        finally:
            # join even when the device pipeline raises: an orphan assist
            # thread would compete with the caller's retry for host cores
            # (r05 review)
            if assist_join is not None:
                assist_join()
        if assist_err:
            # assist failure surfaces on the caller exactly like a device
            # failure would — after the join, so no orphan thread races a
            # retry for host cores
            raise assist_err[0]
        # wall time of the whole batched call: staging + hashing + device
        # compute + sync (NOT device-only — see stats())
        self.verify_seconds += time.perf_counter() - t0
        return out

    def verify_torsion(self, encs: Sequence[bytes]) -> List[bool]:
        """Batched prime-order-subgroup proofs on the SAME compiled
        verify kernel: [L]·P == identity is computed AS-IS via
        verify(A := P, h := L, s := 0, R := identity-encoding) — the
        ladder evaluates 0·B + L·(−P) and the byte compare against the
        identity encoding passes iff L·P is the identity (−identity ==
        identity).  No hash stage runs at all: the h column carries L
        directly, and under the device-hash layout the all-flag-0
        torsion chunk takes the sha stage's chunk-level lax.cond
        passthrough — the 80 rounds are skipped, not computed-and-
        discarded.

        This is the aggregate plane's fresh-R proof offload (ROADMAP #3
        remainder (a)): ~31 µs/point of host ``torsion_free`` becomes a
        device batch lane at ~the marginal verify cost, through the same
        mesh dispatch / staging-pool / drain machinery as verify().

        Input contract: ``encs`` are compressed point encodings.  A
        malformed length, non-canonical y, or undecodable encoding
        returns False (matching the host path, which strict-decodes
        first); callers on the aggregate plane only pass gated canonical
        encodings."""
        encs = encs if isinstance(encs, (list, tuple)) else list(encs)
        out = [False] * len(encs)
        if not encs:
            return out
        self.n_torsion_items += len(encs)
        pending = []

        def drain_one():
            (start, n), staged, fut = pending.pop(0)
            dsp = self._tracer.begin("ed25519.torsion_drain")
            if fut is not None:
                res = np.logical_and(
                    np.asarray(fut)[:n], staged.ok[:n]
                ).tolist()
                out[start : start + n] = res
            self._tracer.end(dsp, items=n)
            if staged is not None:
                self._pool.release(staged.bufs)

        chunks = [
            (s, min(self.max_batch, len(encs) - s))
            for s in range(0, len(encs), self.max_batch)
        ]
        self._run_pipeline(
            encs, chunks, pending, drain_one, stage_fn=self._stage_torsion
        )
        return out

    def _stage_torsion(self, encs, start, n) -> Optional[_Staged]:
        """Stage a torsion-proof chunk: A column = the encodings, R =
        identity encoding, s = 0, h = L (host-precomputed — no hash).
        Same pooled buffers / per-shard upload as the verify path."""
        if n == 0:
            return None
        if self.mesh is not None:
            n_shards = len(self.mesh.devices.flat)
            bucket = self._bucket(n)
            shard_bucket = bucket // n_shards
            bufs = []
            ok = np.empty(n, dtype=bool)
            for k in range(n_shards):
                pair = self._pool.acquire(shard_bucket, self._rows)
                bufs.append(pair)
                packed, okbuf = pair
                lo = k * shard_bucket
                cnt = min(shard_bucket, max(0, n - lo))
                if cnt == 0:
                    packed[:] = 0
                    continue
                self._fill_torsion(encs, start + lo, cnt, packed, okbuf)
                ok[lo : lo + cnt] = okbuf[:cnt].astype(bool)
            return _Staged([p for p, _ in bufs], ok, n, tuple(bufs))
        bucket = self._bucket(n)
        bufs = self._pool.acquire(bucket, self._rows)
        packed, okbuf = bufs
        self._fill_torsion(encs, start, n, packed, okbuf)
        return _Staged(packed, okbuf[:n].astype(bool), n, bufs)

    @staticmethod
    def _fill_torsion(encs, start, n, packed, okbuf) -> None:
        """numpy fill of one torsion chunk.  The device decompress does
        not re-check y-canonicity (the verify path's host gate does), so
        non-canonical encodings are gated right here to keep parity with
        the strict host decode."""
        from . import sha512 as dsha

        packed[:, :] = 0
        ok = np.zeros(n, dtype=bool)
        well = [j for j in range(n) if len(encs[start + j]) == 32]
        if well:
            enc_arr = np.frombuffer(
                b"".join(encs[start + j] for j in well), dtype=np.uint8
            ).reshape(-1, 32)
            # canonical y < 2^255 - 19 (sign bit masked) — the SAME
            # vectorized compare ref.strict_input_ok_batch runs, so the
            # torsion accept set has one implementation, not a twin
            enc_m = enc_arr.copy()
            enc_m[:, 31] &= 0x7F
            canon = ref._le_lt(enc_m.view("<u8").reshape(-1, 4), ref.P)
            idx = np.asarray(well, dtype=np.intp)
            ok[idx] = canon
            live = idx[canon]
            packed[0:32, live] = enc_arr[canon].T
        # R := identity encoding (0x01 ‖ 0^31), h := L, on live lanes only
        packed[32, :n] = ok
        packed[96:128, :n] = dsha.L_BYTES[:, None] * ok[None, :]
        okbuf[:n] = ok

    def _run_pipeline(self, items, chunks, pending, drain_one, stage_fn=None):
        stage = stage_fn if stage_fn is not None else self._stage_chunk
        if len(chunks) <= 1:
            for rng in chunks:
                staged = stage(items, *rng)
                pending.append((rng, staged, self._dispatch_staged(staged)))
            while pending:
                drain_one()
        else:
            from concurrent.futures import ThreadPoolExecutor

            # Bound SUBMITTED-but-undrained chunks at `depth`: a queued
            # future can start the moment a worker frees, so the
            # submission count is the device in-flight bound.  The bound
            # lives in a plain main-thread counter, NOT a semaphore
            # acquired on the workers — with streams>1 a later chunk's
            # worker could steal the last permit out of chunk order while
            # the main thread blocks on an earlier chunk's future that
            # can then never dispatch (deadlock, r05 review).  With >1
            # streams each needs an in-flight slot plus one being
            # drained, or the second stream can never overlap.
            depth = max(PIPELINE_DEPTH, self.streams + 1)

            def stage_and_dispatch(rng):
                staged = stage(items, *rng)
                return staged, self._dispatch_staged(staged)

            with ThreadPoolExecutor(max_workers=self.streams) as stager:
                futs = []
                drained = 0

                def drain_oldest():
                    nonlocal drained
                    rng, f = futs[drained]
                    drained += 1
                    staged, fut = f.result()
                    pending.append((rng, staged, fut))
                    drain_one()

                try:
                    for rng in chunks:
                        if len(futs) - drained >= depth:
                            drain_oldest()
                        futs.append(
                            (rng, stager.submit(stage_and_dispatch, rng))
                        )
                    while drained < len(futs):
                        drain_oldest()
                except BaseException:
                    # drop queued work; running workers just finish their
                    # chunk (nothing blocks on a lock), so executor
                    # __exit__ joins cleanly and the error propagates
                    for _, f in futs:
                        f.cancel()
                    raise

    def _stage_chunk(self, items, start, n) -> Optional[_Staged]:
        """Host stage over ``items[start:start+n]``: strict-input gate +
        h = SHA-512(R‖A‖M) mod L + the packed transposed (128, bucket)
        upload layout, into a pooled staging buffer.  The native C stage
        releases the GIL for the whole pass (and fans out over its
        internal thread pool on large chunks), so a stager thread running
        this genuinely overlaps device compute; the hashlib/numpy
        fallback covers toolchain-less hosts."""
        if n == 0:
            return None
        if self.mesh is not None:
            return self._stage_chunk_sharded(items, start, n)
        bucket = self._bucket(n)
        bufs = self._pool.acquire(bucket, self._rows)
        packed, okbuf = bufs
        sp = self._tracer.begin("ed25519.host_hash")
        rejects = self._stage_into(items, start, n, packed, okbuf)
        self._tracer.end(
            sp,
            items=n,
            native=self._sighash is not None,
            rejects=rejects,
            device_hash=self.device_hash,
        )
        if rejects:
            with self._calls_lock:  # stager threads update concurrently
                self.n_gate_rejects += int(rejects)
        return _Staged(packed, okbuf[:n].astype(bool), n, bufs)

    def _stage_chunk_sharded(self, items, start, n) -> _Staged:
        """Mesh staging: one pooled ``(128, bucket // n_shards)`` buffer
        PER SHARD, each filled by its own host-stage pass (the native C
        stage releases the GIL per call) and uploaded straight to its
        chip in _dispatch_staged — the global chunk is never repacked on
        host.  Live lanes occupy global columns [0, n) shard-major; a
        chunk not divisible by n_shards pads the tail shard and shards
        past the live range stage nothing (zeroed, inert lanes), so the
        drain's [:n] mask makes remainders bit-exact with the unsharded
        path."""
        n_shards = len(self.mesh.devices.flat)
        bucket = self._bucket(n)
        shard_bucket = bucket // n_shards
        bufs = []
        ok = np.empty(n, dtype=bool)
        rejects = 0
        sp = self._tracer.begin("ed25519.host_hash")
        for k in range(n_shards):
            pair = self._pool.acquire(shard_bucket, self._rows)
            bufs.append(pair)
            packed, okbuf = pair
            lo = k * shard_bucket
            cnt = min(shard_bucket, max(0, n - lo))
            if cnt == 0:
                packed[:] = 0  # dead shard: every lane is inert padding
                continue
            # under device_hash the per-chip pass drops its SHA stage:
            # gate + raw-byte packing only (the r16 lever — one full C
            # hash pass PER CHIP was the mesh's host feed bottleneck)
            rejects += self._stage_into(items, start + lo, cnt, packed, okbuf)
            ok[lo : lo + cnt] = okbuf[:cnt].astype(bool)
        self._tracer.end(
            sp,
            items=n,
            native=self._sighash is not None,
            rejects=rejects,
            shards=n_shards,
            device_hash=self.device_hash,
        )
        if rejects:
            with self._calls_lock:  # stager threads update concurrently
                self.n_gate_rejects += int(rejects)
        return _Staged([p for p, _ in bufs], ok, n, tuple(bufs))

    def _stage_into(self, items, start, n, packed, okbuf) -> int:
        """One host-stage pass into a pooled buffer: the C extension when
        it built (GIL released for the whole pass), else the Python
        fallback — routed by layout.  Host-hash: gate + SHA-512 mod L +
        (128, ·) staging.  Device-hash: gate + raw-byte (160, ·) staging
        (stage_raw; a stale pre-r16 .so without it rides the Python
        fallback bit-exactly)."""
        if self.device_hash:
            if self._has_stage_raw:
                return self._sighash.stage_raw(
                    items, start, n, packed, okbuf, _BLACKLIST,
                    self._hash_threads,
                )
            return self._stage_py_raw(items, start, n, packed, okbuf)
        if self._sighash is not None:
            return self._sighash.stage(
                items, start, n, packed, okbuf, _BLACKLIST,
                self._hash_threads,
            )
        return self._stage_py(items, start, n, packed, okbuf)

    def _stage_py_raw(self, items, start, n, packed, okbuf) -> int:
        """Pure-Python device-hash staging (numpy gate + raw-byte pack;
        hashlib only for the multi-block residual class) filling the
        (160, ·) layout — the no-toolchain / stale-.so fallback twin of
        native stage_raw."""
        from . import sha512 as dsha

        chunk = [items[start + j] for j in range(n)]
        ok = np.zeros(n, dtype=bool)
        well = [
            j
            for j, it in enumerate(chunk)
            if len(it[-3]) == 32 and len(it[-1]) == 64
        ]
        packed[:, :n] = 0
        if well:
            pk_arr = np.frombuffer(
                b"".join(chunk[j][-3] for j in well), dtype=np.uint8
            ).reshape(-1, 32)
            sig_arr = np.frombuffer(
                b"".join(chunk[j][-1] for j in well), dtype=np.uint8
            ).reshape(-1, 64)
            gate = ref.strict_input_ok_batch(pk_arr, sig_arr)
            sha = hashlib.sha512
            for k, j in enumerate(well):
                if not gate[k]:
                    continue
                ok[j] = True
                pk, msg, sig = chunk[j][-3], chunk[j][-2], chunk[j][-1]
                packed[0:32, j] = pk_arr[k]
                packed[32:64, j] = sig_arr[k, :32]
                packed[64:96, j] = sig_arr[k, 32:]
                if len(msg) <= dsha.MAX_DEVICE_MSG:
                    if msg:
                        packed[96 : 96 + len(msg), j] = np.frombuffer(
                            msg, dtype=np.uint8
                        )
                    packed[dsha.ROW_MLEN, j] = len(msg)
                    packed[dsha.ROW_FLAG, j] = 1
                else:
                    h = (
                        int.from_bytes(
                            sha(sig[:32] + pk + msg).digest(), "little"
                        )
                        % L
                    )
                    packed[96:128, j] = np.frombuffer(
                        h.to_bytes(32, "little"), dtype=np.uint8
                    )
        packed[:, n:] = 0
        okbuf[:n] = ok
        return n - int(ok.sum())

    def _stage_py(self, items, start, n, packed, okbuf) -> int:
        """Pure-Python host stage (hashlib + the vectorized numpy gate)
        filling the same packed layout — the pre-native code path, kept
        as the no-toolchain fallback and the bench A/B baseline."""
        chunk = [items[start + j] for j in range(n)]
        ok = np.zeros(n, dtype=bool)
        well = [
            j
            for j, it in enumerate(chunk)
            if len(it[-3]) == 32 and len(it[-1]) == 64
        ]
        packed[:, :n] = 0
        if well:
            pk_arr = np.frombuffer(
                b"".join(chunk[j][-3] for j in well), dtype=np.uint8
            ).reshape(-1, 32)
            sig_arr = np.frombuffer(
                b"".join(chunk[j][-1] for j in well), dtype=np.uint8
            ).reshape(-1, 64)
            gate = ref.strict_input_ok_batch(pk_arr, sig_arr)
            sha = hashlib.sha512
            for k, j in enumerate(well):
                if not gate[k]:
                    continue
                ok[j] = True
                pk, msg, sig = chunk[j][-3], chunk[j][-2], chunk[j][-1]
                packed[0:32, j] = pk_arr[k]
                packed[32:64, j] = sig_arr[k, :32]
                packed[64:96, j] = sig_arr[k, 32:]
                h = (
                    int.from_bytes(
                        sha(sig[:32] + pk + msg).digest(), "little"
                    )
                    % L
                )
                packed[96:128, j] = np.frombuffer(
                    h.to_bytes(32, "little"), dtype=np.uint8
                )
        packed[:, n:] = 0
        okbuf[:n] = ok
        return n - int(ok.sum())

    def _dispatch_staged(self, staged: Optional[_Staged]):
        """Upload the packed staging buffer (ONE transfer) and launch the
        kernel.  Runs on the stager thread in the multi-chunk pipeline,
        on the caller's thread for single-chunk batches.  Returns the
        in-flight device result, or None when every lane was
        gate-rejected (hostile floods never reach the chip)."""
        if staged is None or not staged.ok.any():
            return None
        dsp = self._tracer.begin("ed25519.device_dispatch")
        if self.mesh is not None:
            arr = self._upload_sharded(staged.packed)
            bucket = arr.shape[1]
        else:
            arr = jnp.asarray(staged.packed)
            bucket = staged.packed.shape[1]
        ok = self._kernel(arr)
        self._tracer.end(dsp, bucket=bucket, backend=self.backend)
        with self._calls_lock:
            self.n_device_calls += 1
        return ok

    def _upload_sharded(self, shards):
        """One host->device transfer PER SHARD: each chip's C-contiguous
        staging buffer goes straight to that chip, and the global chunk
        array is assembled from the single-device pieces under the exact
        sharding the jitted kernel expects — XLA inserts no reshard, so
        the only collective in the whole round-trip is the (N,) bool
        output all-gather the drain joins."""
        devices = list(self.mesh.devices.flat)
        singles = [
            jax.device_put(buf, dev) for buf, dev in zip(shards, devices)
        ]
        bucket = sum(buf.shape[1] for buf in shards)
        return jax.make_array_from_single_device_arrays(
            (self._rows, bucket), self._shard_sharding, singles
        )

    def stats(self) -> dict:
        # gate_rejects counts the device pipeline's strict-gate verdicts
        # (malformed lengths included); host-assist items go through
        # libsodium whole and are not broken out
        return {
            "backend": "tpu",
            "device_calls": self.n_device_calls,
            "items": self.n_items,
            "gate_rejects": self.n_gate_rejects,
            "host_assist_items": self.n_host_assist_items,
            "native_host_stage": self._sighash is not None,
            # device-resident SHA-512 stage (ops/sha512.py): True = the
            # host keeps only the strict gate for single-block preimages
            "device_hash": self.device_hash,
            # [L]·P == identity proofs served on the batch plane (the
            # aggregate scheme's fresh-R offload)
            "torsion_items": self.n_torsion_items,
            "verify_seconds": self.verify_seconds,
            # 0 = unsharded single-queue dispatch; >0 = chips on the
            # batch-axis mesh (Config.SIG_MESH; bench close lines carry
            # this as sig_mesh_devices so every JSON records the mode)
            "mesh_devices": (
                len(self.mesh.devices.flat) if self.mesh is not None else 0
            ),
        }
