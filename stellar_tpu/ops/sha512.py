"""Batched single-block SHA-512 + mod-L on the device, in JAX.

The verify host stage's dominant cost is h = SHA-512(R‖A‖M) mod L
(native/sighash.c: ~0.5 µs/item pooled — and under a SIG_MESH mesh the
host pays one full C pass PER CHIP, so at per-pod rates the host hash
becomes the feed bottleneck the kernel cannot outrun; ROADMAP #2,
VERDICT r5 sized it at ~30% of end-to-end).  The dominant verify class
hashes a FIXED 96-byte preimage (R‖A‖contents-hash): one padded block,
no length loop.  This module moves that whole class onto the device —
"Enabling AI ASICs for Zero Knowledge Proof" (arXiv:2604.17808) is the
playbook for exactly this hostile-to-ML integer arithmetic — so packed
raw bytes upload and the host keeps only the strict gate.

Representation: TPUs have no 64-bit integer lane ops, so every SHA-512
word is a **hi/lo pair of 32-bit lanes held in int32** (the bit pattern
is what matters; logical right shifts are emulated as arithmetic shift +
mask, adds wrap two's-complement exactly like uint32).  The 80 rounds
run under ONE ``lax.fori_loop`` whose body rolls a 16-word schedule
window by static-slice concatenation — Mosaic-safe (no scatter, no
dynamic value slicing) and a compile-time-bounded graph.

The mod-L reduction reuses ops/fe.py's radix-2^13 int32 limb
conventions in the SCALAR domain: the 512-bit digest folds at the 2^252
boundary against c = L − 2^252 (125 bits) like native/sighash.c's
``mod_L`` — but branch-free: each fold adds a precomputed multiple of L
large enough to keep every intermediate nonnegative, so four folds plus
one conditional subtract land exactly in [0, L).

Device-hash packed staging layout (uint8, ``DH_ROWS`` = 160 rows/item,
vs 128 for the host-hash path):

    rows   0:32   A          (pubkey bytes)
    rows  32:64   R          (signature first half)
    rows  64:96   s          (signature second half)
    rows  96:144  M          (raw message, mlen <= 47, zero-padded)
                  — or h, host-computed, in rows 96:128 when flag == 0
    row  144      mlen       (0..47; 0 when flag == 0)
    row  145      flag       (1 = single-block, hash on device;
                              0 = h precomputed on host: the multi-block
                              >111-byte-preimage residual class, and the
                              torsion-proof plane's h := L column)
    rows 146:160  zero       (alignment padding: 160 = 5 * the int8
                              sublane tile)

Single-block covers preimages <= 111 bytes (M <= ``MAX_DEVICE_MSG`` =
47); longer messages ride the existing C host stage bit-exactly and
merge at the same kernel via flag = 0.  Bit-exactness vs
native/sighash.c (and hashlib + Python bigints) is pinned by
tests/test_sha512_device.py across the 95/96/111/112-byte boundary
lanes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import fe
from . import ref25519 as ref

L = ref.L
C = L - (1 << 252)  # 125-bit tail of L

MAX_DEVICE_MSG = 47  # single-block: 64 + mlen <= 111
DH_ROWS = 160
ROW_M = 96
ROW_MLEN = 144
ROW_FLAG = 145

_MASK32 = 0xFFFFFFFF


def _i32(v: int) -> int:
    """uint32 bit pattern -> the equal int32 two's-complement value
    (Python ints outside int32 range cannot feed int32 jnp ops)."""
    v &= _MASK32
    return v - (1 << 32) if v >= (1 << 31) else v


# FIPS 180-4 round constants / IV, split into (hi, lo) int32 pairs
_K512 = [
    0x428A2F98D728AE22, 0x7137449123EF65CD, 0xB5C0FBCFEC4D3B2F,
    0xE9B5DBA58189DBBC, 0x3956C25BF348B538, 0x59F111F1B605D019,
    0x923F82A4AF194F9B, 0xAB1C5ED5DA6D8118, 0xD807AA98A3030242,
    0x12835B0145706FBE, 0x243185BE4EE4B28C, 0x550C7DC3D5FFB4E2,
    0x72BE5D74F27B896F, 0x80DEB1FE3B1696B1, 0x9BDC06A725C71235,
    0xC19BF174CF692694, 0xE49B69C19EF14AD2, 0xEFBE4786384F25E3,
    0x0FC19DC68B8CD5B5, 0x240CA1CC77AC9C65, 0x2DE92C6F592B0275,
    0x4A7484AA6EA6E483, 0x5CB0A9DCBD41FBD4, 0x76F988DA831153B5,
    0x983E5152EE66DFAB, 0xA831C66D2DB43210, 0xB00327C898FB213F,
    0xBF597FC7BEEF0EE4, 0xC6E00BF33DA88FC2, 0xD5A79147930AA725,
    0x06CA6351E003826F, 0x142929670A0E6E70, 0x27B70A8546D22FFC,
    0x2E1B21385C26C926, 0x4D2C6DFC5AC42AED, 0x53380D139D95B3DF,
    0x650A73548BAF63DE, 0x766A0ABB3C77B2A8, 0x81C2C92E47EDAEE6,
    0x92722C851482353B, 0xA2BFE8A14CF10364, 0xA81A664BBC423001,
    0xC24B8B70D0F89791, 0xC76C51A30654BE30, 0xD192E819D6EF5218,
    0xD69906245565A910, 0xF40E35855771202A, 0x106AA07032BBD1B8,
    0x19A4C116B8D2D0C8, 0x1E376C085141AB53, 0x2748774CDF8EEB99,
    0x34B0BCB5E19B48A8, 0x391C0CB3C5C95A63, 0x4ED8AA4AE3418ACB,
    0x5B9CCA4F7763E373, 0x682E6FF3D6B2B8A3, 0x748F82EE5DEFB2FC,
    0x78A5636F43172F60, 0x84C87814A1F0AB72, 0x8CC702081A6439EC,
    0x90BEFFFA23631E28, 0xA4506CEBDE82BDE9, 0xBEF9A3F7B2C67915,
    0xC67178F2E372532B, 0xCA273ECEEA26619C, 0xD186B8C721C0C207,
    0xEADA7DD6CDE0EB1E, 0xF57D4F7FEE6ED178, 0x06F067AA72176FBA,
    0x0A637DC5A2C898A6, 0x113F9804BEF90DAE, 0x1B710B35131C471B,
    0x28DB77F523047D84, 0x32CAAB7B40C72493, 0x3C9EBE0A15C9BEBC,
    0x431D67C49C100D4C, 0x4CC5D4BECB3E42B6, 0x597F299CFC657E2A,
    0x5FCB6FAB3AD6FAEC, 0x6C44198C4A475817,
]
_H512_IV = [
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B, 0x3C6EF372FE94F82B,
    0xA54FF53A5F1D36F1, 0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
]

_K_HI_NP = np.asarray([_i32(k >> 32) for k in _K512], dtype=np.int32)
_K_LO_NP = np.asarray([_i32(k) for k in _K512], dtype=np.int32)


# ---------------------------------------------------------------------------
# uint32-in-int32 word ops
# ---------------------------------------------------------------------------


def _shr(x, n: int):
    """Logical right shift of the uint32 bit pattern (arithmetic shift +
    clearing the sign-extension bits; jnp's int32 >> is arithmetic)."""
    return (x >> n) & ((1 << (32 - n)) - 1)


def _shl(x, n: int):
    return jnp.left_shift(x, n)  # int32 wraps two's-complement


def _add64(ah, al, bh, bl):
    """64-bit add over (hi, lo) int32 pairs.  The carry out of the low
    half is bit 31 of floor((a + b) / 2), computed without unsigned
    compares: floor(a/2) + floor(b/2) + (a & b & 1)."""
    lo = al + bl
    carry = _shr(_shr(al, 1) + _shr(bl, 1) + (al & bl & 1), 31)
    return ah + bh + carry, lo


def _rotr(h, l, n: int):
    """(hi, lo) rotated right by n (1..63, n != 32 handled too)."""
    if n == 32:
        return l, h
    if n > 32:
        h, l, n = l, h, n - 32
    return (
        _shr(h, n) | _shl(l, 32 - n),
        _shr(l, n) | _shl(h, 32 - n),
    )


def _shr64(h, l, n: int):
    """64-bit logical right shift by n < 32."""
    return _shr(h, n), _shr(l, n) | _shl(h, 32 - n)


def _xor3(a, b, c):
    return a ^ b ^ c


# ---------------------------------------------------------------------------
# the compression function (one block), fori_loop over 80 rounds
# ---------------------------------------------------------------------------


def _compress_block(block_rows, k_at):
    """One SHA-512 compression over a padded 128-byte block.

    block_rows — list of 128 int32 (N,) byte rows.
    k_at(t)    — round-constant accessor -> (hi, lo); a value index for
                 the XLA path, a VMEM-ref read inside the Pallas kernel
                 (Mosaic allows dynamic ROW reads on int32 refs, not
                 dynamic slices of values).
    Returns 8 digest words as ((8, N) hi, (8, N) lo).
    """
    # 16 big-endian words from the block bytes
    w_hi, w_lo = [], []
    for t in range(16):
        b = block_rows[8 * t : 8 * t + 8]
        w_hi.append(_shl(b[0], 24) | _shl(b[1], 16) | _shl(b[2], 8) | b[3])
        w_lo.append(_shl(b[4], 24) | _shl(b[5], 16) | _shl(b[6], 8) | b[7])
    n_shape = w_hi[0].shape
    iv_hi = [jnp.full(n_shape, _i32(v >> 32), jnp.int32) for v in _H512_IV]
    iv_lo = [jnp.full(n_shape, _i32(v), jnp.int32) for v in _H512_IV]

    def round_body(t, carry):
        st_hi, st_lo, wh, wl = carry
        kh, kl = k_at(t)
        # working variables a..h are state rows 0..7
        ah, al = st_hi[0], st_lo[0]
        bh, bl = st_hi[1], st_lo[1]
        ch_, cl_ = st_hi[2], st_lo[2]
        dh, dl = st_hi[3], st_lo[3]
        eh, el = st_hi[4], st_lo[4]
        fh, fl = st_hi[5], st_lo[5]
        gh, gl = st_hi[6], st_lo[6]
        hh, hl = st_hi[7], st_lo[7]
        s1h, s1l = _rotr(eh, el, 14)
        t2h, t2l = _rotr(eh, el, 18)
        t3h, t3l = _rotr(eh, el, 41)
        s1h, s1l = _xor3(s1h, t2h, t3h), _xor3(s1l, t2l, t3l)
        chh = (eh & fh) ^ (~eh & gh)
        chl = (el & fl) ^ (~el & gl)
        t1h, t1l = _add64(hh, hl, s1h, s1l)
        t1h, t1l = _add64(t1h, t1l, chh, chl)
        t1h, t1l = _add64(t1h, t1l, kh, kl)
        t1h, t1l = _add64(t1h, t1l, wh[0], wl[0])
        s0h, s0l = _rotr(ah, al, 28)
        t2h, t2l = _rotr(ah, al, 34)
        t3h, t3l = _rotr(ah, al, 39)
        s0h, s0l = _xor3(s0h, t2h, t3h), _xor3(s0l, t2l, t3l)
        mjh = (ah & bh) ^ (ah & ch_) ^ (bh & ch_)
        mjl = (al & bl) ^ (al & cl_) ^ (bl & cl_)
        t2h_, t2l_ = _add64(s0h, s0l, mjh, mjl)
        neh, nel = _add64(dh, dl, t1h, t1l)
        nah, nal = _add64(t1h, t1l, t2h_, t2l_)
        # state rotation: (a..h) -> (t1+t2, a, b, c, d+t1, e, f, g)
        st_hi = jnp.concatenate(
            [nah[None], st_hi[0:3], neh[None], st_hi[4:7]], axis=0
        )
        st_lo = jnp.concatenate(
            [nal[None], st_lo[0:3], nel[None], st_lo[4:7]], axis=0
        )
        # schedule roll: w holds w[t .. t+15]; produce w[t+16] (garbage
        # past round 63 — never consumed)
        g0h, g0l = _rotr(wh[1], wl[1], 1)
        g1h, g1l = _rotr(wh[1], wl[1], 8)
        g2h, g2l = _shr64(wh[1], wl[1], 7)
        sg0h, sg0l = _xor3(g0h, g1h, g2h), _xor3(g0l, g1l, g2l)
        g0h, g0l = _rotr(wh[14], wl[14], 19)
        g1h, g1l = _rotr(wh[14], wl[14], 61)
        g2h, g2l = _shr64(wh[14], wl[14], 6)
        sg1h, sg1l = _xor3(g0h, g1h, g2h), _xor3(g0l, g1l, g2l)
        nwh, nwl = _add64(wh[0], wl[0], sg0h, sg0l)
        nwh, nwl = _add64(nwh, nwl, wh[9], wl[9])
        nwh, nwl = _add64(nwh, nwl, sg1h, sg1l)
        wh = jnp.concatenate([wh[1:], nwh[None]], axis=0)
        wl = jnp.concatenate([wl[1:], nwl[None]], axis=0)
        return st_hi, st_lo, wh, wl

    init = (
        jnp.stack(iv_hi),
        jnp.stack(iv_lo),
        jnp.stack(w_hi),
        jnp.stack(w_lo),
    )
    st_hi, st_lo, _, _ = jax.lax.fori_loop(0, 80, round_body, init)
    out_hi, out_lo = [], []
    for i in range(8):
        oh, ol = _add64(st_hi[i], st_lo[i], iv_hi[i], iv_lo[i])
        out_hi.append(oh)
        out_lo.append(ol)
    return jnp.stack(out_hi), jnp.stack(out_lo)


def _digest_byte_rows(d_hi, d_lo):
    """8 digest words -> 64 byte rows in SHA-512 output order (word
    big-endian) — i.e. the exact byte string hashlib would emit."""
    rows = []
    for i in range(8):
        for half in (d_hi[i], d_lo[i]):
            rows.extend(
                [
                    _shr(half, 24) & 0xFF,
                    _shr(half, 16) & 0xFF,
                    _shr(half, 8) & 0xFF,
                    half & 0xFF,
                ]
            )
    return rows


# ---------------------------------------------------------------------------
# mod L — branch-free fold at the 2^252 boundary, radix-2^13 limbs
# ---------------------------------------------------------------------------

RADIX = fe.RADIX  # 13
MASK = fe.MASK


def _int_to_limb_list(v: int, n: int):
    out = []
    for _ in range(n):
        out.append(v & MASK)
        v >>= RADIX
    assert v == 0
    return out


# fold compensators: K >= max possible B*c at that fold, as a multiple of
# L, so A + K - B*c stays nonnegative (bounds audited in _mod_l_rows)
_C_LIMBS = _int_to_limb_list(C, 10)
_K1_LIMBS = _int_to_limb_list(((1 << 385) // L + 1) * L, 30)
_K2_LIMBS = _int_to_limb_list(((1 << 260) // L + 1) * L, 21)
_L_LIMBS = _int_to_limb_list(L, 20)


def _norm_limbs(raw, out_len: int):
    """Sequential bottom-up carry: limbs land in [0, 2^13) with any
    residue in the top limb.  Values are nonnegative by construction
    (every fold adds a compensating multiple of L), so the top limb is
    nonnegative too; transiently negative low limbs borrow correctly
    through the arithmetic shift."""
    out = []
    carry = None
    for i in range(out_len):
        v = raw[i] if i < len(raw) else jnp.zeros_like(raw[0])
        if carry is not None:
            v = v + carry
        if i == out_len - 1:
            out.append(v)
        else:
            out.append(v & MASK)
            carry = v >> RADIX
    return out


def _split_252(x):
    """Normalized nonneg limbs -> (A, B) with x = A + B * 2^252.
    Bit 252 sits at limb 19 bit 5 (19*13 = 247); every limb is in
    [0, 2^13) so plain shifts are logical."""
    a = list(x[:19]) + [x[19] & 0x1F]
    b = []
    for j in range(len(x) - 19):
        lo = x[19 + j] >> 5
        if 20 + j < len(x):
            lo = lo | _shl(x[20 + j] & 0x1F, 8)
        b.append(lo)
    return a, b


def _mul_c(b):
    """Schoolbook b * c over limb lists (b nonneg, < 2^13 per limb):
    column sums <= 10 * 2^26 < 2^30 — int32-safe."""
    cols = [None] * (len(b) + len(_C_LIMBS) - 1)
    for j, cj in enumerate(_C_LIMBS):
        if cj == 0:
            continue
        for i in range(len(b)):
            term = b[i] * cj
            cols[i + j] = term if cols[i + j] is None else cols[i + j] + term
    zero = jnp.zeros_like(b[0])
    return [c if c is not None else zero for c in cols]


def _fold_252(x, k_limbs, out_len: int):
    """One branch-free fold: x = A + B*2^252 ≡ A + K − B*c (mod L), with
    K a precomputed multiple of L >= max(B*c) so the result is nonneg."""
    a, b = _split_252(x)
    t = _mul_c(b)
    n = max(len(a), len(t), len(k_limbs))
    zero = jnp.zeros_like(x[0])
    raw = []
    for i in range(n):
        v = a[i] if i < len(a) else zero
        if i < len(k_limbs) and k_limbs[i]:
            v = v + k_limbs[i]
        if i < len(t):
            v = v - t[i]
        raw.append(v)
    return _norm_limbs(raw, out_len)


def _mod_l_rows(digest_rows):
    """64 little-endian digest byte rows -> 32 byte rows of the value
    mod L (little-endian) — the device twin of native/sighash.c's
    ``reduce512_le``.

    Bound audit (x = the 512-bit digest value; every fold's schoolbook
    column stays under 10 * 2^26 < 2^30, int32-safe):
      fold 1: B1 = x >> 252 < 2^260, T1 = B1*c < 2^385,
              K1 = ceil(2^385/L)*L < 2^386
              -> y1 = A1 + K1 - T1 in [0, 2^387)         (30 limbs)
      fold 2: B2 < 2^135, T2 < 2^260, K2 = ceil(2^260/L)*L < 2^261
              -> y2 in [0, 2^262)                        (21 limbs)
      fold 3: B3 < 2^10, T3 < 2^135 < L, K3 = L
              -> y3 in [0, 2^252 + L) < 2^254            (20 limbs)
      fold 4: B4 < 4, T4 < 2^127 < L, K4 = L
              -> y4 in [0, 2^252 + L) < 2L               (20 limbs)
      + one conditional subtract of L -> exactly [0, L).
    """
    # digest limbs (40 x 13 = 520 >= 512 bits), already in [0, 2^13)
    x = _limbs_from_le_byte_rows(digest_rows, 40)
    y1 = _fold_252(x, _K1_LIMBS, 30)
    y2 = _fold_252(y1, _K2_LIMBS, 21)
    y3 = _fold_252(y2, _L_LIMBS, 20)
    y4 = _fold_252(y3, _L_LIMBS, 20)
    ge = _limbs_ge(y4, _L_LIMBS)
    raw = [
        y4[i] - jnp.where(ge, _L_LIMBS[i], 0) if _L_LIMBS[i] else y4[i]
        for i in range(20)
    ]
    out = _norm_limbs(raw, 20)
    return _le_byte_rows_from_limbs(out, 32)


def _limbs_ge(x, const_limbs):
    """Lexicographic x >= const over normalized limbs (top-down), like
    fe.canonical's compare."""
    eq_so_far = jnp.ones_like(x[0], dtype=jnp.bool_)
    gt = jnp.zeros_like(x[0], dtype=jnp.bool_)
    for i in range(len(x) - 1, -1, -1):
        ci = const_limbs[i] if i < len(const_limbs) else 0
        gt = gt | (eq_so_far & (x[i] > ci))
        eq_so_far = eq_so_far & (x[i] == ci)
    return gt | eq_so_far


def _limbs_from_le_byte_rows(rows, nlimbs: int):
    """Little-endian byte rows -> radix-2^13 limb rows (generalized
    fe.limbs_from_bytes — same bit walk, arbitrary widths)."""
    nbytes = len(rows)
    limbs = []
    for k in range(nlimbs):
        bit0 = RADIX * k
        j0, r0 = divmod(bit0, 8)
        if j0 >= nbytes:
            limbs.append(jnp.zeros_like(rows[0]))
            continue
        acc = _shr(rows[j0], r0) if r0 else rows[j0]
        width = 8 - r0
        j = j0 + 1
        while width < RADIX and j < nbytes:
            acc = acc | _shl(rows[j], width)
            width += 8
            j += 1
        limbs.append(acc & MASK)
    return limbs


def _le_byte_rows_from_limbs(limbs, nbytes: int):
    """Canonical [0, 2^13) limb rows -> little-endian byte rows
    (generalized fe.bytes_from_limbs)."""
    out = []
    for j in range(nbytes):
        bit0 = 8 * j
        k0, r0 = divmod(bit0, RADIX)
        acc = _shr(limbs[k0], r0) if r0 else limbs[k0]
        width = RADIX - r0
        if width < 8 and k0 + 1 < len(limbs):
            acc = acc | _shl(limbs[k0 + 1], width)
        out.append(acc & 0xFF)
    return out


# ---------------------------------------------------------------------------
# the fused stage over the packed device-hash layout
# ---------------------------------------------------------------------------


def _build_block_rows(rows):
    """(160, N) int32 packed rows -> 128 padded-block byte rows of
    SHA-512(R ‖ A ‖ M) for the single-block class.  Per-lane padding:
    byte 64+j is M[j] below mlen, 0x80 at mlen, 0 above; the bit-length
    field is (64 + mlen) * 8 < 2^10 — only the last two bytes are ever
    nonzero."""
    mlen = rows[ROW_MLEN]
    block = [rows[32 + j] for j in range(32)]  # R first
    block += [rows[j] for j in range(32)]  # then A
    for j in range(MAX_DEVICE_MSG + 1):  # bytes 64..111
        mj = rows[ROW_M + j]
        block.append(
            jnp.where(j < mlen, mj, jnp.where(j == mlen, 0x80, 0))
        )
    zero = jnp.zeros_like(mlen)
    block += [zero] * 14  # bytes 112..125
    total_bits = (mlen + 64) * 8
    block.append(_shr(total_bits, 8))
    block.append(total_bits & 0xFF)
    assert len(block) == 128
    return block


def _h_rows(rows, k_at):
    """(160, N) int32 packed rows -> (32, N) int32 h byte rows: the
    device SHA-512 mod L for flag == 1 lanes, the uploaded host h for
    flag == 0 lanes (multi-block residual / hash-free torsion proofs)."""
    d_hi, d_lo = _compress_block(_build_block_rows(rows), k_at)
    digest = _digest_byte_rows(
        [d_hi[i] for i in range(8)], [d_lo[i] for i in range(8)]
    )
    h_dev = _mod_l_rows(digest)
    flag = rows[ROW_FLAG]
    host = (flag == 0)[None, :]
    return jnp.where(host, jnp.stack(rows[96:128]), jnp.stack(h_dev))


def h_rows_from_packed(p):
    """XLA entry: (160, N) uint8 packed device-hash staging -> (32, N)
    int32 h byte rows (device-hashed or host-merged per the flag row).

    The whole sha stage sits under a chunk-level ``lax.cond``: a chunk
    with NO flag=1 lane (torsion-proof columns, an all-multi-block
    residual chunk) takes the passthrough branch and never executes the
    80 rounds — XLA's conditional runs only the taken branch, so the
    torsion plane's "no hash stage" is literal, not a discarded
    compute."""

    def compute(p):
        rows = [p[i].astype(jnp.int32) for i in range(DH_ROWS)]
        k_hi = jnp.asarray(_K_HI_NP)
        k_lo = jnp.asarray(_K_LO_NP)
        return _h_rows(rows, lambda t: (k_hi[t], k_lo[t]))

    def passthrough(p):
        return p[96:128].astype(jnp.int32)

    return jax.lax.cond(
        jnp.any(p[ROW_FLAG] != 0), compute, passthrough, p
    )


# ---------------------------------------------------------------------------
# Pallas kernel (TPU): same math, constants arriving as kernel inputs
# ---------------------------------------------------------------------------


def _sha_kernel(k_ref, p_ref, out_ref):
    rows = [p_ref[i].astype(jnp.int32) for i in range(DH_ROWS)]
    # Mosaic cannot dynamic-slice a VALUE, but CAN dynamic-row-read an
    # int32 ref — the round constants stay behind the ref accessor
    # (pre-broadcast to the lane tile like ed25519_pallas' tables)
    out_ref[:] = _h_rows(rows, lambda t: (k_ref[0, t], k_ref[1, t]))


def sha512_pallas(p, interpret: bool = False):
    """Pallas stage over the packed (160, N) uint8 device-hash layout ->
    (32, N) int32 h rows.  N must be a multiple of the verify kernel's
    batch tile (it shares the grid split with verify_kernel_pallas so
    the two kernels fuse into one jit with no host hop)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .ed25519_pallas import NT

    n = p.shape[1]
    assert n % NT == 0, f"batch {n} not a multiple of tile {NT}"
    grid = n // NT

    def compute(p):
        consts = jnp.stack(
            [
                jnp.broadcast_to(jnp.asarray(_K_HI_NP)[:, None], (80, NT)),
                jnp.broadcast_to(jnp.asarray(_K_LO_NP)[:, None], (80, NT)),
            ]
        )  # (2, 80, NT) int32
        return pl.pallas_call(
            _sha_kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec(
                    (2, 80, NT), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (DH_ROWS, NT), lambda i: (0, i), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (32, NT), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            out_shape=jax.ShapeDtypeStruct((32, n), jnp.int32),
            interpret=interpret,
        )(consts, p)

    def passthrough(p):
        return p[96:128].astype(jnp.int32)

    # chunk-level skip, same contract as h_rows_from_packed: an
    # all-flag-0 chunk (torsion proofs / all-multi-block) never runs
    # the sha grid — XLA's conditional executes only the taken branch
    return jax.lax.cond(
        jnp.any(p[ROW_FLAG] != 0), compute, passthrough, p
    )


# ---------------------------------------------------------------------------
# host-side staging helpers (numpy) — shared by the Python fallback and
# the torsion-proof plane
# ---------------------------------------------------------------------------

L_BYTES = np.frombuffer(L.to_bytes(32, "little"), dtype=np.uint8)
IDENT_ENC = np.zeros(32, dtype=np.uint8)
IDENT_ENC[0] = 1  # compress((0, 1)) — the identity point


def reduce_digest(digest: bytes) -> bytes:
    """Host oracle twin of _mod_l_rows for tests: 64 LE digest bytes ->
    32 LE bytes of the value mod L, via Python bigints."""
    v = int.from_bytes(digest, "little") % L
    return v.to_bytes(32, "little")
