"""Batched GF(2^255-19) arithmetic for TPU, in JAX.

Design (SURVEY.md §7 hard-part #1): TPU VPUs have no 64-bit integer multiply,
so field elements use **radix 2^13 with 20 int32 limbs**, batch-last layout
``(20, N)`` (N rides the 8x128 vector lanes; the limb axis stays on sublanes).
Bounds that make int32 safe throughout:

- weakly-reduced elements have limbs < 2^13, value < 2^255 + ε
- schoolbook products: ≤ 20 terms × (2^13-1)² < 2^31          (no overflow)
- 2^260 ≡ 608 (mod p) folds the high 19 limbs back with ≤ 2^23 additions

Everything is shape-polymorphic in N and differentiably irrelevant — pure
integer ops, jit-compiled once per batch shape.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

P = 2**255 - 19
LIMBS = 20
RADIX = 13
MASK = (1 << RADIX) - 1  # 8191
FOLD = 608  # 2^260 mod p = 19 * 2^5



def int_to_limbs(v: int) -> np.ndarray:
    """Python int -> (20,) int32 limb vector (host-side)."""
    out = np.zeros(LIMBS, dtype=np.int32)
    for i in range(LIMBS):
        out[i] = v & MASK
        v >>= RADIX
    assert v == 0
    return out


def limbs_to_int(l) -> int:
    l = np.asarray(l)
    return sum(int(l[i]) << (RADIX * i) for i in range(LIMBS))


def const_fe(v: int) -> jnp.ndarray:
    """(20, 1) broadcastable constant."""
    return jnp.asarray(int_to_limbs(v % P)).reshape(LIMBS, 1)


def _sub_pad_limbs() -> np.ndarray:
    """4p written with every limb >= 2^14 (limb 19 >= 2^9): ``a - b + pad``
    then has all-positive limbs for weakly-reduced a, b, so the parallel
    carry passes never ripple borrows.  Built by borrowing 2 units of each
    limb's radix from the limb above (value preserved)."""
    four_p = 4 * P
    l = np.zeros(LIMBS, dtype=np.int64)
    v = four_p
    for i in range(LIMBS):
        l[i] = v & MASK if i < LIMBS - 1 else v
        v >>= RADIX
    assert l[LIMBS - 1] >= 2 + 512, l[LIMBS - 1]  # room to borrow 2
    d = l.copy()
    d[0] += 2 << RADIX
    for i in range(1, LIMBS - 1):
        d[i] += (2 << RADIX) - 2
    d[LIMBS - 1] -= 2
    assert sum(int(d[i]) << (RADIX * i) for i in range(LIMBS)) == four_p
    assert all(d[i] >= 2 * MASK for i in range(LIMBS - 1)) and d[LIMBS - 1] >= 512
    return d.astype(np.int32)


SUB_PAD = jnp.asarray(_sub_pad_limbs()).reshape(LIMBS, 1)
P_LIMBS_COL = jnp.asarray(int_to_limbs(P)).reshape(LIMBS, 1)

# Pallas kernels may not close over array constants — they must arrive as
# kernel inputs.  ops/ed25519_pallas.py passes a packed constant block and
# installs these overrides for the duration of the kernel trace.  A
# ContextVar (not a module global) keeps a trace on one thread — e.g. the
# BatchVerifier stager thread — from leaking its tracer constants into a
# concurrent trace on another thread.
import contextvars

_OVERRIDE: contextvars.ContextVar = contextvars.ContextVar(
    "fe_const_override", default={}
)


class const_override:
    """Context manager substituting the module's array constants during a
    pallas kernel trace (keys: SUB_PAD, P_COL, D, D2, SQRT_M1, PALLAS)."""

    def __init__(self, d):
        self.d = d

    def __enter__(self):
        self._token = _OVERRIDE.set(self.d)

    def __exit__(self, *exc):
        _OVERRIDE.reset(self._token)


def _c(name, default):
    return _OVERRIDE.get().get(name, default)


def zero_like(x):
    return jnp.zeros_like(x)


def set_row(x, i: int, v):
    """x with row i replaced by v (static i), via concatenation — the
    jnp ``.at[i].set`` form lowers to lax.scatter, which Pallas/Mosaic
    cannot compile."""
    parts = []
    if i > 0:
        parts.append(x[:i])
    parts.append(v[None] if v.ndim == x.ndim - 1 else v)
    if i < x.shape[0] - 1:
        parts.append(x[i + 1 :])
    return jnp.concatenate(parts, axis=0)


def one_fe(n, dtype=jnp.int32):
    """(20, *n) field element 1 without scatter ops."""
    shape = n if isinstance(n, tuple) else (n,)
    one = jnp.ones((1,) + shape, dtype)
    rest = jnp.zeros((LIMBS - 1,) + shape, dtype)
    return jnp.concatenate([one, rest], axis=0)


def _carry_pass(x: jnp.ndarray) -> jnp.ndarray:
    """ONE data-parallel carry pass over all limbs at once.

    The sequential 19-step chain was the kernel's critical path (each step
    a tiny dependent (N,) op); a pass is ~6 full-(20,N) ops with depth 3.
    Limbs 0..18 carry at 2^13; limb 19 holds bits 247..254 and folds its
    overflow back to limb 0 via 2^255 ≡ 19 (mod p).  Arithmetic shifts
    floor-divide, so negative limbs borrow correctly.
    """
    k = x.shape[0] - 1  # positive static indices: negative indexing
    c_lo = x[:k] >> RADIX  # lowers to dynamic_slice, which Mosaic lacks
    r_lo = x[:k] - (c_lo << RADIX)
    c_hi = x[k] >> 8
    r_hi = x[k] - (c_hi << 8)
    carries = jnp.concatenate([(c_hi * 19)[None], c_lo], axis=0)
    return jnp.concatenate([r_lo, r_hi[None]], axis=0) + carries


def carry(x: jnp.ndarray, passes: int = 3) -> jnp.ndarray:
    """Parallel carry -> weakly reduced (limbs <= 2^13 + 3).

    Pass-count bounds (see the mul/add/sub callers): products after the
    fold have limbs < 2^31 -> 3 passes leave every limb <= MASK + 3;
    add/sub inputs <= 2^14.6 need only 2.
    """
    for _ in range(passes):
        x = _carry_pass(x)
    return x


def carry_exact(x: jnp.ndarray) -> jnp.ndarray:
    """Sequential full chain: limbs land exactly in [0, 2^13) (limb 19 in
    [0, 2^8)).  O(limbs) dependent steps — only for ``canonical`` (a few
    calls per verify); the hot path uses the parallel ``carry``."""
    limbs = [x[i] for i in range(LIMBS)]
    for i in range(LIMBS - 1):
        c = limbs[i] >> RADIX
        limbs[i] = limbs[i] - (c << RADIX)
        limbs[i + 1] = limbs[i + 1] + c
    t = limbs[LIMBS - 1] >> 8
    limbs[LIMBS - 1] = limbs[LIMBS - 1] & 0xFF
    limbs[0] = limbs[0] + t * 19
    for i in range(2):
        c = limbs[i] >> RADIX
        limbs[i] = limbs[i] - (c << RADIX)
        limbs[i + 1] = limbs[i + 1] + c
    return jnp.stack(limbs)


def _bcast(c, x):
    """Reshape a (20, 1) constant to broadcast against x's trailing dims.
    Pallas overrides pass constants already expanded to x's full shape
    (Mosaic cannot broadcast in sublanes and lanes at once) — pass through.
    """
    if c.shape == x.shape:
        return c
    return c.reshape((LIMBS,) + (1,) * (x.ndim - 1))


def add(a, b):
    # both weakly reduced (<= MASK+3): sums <= 2^14, 2 passes suffice
    return carry(a + b, passes=2)


def sub(a, b):
    # a - b + pad: pad has every limb >= 2^13+ε, so limbs stay positive in
    # [~8150, 3*2^13] — no borrow ripple, 2 passes suffice
    return carry(a - b + _bcast(_c("SUB_PAD", SUB_PAD), a), passes=2)


def neg(a):
    return carry(_bcast(_c("SUB_PAD", SUB_PAD), a) - a, passes=2)


def mul(a, b):
    """Schoolbook multiply + parallel fold + carry.

    Inputs weakly reduced (limbs <= ~2^13): every product column is
    < 20·(2^13+3)^2 < 2^31, so sums stay in int32.  (Tree-structured and
    grouped accumulation variants were measured on the axon TPU relay:
    both blew compile time through the roof; the plain accumulate loop
    fuses fine.)  The 19 high limbs fold back with 2^260 ≡ 608 (mod p),
    split into a low part (<= MASK, ×608 <= 2^22.3) and a carry part
    (<= 2^17.7, ×608 <= 2^27.3, shifted one limb up) so the fold
    multiplies can't overflow either.
    """
    n = a.shape[1:]
    if _c("PALLAS", False):
        # Mosaic can lower neither lax.scatter (.at[].add) nor
        # lax.dynamic_slice on values — accumulate the low (cols 0..19)
        # and high (cols 20..38) halves with static slices + concats.
        lo = jnp.zeros((LIMBS,) + n, dtype=jnp.int32)
        hi = jnp.zeros((LIMBS - 1,) + n, dtype=jnp.int32)
        for j in range(LIMBS):
            term = a * b[j][None]  # contributes to columns j .. j+19
            if j == 0:
                lo = lo + term
            else:
                lo = lo + jnp.concatenate(
                    [jnp.zeros((j,) + n, jnp.int32), term[: LIMBS - j]], 0
                )
                hi_parts = [term[LIMBS - j :]]
                if LIMBS - 1 - j > 0:
                    hi_parts.append(
                        jnp.zeros((LIMBS - 1 - j,) + n, jnp.int32)
                    )
                hi = hi + (
                    jnp.concatenate(hi_parts, 0)
                    if len(hi_parts) > 1
                    else hi_parts[0]
                )
        prod = jnp.concatenate([lo, hi], axis=0)
    else:
        prod = jnp.zeros((2 * LIMBS - 1,) + n, dtype=jnp.int32)
        for j in range(LIMBS):
            prod = prod.at[j : j + LIMBS].add(a * b[j][None])
    return _fold_and_carry(prod, n)


def _fold_and_carry(prod, n):
    """(39, ...) product columns -> weakly-reduced (20, ...) element.

    Shared tail of mul/sqr: fold the 19 high limbs back with
    2^260 ≡ 608 (mod p), split so no int32 overflow (see mul), then 3
    parallel carry passes.
    """
    lo = prod[:LIMBS]
    hi = prod[LIMBS:]  # 19 limbs, each < 2^31
    hi_lo = hi & MASK
    hi_hi = hi >> RADIX
    zero = jnp.zeros((1,) + n, dtype=jnp.int32)
    lo = lo + jnp.concatenate([hi_lo * FOLD, zero], axis=0)
    lo = lo + jnp.concatenate([zero, hi_hi * FOLD], axis=0)
    return carry(lo, passes=3)


def sqr(a):
    """Squaring = mul(a, a).  A half-product triangular variant was
    measured SLOWER on TPU: variable-length slice updates and the strided
    diagonal scatter defeat XLA's fusion, costing more than the saved
    multiplies.  The uniform schoolbook wins."""
    return mul(a, a)


def mul_small(a, k: int):
    """Multiply by a small scalar constant (k < 2^17)."""
    return carry(a * k)


def _sq_n(x, n: int):
    if n <= 4:
        for _ in range(n):
            x = sqr(x)
        return x
    return jax.lax.fori_loop(0, n, lambda _, v: sqr(v), x)


def _pow_core(z):
    """Shared prefix of the classic curve25519 exponentiation chains:
    returns (z^(2^250 - 1), z^11, z^(2^5 - 1))."""
    t0 = sqr(z)  # 2
    t1 = mul(z, _sq_n(t0, 2))  # 9
    t0 = mul(t0, t1)  # 11
    t2 = sqr(t0)  # 22
    t1 = mul(t1, t2)  # 31 = 2^5 - 1
    z5 = t1
    t2 = _sq_n(t1, 5)
    t1 = mul(t1, t2)  # 2^10 - 1
    t2 = mul(_sq_n(t1, 10), t1)  # 2^20 - 1
    t3 = mul(_sq_n(t2, 20), t2)  # 2^40 - 1
    t2 = mul(_sq_n(t3, 10), t1)  # 2^50 - 1
    t3 = mul(_sq_n(t2, 50), t2)  # 2^100 - 1
    t4 = mul(_sq_n(t3, 100), t3)  # 2^200 - 1
    t3 = mul(_sq_n(t4, 50), t2)  # 2^250 - 1
    return t3, t0, z5


def inv(z):
    """z^(p-2) = z^(2^255 - 21)."""
    t3, z11, _ = _pow_core(z)
    return mul(_sq_n(t3, 5), z11)  # 2^255 - 32 + 11 = 2^255 - 21


def pow_p58(z):
    """z^((p-5)/8) = z^(2^252 - 3)."""
    t3, _, _ = _pow_core(z)
    return mul(_sq_n(t3, 2), z)  # 2^252 - 4 + 1 = 2^252 - 3


def inv_batch(z, min_width: int = 128):
    """Montgomery-style batched inversion across the lane (batch) axis.

    ``inv`` runs a ~254-step square/multiply ladder on every lane; on TPU a
    (20, 512) tile occupies four 128-lane vregs, so the ladder's cost is
    proportional to width.  Tree-reduce the batch by pairwise lane products
    down to ``min_width`` (one vreg), run the ladder ONCE at that width,
    then expand the inverses back up: from i = 1/(a·b), 1/a = i·b and
    1/b = i·a.  Extra cost ≈ 2–3 full-width muls; saving ≈ 3/4 of the
    ladder at 512 lanes.

    A single zero lane would null every tree product, poisoning the whole
    batch, so zeros are substituted with 1 first; their output slot is
    garbage (NOT 0, unlike ``inv``) — callers must already be masking those
    lanes (in the verify kernel a zero Z can only arise from a
    decompress-failed lane, which ``fail`` masks; complete Edwards
    additions keep Z ≠ 0 for curve points).

    mul/sqr use no broadcast constants, so narrow widths are safe under
    the Pallas const-override scheme (constants there are pre-broadcast to
    the full tile width and never reach this code path).
    """
    n = z.shape[1]
    if n <= min_width or n % 2:
        return inv(z)
    zero = is_zero(z)
    cur = select(zero, one_fe(z.shape[1:], z.dtype), z)
    levels = [cur]
    while cur.shape[1] > min_width and cur.shape[1] % 2 == 0:
        half = cur.shape[1] // 2
        cur = mul(cur[:, :half], cur[:, half:])
        levels.append(cur)
    invs = inv(cur)
    for lvl in reversed(levels[:-1]):
        half = lvl.shape[1] // 2
        inv_lo = mul(invs, lvl[:, half:])
        inv_hi = mul(invs, lvl[:, :half])
        invs = jnp.concatenate([inv_lo, inv_hi], axis=1)
    return invs


def canonical(x):
    """Weakly-reduced -> fully reduced (< p), canonical limbs."""
    x = carry_exact(x)
    # weakly reduced: x < p + ε < 2p, so at most one subtraction of p.
    # lexicographic compare with p from the top limb down: x >= p?
    p_limbs = int_to_limbs(P)
    eq_so_far = jnp.ones_like(x[0], dtype=jnp.bool_)
    gt = jnp.zeros_like(x[0], dtype=jnp.bool_)
    for i in range(LIMBS - 1, -1, -1):
        pi = int(p_limbs[i])
        gt = gt | (eq_so_far & (x[i] > pi))
        eq_so_far = eq_so_far & (x[i] == pi)
    need_sub = gt | eq_so_far
    sub_p = _bcast(_c("P_COL", P_LIMBS_COL), x)
    return carry_exact(x - jnp.where(need_sub[None], sub_p, 0))


def eq(a, b):
    ca, cb = canonical(a), canonical(b)
    return jnp.all(ca == cb, axis=0)


def is_zero(a):
    return jnp.all(canonical(a) == 0, axis=0)


def parity(a):
    """Least-significant bit of the canonical value."""
    return canonical(a)[0] & 1


def select(cond, a, b):
    """cond: (N,) bool; a, b: (20, N)."""
    return jnp.where(cond[None], a, b)


# -- byte conversion (device) ----------------------------------------------
def limbs_from_bytes(b):
    """(32, N) int32 bytes (little-endian) -> (20, N) limbs.  The caller
    masks the sign bit out of byte 31 first if decoding a point."""
    limbs = []
    for k in range(LIMBS):
        bit0 = RADIX * k
        j0, r0 = divmod(bit0, 8)
        acc = b[j0] >> r0
        width = 8 - r0
        j = j0 + 1
        while width < RADIX and j < 32:
            acc = acc | (b[j] << width)
            width += 8
            j += 1
        limbs.append(acc & MASK)
    return jnp.stack(limbs)


def bytes_from_limbs(x):
    """canonical (20, N) limbs -> (32, N) int32 bytes little-endian."""
    out = []
    for j in range(32):
        bit0 = 8 * j
        k0, r0 = divmod(bit0, RADIX)
        acc = x[k0] >> r0
        width = RADIX - r0
        if width < 8 and k0 + 1 < LIMBS:
            acc = acc | (x[k0 + 1] << width)
        out.append(acc & 0xFF)
    return jnp.stack(out)
