"""Batched multi-block SHA-256 on the device, in JAX (ISSUE r22).

The state plane's dominant hash workload is per-record digests of
variable-length bucket entries (bucket/hashplane.py): every
``Bucket.fresh``, every level-spill merge, and selfcheck's full-tree
re-hash walk thousands-to-millions of XDR frames and SHA-256 each one
independently — embarrassingly parallel many-block hashing, the same
integer-kernel-on-AI-ASIC playbook as ops/sha512.py (arXiv:2604.17808)
applied to SHA-256.

Representation: SHA-256 words are 32-bit, so unlike the SHA-512 kernel
there are no hi/lo lane pairs — every word is ONE int32 lane (the bit
pattern is what matters; logical right shifts are emulated as
arithmetic shift + mask, int32 adds wrap two's-complement exactly like
uint32).  The 64 rounds run under one ``lax.fori_loop`` whose body
rolls a 16-word schedule window by static-slice concatenation —
Mosaic-safe, no scatter, no dynamic value slicing.

Variable length rides fixed shapes through **chained compression over
per-item block counts**: the host pads each item per FIPS 180-4 (0x80
terminator + 8-byte big-endian bit length) into a
``(max_blocks * 64, N)`` uint8 column layout plus an ``(N,)`` int32
block-count vector; the kernel runs ``max_blocks`` compressions and
carries each lane's chaining state forward only while
``b < nblocks[lane]`` (``jnp.where`` select — lanes past their last
block coast, their digest frozen).  A 55-byte entry and a 500-byte
entry land in the same batch, same grid, same compiled graph.

Two lowerings share all the math: ``sha256_rows_from_packed`` (XLA)
and ``sha256_pallas`` (TPU Pallas, constants pre-broadcast to a VMEM
ref because Mosaic allows dynamic ROW reads on int32 refs but not
dynamic slicing of values — same trick as ops/sha512.py's
``_sha_kernel``).  Bit-exactness vs hashlib is pinned by
tests/test_sha256_device.py across the 55/56/63/64/65-byte padding
boundaries, multi-block sizes, and the empty string.
"""

from __future__ import annotations

import struct

import jax
import jax.numpy as jnp
import numpy as np

from .sha512 import _i32, _shl, _shr

# FIPS 180-4 round constants / IV as int32 bit patterns
_K256 = [
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B,
    0x59F111F1, 0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01,
    0x243185BE, 0x550C7DC3, 0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7,
    0xC19BF174, 0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA, 0x983E5152,
    0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
    0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC,
    0x53380D13, 0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3, 0xD192E819,
    0xD6990624, 0xF40E3585, 0x106AA070, 0x19A4C116, 0x1E376C08,
    0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F,
    0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
]
_H256_IV = [
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
]

_K_NP = np.asarray([_i32(k) for k in _K256], dtype=np.int32)
_IV_NP = np.asarray([_i32(v) for v in _H256_IV], dtype=np.int32)


def _rotr(x, n: int):
    """32-bit rotate right of the uint32 bit pattern in an int32 lane."""
    return _shr(x, n) | _shl(x, 32 - n)


# ---------------------------------------------------------------------------
# the compression function (one block), fori_loop over 64 rounds
# ---------------------------------------------------------------------------


def _compress_block(state, block_rows, k_at):
    """One SHA-256 compression: ``state`` is the (8, N) int32 chaining
    value, ``block_rows`` 64 int32 (N,) byte rows of one padded block,
    ``k_at(t)`` the round-constant accessor (a value index on the XLA
    path, a VMEM-ref row read inside the Pallas kernel).  Returns the
    new (8, N) chaining value (feedback add included)."""
    w = [
        _shl(block_rows[4 * t], 24)
        | _shl(block_rows[4 * t + 1], 16)
        | _shl(block_rows[4 * t + 2], 8)
        | block_rows[4 * t + 3]
        for t in range(16)
    ]

    def round_body(t, carry):
        st, w = carry
        k = k_at(t)
        a, b, c, d = st[0], st[1], st[2], st[3]
        e, f, g, h = st[4], st[5], st[6], st[7]
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + k + w[0]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        mj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + mj
        # state rotation: (a..h) -> (t1+t2, a, b, c, d+t1, e, f, g)
        st = jnp.concatenate(
            [(t1 + t2)[None], st[0:3], (d + t1)[None], st[4:7]], axis=0
        )
        # schedule roll: w holds w[t .. t+15]; produce w[t+16] (garbage
        # past round 47 — never consumed)
        sg0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ _shr(w[1], 3)
        sg1 = _rotr(w[14], 17) ^ _rotr(w[14], 19) ^ _shr(w[14], 10)
        nw = w[0] + sg0 + w[9] + sg1
        w = jnp.concatenate([w[1:], nw[None]], axis=0)
        return st, w

    st, _ = jax.lax.fori_loop(0, 64, round_body, (state, jnp.stack(w)))
    return st + state  # int32 add wraps mod 2^32 — the feedback add


def _digest_rows(rows, nblocks, k_at):
    """``len(rows)`` = max_blocks * 64 int32 byte rows + per-lane block
    counts -> 32 digest byte rows via chained compression: block b only
    advances lanes with b < nblocks (earlier-finished lanes coast with
    their digest frozen)."""
    max_blocks = len(rows) // 64
    n_shape = rows[0].shape
    st = jnp.stack(
        [jnp.full(n_shape, int(_IV_NP[i]), jnp.int32) for i in range(8)]
    )
    for b in range(max_blocks):
        new_st = _compress_block(st, rows[64 * b : 64 * (b + 1)], k_at)
        if b == 0:
            st = new_st  # every item has >= 1 block (padding guarantees)
        else:
            st = jnp.where((b < nblocks)[None, :], new_st, st)
    out = []
    for i in range(8):
        out.extend(
            [
                _shr(st[i], 24) & 0xFF,
                _shr(st[i], 16) & 0xFF,
                _shr(st[i], 8) & 0xFF,
                st[i] & 0xFF,
            ]
        )
    return jnp.stack(out)


# ---------------------------------------------------------------------------
# XLA entry
# ---------------------------------------------------------------------------


def sha256_rows_from_packed(p, nblocks):
    """XLA entry: (max_blocks * 64, N) uint8 padded columns + (N,) int32
    block counts -> (32, N) int32 digest byte rows (big-endian word
    order — the exact byte string hashlib would emit per column)."""
    rows = [p[i].astype(jnp.int32) for i in range(p.shape[0])]
    k = jnp.asarray(_K_NP)
    return _digest_rows(rows, nblocks.astype(jnp.int32), lambda t: k[t])


_jit_rows_from_packed = jax.jit(sha256_rows_from_packed)


# ---------------------------------------------------------------------------
# Pallas kernel (TPU): same math, constants arriving as a VMEM ref
# ---------------------------------------------------------------------------


def _sha256_kernel(k_ref, nb_ref, p_ref, out_ref):
    rows = [p_ref[i].astype(jnp.int32) for i in range(p_ref.shape[0])]
    # Mosaic cannot dynamic-slice a VALUE, but CAN dynamic-row-read an
    # int32 ref — the round constants stay behind the ref accessor
    # (pre-broadcast to the lane tile like ops/sha512.py)
    out_ref[:] = _digest_rows(rows, nb_ref[0], lambda t: k_ref[t])


def sha256_pallas(p, nblocks, interpret: bool = False):
    """Pallas stage over the packed (max_blocks * 64, N) uint8 columns
    -> (32, N) int32 digest rows.  N must be a multiple of the verify
    kernel's batch tile (shared grid split with ed25519_pallas)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from .ed25519_pallas import NT

    rows, n = p.shape
    assert n % NT == 0, f"batch {n} not a multiple of tile {NT}"
    grid = n // NT

    consts = jnp.broadcast_to(
        jnp.asarray(_K_NP)[:, None], (64, NT)
    )  # (64, NT) int32
    nb = nblocks.astype(jnp.int32).reshape(1, n)
    return pl.pallas_call(
        _sha256_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(
                (64, NT), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, NT), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (rows, NT), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (32, NT), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((32, n), jnp.int32),
        interpret=interpret,
    )(consts, nb, p)


# ---------------------------------------------------------------------------
# host-side staging (numpy) — FIPS 180-4 padding into fixed shapes
# ---------------------------------------------------------------------------


def blocks_for(length: int) -> int:
    """Padded block count of an ``length``-byte message (terminator byte
    + 8-byte length field force a new block past length % 64 == 55)."""
    return (length + 8) // 64 + 1


def pack_frames(items, max_blocks: int = 0):
    """Pad each item per FIPS 180-4 into the fixed (max_blocks * 64, N)
    uint8 column layout + (N,) int32 block counts the kernels consume.
    ``max_blocks`` > 0 pins the row count (for shape-stable jit reuse);
    it must cover the longest item."""
    n = len(items)
    counts = np.asarray([blocks_for(len(it)) for it in items], np.int32)
    need = int(counts.max()) if n else 1
    if max_blocks:
        if need > max_blocks:
            raise ValueError(
                f"item needs {need} blocks > pinned max {max_blocks}"
            )
        need = max_blocks
    packed = np.zeros((need * 64, max(n, 1)), dtype=np.uint8)
    for i, it in enumerate(items):
        ln = len(it)
        end = int(counts[i]) * 64
        if ln:
            packed[:ln, i] = np.frombuffer(it, dtype=np.uint8)
        packed[ln, i] = 0x80
        packed[end - 8 : end, i] = np.frombuffer(
            struct.pack(">Q", ln * 8), dtype=np.uint8
        )
    return packed, counts


def sha256_batch(items, pallas: bool = False, interpret: bool = False):
    """Convenience oracle for tests and the hashplane device backend:
    a list of bytes -> a list of their 32-byte SHA-256 digests via the
    batched kernel (Pallas pads the batch to the NT tile with empty
    columns; the pads are computed and dropped)."""
    if not items:
        return []
    n = len(items)
    if pallas:
        from .ed25519_pallas import NT

        pad = (-n) % NT
        packed, counts = pack_frames(list(items) + [b""] * pad)
        rows = sha256_pallas(
            jnp.asarray(packed), jnp.asarray(counts), interpret=interpret
        )
    else:
        packed, counts = pack_frames(items)
        rows = _jit_rows_from_packed(
            jnp.asarray(packed), jnp.asarray(counts)
        )
    out = np.asarray(rows, dtype=np.int32).astype(np.uint8)
    return [out[:, i].tobytes() for i in range(n)]
