"""Pure-Python edwards25519 arithmetic, written from RFC 8032 / the curve
equations.  Three jobs:

1. independent oracle for differential tests of the TPU kernels;
2. source of derived constants (d, sqrt(-1), the small-order blacklist,
   the fixed-base window table) used by stellar_tpu/ops/ed25519.py;
3. host-side strict-input prechecks replicating libsodium's verify gate
   (sc25519_is_canonical / ge25519_is_canonical / ge25519_has_small_order),
   validated against the real libsodium by tests/test_ed25519_tpu.py.

This is NOT a performance path — the CPU fast path is ctypes libsodium.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import List, Optional, Tuple

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z
IDENT = (0, 1, 1, 0)


def fe_inv(x: int) -> int:
    return pow(x, P - 2, P)


def point_add(p, q):
    X1, Y1, Z1, T1 = p
    X2, Y2, Z2, T2 = q
    A = (Y1 - X1) * (Y2 - X2) % P
    B = (Y1 + X1) * (Y2 + X2) % P
    C = 2 * T1 * T2 * D % P
    Dd = 2 * Z1 * Z2 % P
    E, F, G, H = B - A, Dd - C, Dd + C, B + A
    return (E * F % P, G * H % P, F * G % P, E * H % P)


def point_double(p):
    return point_add(p, p)


def scalar_mult(k: int, p):
    q = IDENT
    while k > 0:
        if k & 1:
            q = point_add(q, p)
        p = point_double(p)
        k >>= 1
    return q


def point_equal(p, q) -> bool:
    # x1/z1 == x2/z2 and y1/z1 == y2/z2
    return (
        (p[0] * q[2] - q[0] * p[2]) % P == 0
        and (p[1] * q[2] - q[1] * p[2]) % P == 0
    )


def compress(p) -> bytes:
    zinv = fe_inv(p[2])
    x = p[0] * zinv % P
    y = p[1] * zinv % P
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def decompress(s: bytes) -> Optional[Tuple[int, int, int, int]]:
    """RFC 8032 §5.1.3 point decoding; returns None if not on curve."""
    if len(s) != 32:
        return None
    n = int.from_bytes(s, "little")
    sign = n >> 255
    y = n & ((1 << 255) - 1)
    if y >= P:
        # ref10's fe_frombytes would alias mod p; the strict libsodium gate
        # rejects earlier via is_canonical, but mirror the permissive decode
        y %= P
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # x = u v^3 (u v^7)^((p-5)/8)
    x = u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P) % P
    vxx = v * x * x % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = x * SQRT_M1 % P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, x * y % P)


# -- base point -------------------------------------------------------------
_BY = 4 * fe_inv(5) % P
_BX = None


def base_point():
    global _BX
    if _BX is None:
        pt = decompress(int.to_bytes(_BY, 32, "little"))
        _BX = pt
    return _BX


# -- small-order blacklist (libsodium ge25519_has_small_order equivalent) ---
@lru_cache(maxsize=1)
def small_order_blacklist() -> Tuple[bytes, ...]:
    """The 7 encodings libsodium blacklists: y-encodings of the 8-torsion
    subgroup (5 distinct with sign bit ignored) plus the two non-canonical
    aliases p and p+1.  Derived here from the curve itself."""
    # find a point of order exactly 8: decompress increasing y until the
    # point has full 8L order structure
    t8 = None
    y = 2
    while t8 is None:
        pt = decompress(int.to_bytes(y, 32, "little"))
        y += 1
        if pt is None:
            continue
        t = scalar_mult(L, pt)
        # t has order dividing 8; order exactly 8 iff 4t is not the identity
        if not point_equal(scalar_mult(4, t), IDENT):
            t8 = t
    encs = set()
    q = IDENT
    for _ in range(8):
        e = bytearray(compress(q))
        e[31] &= 0x7F  # comparisons ignore the sign bit
        encs.add(bytes(e))
        q = point_add(q, t8)
    # non-canonical aliases of y=0 -> p and y=1 -> p+1
    encs.add(int.to_bytes(P, 32, "little"))
    encs.add(int.to_bytes(P + 1, 32, "little"))
    return tuple(sorted(encs))


# -- libsodium strict-verify input gate -------------------------------------
def sc_is_canonical(s: bytes) -> bool:
    return int.from_bytes(s, "little") < L


def fe_is_canonical(s: bytes) -> bool:
    """y-coordinate (sign bit ignored) < p."""
    return (int.from_bytes(s, "little") & ((1 << 255) - 1)) < P


def has_small_order(s: bytes) -> bool:
    e = bytearray(s)
    e[31] &= 0x7F
    return bytes(e) in small_order_blacklist()


def is_torsion_free(pt) -> bool:
    """Prime-order-subgroup membership: [L]·P == identity.  The strict
    gate only rejects SMALL-order encodings; a mixed-torsion point
    (prime-order part plus nonzero 8-torsion) passes it, and the
    cofactorless aggregate MSM has only 1/8 soundness against such
    points — the aggregate plane therefore requires this proof on every
    point it trusts (native twin: halfagg.c ``torsion_free``)."""
    return point_equal(scalar_mult(L, pt), IDENT)


def _le_lt(x_words: "np.ndarray", bound: int) -> "np.ndarray":
    """(N, 4) uint64 little-endian words < bound, vectorized."""
    import numpy as np

    bw = [(bound >> (64 * i)) & 0xFFFFFFFFFFFFFFFF for i in range(4)]
    lt = np.zeros(x_words.shape[0], dtype=bool)
    eq = np.ones(x_words.shape[0], dtype=bool)
    for i in range(3, -1, -1):
        w = np.uint64(bw[i])
        lt |= eq & (x_words[:, i] < w)
        eq &= x_words[:, i] == w
    return lt


def strict_input_ok_batch(pk: "np.ndarray", sig: "np.ndarray") -> "np.ndarray":
    """Vectorized ``strict_input_ok`` over a batch: pk (N, 32) uint8,
    sig (N, 64) uint8 -> (N,) bool.  Same accept set (differential test
    in tests/test_ed25519_tpu.py); the per-item loop costs ~1.9 µs/item
    (15.5 ms per 8192, PROFILE.md) — this is ~50× cheaper."""
    import numpy as np

    s_words = np.ascontiguousarray(sig[:, 32:]).view("<u8").reshape(-1, 4)
    ok = _le_lt(s_words, L)  # canonical s

    blacklist = np.stack(
        [np.frombuffer(b, dtype=np.uint8) for b in small_order_blacklist()]
    )  # (B, 32)

    def masked(x):
        m = x.copy()
        m[:, 31] &= 0x7F
        return m

    r_m = masked(sig[:, :32])
    pk_m = masked(pk)
    ok &= ~(r_m[:, None, :] == blacklist[None]).all(axis=2).any(axis=1)
    ok &= ~(pk_m[:, None, :] == blacklist[None]).all(axis=2).any(axis=1)
    # pk_m is already a fresh contiguous uint8 copy from masked()
    pk_words = pk_m.view("<u8").reshape(-1, 4)
    ok &= _le_lt(pk_words, P)  # canonical A (sign bit ignored)
    return ok


def agg_input_ok_batch(pk: "np.ndarray", sig: "np.ndarray") -> "np.ndarray":
    """The aggregate plane's item gate: libsodium's strict gate PLUS a
    canonical-R requirement.  libsodium never decodes R — it compares the
    signature's R bytes against the canonical encoding of s·B - h·A, so a
    non-canonical R can never verify; the aggregate path DOES decode R and
    must therefore reject the non-canonical aliases up front or its accept
    set would exceed libsodium's (verdict-parity contract,
    tests/test_halfagg.py hostile lanes)."""
    import numpy as np

    ok = strict_input_ok_batch(pk, sig)
    r_m = sig[:, :32].copy()
    r_m[:, 31] &= 0x7F
    r_words = r_m.view("<u8").reshape(-1, 4)
    ok &= _le_lt(r_words, P)  # canonical R (sign bit ignored)
    return ok


def agg_input_ok(pk: bytes, sig: bytes) -> bool:
    """Scalar twin of ``agg_input_ok_batch`` (oracle + tiny batches)."""
    return (
        strict_input_ok(pk, sig)
        and len(sig) == 64
        and fe_is_canonical(sig[:32])
    )


def strict_input_ok(pk: bytes, sig: bytes) -> bool:
    """The pre-curve-math reject gate of libsodium crypto_sign_verify_detached
    (non-COMPAT build): non-canonical s, small-order R, non-canonical or
    small-order A are all rejected before any scalar mult."""
    if len(sig) != 64 or len(pk) != 32:
        return False
    if not sc_is_canonical(sig[32:]):
        return False
    if has_small_order(sig[:32]):
        return False
    if not fe_is_canonical(pk) or has_small_order(pk):
        return False
    return True


# -- full reference verify (the oracle) -------------------------------------
def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    if not strict_input_ok(pk, sig):
        return False
    a = decompress(pk)
    if a is None:
        return False
    neg_a = ((P - a[0]) % P, a[1], a[2], (P - a[3]) % P)
    h = (
        int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(), "little") % L
    )
    s = int.from_bytes(sig[32:], "little")
    r_check = point_add(scalar_mult(s, base_point()), scalar_mult(h, neg_a))
    return compress(r_check) == sig[:32]


def sign_with_seed(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 signing — only used to build test fixtures without libsodium."""
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    A = compress(scalar_mult(a, base_point()))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = compress(scalar_mult(r, base_point()))
    k = int.from_bytes(hashlib.sha512(R + A + msg).digest(), "little") % L
    s = (r + k * a) % L
    return R + int.to_bytes(s, 32, "little")
