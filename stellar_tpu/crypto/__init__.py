"""Crypto layer (reference: src/crypto/, SURVEY.md §2.8).

- ``sha``        SHA-256, HMAC, single-step HKDF
- ``keys``       SecretKey / PubKeyUtils + global verify cache
- ``sigcache``   the LRU(65535) memoizer behind all verifies
- ``sigbackend`` batched SigBackend: cpu (libsodium) | tpu (JAX kernels)
- ``strkey``     base32+CRC16 key encoding
- ``ecdh``       curve25519 session keys for peer auth
- ``sodium``     ctypes ground-truth bindings
"""

from .keys import PubKeyUtils, SecretKey, verify_cache  # noqa: F401
from .sha import (  # noqa: F401
    SHA256,
    hkdf_expand,
    hkdf_extract,
    hmac_sha256,
    hmac_sha256_verify,
    sha256,
)
from .sigbackend import (  # noqa: F401
    CachingSigBackend,
    CpuSigBackend,
    SigBackend,
    TpuSigBackend,
    make_backend,
)
