"""stellar_tpu.crypto.aggregate — the aggregate-signature consensus plane.

A second signature scheme behind the SigBackend seam (ROADMAP #3):
ed25519 half-aggregation for SCP ballot envelopes, selected per quorum
set via ``Config.SCP_SIG_SCHEME``.  ``halfagg`` is the certificate core
(transcript-bound coefficients, one-MSM verification, native Pippenger
engine with a ref25519 oracle fallback); ``scheme`` is the dispatch seam
the herder/overlay route through (slot buckets, strict gate, per-envelope
fallback, valid-only cache latch).

The registry below is what ``Config.validate`` checks — an unknown scheme
name fails the boot, not the first flush.
"""

from __future__ import annotations

from .halfagg import (
    PointCache,
    aggregate,
    native_available,
    verify_aggregated,
    verify_batch_aggregated,
)
from .scheme import Ed25519Scheme, HalfAggScheme, ScpSigScheme, make_scheme

# every scheme name Config.SCP_SIG_SCHEME accepts
SIG_SCHEMES = ("ed25519", "ed25519-halfagg")
DEFAULT_SCHEME = "ed25519"


def validate_scheme(name) -> None:
    if name not in SIG_SCHEMES:
        raise ValueError(
            f"SCP_SIG_SCHEME must be one of {SIG_SCHEMES}, got {name!r}"
        )


__all__ = [
    "SIG_SCHEMES",
    "DEFAULT_SCHEME",
    "validate_scheme",
    "make_scheme",
    "ScpSigScheme",
    "Ed25519Scheme",
    "HalfAggScheme",
    "PointCache",
    "aggregate",
    "verify_aggregated",
    "verify_batch_aggregated",
    "native_available",
]
