"""ed25519 half-aggregation — the certificate core.

"Performance of EdDSA and BLS Signatures in Committee-Based Consensus"
(PAPERS.md, arXiv:2302.00418) shows committee throughput is bounded by the
envelope-verification plane; half-aggregation (Chalkias et al.) changes the
asymptotics without changing keys or signing: given n ed25519 signatures
``(R_i, s_i)`` over ``(A_i, m_i)``, the aggregate certificate is

    agg = R_1 ‖ … ‖ R_n ‖ s̄        with  s̄ = Σ z_i·s_i  (mod L)

— half the size of the signature list (the s-halves collapse into one
scalar), verified with ONE multi-scalar-multiplication check

    (L - s̄)·B + Σ z_i·R_i + Σ (z_i·h_i mod L)·A_i  ==  identity

where ``h_i = SHA-512(R_i‖A_i‖m_i) mod L`` is the standard ed25519
challenge and the ``z_i`` are Fiat-Shamir coefficients bound to the WHOLE
statement list (every R, A and message hash feeds the transcript root), so
splicing a signature between lists, reordering, or tampering with s̄ all
break the equation.  ``z_i`` are 128-bit: forging an aggregate over an
invalid item means hitting a 2^-128 linear relation — *in the prime-order
subgroup*.  The 8-torsion subgroup sees only ``z_i mod 8``: a defect that
is pure torsion (a mixed-torsion A or a mauled R = R₀ + T) survives the
MSM whenever the coefficients conspire mod 8 — grindable Fiat-Shamir odds
of 1/8 per transcript, exactly the failure PROFILE.md's round-3 batch-RLC
note documents.  Soundness therefore additionally requires every A_i and
R_i PROVEN in the prime-order subgroup ([L]·P == identity, ``torsion_free``
in the native engine / ``ref25519.is_torsion_free``).  The proof costs
~one scalar multiplication per point: amortized to zero for validator
keys through the PointCache, paid once per fresh R — the irreducible
price of bit-parity with a cofactorless reference verifier.

Completeness is exact, not probabilistic: if every item passes libsodium's
``crypto_sign_verify_detached`` (byte-compared R), then each
``s_i·B - h_i·A_i - R_i`` is the identity POINT and any linear combination
is too — so an honest batch can never fall back.  The item accept set is
libsodium's: the strict gate (canonical s, small-order R/A, canonical A —
``ref25519.strict_input_ok``) plus canonical-R (libsodium's byte compare
can never accept a non-canonical R; see ``ref25519.agg_input_ok``), and
point decoding is STRICT in both engines.

Point work rides ``native/halfagg.c`` (Pippenger MSM + batch strict
decompress, ~7 µs/point decode on this host) with a pure-Python ref25519
fallback that doubles as the differential oracle.  Decoded validator keys
(the A_i, stable across slots) memoize in a bounded ``PointCache`` so a
steady-state slot pays decompression only for its fresh R_i.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

from ...ops import ref25519 as ref

# (pubkey32, msg, sig64) — the SigBackend triple shape
VerifyTriple = Tuple[bytes, bytes, bytes]

DOMAIN = b"stellar-tpu.halfagg.v1"
L = ref.L
_IDENT_ENC = b"\x01" + b"\x00" * 31  # compress((0, 1)) — the identity point
_EXT_BYTES = 160  # native extended-point blob (4 coords x 5 limbs x 8)


def _native():
    from ... import native

    return native.load_halfagg()


def native_available() -> bool:
    return _native() is not None


# ---------------------------------------------------------------------------
# transcript / coefficients
# ---------------------------------------------------------------------------


def _item_digest(pk: bytes, msg: bytes, r: bytes) -> bytes:
    h = hashlib.sha512()
    h.update(r)
    h.update(pk)
    h.update(hashlib.sha512(msg).digest())
    return h.digest()


def transcript_root(pks: Sequence[bytes], msgs: Sequence[bytes],
                    rs: Sequence[bytes]) -> bytes:
    """SHA-512 root binding every (R_i, A_i, m_i) in order."""
    h = hashlib.sha512()
    h.update(DOMAIN)
    h.update(len(pks).to_bytes(8, "little"))
    for pk, msg, r in zip(pks, msgs, rs):
        h.update(_item_digest(pk, msg, r))
    return h.digest()


def coefficients(root: bytes, n: int) -> List[int]:
    """The 128-bit Fiat-Shamir multipliers z_i (z_0 included — a uniform
    rule keeps the native and oracle paths trivially in lockstep)."""
    out = []
    for i in range(n):
        d = hashlib.sha512(
            DOMAIN + b".coeff" + root + i.to_bytes(8, "little")
        ).digest()
        out.append(int.from_bytes(d[:16], "little"))
    return out


def challenge(pk: bytes, msg: bytes, r: bytes) -> int:
    """The standard ed25519 challenge h = SHA-512(R‖A‖M) mod L."""
    return (
        int.from_bytes(hashlib.sha512(r + pk + msg).digest(), "little") % L
    )


# ---------------------------------------------------------------------------
# the certificate API
# ---------------------------------------------------------------------------


def aggregate(items: Sequence[VerifyTriple]) -> bytes:
    """Half-aggregate: R_1‖…‖R_n‖s̄ (32n + 32 bytes).  Pure scalar work —
    no point operation; aggregation is cheap, verification carries the
    curve math."""
    for pk, _msg, sig in items:
        if len(pk) != 32 or len(sig) != 64:
            raise ValueError(
                "halfagg aggregate needs 32-byte pubkeys and 64-byte "
                f"signatures (got pk={len(pk)}, sig={len(sig)})"
            )
    pks = [it[0] for it in items]
    msgs = [it[1] for it in items]
    rs = [it[2][:32] for it in items]
    zs = coefficients(transcript_root(pks, msgs, rs), len(items))
    s_bar = 0
    for (pk, msg, sig), z in zip(items, zs):
        s_bar = (s_bar + z * int.from_bytes(sig[32:], "little")) % L
    return b"".join(rs) + s_bar.to_bytes(32, "little")


class PointCache:
    """Bounded LRU of strict-decoded, PRIME-ORDER-PROVEN points keyed by
    their compressed encoding — the validator-key (A_i) memo.  Values are
    the native extended-limb blob, or the ref25519 coordinate tuple on
    toolchain-less hosts; ``None`` records a PERMANENT unusability:
    undecodable, or decodable but outside the prime-order subgroup (a
    mixed-torsion key would defeat the cofactorless MSM's soundness).
    Both properties are intrinsic to the encoding, so the negative cache
    keeps a hostile peer from making the node re-derive the same failed
    square root — or re-run the same [L]·P ladder — every slot."""

    def __init__(self, capacity: int = 0x10000):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._map: OrderedDict = OrderedDict()

    def get_many(self, encs: Sequence[bytes]) -> list:
        out = []
        with self._lock:
            for e in encs:
                if e in self._map:
                    self._map.move_to_end(e)
                    out.append(self._map[e])
                else:
                    out.append(False)  # miss marker (None = cached failure)
        return out

    def put_many(self, pairs) -> None:
        with self._lock:
            for enc, val in pairs:
                self._map[enc] = val
                self._map.move_to_end(enc)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def __len__(self):
        with self._lock:
            return len(self._map)


def _decompress_many(
    encs: Sequence[bytes],
    cache: Optional[PointCache],
    check_torsion: bool = True,
):
    """Strict-decode a point column, through the cache when given.
    Returns a list of native ext blobs / ref tuples, with None for
    unusable encodings — undecodable, or (with ``check_torsion``, the
    default) outside the prime-order subgroup.  ``check_torsion=False``
    defers the [L]·P proof to the caller (the R column runs it only
    after the MSM passes, so a poisoned bucket skips it) and is only
    valid with ``cache=None`` — the cache stores proven points."""
    if not check_torsion and cache is not None:
        raise ValueError(
            "check_torsion=False would cache torsion-unproven points"
        )
    mod = _native()
    vals = cache.get_many(encs) if cache is not None else [False] * len(encs)
    miss = [i for i, v in enumerate(vals) if v is False]
    if miss:
        if mod is not None:
            ok, ext = mod.decompress(b"".join(encs[i] for i in miss))
            for j, i in enumerate(miss):
                vals[i] = (
                    ext[j * _EXT_BYTES : (j + 1) * _EXT_BYTES]
                    if ok[j]
                    else None
                )
        else:
            for i in miss:
                enc = encs[i]
                pt = (
                    ref.decompress(enc)
                    if ref.fe_is_canonical(enc)
                    else None
                )
                vals[i] = pt
        if check_torsion:
            decoded = [i for i in miss if vals[i] is not None]
            if decoded:
                free = _torsion_free_many([vals[i] for i in decoded])
                for i, tf in zip(decoded, free):
                    if not tf:
                        vals[i] = None
        if cache is not None:
            cache.put_many((encs[i], vals[i]) for i in miss)
    return vals


def torsion_free_encs(encs: Sequence[bytes]) -> List[bool]:
    """Host prime-order proofs straight from compressed encodings: True
    iff the encoding is canonical, strict-decodable AND torsion-free.
    This is the SigBackend.torsion_check host path (and the oracle the
    device batch-plane prover is differential-tested against)."""
    out = [False] * len(encs)
    well = [i for i, e in enumerate(encs) if len(e) == 32]
    if not well:
        return out
    vals = _decompress_many([encs[i] for i in well], None, check_torsion=False)
    idx = [k for k, v in enumerate(vals) if v is not None]
    free = _torsion_free_many([vals[k] for k in idx])
    for k, tf in zip(idx, free):
        out[well[k]] = tf
    return out


def torsion_free_points(vals: Sequence) -> List[bool]:
    """Prime-order proofs over ALREADY-DECODED points (the non-None
    values ``_decompress_many`` returns) — the re-decode-free host path
    for callers that hold both the encodings and the decoded points
    (SigBackend.torsion_check's ``vals`` fast path)."""
    return _torsion_free_many(vals)


def _torsion_free_many(vals: Sequence) -> List[bool]:
    """Prime-order-subgroup proof per decoded point ([L]·P == identity).
    ``vals`` are non-None values from ``_decompress_many`` — native ext
    blobs or ref tuples.  See the module docstring: the cofactorless MSM
    alone has only 1/8 soundness against torsion components, so every
    point the aggregate plane trusts must pass this."""
    mod = _native()
    if not vals:
        return []
    if mod is not None and isinstance(vals[0], bytes):
        ok = mod.torsion_free(b"".join(vals))
        return [bool(b) for b in ok]
    return [ref.is_torsion_free(v) for v in vals]


def _msm_is_identity(points, scalars: Sequence[int]) -> bool:
    """One Pippenger check: Σ scalar_i·P_i == identity.  ``points`` are
    decoded values from ``_decompress_many`` (all non-None)."""
    mod = _native()
    if mod is not None:
        sc = b"".join(s.to_bytes(32, "little") for s in scalars)
        return mod.msm_ext(b"".join(points), sc) == _IDENT_ENC
    acc = ref.IDENT
    for pt, s in zip(points, scalars):
        acc = ref.point_add(acc, ref.scalar_mult(s, pt))
    return ref.point_equal(acc, ref.IDENT)


def verify_aggregated(
    pks: Sequence[bytes],
    msgs: Sequence[bytes],
    aggsig: bytes,
    point_cache: Optional[PointCache] = None,
    torsion_prover=None,
) -> bool:
    """Verify a half-aggregate certificate against its statement list.
    True ⇒ every (A_i, m_i) carries a signature libsodium would accept
    (up to the 2^-128 batch-soundness bound); any tampered R, spliced
    item, reordered list, or forged s̄ fails.  The accept set is further
    restricted to prime-order A_i and R_i (honest signers never produce
    anything else): a mixed-torsion point would cut the MSM's soundness
    to 1/8, so it is rejected outright — the certificate API has no
    per-item fallback to shelter it."""
    n = len(pks)
    if len(msgs) != n or len(aggsig) != 32 * n + 32:
        return False
    rs = [aggsig[32 * i : 32 * i + 32] for i in range(n)]
    s_bar = int.from_bytes(aggsig[32 * n :], "little")
    if s_bar >= L:
        return False
    # item gate: small-order R/A and non-canonical A/R are outside
    # libsodium's accept set regardless of any equation
    for pk, r in zip(pks, rs):
        if not (
            len(pk) == 32
            and ref.fe_is_canonical(pk)
            and not ref.has_small_order(pk)
            and ref.fe_is_canonical(r)
            and not ref.has_small_order(r)
        ):
            return False
    if n == 0:
        return s_bar == 0
    a_pts = _decompress_many(list(pks), point_cache)
    r_pts = _decompress_many(rs, None, check_torsion=False)
    if any(p is None for p in a_pts) or any(p is None for p in r_pts):
        return False
    zs = coefficients(transcript_root(pks, msgs, rs), n)
    hs = [challenge(pk, msg, r) for pk, msg, r in zip(pks, msgs, rs)]
    b_pt = _decompress_many([_BASE_ENC], _base_cache)[0]
    points = [b_pt] + r_pts + a_pts
    scalars = [(L - s_bar) % L] + zs + [
        (z * h) % L for z, h in zip(zs, hs)
    ]
    if not _msm_is_identity(points, scalars):
        return False
    # the MSM is blind to torsion whenever the z_i conspire mod 8; only
    # a prime-order proof of the fresh R column closes the 1/8 hole (the
    # A column was proven inside _decompress_many, cached).  A
    # torsion_prover (the device batch plane, SigBackend.torsion_check)
    # serves the proofs from the R ENCODINGS — already proven canonical
    # and decodable above, so prover and host ladder agree bit-exactly;
    # the decoded r_pts ride along so a host-riding prover (cutover,
    # wedge latch) never re-decodes what this pass already decoded.
    if torsion_prover is not None:
        return all(torsion_prover(rs, r_pts))
    return all(_torsion_free_many(r_pts))


_BASE_ENC = ref.compress(ref.base_point())
_base_cache = PointCache(capacity=4)


def verify_batch_aggregated(
    items: Sequence[VerifyTriple],
    point_cache: Optional[PointCache] = None,
    gated: bool = False,
    torsion_prover=None,
) -> bool:
    """Aggregate-then-verify a batch of full signatures in one check —
    the node-local form the SCP scheme uses (the node holds every s_i; a
    wire-format certificate would drop them).  Semantically identical to
    ``verify_aggregated(aggregate(items))`` minus one transcript pass.
    ``gated=True`` skips the per-item strict gate (the caller already
    ran ``agg_input_ok_batch`` and excluded the rejects).
    ``torsion_prover`` ((encs, decoded_pts) -> [bool]) serves the
    post-MSM fresh-R prime-order proofs — the scheme passes the
    backend's device batch plane here (ROADMAP #3 remainder (a));
    None = the host ladder."""
    n = len(items)
    if n == 0:
        return True
    pks = [it[0] for it in items]
    msgs = [it[1] for it in items]
    rs = [it[2][:32] for it in items]
    if not gated:
        for pk, msg, sig in items:
            if len(sig) != 64 or not ref.agg_input_ok(pk, sig):
                return False
    a_pts = _decompress_many(pks, point_cache)
    if any(p is None for p in a_pts):
        return False
    r_pts = _decompress_many(rs, None, check_torsion=False)
    if any(p is None for p in r_pts):
        return False
    zs = coefficients(transcript_root(pks, msgs, rs), n)
    hs = [challenge(pk, msg, r) for pk, msg, r in zip(pks, msgs, rs)]
    s_bar = 0
    for (pk, msg, sig), z in zip(items, zs):
        s_bar = (s_bar + z * int.from_bytes(sig[32:], "little")) % L
    b_pt = _decompress_many([_BASE_ENC], _base_cache)[0]
    points = [b_pt] + r_pts + a_pts
    scalars = [(L - s_bar) % L] + zs + [
        (z * h) % L for z, h in zip(zs, hs)
    ]
    if not _msm_is_identity(points, scalars):
        return False
    # cofactorless-MSM pass alone is 1/8-sound against a mauled R = R₀+T;
    # only latch-grade once every fresh R is proven prime-order (A column
    # proven via the cache in _decompress_many; B is prime-order).  The
    # prover sees the R ENCODINGS (canonical + decodable by this point),
    # where device and host ladders agree bit-exactly, plus the decoded
    # r_pts so a host-riding prover skips the second decompress pass.
    if torsion_prover is not None:
        return all(torsion_prover(rs, r_pts))
    return all(_torsion_free_many(r_pts))
