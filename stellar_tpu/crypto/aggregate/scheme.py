"""The SCP signature-scheme seam — how envelope verification is dispatched.

``Config.SCP_SIG_SCHEME`` selects, per node (i.e. per the quorum set this
validator faces), which scheme serves the overlay's per-crank SCP envelope
batch flush:

- ``"ed25519"`` (default): the reference path, byte-for-byte — one
  ``SigBackend.verify_batch`` over the whole batch (CALLER_OVERLAY), the
  TPU batch plane / SIG_MESH dispatch and the shared verify cache exactly
  as before this seam existed.
- ``"ed25519-halfagg"``: the aggregate-signature consensus plane.  The
  flush groups its cache-miss envelopes into per-slot aggregation buckets
  (a slot's ballots are one statement list), strict-gates each item, and
  verifies each bucket with ONE half-aggregation MSM check
  (crypto/aggregate/halfagg.py) instead of one batch lane per signature.
  A bucket whose aggregate check fails — any invalid signature, hostile
  point (including a mixed-torsion A or R, against which the cofactorless
  MSM alone would only be 1/8-sound; halfagg.py proves every trusted
  point prime-order), 2^-128 bad luck — FALLS BACK to the per-envelope
  SigBackend for that bucket, so per-item verdicts are always
  bit-identical to the reference path: honest buckets pay one aggregate
  check, poisoned buckets pay aggregate + the reference cost
  (arXiv:2302.00418's speculative-aggregate-verify shape; the TPU batch
  plane stays the non-aggregatable fallback per arXiv:2604.17808).
  Items whose pubkey is negative-cached as permanently unusable
  (undecodable or torsioned — properties libsodium itself may tolerate
  on crafted signatures) are routed per-item BEFORE bucketing, so one
  hostile key poisons a bucket only on first sight.

Cache contract: both schemes latch VALID verdicts only into the shared
verify cache (the flood-defense latch contract, PR 8).  The aggregate
path's latch happens right here in ``HalfAggScheme`` — an
analysis-recognized latch class (stellar_tpu/analysis/rules.py
``cache-latch``) because an aggregate-accepted bucket's verdicts were
just computed synchronously on the caller's thread against live state;
there is no async future to quarantine.  The fallback path latches
through ``CachingSigBackend`` like every other batch, so the wedge-latch
(per caller class) and quarantine contracts hold unchanged.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ...trace import NULL_TRACER
from ..sigbackend import CALLER_OVERLAY
from . import halfagg

VerifyTriple = Tuple[bytes, bytes, bytes]


class ScpSigScheme:
    """Per-envelope reference scheme — the seam's identity element."""

    name = "ed25519"
    # the close pipeline's per-envelope async SCP prewarm only helps a
    # scheme that will verify per-envelope anyway; the aggregate scheme
    # opts out (a prewarm would pre-latch every verdict and starve the
    # aggregate path of its batch)
    wants_envelope_prewarm = True

    def __init__(self, backend, cache, tracer=None):
        self.backend = backend
        self.cache = cache
        self._tracer = tracer if tracer is not None else NULL_TRACER
        # wall the envelope-verification plane steals from the crank —
        # the number the chaos plane's flood A/B compares across schemes
        # (a flooded 1-core node wedges when this approaches the crank
        # budget; telemetry only, never in a replay digest)
        self.verify_wall_ms = 0.0
        self.n_flush_envelopes = 0

    def verify_flush(
        self, items: Sequence[VerifyTriple], slots: Sequence[int]
    ) -> List[bool]:
        """Verdicts for one overlay batch flush; ``slots`` carries each
        item's slot index (the aggregate scheme's bucket key — unused
        here)."""
        t0 = time.perf_counter()
        out = self.backend.verify_batch(items, caller=CALLER_OVERLAY)
        self.verify_wall_ms += (time.perf_counter() - t0) * 1000.0
        self.n_flush_envelopes += len(items)
        return out

    def verify_envelope_cached(self, key, signature: bytes, msg: bytes) -> bool:
        """The herder's eager single-envelope check (recv gate + SCP's
        own pre-process verify).  Single envelopes have nothing to
        aggregate with, so BOTH schemes serve them from the per-envelope
        plane — after a batch flush this is a warm-cache hit either way."""
        from ..keys import PubKeyUtils

        return PubKeyUtils.verify_sig(key, signature, msg)

    def stats(self) -> dict:
        return {
            "scheme": self.name,
            "flush_envelopes": self.n_flush_envelopes,
            "verify_wall_ms": round(self.verify_wall_ms, 2),
        }


class HalfAggScheme(ScpSigScheme):
    """Slot-bucketed half-aggregation with per-envelope fallback."""

    name = "ed25519-halfagg"
    wants_envelope_prewarm = False

    # below this many cache-miss items in a slot bucket, the MSM setup
    # (transcript hashing + decompress) costs more than looping libsodium
    # — lone envelopes and thin slots ride the reference path
    MIN_AGG = 4

    def __init__(self, backend, cache, tracer=None, point_cache=None):
        super().__init__(backend, cache, tracer=tracer)
        # decoded validator keys (A_i) memoized across slots — the
        # validator set is stable, so steady state decompresses only
        # each envelope's fresh R
        self.point_cache = (
            point_cache if point_cache is not None else halfagg.PointCache()
        )
        self.n_agg_checks = 0
        self.n_agg_passed = 0
        self.n_agg_envelopes = 0
        self.n_fallback_envelopes = 0
        self.n_gate_rejects = 0
        self.n_small_buckets = 0
        self.n_unaggregatable = 0  # negative-cached A: per-item, pre-bucket
        self.n_r_proof_points = 0  # post-MSM fresh-R proofs routed below

    def verify_flush(
        self, items: Sequence[VerifyTriple], slots: Sequence[int]
    ) -> List[bool]:
        t0 = time.perf_counter()
        items = list(items)
        n = len(items)
        sp = self._tracer.begin("scp.agg_flush")
        keys = [
            self.cache.key_for(pk, sig, msg) for pk, msg, sig in items
        ]
        cached = self.cache.peek_many(keys)
        verdicts: List[Optional[bool]] = [
            bool(c) if c is not None else None for c in cached
        ]
        # per-slot aggregation buckets over the cache misses — one slot's
        # ballots are one jointly-verified statement list
        buckets: Dict[int, List[int]] = {}
        for i, v in enumerate(verdicts):
            if v is None:
                buckets.setdefault(slots[i], []).append(i)
        fallback: List[int] = []
        n_checks = n_passed = n_agg = n_gate = n_small = n_unagg = 0
        for slot, idxs in buckets.items():
            if len(idxs) < self.MIN_AGG:
                n_small += len(idxs)
                fallback.extend(idxs)
                continue
            gate_ok = self._gate([items[i] for i in idxs])
            for i, ok in zip(idxs, gate_ok):
                if not ok:
                    # outside libsodium's accept set — same verdict the
                    # reference path would return, at gate cost
                    verdicts[i] = False
                    n_gate += 1
            eligible = [i for i, ok in zip(idxs, gate_ok) if ok]
            # pubkeys negative-cached as permanently unusable (undecodable
            # or torsioned) can never aggregate but CAN carry signatures
            # libsodium accepts — per-item verdicts, without letting one
            # such key poison this bucket every flush
            a_vals = self.point_cache.get_many(
                [items[i][0] for i in eligible]
            )
            bad_a = [i for i, v in zip(eligible, a_vals) if v is None]
            if bad_a:
                n_unagg += len(bad_a)
                fallback.extend(bad_a)
                eligible = [
                    i for i, v in zip(eligible, a_vals) if v is not None
                ]
            if len(eligible) < self.MIN_AGG:
                n_small += len(eligible)
                fallback.extend(eligible)
                continue
            n_checks += 1
            if halfagg.verify_batch_aggregated(
                [items[i] for i in eligible],
                point_cache=self.point_cache,
                gated=True,
                torsion_prover=self._torsion_prover,
            ):
                n_passed += 1
                n_agg += len(eligible)
                for i in eligible:
                    verdicts[i] = True
                # valid-only latch, synchronously on the caller's thread:
                # the aggregate check just proved every one of these
                # signatures libsodium-valid (completeness is exact, and
                # soundness is 2^-128 because every A and fresh R was
                # proven prime-order before the MSM verdict counts), so
                # invalid items can never reach this line — the bounded
                # LRU stays un-pollutable under flood exactly like the
                # reference path
                self.cache.put_many((keys[i], True) for i in eligible)
            else:
                # poisoned bucket: per-item verdicts come from the
                # reference plane (the caching backend latches its own
                # valid-only results)
                fallback.extend(eligible)
        if fallback:
            self.n_fallback_envelopes += len(fallback)
            fresh = self.backend.verify_batch(
                [items[i] for i in fallback], caller=CALLER_OVERLAY
            )
            for i, ok in zip(fallback, fresh):
                verdicts[i] = bool(ok)
        self.n_agg_checks += n_checks
        self.n_agg_passed += n_passed
        self.n_agg_envelopes += n_agg
        self.n_gate_rejects += n_gate
        self.n_small_buckets += n_small
        self.n_unaggregatable += n_unagg
        self._tracer.end(
            sp,
            batch=n,
            cache_hits=sum(1 for c in cached if c is not None),
            agg_checks=n_checks,
            aggregated=n_agg,
            fallback=len(fallback),
        )
        self.verify_wall_ms += (time.perf_counter() - t0) * 1000.0
        self.n_flush_envelopes += n
        return [bool(v) for v in verdicts]

    def _torsion_prover(self, encs: Sequence[bytes], vals=None) -> List[bool]:
        """Post-MSM fresh-R prime-order proofs, routed through the
        backend's torsion surface (ROADMAP #3 remainder (a)): on the tpu
        backend the verify kernel computes [L]·R == identity AS-IS as a
        batch lane (~device marginal cost vs ~31 µs/point of host
        ladder), under the SAME caller class (CALLER_OVERLAY) so the
        wedge latch and cutover contracts hold; the cpu backend serves
        the identical host ladder — on halfagg's already-decoded
        ``vals``, no second decompress — verdicts bit-exact either
        way."""
        self.n_r_proof_points += len(encs)
        return self.backend.torsion_check(
            encs, caller=CALLER_OVERLAY, vals=vals
        )

    @staticmethod
    def _gate(items: Sequence[VerifyTriple]) -> List[bool]:
        """Vectorized strict gate + canonical-R (ref25519.agg_input_ok),
        with a scalar fallback for malformed-length items."""
        import numpy as np

        from ...ops import ref25519 as ref

        if any(len(pk) != 32 or len(sig) != 64 for pk, _, sig in items):
            return [
                len(pk) == 32
                and len(sig) == 64
                and ref.agg_input_ok(pk, sig)
                for pk, _, sig in items
            ]
        pk = np.frombuffer(
            b"".join(it[0] for it in items), dtype=np.uint8
        ).reshape(-1, 32)
        sig = np.frombuffer(
            b"".join(it[2] for it in items), dtype=np.uint8
        ).reshape(-1, 64)
        return [bool(x) for x in ref.agg_input_ok_batch(pk, sig)]

    def stats(self) -> dict:
        return {
            "scheme": self.name,
            "flush_envelopes": self.n_flush_envelopes,
            "verify_wall_ms": round(self.verify_wall_ms, 2),
            "agg_checks": self.n_agg_checks,
            "agg_passed": self.n_agg_passed,
            "agg_envelopes": self.n_agg_envelopes,
            "fallback_envelopes": self.n_fallback_envelopes,
            "gate_rejects": self.n_gate_rejects,
            "small_bucket_envelopes": self.n_small_buckets,
            "unaggregatable_envelopes": self.n_unaggregatable,
            "r_proof_points": self.n_r_proof_points,
            "point_cache_entries": len(self.point_cache),
            "native_msm": halfagg.native_available(),
        }


# the reference scheme under its registry name (the base class IS the
# per-envelope dispatch)
Ed25519Scheme = ScpSigScheme


def make_scheme(name: str, backend, cache, tracer=None) -> ScpSigScheme:
    if name == "ed25519":
        return ScpSigScheme(backend, cache, tracer=tracer)
    if name == "ed25519-halfagg":
        return HalfAggScheme(backend, cache, tracer=tracer)
    raise ValueError(f"unknown SCP_SIG_SCHEME {name!r}")
