"""SigBackend — the batched signature-verification abstraction.

This is the north-star design point of the framework (BASELINE.json): the
reference calls libsodium inline at three sites (SURVEY.md §2.8); here every
verify is expressed as a *batch* of (pubkey, msg, sig) triples so the hot
paths (TxSetFrame.check_valid, Herder.verify_envelope, ledger close) can
flush hundreds-to-thousands of verifies at once onto the TPU.

Selected via config ``SIGNATURE_BACKEND = "cpu" | "tpu"`` (the reference has
no such knob; its equivalent is the hardwired libsodium call at
SecretKey.cpp:277-279).  Both backends sit behind the same global verify
cache, so eager single verifies (PubKeyUtils.verify_sig) and batch verifies
share memoization exactly like the reference's gVerifySigCache.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from ..trace import NULL_TRACER
from ..util import xlog
from . import sodium
from .sigcache import VerifySigCache

_log = xlog.logger("Tx")

VerifyTriple = Tuple[bytes, bytes, bytes]  # (pubkey32, msg, sig64)

# Default device/host breakeven for the tpu backend, in cache-miss verifies:
# n/host_rate = rtt + n/device_rate at the MEASURED relay (68 ms RTT, 230k/s
# device, 16k/s host core) gives n ≈ 1,100.  Locally-attached TPU (sub-ms
# dispatch) breaks even near ~20 — retune HERE (Config.TPU_CPU_CUTOVER
# references this constant).
DEFAULT_TPU_CPU_CUTOVER = 1024


class SigBackend:
    name = "abstract"

    def verify_batch(self, items: Sequence[VerifyTriple]) -> List[bool]:
        raise NotImplementedError

    def stats(self) -> dict:
        return {}


class CachingSigBackend(SigBackend):
    """Wraps an inner backend with the shared verify cache: cached results
    are served immediately, only misses reach the inner backend, and results
    scatter back into the cache."""

    def __init__(self, inner: SigBackend, cache: VerifySigCache, tracer=None):
        self.inner = inner
        self.cache = cache
        self.name = inner.name
        self._tracer = tracer if tracer is not None else NULL_TRACER

    def verify_batch(self, items: Sequence[VerifyTriple]) -> List[bool]:
        # one sig-flush span per batch (never per item): batch size and the
        # cache-hit/miss split are THE attribution the close trace needs
        sp = self._tracer.begin("sig.flush")
        keys = [self.cache.key_for(pk, sig, msg) for pk, msg, sig in items]
        cached = self.cache.peek_many(keys)
        miss_idx = [i for i, c in enumerate(cached) if c is None]
        if miss_idx:
            fresh = self.inner.verify_batch([items[i] for i in miss_idx])
            self.cache.put_many(
                (keys[i], ok) for i, ok in zip(miss_idx, fresh)
            )
            for i, ok in zip(miss_idx, fresh):
                cached[i] = ok
        self._tracer.end(
            sp,
            batch=len(items),
            cache_hits=len(items) - len(miss_idx),
            misses=len(miss_idx),
            backend=self.name,
        )
        return [bool(c) for c in cached]

    def stats(self) -> dict:
        return self.inner.stats()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            _log.warning("ignoring malformed %s=%r; using %s", name, raw, default)
    return default


_pool = None
_pool_lock = threading.Lock()


def _sodium_verify_native(items: Sequence[VerifyTriple]) -> Optional[List[bool]]:
    """Fan a whole cache-miss batch over the native sighash worker pool:
    ONE GIL-released C call whose tiles invoke libsodium's
    crypto_sign_verify_detached through a function pointer (resolved from
    the SAME loaded library the serial path calls), so multi-core hosts
    parallelize the strict-verify leg with zero per-item Python dispatch
    — the Python ThreadPoolExecutor fallback below still serializes the
    per-chunk loop bookkeeping under the GIL.

    Returns None when the extension, libsodium, or the bytes-only item
    contract is unavailable; the caller falls back.  Verdicts are
    byte-identical to sodium.verify_detached (the C tile mirrors its
    length prechecks, then calls the same function)."""
    from ..native import load_sighash

    mod = load_sighash()
    if mod is None or not hasattr(mod, "sodium_verify"):
        return None
    try:
        fn = sodium.verify_fn_addr()
    except RuntimeError:
        return None
    ok = bytearray(len(items))
    try:
        mod.sodium_verify(fn, items, ok)
    except TypeError:
        # a non-bytes buffer slipped into the batch (the C side borrows
        # pointers across the GIL release, so it accepts bytes only) —
        # the Python loop handles such items fine
        return None
    return [bool(b) for b in ok]


def _sodium_verify_loop(items: Sequence[VerifyTriple]) -> List[bool]:
    """One libsodium verify per triple — the reference's exact behavior
    (crypto_sign_verify_detached, SecretKey.cpp:277-279).  Shared by the
    cpu backend and the tpu backend's small-batch cutover.

    Large batches fan out over the native sighash pthread pool when the
    extension built (one GIL-released C call, see _sodium_verify_native),
    else over a Python thread pool (the ctypes call releases the GIL, so
    it still scales, minus the per-chunk Python overhead).  Single-core
    hosts and small batches keep the plain serial loop — byte-identical
    to the reference, per the r09 satellite contract."""
    import os

    n = len(items)
    workers = min(8, os.cpu_count() or 1)
    if n < 256 or workers < 2:
        return [sodium.verify_detached(sig, msg, pk) for pk, msg, sig in items]
    native = _sodium_verify_native(items)
    if native is not None:
        return native
    global _pool
    if _pool is None:
        from concurrent.futures import ThreadPoolExecutor

        with _pool_lock:  # e.g. prewarm worker + main thread racing init
            if _pool is None:
                _pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="sodium-verify"
                )
    chunk = (n + workers - 1) // workers

    def run(lo):
        return [
            sodium.verify_detached(sig, msg, pk)
            for pk, msg, sig in items[lo : lo + chunk]
        ]

    parts = list(_pool.map(run, range(0, n, chunk)))
    return [ok for part in parts for ok in part]


class CpuSigBackend(SigBackend):
    name = "cpu"

    def verify_batch(self, items: Sequence[VerifyTriple]) -> List[bool]:
        return _sodium_verify_loop(items)


class TpuSigBackend(SigBackend):
    """JAX batched ed25519 verify: strict canonicity/small-order prechecks and
    SHA-512 reduction on host, curve math (decompress + double-scalar-mult)
    on the accelerator.  Bit-exact with libsodium by construction + the
    differential test suite (tests/test_ed25519_tpu.py)."""

    name = "tpu"
    # class-level default: harness code (and tests) that build the backend
    # via __new__ + hand-set attributes still get a working no-op tracer
    _tracer = NULL_TRACER

    def __init__(
        self,
        max_batch: int = 4096,
        mesh=None,
        cpu_cutover: int = DEFAULT_TPU_CPU_CUTOVER,
        streams: Optional[int] = None,
        native_hash: Optional[bool] = None,
        tracer=None,
    ):
        from ..ops.ed25519 import BatchVerifier  # lazy: JAX import

        self._tracer = tracer if tracer is not None else NULL_TRACER
        # native_hash: the C host stage (gate + batch SHA-512 mod L,
        # native/sighash.c) — default auto (on when it builds); stats()
        # reports which stage is live as "native_host_stage"
        self._verifier = BatchVerifier(
            max_batch=max_batch,
            mesh=mesh,
            streams=streams,
            native_hash=native_hash,
            tracer=tracer,
        )
        # Below this many cache misses a device round-trip costs more than
        # looping libsodium on host — lone SCP envelopes and small tx sets
        # must never pay device latency just because the backend is "tpu"
        # (see DEFAULT_TPU_CPU_CUTOVER for the breakeven arithmetic).
        self.cpu_cutover = cpu_cutover
        self.n_cutover_items = 0
        self.n_wedge_fallback_items = 0
        self._wedged_until = 0.0
        # verify_batch is called concurrently (async signature prewarm
        # worker + the SCP crank); the latch read/write and the budget
        # choice go under one small lock so callers see consistent state
        self._wedge_lock = threading.Lock()

    # A wedged device dispatch (e.g. accelerator transport outage) must
    # never stall a caller indefinitely — SCP envelope flushes run on the
    # main crank and ledger close joins the prewarm; the reference's
    # inline libsodium path cannot hang, so neither may this one.  After
    # the timeout the batch finishes on host and the backend LATCHES onto
    # host for RETRY_INTERVAL (a persistently-dead transport costs at
    # most one bounded stall per interval, not one per batch).  The FIRST
    # dispatch gets a much longer budget: per-bucket XLA/remote compiles
    # legitimately take tens of seconds and must not false-latch a
    # healthy device (a false latch would self-heal after RETRY_INTERVAL,
    # but costs double work and misleading wedge telemetry).
    # Env-overridable: a loaded CI/test host can push the interpret-mode
    # compile past 90s, and a false latch there fails device-path tests
    # (tests/conftest.py raises the first-dispatch budget for exactly
    # that; production keeps the measured defaults).  A malformed value
    # falls back to the default — a typo'd budget must not kill the node
    # at import.
    DEVICE_TIMEOUT = _env_float("STELLAR_TPU_DISPATCH_BUDGET", 15.0)
    DEVICE_FIRST_TIMEOUT = _env_float("STELLAR_TPU_FIRST_DISPATCH_BUDGET", 90.0)
    RETRY_INTERVAL = 60.0

    def verify_batch(self, items: Sequence[VerifyTriple]) -> List[bool]:
        if len(items) < self.cpu_cutover:
            self.n_cutover_items += len(items)
            with self._tracer.span(
                "sig.host_verify", items=len(items), reason="cutover"
            ):
                return _sodium_verify_loop(items)
        # the lock covers only the latch read/write and the budget choice —
        # never the verify work itself, or every concurrent caller inherits
        # the slowest batch's host-verify latency
        with self._wedge_lock:
            wedged = time.monotonic() < self._wedged_until
            # every caller keeps the long budget until the first device call
            # has COMPLETED (not merely been dispatched): a second caller
            # arriving mid-compile rides the same XLA compile and must not
            # false-latch a healthy device with the short budget
            first = self._verifier.n_device_calls == 0
        if wedged:
            self.n_wedge_fallback_items += len(items)
            with self._tracer.span(
                "sig.host_verify", items=len(items), reason="wedge-latch"
            ):
                return _sodium_verify_loop(items)
        result: List[Any] = [None]
        err: List[BaseException] = []
        done = threading.Event()

        def work():
            try:
                result[0] = self._verifier.verify(items)
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=work, name="tpu-verify", daemon=True)
        t.start()
        timeout = self.DEVICE_FIRST_TIMEOUT if first else self.DEVICE_TIMEOUT
        if not done.wait(timeout):
            with self._wedge_lock:
                self._wedged_until = time.monotonic() + self.RETRY_INTERVAL
            self.n_wedge_fallback_items += len(items)
            _log.warning(
                "device verify batch stalled >%.0fs; finishing %d verifies"
                " on host and latching onto host for %.0fs",
                timeout,
                len(items),
                self.RETRY_INTERVAL,
            )
            # the orphaned worker's eventual completion is harmless: the
            # caller-side cache scatter-back writes identical values
            with self._tracer.span(
                "sig.host_verify", items=len(items), reason="device-stall"
            ):
                return _sodium_verify_loop(items)
        if err:
            raise err[0]
        return result[0]

    def stats(self) -> dict:
        s = self._verifier.stats()
        s["cpu_cutover_items"] = self.n_cutover_items
        s["wedge_fallback_items"] = self.n_wedge_fallback_items
        return s


def make_backend(
    kind: str = "cpu",
    cache: VerifySigCache = None,
    tracer=None,
    **kw,
) -> SigBackend:
    if kind == "cpu":
        inner: SigBackend = CpuSigBackend()
    elif kind == "tpu":
        inner = TpuSigBackend(tracer=tracer, **kw)
    else:
        raise ValueError(f"unknown SIGNATURE_BACKEND {kind!r}")
    if cache is None:
        from .keys import verify_cache

        cache = verify_cache()
    return CachingSigBackend(inner, cache, tracer=tracer)
