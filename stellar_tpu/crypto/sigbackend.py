"""SigBackend — the batched signature-verification abstraction.

This is the north-star design point of the framework (BASELINE.json): the
reference calls libsodium inline at three sites (SURVEY.md §2.8); here every
verify is expressed as a *batch* of (pubkey, msg, sig) triples so the hot
paths (TxSetFrame.check_valid, Herder.verify_envelope, ledger close) can
flush hundreds-to-thousands of verifies at once onto the TPU.

Selected via config ``SIGNATURE_BACKEND = "cpu" | "tpu"`` (the reference has
no such knob; its equivalent is the hardwired libsodium call at
SecretKey.cpp:277-279).  Both backends sit behind the same global verify
cache, so eager single verifies (PubKeyUtils.verify_sig) and batch verifies
share memoization exactly like the reference's gVerifySigCache.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from ..trace import NULL_TRACER
from ..util import xlog
from . import sodium
from .sigcache import VerifySigCache

_log = xlog.logger("Tx")

VerifyTriple = Tuple[bytes, bytes, bytes]  # (pubkey32, msg, sig64)

# Caller classes for the tpu backend's host-fallback latch (and the async
# flush plane's attribution): a stalled PIPELINED prewarm must never route
# subsequent SYNCHRONOUS close-path batches onto host — the latch is scoped
# per class (ISSUE r10 satellite; see TpuSigBackend.verify_batch).
CALLER_CLOSE = "close"        # synchronous close-path / check_valid flushes
CALLER_PIPELINE = "pipeline"  # close-pipeline async prewarms (ledger N+1)
CALLER_OVERLAY = "overlay"    # per-crank SCP envelope batch flushes
CALLER_INGEST = "ingest"      # tx admission-plane micro-batches (front door)


class SigFlushFuture:
    """Handle to one in-flight asynchronous batch verify — the unit the
    close-pipeline scheduler dispatches while ledger N applies and joins at
    the top of ledger N+1's close.

    Lifecycle: ``dispatch`` (worker starts) → ``complete`` (verdicts ready;
    a caching backend latches them into the shared verify cache at this
    point, never earlier) → ``result()`` (join; re-raises a worker error).
    ``quarantine()`` severs the future from the cache plane: verdicts from
    a quarantined batch are never latched, and any already latched are
    evicted — an aborted/forked close must not leave its in-flight flush's
    writes behind (the contract tests/test_closepipeline.py pins).

    Timestamps (``time.monotonic``) let the scheduler account overlap:
    ``completed_at - dispatched_at`` is the async verify's duration; the
    part of it that elapsed before the join is hidden work."""

    def __init__(self, n_items: int):
        self.items = n_items
        self.dispatched_at = time.monotonic()
        self.completed_at: Optional[float] = None
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[List[bool]] = None
        self._err: Optional[BaseException] = None
        self._quarantined = False  # analysis: locked-by _lock
        # set by CachingSigBackend before dispatch: (cache, [(key, idx)...])
        # mapping miss keys to result rows — the latch happens inside
        # _complete under the future's lock so quarantine() can never race
        # a put_many it doesn't see
        self._latch = None  # analysis: locked-by _lock
        self._latched = False  # analysis: locked-by _lock

    def done(self) -> bool:
        return self._done.is_set()

    def quarantined(self) -> bool:
        with self._lock:
            return self._quarantined

    def quarantine(self) -> None:
        """Disown the batch: results will not (and no longer do) back the
        shared verify cache.  Idempotent; safe in any state."""
        with self._lock:
            self._quarantined = True
            if self._latched and self._latch is not None:
                cache, key_rows = self._latch
                cache.drop_many(k for k, _ in key_rows)
                self._latched = False

    def _complete(self, result=None, err=None) -> None:
        with self._lock:
            self.completed_at = time.monotonic()
            if err is not None:
                self._err = err
            else:
                self._result = result
                if self._latch is not None and not self._quarantined:
                    cache, key_rows = self._latch
                    # valid verdicts only, mirroring the synchronous path:
                    # the shared cache never holds an invalid-sig verdict
                    # (flood cache-pollution defense)
                    cache.put_many(
                        (k, result[i]) for k, i in key_rows if result[i]
                    )
                    self._latched = True
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> List[bool]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"sig-flush future ({self.items} items) not done in {timeout}s"
            )
        with self._lock:
            if self._quarantined:
                raise RuntimeError("sig-flush future was quarantined")
            if self._err is not None:
                raise self._err
            return self._result

# Default device/host breakeven for the tpu backend, in cache-miss verifies:
# n/host_rate = rtt + n/device_rate at the MEASURED relay (68 ms RTT, 230k/s
# device, 16k/s host core) gives n ≈ 1,100.  Locally-attached TPU (sub-ms
# dispatch) breaks even near ~20 — retune HERE (Config.TPU_CPU_CUTOVER
# references this constant).
DEFAULT_TPU_CPU_CUTOVER = 1024


class SigBackend:
    name = "abstract"

    def verify_batch(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_CLOSE
    ) -> List[bool]:
        raise NotImplementedError

    def torsion_check(
        self,
        encs: Sequence[bytes],
        caller: str = CALLER_OVERLAY,
        vals: Optional[Sequence] = None,
    ) -> List[bool]:
        """Batched prime-order-subgroup proofs ([L]·P == identity) over
        compressed point encodings — the aggregate plane's fresh-R proof
        surface (ROADMAP #3 remainder (a)).  True iff the encoding is a
        canonical, decodable, torsion-free point.  The base
        implementation strict-decodes + proves on host
        (native/halfagg.c's ladder or the ref25519 oracle); the tpu
        backend overrides with the device batch plane, same
        cutover/wedge-latch contracts as verify_batch.  ``vals`` —
        optional decoded points parallel to ``encs`` (what the aggregate
        plane's _decompress_many already produced): the host path proves
        them directly instead of re-decoding the encodings."""
        from ..crypto.aggregate import halfagg

        if vals is not None:
            return halfagg.torsion_free_points(vals)
        return halfagg.torsion_free_encs(encs)

    def verify_batch_async(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_PIPELINE
    ) -> SigFlushFuture:
        """Dispatch the batch on a worker thread and return a future over
        it — the stage/drain split promoted to the backend surface, so a
        caller (ledger close, bench's deferred-flush leg) can overlap the
        verify with its own host work and join later.  Uncached backends
        just run verify_batch off-thread; CachingSigBackend adds the
        peek/latch split (and the quarantine contract) on top."""
        fut = SigFlushFuture(len(items))

        def work():
            try:
                fut._complete(result=self.verify_batch(items, caller=caller))
            except BaseException as e:  # re-raised at fut.result()
                fut._complete(err=e)

        threading.Thread(target=work, name="sig-flush", daemon=True).start()
        return fut

    def stats(self) -> dict:
        return {}


class CachingSigBackend(SigBackend):
    """Wraps an inner backend with the shared verify cache: cached results
    are served immediately, only misses reach the inner backend, and results
    scatter back into the cache."""

    def __init__(self, inner: SigBackend, cache: VerifySigCache, tracer=None):
        self.inner = inner
        self.cache = cache
        self.name = inner.name
        self._tracer = tracer if tracer is not None else NULL_TRACER

    def verify_batch(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_CLOSE
    ) -> List[bool]:
        # one sig-flush span per batch (never per item): batch size and the
        # cache-hit/miss split are THE attribution the close trace needs
        sp = self._tracer.begin("sig.flush")
        keys = [self.cache.key_for(pk, sig, msg) for pk, msg, sig in items]
        cached = self.cache.peek_many(keys)
        miss_idx = [i for i, c in enumerate(cached) if c is None]
        if miss_idx:
            fresh = self.inner.verify_batch(
                [items[i] for i in miss_idx], caller=caller
            )
            # latch VALID verdicts only: a byzantine flood of distinct
            # invalid-sig items must not be able to evict honest entries
            # from the bounded LRU (cache-pollution defense; re-verifying
            # an invalid item is cheap and pure, so nothing is lost) —
            # the chaos plane's flood scenarios pin this contract
            self.cache.put_many(
                (keys[i], ok) for i, ok in zip(miss_idx, fresh) if ok
            )
            for i, ok in zip(miss_idx, fresh):
                cached[i] = ok
        self._tracer.end(
            sp,
            batch=len(items),
            cache_hits=len(items) - len(miss_idx),
            misses=len(miss_idx),
            backend=self.name,
        )
        return [bool(c) for c in cached]

    def verify_batch_async(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_PIPELINE
    ) -> SigFlushFuture:
        """The async flush over the peek/verify/latch split, ENTIRELY on
        the worker: key hashing, the cache peek, the miss verify, and the
        at-completion scatter-back all run off the caller's thread — the
        dispatching close overlaps every pure-compute part of the flush
        with its own host work (the caller only pays the list snapshot +
        thread spawn).  The latch rides the future, so a quarantined
        (aborted-close) batch can never leave verdicts behind."""
        items = list(items)
        fut = SigFlushFuture(len(items))

        def work():
            sp = self._tracer.begin("sig.flush_async")
            try:
                keys = [
                    self.cache.key_for(pk, sig, msg) for pk, msg, sig in items
                ]
                cached = self.cache.peek_many(keys)
                miss_idx = [i for i, c in enumerate(cached) if c is None]
                self._tracer.end(
                    sp,
                    batch=len(items),
                    cache_hits=len(items) - len(miss_idx),
                    misses=len(miss_idx),
                    backend=self.name,
                )
                if not miss_idx:
                    fut._complete(result=[bool(c) for c in cached])
                    return
                # plain attribute store is atomic; _complete reads it
                # under fut._lock and skips the latch if a quarantine won
                # analysis: off locked-field -- happens-before by program order on the worker: _latch is written before the inner verify_batch, and _complete (same thread, after it) is the only reader path — there is no concurrent writer to exclude
                fut._latch = (self.cache, [(keys[i], i) for i in miss_idx])
                fresh = self.inner.verify_batch(
                    [items[i] for i in miss_idx], caller=caller
                )
                merged = list(cached)
                for i, ok in zip(miss_idx, fresh):
                    merged[i] = ok
                fut._complete(result=[bool(c) for c in merged])
            except BaseException as e:  # re-raised at fut.result()
                fut._complete(err=e)

        threading.Thread(target=work, name="sig-flush", daemon=True).start()
        return fut

    def torsion_check(
        self,
        encs: Sequence[bytes],
        caller: str = CALLER_OVERLAY,
        vals: Optional[Sequence] = None,
    ) -> List[bool]:
        # no verdict caching here: point-level memoization lives in the
        # aggregate plane's PointCache (keyed by encoding, where the
        # proof is intrinsic), not the signature verify cache
        return self.inner.torsion_check(encs, caller=caller, vals=vals)

    def stats(self) -> dict:
        return self.inner.stats()


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            _log.warning("ignoring malformed %s=%r; using %s", name, raw, default)
    return default


_pool = None
_pool_lock = threading.Lock()


def _sodium_verify_native(items: Sequence[VerifyTriple]) -> Optional[List[bool]]:
    """Fan a whole cache-miss batch over the native sighash worker pool:
    ONE GIL-released C call whose tiles invoke libsodium's
    crypto_sign_verify_detached through a function pointer (resolved from
    the SAME loaded library the serial path calls), so multi-core hosts
    parallelize the strict-verify leg with zero per-item Python dispatch
    — the Python ThreadPoolExecutor fallback below still serializes the
    per-chunk loop bookkeeping under the GIL.

    Returns None when the extension, libsodium, or the bytes-only item
    contract is unavailable; the caller falls back.  Verdicts are
    byte-identical to sodium.verify_detached (the C tile mirrors its
    length prechecks, then calls the same function)."""
    from ..native import load_sighash

    mod = load_sighash()
    if mod is None or not hasattr(mod, "sodium_verify"):
        return None
    try:
        fn = sodium.verify_fn_addr()
    except RuntimeError:
        return None
    ok = bytearray(len(items))
    try:
        mod.sodium_verify(fn, items, ok)
    except TypeError:
        # a non-bytes buffer slipped into the batch (the C side borrows
        # pointers across the GIL release, so it accepts bytes only) —
        # the Python loop handles such items fine
        return None
    return [bool(b) for b in ok]


def _sodium_verify_loop(items: Sequence[VerifyTriple]) -> List[bool]:
    """One libsodium verify per triple — the reference's exact behavior
    (crypto_sign_verify_detached, SecretKey.cpp:277-279).  Shared by the
    cpu backend and the tpu backend's small-batch cutover.

    Large batches fan out over the native sighash pthread pool when the
    extension built (one GIL-released C call, see _sodium_verify_native),
    else over a Python thread pool (the ctypes call releases the GIL, so
    it still scales, minus the per-chunk Python overhead).  Single-core
    hosts and small batches keep the plain serial loop — byte-identical
    to the reference, per the r09 satellite contract."""
    import os

    n = len(items)
    workers = min(8, os.cpu_count() or 1)
    if n < 256 or workers < 2:
        return [sodium.verify_detached(sig, msg, pk) for pk, msg, sig in items]
    native = _sodium_verify_native(items)
    if native is not None:
        return native
    global _pool
    if _pool is None:
        from concurrent.futures import ThreadPoolExecutor

        with _pool_lock:  # e.g. prewarm worker + main thread racing init
            if _pool is None:
                _pool = ThreadPoolExecutor(
                    max_workers=workers, thread_name_prefix="sodium-verify"
                )
    chunk = (n + workers - 1) // workers

    def run(lo):
        return [
            sodium.verify_detached(sig, msg, pk)
            for pk, msg, sig in items[lo : lo + chunk]
        ]

    parts = list(_pool.map(run, range(0, n, chunk)))
    return [ok for part in parts for ok in part]


class CpuSigBackend(SigBackend):
    name = "cpu"

    def verify_batch(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_CLOSE
    ) -> List[bool]:
        return _sodium_verify_loop(items)


class TpuSigBackend(SigBackend):
    """JAX batched ed25519 verify: strict canonicity/small-order prechecks and
    SHA-512 reduction on host, curve math (decompress + double-scalar-mult)
    on the accelerator.  Bit-exact with libsodium by construction + the
    differential test suite (tests/test_ed25519_tpu.py)."""

    name = "tpu"
    # class-level default: harness code (and tests) that build the backend
    # via __new__ + hand-set attributes still get a working no-op tracer
    _tracer = NULL_TRACER

    def __init__(
        self,
        max_batch: int = 4096,
        mesh=None,
        sig_mesh=0,
        cpu_cutover: int = DEFAULT_TPU_CPU_CUTOVER,
        streams: Optional[int] = None,
        native_hash: Optional[bool] = None,
        device_hash: Optional[bool] = None,
        tracer=None,
    ):
        from ..ops.ed25519 import BatchVerifier  # lazy: JAX import

        self._tracer = tracer if tracer is not None else NULL_TRACER
        # sig_mesh: the Config.SIG_MESH production wiring — 0/off,
        # "auto" (all addressable chips), or an explicit device count;
        # an explicit ``mesh=`` object (tests, the dryrun harness) wins.
        # Sharded dispatch rides the same BatchVerifier surface, so every
        # caller class (close flush, pipeline prewarms, overlay batches)
        # and the wedge-latch/quarantine contracts inherit it unchanged.
        if mesh is None and sig_mesh:
            from ..parallel.mesh import mesh_from_spec

            mesh = mesh_from_spec(sig_mesh)
        # native_hash: the C host stage (gate + batch SHA-512 mod L,
        # native/sighash.c) — default auto (on when it builds); stats()
        # reports which stage is live as "native_host_stage".
        # device_hash: the Config.DEVICE_HASH production wiring — the
        # SHA-512 stage runs ON DEVICE fused ahead of the verify kernel
        # (ops/sha512.py) and the host keeps only the strict gate; None
        # defers to the STELLAR_TPU_DEVICE_HASH env default (off).
        self._verifier = BatchVerifier(
            max_batch=max_batch,
            mesh=mesh,
            streams=streams,
            native_hash=native_hash,
            device_hash=device_hash,
            tracer=tracer,
        )
        # Below this many cache misses a device round-trip costs more than
        # looping libsodium on host — lone SCP envelopes and small tx sets
        # must never pay device latency just because the backend is "tpu"
        # (see DEFAULT_TPU_CPU_CUTOVER for the breakeven arithmetic).
        self.cpu_cutover = cpu_cutover
        self.n_cutover_items = 0
        self.n_cutover_torsion = 0
        self.n_wedge_fallback_items = 0
        # per-surface first-dispatch latches: verify and torsion compile
        # DIFFERENT executables (different bucket/branch), so each
        # surface keeps the long compile budget until ITS OWN first
        # device call has completed — a completed torsion dispatch must
        # not shrink the first verify dispatch's budget, or vice versa
        self._verify_warm = False
        self._torsion_warm = False
        # Host-fallback latch, scoped PER CALLER CLASS (ISSUE r10): a
        # stalled pipelined prewarm (caller="pipeline") latches only the
        # pipeline plane — the synchronous close-path batches
        # (caller="close") keep probing the device, and vice versa.  A
        # single shared latch silently routed every subsequent close flush
        # onto host for RETRY_INTERVAL after one stalled async prewarm.
        self._wedged_until: dict = {}  # analysis: locked-by _wedge_lock
        self.n_latch_flips: dict = {}
        # verify_batch is called concurrently (async signature prewarm
        # worker + the SCP crank); the latch read/write and the budget
        # choice go under one small lock so callers see consistent state
        self._wedge_lock = threading.Lock()

    # A wedged device dispatch (e.g. accelerator transport outage) must
    # never stall a caller indefinitely — SCP envelope flushes run on the
    # main crank and ledger close joins the prewarm; the reference's
    # inline libsodium path cannot hang, so neither may this one.  After
    # the timeout the batch finishes on host and the backend LATCHES onto
    # host for RETRY_INTERVAL (a persistently-dead transport costs at
    # most one bounded stall per interval, not one per batch).  The FIRST
    # dispatch gets a much longer budget: per-bucket XLA/remote compiles
    # legitimately take tens of seconds and must not false-latch a
    # healthy device (a false latch would self-heal after RETRY_INTERVAL,
    # but costs double work and misleading wedge telemetry).
    # Env-overridable: a loaded CI/test host can push the interpret-mode
    # compile past 90s, and a false latch there fails device-path tests
    # (tests/conftest.py raises the first-dispatch budget for exactly
    # that; production keeps the measured defaults).  A malformed value
    # falls back to the default — a typo'd budget must not kill the node
    # at import.
    DEVICE_TIMEOUT = _env_float("STELLAR_TPU_DISPATCH_BUDGET", 15.0)
    DEVICE_FIRST_TIMEOUT = _env_float("STELLAR_TPU_FIRST_DISPATCH_BUDGET", 90.0)
    RETRY_INTERVAL = 60.0

    def verify_batch(
        self, items: Sequence[VerifyTriple], caller: str = CALLER_CLOSE
    ) -> List[bool]:
        if len(items) < self.cpu_cutover:
            self.n_cutover_items += len(items)
            with self._tracer.span(
                "sig.host_verify", items=len(items), reason="cutover"
            ):
                return _sodium_verify_loop(items)
        # the lock covers only the latch read/write and the budget choice —
        # never the verify work itself, or every concurrent caller inherits
        # the slowest batch's host-verify latency
        with self._wedge_lock:
            wedged = time.monotonic() < self._wedged_until.get(caller, 0.0)
            # every caller keeps the long budget until the first VERIFY
            # device call has COMPLETED (not merely been dispatched): a
            # second caller arriving mid-compile rides the same XLA
            # compile and must not false-latch a healthy device with the
            # short budget.  Torsion dispatches do not count — they
            # compile a different executable (_torsion_warm below)
            first = not self._verify_warm
        if wedged:
            self.n_wedge_fallback_items += len(items)
            with self._tracer.span(
                "sig.host_verify",
                items=len(items),
                reason="wedge-latch",
                caller=caller,
            ):
                return _sodium_verify_loop(items)
        result: List[Any] = [None]
        err: List[BaseException] = []
        done = threading.Event()

        calls_before = self._verifier.n_device_calls

        def work():
            try:
                result[0] = self._verifier.verify(items)
                # warm on COMPLETION of a REAL device dispatch, even when
                # the caller's wait already timed out (orphaned worker):
                # the executable is compiled now, so later retries must
                # drop to the short budget.  An all-gate-rejected batch
                # never dispatches (n_device_calls unchanged) and must
                # NOT consume the first-dispatch compile budget
                if self._verifier.n_device_calls > calls_before:
                    self._verify_warm = True
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=work, name="tpu-verify", daemon=True)
        t.start()
        timeout = self.DEVICE_FIRST_TIMEOUT if first else self.DEVICE_TIMEOUT
        if not done.wait(timeout):
            with self._wedge_lock:
                # latch flips are metered per caller class so telemetry
                # (stats() → /info) shows WHICH plane is riding host
                self._wedged_until[caller] = (
                    time.monotonic() + self.RETRY_INTERVAL
                )
                self.n_latch_flips[caller] = (
                    self.n_latch_flips.get(caller, 0) + 1
                )
            self.n_wedge_fallback_items += len(items)
            _log.warning(
                "device verify batch stalled >%.0fs; finishing %d verifies"
                " on host and latching the %r caller class onto host for"
                " %.0fs",
                timeout,
                len(items),
                caller,
                self.RETRY_INTERVAL,
            )
            # the orphaned worker's eventual completion is harmless: the
            # caller-side cache scatter-back writes identical values
            with self._tracer.span(
                "sig.host_verify",
                items=len(items),
                reason="device-stall",
                caller=caller,
            ):
                return _sodium_verify_loop(items)
        if err:
            raise err[0]
        return result[0]

    def torsion_check(
        self,
        encs: Sequence[bytes],
        caller: str = CALLER_OVERLAY,
        vals: Optional[Sequence] = None,
    ) -> List[bool]:
        """Prime-order proofs on the device batch plane: the verify
        kernel computes [L]·P == identity AS-IS via verify(A := P,
        h := L, s := 0, R := identity-encoding) — no hash stage at all
        (BatchVerifier.verify_torsion).  Same cutover arithmetic and
        per-caller wedge latch as verify_batch: small batches (and a
        wedged/stalled device) ride the host ladder — with the caller's
        already-decoded ``vals`` when provided, so no second decompress
        pass — and the aggregate plane can never hang on a dead
        transport."""
        if len(encs) < self.cpu_cutover:
            self.n_cutover_torsion += len(encs)
            with self._tracer.span(
                "sig.host_torsion", items=len(encs), reason="cutover"
            ):
                return SigBackend.torsion_check(
                    self, encs, caller=caller, vals=vals
                )
        with self._wedge_lock:
            wedged = time.monotonic() < self._wedged_until.get(caller, 0.0)
            # the torsion chunk compiles its OWN executable (different
            # bucket/branch than verify), so the first TORSION dispatch
            # gets the first-dispatch compile budget even when verify
            # has already run — and symmetrically (see _verify_warm)
            first = not self._torsion_warm
        if wedged:
            self.n_wedge_fallback_items += len(encs)
            with self._tracer.span(
                "sig.host_torsion",
                items=len(encs),
                reason="wedge-latch",
                caller=caller,
            ):
                return SigBackend.torsion_check(
                    self, encs, caller=caller, vals=vals
                )
        result: List[Any] = [None]
        err: List[BaseException] = []
        done = threading.Event()

        calls_before = self._verifier.n_device_calls

        def work():
            try:
                result[0] = self._verifier.verify_torsion(encs)
                # warm only on a real completed dispatch — see
                # _verify_warm (an all-undecodable batch never compiles)
                if self._verifier.n_device_calls > calls_before:
                    self._torsion_warm = True
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=work, name="tpu-torsion", daemon=True)
        t.start()
        timeout = self.DEVICE_FIRST_TIMEOUT if first else self.DEVICE_TIMEOUT
        if not done.wait(timeout):
            with self._wedge_lock:
                self._wedged_until[caller] = (
                    time.monotonic() + self.RETRY_INTERVAL
                )
                self.n_latch_flips[caller] = (
                    self.n_latch_flips.get(caller, 0) + 1
                )
            self.n_wedge_fallback_items += len(encs)
            _log.warning(
                "device torsion batch stalled >%.0fs; finishing %d proofs"
                " on host and latching the %r caller class onto host for"
                " %.0fs",
                timeout,
                len(encs),
                caller,
                self.RETRY_INTERVAL,
            )
            with self._tracer.span(
                "sig.host_torsion",
                items=len(encs),
                reason="device-stall",
                caller=caller,
            ):
                return SigBackend.torsion_check(
                    self, encs, caller=caller, vals=vals
                )
        if err:
            raise err[0]
        return result[0]

    def stats(self) -> dict:
        s = self._verifier.stats()
        s["cpu_cutover_items"] = self.n_cutover_items
        s["cpu_cutover_torsion"] = self.n_cutover_torsion
        s["wedge_fallback_items"] = self.n_wedge_fallback_items
        s["wedge_latch_flips"] = dict(self.n_latch_flips)
        return s


def make_backend(
    kind: str = "cpu",
    cache: VerifySigCache = None,
    tracer=None,
    **kw,
) -> SigBackend:
    if kind == "cpu":
        inner: SigBackend = CpuSigBackend()
    elif kind == "tpu":
        inner = TpuSigBackend(tracer=tracer, **kw)
    else:
        raise ValueError(f"unknown SIGNATURE_BACKEND {kind!r}")
    if cache is None:
        from .keys import verify_cache

        cache = verify_cache()
    return CachingSigBackend(inner, cache, tracer=tracer)
