"""ctypes bindings to the system libsodium — the CPU ground truth.

The reference links libsodium statically (lib/libsodium submodule); we bind
the shared library.  ``crypto_sign_verify_detached`` here is the bit-exactness
oracle the TPU backend (stellar_tpu/ops) must agree with on every input.
"""

from __future__ import annotations

import ctypes
import ctypes.util
from typing import Optional

_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    name = ctypes.util.find_library("sodium")
    for cand in ([name] if name else []) + [
        "libsodium.so.23",
        "libsodium.so",
        "libsodium.dylib",
    ]:
        try:
            lib = ctypes.CDLL(cand)
        except OSError:
            continue
        if lib.sodium_init() < 0:
            raise RuntimeError("sodium_init failed")
        _lib = lib
        return lib
    raise RuntimeError("libsodium not found")


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def sign_seed_keypair(seed: bytes) -> tuple:
    """(public_key_32, secret_key_64) from a 32-byte seed."""
    lib = _load()
    pk = ctypes.create_string_buffer(32)
    sk = ctypes.create_string_buffer(64)
    if lib.crypto_sign_seed_keypair(pk, sk, seed) != 0:
        raise RuntimeError("crypto_sign_seed_keypair failed")
    return pk.raw, sk.raw


def sign_detached(msg: bytes, secret_key64: bytes) -> bytes:
    lib = _load()
    sig = ctypes.create_string_buffer(64)
    siglen = ctypes.c_ulonglong(0)
    if (
        lib.crypto_sign_detached(
            sig, ctypes.byref(siglen), msg, ctypes.c_ulonglong(len(msg)), secret_key64
        )
        != 0
    ):
        raise RuntimeError("crypto_sign_detached failed")
    return sig.raw


def verify_detached(sig: bytes, msg: bytes, public_key32: bytes) -> bool:
    if len(sig) != 64 or len(public_key32) != 32:
        return False
    lib = _load()
    return (
        lib.crypto_sign_verify_detached(
            sig, msg, ctypes.c_ulonglong(len(msg)), public_key32
        )
        == 0
    )


def verify_fn_addr() -> int:
    """Address of ``crypto_sign_verify_detached`` in the loaded libsodium
    — handed to the native sighash worker pool so its C tiles can call
    libsodium directly with the GIL released (one verifier, two drivers:
    crypto/sigbackend routes large pure-CPU batches through the pool and
    keeps this module's serial loop for small batches / 1-core hosts)."""
    lib = _load()
    addr = ctypes.cast(lib.crypto_sign_verify_detached, ctypes.c_void_p).value
    if not addr:
        raise RuntimeError("crypto_sign_verify_detached unresolved")
    return addr


def randombytes(n: int) -> bytes:
    lib = _load()
    buf = ctypes.create_string_buffer(n)
    lib.randombytes_buf(buf, ctypes.c_size_t(n))
    return buf.raw


def scalarmult_base(secret32: bytes) -> bytes:
    lib = _load()
    out = ctypes.create_string_buffer(32)
    if lib.crypto_scalarmult_base(out, secret32) != 0:
        raise RuntimeError("crypto_scalarmult_base failed")
    return out.raw


def scalarmult(secret32: bytes, public32: bytes) -> bytes:
    lib = _load()
    out = ctypes.create_string_buffer(32)
    if lib.crypto_scalarmult(out, secret32, public32) != 0:
        raise RuntimeError("crypto_scalarmult failed (weak public key)")
    return out.raw
