"""Curve25519 ECDH for peer session keys (reference: src/crypto/ECDH.cpp).

Never reuses ed25519 identity keys — ephemeral curve25519 only (ECDH.h:13-24).
Shared key = hkdf_extract( scalarmult(local_sec, remote_pub) ‖ pubA ‖ pubB )
where (pubA, pubB) is (local, remote) ordered by who called first.
"""

from __future__ import annotations

from . import sodium
from .sha import hkdf_extract


def ecdh_random_secret() -> bytes:
    return sodium.randombytes(32)


def ecdh_derive_public(secret: bytes) -> bytes:
    return sodium.scalarmult_base(secret)


def ecdh_derive_shared_key(
    local_secret: bytes,
    local_public: bytes,
    remote_public: bytes,
    local_first: bool,
) -> bytes:
    public_a = local_public if local_first else remote_public
    public_b = remote_public if local_first else local_public
    q = sodium.scalarmult(local_secret, remote_public)
    return hkdf_extract(q + public_a + public_b)
