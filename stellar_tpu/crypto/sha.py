"""SHA-256 / HMAC-SHA256 / HKDF (reference: src/crypto/SHA.{h,cpp}).

The reference wraps libsodium; hashlib/hmac are the host-side equivalents and
produce identical bytes.  The HKDF here is the reference's two single-step
helpers (SHA.cpp:105-135), NOT full RFC 5869:

- ``hkdf_extract(bytes)``  == HMAC(zero_key, bytes)
- ``hkdf_expand(key, bytes)`` == HMAC(key, bytes || 0x01)
"""

from __future__ import annotations

import hashlib
import hmac as _hmac

ZERO_KEY = b"\x00" * 32


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


class SHA256:
    """Incremental SHA-256 (reference SHA256::create/add/finish)."""

    def __init__(self):
        self._h = hashlib.sha256()
        self._finished = False

    def reset(self) -> None:
        self._h = hashlib.sha256()
        self._finished = False

    def add(self, data: bytes) -> None:
        if self._finished:
            raise RuntimeError("adding bytes to finished SHA256")
        self._h.update(data)

    def finish(self) -> bytes:
        if self._finished:
            raise RuntimeError("finishing already-finished SHA256")
        self._finished = True
        return self._h.digest()


def hmac_sha256(key: bytes, data: bytes) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()


def hmac_sha256_verify(mac: bytes, key: bytes, data: bytes) -> bool:
    return _hmac.compare_digest(mac, hmac_sha256(key, data))


def hkdf_extract(data: bytes) -> bytes:
    """Unsalted HKDF-extract == HMAC(<zero>, data) (SHA.cpp:107-115)."""
    return hmac_sha256(ZERO_KEY, data)


def hkdf_expand(key: bytes, data: bytes) -> bytes:
    """Single-step HKDF-expand == HMAC(key, data|0x01) (SHA.cpp:117-128)."""
    return hmac_sha256(key, data + b"\x01")
