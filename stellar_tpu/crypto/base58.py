"""Base58 / base58-check encodings (reference: src/crypto/Base58.{h,cpp}).

Deprecated in-reference in favor of strkey (crypto/strkey.py carries the
live identity encodings) but kept for strict capability parity: both the
bitcoin alphabet and the shuffled "stellar" alphabet, plus the
version-byte + double-SHA256-checksum check encoding.  Python ints
replace the reference's digit-vector bignum loops; identical outputs
(reference test vectors in tests/test_crypto.py).
"""

from __future__ import annotations

from typing import Tuple

from .sha import sha256

BITCOIN_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
STELLAR_ALPHABET = "gsphnaf39wBUDNEGHJKLM4PQRST7VWXYZ2bcdeCr65jkm8oFqi1tuvAxyz"

# version bytes (reference Base58.h Base58CheckVersionByte)
VER_ACCOUNT_ID = 0  # 'g' in the stellar alphabet
VER_SEED = 33  # 's'


def base_encode(data: bytes, alphabet: str = BITCOIN_ALPHABET) -> str:
    if not data:
        return ""
    n = int.from_bytes(data, "big")
    digits = []
    while n > 0:
        n, r = divmod(n, 58)
        digits.append(alphabet[r])
    if not digits:  # value part is at least one zero digit
        digits.append(alphabet[0])
    # preserve leading zero bytes as leading zero-digits (all but the last
    # byte, mirroring the reference's append-leading-zeroes loop)
    pad = 0
    for b in data[: len(data) - 1]:
        if b != 0:
            break
        pad += 1
    return alphabet[0] * pad + "".join(reversed(digits))


def base_decode(encoded: str, alphabet: str = BITCOIN_ALPHABET) -> bytes:
    if not encoded:
        return b""
    n = 0
    for c in encoded:
        idx = alphabet.find(c)
        if idx < 0:
            raise ValueError(f"unknown character {c!r} in base58 decode")
        n = n * 58 + idx
    out = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b"\x00"
    # restore leading zeros (all but the last character)
    pad = 0
    for c in encoded[: len(encoded) - 1]:
        if c != alphabet[0]:
            break
        pad += 1
    # n == 0 already produced one zero byte
    if n == 0:
        return b"\x00" * (pad + 1)
    return b"\x00" * pad + out


def base_check_encode(
    ver: int, data: bytes, alphabet: str = STELLAR_ALPHABET
) -> str:
    vb = bytes([ver]) + data
    checksum = sha256(sha256(vb))[:4]
    return base_encode(vb + checksum, alphabet)


def base_check_decode(
    encoded: str, alphabet: str = STELLAR_ALPHABET
) -> Tuple[int, bytes]:
    raw = base_decode(encoded, alphabet)
    if len(raw) < 5:
        raise ValueError("base58-check decoded to <5 bytes")
    body, checksum = raw[:-4], raw[-4:]
    if sha256(sha256(body))[:4] != checksum:
        raise ValueError("base58-check checksum failed")
    return body[0], body[1:]
