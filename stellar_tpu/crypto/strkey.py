"""StrKey: base32 + CRC16-XModem key encoding (reference: src/crypto/StrKey.*,
lib/util/crc16.cpp, lib/util/basen.h).

Format: base32( version_byte<<3 ‖ payload ‖ crc16_le ).  32-byte payloads
encode to exactly 56 chars with no padding ('G...' pubkeys, 'S...' seeds).
"""

from __future__ import annotations

import base64
from functools import lru_cache
from typing import Tuple

# 5-bit version bytes (StrKey.h:18-20)
STRKEY_PUBKEY_ED25519 = 6  # 'G'
STRKEY_SEED_ED25519 = 18  # 'S'


def _crc16_table() -> list:
    tab = []
    for hi in range(256):
        crc = hi << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
        tab.append(crc & 0xFFFF)
    return tab


_CRC16_TAB = _crc16_table()


def crc16(data: bytes) -> int:
    """CRC16-CCITT XModem: poly 0x1021, init 0 (lib/util/crc16.cpp);
    byte-wise table lookup (the bit-loop was the hottest non-SQL function
    in the ledger-close profile — strkeys are SQL row keys)."""
    crc = 0
    tab = _CRC16_TAB
    for b in data:
        crc = ((crc << 8) & 0xFFFF) ^ tab[(crc >> 8) ^ b]
    return crc


def to_strkey(version: int, payload: bytes) -> str:
    raw = bytes([(version << 3) & 0xFF]) + payload
    c = crc16(raw)
    raw += bytes([c & 0xFF, (c >> 8) & 0xFF])
    return base64.b32encode(raw).decode("ascii").rstrip("=")


def from_strkey(s: str) -> Tuple[int, bytes]:
    """Returns (version, payload); raises ValueError on any corruption."""
    pad = (-len(s)) % 8
    try:
        raw = base64.b32decode(s + "=" * pad)
    except Exception as e:
        raise ValueError(f"bad base32: {e}") from e
    if len(raw) < 3:
        raise ValueError("strkey too short")
    body, crc_lo, crc_hi = raw[:-2], raw[-2], raw[-1]
    if crc16(body) != (crc_hi << 8 | crc_lo):
        raise ValueError("strkey checksum mismatch")
    return body[0] >> 3, body[1:]


# Only the ACCOUNT paths are cached: they are the ledger's SQL row keys
# (hot in the close path), and caching the generic functions would retain
# secret 'S...' seeds in a long-lived global dict.
@lru_cache(maxsize=65536)
def to_account_strkey(pubkey: bytes) -> str:
    return to_strkey(STRKEY_PUBKEY_ED25519, pubkey)


@lru_cache(maxsize=65536)
def from_account_strkey(s: str) -> bytes:
    ver, payload = from_strkey(s)
    if ver != STRKEY_PUBKEY_ED25519 or len(payload) != 32:
        raise ValueError("not an ed25519 account strkey")
    return payload


def to_seed_strkey(seed: bytes) -> str:
    return to_strkey(STRKEY_SEED_ED25519, seed)


def from_seed_strkey(s: str) -> bytes:
    ver, payload = from_strkey(s)
    if ver != STRKEY_SEED_ED25519 or len(payload) != 32:
        raise ValueError("not an ed25519 seed strkey")
    return payload


def hex_encode(data: bytes) -> str:
    return data.hex()


def hex_decode(s: str) -> bytes:
    return bytes.fromhex(s)
