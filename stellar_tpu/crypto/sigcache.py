"""Global ed25519 verification cache (reference: src/crypto/SecretKey.cpp:29-52).

Pure-function memoization: key = SHA256(pubkey ‖ sig ‖ msg) → bool.  The
reference guards a 65,535-entry LRU with a mutex; we do the same (the lock
also covers the TPU backend's batch scatter-back, which may run off-thread).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Tuple


class VerifySigCache:
    def __init__(self, capacity: int = 0xFFFF):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._map: OrderedDict[bytes, bool] = OrderedDict()  # analysis: locked-by _lock
        self._hits = 0
        self._misses = 0

    @staticmethod
    def key_for(pubkey_raw: bytes, signature: bytes, msg: bytes) -> bytes:
        h = hashlib.sha256()
        h.update(pubkey_raw)
        h.update(signature)
        h.update(msg)
        return h.digest()

    def get(self, key: bytes) -> Tuple[bool, bool]:
        """Returns (hit, value)."""
        with self._lock:
            if key in self._map:
                self._map.move_to_end(key)
                self._hits += 1
                return True, self._map[key]
            self._misses += 1
            return False, False

    def peek_many(self, keys) -> list:
        """Batch lookup WITHOUT counting misses (used by the batch verifier
        to split a batch into cached/uncached without double-counting)."""
        out = []
        with self._lock:
            for k in keys:
                if k in self._map:
                    self._map.move_to_end(k)
                    self._hits += 1
                    out.append(self._map[k])
                else:
                    out.append(None)
        return out

    def put(self, key: bytes, value: bool) -> None:
        with self._lock:
            self._map[key] = value
            self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def put_many(self, pairs) -> None:
        with self._lock:
            for key, value in pairs:
                self._map[key] = value
                self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def drop_many(self, keys) -> None:
        """Evict entries (quarantine path: verdicts latched by an async
        flush whose close was aborted are withdrawn — see
        SigFlushFuture.quarantine)."""
        with self._lock:
            for k in keys:
                self._map.pop(k, None)

    def flush_counts(self) -> Tuple[int, int]:
        with self._lock:
            h, m = self._hits, self._misses
            self._hits = self._misses = 0
            return h, m

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def __len__(self):
        with self._lock:
            return len(self._map)
