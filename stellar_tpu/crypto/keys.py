"""Key management: SecretKey / PubKeyUtils (reference: src/crypto/SecretKey.*).

Signing and eager verification go through libsodium (ctypes, see sodium.py);
verification results are memoized in the global LRU cache exactly like the
reference's gVerifySigCache (SecretKey.cpp:29-52): 65,535 entries keyed
SHA256(pubkey ‖ sig ‖ msg), with hit/miss counters surfaced to metrics.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from ..xdr.xtypes import PublicKey
from . import sodium, strkey
from .sha import sha256
from .sigcache import VerifySigCache

# process-wide verify cache (reference SecretKey.cpp:30: lru_cache(0xffff))
_verify_cache = VerifySigCache(0xFFFF)


class SecretKey:
    """Ed25519 secret key wrapping a libsodium (seed, sk64) pair."""

    __slots__ = ("_seed", "_sk64", "_pk_raw", "_pk")

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self._seed = bytes(seed)
        self._pk_raw, self._sk64 = sodium.sign_seed_keypair(self._seed)
        self._pk = PublicKey.from_ed25519(self._pk_raw)

    # -- constructors ------------------------------------------------------
    @classmethod
    def random(cls) -> "SecretKey":
        return cls(sodium.randombytes(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "SecretKey":
        return cls(seed)

    @classmethod
    def from_strkey_seed(cls, s: str) -> "SecretKey":
        return cls(strkey.from_seed_strkey(s))

    @classmethod
    def pseudo_random_for_testing(cls, n: int) -> "SecretKey":
        """Deterministic per-index test key (the reference's getTestAccount
        style: derived, reproducible, NOT secure)."""
        return cls(sha256(b"stellar_tpu test seed %d" % n))

    # -- accessors ---------------------------------------------------------
    def get_public_key(self) -> PublicKey:
        return self._pk

    @property
    def public_raw(self) -> bytes:
        return self._pk_raw

    def get_seed(self) -> bytes:
        return self._seed

    def get_strkey_seed(self) -> str:
        return strkey.to_seed_strkey(self._seed)

    def get_strkey_public(self) -> str:
        return strkey.to_account_strkey(self._pk_raw)

    # -- operations --------------------------------------------------------
    def sign(self, msg: bytes) -> bytes:
        return sodium.sign_detached(msg, self._sk64)

    def __repr__(self):
        return f"SecretKey({self.get_strkey_public()[:8]}…)"


class PubKeyUtils:
    """Static helpers mirroring the reference's PubKeyUtils."""

    @staticmethod
    def verify_sig(key: PublicKey, signature: bytes, msg: bytes) -> bool:
        """Cached eager verify (SecretKey.cpp:254-286)."""
        cache_key = _verify_cache.key_for(key.value, signature, msg)
        hit, val = _verify_cache.get(cache_key)
        if hit:
            return val
        ok = sodium.verify_detached(signature, msg, key.value)
        # valid verdicts only: the bounded LRU must be un-pollutable by a
        # flood of distinct invalid-sig items (same contract as the batch
        # paths in sigbackend.py; re-verifying an invalid item is pure)
        if ok:
            # analysis: off cache-latch -- synchronous single-verify memoization on the caller's own thread (the reference's SecretKey.cpp eager path): the verdict was just computed against live state, there is no async batch to quarantine
            _verify_cache.put(cache_key, ok)
        return ok

    @staticmethod
    def verify_sig_uncached(key_raw: bytes, signature: bytes, msg: bytes) -> bool:
        return sodium.verify_detached(signature, msg, key_raw)

    @staticmethod
    def get_hint(pk: PublicKey) -> bytes:
        """Last 4 bytes of the public key (SecretKey.cpp:333-338)."""
        return pk.value[-4:]

    @staticmethod
    def has_hint(pk: PublicKey, hint: bytes) -> bool:
        return pk.value[-4:] == hint

    @staticmethod
    def to_short_string(pk: PublicKey) -> str:
        return strkey.to_account_strkey(pk.value)[:8]

    @staticmethod
    def to_strkey(pk: PublicKey) -> str:
        return strkey.to_account_strkey(pk.value)

    @staticmethod
    def from_strkey(s: str) -> PublicKey:
        return PublicKey.from_ed25519(strkey.from_account_strkey(s))

    @staticmethod
    def random() -> PublicKey:
        return PublicKey.from_ed25519(sodium.randombytes(32))

    # cache introspection (SecretKey.cpp:241-252)
    @staticmethod
    def flush_verify_sig_cache_counts() -> Tuple[int, int]:
        return _verify_cache.flush_counts()

    @staticmethod
    def clear_verify_sig_cache() -> None:
        _verify_cache.clear()


def verify_cache() -> VerifySigCache:
    return _verify_cache
