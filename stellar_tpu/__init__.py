"""stellar_tpu — a TPU-native validator framework with stellar-core's capabilities.

Layer map (mirrors SURVEY.md §1; each subpackage documents its reference
counterpart):

- ``xdr``        wire protocol (xdrpp/xdrc equivalent, byte-exact)
- ``crypto``     hashing, keys, strkey, SigBackend (incl. TPU batch verify)
- ``ops``        JAX/Pallas kernels: ed25519 field/curve math on TPU
- ``parallel``   device-mesh sharding of the crypto data plane
- ``util``       VirtualClock event loop, metrics, logging, streams
- ``database``   SQL hot state (sqlite)
- ``ledger``     ledger state machine (frames, delta, manager)
- ``tx``         transactions + 10 operation types + order book
- ``scp``        Stellar Consensus Protocol library
- ``herder``     consensus glue (txsets, pending envelopes)
- ``overlay``    authenticated P2P flood mesh
- ``bucket``     log-structured 11-level bucket list
- ``history``    checkpoint publish/catchup state machines
- ``process``    async subprocess management
- ``main``       Application composition root, config, CLI, admin HTTP
- ``simulation`` in-process multi-node simulation + load generation
"""

__version__ = "0.1.0"
