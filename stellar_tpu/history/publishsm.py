"""Publish state machine — snapshot → gzip → observe → send → commit
(reference: src/history/PublishStateMachine.{h,cpp}).

One PublishRun handles one queued checkpoint against every writable archive:

1. SNAPSHOT: write the checkpoint's ledger/transactions/results XDR files
   from SQL into a staging tmp dir; stage the bucket files the archive
   state references.
2. COMPRESS: gzip every staged file via subprocesses.
3. OBSERVE (per archive): fetch the archive's current ``.well-known`` state
   to learn which buckets it already has.
4. SEND (per archive): mkdir + put the missing files.
5. COMMIT (per archive): put the per-checkpoint state file and the new
   ``.well-known`` root state.

Everything is subprocess-driven through ProcessManager, completions posted
back to the main crank; the queue row (crash-safe, written inside the
ledger-close transaction) is removed only after every archive commits.
"""

from __future__ import annotations

import os
import shutil
from typing import Callable, List, Optional

from ..util import fs, xlog
from ..xdr.ledger import (
    LedgerHeaderHistoryEntry,
    TransactionHistoryEntry,
    TransactionHistoryResultEntry,
    TransactionResultSet,
)
from ..util.xdrstream import XDROutputFileStream
from .archive import WELL_KNOWN_PATH, HistoryArchive, HistoryArchiveState
from .filetransfer import (
    CAT_LEDGER,
    CAT_RESULTS,
    CAT_TRANSACTIONS,
    FileTransferInfo,
)

log = xlog.logger("History")

# publish staging kill-points: everything under the publish tmp dir is
# reconstructible (the queue row survives in SQL), so a kill anywhere
# here must repair to "staging reaped at boot, checkpoint republished"
KP_SNAPSHOT = {
    cat: fs.register_durable_site(
        f"publish.snapshot.{cat}", stages=(fs.STAGE_WRITE, fs.STAGE_STAGED),
        doc=f"checkpoint {cat} XDR stream staged for publish",
    )
    for cat in (CAT_LEDGER, CAT_TRANSACTIONS, CAT_RESULTS)
}
KP_STAGE_BUCKET = fs.register_kill_point(
    "publish.stage-bucket", "bucket hard-linked/copied into publish staging"
)
KP_COMMIT_JSON = fs.register_durable_site(
    "publish.commit-json",
    doc="per-archive checkpoint state JSON written for commit",
)


def write_checkpoint_snapshot(app, checkpoint_ledger: int, out_dir: str) -> List[FileTransferInfo]:
    """Write ledger/transactions/results files for the checkpoint range
    (ArchivePublisher::writeNextSnapshot).  Range = (prev checkpoint, this
    checkpoint], clamped to genesis."""
    from ..ledger.headerframe import LedgerHeaderFrame
    from ..tx import history as tx_history

    freq = app.config.CHECKPOINT_FREQUENCY
    first = max(1, (checkpoint_ledger + 1) - freq)

    files = []
    f_ledger = FileTransferInfo.for_checkpoint(out_dir, CAT_LEDGER, checkpoint_ledger)
    f_txs = FileTransferInfo.for_checkpoint(
        out_dir, CAT_TRANSACTIONS, checkpoint_ledger
    )
    f_results = FileTransferInfo.for_checkpoint(out_dir, CAT_RESULTS, checkpoint_ledger)

    db = app.database
    with XDROutputFileStream(
        f_ledger.local_path, durable=True,
        point=KP_SNAPSHOT[CAT_LEDGER], ctx=db,
    ) as lo, XDROutputFileStream(
        f_txs.local_path, durable=True,
        point=KP_SNAPSHOT[CAT_TRANSACTIONS], ctx=db,
    ) as to, XDROutputFileStream(
        f_results.local_path, durable=True,
        point=KP_SNAPSHOT[CAT_RESULTS], ctx=db,
    ) as ro:
        for frame in LedgerHeaderFrame.load_range(
            app.database, first, checkpoint_ledger
        ):
            lo.write_one(
                LedgerHeaderHistoryEntry(frame.get_hash(), frame.header, 0)
            )
            seq = frame.header.ledgerSeq
            rows = tx_history.load_transaction_history(app.database, seq)
            if not rows:
                continue
            # canonical (sorted-by-hash) txset rebuilt from apply-order rows
            from ..herder.txset import TxSetFrame
            from ..tx.frame import TransactionFrame

            prev = LedgerHeaderFrame.load_by_sequence(app.database, seq - 1)
            prev_hash = prev.get_hash() if prev else b"\x00" * 32
            ts = TxSetFrame(prev_hash)
            for env, _res in rows:
                ts.add_transaction(
                    TransactionFrame.make_from_wire(app.network_id, env)
                )
            to.write_one(TransactionHistoryEntry(seq, ts.to_xdr(), 0))
            ro.write_one(
                TransactionHistoryResultEntry(
                    seq, TransactionResultSet([r for _, r in rows]), 0
                )
            )
    files.extend([f_ledger, f_txs, f_results])
    return files


def stage_bucket_files(app, has: HistoryArchiveState, out_dir: str) -> List[FileTransferInfo]:
    """Hard-link/copy every referenced bucket into the staging dir."""
    files = []
    seen = set()  # all_bucket_hashes() repeats hashes shared across levels
    for h in has.all_bucket_hashes():
        if h in seen:
            continue
        seen.add(h)
        fi = FileTransferInfo.for_bucket(out_dir, h)
        src = app.bucket_manager.get_bucket_by_hash(h).path
        if not os.path.exists(fi.local_path):
            try:
                os.link(src, fi.local_path)
            except OSError:
                shutil.copyfile(src, fi.local_path)
            fs.kill_point(
                KP_STAGE_BUCKET, path=fi.local_path, ctx=app.database
            )
        files.append(fi)
    return files


class PublishRun:
    """Publish ONE checkpoint to ALL writable archives, then call done(ok)."""

    def __init__(self, app, checkpoint_ledger: int, state_json: str, done: Callable):
        self.app = app
        self.seq = checkpoint_ledger
        self.has = HistoryArchiveState.from_json(state_json)
        self.state_json = state_json
        self.done = done
        self.archives = [
            HistoryArchive(name, spec)
            for name, spec in app.config.HISTORY.items()
            if spec.get("put")
        ]
        self.tmp = app.tmp_dirs.tmp_dir(f"publish-{checkpoint_ledger}")
        self.files: List[FileTransferInfo] = []
        self._failed = False

    # phase 1+2: snapshot + compress everything once.  The SQL→XDR pass
    # runs on the main crank because the sqlite session is single-threaded
    # (an in-memory DB has no second connection); it covers only one
    # checkpoint range.  The heavy work — bucket staging (hard links) and
    # compression/transfer (subprocesses) — never blocks the crank.
    def start(self) -> None:
        try:
            self.files = write_checkpoint_snapshot(
                self.app, self.seq, self.tmp.get_name()
            )
            self.files += stage_bucket_files(self.app, self.has, self.tmp.get_name())
        except Exception as e:
            log.error("publish %d: snapshot failed: %s", self.seq, e)
            self._finish(False)
            return
        pending = len(self.files)
        if pending == 0:
            self._observe_archives()
            return
        results = {"left": pending, "ok": True}

        def one_done(fi, rc):
            results["left"] -= 1
            if rc != 0:
                log.error("publish %d: gzip failed for %s", self.seq, fi.base_name)
                results["ok"] = False
            if results["left"] == 0:
                if results["ok"]:
                    self._observe_archives()
                else:
                    self._finish(False)

        for fi in self.files:
            self.app.process_manager.run_process(
                f"gzip -c '{fi.local_path}' > '{fi.local_path_gz}'",
                lambda rc, fi=fi: one_done(fi, rc),
            )

    # phase 3..5 per archive, run in parallel across archives
    def _observe_archives(self) -> None:
        if not self.archives:
            self._finish(True)
            return
        counter = {"left": len(self.archives), "ok": True}

        def archive_done(ok):
            counter["left"] -= 1
            counter["ok"] = counter["ok"] and ok
            if counter["left"] == 0:
                self._finish(counter["ok"])

        for ar in self.archives:
            _ArchivePublisher(self, ar, archive_done).start()

    def _finish(self, ok: bool) -> None:
        self.app.tmp_dirs.forget(self.tmp)
        self.done(ok)


class _ArchivePublisher:
    """Phases observe→send→commit against one archive
    (reference ArchivePublisher, PublishStateMachine.h:34-99)."""

    def __init__(self, run: PublishRun, archive: HistoryArchive, done: Callable):
        self.run = run
        self.app = run.app
        self.archive = archive
        self.done = done
        self.remote_state: Optional[HistoryArchiveState] = None

    def start(self) -> None:
        local = os.path.join(
            self.run.tmp.get_name(), f"remote-was-{self.archive.name}.json"
        )
        if not self.archive.has_get():
            self.remote_state = HistoryArchiveState(0)
            self._send()
            return

        def got(rc):
            self.remote_state = HistoryArchiveState(0)
            if rc == 0:
                try:
                    with open(local) as f:
                        self.remote_state = HistoryArchiveState.from_json(f.read())
                except Exception as e:
                    log.info(
                        "archive %s: unreadable remote state (%s); sending all",
                        self.archive.name,
                        e,
                    )
            self._send()

        self.app.process_manager.run_process(
            self.archive.get_file_cmd(WELL_KNOWN_PATH, local), got
        )

    def _send(self) -> None:
        need_hashes = set(
            h.hex() for h in self.run.has.differing_buckets(self.remote_state)
        )
        to_send = [
            fi
            for fi in self.run.files
            if fi.category != "bucket" or fi.base_name[7:-4] in need_hashes
        ]
        counter = {"left": len(to_send), "ok": True}
        if not to_send:
            self._commit()
            return

        def one_done(fi, rc):
            counter["left"] -= 1
            if rc != 0:
                log.error(
                    "archive %s: put failed for %s", self.archive.name, fi.base_name
                )
                counter["ok"] = False
            if counter["left"] == 0:
                if counter["ok"]:
                    self._commit()
                else:
                    self.done(False)

        for fi in to_send:
            self._put(fi.local_path_gz, fi.remote_name, lambda rc, fi=fi: one_done(fi, rc))

    def _put(self, local: str, remote: str, cb) -> None:
        def after_mkdir(_rc):
            self.app.process_manager.run_process(
                self.archive.put_file_cmd(local, remote), cb
            )

        rdir = os.path.dirname(remote)
        if self.archive.has_mkdir() and rdir:
            self.app.process_manager.run_process(
                self.archive.mkdir_cmd(rdir), after_mkdir
            )
        else:
            after_mkdir(0)

    def _commit(self) -> None:
        """Write the per-checkpoint state file then the root .well-known."""
        from .archive import remote_checkpoint_name

        local = os.path.join(
            self.run.tmp.get_name(), f"commit-{self.archive.name}.json"
        )
        fs.durable_write(
            local, self.run.state_json, point=KP_COMMIT_JSON,
            ctx=self.app.database,
        )
        cp_remote = remote_checkpoint_name("history", self.run.seq, ".json")

        def after_cp(rc):
            if rc != 0:
                self.done(False)
                return
            if (
                self.remote_state is not None
                and self.remote_state.current_ledger >= self.run.seq
            ):
                # never regress the archive root (e.g. replay republish)
                self.done(True)
                return
            self._put(
                local,
                WELL_KNOWN_PATH,
                lambda rc2: self.done(rc2 == 0),
            )

        self._put(local, cp_remote, after_cp)
