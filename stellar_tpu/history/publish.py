"""Crash-safe publish queue table (reference: HistoryManagerImpl.cpp:48-53,
publishqueue; snapshots queue inside the ledger-close SQL transaction at
LedgerManagerImpl.cpp:710-736 so a crash never loses a checkpoint).
"""

from __future__ import annotations

from typing import List

from ..util import fs

# the queue row is written INSIDE the ledger-close transaction; a kill
# here must repair to "checkpoint still queued" (or "close never
# happened") on restart — never to a lost checkpoint
KP_QUEUE_ROW = fs.register_kill_point(
    "publish.queue-row", "crash-safe publishqueue row written in the close txn"
)


def drop_publish_queue(db) -> None:
    db.execute("DROP TABLE IF EXISTS publishqueue")
    db.execute(
        """CREATE TABLE publishqueue (
            ledger   INTEGER PRIMARY KEY,
            state    TEXT
        )"""
    )


def queue_checkpoint(db, ledger_seq: int, state_json: str) -> None:
    db.execute(
        "INSERT OR REPLACE INTO publishqueue (ledger, state) VALUES (?,?)",
        (ledger_seq, state_json),
    )
    fs.kill_point(KP_QUEUE_ROW, ctx=db)


def queued_checkpoints(db) -> List[tuple]:
    return db.query_all("SELECT ledger, state FROM publishqueue ORDER BY ledger")


def min_queued(db) -> int:
    """Smallest queued checkpoint ledger, 0 if none (avoids pulling the
    archive-state blobs just to read a number)."""
    row = db.query_one("SELECT MIN(ledger) FROM publishqueue")
    return row[0] if row and row[0] is not None else 0


def dequeue_checkpoint(db, ledger_seq: int) -> None:
    db.execute("DELETE FROM publishqueue WHERE ledger=?", (ledger_seq,))
