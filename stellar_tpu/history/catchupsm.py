"""Catchup state machine — BEGIN → ANCHORED → FETCHING → VERIFYING →
APPLYING → END (reference: src/history/CatchupStateMachine.{h,cpp}).

Two modes (HistoryManager.h:186-197):

- MINIMAL: fetch the anchor checkpoint's bucket files, verify the anchor
  ledger-header chain, replay the buckets into the SQL store
  (Bucket.apply), adopt the bucket-list shape (assumeState), and jump the
  LCL to the anchor header.
- COMPLETE: fetch every ledger/transactions/results checkpoint from the
  local LCL forward, verify the header hash-chain back from the anchor,
  and replay each ledger through the normal ``close_ledger`` path (full
  signature checks — this is the reference's replay semantics).

Failures retry with a fresh random archive after a backoff, up to
``MAX_RETRIES`` (CatchupStateMachine.h RETRYING loop).
"""

from __future__ import annotations

import os
import random
from typing import Callable, Dict, List, Optional

from ..util import VirtualTimer, xlog
from ..util.xdrstream import XDRInputFileStream
from ..xdr.ledger import (
    LedgerHeaderHistoryEntry,
    TransactionHistoryEntry,
)
from .archive import WELL_KNOWN_PATH, HistoryArchive, HistoryArchiveState
from .filetransfer import (
    CAT_BUCKET,
    CAT_LEDGER,
    CAT_TRANSACTIONS,
    FILE_FAILED,
    FILE_VERIFIED,
    FileTransferInfo,
)

log = xlog.logger("History")

CATCHUP_MINIMAL = "minimal"
CATCHUP_COMPLETE = "complete"
# fetch bucket files referenced by a known-good local state but missing on
# disk (reference: CATCHUP_BUCKET_REPAIR, HistoryManager.h:197,
# HistoryManagerImpl::downloadMissingBuckets at .cpp:700)
CATCHUP_BUCKET_REPAIR = "bucket-repair"

MAX_RETRIES = 5
RETRY_DELAY_SECONDS = 2.0


class CatchupStateMachine:
    # per-process construction counter feeding the archive-pick seed (see
    # __init__): deterministic within a run, rotates across sessions
    _nonce = 0

    def __init__(
        self,
        app,
        mode: str,
        done: Callable[[bool, Optional[object]], None],
        desired_state: Optional[HistoryArchiveState] = None,
    ):
        """``done(ok, anchor_header_frame_or_None)`` fires on completion.
        The fetch range is derived from the local LCL and the archive
        anchor, not from the ledgers that triggered the catchup.  In
        CATCHUP_BUCKET_REPAIR mode, ``desired_state`` names the buckets the
        LOCAL node needs (the archive's own state is only used to pick a
        reachable archive)."""
        self.app = app
        self.mode = mode
        self.done = done
        self.desired_state = desired_state
        self.state = "BEGIN"
        self.retries = 0
        self.archive: Optional[HistoryArchive] = None
        self.has: Optional[HistoryArchiveState] = None
        self.tmp = app.tmp_dirs.tmp_dir("catchup")
        self.headers: Dict[int, LedgerHeaderHistoryEntry] = {}
        self.tx_sets: Dict[int, object] = {}
        self._timer = VirtualTimer(app.clock)
        # archive spread is load-balancing; seed the pick from the node's
        # identity XOR a per-process construction nonce so a catchup run
        # replays identically (same construction order => same picks)
        # while successive catchup sessions — and distinct nodes — still
        # rotate across archives instead of pinning one forever
        # (determinism rule — module-level random would diverge two
        # otherwise-equal runs)
        seed = getattr(app.config, "NODE_SEED", None)
        ident = (
            int.from_bytes(seed.get_public_key().value[:8], "big")
            if seed is not None
            else 0xCA7C4
        )
        CatchupStateMachine._nonce += 1
        self._rng = random.Random(ident ^ (CatchupStateMachine._nonce << 16))

    # -- BEGIN: pick archive, fetch root state -----------------------------
    def begin(self) -> None:
        self.state = "BEGIN"
        readable = [
            HistoryArchive(name, spec)
            for name, spec in self.app.config.HISTORY.items()
            if spec.get("get")
        ]
        if not readable:
            log.error("catchup: no readable history archives configured")
            self._fail()
            return
        self.archive = self._rng.choice(readable)
        local = os.path.join(self.tmp.get_name(), "remote-state.json")

        def got(rc):
            if rc != 0:
                log.info("catchup: could not fetch %s state", self.archive.name)
                self._retry()
                return
            try:
                with open(local) as f:
                    self.has = HistoryArchiveState.from_json(f.read())
            except Exception as e:
                log.info("catchup: bad archive state: %s", e)
                self._retry()
                return
            self._anchored()

        self.app.process_manager.run_process(
            self.archive.get_file_cmd(WELL_KNOWN_PATH, local), got
        )

    # -- ANCHORED: pick range, queue files ---------------------------------
    def _anchored(self) -> None:
        self.state = "ANCHORED"
        if self.mode == CATCHUP_BUCKET_REPAIR:
            # repair wants the LOCAL state's buckets, regardless of how far
            # along the archive is (CatchupStateMachine.cpp:564-573)
            bm = self.app.bucket_manager
            missing = bm.check_for_missing_bucket_files(self.desired_state)
            for h in self.app.history_manager.missing_publish_queue_buckets():
                if h not in missing:
                    missing.append(h)
            self._fetch(
                [
                    FileTransferInfo.for_bucket(self.tmp.get_name(), h)
                    for h in missing
                ]
            )
            return
        anchor = self.has.current_ledger
        lcl = self.app.ledger_manager.get_last_closed_ledger_num()
        if anchor <= lcl:
            log.info(
                "catchup: archive at %d is not ahead of LCL %d; retrying later",
                anchor,
                lcl,
            )
            self._retry()
            return
        freq = self.app.config.CHECKPOINT_FREQUENCY
        files: List[FileTransferInfo] = []
        if self.mode == CATCHUP_MINIMAL:
            needed = []  # deduped: a hash can be referenced by several levels
            for h in self.has.all_bucket_hashes():
                if h not in needed and not self.app.bucket_manager.has_bucket(h):
                    needed.append(h)
            for h in needed:
                files.append(FileTransferInfo.for_bucket(self.tmp.get_name(), h))
            files.append(
                FileTransferInfo.for_checkpoint(self.tmp.get_name(), CAT_LEDGER, anchor)
            )
        else:
            # every checkpoint covering (lcl, anchor]
            from .manager import checkpoint_containing_ledger

            start_cp = min(checkpoint_containing_ledger(lcl + 1, freq), anchor)
            checkpoints = list(range(start_cp, anchor + 1, freq))
            if checkpoints and checkpoints[-1] != anchor:
                checkpoints.append(anchor)
            if not checkpoints:
                checkpoints = [anchor]
            for cp in checkpoints:
                files.append(
                    FileTransferInfo.for_checkpoint(self.tmp.get_name(), CAT_LEDGER, cp)
                )
                files.append(
                    FileTransferInfo.for_checkpoint(
                        self.tmp.get_name(), CAT_TRANSACTIONS, cp
                    )
                )
        self._fetch(files)

    # -- FETCHING: download + gunzip each ----------------------------------
    def _fetch(self, files: List[FileTransferInfo]) -> None:
        self.state = "FETCHING"
        if not files:
            self._verify([])
            return
        counter = {"left": len(files), "ok": True}

        def file_done(fi, ok):
            fi.state = FILE_VERIFIED if ok else FILE_FAILED
            counter["left"] -= 1
            counter["ok"] = counter["ok"] and ok
            if counter["left"] == 0:
                if counter["ok"]:
                    self._verify(files)
                else:
                    self._retry()

        for fi in files:
            self._download_one(fi, file_done)

    def _download_one(self, fi: FileTransferInfo, cb) -> None:
        def got(rc):
            if rc != 0:
                log.info("catchup: download failed: %s", fi.remote_name)
                cb(fi, False)
                return

            def gunzipped(rc2):
                cb(fi, rc2 == 0)

            self.app.process_manager.run_process(
                f"gzip -d -f '{fi.local_path_gz}'", gunzipped
            )

        self.app.process_manager.run_process(
            self.archive.get_file_cmd(fi.remote_name, fi.local_path_gz), got
        )

    # -- VERIFYING: ledger-header hash chain -------------------------------
    def _verify(self, files: List[FileTransferInfo]) -> None:
        self.state = "VERIFYING"
        if self.mode == CATCHUP_BUCKET_REPAIR:
            # bucket files verify against their own content hash during
            # adoption (CatchupStateMachine.cpp:718-721); no header chain
            self._apply(files)
            return
        try:
            self.headers.clear()
            self.tx_sets.clear()
            for fi in files:
                if fi.category == CAT_LEDGER:
                    with XDRInputFileStream(fi.local_path) as f:
                        for lhe in f.read_all(LedgerHeaderHistoryEntry):
                            self.headers[lhe.header.ledgerSeq] = lhe
                elif fi.category == CAT_TRANSACTIONS:
                    with XDRInputFileStream(fi.local_path) as f:
                        for the in f.read_all(TransactionHistoryEntry):
                            self.tx_sets[the.ledgerSeq] = the.txSet
            ok = self._verify_header_chain()
        except Exception as e:
            log.error("catchup: verification error: %s", e)
            ok = False
        if not ok:
            self._retry()
            return
        self._apply(files)

    def _verify_header_chain(self) -> bool:
        """Each header's hash must be self-consistent and chain to its
        predecessor (HistoryManager VerifyHashStatus)."""
        from ..crypto import sha256
        from ..ledger.headerframe import LedgerHeaderFrame

        anchor = self.has.current_ledger
        if anchor not in self.headers:
            log.error("catchup: anchor header %d missing from archive", anchor)
            return False
        for seq in sorted(self.headers):
            lhe = self.headers[seq]
            recomputed = sha256(lhe.header.to_xdr())
            if recomputed != lhe.hash:
                log.error("catchup: header %d hash mismatch", seq)
                return False
            prev = self.headers.get(seq - 1)
            if prev is not None and lhe.header.previousLedgerHash != prev.hash:
                log.error("catchup: header chain broken at %d", seq)
                return False
        # chain must connect to our own LCL when replaying forward
        if self.mode == CATCHUP_COMPLETE:
            lcl = self.app.ledger_manager.last_closed
            nxt = self.headers.get(lcl.header.ledgerSeq + 1)
            if nxt is not None and nxt.header.previousLedgerHash != lcl.hash:
                log.error("catchup: archive chain does not connect to local LCL")
                return False
        return True

    # -- APPLYING ----------------------------------------------------------
    def _apply(self, files: List[FileTransferInfo]) -> None:
        self.state = "APPLYING"
        if self.mode == CATCHUP_BUCKET_REPAIR:
            try:
                self._adopt_bucket_files(files)
            except Exception as e:
                log.error("bucket repair: adopt failed: %s", e)
                self._retry()
                return
            self.state = "END"
            self.done(True, None)
            self.app.tmp_dirs.forget(self.tmp)
            return
        try:
            if self.mode == CATCHUP_MINIMAL:
                self._apply_minimal(files)
            else:
                self._apply_complete()
        except Exception as e:
            log.error("catchup: apply failed: %s", e)
            self._retry()
            return
        anchor = self.headers[self.has.current_ledger]
        try:
            self.state = "END"
            self.done(True, anchor)
        except Exception as e:
            # completion handler found a deeper inconsistency (e.g. anchor
            # bucket hash mismatch) — treat like any other failed round
            log.error("catchup: completion handler rejected result: %s", e)
            self.state = "APPLYING"
            self._retry()
            return
        self.app.tmp_dirs.forget(self.tmp)

    def _adopt_bucket_files(self, files: List[FileTransferInfo]) -> None:
        """Verify each fetched bucket file against its content hash and
        adopt it into the bucket dir.  Archive names carry the v2
        state-plane hash (bucket/hashplane.py), so verification is the
        same batched per-record re-hash the boot self-check runs — a
        malformed frame stream fails verification like any wrong hash."""
        from ..bucket import hashplane

        bm = self.app.bucket_manager
        for fi in files:
            if fi.category != CAT_BUCKET:
                continue
            try:
                got, _count = hashplane.hash_file(
                    fi.local_path, config=self.app.config
                )
            except ValueError:
                raise RuntimeError(
                    f"bucket {fi.base_name} has malformed frames"
                )
            want = bytes.fromhex(fi.base_name[7:-4])
            if got != want:
                raise RuntimeError(f"bucket {fi.base_name} hash mismatch")
            bm.adopt_file_as_bucket(fi.local_path, want, 0)

    def _apply_minimal(self, files: List[FileTransferInfo]) -> None:
        """Adopt fetched buckets, wipe ledger-object state, replay buckets
        oldest→newest, assume the bucket-list shape."""
        from ..bucket.bucket import ZERO_HASH

        # validate BEFORE any destructive step: the HAS must reconstruct
        # the anchor header's bucketListHash, or this archive is lying and
        # we must retry without having wiped anything
        anchor = self.headers[self.has.current_ledger]
        if self.has.bucket_list_hash() != anchor.header.bucketListHash:
            raise RuntimeError(
                "archive bucket list does not hash to the anchor header"
            )
        self._adopt_bucket_files(files)
        bm = self.app.bucket_manager
        db = self.app.database
        with db.transaction():
            for table in ("accounts", "signers", "trustlines", "offers"):
                db.execute(f"DELETE FROM {table}")
            from ..ledger.entryframe import entry_cache_of

            entry_cache_of(db).clear()
            # oldest level first so younger entries overwrite older ones
            has = self.has
            for lev_state in reversed(has.current_buckets):
                for h in (lev_state.snap, lev_state.curr):
                    if h != ZERO_HASH:
                        bm.get_bucket_by_hash(h).apply(db)
        bm.assume_state(has.to_json())

    def _apply_complete(self) -> None:
        """Replay each fetched ledger through close_ledger (full checks)."""
        from ..herder.ledgerclose import LedgerCloseData
        from ..herder.txset import TxSetFrame

        lm = self.app.ledger_manager
        seq = lm.get_last_closed_ledger_num() + 1
        anchor = self.has.current_ledger
        while seq <= anchor:
            lhe = self.headers.get(seq)
            if lhe is None:
                raise RuntimeError(f"missing header {seq} in archive")
            xdr_set = self.tx_sets.get(seq)
            if xdr_set is not None:
                ts = TxSetFrame.from_xdr_set(self.app.network_id, xdr_set)
            else:
                ts = TxSetFrame(lm.last_closed.hash)
            lm.close_ledger(LedgerCloseData(seq, ts, lhe.header.scpValue))
            if lm.last_closed.hash != lhe.hash:
                raise RuntimeError(
                    f"replayed ledger {seq} hash mismatch vs archive"
                )
            seq += 1

    # -- retry loop --------------------------------------------------------
    def _retry(self) -> None:
        self.retries += 1
        if self.retries > MAX_RETRIES:
            self._fail()
            return
        self.state = "RETRYING"
        log.info("catchup: retry %d/%d", self.retries, MAX_RETRIES)
        self._timer.expires_from_now(RETRY_DELAY_SECONDS)
        self._timer.async_wait(self.begin)

    def _fail(self) -> None:
        self.state = "FAILED"
        self.app.tmp_dirs.forget(self.tmp)
        self.done(False, None)
