"""Per-file transfer bookkeeping for publish/catchup
(reference: src/history/FileTransferInfo.{h,cpp}).

A FileTransferInfo names one checkpoint file in three places: the local
snapshot/staging path, the gzipped staging path, and the remote archive path
(``category/ww/xx/yy/category-<hex8>.xdr.gz``).  The download/upload FSM per
file (FILE_CATCHUP_NEEDED → DOWNLOADING → DOWNLOADED → DECOMPRESSING →
VERIFYING → VERIFIED, CatchupStateMachine.h:78-89) is tracked by the state
machines; this module is just naming + status.
"""

from __future__ import annotations

import os

from .archive import checkpoint_hex, remote_bucket_name, remote_checkpoint_name

CAT_LEDGER = "ledger"
CAT_TRANSACTIONS = "transactions"
CAT_RESULTS = "results"
CAT_BUCKET = "bucket"

# per-file FSM states
FILE_NEEDED = "needed"
FILE_DOWNLOADING = "downloading"
FILE_DOWNLOADED = "downloaded"
FILE_DECOMPRESSING = "decompressing"
FILE_VERIFIED = "verified"
FILE_FAILED = "failed"


class FileTransferInfo:
    def __init__(self, local_dir: str, category: str, base_name: str, remote: str):
        self.category = category
        self.base_name = base_name
        self.local_path = os.path.join(local_dir, base_name)
        self.local_path_gz = self.local_path + ".gz"
        self.remote_name = remote
        self.remote_dir = os.path.dirname(remote)
        self.state = FILE_NEEDED

    @classmethod
    def for_checkpoint(
        cls, local_dir: str, category: str, ledger_seq: int
    ) -> "FileTransferInfo":
        base = f"{category}-{checkpoint_hex(ledger_seq)}.xdr"
        return cls(
            local_dir,
            category,
            base,
            remote_checkpoint_name(category, ledger_seq, ".xdr.gz"),
        )

    @classmethod
    def for_bucket(cls, local_dir: str, bucket_hash: bytes) -> "FileTransferInfo":
        base = f"bucket-{bucket_hash.hex()}.xdr"
        return cls(local_dir, CAT_BUCKET, base, remote_bucket_name(bucket_hash))

    def __repr__(self):
        return f"<FileTransferInfo {self.category} {self.base_name} {self.state}>"
