"""History archives and their published state
(reference: src/history/HistoryArchive.{h,cpp}).

A HistoryArchive is a remote blob store driven entirely through
user-configured shell command templates (get/put/mkdir) run as subprocesses —
`cp` for local test archives, `curl`/`aws s3` in production.  Its root object
is ``.well-known/stellar-history.json``: a HistoryArchiveState recording the
archive's current ledger and the full 11-level bucket-list shape, including
any in-progress FutureBucket merges (which is what makes merges resumable
across restart).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from ..bucket.bucket import ZERO_HASH
from ..bucket.futurebucket import FB_CLEAR, FutureBucket

HISTORY_ARCHIVE_STATE_VERSION = 1
WELL_KNOWN_PATH = ".well-known/stellar-history.json"


def _split_hex(hex8: str) -> str:
    return f"{hex8[0:2]}/{hex8[2:4]}/{hex8[4:6]}"


def checkpoint_hex(ledger_seq: int) -> str:
    return f"{ledger_seq:08x}"


def remote_checkpoint_name(category: str, ledger_seq: int, ext: str) -> str:
    """`category/ww/xx/yy/category-<hex8>.<ext>` layout
    (reference: FileTransferInfo.h remoteName)."""
    h = checkpoint_hex(ledger_seq)
    return f"{category}/{_split_hex(h)}/{category}-{h}{ext}"


def remote_bucket_name(bucket_hash: bytes) -> str:
    h = bucket_hash.hex()
    return f"bucket/{_split_hex(h)}/bucket-{h}.xdr.gz"


class HistoryStateBucketLevel:
    """One level of the serialized bucket list: curr/snap hashes + next."""

    def __init__(
        self,
        curr: bytes = ZERO_HASH,
        snap: bytes = ZERO_HASH,
        next_state: Optional[dict] = None,
    ):
        self.curr = curr
        self.snap = snap
        self.next = next_state or {"state": FB_CLEAR}

    def to_json(self) -> dict:
        return {"curr": self.curr.hex(), "snap": self.snap.hex(), "next": self.next}

    @classmethod
    def from_json(cls, d: dict) -> "HistoryStateBucketLevel":
        return cls(
            bytes.fromhex(d.get("curr", ZERO_HASH.hex())),
            bytes.fromhex(d.get("snap", ZERO_HASH.hex())),
            d.get("next", {"state": FB_CLEAR}),
        )


class HistoryArchiveState:
    def __init__(
        self,
        current_ledger: int = 0,
        levels: Optional[List[HistoryStateBucketLevel]] = None,
        server: str = "stellar-tpu",
    ):
        from ..bucket.bucketlist import NUM_LEVELS

        self.version = HISTORY_ARCHIVE_STATE_VERSION
        self.server = server
        self.current_ledger = current_ledger
        self.current_buckets = levels or [
            HistoryStateBucketLevel() for _ in range(NUM_LEVELS)
        ]

    @classmethod
    def from_bucket_list(
        cls, ledger_seq: int, bucket_list, server: str = "stellar-tpu"
    ) -> "HistoryArchiveState":
        levels = [
            HistoryStateBucketLevel(
                lev.curr.get_hash(), lev.snap.get_hash(), lev.next.to_state()
            )
            for lev in bucket_list.levels
        ]
        return cls(ledger_seq, levels, server)

    def to_json(self) -> str:
        return json.dumps(
            {
                "version": self.version,
                "server": self.server,
                "currentLedger": self.current_ledger,
                "currentBuckets": [b.to_json() for b in self.current_buckets],
            },
            indent=1,
        )

    @classmethod
    def from_json(cls, s: str) -> "HistoryArchiveState":
        d = json.loads(s)
        st = cls(
            d.get("currentLedger", 0),
            [HistoryStateBucketLevel.from_json(b) for b in d.get("currentBuckets", [])],
            d.get("server", ""),
        )
        st.version = d.get("version", HISTORY_ARCHIVE_STATE_VERSION)
        return st

    def bucket_list_hash(self) -> bytes:
        """The bucketListHash this state reconstructs to, computed from
        hashes alone (BucketList::getHash shape: H(concat H(curr‖snap))) —
        lets catchup validate an archive BEFORE adopting anything."""
        from ..crypto import SHA256

        outer = SHA256()
        for lev in self.current_buckets:
            inner = SHA256()
            inner.add(lev.curr)
            inner.add(lev.snap)
            outer.add(inner.finish())
        return outer.finish()

    def all_bucket_hashes(self) -> List[bytes]:
        """Every nonzero bucket hash referenced (incl. future inputs/outputs)."""
        out: List[bytes] = []
        for lev in self.current_buckets:
            out.append(lev.curr)
            out.append(lev.snap)
            out.extend(FutureBucket.from_state(lev.next).referenced_hashes())
        return [h for h in out if h != ZERO_HASH]

    def differing_buckets(self, other: "HistoryArchiveState") -> List[bytes]:
        """Hashes we reference that ``other`` doesn't (publish delta,
        reference HistoryArchiveState::differingBuckets)."""
        theirs = set(other.all_bucket_hashes())
        seen = set()
        out = []
        for h in self.all_bucket_hashes():
            if h not in theirs and h not in seen:
                seen.add(h)
                out.append(h)
        return out


class HistoryArchive:
    """One configured archive: name + get/put/mkdir command templates with
    ``{0}`` (remote) / ``{1}`` (local) placeholders
    (reference: HistoryArchive.h:166-170)."""

    def __init__(self, name: str, spec: Dict[str, str]):
        self.name = name
        self.get_tmpl = spec.get("get", "")
        self.put_tmpl = spec.get("put", "")
        self.mkdir_tmpl = spec.get("mkdir", "")

    def has_get(self) -> bool:
        return bool(self.get_tmpl)

    def has_put(self) -> bool:
        return bool(self.put_tmpl)

    def has_mkdir(self) -> bool:
        return bool(self.mkdir_tmpl)

    def get_file_cmd(self, remote: str, local: str) -> str:
        return self.get_tmpl.format(remote, local)

    def put_file_cmd(self, local: str, remote: str) -> str:
        # NB: reference putFileCmd substitutes {0}=local {1}=remote
        return self.put_tmpl.format(local, remote)

    def mkdir_cmd(self, remote_dir: str) -> str:
        return self.mkdir_tmpl.format(remote_dir)
