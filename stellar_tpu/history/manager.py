"""HistoryManager (reference: src/history/HistoryManagerImpl.{h,cpp}).

Owns checkpoint cadence, the crash-safe publish queue, and the catchup
entry point.  Checkpoints are queued INSIDE the ledger-close SQL
transaction (LedgerManagerImpl.cpp:710-736) and published asynchronously
afterwards; a crash between the two just republishes on next boot.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..util import xlog
from . import publish as publish_queue
from .catchupsm import CATCHUP_COMPLETE, CATCHUP_MINIMAL, CatchupStateMachine
from .publishsm import PublishRun

log = xlog.logger("History")


def checkpoint_containing_ledger(ledger: int, freq: int = 64) -> int:
    """First checkpoint ledger >= ledger (boundaries at freq-1, 2*freq-1...)."""
    return ((ledger // freq) + 1) * freq - 1


class HistoryManager:
    def __init__(self, app):
        self.app = app
        self.publishing = False
        self.catchup: Optional[CatchupStateMachine] = None
        self._publish_success = 0
        self._publish_failure = 0

    @property
    def checkpoint_frequency(self) -> int:
        return self.app.config.CHECKPOINT_FREQUENCY

    @property
    def has_archives(self) -> bool:
        return bool(self.app.config.HISTORY)

    @property
    def has_writable_archives(self) -> bool:
        return any(spec.get("put") for spec in self.app.config.HISTORY.values())

    @property
    def has_readable_archives(self) -> bool:
        return any(spec.get("get") for spec in self.app.config.HISTORY.values())

    def next_checkpoint_ledger(self, ledger: int) -> int:
        return checkpoint_containing_ledger(ledger, self.checkpoint_frequency)

    # -- publishing --------------------------------------------------------
    def maybe_queue_history_checkpoint(self) -> None:
        # called after ledger pointers advanced: the just-closed ledger is
        # LCL.  Checkpoints close at seqs freq-1, 2*freq-1, ... (the
        # reference queues when the NEXT ledger is a frequency multiple).
        closed_seq = self.app.ledger_manager.last_closed.header.ledgerSeq
        if (closed_seq + 1) % self.checkpoint_frequency != 0:
            return
        if not self.has_writable_archives:
            return
        publish_queue.queue_checkpoint(
            self.app.database,
            closed_seq,
            self.app.bucket_manager.archive_state_json(closed_seq),
        )
        log.info("queued checkpoint at ledger %d", closed_seq)

    def publish_queued_history(self) -> int:
        """Drain the publish queue one checkpoint at a time; returns how
        many checkpoints are queued (reference publishQueuedHistory
        returns the count kicked off)."""
        if not self.has_writable_archives or self.publishing:
            return 0
        if getattr(self.app.database, "closed", False):
            return 0  # app shut down while a publish-kick was queued
        from ..ledger.manager import LedgerState

        if self.app.ledger_manager.state == LedgerState.LM_CATCHING_UP_STATE:
            # replaying history re-queues old checkpoints; publishing them
            # now would regress the archive root state — drain after catchup
            return 0
        queued = publish_queue.queued_checkpoints(self.app.database)
        if not queued:
            return 0
        seq, state_json = queued[0]
        self.publishing = True

        def done(ok: bool):
            self.publishing = False
            if ok:
                self._publish_success += 1
                publish_queue.dequeue_checkpoint(self.app.database, seq)
                log.info("published checkpoint %d", seq)
                # more may be queued (e.g. after catchup replay)
                self.app.clock.post(self.publish_queued_history)
            else:
                self._publish_failure += 1
                log.error("publishing checkpoint %d failed; will retry", seq)

        PublishRun(self.app, seq, state_json, done).start()
        return len(queued)

    # -- catchup -----------------------------------------------------------
    def catchup_history(
        self, mode: Optional[str] = None, done_cb: Callable = None
    ) -> None:
        """Start (or restart) the catchup FSM toward the newest archive
        state.  ``done_cb(ok, anchor_header)`` defaults to the
        LedgerManager's completion handler."""
        if self.catchup is not None and self.catchup.state not in ("END", "FAILED"):
            return  # already running
        if mode is None:
            mode = (
                CATCHUP_COMPLETE
                if self.app.config.CATCHUP_COMPLETE
                else CATCHUP_MINIMAL
            )
        if done_cb is None:
            done_cb = self.app.ledger_manager.catchup_finished
        self.catchup = CatchupStateMachine(self.app, mode, done_cb)
        self.catchup.begin()

    # -- bucket repair (HistoryManagerImpl::downloadMissingBuckets) --------
    def download_missing_buckets(
        self, state_json: str, handler: Callable[[bool], None]
    ) -> None:
        """Fetch bucket files referenced by ``state_json`` (and the publish
        queue) that are missing from the bucket dir, then call
        ``handler(ok)`` (reference: HistoryManagerImpl.cpp:700-718)."""
        from .archive import HistoryArchiveState
        from .catchupsm import CATCHUP_BUCKET_REPAIR

        if self.catchup is not None and self.catchup.state not in (
            "END",
            "FAILED",
        ):
            raise RuntimeError("a catchup state machine is already running")
        desired = HistoryArchiveState.from_json(state_json)

        def done(ok, _anchor):
            self.catchup = None
            handler(ok)

        self.catchup = CatchupStateMachine(
            self.app, CATCHUP_BUCKET_REPAIR, done, desired_state=desired
        )
        self.catchup.begin()

    def missing_publish_queue_buckets(self) -> list:
        """Bucket hashes referenced by queued-but-unpublished checkpoints
        with no file on disk (reference:
        getMissingBucketsReferencedByPublishQueue)."""
        from .archive import HistoryArchiveState

        bm = self.app.bucket_manager
        missing = []
        for _seq, state_json in publish_queue.queued_checkpoints(
            self.app.database
        ):
            try:
                has = HistoryArchiveState.from_json(state_json)
            except Exception:
                continue
            for h in bm.check_for_missing_bucket_files(has):
                if h not in missing:
                    missing.append(h)
        return missing

    def get_min_ledger_queued_to_publish(self) -> int:
        """Smallest queued-but-unpublished checkpoint ledger, 0 if none
        (reference: getMinLedgerQueuedToPublish, gates maintenance)."""
        return publish_queue.min_queued(self.app.database)

    def get_publish_success_count(self) -> int:
        return self._publish_success

    def get_publish_failure_count(self) -> int:
        return self._publish_failure
