"""HistoryManager (reference: src/history/HistoryManagerImpl.cpp).

INTERIM shell: checkpoint cadence constants + crash-safe queue wiring; the
publish/catchup state machines land in publishsm.py / catchupsm.py.
"""

from __future__ import annotations

from ..util import xlog
from . import publish as publish_queue

log = xlog.logger("History")

CHECKPOINT_FREQUENCY = 64  # ledgers (~5 min; HistoryManagerImpl.cpp:230)


def checkpoint_containing_ledger(ledger: int) -> int:
    """First checkpoint ledger >= ledger (boundaries at 63, 127, ...)."""
    return ((ledger // CHECKPOINT_FREQUENCY) + 1) * CHECKPOINT_FREQUENCY - 1


class HistoryManager:
    def __init__(self, app):
        self.app = app
        self.publishing = False

    @property
    def has_archives(self) -> bool:
        return bool(self.app.config.HISTORY)

    def next_checkpoint_ledger(self, ledger: int) -> int:
        return checkpoint_containing_ledger(ledger)

    def maybe_queue_history_checkpoint(self) -> None:
        # called after ledger pointers advanced: the just-closed ledger is LCL.
        # Checkpoints close at seqs 63, 127, ... (HistoryManagerImpl queues
        # when the NEXT ledger number is a multiple of the frequency).
        closed_seq = self.app.ledger_manager.last_closed.header.ledgerSeq
        if (closed_seq + 1) % CHECKPOINT_FREQUENCY != 0:
            return
        if not self.has_archives:
            return
        publish_queue.queue_checkpoint(
            self.app.database, closed_seq,
            self.app.bucket_manager.archive_state_json(closed_seq),
        )
        log.info("queued checkpoint at ledger %d", closed_seq)

    def publish_queued_history(self) -> None:
        if not self.has_archives or self.publishing:
            return
        # full publish state machine lands in history/publishsm.py

    def catchup_history(self, init_ledger: int, mode: str, done_cb) -> None:
        # full catchup state machine lands in history/catchupsm.py
        raise NotImplementedError("catchup state machine not wired yet")
