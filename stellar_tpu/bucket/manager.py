"""BucketManager (reference: src/bucket/BucketManagerImpl.cpp).

INTERIM (single-level) implementation: hashes each ledger's live/dead entry
batch into a running chain so headers commit to state changes deterministically.
The full 11-level log-structured BucketList with worker-thread merges and
resumable FutureBuckets replaces the internals in bucket/bucketlist.py —
this class keeps the same interface either way.
"""

from __future__ import annotations

import json
from typing import List

from ..crypto import SHA256, sha256
from ..xdr.ledger import BucketEntry, BucketEntryType


class BucketManager:
    def __init__(self, app):
        self.app = app
        self._hash = b"\x00" * 32

    def add_batch(self, ledger_seq: int, live_entries, dead_entries) -> None:
        h = SHA256()
        h.add(self._hash)
        for e in live_entries:
            h.add(BucketEntry(BucketEntryType.LIVEENTRY, e).to_xdr())
        for k in dead_entries:
            h.add(BucketEntry(BucketEntryType.DEADENTRY, k).to_xdr())
        self._hash = h.finish()

    def get_hash(self) -> bytes:
        return self._hash

    def archive_state_json(self, ledger_seq: int) -> str:
        return json.dumps(
            {"version": 1, "currentLedger": ledger_seq, "bucketHash": self._hash.hex()}
        )

    def forget_unreferenced_buckets(self) -> None:
        pass

    def assume_state(self, state_json: str) -> None:
        st = json.loads(state_json)
        self._hash = bytes.fromhex(st.get("bucketHash", "00" * 32))
