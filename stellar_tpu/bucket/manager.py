"""BucketManager — owns the bucket directory and the hash→Bucket map
(reference: src/bucket/BucketManagerImpl.{h,cpp}).

Content-addressed: a merged/fresh bucket file is renamed to
``bucket-<hash>.xdr`` inside the bucket dir and shared by hash thereafter.
Worker threads adopt buckets concurrently (merges run on the pool), so the
map is lock-guarded — the reference's one mutex-guarded subsystem outside
crypto (BucketManagerImpl.h mBucketMutex).

GC (``forget_unreferenced_buckets``) drops map entries and files whose hash
is no longer referenced by the live bucket list, any in-progress future
merge, or any queued-but-unpublished history checkpoint state.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from ..util import fs, xlog
from .bucket import ZERO_HASH, Bucket
from .bucketlist import BucketList

log = xlog.logger("Bucket")

# adoption is the rename half of every bucket write's durability story
KP_ADOPT = fs.register_durable_site(
    "bucket.adopt", stages=(fs.STAGE_STAGED, fs.STAGE_RENAMED),
    doc="staged bucket renamed to its content-addressed canonical name",
)


class BucketManager:
    def __init__(self, app):
        self.app = app
        self.bucket_list = BucketList()
        self._buckets: Dict[bytes, Bucket] = {}
        self._lock = threading.Lock()
        self.last_checkdb: Optional[dict] = None
        self._checkdb_run = None
        # NB: must NOT live under TMP_DIR_PATH — that root is wiped on app
        # construction, and buckets must survive restart (merge resume).
        self.bucket_dir = os.path.abspath(app.config.BUCKET_DIR_PATH)
        os.makedirs(self.bucket_dir, exist_ok=True)
        # sweep merge temp files (and boot-quarantined corpses) orphaned
        # by a crash — the dir is persistent by design, so nothing else
        # cleans them.  Counted so the boot self-check can meter it.
        self.tmp_swept_at_boot = 0
        for name in os.listdir(self.bucket_dir):
            if name.startswith((".durable-", "tmp-bucket-")) or (
                ".quarantined" in name
            ):
                try:
                    os.unlink(os.path.join(self.bucket_dir, name))
                    self.tmp_swept_at_boot += 1
                except OSError:
                    pass

    # -- paths -------------------------------------------------------------
    def get_tmp_dir(self) -> str:
        return self.bucket_dir

    def bucket_filename(self, h: bytes) -> str:
        return os.path.join(self.bucket_dir, f"bucket-{h.hex()}.xdr")

    # -- adoption / lookup (BucketManagerImpl::adoptFileAsBucket) ----------
    def adopt_file_as_bucket(self, path: str, h: bytes, objects: int) -> Bucket:
        with self._lock:
            existing = self._buckets.get(h)
            if existing is not None:
                os.unlink(path)
                return existing
            canonical = self.bucket_filename(h)
            # every producer stages through the fs discipline (fresh /
            # _write_merged sync on close, the native merge fsyncs
            # explicitly), so the file is already durable — skip the
            # redundant per-adoption fsync
            fs.durable_rename(
                path, canonical, point=KP_ADOPT, ctx=self.app.database,
                presynced=True,
            )
            b = Bucket(canonical, h, objects)
            self._buckets[h] = b
            return b

    def get_bucket_by_hash(self, h: bytes) -> Bucket:
        if h == ZERO_HASH:
            return Bucket()
        with self._lock:
            b = self._buckets.get(h)
            if b is not None:
                return b
            path = self.bucket_filename(h)
            if os.path.exists(path):
                b = Bucket(path, h)
                self._buckets[h] = b
                return b
        raise KeyError(f"no bucket with hash {h.hex()}")

    def has_bucket(self, h: bytes) -> bool:
        if h == ZERO_HASH:
            return True
        with self._lock:
            return h in self._buckets or os.path.exists(self.bucket_filename(h))

    def check_for_missing_bucket_files(self, has) -> list:
        """Hashes referenced by a HistoryArchiveState with no file on disk,
        deduplicated — one hash can back several levels/merges (reference:
        BucketManagerImpl::checkForMissingBucketsFiles, used by the
        boot-time bucket repair at LedgerManagerImpl.cpp:233-247)."""
        missing = []
        seen = set()  # ordered result, O(1) dedup (advisor r03)
        for h in has.all_bucket_hashes():
            if (
                h != ZERO_HASH
                and h not in seen
                and not os.path.exists(self.bucket_filename(h))
            ):
                seen.add(h)
                missing.append(h)
        return missing

    # -- on-disk integrity (boot self-check, stellar_tpu/main/selfcheck.py) -
    def verify_bucket_file(self, h: bytes) -> str:
        """One referenced bucket file's on-disk state: ``"ok"``,
        ``"missing"``, or ``"corrupt"`` (zero-length, truncated, or any
        content whose SHA256 is not the name — the hash IS the file's
        identity, so a full re-hash is the only honest check)."""
        if h == ZERO_HASH:
            return "ok"
        path = self.bucket_filename(h)
        if not os.path.exists(path):
            return "missing"
        if os.path.getsize(path) == 0:
            return "corrupt"
        # v2 re-hash through the state-plane pipeline (hashplane.py):
        # per-record digests fan over device lanes / pooled C tiles, so
        # the boot self-check's full-tree re-hash scales with cores —
        # and a frame-level parse failure is corruption by definition
        from . import hashplane

        try:
            got, _count = hashplane.hash_file(path, config=self.app.config)
        except (ValueError, OSError):
            return "corrupt"
        return "ok" if got == h else "corrupt"

    def verify_bucket_files(self, *states) -> dict:
        """Every hash the given HistoryArchiveState(s) reference,
        classified (deduplicated across states) — the integrity
        extension of ``check_for_missing_bucket_files``.  The boot
        self-check feeds the persisted HAS plus every queued-checkpoint
        state through here (main/selfcheck.py)."""
        out = {"ok": [], "missing": [], "corrupt": []}
        seen = set()
        for has in states:
            for h in has.all_bucket_hashes():
                if h == ZERO_HASH or h in seen:
                    continue
                seen.add(h)
                out[self.verify_bucket_file(h)].append(h)
        return out

    def quarantine_bucket_file(self, h: bytes) -> None:
        """Move a failed-verification file out of the content-addressed
        namespace so every downstream path (has_bucket, the boot repair's
        missing-file scan, catchup) treats it as MISSING rather than
        trusting corrupt bytes.  The corpse keeps its data for forensics
        until the next boot's tmp sweep reaps it."""
        path = self.bucket_filename(h)
        try:
            # analysis: off durable-write -- quarantine moves already-CORRUPT bytes out of the namespace; fsync discipline buys nothing (a crash mid-move just re-quarantines at the next boot — idempotent)
            os.replace(path, path + ".quarantined")
        except OSError:
            pass
        with self._lock:
            self._buckets.pop(h, None)

    # -- ledger-close interface (LedgerManager calls these) ----------------
    def add_batch(self, ledger_seq: int, live_entries, dead_entries) -> None:
        self.bucket_list.add_batch(self.app, ledger_seq, live_entries, dead_entries)

    # ledger-header snapshot hooks (reference BucketManagerImpl.cpp:300-332)
    SKIP_1 = 50
    SKIP_2 = 5000
    SKIP_3 = 50000
    SKIP_4 = 500000

    def snapshot_ledger(self, header) -> None:
        """Write bucketListHash + rotate the header skipList
        (reference: BucketManagerImpl::snapshotLedger, .cpp:300-306)."""
        header.bucketListHash = self.get_hash()
        self.calculate_skip_values(header)

    def calculate_skip_values(self, header) -> None:
        """skipList rotation at SKIP_1/2/3/4 boundaries (reference:
        BucketManagerImpl::calculateSkipValues, .cpp:308-331; behavior
        pinned by BucketTests.cpp:100-176)."""
        if header.ledgerSeq % self.SKIP_1 != 0:
            return
        v = header.ledgerSeq - self.SKIP_1
        if v > 0 and v % self.SKIP_2 == 0:
            v = header.ledgerSeq - self.SKIP_2 - self.SKIP_1
            if v > 0 and v % self.SKIP_3 == 0:
                v = header.ledgerSeq - self.SKIP_3 - self.SKIP_2 - self.SKIP_1
                if v > 0 and v % self.SKIP_4 == 0:
                    header.skipList[3] = header.skipList[2]
                header.skipList[2] = header.skipList[1]
            header.skipList[1] = header.skipList[0]
        header.skipList[0] = header.bucketListHash

    def get_hash(self) -> bytes:
        return self.bucket_list.get_hash()

    def archive_state_json(self, ledger_seq: int) -> str:
        from ..history.archive import HistoryArchiveState

        return HistoryArchiveState.from_bucket_list(
            ledger_seq, self.bucket_list
        ).to_json()

    # -- restart / catchup (BucketManagerImpl::assumeState) ----------------
    def assume_state(self, state_json: str) -> None:
        """Adopt a serialized bucket-list shape (boot after restart, or the
        end of catchup-minimal).  Buckets must exist in the bucket dir."""
        from ..bucket.futurebucket import FutureBucket
        from ..history.archive import HistoryArchiveState

        has = HistoryArchiveState.from_json(state_json)
        for i, lev_state in enumerate(has.current_buckets):
            lev = self.bucket_list.get_level(i)
            lev.curr = self.get_bucket_by_hash(lev_state.curr)
            lev.snap = self.get_bucket_by_hash(lev_state.snap)
            lev.next = FutureBucket.from_state(lev_state.next)
        self.bucket_list.restart_merges(self.app)

    def restart_merges(self) -> None:
        self.bucket_list.restart_merges(self.app)

    # -- audit (reference: BucketManagerImpl::checkDB / 'checkdb' command) -
    def check_db(self) -> dict:
        """Replay the whole bucket list oldest→newest into a live map and
        compare every entry (and the table counts) against the SQL store.
        Returns a report; raises RuntimeError on any mismatch."""
        from ..ledger.entryframe import (
            entry_cache_of,
            ledger_key_of,
            load_entry_by_key,
        )
        from ..xdr.entries import LedgerEntryType
        from ..xdr.ledger import BucketEntryType

        # the frame loaders consult the entry cache first; flush it so every
        # comparison below reads the actual SQL rows (the whole point)
        entry_cache_of(self.app.database).clear()
        state = {}
        for lev in reversed(self.bucket_list.levels):
            for b in (lev.snap, lev.curr):
                for e in b:
                    if e.type == BucketEntryType.LIVEENTRY:
                        state[ledger_key_of(e.value).to_xdr()] = e.value
                    else:
                        state.pop(e.value.to_xdr(), None)
        db = self.app.database
        counts = {LedgerEntryType.ACCOUNT: 0, LedgerEntryType.TRUSTLINE: 0,
                  LedgerEntryType.OFFER: 0}
        from ..xdr.ledger import LedgerKey

        compared = 0
        for key_xdr, entry in state.items():
            key = LedgerKey.from_xdr(key_xdr)
            counts[key.type] += 1
            frame = load_entry_by_key(key, db)
            if frame is None:
                raise RuntimeError(f"checkdb: entry missing from DB: {key}")
            if frame.entry.to_xdr() != entry.to_xdr():
                raise RuntimeError(f"checkdb: entry differs from DB: {key}")
            compared += 1
        entry_cache_of(db).clear()  # don't leave audit reads as the hot set
        table_counts = {
            LedgerEntryType.ACCOUNT: db.query_one(
                "SELECT COUNT(*) FROM accounts")[0],
            LedgerEntryType.TRUSTLINE: db.query_one(
                "SELECT COUNT(*) FROM trustlines")[0],
            LedgerEntryType.OFFER: db.query_one("SELECT COUNT(*) FROM offers")[0],
        }
        for ty, n in counts.items():
            if table_counts[ty] != n:
                raise RuntimeError(
                    f"checkdb: {ty.name} count mismatch: "
                    f"buckets={n} db={table_counts[ty]}"
                )
        return {
            "status": "ok",
            "objects_compared": compared,
            "accounts": counts[LedgerEntryType.ACCOUNT],
            "trustlines": counts[LedgerEntryType.TRUSTLINE],
            "offers": counts[LedgerEntryType.OFFER],
        }

    def start_check_db_async(self, batch: int = 2000) -> dict:
        """Cooperative audit for the admin API: one bucket (then one
        ``batch`` of SQL comparisons) per crank, so the reactor keeps
        serving SCP and peers during a long scan.  Aborts if a ledger
        closes mid-audit (the snapshot would no longer be consistent).
        Result lands in ``self.last_checkdb``."""
        if getattr(self, "_checkdb_run", None) is not None:
            return {"status": "running", **self._checkdb_run.progress()}
        run = _CheckDBRun(self, batch)
        self._checkdb_run = run
        self.app.clock.post(run.step)
        return {"status": "started"}

    # -- GC (BucketManagerImpl::forgetUnreferencedBuckets) -----------------
    def referenced_hashes(self) -> set:
        refs = set()
        for lev in self.bucket_list.levels:
            refs.add(lev.curr.get_hash())
            refs.add(lev.snap.get_hash())
            refs.update(lev.next.referenced_hashes())
        # queued-but-unpublished checkpoints still need their buckets
        from ..history import publish as publish_queue
        from ..history.archive import HistoryArchiveState

        for _seq, state_json in publish_queue.queued_checkpoints(self.app.database):
            refs.update(HistoryArchiveState.from_json(state_json).all_bucket_hashes())
        refs.discard(ZERO_HASH)
        return refs

    def forget_unreferenced_buckets(self) -> None:
        # A worker adopts its merge output before the future records the
        # output hash; GC while a merge is in flight could catch that window
        # and delete the fresh output.  Merges only start from the main
        # thread, so checking completion first closes the race.
        for lev in self.bucket_list.levels:
            if lev.next.is_live() and not lev.next._done.is_set():
                return  # defer GC to the next close
        try:
            refs = self.referenced_hashes()
        except Exception as e:
            log.error("skipping bucket GC, could not compute referenced set: %s", e)
            return
        with self._lock:
            for h in list(self._buckets):
                if h not in refs:
                    b = self._buckets.pop(h)
                    try:
                        if b.path:
                            os.unlink(b.path)
                    except OSError:
                        pass


class _CheckDBRun:
    """Incremental checkdb: replays one bucket per crank into the live map,
    then compares SQL rows in batches; consistency guarded by aborting if
    the LCL moves (the reference gets isolation from worker-thread DB
    snapshots instead — sqlite in-process has no second session)."""

    def __init__(self, bm: BucketManager, batch: int):
        from ..ledger.entryframe import entry_cache_of

        self.bm = bm
        self.app = bm.app
        self.batch = batch
        self.start_lcl = self.app.ledger_manager.last_closed.header.ledgerSeq
        self.buckets = [
            b
            for lev in reversed(bm.bucket_list.levels)
            for b in (lev.snap, lev.curr)
        ]
        self.state: Dict[bytes, object] = {}
        self._replay_iter = None  # held iterator into the current bucket
        self.items = None  # iterator over final state, set after replay
        self.compared = 0
        self.counts = None
        entry_cache_of(self.app.database).clear()

    def progress(self) -> dict:
        return {
            "buckets_left": len(self.buckets),
            "objects_compared": self.compared,
        }

    def _finish(self, report: dict) -> None:
        from ..ledger.entryframe import entry_cache_of

        entry_cache_of(self.app.database).clear()
        self.bm.last_checkdb = report
        self.bm._checkdb_run = None
        if report.get("status") != "ok":
            log.error("checkdb failed: %s", report)
        else:
            log.info("checkdb ok: %s objects", report.get("objects_compared"))

    def step(self) -> None:
        from ..ledger.entryframe import ledger_key_of, load_entry_by_key
        from ..xdr.entries import LedgerEntryType
        from ..xdr.ledger import BucketEntryType, LedgerKey

        if (
            self.app.ledger_manager.last_closed.header.ledgerSeq
            != self.start_lcl
        ):
            self._finish(
                {"status": "aborted", "error": "ledger closed during audit"}
            )
            return
        try:
            if self.buckets or self._replay_iter is not None:
                # bounded replay: the deepest bucket holds most of the
                # entries, so one-whole-bucket-per-crank would block the
                # reactor nearly as long as a synchronous scan — hold an
                # iterator into the current bucket and replay at most
                # 10*batch entries per crank
                budget = self.batch * 10
                while budget > 0:
                    if self._replay_iter is None:
                        if not self.buckets:
                            break
                        self._replay_iter = iter(self.buckets.pop(0))
                    e = next(self._replay_iter, None)
                    if e is None:
                        self._replay_iter = None
                        continue
                    if e.type == BucketEntryType.LIVEENTRY:
                        self.state[ledger_key_of(e.value).to_xdr()] = e.value
                    else:
                        self.state.pop(e.value.to_xdr(), None)
                    budget -= 1
                if self.buckets or self._replay_iter is not None:
                    self.app.clock.post(self.step)
                    return
            if self.items is None:
                self.items = iter(list(self.state.items()))
                self.counts = {
                    LedgerEntryType.ACCOUNT: 0,
                    LedgerEntryType.TRUSTLINE: 0,
                    LedgerEntryType.OFFER: 0,
                }
            db = self.app.database
            for _ in range(self.batch):
                nxt = next(self.items, None)
                if nxt is None:
                    table_counts = {
                        LedgerEntryType.ACCOUNT: db.query_one(
                            "SELECT COUNT(*) FROM accounts")[0],
                        LedgerEntryType.TRUSTLINE: db.query_one(
                            "SELECT COUNT(*) FROM trustlines")[0],
                        LedgerEntryType.OFFER: db.query_one(
                            "SELECT COUNT(*) FROM offers")[0],
                    }
                    for ty, n in self.counts.items():
                        if table_counts[ty] != n:
                            raise RuntimeError(
                                f"{ty.name} count mismatch: buckets={n} "
                                f"db={table_counts[ty]}"
                            )
                    self._finish({
                        "status": "ok",
                        "objects_compared": self.compared,
                        "accounts": self.counts[LedgerEntryType.ACCOUNT],
                        "trustlines": self.counts[LedgerEntryType.TRUSTLINE],
                        "offers": self.counts[LedgerEntryType.OFFER],
                    })
                    return
                key_xdr, entry = nxt
                key = LedgerKey.from_xdr(key_xdr)
                self.counts[key.type] += 1
                frame = load_entry_by_key(key, db)
                if frame is None:
                    raise RuntimeError(f"entry missing from DB: {key}")
                if frame.entry.to_xdr() != entry.to_xdr():
                    raise RuntimeError(f"entry differs from DB: {key}")
                self.compared += 1
            self.app.clock.post(self.step)
        except Exception as e:
            self._finish({"status": "error", "error": str(e)})
