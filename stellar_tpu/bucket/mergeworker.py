"""Dedicated background workers for level-spill bucket merges (ISSUE r22).

FutureBucket merges used to ride ``app.clock._workers`` — a pool sized
for *callback* work (history publish, herder timers) that owns exactly
one thread on small hosts, so a deep-level spill merge could queue
behind unrelated work and stall the close that needed to ``resolve()``
it.  This module gives merges their own threads, sized to the machine:
a merge starts the moment ``prepare`` fires and the close boundary that
commits it 4^level ledgers later finds it already done.

Semantics are untouched: the merge closure is the same one FutureBucket
always ran (same durable-write kill-points crossed, same error capture
into ``_done``/``_error``, resolved at the next close boundary), so
background and inline merging are bit-exact — pinned by
tests/test_hashplane.py's background-vs-inline differential and the
kill-point sweep.  ``Config.BACKGROUND_BUCKET_MERGE = False`` runs
every merge synchronously inside ``prepare`` instead (the differential
baseline, and a determinism crutch for single-stepped debugging).

Threads are daemonic and process-wide: merges are resumable across
process death by design (FutureBucket.make_live re-runs them from
hashes), so an exit mid-merge just leaves a reapable tmp file for the
boot sweep — the same contract a hard kill already has.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, List


class MergeWorkers:
    """A lazy, fixed-size pool draining merge closures from a queue."""

    def __init__(self, threads: int = 0):
        self._want = threads
        self._q: "queue.SimpleQueue[Callable[[], None]]" = queue.SimpleQueue()
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []  # analysis: locked-by _lock
        self._started = False  # analysis: locked-by _lock

    def _size(self) -> int:
        if self._want > 0:
            return self._want
        # merges are C-heavy (native engine, GIL released): use the
        # cores, but leave headroom for the close loop itself
        return max(1, min(4, (os.cpu_count() or 1) - 1 or 1))

    def submit(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if not self._started:
                self._started = True
                for i in range(self._size()):
                    t = threading.Thread(
                        target=self._run,
                        name=f"bucket-merge-{i}",
                        daemon=True,
                    )
                    t.start()
                    self._threads.append(t)
        self._q.put(fn)

    def _run(self) -> None:
        while True:
            fn = self._q.get()
            try:
                fn()
            except BaseException:  # pragma: no cover — fn captures its own
                pass


# process-wide singleton: merges from every app instance share one pool
# (like the native pthread pool), bounded regardless of test app churn
_pool = MergeWorkers()


def submit(fn: Callable[[], None]) -> None:
    _pool.submit(fn)
