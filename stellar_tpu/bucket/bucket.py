"""Bucket — immutable, sorted, content-hashed XDR flat file of ledger entries
(reference: src/bucket/Bucket.{h,cpp}, src/bucket/LedgerCmp.h).

A bucket holds BucketEntry records (LIVEENTRY LedgerEntry | DEADENTRY
LedgerKey) sorted by entry identity; its hash is the v2 state-plane hash
(bucket/hashplane.py, ISSUE r22): SHA256 over the concatenated
per-record digests, each digest the SHA256 of one full frame as written
— parallelizable across device lanes / pthread tiles, unlike the raw
stream hash it replaced.  The two construction paths are ``fresh`` (one
ledger's live+dead batch, Bucket.cpp:322) and ``merge`` (single-pass
2-way merge with shadow elision, Bucket.cpp:367-430).  ``apply`` replays
a bucket into the SQL store for catchup-minimal (Bucket.cpp
"Bucket::apply").

Entry identity order is defined by (entry type, key XDR bytes) — canonical
within this framework; hashes are framework-local, like the reference's are
network-local.
"""

from __future__ import annotations

import os
import uuid
from typing import Iterable, Iterator, List, Optional, Tuple

from ..ledger.entryframe import ledger_key_of, store_add_or_change, store_delete_key
from ..util import fs
from . import hashplane
from ..util.xdrstream import XDRInputFileStream, XDROutputFileStream
from ..xdr.base import pack_many
from ..xdr.entries import LedgerEntry
from ..xdr.ledger import BucketEntry, BucketEntryType, LedgerKey

ZERO_HASH = b"\x00" * 32

# storage kill-points (util/fs.py): every durable bucket write is a
# named fault-injection site for the kill-sweep / hard-kill chaos plane
KP_FRESH = fs.register_durable_site(
    "bucket.fresh", stages=(fs.STAGE_WRITE, fs.STAGE_STAGED),
    doc="one ledger's fresh batch packed+staged as a tmp bucket file",
)
KP_MERGE = fs.register_durable_site(
    "bucket.merge", stages=(fs.STAGE_WRITE, fs.STAGE_STAGED),
    doc="python streaming merge writing the level-spill tmp bucket",
)
KP_NATIVE_MERGE = fs.register_durable_site(
    "bucket.native-merge", stages=(fs.STAGE_STAGED,),
    doc="C merge engine output fsynced before adoption",
)


def entry_identity(e: BucketEntry) -> Tuple[int, bytes]:
    """Sort/identity key of a BucketEntry: live and dead entries with the
    same LedgerKey compare equal (LedgerCmp.h BucketEntryIdCmp)."""
    if e.type == BucketEntryType.LIVEENTRY:
        k = ledger_key_of(e.value)
    else:
        k = e.value
    return (int(k.type), k.value.to_xdr())


class _Peekable:
    """Iterator with 1-entry lookahead over (identity, BucketEntry) pairs."""

    __slots__ = ("_it", "head")

    def __init__(self, it: Iterator[BucketEntry]):
        self._it = it
        self.head: Optional[Tuple[Tuple[int, bytes], BucketEntry]] = None
        self.advance()

    def advance(self) -> None:
        try:
            e = next(self._it)
            self.head = (entry_identity(e), e)
        except StopIteration:
            self.head = None


def _shadowed(identity, shadow_iters: List[_Peekable]) -> bool:
    """True if an entry with this identity appears in any shadow stream
    (Bucket.cpp maybe_put): each shadow iterator advances monotonically —
    the candidate stream is itself sorted, so one pass suffices."""
    for si in shadow_iters:
        while si.head is not None and si.head[0] < identity:
            si.advance()
        if si.head is not None and si.head[0] == identity:
            return True
    return False


class Bucket:
    """Immutable handle on one bucket file (possibly the empty bucket)."""

    __slots__ = ("path", "hash", "objects")

    def __init__(self, path: str = "", hash: bytes = ZERO_HASH, objects: int = 0):
        self.path = path
        self.hash = hash
        self.objects = objects

    def is_empty(self) -> bool:
        return self.hash == ZERO_HASH

    def get_hash(self) -> bytes:
        return self.hash

    def __iter__(self) -> Iterator[BucketEntry]:
        if not self.path or not os.path.exists(self.path):
            if self.hash != ZERO_HASH:
                # a non-empty bucket with no backing file is always
                # corruption — iterating it as empty would silently
                # diverge the bucket-list hash
                raise RuntimeError(
                    f"bucket file missing for {self.hash.hex()}: {self.path!r}"
                )
            return
        with XDRInputFileStream(self.path) as f:
            while True:
                e = f.read_one(BucketEntry)
                if e is None:
                    return
                yield e

    def contains_identity(self, e: BucketEntry) -> bool:
        """Linear scan (reference containsBucketIdentity — test helper)."""
        ident = entry_identity(e)
        return any(entry_identity(x) == ident for x in self)

    def apply(self, db) -> None:
        """Replay entries into the SQL store (catchup-minimal path).  Buckets
        are header-independent, so a throwaway header/delta is used."""
        from ..ledger.delta import LedgerDelta
        from ..xdr.ledger import LedgerHeader

        if self.is_empty():
            return
        with db.transaction():
            for e in self:
                delta = LedgerDelta(LedgerHeader(), db, update_last_modified=False)
                if e.type == BucketEntryType.LIVEENTRY:
                    store_add_or_change(e.value, delta, db)
                else:
                    store_delete_key(e.value, delta, db)
                delta.commit()

    # -- construction ------------------------------------------------------
    @staticmethod
    def fresh(
        bucket_manager,
        live_entries: Iterable[LedgerEntry],
        dead_entries: Iterable[LedgerKey],
    ) -> "Bucket":
        """One ledger's output batch as a bucket: dead keys win over live
        entries of the same identity (Bucket.cpp:322-363 merges the dead
        bucket as 'new').

        The batch is merged/deduped as a list in Python (pure ordering
        logic) and then packed through ONE ``pack_many`` call with RFC
        5531 record framing — one buffer to hash and one write, instead
        of a per-entry to_xdr + struct.pack + hasher.add + file write
        (the r7 profile's third copy-plane lever; BucketList.add_batch
        runs this once per close).  Differential-pinned against the
        streaming ``_write_merged`` path in tests/test_bucket.py."""
        live = [
            (entry_identity(e), e)
            for e in (
                BucketEntry(BucketEntryType.LIVEENTRY, x) for x in live_entries
            )
        ]
        dead = [
            (entry_identity(k), k)
            for k in (
                BucketEntry(BucketEntryType.DEADENTRY, x) for x in dead_entries
            )
        ]
        live.sort(key=lambda p: p[0])
        dead.sort(key=lambda p: p[0])
        merged = _merge_fresh_batch(live, dead)
        if not merged:
            return Bucket()
        data = pack_many(merged, BucketEntry, frames=True)
        tmp = os.path.join(
            bucket_manager.get_tmp_dir(), f"tmp-bucket-{uuid.uuid4().hex}.xdr"
        )
        # v2 state-plane hash (hashplane.py): the packed buffer's frame
        # boundaries are walked and every record digested in batch —
        # device lanes or the pooled C tiles, per the backend knob
        h, count = hashplane.hash_frames(
            data, config=bucket_manager.app.config
        )
        assert count == len(merged)
        # crash-safe staging (util/fs.py): write + fsync before adoption
        # renames it to the content-addressed home — a kill at any point
        # leaves either a reapable tmp or the complete file
        fs.stage_write(
            tmp, data, point=KP_FRESH, ctx=bucket_manager.app.database
        )
        return bucket_manager.adopt_file_as_bucket(tmp, h, len(merged))

    @staticmethod
    def merge(
        bucket_manager,
        old_bucket: "Bucket",
        new_bucket: "Bucket",
        shadows: Iterable["Bucket"] = (),
        keep_dead_entries: bool = True,
    ) -> "Bucket":
        """Single-pass merge: new wins over old on identity collision; any
        entry present in a shadow (younger level) is elided; DEADENTRYs are
        dropped entirely when ``keep_dead_entries`` is false (bottom level).

        File-backed inputs run through the native C engine (GIL-free on
        worker threads, bit-identical output — tests/test_native_merge.py);
        anything else falls back to the Python path."""
        shadows = list(shadows)
        native_result = _try_native_merge(
            bucket_manager, old_bucket, new_bucket, shadows, keep_dead_entries
        )
        if native_result is not None:
            return native_result
        shadow_iters = [_Peekable(iter(s)) for s in shadows]
        return _write_merged(
            bucket_manager,
            iter(old_bucket),
            iter(new_bucket),
            shadow_iters,
            keep_dead_entries,
        )


def _merge_fresh_batch(live, dead):
    """Merged (identity, BucketEntry) batch for one ledger: exactly the
    record stream ``_write_merged(live, dead, shadows=[], keep_dead)``
    emits — sorted by identity, dead (the 'new' stream) wins an identity
    collision, and adjacent same-identity records collapse last-wins (the
    reference's BucketOutputIterator::put dedup window, which makes a
    batch containing duplicates hash identically to the deduplicated
    batch).  Inputs are identity-decorated sorted lists; returns the
    plain BucketEntry list for pack_many."""
    out = []  # (identity, entry)

    def put(pair):
        if out and out[-1][0] == pair[0]:
            out[-1] = pair
        else:
            out.append(pair)

    i = j = 0
    nl, nd = len(live), len(dead)
    while i < nl or j < nd:
        if j >= nd or (i < nl and live[i][0] < dead[j][0]):
            put(live[i])
            i += 1
        elif i >= nl or dead[j][0] < live[i][0]:
            put(dead[j])
            j += 1
        else:  # same identity: dead (new) wins
            put(dead[j])
            i += 1
            j += 1
    return [e for _, e in out]


def _try_native_merge(
    bucket_manager, old_bucket, new_bucket, shadows, keep_dead_entries
):
    """Run the merge in C if every participant is file-backed (or empty).
    Returns the merged Bucket, or None to fall back to Python."""
    from .. import native

    # test/chaos knob: the kill-sweep drives the Python merge leg's
    # kill-points through here (output is bit-identical either way,
    # pinned by tests/test_native_merge.py)
    if os.environ.get("STELLAR_TPU_NO_NATIVE_MERGE"):
        return None

    def path_of(b):
        if b.is_empty():
            return ""
        return b.path if b.path and os.path.exists(b.path) else None

    paths = [path_of(b) for b in (old_bucket, new_bucket, *shadows)]
    if any(p is None for p in paths):
        return None
    tmp = os.path.join(
        bucket_manager.get_tmp_dir(), f"tmp-bucket-{uuid.uuid4().hex}.xdr"
    )
    res = native.merge_files_v2(
        paths[0], paths[1], paths[2:], keep_dead_entries, tmp
    )
    if res is None:
        # engine unavailable, merge failed, or the .so predates the v2
        # hash symbol: the Python merge below produces the identical
        # record stream AND the identical v2 hash
        return None
    h, count = res
    if count == 0:
        if os.path.exists(tmp):
            os.unlink(tmp)
        return Bucket()
    # the C engine wrote with plain stdio: fsync before adoption renames
    # it into the content-addressed namespace (util/fs.py discipline)
    fs.fsync_path(tmp)
    fs.kill_point(
        KP_NATIVE_MERGE + fs.STAGE_STAGED, path=tmp,
        ctx=bucket_manager.app.database,
    )
    return bucket_manager.adopt_file_as_bucket(tmp, h, count)


def _write_merged(
    bucket_manager,
    old_it: Iterator[BucketEntry],
    new_it: Iterator[BucketEntry],
    shadow_iters: List[_Peekable],
    keep_dead_entries: bool,
) -> Bucket:
    tmp = os.path.join(
        bucket_manager.get_tmp_dir(), f"tmp-bucket-{uuid.uuid4().hex}.xdr"
    )
    # every write_one feeds the hasher exactly one full frame, which is
    # the unit the v2 per-record-digest hash batches over
    hasher = hashplane.BucketHasher(config=bucket_manager.app.config)
    objects = 0
    oi = _Peekable(old_it)
    ni = _Peekable(new_it)
    buffered = None  # (identity, entry): one-entry dedup window
    with XDROutputFileStream(
        tmp, hasher=hasher, durable=True, point=KP_MERGE,
        ctx=bucket_manager.app.database,
    ) as out:

        def put(e: BucketEntry, identity) -> None:
            """Buffer one entry so adjacent same-identity entries collapse
            (last wins) — the reference's BucketOutputIterator::put does
            the same, which is what makes a batch containing duplicates
            hash identically to the deduplicated batch
            (BucketTests.cpp:296 'duplicate bucket entries')."""
            nonlocal buffered, objects
            if e.type == BucketEntryType.DEADENTRY and not keep_dead_entries:
                return
            if _shadowed(identity, shadow_iters):
                return
            if buffered is not None and buffered[0] == identity:
                buffered = (identity, e)
                return
            if buffered is not None:
                out.write_one(buffered[1])
                objects += 1
            buffered = (identity, e)

        while oi.head is not None or ni.head is not None:
            if ni.head is None:
                put(oi.head[1], oi.head[0])
                oi.advance()
            elif oi.head is None:
                put(ni.head[1], ni.head[0])
                ni.advance()
            elif oi.head[0] < ni.head[0]:
                put(oi.head[1], oi.head[0])
                oi.advance()
            elif ni.head[0] < oi.head[0]:
                put(ni.head[1], ni.head[0])
                ni.advance()
            else:  # same identity: new wins
                put(ni.head[1], ni.head[0])
                oi.advance()
                ni.advance()
        if buffered is not None:
            out.write_one(buffered[1])
            objects += 1
    if objects == 0:
        os.unlink(tmp)
        return Bucket()
    return bucket_manager.adopt_file_as_bucket(tmp, hasher.finish(), objects)
