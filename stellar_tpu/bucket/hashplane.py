"""State-plane hash pipeline: batched per-record bucket hashing behind a
backend seam (ISSUE r22, ROADMAP #4).

The v2 bucket content hash is

    H(bucket) = SHA256( d_1 ‖ d_2 ‖ … ‖ d_n ),   d_i = SHA256(frame_i)

where ``frame_i`` is the full i-th record as written (4-byte RFC 5531
header ‖ XDR body).  Bucket hashes are framework-local (bucket/bucket.py
header note), so the scheme is free to differ from the reference's raw
stream hash — what it buys is parallelism: the per-record digests are an
embarrassingly parallel batch (the device kernel's lanes, the C pool's
tiles), and the sequential combine touches only 32 bytes per record
(~3% of the stream at typical entry sizes).  Every producer and verifier
moved together: ``Bucket.fresh``, ``_write_merged``, the native merge
(``bucket_merge_v2``), ``verify_bucket_file``, and catchup's archive
adoption — so the hash stays self-consistent end to end, including
bucket file names and the HistoryArchiveState combinators above them
(level hash = H(curr‖snap), list hash — unchanged shapes, new leaf
values).  The empty stream hashes to SHA256(b"") under both schemes.

Three interchangeable backends, all bit-identical (pinned by
tests/test_hashplane.py):

- ``device``  — the batched multi-block SHA-256 kernel (ops/sha256.py,
  XLA or Pallas), knob ``Config.DEVICE_BUCKET_HASH``.  Oversized frames
  (> ``DEVICE_MAX_BLOCKS`` compression blocks) spill to hashlib — same
  digests, merged in order.
- ``native``  — native/sighash.c's ``sha256_batch`` /
  ``bucket_hash_frames``: GIL-released, tile-fanned over the pthread
  pool.  The default whenever the extension builds.
- ``hashlib`` — the always-available last resort (and the differential
  oracle), forced by ``STELLAR_TPU_NO_NATIVE_HASH=1``.

A stale prebuilt native .so that predates the v2 entry points simply
lacks the symbols; the loaders report None and resolution falls through
to hashlib — never to a silently different hash.
"""

from __future__ import annotations

import hashlib
import os
import struct
import threading
import time
from typing import List, Optional, Tuple

_MAX_FRAME = 64 << 20  # util/xdrstream.py's body cap
_FLUSH_BYTES = 4 << 20  # BucketHasher batches this much before digesting
DEVICE_MAX_BLOCKS = 64  # frames above 64 SHA blocks (~4 KB) skip the device


def split_frames(buf) -> List[bytes]:
    """A framed record buffer -> the list of full frames (header+body).
    Raises ValueError on a truncated/malformed frame — the verify layer
    maps that to "corrupt"."""
    frames = []
    view = memoryview(buf)
    off, n = 0, len(view)
    while off < n:
        if off + 4 > n:
            raise ValueError("truncated bucket frame header")
        (hdr,) = struct.unpack_from(">I", view, off)
        if not hdr & 0x80000000:
            raise ValueError("bucket frame missing continuation bit")
        ln = hdr & 0x7FFFFFFF
        if ln > _MAX_FRAME:
            raise ValueError("oversized bucket frame")
        end = off + 4 + ln
        if end > n:
            raise ValueError("truncated bucket frame body")
        frames.append(bytes(view[off:end]))
        off = end
    return frames


def combine(digests) -> bytes:
    """The ordered digest combine — the only sequential stage."""
    comb = hashlib.sha256()
    for d in digests:
        comb.update(d)
    return comb.digest()


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------


class BucketHashBackend:
    """One way to produce per-frame SHA-256 digests in batch."""

    name = "?"

    def digests(self, frames: List[bytes]) -> List[bytes]:
        raise NotImplementedError

    def hash_frames(self, buf) -> Tuple[bytes, int]:
        """(v2 hash, record count) of a whole framed buffer."""
        frames = split_frames(buf)
        return combine(self.digests(frames)), len(frames)

    def hash_file(self, path: str) -> Tuple[bytes, int]:
        with open(path, "rb") as f:
            return self.hash_frames(f.read())


class HashlibBackend(BucketHashBackend):
    name = "hashlib"

    def digests(self, frames):
        return [hashlib.sha256(f).digest() for f in frames]


class NativeBackend(BucketHashBackend):
    """native/sighash.c: GIL-released, pthread-pool-fanned batches."""

    name = "native"

    def __init__(self, mod):
        self._mod = mod

    def digests(self, frames):
        out = bytearray(32 * len(frames))
        self._mod.sha256_batch(frames, out)
        return [bytes(out[32 * i : 32 * i + 32]) for i in range(len(frames))]

    def hash_frames(self, buf):
        # one C call: frame walk + parallel digests + ordered combine
        return self._mod.bucket_hash_frames(bytes(buf))

    def hash_file(self, path):
        from .. import native

        res = native.bucket_hash_v2_file(path)
        if res is not None:
            return res
        # C reported failure (unreadable or malformed): re-walk in
        # Python for the precise verdict (raises ValueError on corrupt)
        return super().hash_file(path)


class DeviceBackend(BucketHashBackend):
    """ops/sha256.py: the batched multi-block kernel.  Frames are
    size-classed into power-of-two ``max_blocks`` shapes so jit reuse is
    bounded; frames past DEVICE_MAX_BLOCKS spill to hashlib (bucket
    entries are a few hundred bytes — the spill class is empty in
    practice)."""

    def __init__(self, pallas: bool = False, interpret: bool = False):
        self.pallas = pallas
        self.interpret = interpret
        self.name = "device-pallas" if pallas else "device-xla"

    def digests(self, frames):
        import jax.numpy as jnp

        from ..ops import sha256 as dev

        out: List[Optional[bytes]] = [None] * len(frames)
        classes: dict = {}
        for i, f in enumerate(frames):
            nb = dev.blocks_for(len(f))
            if nb > DEVICE_MAX_BLOCKS:
                out[i] = hashlib.sha256(f).digest()
                continue
            cap = 1
            while cap < nb:
                cap *= 2
            classes.setdefault(cap, []).append(i)
        for cap, idxs in classes.items():
            batch = [frames[i] for i in idxs]
            if self.pallas:
                from ..ops.ed25519_pallas import NT

                pad = (-len(batch)) % NT
                packed, counts = dev.pack_frames(
                    batch + [b""] * pad, max_blocks=cap
                )
                rows = dev.sha256_pallas(
                    jnp.asarray(packed),
                    jnp.asarray(counts),
                    interpret=self.interpret,
                )
            else:
                packed, counts = dev.pack_frames(batch, max_blocks=cap)
                rows = dev._jit_rows_from_packed(
                    jnp.asarray(packed), jnp.asarray(counts)
                )
            import numpy as np

            arr = np.asarray(rows, dtype=np.int32).astype(np.uint8)
            for j, i in enumerate(idxs):
                out[i] = arr[:, j].tobytes()
        return out  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# resolution + throughput stats
# ---------------------------------------------------------------------------


class _Stats:
    """Whole-process hash-plane throughput ledger: bytes hashed and wall
    seconds per backend, read by selfcheck's boot report and bench.py's
    close lines."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes = 0  # analysis: locked-by _lock
        self._seconds = 0.0  # analysis: locked-by _lock
        self._backend_name = ""  # analysis: locked-by _lock

    def note(self, nbytes: int, seconds: float, backend: str) -> None:
        with self._lock:
            self._bytes += nbytes
            self._seconds += seconds
            self._backend_name = backend

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "seconds": self._seconds,
                "backend": self._backend_name,
            }

    @staticmethod
    def rate_mb_per_sec(before: dict, after: dict) -> float:
        db = after["bytes"] - before["bytes"]
        dt = after["seconds"] - before["seconds"]
        return round(db / dt / 1e6, 1) if dt > 0 else 0.0


stats = _Stats()

_cache_lock = threading.Lock()
_cache: dict = {}  # guarded by _cache_lock (module-level, not a field)


def backend_by_name(
    name: str, interpret: bool = False
) -> Optional[BucketHashBackend]:
    """An explicit backend instance (bench/profile A/B legs), or None
    when that backend can't load here."""
    if name == "hashlib":
        return HashlibBackend()
    if name == "native":
        from .. import native

        mod = native.load_sighash()
        if mod is None or not hasattr(mod, "sha256_batch"):
            return None
        return NativeBackend(mod)
    if name in ("device", "device-xla", "device-pallas"):
        try:
            import jax

            pallas = (
                name == "device-pallas"
                or (name == "device" and jax.default_backend() == "tpu")
            )
            return DeviceBackend(pallas=pallas, interpret=interpret)
        except Exception:
            return None
    raise ValueError(f"unknown bucket hash backend {name!r}")


def get_backend(config=None) -> BucketHashBackend:
    """Resolve the active backend: device when Config.DEVICE_BUCKET_HASH
    (and jax imports), else native (when the extension builds AND has
    the v2 entries — a stale .so falls through), else hashlib."""
    want_device = bool(config is not None and getattr(
        config, "DEVICE_BUCKET_HASH", False
    ))
    no_native = bool(os.environ.get("STELLAR_TPU_NO_NATIVE_HASH"))
    key = (want_device, no_native)
    with _cache_lock:
        hit = _cache.get(key)
    if hit is not None:
        return hit
    backend: Optional[BucketHashBackend] = None
    if want_device:
        backend = backend_by_name("device")
    if backend is None and not no_native:
        backend = backend_by_name("native")
    if backend is None:
        backend = HashlibBackend()
    with _cache_lock:
        _cache[key] = backend
    return backend


def reset_backend_cache() -> None:
    """Test hook: drop resolved backends (knob/env changes re-resolve)."""
    with _cache_lock:
        _cache.clear()


# ---------------------------------------------------------------------------
# the wired entry points (bucket.py / manager.py / catchup call these)
# ---------------------------------------------------------------------------


def hash_frames(buf, config=None) -> Tuple[bytes, int]:
    """(v2 bucket hash, record count) of a framed record buffer.
    Raises ValueError on a malformed/truncated frame."""
    backend = get_backend(config)
    t0 = time.perf_counter()
    out = backend.hash_frames(buf)
    stats.note(len(buf), time.perf_counter() - t0, backend.name)
    return out


def hash_file(path: str, config=None) -> Tuple[bytes, int]:
    """(v2 bucket hash, record count) of a bucket file on disk.  Raises
    OSError when unreadable, ValueError when malformed."""
    backend = get_backend(config)
    t0 = time.perf_counter()
    out = backend.hash_file(path)
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        nbytes = 0
    stats.note(nbytes, time.perf_counter() - t0, backend.name)
    return out


class BucketHasher:
    """Drop-in for crypto.sha.SHA256 in the bucket writers (the
    ``hasher=`` slot of util/xdrstream.XDROutputFileStream): ``add``
    takes EXACTLY ONE full frame per call — which is what write_one
    feeds it — and ``finish`` returns the v2 hash.  Frames batch up to
    ~4 MB before a backend digest pass, so memory stays bounded on
    million-record merges while batches stay big enough to fan out."""

    def __init__(self, config=None):
        self._backend = get_backend(config)
        self._comb = hashlib.sha256()
        self._pending: List[bytes] = []
        self._pending_bytes = 0
        self._count = 0
        self._finished = False

    def add(self, frame) -> None:
        assert not self._finished, "hash already finished"
        self._pending.append(bytes(frame))
        self._pending_bytes += len(frame)
        self._count += 1
        if self._pending_bytes >= _FLUSH_BYTES:
            self._flush()

    def _flush(self) -> None:
        if not self._pending:
            return
        t0 = time.perf_counter()
        for d in self._backend.digests(self._pending):
            self._comb.update(d)
        stats.note(
            self._pending_bytes,
            time.perf_counter() - t0,
            self._backend.name,
        )
        self._pending = []
        self._pending_bytes = 0

    @property
    def count(self) -> int:
        return self._count

    def finish(self) -> bytes:
        self._flush()
        self._finished = True
        return self._comb.digest()
