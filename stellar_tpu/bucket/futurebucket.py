"""FutureBucket — an in-progress (or potential) bucket merge
(reference: src/bucket/FutureBucket.{h,cpp}).

A FutureBucket is in one of three states:

- CLEAR: nothing here.
- LIVE: a merge is running on the worker pool (inputs held live); ``resolve``
  blocks until the output bucket exists.
- HASHES: only the input (or output) hashes are known — the deserialized
  form.  ``make_live`` re-launches the merge from hashes after a restart
  (BucketList::restartMerges), which is what makes merges resumable across
  process death: the merge is deterministic, so re-running it from the same
  inputs yields the same output hash.

Serialization round-trips through the HistoryArchiveState JSON
(history/archive.py), matching the reference's cereal form.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..util import xlog
from .bucket import Bucket

log = xlog.logger("Bucket")

FB_CLEAR = 0
FB_HASH_OUTPUT = 1
FB_HASH_INPUTS = 2
FB_LIVE_OUTPUT = 3
FB_LIVE_INPUTS = 4


class FutureBucket:
    def __init__(
        self,
        app=None,
        curr: Optional[Bucket] = None,
        snap: Optional[Bucket] = None,
        shadows: Optional[List[Bucket]] = None,
        keep_dead_entries: bool = True,
    ):
        self.state = FB_CLEAR
        self.keep_dead_entries = keep_dead_entries
        self.input_curr: Optional[Bucket] = None
        self.input_snap: Optional[Bucket] = None
        self.input_shadows: List[Bucket] = []
        self.input_curr_hash: Optional[bytes] = None
        self.input_snap_hash: Optional[bytes] = None
        self.input_shadow_hashes: List[bytes] = []
        self.output: Optional[Bucket] = None
        self.output_hash: Optional[bytes] = None
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        if curr is not None:
            assert app is not None and snap is not None
            self.input_curr = curr
            self.input_snap = snap
            self.input_shadows = list(shadows or [])
            self.input_curr_hash = curr.get_hash()
            self.input_snap_hash = snap.get_hash()
            self.input_shadow_hashes = [s.get_hash() for s in self.input_shadows]
            self.state = FB_LIVE_INPUTS
            self._start_merge(app)

    # -- state predicates (FutureBucket.h:40-70) ---------------------------
    def is_clear(self) -> bool:
        return self.state == FB_CLEAR

    def is_live(self) -> bool:
        return self.state in (FB_LIVE_INPUTS, FB_LIVE_OUTPUT)

    def is_merging(self) -> bool:
        return self.state == FB_LIVE_INPUTS and not self._done.is_set()

    def has_hashes(self) -> bool:
        return self.state in (FB_HASH_INPUTS, FB_HASH_OUTPUT)

    def has_output_hash(self) -> bool:
        return self.state in (FB_HASH_OUTPUT, FB_LIVE_OUTPUT) or (
            self.state == FB_LIVE_INPUTS
            and self._done.is_set()
            and self._error is None  # failed merge serializes as inputs,
            # so a restart re-launches it
        )

    def clear(self) -> None:
        self.__init__()

    # -- merge lifecycle ---------------------------------------------------
    def _start_merge(self, app) -> None:
        curr, snap = self.input_curr, self.input_snap
        shadows = self.input_shadows
        keep_dead = self.keep_dead_entries
        bm = app.bucket_manager

        def work():
            return Bucket.merge(bm, curr, snap, shadows, keep_dead)

        def done(result):
            if isinstance(result, BaseException):
                self._error = result
                log.error("bucket merge failed: %s", result)
            else:
                self.output = result
                self.output_hash = result.get_hash()
            self._done.set()

        # completion is recorded from the merging thread itself so
        # resolve() can block without needing the main loop to crank
        def run():
            try:
                done(work())
            except BaseException as e:  # pragma: no cover
                done(e)

        # dedicated merge workers (ISSUE r22, bucket/mergeworker.py):
        # spills merge in the background and the close boundary that
        # commits them finds them done.  Knob off = merge synchronously
        # right here (bit-exact differential baseline — the output hash
        # cannot depend on WHERE the deterministic merge ran)
        cfg = getattr(app, "config", None)
        if cfg is None or getattr(cfg, "BACKGROUND_BUCKET_MERGE", True):
            from . import mergeworker

            mergeworker.submit(run)
        else:
            run()

    def resolve(self) -> Bucket:
        """Block until merged; flip to LIVE_OUTPUT (FutureBucket::resolve)."""
        assert self.is_live()
        self._done.wait()
        if self._error is not None:
            raise self._error
        self.state = FB_LIVE_OUTPUT
        return self.output

    def merge_complete(self) -> bool:
        assert self.is_live()
        return self._done.is_set()

    def make_live(self, app) -> None:
        """Reanimate from hashes: either adopt the known output bucket, or
        re-launch the merge from input buckets (must exist on disk)."""
        assert self.has_hashes()
        bm = app.bucket_manager
        if self.state == FB_HASH_OUTPUT:
            self.output = bm.get_bucket_by_hash(self.output_hash)
            self._done.set()
            self.state = FB_LIVE_OUTPUT
        else:
            self.input_curr = bm.get_bucket_by_hash(self.input_curr_hash)
            self.input_snap = bm.get_bucket_by_hash(self.input_snap_hash)
            self.input_shadows = [
                bm.get_bucket_by_hash(h) for h in self.input_shadow_hashes
            ]
            self._done = threading.Event()
            self._error = None
            self.state = FB_LIVE_INPUTS
            self._start_merge(app)

    # -- (de)serialization (FutureBucket.h:98-118 / cereal form) -----------
    def to_state(self) -> dict:
        if self.is_live() or self.state == FB_HASH_OUTPUT:
            if self.has_output_hash():
                out = self.output_hash or (self.output and self.output.get_hash())
                return {"state": FB_HASH_OUTPUT, "output": out.hex()}
            return {
                "state": FB_HASH_INPUTS,
                "curr": self.input_curr_hash.hex(),
                "snap": self.input_snap_hash.hex(),
                "shadow": [h.hex() for h in self.input_shadow_hashes],
                "keepDead": self.keep_dead_entries,
            }
        if self.state == FB_HASH_INPUTS:
            return {
                "state": FB_HASH_INPUTS,
                "curr": self.input_curr_hash.hex(),
                "snap": self.input_snap_hash.hex(),
                "shadow": [h.hex() for h in self.input_shadow_hashes],
                "keepDead": self.keep_dead_entries,
            }
        return {"state": FB_CLEAR}

    @classmethod
    def from_state(cls, st: dict) -> "FutureBucket":
        fb = cls()
        s = st.get("state", FB_CLEAR)
        if s == FB_HASH_OUTPUT:
            fb.state = FB_HASH_OUTPUT
            fb.output_hash = bytes.fromhex(st["output"])
        elif s == FB_HASH_INPUTS:
            fb.state = FB_HASH_INPUTS
            fb.input_curr_hash = bytes.fromhex(st["curr"])
            fb.input_snap_hash = bytes.fromhex(st["snap"])
            fb.input_shadow_hashes = [bytes.fromhex(h) for h in st.get("shadow", [])]
            fb.keep_dead_entries = bool(st.get("keepDead", True))
        return fb

    def referenced_hashes(self) -> List[bytes]:
        """Every bucket hash this future pins (for GC + publish sets)."""
        out: List[bytes] = []
        if self.output_hash:
            out.append(self.output_hash)
        if self.output is not None:
            out.append(self.output.get_hash())
        for h in (self.input_curr_hash, self.input_snap_hash):
            if h:
                out.append(h)
        out.extend(self.input_shadow_hashes)
        return out
