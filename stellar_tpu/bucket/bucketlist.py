"""BucketList — 11-level log-structured ledger-state store
(reference: src/bucket/BucketList.{h,cpp}).

Level i holds ~levelSize(i) = 4^(i+1) ledgers of churn in two buckets
{curr, snap}; each level spills into the next when the ledger count crosses
half/size boundaries (levelShouldSpill, BucketList.cpp:186-196).  Merges run
asynchronously on the worker pool as FutureBuckets and are committed (made
curr) the next time the receiving level spills.  The list hash commits to the
whole ledger state: H(concat level hashes), level hash = H(curr ‖ snap)
(BucketList.cpp:29-33,175-181).
"""

from __future__ import annotations

from typing import List

from ..crypto import SHA256
from .bucket import Bucket
from .futurebucket import FutureBucket

NUM_LEVELS = 11  # BucketList.cpp:320


def level_size(level: int) -> int:
    return 1 << (2 * (level + 1))  # 4^(level+1)


def level_half(level: int) -> int:
    return level_size(level) >> 1


def _mask(v: int, m: int) -> int:
    return v & ~(m - 1)


def level_should_spill(ledger: int, level: int) -> bool:
    if level == NUM_LEVELS - 1:
        return False  # the max level never spills
    return ledger == _mask(ledger, level_half(level)) or ledger == _mask(
        ledger, level_size(level)
    )


class BucketLevel:
    def __init__(self, level: int):
        self.level = level
        self.curr = Bucket()
        self.snap = Bucket()
        self.next = FutureBucket()

    def get_hash(self) -> bytes:
        h = SHA256()
        h.add(self.curr.get_hash())
        h.add(self.snap.get_hash())
        return h.finish()

    def commit(self) -> None:
        """Resolve the pending merge into curr (BucketLevel::commit)."""
        if self.next.is_live():
            self.curr = self.next.resolve()
            self.next.clear()

    def prepare(self, app, curr_ledger: int, snap: Bucket, shadows) -> None:
        """Start merging ``snap`` (spilled from the level above) into this
        level's curr (BucketLevel::prepare)."""
        assert not self.next.is_live()
        curr = self.curr
        # Subtle (BucketList.cpp:120-135): if this level's own curr will be
        # snapshotted at its next change-ledger, the incoming material merges
        # into an empty bucket instead — curr is about to be pulled aside.
        if self.level > 0:
            next_change = curr_ledger + level_half(self.level - 1)
            if level_should_spill(next_change, self.level):
                curr = Bucket()
        keep_dead = self.level < NUM_LEVELS - 1
        self.next = FutureBucket(app, curr, snap, list(shadows), keep_dead)

    def take_snap(self) -> Bucket:
        """curr → snap, fresh empty curr; returns the snap (BucketLevel::snap)."""
        self.snap = self.curr
        self.curr = Bucket()
        return self.snap


class BucketList:
    def __init__(self):
        self.levels: List[BucketLevel] = [BucketLevel(i) for i in range(NUM_LEVELS)]

    def get_level(self, i: int) -> BucketLevel:
        return self.levels[i]

    def get_hash(self) -> bytes:
        h = SHA256()
        for lev in self.levels:
            h.add(lev.get_hash())
        return h.finish()

    def add_batch(self, app, curr_ledger: int, live_entries, dead_entries) -> None:
        """One ledger's batch (BucketList::addBatch).  Processes levels
        deepest-first so each curr is snapped the moment it is half full;
        shadows for a level-i merge are the curr/snap of levels 0..i-2
        (see the long comment at BucketList.cpp:214-240 for why i-1's own
        buckets are excluded)."""
        assert curr_ledger > 0
        shadows: List[Bucket] = []
        for lev in self.levels:
            shadows.append(lev.curr)
            shadows.append(lev.snap)
        shadows.pop()
        shadows.pop()
        for i in range(NUM_LEVELS - 1, 0, -1):
            shadows.pop()
            shadows.pop()
            if level_should_spill(curr_ledger, i - 1):
                snap = self.levels[i - 1].take_snap()
                self.levels[i].commit()
                self.levels[i].prepare(app, curr_ledger, snap, shadows)
        assert not shadows
        self.levels[0].prepare(
            app,
            curr_ledger,
            Bucket.fresh(app.bucket_manager, live_entries, dead_entries),
            [],
        )
        self.levels[0].commit()

    def restart_merges(self, app) -> None:
        """Re-launch deserialized in-progress merges (BucketList::restartMerges)."""
        for i, lev in enumerate(self.levels):
            if lev.next.has_hashes():
                lev.next.make_live(app)
