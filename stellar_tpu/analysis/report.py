"""Report rendering: human text and machine JSON for the analyzer CLI."""

from __future__ import annotations

import json

from .core import Report
from .registry import rule_docs


def render_human(report: Report, verbose_suppressions: bool = False) -> str:
    out = []
    for path, err in report.parse_errors:
        out.append(f"{path}: PARSE ERROR: {err}")
    for v in report.violations:
        out.append(v.render())
    if verbose_suppressions and report.suppressed:
        out.append("")
        out.append("suppressed (each carries a reviewed rationale):")
        for s in report.suppressed:
            out.append(
                f"  {s.path}:{s.line}: [{s.rule}] -- {s.rationale}"
            )
    out.append(
        f"{len(report.violations)} violation(s),"
        f" {len(report.suppressed)} suppressed,"
        f" {len(report.parse_errors)} parse error(s);"
        f" {report.files_scanned} file(s) scanned,"
        f" {len(report.rules)} rules active"
    )
    return "\n".join(out)


def render_json(report: Report) -> str:
    return json.dumps(report.to_json(), indent=1, sort_keys=True)


def render_rules() -> str:
    width = max(len(rid) for rid, _ in rule_docs())
    return "\n".join(f"{rid.ljust(width)}  {doc}" for rid, doc in rule_docs())
