"""Analyzer engine: file contexts, suppressions, the rule runner.

One parse + one parent-annotated walk per Python module; rules are small
visitors over that shared context (``FileContext``).  C sources get a
line/comment scan instead of an AST (see crules.py).  All state is
per-run — the engine is import-light and never touched by the runtime
planes (profile_close.py --assert-budget pins that).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from .registry import all_rules, rule_ids

# -- suppression / registry comment grammar ---------------------------------

# "# analysis: off <rule-id> -- <rationale>"; the rationale is MANDATORY —
# a suppression is a reviewed exception, and the review lives in the text
_SUPPRESS_RE = re.compile(
    r"analysis:\s*off\s+(?P<rule>[\w-]+)(?:\s+--\s*(?P<rationale>.*?))?\s*(?:\*/)?\s*$"
)
# "# analysis: locked-by <lock>" on a field's declaration line registers
# the field into the locked-field rule's registry for that module
_LOCKED_RE = re.compile(r"analysis:\s*locked-by\s+(?P<lock>\w+)")
_DECL_RE = re.compile(r"self\.(?P<field>\w+)\s*(?::[^=]+)?=")


@dataclass
class Violation:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "message": self.message,
        }


@dataclass
class Suppression:
    path: str
    line: int
    rule: str
    rationale: str
    comment_line: int  # where the comment itself sits (== line for trailing)

    def to_json(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "rule": self.rule,
            "rationale": self.rationale,
        }


@dataclass
class FileContext:
    """Everything a rule needs about one audited file."""

    path: str  # as given / display
    relpath: str  # package-relative, '/'-separated (rule scoping key)
    text: str
    lines: List[str]
    tree: Optional[ast.AST]  # None for C sources / parse failures
    comments: Dict[int, str] = field(default_factory=dict)  # line -> text
    # line -> {rule: (rationale, comment_line)}; a violation on line L is
    # suppressed by an entry at L (trailing comment) or registered FROM an
    # own-line comment above (attaches to the next CODE line, skipping
    # blanks and wrapped-rationale comment continuations)
    suppress: Dict[int, Dict[str, Tuple[str, int]]] = field(default_factory=dict)
    locked: Dict[str, Tuple[str, int]] = field(default_factory=dict)  # field -> (lock, decl line)
    meta_violations: List[Tuple[int, str]] = field(default_factory=list)
    is_c: bool = False

    # -- AST helpers shared by the rules ------------------------------------
    def ancestors(self, node: ast.AST):
        n = getattr(node, "_an_parent", None)
        while n is not None:
            yield n
            n = getattr(n, "_an_parent", None)

    def enclosing_function(self, node: ast.AST) -> Optional[str]:
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return a.name
        return None

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a.name
        return None

    def in_with_lock(self, node: ast.AST, lock: str) -> bool:
        """True when an ancestor ``with`` statement's context expression
        names `lock` as a whole attribute/name token (``self._lock`` holds
        ``_lock``; ``self._wedge_lock`` does NOT — no substring passes)."""
        pat = re.compile(rf"\b{re.escape(lock)}\b")
        for a in self.ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)):
                for item in a.items:
                    try:
                        src = ast.unparse(item.context_expr)
                    except Exception:  # pragma: no cover - unparse is total on parsed trees
                        continue
                    if pat.search(src):
                        return True
        return False


def attr_chain(node: ast.AST) -> Optional[List[str]]:
    """``self.entry.data.value`` -> ['self','entry','data','value'];
    call links keep their name with ``()`` (``f.mut().balance`` ->
    ['f','mut()','balance']).  None when the base isn't a plain
    name/attribute/call chain (subscripts etc. end the walk)."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                parts.append(f.attr + "()")
                node = f.value
            elif isinstance(f, ast.Name):
                parts.append(f.id + "()")
                break
            else:
                return None
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            break
        else:
            return None
    parts.reverse()
    return parts


@dataclass
class Report:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Suppression] = field(default_factory=list)
    parse_errors: List[Tuple[str, str]] = field(default_factory=list)  # (path, err)
    files_scanned: int = 0
    rules: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.violations and not self.parse_errors

    def exit_code(self) -> int:
        """2 = parse errors (a tree we could not audit must never report
        clean), 1 = unsuppressed violations, 0 = clean."""
        if self.parse_errors:
            return 2
        return 1 if self.violations else 0

    def to_json(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "violations": [v.to_json() for v in self.violations],
            "suppressions": [s.to_json() for s in self.suppressed],
            "parse_errors": [
                {"path": p, "error": e} for p, e in self.parse_errors
            ],
            "clean": self.clean,
        }


# -- context construction ----------------------------------------------------


def _collect_py_comments(text: str) -> Dict[int, str]:
    """line -> comment text, via tokenize (a '#' inside a string is not a
    comment).  On tokenize errors fall back to nothing — the AST parse
    reports the real problem."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


def _collect_c_comments(lines: List[str]) -> Dict[int, str]:
    """Good-enough C comment grab for the suppression/registry grammar:
    any line containing the 'analysis:' marker contributes its tail."""
    out: Dict[int, str] = {}
    for i, ln in enumerate(lines, 1):
        if "analysis:" in ln:
            m = re.search(r"(?://|/\*|#)?\s*(analysis:.*)$", ln)
            if m:
                out[i] = m.group(1)
    return out


def _line_has_code(lines: List[str], lineno: int) -> bool:
    if not (1 <= lineno <= len(lines)):
        return False
    code = lines[lineno - 1].split("#", 1)[0].strip()
    return bool(code) and not code.startswith(("//", "/*", "*"))


def _next_code_line(lines: List[str], lineno: int, limit: int = 10) -> int:
    """The line an own-line suppression attaches to: the next line that
    carries CODE, skipping blanks and further comment lines (a wrapped
    rationale continuation must not swallow the suppression) — bounded so
    a trailing comment block can't attach to something far away."""
    for cand in range(lineno + 1, min(lineno + 1 + limit, len(lines) + 1)):
        if _line_has_code(lines, cand):
            return cand
    return lineno + 1


def build_context(path: str, relpath: str, text: str) -> FileContext:
    is_c = relpath.endswith(".c")
    lines = text.splitlines()
    ctx = FileContext(
        path=path, relpath=relpath, text=text, lines=lines, tree=None, is_c=is_c
    )
    ctx.comments = _collect_c_comments(lines) if is_c else _collect_py_comments(text)
    known = set(rule_ids())
    for lineno, comment in sorted(ctx.comments.items()):
        m = _SUPPRESS_RE.search(comment)
        if m:
            rule = m.group("rule")
            rationale = (m.group("rationale") or "").strip()
            target = (
                lineno
                if _line_has_code(lines, lineno)
                else _next_code_line(lines, lineno)
            )
            if rule not in known:
                ctx.meta_violations.append(
                    (lineno, f"suppression names unknown rule {rule!r}")
                )
            elif not rationale:
                ctx.meta_violations.append(
                    (
                        lineno,
                        f"bare suppression of {rule!r} — a rationale is"
                        " mandatory (… off "
                        f"{rule} -- <why this site is safe>)",
                    )
                )
            else:
                ctx.suppress.setdefault(target, {})[rule] = (rationale, lineno)
        m = _LOCKED_RE.search(comment)
        if m and not is_c:
            dm = _DECL_RE.search(lines[lineno - 1]) if lineno <= len(lines) else None
            if dm:
                ctx.locked[dm.group("field")] = (m.group("lock"), lineno)
            else:
                ctx.meta_violations.append(
                    (
                        lineno,
                        "locked-by registry comment must sit on the"
                        " field's `self.<field> = ...` declaration line",
                    )
                )
    if not is_c:
        tree = ast.parse(text)  # SyntaxError propagates to the runner
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                child._an_parent = parent
        ctx.tree = tree
    return ctx


# -- the runner --------------------------------------------------------------


def _relpath_of(path: str) -> str:
    """Package-relative path used for rule scoping: the portion after the
    LAST 'stellar_tpu' segment, '/'-separated; else the basename."""
    norm = os.path.normpath(os.path.abspath(path)).replace(os.sep, "/")
    parts = norm.split("/")
    for i in range(len(parts) - 1, -1, -1):
        if parts[i] == "stellar_tpu":
            return "/".join(parts[i + 1 :])
    return parts[-1]


def _audit_context(ctx: FileContext, report: Report) -> None:
    fired = []
    for rule in all_rules():
        if rule.is_c_rule != ctx.is_c:
            continue
        if not rule.applies(ctx):
            continue
        for line, message in rule.check(ctx):
            fired.append((line, rule.id, message))
    for line, msg in ctx.meta_violations:
        fired.append((line, "suppression-rationale", msg))
    used = set()
    for line, rule_id, message in sorted(fired):
        sup = ctx.suppress.get(line, {}).get(rule_id)
        if sup is not None and rule_id != "suppression-rationale":
            rationale, comment_line = sup
            used.add((line, rule_id))
            report.suppressed.append(
                Suppression(ctx.path, line, rule_id, rationale, comment_line)
            )
        else:
            report.violations.append(Violation(ctx.path, line, rule_id, message))
    # the unused-noqa pattern: a suppression whose violation no longer
    # fires is stale — it would silently pre-suppress a future regression
    # on that line and drift the SWEEP.md inventory, so it fails the gate
    for line, by_rule in sorted(ctx.suppress.items()):
        for rule_id, (_rationale, comment_line) in sorted(by_rule.items()):
            if (line, rule_id) not in used:
                report.violations.append(
                    Violation(
                        ctx.path,
                        comment_line,
                        "suppression-rationale",
                        f"unused suppression of {rule_id!r} — the violation"
                        " it silenced no longer fires; delete the comment",
                    )
                )


def analyze_source(
    text: str, relpath: str, report: Optional[Report] = None, path: Optional[str] = None
) -> Report:
    """Audit one source text under a (possibly virtual) package-relative
    path — the fixture tests drive path-scoped rules through this."""
    if report is None:
        report = Report(rules=rule_ids())
    try:
        ctx = build_context(path or relpath, relpath, text)
    except SyntaxError as e:
        report.parse_errors.append((path or relpath, f"line {e.lineno}: {e.msg}"))
        report.files_scanned += 1
        return report
    except ValueError as e:
        # ast.parse raises bare ValueError for e.g. NUL bytes in the
        # source — still a file we could not audit, never a clean pass
        report.parse_errors.append((path or relpath, str(e)))
        report.files_scanned += 1
        return report
    _audit_context(ctx, report)
    report.files_scanned += 1
    return report


def iter_audit_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs if d not in ("__pycache__", ".git")
                )
                for name in sorted(files):
                    if name.endswith((".py", ".c")):
                        out.append(os.path.join(root, name))
        else:
            out.append(p)
    return out


def analyze_paths(paths: Iterable[str]) -> Report:
    report = Report(rules=rule_ids())
    for fp in iter_audit_files(paths):
        try:
            with open(fp, "r", encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError as e:
            report.parse_errors.append((fp, str(e)))
            continue
        analyze_source(text, _relpath_of(fp), report, path=fp)
    return report
