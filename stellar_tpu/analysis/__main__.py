"""CLI: ``python -m stellar_tpu.analysis [paths...]`` / ``stellar-tpu-analyze``.

Exit codes: 0 clean, 1 unsuppressed violations, 2 parse errors (a module
the analyzer could not read must never let the tree report clean — the
parse error wins even when every parsed file passed).
"""

from __future__ import annotations

import argparse
import os
import sys

from .core import analyze_paths
from .report import render_human, render_json, render_rules


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="stellar-tpu-analyze",
        description="project-contract static analyzer for stellar_tpu",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/directories to audit (default: the installed"
        " stellar_tpu package)",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable report")
    ap.add_argument(
        "--rules", action="store_true", help="list active rules and exit"
    )
    ap.add_argument(
        "--suppressions",
        action="store_true",
        help="also print the suppression inventory (human mode)",
    )
    args = ap.parse_args(argv)

    if args.rules:
        print(render_rules())
        return 0

    paths = args.paths
    if not paths:
        paths = [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    report = analyze_paths(paths)
    print(render_json(report) if args.json else render_human(report, args.suppressions))
    return report.exit_code()


if __name__ == "__main__":
    sys.exit(main())
