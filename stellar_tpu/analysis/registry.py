"""Rule registry: rules self-register at import via the ``@register``
decorator; the engine and CLI enumerate them through ``all_rules()``.

``suppression-rationale`` is the engine's own meta rule (bare or
unknown-rule suppressions, malformed locked-by registrations) — it has no
visitor class but must be a known id, so it is seeded here.
"""

from __future__ import annotations

from typing import Dict, Iterator, List


class Rule:
    """Base rule.  Subclasses set ``id``/``doc`` and implement ``check``;
    ``applies`` gates by module (path scope, or content probes like 'does
    this module reference the guarded type at all')."""

    id: str = ""
    doc: str = ""
    is_c_rule: bool = False

    def applies(self, ctx) -> bool:
        return True

    def check(self, ctx) -> Iterator:
        raise NotImplementedError


_RULES: Dict[str, Rule] = {}

# engine-level meta rule id (core.py emits it directly)
META_RULE_ID = "suppression-rationale"
META_RULE_DOC = (
    "suppressions must carry a rationale ('-- <why>') and name a real rule;"
    " locked-by registrations must sit on the field declaration line"
)


def register(cls):
    inst = cls()
    if not inst.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if inst.id in _RULES or inst.id == META_RULE_ID:
        raise ValueError(f"duplicate rule id {inst.id!r}")
    _RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    return [_RULES[k] for k in sorted(_RULES)]


def rule_ids() -> List[str]:
    return sorted(_RULES) + [META_RULE_ID]


def rule_docs() -> List[tuple]:
    out = [(r.id, r.doc) for r in all_rules()]
    out.append((META_RULE_ID, META_RULE_DOC))
    return sorted(out)
