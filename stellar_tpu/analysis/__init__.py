"""Project-contract static analyzer (build/test-time only — never imported
by the runtime planes).

PRs 3-6 piled up load-bearing conventions that previously lived only in
comments and review heads: the seal-on-store CoW mutation discipline
(``mut()``/``touch()``), the signing-plane-only FrameContext aliasing rule,
``cxdrpack.getfield`` restricted to the TRUSTED post-verify plane,
quarantine-before-cache-latch in the async signature plane, and
VirtualClock determinism in consensus code.  The invariant plane
(``stellar_tpu/invariant/``) catches the resulting bug classes at RUNTIME,
one forked close before the damage commits; this package catches them at
DIFF time, before the forked close ever runs — the same pairing the
reference gets from ``src/invariant/`` + its clang-tidy wiring.

Engine: one AST walk per audited module with a registry of rule visitors
(``rules.py``), a token-level C scanner for the GIL-release regions of the
native extensions (``crules.py``), per-site suppressions with MANDATORY
rationale strings, and JSON/human reports (``report.py``).  CLI:
``python -m stellar_tpu.analysis [paths...]`` (also installed as
``stellar-tpu-analyze``); exit 0 = clean, 1 = unsuppressed violations,
2 = a module failed to parse (a broken parse must never report clean).

Suppression syntax (same line or the line directly above)::

    f.entry.data.value = body  # analysis: off cow-mutation -- <why this site is safe>

A suppression without a rationale (no ``-- <text>``), or naming an unknown
rule, is itself a violation (``suppression-rationale``).  Lock-protected
fields register through a declaration-site comment::

    self._map = {}  # analysis: locked-by _lock

after which every access outside a ``with <lock>`` block (in any method
but ``__init__``) is a ``locked-field`` violation.

Tier-1 runs the analyzer over the live package and asserts zero
unsuppressed violations (tests/test_analysis.py::test_analysis_clean);
the standing ROADMAP policy is that contract changes land with a rule or
an explicit rationale.
"""

from .core import FileContext, Report, Suppression, Violation, analyze_paths, analyze_source  # noqa: F401
from .registry import all_rules, rule_ids  # noqa: F401

# import for side effect: rule registration
from . import crules, rules  # noqa: F401, E402  isort:skip
